"""Flagship elastic training workload: Llama fed by the shm data plane.

Run under the elastic launcher::

    python -m dlrover_tpu.trainer.elastic_run --standalone \
        examples/llama_train.py -- --steps 50 --ckpt-dir /tmp/llama_ckpt

The full production-shaped stack (VERDICT #9): agent rendezvous ->
master dataset sharding -> coworker shm producers (ElasticShmDataLoader:
each coworker owns a ShardingClient and pushes materialized batches into
the C++ ring) -> DevicePrefetch -> ShardedTrainer jitted step -> flash
checkpoint, with step-progress hang detection and fault injection armed.

Parity role: the reference's model-zoo Llama entrypoints
(atorch/examples/llama2) with the coworker shm context
(atorch/atorch/data/shm_context.py:527) — here the data plane and the
elastic control plane come from one framework.
"""

import argparse
import os
import sys
import time

import jax
import numpy as np
import optax

from dlrover_tpu.agent.master_client import build_master_client
from dlrover_tpu.data.elastic_shm import ElasticShmDataLoader
from dlrover_tpu.models import llama
from dlrover_tpu.parallel.mesh import create_mesh
from dlrover_tpu.trainer.checkpoint import FlashCheckpointer
from dlrover_tpu.trainer.distributed import init_from_env
from dlrover_tpu.trainer.elastic import ElasticTrainer
from dlrover_tpu.trainer.sharded import make_trainer_for_llama


def synth_batch(start: int, end: int, seq_len: int = 128,
                vocab: int = 256):
    """Materialize one shard's batch (coworker-side). A real job reads
    and tokenizes a corpus slice here; the synthetic stream is seeded by
    the sample index so every shard is reproducible."""
    rng = np.random.default_rng(start)
    tokens = rng.integers(
        0, vocab, (end - start, seq_len), dtype=np.int32
    )
    return tokens, tokens


class _BatchFn:
    """Picklable batch_fn with bound shape params (spawn-safe)."""

    def __init__(self, seq_len: int, vocab: int):
        self.seq_len = seq_len
        self.vocab = vocab

    def __call__(self, start, end):
        return synth_batch(start, end, self.seq_len, self.vocab)


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", type=int, default=50)
    parser.add_argument("--batch-size", type=int, default=8)
    parser.add_argument("--seq-len", type=int, default=128)
    parser.add_argument("--num-workers", type=int, default=2)
    parser.add_argument("--strategy", type=str, default="fsdp")
    parser.add_argument("--ckpt-dir", type=str,
                        default="/tmp/llama_ckpt")
    parser.add_argument("--out", type=str, default="")
    parser.add_argument("--timing-out", type=str, default="",
                        help="append '<restart_count>,<secs_to_first_"
                             "step>' per incarnation (the failover "
                             "drill's cold/warm compile probe)")
    args = parser.parse_args()

    t_proc_start = time.time()
    env = init_from_env()
    client = build_master_client()
    cfg = llama.llama_tiny()

    mesh = create_mesh([("data", 1), ("fsdp", len(jax.devices()))])
    trainer = make_trainer_for_llama(
        cfg, mesh, strategy=args.strategy,
        optimizer=optax.adamw(1e-3),
    )
    params, opt_state = trainer.init(jax.random.key(0))

    ckpt = FlashCheckpointer(
        persist_dir=os.path.join(args.ckpt_dir, "persist"),
        ram_dir=os.path.join(args.ckpt_dir, "ram"),
        persist_interval=0, use_orbax=False,
    )
    state = {"params": params, "opt_state": opt_state,
             "step": jax.numpy.array(0)}
    restored, _ = ckpt.restore(target=state)
    start_step = 0
    if restored is not None:
        params = restored["params"]
        opt_state = restored["opt_state"]
        start_step = int(restored["step"])
        print(f"RESTORED from step {start_step}", flush=True)

    # hang detection + fault injection ride on the elastic reporter
    reporter = ElasticTrainer(
        lambda p, b: 0.0, optax.identity(), max_nodes=1, cur_nodes=1,
        master_client=client, report_interval=5,
    )

    dataset_size = args.steps * args.batch_size
    loader = ElasticShmDataLoader(
        _BatchFn(args.seq_len, cfg.vocab_size),
        dataset_name="llama-train",
        batch_size=args.batch_size,
        dataset_size=dataset_size,
        num_epochs=10**6,  # stream until --steps
        num_workers=args.num_workers,
        slot_bytes=8 << 20,
        sharding=trainer.batch_sharding,
    )

    step, loss = start_step, None
    first_step_done = False
    try:
        for batch in loader:
            mb = jax.tree.map(lambda x: x[None], batch)  # 1 microbatch
            params, opt_state, loss = trainer.train_step(
                params, opt_state, mb
            )
            if not first_step_done:
                # the restart tax this incarnation actually paid:
                # process start -> first optimizer step retired
                # (bootstrap + restore + trace + XLA compile or a
                # persistent-cache read — compile_cache.py)
                float(loss)  # device sync
                t_first = time.time() - t_proc_start
                first_step_done = True
                print(
                    f"FIRST_STEP restart={env.restart_count} "
                    f"secs={t_first:.3f}", flush=True,
                )
                if args.timing_out:
                    with open(args.timing_out, "a") as f:
                        f.write(f"{env.restart_count},{t_first:.3f}\n")
            step += 1
            reporter.report_step(step)
            if step % 10 == 0 or step >= args.steps:
                ckpt.save(
                    step,
                    {"params": params, "opt_state": opt_state,
                     "step": jax.numpy.array(step)},
                    # durable: the failover drills hard-kill (os._exit)
                    # shortly after a cadence step — the archive must
                    # already be on tmpfs, not in the async serializer
                    durable=True,
                )
            if step >= args.steps:
                break
    finally:
        loader.shutdown()

    loss_val = float(loss) if loss is not None else float("nan")
    # flush the async save pipeline before exit: the final
    # checkpoint must land even though save() no longer blocks
    ckpt.close()
    print(f"FINAL step={step} loss={loss_val:.6f}", flush=True)
    if args.out:
        with open(args.out, "w") as f:
            f.write(f"{step},{loss_val:.6f},{start_step}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
