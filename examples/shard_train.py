"""Shard-fed elastic workload with INDEPENDENT workers (DeepRec shape).

The reference's throughput-autoscaling story (docs/blogs/
deeprec_autoscale_cn.md) runs workers that each pull data shards from
the master and train independently — job throughput is shards/sec, and
adding workers raises it linearly until the input pipeline saturates.
This workload reproduces that shape for the live scale-UP drill
(tests/test_scale_up_drill.py): each worker fetches master shards at a
fixed per-worker rate (``--batch-seconds`` simulated train time per
shard), records completed sample ranges, and exits cleanly when the
dataset is exhausted.

Exactly-once accounting: a completion line is written ONLY after the
master accepted the task result, and SIGTERM (the agent recycling
workers on membership change) defers until the in-flight shard is
reported — so the drill can assert the union of completed ranges
covers the dataset exactly once across the scale transition.
"""

import argparse
import os
import signal
import sys
import time

from dlrover_tpu.agent.master_client import build_master_client
from dlrover_tpu.agent.sharding.client import ShardingClient
from dlrover_tpu.common.constants import NodeEnv


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--dataset-size", type=int, default=4000)
    parser.add_argument("--batch-size", type=int, default=50)
    parser.add_argument("--batch-seconds", type=float, default=0.2,
                        help="simulated train time per shard — fixes "
                             "the per-worker rate so job throughput "
                             "scales with the worker count")
    parser.add_argument("--progress", type=str, required=True)
    args = parser.parse_args()

    node_rank = int(os.getenv(NodeEnv.NODE_RANK, "0"))
    world = int(os.getenv(NodeEnv.NODE_NUM, "1"))
    client = build_master_client()
    sharding = ShardingClient(
        dataset_name="scaleup-drill", batch_size=args.batch_size,
        num_epochs=1, dataset_size=args.dataset_size,
        num_minibatches_per_shard=1, master_client=client,
    )

    stop_requested = {"flag": False}

    def on_term(signum, frame):
        # finish + report the in-flight shard first: dying between a
        # master-side completion and the progress line would break the
        # drill's exactly-once ledger
        stop_requested["flag"] = True

    signal.signal(signal.SIGTERM, on_term)
    print(f"WORLD world={world} rank={node_rank}", flush=True)

    done = 0
    while not stop_requested["flag"]:
        shard = sharding.fetch_shard()
        if shard is None:
            break  # dataset exhausted
        time.sleep(args.batch_seconds)  # the fixed per-worker rate
        if not sharding.report_batch_done():
            # the master did not accept the completion (requeue race
            # during a scale transition): the shard will be re-issued,
            # so writing the line here would double-count the range
            continue
        done += 1
        with open(args.progress, "a") as f:
            f.write(
                f"{shard.start},{shard.end},{node_rank},{world},"
                f"{time.time()}\n"
            )
    print(
        f"FINAL rank={node_rank} world={world} shards={done} "
        f"stopped={stop_requested['flag']}", flush=True,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
