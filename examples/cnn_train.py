"""Elastic-DDP CNN classification — the MNIST-CNN workload shape.

Parity reference: model_zoo/pytorch/mnist/mnist_cnn.py (the reference's
canonical elastic-DDP demo; BASELINE.json config #1). Zero-egress image
data: a procedural "digits" set (class-dependent 28x28 patterns +
noise), streamed through the master's dynamic data sharding exactly
like the reference streams MNIST through ElasticDistributedSampler.

Run under the elastic launcher::

    python -m dlrover_tpu.trainer.elastic_run --standalone \
        examples/cnn_train.py -- --steps 60 --ckpt-dir /tmp/cnn_ckpt
"""

import argparse
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import optax

from dlrover_tpu.agent.master_client import build_master_client
from dlrover_tpu.agent.sharding.client import ShardingClient
from dlrover_tpu.models import cnn
from dlrover_tpu.trainer.checkpoint import FlashCheckpointer
from dlrover_tpu.trainer.distributed import init_from_env
from dlrover_tpu.trainer.elastic import ElasticTrainer


def make_digits(n=2048, size=28, num_classes=10, seed=0):
    """Class-dependent stripe/blob patterns + noise: learnable but not
    trivially separable; no dataset download needed."""
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, num_classes, n).astype(np.int32)
    xs = rng.randn(n, size, size, 1).astype(np.float32) * 0.3
    yy, xx = np.mgrid[0:size, 0:size]
    for cls in range(num_classes):
        mask = labels == cls
        pattern = (
            np.sin(xx * (cls + 1) * np.pi / size)
            + np.cos(yy * (cls + 2) * np.pi / size)
        ).astype(np.float32)[None, :, :, None]
        xs[mask] += pattern
    return xs, labels


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", type=int, default=60)
    parser.add_argument("--batch-size", type=int, default=64)
    parser.add_argument("--ckpt-dir", type=str, default="/tmp/cnn_ckpt")
    parser.add_argument("--out", type=str, default="")
    args = parser.parse_args()

    env = init_from_env()
    client = build_master_client()

    cfg = cnn.mnist_cnn()
    images, labels = make_digits()
    params = cnn.init_params(jax.random.key(0), cfg)
    opt = optax.adam(1e-3)
    opt_state = opt.init(params)

    trainer = ElasticTrainer(
        lambda p, b: cnn.loss(p, b, cfg), opt,
        max_nodes=max(1, env.node_num),
        cur_nodes=max(1, env.node_num), master_client=client,
        report_interval=5,
    )
    ckpt = FlashCheckpointer(
        persist_dir=os.path.join(args.ckpt_dir, "persist"),
        ram_dir=os.path.join(args.ckpt_dir, "ram"),
        persist_interval=0, use_orbax=False,
    )
    state = {"params": params, "opt_state": opt_state,
             "step": jnp.array(0)}
    restored, _ = ckpt.restore(target=state)
    start_step = 0
    if restored is not None:
        state = restored
        start_step = int(state["step"])
        print(f"RESTORED from step {start_step}", flush=True)

    sharding = ShardingClient(
        dataset_name="digits", batch_size=args.batch_size,
        num_epochs=10**6, dataset_size=len(images), shuffle=True,
        num_minibatches_per_shard=1, master_client=client,
    )

    params, opt_state = state["params"], state["opt_state"]
    step = start_step
    loss = None
    while step < args.steps:
        shard = sharding.fetch_shard()
        if shard is None:
            break
        idx = (
            shard.record_indices
            if getattr(shard, "record_indices", None)
            else list(range(shard.start, shard.end))
        )
        xb, yb = images[idx], labels[idx]
        pad = args.batch_size - len(xb)
        if pad > 0:
            xb = np.pad(xb, ((0, pad), (0, 0), (0, 0), (0, 0)))
            # label -1 marks padding; cnn.loss masks it out of the CE
            yb = np.pad(yb, ((0, pad),), constant_values=-1)
        batch = (xb[None], yb[None])  # single microbatch layout
        params, opt_state, loss = trainer.train_step(
            params, opt_state, batch
        )
        sharding.report_batch_done()
        step += 1
        trainer.report_step(step)
        if step % 10 == 0 or step == args.steps:
            ckpt.save(
                step,
                {"params": params, "opt_state": opt_state,
                 "step": jnp.array(step)},
                # durable: the failover drills hard-kill (os._exit)
                # shortly after a cadence step — the archive must
                # already be on tmpfs, not in the async serializer
                durable=True,
            )

    loss_val = float(loss) if loss is not None else float("nan")
    # training accuracy on a fixed probe batch
    logits = cnn.forward(params, jnp.asarray(images[:256]), cfg)
    acc = float(
        jnp.mean(jnp.argmax(logits, -1) == jnp.asarray(labels[:256]))
    )
    # flush the async save pipeline before exit: the final
    # checkpoint must land even though save() no longer blocks
    ckpt.close()
    print(f"FINAL step={step} loss={loss_val:.6f} acc={acc:.3f}",
          flush=True)
    if args.out:
        with open(args.out, "w") as f:
            f.write(f"{step},{loss_val:.6f},{acc:.3f},{start_step}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
