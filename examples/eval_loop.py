"""Evaluator side-job entrypoint: eval loop over flash checkpoints.

Launched by the master's scaler for ``spec.evaluator`` replicas::

    spec:
      worker: {replicas: 4, command: [...train...]}
      evaluator:
        replicas: 1
        command: [python, examples/eval_loop.py, --ckpt-dir, /ckpt]

The loop (trainer/evaluator.py) watches the training job's flash
checkpoints, computes eval loss on a held-out batch for every new
step, and reports results into the master's stats pipeline. Parity
role: the reference's estimator evaluator replica
(master/node/worker.py:32 EvaluatorManager).
"""

import argparse


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--ckpt-dir", required=True)
    parser.add_argument("--dim", type=int, default=16)
    parser.add_argument("--eval-batch", type=int, default=64)
    parser.add_argument("--poll", type=float, default=5.0)
    parser.add_argument("--max-evals", type=int, default=0)
    parser.add_argument("--out", default="")
    args = parser.parse_args()

    import jax.numpy as jnp
    import numpy as np

    from dlrover_tpu.trainer.evaluator import run_evaluator_from_env

    # held-out data from a seed the training stream never uses
    rng = np.random.RandomState(9999)
    w_true = np.random.RandomState(0).randn(args.dim, 1).astype(
        np.float32
    )
    x = rng.randn(args.eval_batch, args.dim).astype(np.float32)
    y = (x @ w_true).astype(np.float32)

    def eval_fn(state, step):
        params = state["params"]
        pred = x @ np.asarray(params["w"]) + np.asarray(params["b"])
        loss = float(jnp.mean((pred - y) ** 2))
        if args.out:
            with open(args.out, "a") as f:
                f.write(f"{step},{loss:.6f}\n")
        return {"loss": loss}

    n = run_evaluator_from_env(
        eval_fn, ckpt_dir=args.ckpt_dir, poll_interval=args.poll,
        max_evals=args.max_evals or None,
    )
    print(f"EVALUATOR done after {n} evals", flush=True)


if __name__ == "__main__":
    main()
