"""Two-slice elastic workload: live hybrid ICI x DCN mesh (soak drill).

Run by tests/test_slice_soak_drill.py under the elastic launcher: each
process is one "host" of a mocked TPU slice (slice id = node_rank //
DLROVER_TPU_SLICE_SIZE). Every incarnation builds the hybrid mesh LIVE
over the re-formed jax.distributed world — the DCN axis spans slices,
the ICI axis spans hosts within a slice — so killing a whole slice
shrinks the DCN axis from 2 to 1 in the next incarnation's mesh, while
gradients keep psum-ing over BOTH axes every step.

Fault surface:
  * DLROVER_TPU_DEAD_SLICE_FILE — while the file exists, processes
    whose slice id appears in it exit(43) immediately (a preempted
    slice has no capacity: relaunches die until the master prunes it);
  * the master-KV fault injector (fault_tolerance/injection.py) is
    polled every step, so the drill can target one rank with
    ``crash@now:137`` (OOM-class death -> the agent escalates to the
    master's grow-and-relaunch path) without touching the others.

Progress lines: ``step,world,dcn,loss,unix_ts``.
"""

import argparse
import os
import sys
import time


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", type=int, default=400)
    parser.add_argument("--per-proc-batch", type=int, default=8)
    parser.add_argument("--dim", type=int, default=16)
    parser.add_argument("--ckpt-dir", type=str, required=True)
    parser.add_argument("--progress", type=str, required=True)
    parser.add_argument("--step-time", type=float, default=0.25)
    args = parser.parse_args()

    from dlrover_tpu.common.constants import NodeEnv

    node_rank = int(os.getenv(NodeEnv.NODE_RANK, "0"))
    slice_size = int(os.getenv("DLROVER_TPU_SLICE_SIZE", "4"))
    slice_id = node_rank // slice_size
    dead_file = os.getenv("DLROVER_TPU_DEAD_SLICE_FILE", "")

    def slice_dead() -> bool:
        if not dead_file or not os.path.exists(dead_file):
            return False
        try:
            dead = {
                int(x) for x in open(dead_file).read().split() if x
            }
        except ValueError:
            return False
        return slice_id in dead

    if slice_dead():
        print(f"SLICE {slice_id} DEAD: exiting", flush=True)
        os._exit(43)

    # one device per mocked host: the drill env may carry the test
    # suite's 8-virtual-device setting, which would explode the world
    # to 64 devices of collectives on one core
    os.environ["JAX_NUM_CPU_DEVICES"] = "1"
    import jax

    try:
        jax.config.update("jax_num_cpu_devices", 1)
    except Exception:
        pass

    from dlrover_tpu.trainer.checkpoint import FlashCheckpointer
    from dlrover_tpu.trainer.distributed import init_from_env

    init_from_env()
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dlrover_tpu.parallel.mesh import create_hybrid_mesh

    world = jax.process_count()
    n_slices = max(1, (world + slice_size - 1) // slice_size)
    # LIVE hybrid mesh over the re-formed world: data over DCN
    # (slices), fsdp over ICI (the devices within a slice)
    n_dev = len(jax.devices())
    mesh = create_hybrid_mesh(
        [("fsdp", n_dev // n_slices)], [("data", n_slices)]
    )
    dcn = mesh.shape["data"]
    print(
        f"HYBRID MESH world={world} dcn={dcn} ici={mesh.shape['fsdp']}"
        f" slice={slice_id}", flush=True,
    )
    repl = NamedSharding(mesh, P())
    # batch over BOTH axes: every grad psum crosses DCN and ICI
    batch_sh = NamedSharding(mesh, P(("data", "fsdp")))

    rng = np.random.RandomState(0)
    w_true = rng.randn(args.dim, 1).astype(np.float32)

    import optax

    def loss_fn(params, batch):
        x, y = batch
        pred = x @ params["w"] + params["b"]
        return jnp.mean((pred - y) ** 2)

    opt = optax.adam(0.05)

    @jax.jit
    def train_step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    params = {"w": jnp.zeros((args.dim, 1)), "b": jnp.zeros((1,))}
    opt_state = opt.init(params)
    params = jax.device_put(params, repl)

    ckpt = FlashCheckpointer(
        persist_dir=os.path.join(args.ckpt_dir, "persist"),
        ram_dir=os.path.join(args.ckpt_dir, "ram"),
        persist_interval=0, use_orbax=False,
    )
    state = {"params": params, "opt_state": opt_state,
             "step": jnp.array(0)}
    restored, _ = ckpt.restore(target=state)
    start_step = 0
    if restored is not None:
        state = restored
        start_step = int(state["step"])
        print(f"RESTORED from step {start_step}", flush=True)
    params = jax.device_put(jax.device_get(state["params"]), repl)
    opt_state = jax.device_put(jax.device_get(state["opt_state"]), repl)

    # master plumbing: rank 0 feeds the speed monitor; EVERY process
    # polls the KV fault injector so the drill can target one rank
    client = None
    injector = None
    if os.getenv(NodeEnv.MASTER_ADDR):
        try:
            from dlrover_tpu.agent.master_client import (
                build_master_client,
            )
            from dlrover_tpu.fault_tolerance.injection import (
                FaultInjector,
            )

            client = build_master_client()
            injector = FaultInjector(
                "", master_client=client, node_rank=node_rank,
                poll_every=2,
            )
        except Exception:
            client = injector = None

    n_local = args.per_proc_batch * jax.local_device_count()
    global_batch = n_local * world
    step = start_step
    loss_val = float("nan")
    while step < args.steps:
        t0 = time.time()
        if slice_dead():
            print(f"SLICE {slice_id} DEAD at step {step}", flush=True)
            os._exit(43)
        seed = 1000 * step + jax.process_index()
        r = np.random.RandomState(seed)
        xl = r.randn(n_local, args.dim).astype(np.float32)
        yl = (xl @ w_true).astype(np.float32)
        x = jax.make_array_from_process_local_data(
            batch_sh, xl, (global_batch, args.dim))
        y = jax.make_array_from_process_local_data(
            batch_sh, yl, (global_batch, 1))
        params, opt_state, loss = train_step(params, opt_state, (x, y))
        loss_val = float(loss)
        step += 1
        if injector is not None:
            injector.maybe_inject(step)
        # drill determinism: the auto-scaler gates straggler action on
        # reported training progress; the drill opens the report gate
        # only after the master's node view has settled, sequencing
        # the transitions (slice kill first, straggler policy second)
        report_gate = os.getenv("DLROVER_TPU_REPORT_GATE", "")
        if client is not None and jax.process_index() == 0 and (
            step % 5 == 0
            and (not report_gate or os.path.exists(report_gate))
        ):
            try:
                client.report_global_step(step)
            except Exception:
                pass
        if jax.process_index() == 0:
            with open(args.progress, "a") as f:
                f.write(
                    f"{step},{world},{dcn},{loss_val:.6f},{time.time()}\n"
                )
        if step % 5 == 0 or step == args.steps:
            ckpt.save(
                step,
                {"params": jax.device_get(params),
                 "opt_state": jax.device_get(opt_state),
                 "step": jnp.array(step)},
                # durable: the failover drills hard-kill (os._exit)
                # shortly after a cadence step — the archive must
                # already be on tmpfs, not in the async serializer
                durable=True,
            )
        dt = time.time() - t0
        # simulated data-parallel speedup: a bigger world steps faster,
        # so the speed monitor sees real per-worker throughput (the
        # plateau veto must not block restoring a preempted slice)
        floor = args.step_time * 8.0 / max(world, 1)
        if dt < floor:
            time.sleep(floor - dt)

    # flush the async save pipeline before exit: the final
    # checkpoint must land even though save() no longer blocks
    ckpt.close()
    print(f"FINAL step={step} loss={loss_val:.6f} world={world}",
          flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
