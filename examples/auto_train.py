"""auto_accelerate end to end: search the strategy space, train with
the winner, save it for the next (possibly resized) run.

Run directly (uses all local devices)::

    python examples/auto_train.py --steps 20 --dryrun-top-k 2
    python examples/auto_train.py --load-strategy /tmp/strategy.json

Parity role: the reference's semi-automatic `auto_accelerate(model,
optim_func, dataset, ...)` usage (atorch/examples) — here the search is
a plain function of the model config and the cluster (no rank-0 engine
choreography), and the saved strategy refits its data-parallel dim when
the device count changes (auto/accelerate.py adjust_strategy).
"""

import argparse
import os
import sys

# runnable directly (python examples/auto_train.py) without pip install
sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

import jax
import numpy as np
import optax

from dlrover_tpu.auto.accelerate import auto_accelerate
from dlrover_tpu.auto.strategy import save_strategy
from dlrover_tpu.models import llama


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--global-batch", type=int, default=8)
    ap.add_argument("--seq-len", type=int, default=64)
    ap.add_argument("--dryrun-top-k", type=int, default=0)
    ap.add_argument("--bo-iters", type=int, default=0)
    ap.add_argument("--save-strategy", type=str, default="")
    ap.add_argument("--load-strategy", type=str, default="")
    args = ap.parse_args()

    cfg = llama.llama_tiny()
    result = auto_accelerate(
        cfg,
        global_batch=args.global_batch,
        seq_len=args.seq_len,
        dryrun_top_k=args.dryrun_top_k,
        bo_iters=args.bo_iters,
        load_strategy_path=args.load_strategy or None,
        optimizer=optax.adamw(1e-3),
    )
    print(f"strategy: {result.strategy}")
    if args.save_strategy:
        save_strategy(result.strategy, args.save_strategy)
        print(f"saved -> {args.save_strategy}")

    trainer = result.trainer
    params, opt_state = trainer.init(jax.random.key(0))
    rng = np.random.default_rng(0)
    tokens = rng.integers(
        0, cfg.vocab_size, (args.global_batch, args.seq_len),
        dtype=np.int32,
    )
    batch = trainer.shard_batch(trainer.microbatch((tokens, tokens)))
    loss = None
    for step in range(1, args.steps + 1):
        params, opt_state, loss = trainer.train_step(
            params, opt_state, batch
        )
        if step % 5 == 0:
            print(f"step {step}: loss={float(loss):.4f}")
    loss_val = float(loss) if loss is not None else float("nan")
    print(f"FINAL loss={loss_val:.4f}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
