"""Streaming-source elastic workload (unbounded-splitter partitions).

The master-side streaming pipeline (StreamingDatasetSplitter partition
offsets -> StreamingDatasetManager tasks) consumed end to end through
the launcher: the worker fetches partition-offset shards, simulates
train time, and records completed ranges. ``--crash-after N`` makes
incarnation 0 FETCH one more shard and die WITHOUT reporting it — the
orphaned in-flight offset range must be re-delivered (task-timeout
watchdog / node-failure recovery) to the restarted worker, never lost
and never duplicated. Right before dying it snapshots the master's
shard checkpoint (the get_shard_checkpoint RPC) so the drill can
assert the orphan really was tracked as in-flight.

Parity: dlrover/python/master/shard/dataset_splitter.py:359
(StreamingDatasetSplitter) + streaming_dataset_manager.py:32.
"""

import argparse
import os
import sys
import time

from dlrover_tpu.agent.master_client import build_master_client
from dlrover_tpu.agent.sharding.client import ShardingClient
from dlrover_tpu.common.constants import NodeEnv


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--total", type=int, default=2000,
                        help="bounded stream length (so the run ends)")
    parser.add_argument("--batch-size", type=int, default=100)
    parser.add_argument("--batch-seconds", type=float, default=0.05)
    parser.add_argument("--crash-after", type=int, default=0,
                        help="incarnation 0 dies after N completions "
                             "with one shard fetched but unreported")
    parser.add_argument("--progress", type=str, required=True)
    args = parser.parse_args()

    restart = int(os.getenv(NodeEnv.RESTART_COUNT, "0"))
    client = build_master_client()
    sharding = ShardingClient(
        dataset_name="stream-e2e", batch_size=args.batch_size,
        num_epochs=1, dataset_size=args.total,
        num_minibatches_per_shard=1, master_client=client,
        storage_type="stream",
    )
    print(f"WORLD restart={restart}", flush=True)

    done = 0
    while True:
        shard = sharding.fetch_shard()
        if shard is None:
            break
        if args.crash_after and restart == 0 and done >= args.crash_after:
            # die with this shard IN FLIGHT (fetched, never reported):
            # the master must re-deliver exactly this offset range
            ckpt = sharding.get_shard_checkpoint()
            print(f"SHARD_CKPT {ckpt}", flush=True)
            print(
                f"CRASH holding {shard.name}:{shard.start}-{shard.end}",
                flush=True,
            )
            os._exit(17)
        time.sleep(args.batch_seconds)
        if not sharding.report_batch_done():
            continue
        done += 1
        with open(args.progress, "a") as f:
            f.write(
                f"{shard.name},{shard.start},{shard.end},{restart},"
                f"{time.time()}\n"
            )
    print(f"FINAL restart={restart} shards={done}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
