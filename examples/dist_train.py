"""Multi-host elastic training workload: real ``jax.distributed`` world.

Run one launcher per "host" against a shared master::

    dlrover-tpu-run --master_addr HOST:PORT --nnodes 1:2 --node_rank R \
        examples/dist_train.py -- --steps 40 --ckpt-dir /tmp/dist_ckpt_R

Each process contributes its slice of the global batch (sharded over the
``data`` mesh axis), so every train step runs a cross-process gradient
psum — killing a peer stalls the survivor's collectives, which is exactly
what the elastic machinery must recover from: the coordination-service
heartbeat (DLROVER_TPU_DIST_HEARTBEAT_TIMEOUT) kills the stalled process,
the master's watchdog prunes the dead node, the agent re-rendezvouses,
and training resumes from the flash checkpoint in the surviving world.

Progress is appended to ``--progress`` as ``step,world,loss,unix_ts``
lines — the failover drill derives its recovery_seconds metric from them.
Parity role: the reference's multi-node system tests
(.github/actions/dlrover-system-test-*/action.yaml).
"""

import argparse
import os
import sys
import time

from dlrover_tpu.trainer.checkpoint import FlashCheckpointer
from dlrover_tpu.trainer.distributed import init_from_env


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", type=int, default=40)
    parser.add_argument("--per-proc-batch", type=int, default=8)
    parser.add_argument("--dim", type=int, default=16)
    parser.add_argument("--ckpt-dir", type=str, default="/tmp/dist_ckpt")
    parser.add_argument("--progress", type=str, default="")
    parser.add_argument("--out", type=str, default="")
    parser.add_argument("--step-time", type=float, default=0.2,
                        help="min seconds per step (keeps the drill's "
                             "kill window wide)")
    args = parser.parse_args()

    env = init_from_env()

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    world = jax.process_count()
    mesh = Mesh(np.array(jax.devices()), ("data",))
    repl = NamedSharding(mesh, P())
    data_sh = NamedSharding(mesh, P("data"))
    print(f"WORLD process_count={world} pid={jax.process_index()}",
          flush=True)

    rng = np.random.RandomState(0)
    w_true = rng.randn(args.dim, 1).astype(np.float32)

    def loss_fn(params, batch):
        x, y = batch
        pred = x @ params["w"] + params["b"]
        return jnp.mean((pred - y) ** 2)

    opt = optax.adam(0.05)

    @jax.jit
    def train_step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    params = {"w": jnp.zeros((args.dim, 1)), "b": jnp.zeros((1,))}
    opt_state = opt.init(params)
    params = jax.device_put(params, repl)

    # peer shard tier (docs/CHECKPOINT.md "Format v2"): serve this
    # process's RAM archives over /ckpt/shard and advertise them in
    # the master KV, so a relaunched peer restores hot shards from
    # survivors instead of the persist store. Every piece is guarded:
    # masterless runs (no DLROVER_TPU_MASTER_ADDR) train as before.
    peer_registry = None
    shard_server = None
    if os.getenv("DLROVER_TPU_MASTER_ADDR"):
        try:
            from dlrover_tpu.agent.master_client import (
                build_master_client,
            )
            from dlrover_tpu.agent.elastic.training import _local_ip
            from dlrover_tpu.checkpoint.peer import PeerRegistry
            from dlrover_tpu.telemetry.http import (
                set_shard_provider,
                start_metrics_server,
            )

            shard_server = start_metrics_server()
            if shard_server is not None:
                url = f"http://{_local_ip()}:{shard_server.port}"
                peer_registry = PeerRegistry(
                    build_master_client(), jax.process_index(), url)
        except Exception:
            peer_registry = None

    ckpt = FlashCheckpointer(
        persist_dir=os.path.join(args.ckpt_dir, "persist"),
        ram_dir=os.path.join(args.ckpt_dir, "ram"),
        persist_interval=0, use_orbax=False,
        peer_registry=peer_registry,
    )
    if shard_server is not None:
        set_shard_provider(ckpt.shard_provider())
    state = {"params": params, "opt_state": opt_state,
             "step": jnp.array(0)}
    restored, _ = ckpt.restore(target=state)
    start_step = 0
    if restored is not None:
        state = restored
        start_step = int(state["step"])
        print(f"RESTORED from step {start_step}", flush=True)
    params, opt_state = state["params"], state["opt_state"]
    params = jax.device_put(jax.device_get(params), repl)
    opt_state = jax.device_put(jax.device_get(opt_state), repl)

    # rank 0 feeds the master's speed monitor (the trainer contract,
    # trainer/elastic.py) — the auto-scaler gates scaling/straggler
    # shrink on training actually progressing
    step_reporter = None
    if jax.process_index() == 0 and os.getenv("DLROVER_TPU_MASTER_ADDR"):
        try:
            from dlrover_tpu.agent.master_client import (
                build_master_client,
            )

            step_reporter = build_master_client()
        except Exception:
            step_reporter = None

    n_local = args.per_proc_batch * jax.local_device_count()
    global_batch = n_local * world
    step = start_step
    loss = None
    while step < args.steps:
        t0 = time.time()
        # deterministic per-(step, process) slice of a global batch
        seed = 1000 * step + jax.process_index()
        r = np.random.RandomState(seed)
        xl = r.randn(n_local, args.dim).astype(np.float32)
        yl = (xl @ w_true).astype(np.float32)
        x = jax.make_array_from_process_local_data(
            data_sh, xl, (global_batch, args.dim))
        y = jax.make_array_from_process_local_data(
            data_sh, yl, (global_batch, 1))
        params, opt_state, loss = train_step(params, opt_state, (x, y))
        loss_val = float(loss)
        step += 1
        if step_reporter is not None and step % 5 == 0:
            try:
                step_reporter.report_global_step(step)
            except Exception:
                pass
        if args.progress:
            with open(args.progress, "a") as f:
                f.write(f"{step},{world},{loss_val:.6f},{time.time()}\n")
        if step % 5 == 0 or step == args.steps:
            ckpt.save(
                step,
                {"params": jax.device_get(params),
                 "opt_state": jax.device_get(opt_state),
                 "step": jnp.array(step)},
                # durable: the failover drills hard-kill (os._exit)
                # shortly after a cadence step — the archive must
                # already be on tmpfs, not in the async serializer
                durable=True,
            )
        dt = time.time() - t0
        if dt < args.step_time:
            time.sleep(args.step_time - dt)

    loss_val = float(loss) if loss is not None else float("nan")
    # flush the async save pipeline before exit: the final
    # checkpoint must land even though save() no longer blocks
    ckpt.close()
    print(f"FINAL step={step} loss={loss_val:.6f} world={world}",
          flush=True)
    if args.out:
        with open(args.out, "w") as f:
            f.write(f"{step},{loss_val:.6f},{start_step},{world}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
