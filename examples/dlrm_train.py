"""Elastic sparse-embedding recommender training (CRITEO workload shape).

Parity reference: the reference trains CRITEO Wide&Deep/xDeepFM under
elastic PS (model_zoo/tf_estimator/criteo_deeprec/train.py role;
BASELINE config #4 — the DeepRec autoscaling blog's job). TPU shape:
no PS — the stacked embedding table shards over the mesh
(models/dlrm.py), fed by the master's dynamic data sharding exactly
like the other families. Zero-egress data: a procedural click stream
with planted per-id effects (learnable, not separable).

Run under the elastic launcher::

    python -m dlrover_tpu.trainer.elastic_run --standalone \
        examples/dlrm_train.py -- --steps 60 --ckpt-dir /tmp/dlrm_ckpt
"""

import argparse
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np

from dlrover_tpu.agent.master_client import build_master_client
from dlrover_tpu.agent.sharding.client import ShardingClient
from dlrover_tpu.models import dlrm
from dlrover_tpu.trainer.checkpoint import FlashCheckpointer
from dlrover_tpu.trainer.distributed import init_from_env


def make_clicks(n, cfg, seed=0, hot_per_feature=50):
    """Procedural CTR data: each feature has a small set of hot ids
    with planted logit effects, plus dense-feature effects — a
    learnable logistic ground truth over exactly the table rows the
    run will touch."""
    rng = np.random.RandomState(seed)
    dense = rng.randn(n, cfg.dense_dim).astype(np.float32)
    hot = [min(s, hot_per_feature) for s in cfg.vocab_sizes]
    cat = np.stack(
        [rng.randint(0, h, n) for h in hot], axis=1
    ).astype(np.int32)
    logit = np.zeros(n, np.float32)
    for j, h in enumerate(hot):
        w = rng.randn(h).astype(np.float32) * 0.8
        logit += w[cat[:, j]]
    logit += dense[:, 0] * 0.5 - dense[:, 1] * 0.5
    prob = 1.0 / (1.0 + np.exp(-logit))
    labels = (rng.rand(n) < prob).astype(np.int32)
    return dense, cat, labels


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", type=int, default=60)
    parser.add_argument("--batch-size", type=int, default=128)
    parser.add_argument("--ckpt-dir", type=str, default="/tmp/dlrm_ckpt")
    parser.add_argument("--out", type=str, default="")
    args = parser.parse_args()

    init_from_env()
    client = build_master_client()

    cfg = dlrm.criteo_wide_deep()
    dense, cat, labels = make_clicks(4096, cfg)
    trainer = dlrm.make_trainer(cfg)
    # hang detection + fault injection ride on the elastic reporter
    # (the compute path is the ShardedTrainer above)
    from dlrover_tpu.trainer.elastic import ElasticTrainer

    reporter = ElasticTrainer(
        lambda p, b: 0.0, None, max_nodes=1, cur_nodes=1,
        master_client=client, report_interval=5,
    )

    ckpt = FlashCheckpointer(
        persist_dir=os.path.join(args.ckpt_dir, "persist"),
        ram_dir=os.path.join(args.ckpt_dir, "ram"),
        persist_interval=0, use_orbax=False,
    )
    params, opt_state = trainer.init(jax.random.key(0))
    state = {"params": params, "opt_state": opt_state,
             "step": jnp.array(0)}
    restored, _ = ckpt.restore(target=state)
    start_step = 0
    if restored is not None:
        state = restored
        start_step = int(state["step"])
        print(f"RESTORED from step {start_step}", flush=True)

    sharding = ShardingClient(
        dataset_name="clicks", batch_size=args.batch_size,
        num_epochs=10**6, dataset_size=len(labels), shuffle=True,
        num_minibatches_per_shard=1, master_client=client,
    )

    params, opt_state = state["params"], state["opt_state"]
    step = start_step
    loss = None
    while step < args.steps:
        shard = sharding.fetch_shard()
        if shard is None:
            break
        idx = (
            shard.record_indices
            if getattr(shard, "record_indices", None)
            else list(range(shard.start, shard.end))
        )
        db, cb, yb = dense[idx], cat[idx], labels[idx]
        pad = args.batch_size - len(yb)
        if pad > 0:
            db = np.pad(db, ((0, pad), (0, 0)))
            cb = np.pad(cb, ((0, pad), (0, 0)))
            # label -1 marks padding; dlrm.loss masks it out of the BCE
            yb = np.pad(yb, ((0, pad),), constant_values=-1)
        batch = trainer.shard_batch((db[None], cb[None], yb[None]))
        params, opt_state, loss = trainer.train_step(
            params, opt_state, batch
        )
        sharding.report_batch_done()
        step += 1
        reporter.report_step(step)
        if step % 10 == 0 or step == args.steps:
            ckpt.save(
                step,
                {"params": params, "opt_state": opt_state,
                 "step": jnp.array(step)},
                # durable: the failover drills hard-kill (os._exit)
                # shortly after a cadence step — the archive must
                # already be on tmpfs, not in the async serializer
                durable=True,
            )

    loss_val = float(loss) if loss is not None else float("nan")
    # training accuracy on a fixed probe slice (jit: eager shard_map
    # collectives can trip XLA CPU's stuck-rendezvous watchdog)
    logits = jax.jit(
        lambda p, d, c: dlrm.forward(p, d, c, cfg, mesh=trainer.mesh)
    )(params, jnp.asarray(dense[:512]), jnp.asarray(cat[:512]))
    acc = float(jnp.mean(
        (logits > 0).astype(jnp.int32) == jnp.asarray(labels[:512])
    ))
    # flush the async save pipeline before exit: the final
    # checkpoint must land even though save() no longer blocks
    ckpt.close()
    print(f"FINAL step={step} loss={loss_val:.6f} acc={acc:.3f}",
          flush=True)
    if args.out:
        with open(args.out, "w") as f:
            f.write(f"{step},{loss_val:.6f},{acc:.3f},{start_step}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
