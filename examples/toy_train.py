"""Toy elastic training workload: the minimum end-to-end slice.

Run under the elastic launcher::

    python -m dlrover_tpu.trainer.elastic_run --standalone \
        examples/toy_train.py -- --steps 50 --ckpt-dir /tmp/toy_ckpt

Exercises the full stack: agent rendezvous -> env bootstrap -> master data
sharding -> jitted accumulation train step -> flash checkpoint save; on
restart (failure or membership change) it restores from the RAM tier and
continues from the saved step. Parity role: model_zoo/pytorch/mnist of the
reference (the CI smoke workload).
"""

import argparse
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import optax

from dlrover_tpu.agent.master_client import build_master_client
from dlrover_tpu.agent.sharding.client import ShardingClient
from dlrover_tpu.trainer.checkpoint import FlashCheckpointer
from dlrover_tpu.trainer.distributed import init_from_env
from dlrover_tpu.trainer.elastic import ElasticTrainer


def make_data(n=512, dim=8, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, dim).astype(np.float32)
    w_true = rng.randn(dim, 1).astype(np.float32)
    y = x @ w_true + 0.01 * rng.randn(n, 1).astype(np.float32)
    return x, y


def loss_fn(params, batch):
    x, y = batch
    pred = x @ params["w"] + params["b"]
    return jnp.mean((pred - y) ** 2)


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", type=int, default=50)
    parser.add_argument("--batch-size", type=int, default=32)
    parser.add_argument("--ckpt-dir", type=str, default="/tmp/toy_ckpt")
    parser.add_argument("--out", type=str, default="")
    args = parser.parse_args()

    env = init_from_env()
    client = build_master_client()

    x, y = make_data()
    params = {"w": jnp.zeros((8, 1)), "b": jnp.zeros((1,))}
    opt = optax.adam(0.1)
    opt_state = opt.init(params)

    trainer = ElasticTrainer(
        loss_fn, opt, max_nodes=max(1, env.node_num),
        cur_nodes=max(1, env.node_num), master_client=client,
        report_interval=5,
    )
    ckpt = FlashCheckpointer(
        persist_dir=os.path.join(args.ckpt_dir, "persist"),
        ram_dir=os.path.join(args.ckpt_dir, "ram"),
        persist_interval=0, use_orbax=False,
    )
    state = {"params": params, "opt_state": opt_state,
             "step": jnp.array(0)}
    restored, step0 = ckpt.restore(target=state)
    start_step = 0
    if restored is not None:
        state = restored
        start_step = int(state["step"])
        print(f"RESTORED from step {start_step}", flush=True)

    sharding = ShardingClient(
        dataset_name="toy", batch_size=args.batch_size,
        num_epochs=10**6, dataset_size=len(x),
        num_minibatches_per_shard=1, master_client=client,
    )

    params, opt_state = state["params"], state["opt_state"]
    step = start_step
    loss = None
    # wire the trainer's drain coordinator to the live state: a
    # preemption notice (SIGTERM) lands an emergency checkpoint of the
    # CURRENT step inside the notice window, instead of falling back
    # to the last cadenced save
    cur = {"step": step, "state": state}
    trainer.attach_checkpointer(ckpt)
    sent = trainer.sentinel
    trainer.drain.set_state_provider(
        lambda: (cur["step"], cur["state"])
    )
    while step < args.steps:
        shard = sharding.fetch_shard()
        if shard is None:
            break
        xb = x[shard.start:shard.end]
        yb = y[shard.start:shard.end]
        # pad to fixed shape so every step hits the same compiled program
        pad = args.batch_size - len(xb)
        if pad > 0:
            xb = np.pad(xb, ((0, pad), (0, 0)))
            yb = np.pad(yb, ((0, pad), (0, 0)))
        batch = (xb[None], yb[None])  # single microbatch layout
        params, opt_state, loss = trainer.train_step(
            params, opt_state, batch
        )
        sharding.report_batch_done()
        step += 1
        # the sentinel inspects the loss scalar (and a corruption
        # drill poisons it on the way in)
        trainer.report_step(step, loss=loss)
        if sent is not None and sent.pending_rollback() is not None:
            # coordinated rollback: restore the master-ordered last
            # sentinel-clean step and replay from there — the poisoned
            # window never reaches the final state
            order = sent.pending_rollback()
            rolled, got = ckpt.restore(
                target=cur["state"], step=order["step"]
            )
            if rolled is not None:
                state = rolled
                params, opt_state = state["params"], state["opt_state"]
                step = int(state["step"])
                sent.note_restored(step, order["id"])
                print(f"ROLLBACK to step {step}", flush=True)
        # host copies: train_step donates (params, opt_state), so the
        # signal-time emergency save must not read device buffers the
        # next dispatch may have invalidated
        cur["step"], cur["state"] = step, jax.device_get({
            "params": params, "opt_state": opt_state,
            "step": jnp.array(step),
        })
        if step % 10 == 0 or step == args.steps:
            ckpt.save(
                step,
                {"params": params, "opt_state": opt_state,
                 "step": jnp.array(step)},
                # durable: the failover drills hard-kill (os._exit)
                # shortly after a cadence step — the archive must
                # already be on tmpfs, not in the async serializer
                durable=True,
            )
            if sent is not None:
                # ignored inside an anomaly window: a tainted save is
                # never a rollback target
                sent.note_checkpoint(step)

    # loss stays None when the loop body never ran (e.g. restored checkpoint
    # already at/after --steps, or the dataset was exhausted immediately)
    loss_val = float(loss) if loss is not None else float("nan")
    # flush the async save pipeline before exit: the final
    # checkpoint must land even though save() no longer blocks
    ckpt.close()
    print(f"FINAL step={step} loss={loss_val:.6f}", flush=True)
    if args.out:
        with open(args.out, "w") as f:
            f.write(f"{step},{loss_val:.6f},{start_step}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
