"""Elastic inference tier end to end: continuous-batching replicas over
a trained artifact, with graceful rotation.

Standalone (embedded master + N replica threads + a load generator)::

    JAX_PLATFORMS=cpu python examples/serve.py --replicas 2 \
        --requests 200

Against a running master (this process becomes ONE replica; run it
once per node id)::

    python examples/serve.py --master_addr localhost:PORT --node_id 0 \
        --ckpt_dir /tmp/job-ckpt

The model here is a toy (echo + weight checksum), but the plumbing is
the real one: requests lease through the master's RequestRouter with
exactly-once redelivery, replicas load weights through the
flash-checkpoint RAM tier, SIGTERM rotates a replica out with zero
dropped responses (rc 21), and the pool autoscales on queue depth.
See docs/SERVING.md.
"""

import argparse
import os
import sys
import threading
import time

# runnable directly (python examples/serve.py) without pip install
sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

import numpy as np

from dlrover_tpu.serving import ServingAutoScaler, ServingWorker


def _init_state():
    return {"w": np.arange(64, dtype=np.float32)}


def _model_fn(payloads, state):
    tag = b"#%d" % int(state["w"].sum())
    return [p.upper() + tag for p in payloads]


def _make_checkpointer(ckpt_dir: str, ram_dir: str = ""):
    if not ckpt_dir:
        return None
    from dlrover_tpu.trainer.checkpoint import FlashCheckpointer

    return FlashCheckpointer(
        persist_dir=ckpt_dir, ram_dir=ram_dir or None, use_orbax=False,
    )


def run_replica(args) -> int:
    """One elastic serving replica against a live master — the per-node
    entrypoint a real deployment launches (and relaunches: rc 21 from a
    rotation means 'clean drain', budget-free)."""
    from dlrover_tpu.agent.master_client import MasterClient

    client = MasterClient(
        args.master_addr, node_id=args.node_id, node_type="worker",
    )
    worker = ServingWorker(
        client, _model_fn, node_id=args.node_id,
        checkpointer=_make_checkpointer(args.ckpt_dir, args.ram_dir),
        init_state_fn=_init_state, batch_size=args.batch,
        status_interval=2.0,
    )
    served = worker.serve()
    print(f"replica {args.node_id}: served {served} requests")
    client.close()
    return 0


def run_standalone(args) -> int:
    """Embedded master + replica threads + load generator in one
    process: the smallest end-to-end serving demo."""
    from dlrover_tpu.agent.master_client import MasterClient
    from dlrover_tpu.master.local_master import LocalJobMaster

    os.environ.setdefault("DLROVER_TPU_METRICS_PORT", "off")
    master = LocalJobMaster(port=0)
    master.prepare()
    print(f"master on {master.addr}")

    clients = [
        MasterClient(master.addr, node_id=i, node_type="worker")
        for i in range(args.replicas)
    ]
    replicas = [
        ServingWorker(
            c, _model_fn, node_id=i,
            checkpointer=_make_checkpointer(args.ckpt_dir, args.ram_dir),
            init_state_fn=_init_state, batch_size=args.batch,
            poll_interval=0.005,
        )
        for i, c in enumerate(clients)
    ]
    threads = [
        threading.Thread(target=r.serve, daemon=True) for r in replicas
    ]
    for t in threads:
        t.start()

    lb = MasterClient(master.addr, node_id=args.replicas,
                      node_type="worker")
    # pool autoscaling on measured queue depth: the demo scale_fn just
    # reports the decision (a platform wires it to real capacity)
    scaler = ServingAutoScaler(
        stats_fn=lb.serve_stats,
        scale_fn=lambda n: print(f"autoscale -> {n} replicas"),
        min_replicas=1, max_replicas=args.replicas + 2,
        queue_high=max(8, args.batch * args.replicas), interval=0.5,
    )
    scaler.start()

    t0 = time.perf_counter()
    req_ids = []
    for i in range(args.requests):
        ok, rid, reason = lb.serve_submit(b"req-%d" % i)
        while not ok:  # bounded queue: wait out the backpressure
            time.sleep(0.005)
            ok, rid, reason = lb.serve_submit(b"req-%d" % i)
        req_ids.append(rid)
    lb.serve_seal()

    answered = 0
    for rid in req_ids:
        while True:
            done, payload, worker_id, latency = lb.serve_poll(rid)
            if done:
                answered += 1
                break
            time.sleep(0.002)
    elapsed = time.perf_counter() - t0
    for t in threads:
        t.join(timeout=10)

    stats = lb.serve_stats()
    print(
        f"{answered}/{args.requests} answered exactly-once in "
        f"{elapsed:.2f}s ({answered / elapsed:.0f} req/s), "
        f"p50={stats['p50_ms']}ms p99={stats['p99_ms']}ms, "
        f"redelivered={stats['redelivered']} "
        f"duplicates={stats['duplicates']}"
    )
    scaler.stop()
    for c in clients + [lb]:
        c.close()
    master.stop()
    return 0 if answered == args.requests else 1


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--master_addr", default="",
                    help="join an existing master as one replica; "
                         "empty = standalone demo")
    ap.add_argument("--node_id", type=int, default=0)
    ap.add_argument("--replicas", type=int, default=2)
    ap.add_argument("--requests", type=int, default=200)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--ckpt_dir", default="",
                    help="flash-checkpoint tree to serve weights from "
                         "(empty = init fresh, no checkpointer)")
    ap.add_argument("--ram_dir", default="")
    args = ap.parse_args()
    if args.master_addr:
        return run_replica(args)
    return run_standalone(args)


if __name__ == "__main__":
    sys.exit(main())
