"""Grandfathered findings, committed with reasons.

The baseline is the escape hatch that lets a new rule land with real
teeth: every pre-existing violation that is *justified* (e.g. the shard
ledger's commit-before-reply journal write is blocking-under-lock BY
DESIGN) gets an entry here, keyed by the finding's line-independent
fingerprint, with a human reason string. ``--check`` fails on any
finding NOT in the baseline (the ratchet) and on any baseline entry
with no live finding (stale entries must be deleted with the code they
excused — the baseline can only shrink; tests assert the count).
"""

import json
from pathlib import Path
from typing import Dict, List, Tuple

from tools.dlint.core import Finding

BASELINE_PATH = Path(__file__).resolve().parent / "baseline.json"


def load_baseline(path: Path = BASELINE_PATH) -> Dict[str, dict]:
    if not path.exists():
        return {}
    return json.loads(path.read_text())


def diff_baseline(
    findings: List[Finding], baseline: Dict[str, dict]
) -> Tuple[List[Finding], List[str]]:
    """Returns (new findings not excused, stale fingerprints)."""
    live = {f.fingerprint for f in findings}
    new = [f for f in findings if f.fingerprint not in baseline]
    stale = sorted(fp for fp in baseline if fp not in live)
    return new, stale


def write_baseline(findings: List[Finding],
                   path: Path = BASELINE_PATH) -> Dict[str, dict]:
    """Regenerate the baseline from the current findings, preserving
    reason strings for fingerprints that already had one. New entries
    get reason "TODO: justify or fix" — a committed TODO is itself a
    finding for a reviewer."""
    prior = load_baseline(path)
    out: Dict[str, dict] = {}
    for f in sorted(findings, key=lambda f: (f.rule, f.path, f.line)):
        entry = {
            "rule": f.rule,
            "path": f.path,
            "anchor": f.anchor,
            "reason": prior.get(f.fingerprint, {}).get(
                "reason", "TODO: justify or fix"
            ),
        }
        out[f.fingerprint] = entry
    path.write_text(json.dumps(out, indent=1, sort_keys=True) + "\n")
    return out
