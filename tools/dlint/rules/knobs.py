"""Knob registry: every env knob has a default and a documented home.

``DLROVER_TPU_*`` environment variables are the system's operational
surface — and the easiest thing to let drift. A knob read without a
default crashes (or silently changes behavior) on a bare environment; a
knob no doc mentions is a support ticket. This rule:

  * inventories every ``DLROVER_TPU_*`` env read in the package +
    bench.py (``os.getenv`` / ``os.environ.get`` / ``os.environ[...]``,
    including reads through string constants like
    ``NodeEnv.COORDINATOR_ADDR``);
  * flags reads with no default (justified required-vars go in the
    baseline with a reason);
  * flags knobs mentioned by no doc (a curated note in ``KNOB_NOTES``
    satisfies this for launcher-plumbing vars whose only home is the
    generated table);
  * generates ``docs/KNOBS.md`` (knob → default → read sites → owning
    doc) and diffs it against the committed file, so the table can
    never go stale: ``python -m tools.dlint --write-knobs``
    regenerates it.
"""

import ast
from pathlib import Path
from typing import Dict, List, Optional, Tuple

from tools.dlint.core import REPO_ROOT, FileContext, Rule

KNOB_PREFIX = "DLROVER_TPU_"
KNOBS_MD = REPO_ROOT / "docs" / "KNOBS.md"

#: one-line descriptions for knobs whose only documentation home is the
#: generated table itself: process identity and launcher plumbing that
#: no feature doc narrates. Everything else must be mentioned in a real
#: doc — adding a note here for a *feature* knob defeats the rule.
KNOB_NOTES: Dict[str, str] = {
    "DLROVER_TPU_MASTER_ADDR": "master host:port the agent dials",
    "DLROVER_TPU_MASTER_PORT": "port the embedded master binds",
    "DLROVER_TPU_COORDINATOR_ADDR":
        "jax.distributed coordinator address for the worker mesh",
    "DLROVER_TPU_NODE_ID": "this node's id, set by the launcher",
    "DLROVER_TPU_NODE_RANK": "this node's rank, set by the launcher",
    "DLROVER_TPU_NODE_TYPE": "node role (worker/master), launcher-set",
    "DLROVER_TPU_NODE_NUM": "world size in nodes, launcher-set",
    "DLROVER_TPU_NUM_PROCESSES": "local process count, launcher-set",
    "DLROVER_TPU_PROCESS_ID": "local process index, launcher-set",
    "DLROVER_TPU_JOB_NAME": "job name stamped on telemetry",
    "DLROVER_TPU_RESTART_COUNT": "incarnation counter the agent bumps",
    "DLROVER_TPU_RDZV_ROUND": "rendezvous round handed to relaunches",
    "DLROVER_TPU_FAKE_PLATFORM":
        "tests: serve a fake TPU platform client",
    "DLROVER_TPU_PROBE_DELAY":
        "tests: per-rank delay spec for network-check probes",
    "DLROVER_TPU_LOG_LEVEL": "log level (default INFO)",
    "DLROVER_TPU_LOG_JSON": "1 = structured JSON log lines",
    "DLROVER_TPU_CACHE": "native helper build cache dir (shm ring)",
    "DLROVER_TPU_AUTO_SHARDING": "opt-in auto-sharding pass",
    "DLROVER_TPU_BRAIN_TOKEN": "brain service bearer token",
    "DLROVER_TPU_BRAIN_TOKEN_FILE": "file the brain token is read from",
    "DLROVER_TPU_CKPT_DIR": "checkpoint root the evaluator reads",
    "DLROVER_TPU_DIST_HEARTBEAT_TIMEOUT":
        "jax.distributed heartbeat timeout seconds",
    "DLROVER_TPU_STRAGGLER_SCORE_INTERVAL":
        "min seconds between straggler re-scores",
}


class _Read:
    __slots__ = ("knob", "default", "relpath", "line")

    def __init__(self, knob: str, default: Optional[str],
                 relpath: str, line: int):
        self.knob = knob
        self.default = default
        self.relpath = relpath
        self.line = line


def _env_call_kind(node: ast.Call) -> Optional[str]:
    """'getenv' for os.getenv / os.environ.get shapes, else None."""
    text = ast.unparse(node.func)
    if text in ("os.getenv", "os.environ.get", "environ.get",
                "getenv"):
        return "getenv"
    return None


class KnobRegistryRule(Rule):
    id = "knob-registry"
    title = "every env knob has a default and a documented home"
    interest = (ast.Call, ast.Subscript, ast.Assign)
    targets = ("dlrover_tpu/", "bench.py")

    def __init__(self):
        super().__init__()
        self.reads: List[_Read] = []
        self._constants: Dict[str, str] = {}  # symbol -> knob name
        # (symbol, has_default, default_text, relpath, line)
        self._pending: List[Tuple[str, Optional[str], str, int]] = []

    # ------------------------------------------------------------- visit

    def visit(self, node: ast.AST, ctx: FileContext) -> None:
        if isinstance(node, ast.Assign):
            self._register_constant(node)
        elif isinstance(node, ast.Call):
            self._visit_call(node, ctx)
        elif isinstance(node, ast.Subscript):
            self._visit_subscript(node, ctx)

    def _register_constant(self, node: ast.Assign) -> None:
        if not (isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, str)
                and node.value.value.startswith(KNOB_PREFIX)):
            return
        for t in node.targets:
            if isinstance(t, ast.Name):
                self._constants[t.id] = node.value.value
            elif isinstance(t, ast.Attribute):
                self._constants[t.attr] = node.value.value

    def _default_of(self, node: ast.Call) -> Optional[str]:
        if len(node.args) > 1:
            return ast.unparse(node.args[1])
        for kw in node.keywords:
            if kw.arg == "default":
                return ast.unparse(kw.value)
        return None

    def _visit_call(self, node: ast.Call, ctx: FileContext) -> None:
        if _env_call_kind(node) is None or not node.args:
            return
        key = node.args[0]
        default = self._default_of(node)
        if isinstance(key, ast.Constant) and isinstance(key.value, str):
            if key.value.startswith(KNOB_PREFIX):
                self.reads.append(
                    _Read(key.value, default, ctx.relpath, node.lineno)
                )
        elif isinstance(key, ast.Name):
            self._pending.append(
                (key.id, default, ctx.relpath, node.lineno)
            )
        elif isinstance(key, ast.Attribute):
            self._pending.append(
                (key.attr, default, ctx.relpath, node.lineno)
            )

    def _visit_subscript(self, node: ast.Subscript,
                         ctx: FileContext) -> None:
        if not isinstance(node.ctx, ast.Load):
            return  # writes/deletes are not reads
        if ast.unparse(node.value) not in ("os.environ", "environ"):
            return
        sl = node.slice
        if isinstance(sl, ast.Constant) and isinstance(sl.value, str):
            if sl.value.startswith(KNOB_PREFIX):
                self.reads.append(
                    _Read(sl.value, None, ctx.relpath, node.lineno)
                )
        elif isinstance(sl, (ast.Name, ast.Attribute)):
            sym = sl.id if isinstance(sl, ast.Name) else sl.attr
            self._pending.append((sym, None, ctx.relpath, node.lineno))

    # ---------------------------------------------------------- finalize

    def finalize(self, full_run: bool) -> None:
        # resolve symbolic reads now that every constant is collected
        for sym, default, relpath, line in self._pending:
            knob = self._constants.get(sym)
            if knob is not None:
                self.reads.append(_Read(knob, default, relpath, line))
        self._pending.clear()
        for r in self.reads:
            if r.default is None:
                self.report(
                    r.relpath, r.line,
                    f"env read of {r.knob} has no default — a bare "
                    "environment crashes or silently flips behavior; "
                    "pass an explicit default (or baseline a truly "
                    "required var with a reason)",
                    anchor=f"default:{r.knob}",
                )
        if not full_run:
            return
        mentioned = _docs_mentions()
        first_site: Dict[str, _Read] = {}
        for r in sorted(self.reads, key=lambda r: (r.relpath, r.line)):
            first_site.setdefault(r.knob, r)
        for knob in sorted(first_site):
            if knob not in mentioned and knob not in KNOB_NOTES:
                r = first_site[knob]
                self.report(
                    r.relpath, r.line,
                    f"{knob} is documented nowhere under docs/ — add "
                    "it to the owning doc's knob table, or (for "
                    "launcher plumbing only) a KNOB_NOTES entry in "
                    "tools/dlint/rules/knobs.py",
                    anchor=f"undocumented:{knob}",
                )
        expected = render_knobs_md(self.reads, mentioned)
        actual = KNOBS_MD.read_text() if KNOBS_MD.exists() else ""
        if expected != actual:
            self.report(
                "docs/KNOBS.md", 1,
                "docs/KNOBS.md is stale vs the code's env reads — "
                "regenerate with `python -m tools.dlint --write-knobs`",
                anchor="drift",
            )


# ------------------------------------------------------------- generation


def _docs_mentions() -> Dict[str, List[str]]:
    """knob -> sorted list of docs (outside KNOBS.md) that mention it."""
    out: Dict[str, List[str]] = {}
    sources = sorted(
        p for p in (REPO_ROOT / "docs").glob("*.md")
        if p.name != "KNOBS.md"
    )
    sources.append(REPO_ROOT / "README.md")
    for doc in sources:
        text = doc.read_text()
        rel = str(doc.relative_to(REPO_ROOT))
        for token in set(_knob_tokens(text)):
            out.setdefault(token, []).append(rel)
    return {k: sorted(v) for k, v in out.items()}


def _knob_tokens(text: str) -> List[str]:
    import re

    return re.findall(r"DLROVER_TPU_[A-Z0-9_]+", text)


def render_knobs_md(reads: List[_Read],
                    mentioned: Optional[Dict[str, List[str]]] = None
                    ) -> str:
    """Deterministic knob table. Regenerate, never hand-edit."""
    if mentioned is None:
        mentioned = _docs_mentions()
    by_knob: Dict[str, List[_Read]] = {}
    for r in reads:
        by_knob.setdefault(r.knob, []).append(r)
    lines = [
        "# Environment knobs",
        "",
        "<!-- GENERATED by `python -m tools.dlint --write-knobs` from",
        "     the env reads in dlrover_tpu/ + bench.py. Do not edit by",
        "     hand: the `knob-registry` dlint rule diffs this file",
        "     against the code on every tier-1 run. -->",
        "",
        "Every `DLROVER_TPU_*` environment variable the system reads,",
        "its in-code default, where it is read, and the doc that owns",
        "its narrative. A knob with no owning doc is either launcher",
        "plumbing (described in the Notes column) or a lint failure.",
        "",
        "| Knob | Default | Read at | Owning doc | Notes |",
        "|---|---|---|---|---|",
    ]
    for knob in sorted(by_knob):
        rs = sorted(by_knob[knob], key=lambda r: (r.relpath, r.line))
        defaults = []
        for r in rs:
            d = "(required)" if r.default is None else f"`{r.default}`"
            if d not in defaults:
                defaults.append(d)
        sites = sorted({r.relpath for r in rs})
        site_txt = sites[0] + (
            f" (+{len(sites) - 1} more)" if len(sites) > 1 else ""
        )
        docs = mentioned.get(knob, [])
        doc_txt = ", ".join(docs) if docs else "(this table)"
        note = KNOB_NOTES.get(knob, "")
        lines.append(
            f"| `{knob}` | {' / '.join(defaults)} | {site_txt} | "
            f"{doc_txt} | {note} |"
        )
    lines += [
        "| `DLROVER_TPU_CTX_*` | per-field | "
        "dlrover_tpu/common/global_context.py | docs/FAULT_TOLERANCE.md"
        " | dynamic prefix: overrides any Context field "
        "(e.g. `DLROVER_TPU_CTX_TASK_PROCESS_TIMEOUT`) |",
        "",
    ]
    return "\n".join(lines)


def write_knobs_md() -> str:
    """Regenerate docs/KNOBS.md from a fresh scan; returns the path."""
    from tools.dlint.core import lint_repo

    rule = KnobRegistryRule()
    lint_repo(rules=[rule])
    KNOBS_MD.write_text(render_knobs_md(rule.reads))
    return str(KNOBS_MD)
