"""Journal-event and span-name contracts (motivated by PRs 4 and 7–14).

The journal is the system's black box: goodput EVENT_RULES, the chaos
drills' asserts, dashboards and the offline ``dump`` replay all match
event names *literally*. A typo'd name doesn't crash anything — it
silently vanishes from every consumer weeks later. Two contracts:

  * every ``record(...)`` name is snake-case dotted (``event-names``);
  * namespaces with downstream consumers are CLOSED vocabularies
    (``event-vocabulary``): every emitted name is documented, every
    documented name has a live emitter. These sets used to live as
    seven near-identical test functions in tests/test_tracing.py; this
    module is now the single source of truth (the tests shim to it).

``span-names`` is the tracing twin: summarize()/Perfetto match spans by
exact name.
"""

import ast
import re
from typing import List, Tuple

from tools.dlint.core import FileContext, Rule

_EVENT_NAME = re.compile(r"^[a-z0-9_]+(\.[a-z0-9_]+)+$")
#: span names allow a single undotted segment ("data", "dispatch" —
#: the bench's train-thread phases predate the dotted convention)
_SPAN_NAME = re.compile(r"^[a-z0-9_]+(\.[a-z0-9_]+)*$")
_FRAGMENT = re.compile(r"^[a-z0-9_.]*$")

#: the closed journal vocabularies: group -> (namespace prefixes,
#: canonical event set). goodput's EVENT_RULES, each drill's journal
#: asserts and docs/TELEMETRY.md match these names literally — an
#: addition or rename must land everywhere in the same PR.
VOCABULARY = {
    # ISSUE 9: the preemption drain
    "preempt": (("preempt",), frozenset({
        "preempt.notice",
        "preempt.emergency_ckpt",
        "preempt.step_timeout",
        "preempt.step_skipped",
        "preempt.drained",
        "preempt.rpc_fallback",
        "preempt.reported",
        "preempt.relinquished",
        "preempt.recovered",
        "preempt.relaunched",
        "preempt.drain_requested",
        "preempt.drain_action",
        "preempt.worker_exit",
    })),
    # PR 10: the silent-failure sentinel (detection on the worker,
    # attribution + rollback coordination on the master). NOTE the
    # anomaly kind rides in a data field named "anomaly" (record()'s
    # first parameter owns "kind", same convention as fault.injected).
    "sentinel": (("anomaly", "rollback", "quarantine"), frozenset({
        "anomaly.detected",
        "anomaly.reported",
        "anomaly.rpc_fallback",
        "rollback.ordered",
        "rollback.initiated",
        "rollback.restored",
        "rollback.recovered",
        "rollback.budget_exhausted",
        "quarantine.imposed",
    })),
    # ISSUE 11: the serving request plane (+ ISSUE 20: live shard
    # re-partition)
    "serve": (("serve",), frozenset({
        "serve.sealed",
        "serve.drained",
        "serve.request_redelivered",
        "serve.relinquished",
        "serve.autoscale",
        "serve.autoscale_held",
        "serve.worker_ready",
        "serve.worker_exit",
        "serve.rpc_fallback",
        "serve.shards_resized",
    })),
    # ISSUE 14: the reshard-in-place transition plane. Deliberately no
    # reshard.rpc_fallback — report_reshard degrades through
    # anomaly.rpc_fallback (rpc="report_reshard") like the other
    # supervised calls.
    "reshard": (("reshard",), frozenset({
        "reshard.detected",
        "reshard.ordered",
        "reshard.adopted",
        "reshard.migrated",
        "reshard.rebalanced",
        "reshard.completed",
        "reshard.aborted",
        "reshard.step_pinned",
    })),
    # ISSUE 18: hot spares — idle ranks registered for sub-second
    # promotion into a dead rank's slot (reshard/spare.py,
    # reshard/coordinator.py)
    "spare": (("spare",), frozenset({
        "spare.registered",
        "spare.warmed",
        "spare.promoted",
    })),
    # ISSUE 12: control-plane fan-in (master side / agent side)
    "control": (("control",), frozenset({
        "control.load_shed",
        "control.journal_recovered",
    })),
    "report": (("report",), frozenset({
        "report.resync",
        "report.retry_after",
        "report.rpc_fallback",
    })),
    # PR 13: the sharded checkpoint plane (format v2).
    # (legacy-archive detection journals "checkpoint.legacy_format",
    # which lives in the checkpoint.* namespace with the other
    # FlashCheckpointer lifecycle events, not here.)
    "ckpt": (("ckpt",), frozenset({
        "ckpt.manifest_committed",
        "ckpt.dedup",
        "ckpt.peer_advertised",
        "ckpt.peer_fetch",
        "ckpt.peer_served",
        "ckpt.shard_refetch",
        "ckpt.topology_restore",
    })),
    # ISSUE 16: the aggregator relay tier (agent/relay.py) and the
    # agents' relay -> direct-master failover (master_client.py)
    # (tier_* / restarted: ISSUE 18's launcher-owned relay lifecycle,
    # agent/relay.py RelayTier)
    "relay": (("relay",), frozenset({
        "relay.started",
        "relay.stopped",
        "relay.forward_failed",
        "relay.failover",
        "relay.tier_started",
        "relay.tier_stopped",
        "relay.restarted",
    })),
    # ISSUE 17: the fleet observability plane — SLO objective state
    # machine (telemetry/fleet.py) and journal file rotation
    # (telemetry/journal.py)
    "slo": (("slo",), frozenset({
        "slo.violated",
        "slo.recovered",
    })),
    "journal_file": (("journal",), frozenset({
        "journal.rotated",
    })),
    # ISSUE 19: the explainable resource advisor (brain/advisor.py) —
    # plan_proposed carries the full evidence chain; adopted/rejected
    # are the advise-mode actuation audit trail
    "brain": (("brain",), frozenset({
        "brain.advisor_started",
        "brain.plan_proposed",
        "brain.plan_adopted",
        "brain.plan_rejected",
    })),
    # ISSUE 15: the runtime lock-order watchdog
    # (telemetry/lockwatch.py) — cycle = potential deadlock in the
    # acquisition-order graph, long_hold = critical section over the
    # configured budget.
    "lockwatch": (("lockwatch",), frozenset({
        "lockwatch.cycle",
        "lockwatch.long_hold",
    })),
}


def _call_name(node: ast.Call):
    fn = node.func
    if isinstance(fn, ast.Name):
        return fn.id
    if isinstance(fn, ast.Attribute):
        return fn.attr
    return None


def _first_arg_literals(node: ast.Call) -> List[Tuple[str, str]]:
    """(value, kind) for a call's first argument: the literal itself,
    or every constant fragment of an f-string (so a typo'd prefix
    still fails)."""
    arg = node.args[0]
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return [(arg.value, "literal")]
    if isinstance(arg, ast.JoinedStr):
        return [
            (part.value, "fragment")
            for part in arg.values
            if isinstance(part, ast.Constant)
            and isinstance(part.value, str)
        ]
    return []


class _LiteralCollector(Rule):
    """Shared machinery: collect first-arg literals of ``<fn>(...)``."""

    call_name = ""
    interest = (ast.Call,)

    def __init__(self):
        super().__init__()
        # (relpath, line, value, kind)
        self.literals: List[Tuple[str, int, str, str]] = []

    def visit(self, node: ast.AST, ctx: FileContext) -> None:
        assert isinstance(node, ast.Call)
        if not node.args or _call_name(node) != self.call_name:
            return
        for value, kind in _first_arg_literals(node):
            self.literals.append((ctx.relpath, node.lineno, value, kind))


class EventNameRule(_LiteralCollector):
    id = "event-names"
    title = "journal event names are snake-case dotted (ISSUE 4)"
    call_name = "record"
    targets = ("dlrover_tpu/",)

    def finalize(self, full_run: bool) -> None:
        for relpath, line, value, kind in self.literals:
            ok = (
                _EVENT_NAME.match(value) if kind == "literal"
                else _FRAGMENT.match(value)
            )
            if not ok:
                self.report(
                    relpath, line,
                    f"journal event name {value!r} ({kind}) is not "
                    "snake-case dotted (e.g. 'checkpoint.save')",
                    anchor=f"event:{value}",
                )
        if full_run and len(self.literals) < 15:
            self.report(
                "dlrover_tpu", 0,
                "the lint found suspiciously few record() calls — did "
                "the instrumentation move?", anchor="coverage",
            )


class EventVocabularyRule(_LiteralCollector):
    id = "event-vocabulary"
    title = "journal namespaces with consumers are closed sets"
    call_name = "record"
    targets = ("dlrover_tpu/",)

    def finalize(self, full_run: bool) -> None:
        for group, (prefixes, canonical) in sorted(VOCABULARY.items()):
            found = {}
            for relpath, line, value, kind in self.literals:
                if kind != "literal":
                    continue
                if value.split(".", 1)[0] in prefixes:
                    found.setdefault(value, (relpath, line))
            for value in sorted(set(found) - canonical):
                relpath, line = found[value]
                self.report(
                    relpath, line,
                    f"{value!r} is not in the closed {group}.* journal "
                    "vocabulary — add it to VOCABULARY in "
                    "tools/dlint/rules/events.py, docs/TELEMETRY.md "
                    "and every consumer in the same PR",
                    anchor=f"unexpected:{value}",
                )
            if full_run:
                # a documented event with no emitter leaves docs and
                # dashboards describing a ghost
                for value in sorted(canonical - set(found)):
                    self.report(
                        "tools/dlint/rules/events.py", 1,
                        f"closed-vocabulary event {value!r} ({group}) "
                        "has no live record() emitter in dlrover_tpu/",
                        anchor=f"ghost:{value}",
                    )


class SpanNameRule(_LiteralCollector):
    id = "span-names"
    title = "tracing span names are canonical (ISSUE 8)"
    call_name = "span"
    targets = ("dlrover_tpu/", "bench.py")

    def finalize(self, full_run: bool) -> None:
        for relpath, line, value, kind in self.literals:
            ok = (
                _SPAN_NAME.match(value) if kind == "literal"
                else _FRAGMENT.match(value)
            )
            if not ok:
                self.report(
                    relpath, line,
                    f"span name {value!r} ({kind}) is not snake-case "
                    "(optionally dotted, e.g. 'data.fetch')",
                    anchor=f"span:{value}",
                )
        if full_run and len(self.literals) < 8:
            self.report(
                "dlrover_tpu", 0,
                "the lint found suspiciously few span() calls — did "
                "the instrumentation move?", anchor="coverage",
            )
