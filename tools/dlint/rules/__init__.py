"""Rule registry: every contract dlint enforces, in catalog order.

docs/STATIC_ANALYSIS.md documents each rule, the bug class it encodes
and the PR that motivated it. Adding a rule = one class + one entry
here + one fixture under tests/fixtures/dlint/ + a catalog row.
"""

from tools.dlint.rules.events import (
    EventNameRule,
    EventVocabularyRule,
    SpanNameRule,
)
from tools.dlint.rules.phases import GoodputPhaseRule
from tools.dlint.rules.signals import SignalChainRule
from tools.dlint.rules.rpc import SupervisedRpcRule
from tools.dlint.rules.threads import ThreadNameRule
from tools.dlint.rules.locks import (
    BlockingUnderLockRule,
    LockDisciplineRule,
)
from tools.dlint.rules.eventloop import NoBlockingInAsyncRule
from tools.dlint.rules.reply import CommitBeforeReplyRule
from tools.dlint.rules.knobs import KnobRegistryRule
from tools.dlint.rules.metrics import MetricRegistryRule

ALL_RULES = [
    EventNameRule,
    EventVocabularyRule,
    SpanNameRule,
    GoodputPhaseRule,
    SignalChainRule,
    SupervisedRpcRule,
    ThreadNameRule,
    LockDisciplineRule,
    BlockingUnderLockRule,
    NoBlockingInAsyncRule,
    CommitBeforeReplyRule,
    KnobRegistryRule,
    MetricRegistryRule,
]

__all__ = ["ALL_RULES"] + [cls.__name__ for cls in ALL_RULES]
