"""Metric registry: every Prometheus metric has a documented home.

The ``dlrover_*`` metric names are a wire contract the same way the
journal vocabularies are: dashboards, the fleet digest series and the
swarm drills query them *literally*, so a metric nobody documented is
invisible to operators, and a documented metric nobody emits is a
dashboard panel that flatlines forever without anyone noticing (the
knob-registry lesson, applied to the other operational surface). This
rule:

  * inventories every ``counter(...)`` / ``gauge(...)`` /
    ``histogram(...)`` construction whose name literal starts with
    ``dlrover_`` in the package + bench.py;
  * flags names that break the ``dlrover_<snake_case>`` shape
    (Prometheus rejects them at scrape time, which is the worst
    possible moment to find out);
  * flags emitted metrics with no row in the docs/TELEMETRY.md metric
    table — the closed-vocabulary check;
  * flags rows whose type column disagrees with the constructor used;
  * on full runs, flags *ghosts*: table rows whose metric no code
    emits anymore (the rename-without-the-doc failure mode).
"""

import ast
import re
from pathlib import Path
from typing import Dict, List, Optional, Tuple

from tools.dlint.core import REPO_ROOT, FileContext, Rule

METRIC_PREFIX = "dlrover_"
TELEMETRY_MD = REPO_ROOT / "docs" / "TELEMETRY.md"

_METRIC_NAME = re.compile(r"^dlrover_[a-z0-9_]+$")
#: a metric table row: | `dlrover_x` | counter | `labels` | site |
_DOC_ROW = re.compile(
    r"^\|\s*`(dlrover_[A-Za-z0-9_]+)`\s*\|\s*"
    r"(counter|gauge|histogram)\b"
)
_CONSTRUCTORS = ("counter", "gauge", "histogram")


class _Emit:
    __slots__ = ("name", "kind", "relpath", "line")

    def __init__(self, name: str, kind: str, relpath: str, line: int):
        self.name = name
        self.kind = kind
        self.relpath = relpath
        self.line = line


def _doc_rows() -> Dict[str, Tuple[str, int]]:
    """metric name -> (documented kind, 1-based line in TELEMETRY.md)."""
    out: Dict[str, Tuple[str, int]] = {}
    if not TELEMETRY_MD.exists():
        return out
    for i, line in enumerate(
        TELEMETRY_MD.read_text().splitlines(), start=1
    ):
        m = _DOC_ROW.match(line)
        if m:
            out.setdefault(m.group(1), (m.group(2), i))
    return out


class MetricRegistryRule(Rule):
    id = "metric-registry"
    title = "every dlrover_* metric has a docs/TELEMETRY.md row"
    interest = (ast.Call,)
    targets = ("dlrover_tpu/", "bench.py")

    def __init__(self):
        super().__init__()
        self.emits: List[_Emit] = []

    def visit(self, node: ast.AST, ctx: FileContext) -> None:
        fn = node.func
        kind: Optional[str] = None
        if isinstance(fn, ast.Name) and fn.id in _CONSTRUCTORS:
            kind = fn.id
        elif isinstance(fn, ast.Attribute) and fn.attr in _CONSTRUCTORS:
            kind = fn.attr
        if kind is None or not node.args:
            return
        arg = node.args[0]
        if not (isinstance(arg, ast.Constant)
                and isinstance(arg.value, str)
                and arg.value.startswith(METRIC_PREFIX)):
            return
        self.emits.append(
            _Emit(arg.value, kind, ctx.relpath, node.lineno)
        )

    def finalize(self, full_run: bool) -> None:
        docs = _doc_rows()
        first_site: Dict[str, _Emit] = {}
        for e in sorted(self.emits, key=lambda e: (e.relpath, e.line)):
            first_site.setdefault(e.name, e)
        for name in sorted(first_site):
            e = first_site[name]
            if not _METRIC_NAME.match(name):
                self.report(
                    e.relpath, e.line,
                    f"metric name {name!r} is not dlrover_<snake_case>"
                    " — Prometheus rejects it at scrape time",
                    anchor=f"name:{name}",
                )
                continue
            row = docs.get(name)
            if row is None:
                self.report(
                    e.relpath, e.line,
                    f"metric {name} has no row in the docs/TELEMETRY.md"
                    " metric table — an undocumented metric is "
                    "invisible to operators; add the row in the same "
                    "PR that adds the metric",
                    anchor=f"undocumented:{name}",
                )
            elif row[0] != e.kind:
                self.report(
                    e.relpath, e.line,
                    f"metric {name} is emitted as a {e.kind} but "
                    f"documented as a {row[0]} "
                    f"(docs/TELEMETRY.md:{row[1]})",
                    anchor=f"kind:{name}",
                )
        if not full_run:
            return  # ghost detection assumes whole-repo coverage
        emitted = set(first_site)
        for name in sorted(set(docs) - emitted):
            self.report(
                "docs/TELEMETRY.md", docs[name][1],
                f"documented metric {name} has no emitter in "
                "dlrover_tpu/ or bench.py — a renamed or deleted "
                "metric leaves a dashboard panel that flatlines "
                "forever; delete the row or restore the emitter",
                anchor=f"ghost:{name}",
            )
