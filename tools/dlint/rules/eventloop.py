"""Event-loop discipline: no blocking work in ``async def`` bodies
(ISSUE 16).

The async ingest front end (common/grpc_utils.py ``AsyncRpcServer``)
replaced thread-per-RPC with ONE event loop for the hot report path.
That inverts the blocking calculus: under the thread pool a stray
``time.sleep`` stalled one RPC; on the loop it stalls EVERY in-flight
RPC — at 10k agents, the whole control plane. The contract:

* an ``async def`` body never calls a synchronous blocker directly —
  ``time.sleep``, ``open``/fsync-class file I/O, ``subprocess.*``, a
  bare ``<lock>.acquire()``, or a sync RPC (receiver named
  ``*client``/``*stub``, the blocking-under-lock convention);
* awaited expressions are exempt (``await asyncio.sleep`` yields, it
  doesn't block), and so are nested function bodies — they execute
  later, usually on an executor (``run_in_executor`` is exactly how
  the ingest plane offloads its blocking section application).
"""

import ast
from typing import Optional

from tools.dlint.core import FileContext, Rule
from tools.dlint.rules.locks import _LOCK_NAME


class NoBlockingInAsyncRule(Rule):
    id = "no-blocking-in-async"
    title = "async def bodies never block the event loop"
    interest = (ast.AsyncFunctionDef,)
    targets = ("dlrover_tpu/",)

    def visit(self, node: ast.AST, ctx: FileContext) -> None:
        assert isinstance(node, ast.AsyncFunctionDef)
        for call in self._direct_calls(node):
            if isinstance(ctx.parents.get(call), ast.Await):
                continue  # awaited = cooperatively scheduled
            why = self._blocking_reason(call)
            if why is None:
                continue
            call_text = ast.unparse(call.func)
            self.report(
                ctx.relpath, call.lineno,
                f"{why} `{call_text}(...)` inside `async def "
                f"{node.name}` blocks the event loop (and every "
                "in-flight RPC with it) — await an async equivalent "
                "or offload via loop.run_in_executor",
                anchor=f"{node.name}:{call_text}",
            )

    # ------------------------------------------------------------ helpers

    @staticmethod
    def _direct_calls(fn: ast.AsyncFunctionDef):
        """Calls in the coroutine body itself; nested def/lambda bodies
        execute later (typically on an executor), and nested async
        defs get their own visit."""
        out = []
        stack = list(fn.body)
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                continue
            if isinstance(n, ast.Call):
                out.append(n)
            stack.extend(ast.iter_child_nodes(n))
        out.sort(key=lambda c: (c.lineno, c.col_offset))
        return out

    @staticmethod
    def _blocking_reason(call: ast.Call) -> Optional[str]:
        f = call.func
        text = ast.unparse(f)
        if text == "sleep" or text.endswith(".sleep"):
            return "sync sleep"
        if text == "open":
            return "file I/O"
        if text in ("os.fsync", "os.fdatasync", "os.replace"):
            return "file I/O"
        if text.startswith("subprocess."):
            return "subprocess"
        if isinstance(f, ast.Attribute):
            recv = ast.unparse(f.value)
            low = recv.lower()
            if f.attr == "acquire" and _LOCK_NAME.search(recv):
                return "bare lock acquire"
            if f.attr in ("call", "wait", "wait_for", "result") and (
                low.endswith("client") or low.endswith("stub")
            ):
                return "sync RPC"
            if low.endswith("client") or low.endswith("stub"):
                return "sync RPC"
        return None
