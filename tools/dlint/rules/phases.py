"""Goodput phase labels are canonical Phase members (PR 7).

A phase label the ledger would reject at runtime (ValueError in
transition/credit) or a typo'd ``Phase.X`` member fails here, at lint
speed, not mid-drill.
"""

import ast
from typing import List, Tuple

from tools.dlint.core import FileContext, Rule


class GoodputPhaseRule(Rule):
    id = "goodput-phases"
    title = "goodput phase labels are canonical Phase members (PR 7)"
    interest = (ast.Call, ast.Attribute)
    targets = ("dlrover_tpu/", "bench.py")

    def __init__(self):
        super().__init__()
        self._strings: List[Tuple[str, int, str]] = []
        self._members: List[Tuple[str, int, str]] = []

    def visit(self, node: ast.AST, ctx: FileContext) -> None:
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in ("transition", "credit")
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
        ):
            self._strings.append(
                (ctx.relpath, node.lineno, node.args[0].value)
            )
        elif (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "Phase"
        ):
            self._members.append((ctx.relpath, node.lineno, node.attr))

    def finalize(self, full_run: bool) -> None:
        from dlrover_tpu.telemetry.goodput import PHASES, Phase

        valid_members = {
            m for m in vars(Phase) if not m.startswith("_")
        }
        for relpath, line, value in self._strings:
            if value not in PHASES:
                self.report(
                    relpath, line,
                    f"goodput phase label {value!r} is not in PHASES",
                    anchor=f"phase:{value}",
                )
        for relpath, line, attr in self._members:
            if attr not in valid_members:
                self.report(
                    relpath, line,
                    f"Phase.{attr} is not a Phase member",
                    anchor=f"member:{attr}",
                )
        if full_run and not self._members:
            self.report(
                "dlrover_tpu", 0,
                "the lint found no Phase.X references — did goodput "
                "move?", anchor="coverage",
            )
