"""Every thread carries a name (ISSUE 15 satellite).

Flight-recorder ``stacks.txt`` and lockwatch reports attribute frames
by thread name; an anonymous ``Thread-7`` turns a hang diagnosis into
archaeology. ``threading.Thread(...)`` must pass ``name=`` so every
frame maps to a subsystem.
"""

import ast

from tools.dlint.core import FileContext, Rule


class ThreadNameRule(Rule):
    id = "thread-name"
    title = "threading.Thread(...) requires name="
    interest = (ast.Call,)
    targets = ("dlrover_tpu/", "bench.py")

    def visit(self, node: ast.AST, ctx: FileContext) -> None:
        assert isinstance(node, ast.Call)
        f = node.func
        name = (
            f.id if isinstance(f, ast.Name)
            else f.attr if isinstance(f, ast.Attribute)
            else None
        )
        if name != "Thread":
            return
        for kw in node.keywords:
            if kw.arg == "name" or kw.arg is None:  # name= or **kwargs
                return
        self.report(
            ctx.relpath, node.lineno,
            "threading.Thread(...) without name= — flight-recorder "
            "stacks and lockwatch reports cannot attribute anonymous "
            "threads to a subsystem",
            anchor="Thread",
        )
