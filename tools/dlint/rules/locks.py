"""Lock contracts: consistency of guarding, and no blocking work under
a lock (ISSUE 15 tentpole passes).

``lock-discipline`` encodes the invariant the codebase already follows
at its 58 lock sites but nothing enforced: an attribute initialized in
``__init__``, *mutated* after construction and accessed from two or
more methods where ANY access runs under ``with self._lock`` is shared
mutable state — so EVERY access must be locked. A single unlocked site
is a torn-read/lost-update waiting for fleet-scale traffic.

``blocking-under-lock`` encodes the PR 8 ``report_batch_done`` bug
class: an RPC, ``time.sleep``, file I/O or queue wait inside a ``with
<lock>`` body in the master/servicer/ledger/serving modules stalls
every other thread contending that lock — the exact convoy the
control-plane scale work (PR 12) exists to avoid. The fix pattern is
PR 12's ``_monitor_heartbeats``: snapshot under the lock, do the slow
work outside. Justified exceptions (e.g. commit-before-reply journal
writes) live in the baseline with a reason.

Both rules are heuristic where they must be (nested functions defer
execution, so a ``with`` wrapping a closure *definition* does not
protect its *body*) and conservative where they can be (a method that
manually ``.acquire()``s a lock counts as fully locked).
"""

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from tools.dlint.core import FileContext, Rule

_LOCK_NAME = re.compile(r"(lock|mutex|cv|cond)", re.IGNORECASE)

#: container-mutator method names: a call of one of these on an
#: attribute is a mutation of the attribute's value
_MUTATORS = frozenset({
    "append", "appendleft", "add", "update", "pop", "popleft",
    "popitem", "remove", "discard", "clear", "extend", "insert",
    "setdefault", "put", "put_nowait",
})


def _is_lock_expr(expr: ast.AST) -> bool:
    """Does this with-item expression look like a project lock?"""
    if isinstance(expr, ast.Attribute):
        return bool(_LOCK_NAME.search(expr.attr))
    if isinstance(expr, ast.Name):
        return bool(_LOCK_NAME.search(expr.id))
    return False


def _enclosing_function(ctx: FileContext,
                        node: ast.AST) -> Optional[ast.AST]:
    for anc in ctx.ancestors(node):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)):
            return anc
    return None


def _locked_here(ctx: FileContext, node: ast.AST) -> bool:
    """True when a lock-like ``with`` encloses ``node`` *within its
    nearest enclosing function* (a with around a nested function
    definition does not protect the nested body at call time)."""
    for anc in ctx.ancestors(node):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)):
            return False
        if isinstance(anc, ast.With) and any(
            _is_lock_expr(item.context_expr) for item in anc.items
        ):
            return True
    return False


class _Access:
    __slots__ = ("method", "line", "locked", "mutation", "const_store")

    def __init__(self, method: str, line: int, locked: bool,
                 mutation: bool, const_store: bool):
        self.method = method
        self.line = line
        self.locked = locked
        self.mutation = mutation
        self.const_store = const_store


class LockDisciplineRule(Rule):
    id = "lock-discipline"
    title = "shared mutable attributes are locked at every access"
    interest = (ast.ClassDef,)
    targets = ("dlrover_tpu/",)

    def visit(self, node: ast.AST, ctx: FileContext) -> None:
        assert isinstance(node, ast.ClassDef)
        methods = [
            n for n in node.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        init = next((m for m in methods if m.name == "__init__"), None)
        if init is None:
            return
        init_attrs = self._init_attrs(init)
        if not init_attrs:
            return
        has_lock = any(_LOCK_NAME.search(a) for a in init_attrs)
        if not has_lock:
            return
        collected: Dict[str, List[_Access]] = {}
        for m in methods:
            if m.name == "__init__":
                continue
            # the repo's convention: a ``*_locked`` method is called
            # with the lock already held (enforced by review; the
            # watchdog catches violations at runtime). Manual
            # .acquire() in a method counts as whole-method locked.
            held = (m.name.endswith("_locked")
                    or self._manually_acquires(m))
            for attr, acc in self._attr_accesses(ctx, m, init_attrs):
                if held:
                    acc.locked = True
                collected.setdefault(attr, []).append(acc)
        for attr in sorted(init_attrs):
            if _LOCK_NAME.search(attr):
                continue  # the lock itself (and friends)
            recs = collected.get(attr, [])
            if not recs:
                continue
            methods_touching = {r.method for r in recs}
            if len(methods_touching) < 2:
                continue
            if not any(r.locked for r in recs):
                continue  # never guarded: not lock-disciplined state
            muts = [r for r in recs if r.mutation]
            if not muts:
                continue  # read-only after __init__: immutable config
            if all(m.const_store for m in muts):
                # flag-style publication (self._stop = True): a single
                # GIL-atomic constant store with no compound invariant
                continue
            unlocked = sorted(
                (r for r in recs if not r.locked),
                key=lambda r: r.line,
            )
            if not unlocked:
                continue
            sites = ", ".join(
                f"{r.method}:{r.line}" for r in unlocked[:5]
            )
            extra = (
                f" (+{len(unlocked) - 5} more)" if len(unlocked) > 5
                else ""
            )
            self.report(
                ctx.relpath, unlocked[0].line,
                f"{node.name}.{attr} is guarded by a lock in some "
                f"methods but accessed unlocked at {sites}{extra} — "
                "lock every access, or snapshot under the lock and "
                "work on the copy",
                anchor=f"{node.name}.{attr}",
            )

    # ------------------------------------------------------------ helpers

    @staticmethod
    def _init_attrs(init: ast.AST) -> Set[str]:
        out: Set[str] = set()
        for n in ast.walk(init):
            if (isinstance(n, ast.Attribute)
                    and isinstance(n.ctx, ast.Store)
                    and isinstance(n.value, ast.Name)
                    and n.value.id == "self"):
                out.add(n.attr)
        return out

    @staticmethod
    def _manually_acquires(fn: ast.AST) -> bool:
        for n in ast.walk(fn):
            if (isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and n.func.attr == "acquire"
                    and isinstance(n.func.value, ast.Attribute)
                    and _LOCK_NAME.search(n.func.value.attr)):
                return True
        return False

    def _attr_accesses(
        self, ctx: FileContext, method: ast.AST, init_attrs: Set[str]
    ) -> List[Tuple[str, _Access]]:
        out: List[Tuple[str, _Access]] = []
        for n in ast.walk(method):
            if not (isinstance(n, ast.Attribute)
                    and isinstance(n.value, ast.Name)
                    and n.value.id == "self"
                    and n.attr in init_attrs):
                continue
            parent = ctx.parents.get(n)
            mutation = False
            const_store = False
            if isinstance(n.ctx, (ast.Store, ast.Del)):
                mutation = True
                if (isinstance(parent, ast.Assign)
                        and isinstance(parent.value, ast.Constant)):
                    const_store = True
            elif isinstance(parent, ast.Subscript) and isinstance(
                parent.ctx, (ast.Store, ast.Del)
            ):
                mutation = True  # self.x[k] = v / del self.x[k]
            elif (isinstance(parent, ast.Attribute)
                  and parent.attr in _MUTATORS):
                grand = ctx.parents.get(parent)
                if isinstance(grand, ast.Call) and grand.func is parent:
                    mutation = True  # self.x.append(...)
            elif isinstance(parent, ast.AugAssign) and parent.target is n:
                mutation = True
            acc = _Access(method.name, n.lineno,
                          _locked_here(ctx, n), mutation, const_store)
            out.append((n.attr, acc))
        return out


#: call shapes that block: (predicate description, matcher)
_STR_JOIN_PREFIXES = ("os.path.join", "posixpath.join", "ntpath.join")


class BlockingUnderLockRule(Rule):
    id = "blocking-under-lock"
    title = "no RPC / sleep / file I/O / queue wait under a lock"
    interest = (ast.With,)
    #: the contended control-plane surfaces: master (servicer, shard
    #: ledger, state journal, rendezvous), serving router, agent
    #: client/reporter, reshard coordinator
    targets = (
        "dlrover_tpu/master/",
        "dlrover_tpu/serving/",
        "dlrover_tpu/agent/",
        "dlrover_tpu/reshard/",
    )

    def begin_file(self, ctx: FileContext) -> None:
        self._reported: Set[Tuple[int, str]] = set()

    def visit(self, node: ast.AST, ctx: FileContext) -> None:
        assert isinstance(node, ast.With)
        lock_texts = [
            ast.unparse(item.context_expr)
            for item in node.items
            if _is_lock_expr(item.context_expr)
        ]
        if not lock_texts:
            return
        for call in self._body_calls(node):
            why = self._blocking_reason(call, ctx)
            if why is None:
                continue
            call_text = ast.unparse(call.func)
            key = (call.lineno, call_text)
            if key in self._reported:
                continue  # already reported from an outer lock-with
            self._reported.add(key)
            fn = _enclosing_function(ctx, node)
            fn_name = getattr(fn, "name", "<module>")
            self.report(
                ctx.relpath, call.lineno,
                f"{why} `{call_text}(...)` under `with "
                f"{lock_texts[0]}` in {fn_name} — move it outside the "
                "critical section or snapshot and release first "
                "(PR 8 report_batch_done bug class)",
                anchor=f"{fn_name}:{call_text}",
            )

    # ------------------------------------------------------------ helpers

    @staticmethod
    def _body_calls(with_node: ast.With) -> List[ast.Call]:
        """Every Call in the with body, skipping nested function /
        lambda bodies (deferred execution escapes the lock)."""
        out: List[ast.Call] = []
        stack: List[ast.AST] = list(with_node.body)
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                continue
            if isinstance(n, ast.Call):
                out.append(n)
            stack.extend(ast.iter_child_nodes(n))
        out.sort(key=lambda c: (c.lineno, c.col_offset))
        return out

    def _blocking_reason(self, call: ast.Call,
                         ctx: FileContext) -> Optional[str]:
        f = call.func
        text = ast.unparse(f)
        if text == "time.sleep" or text.endswith(".sleep"):
            return "sleep"
        if text == "open":
            return "file I/O"
        if text in ("os.fsync", "os.fdatasync", "os.replace"):
            return "file I/O"
        if text.startswith("subprocess."):
            return "subprocess"
        if isinstance(f, ast.Attribute):
            recv = ast.unparse(f.value)
            low = recv.lower()
            if f.attr == "join":
                if isinstance(f.value, ast.Constant):
                    return None  # "sep".join(...)
                if any(text.startswith(p) for p in _STR_JOIN_PREFIXES):
                    return None
                if "thread" in low or "proc" in low:
                    return "thread join"
                return None  # plain .join: almost always a string join
            if f.attr in ("wait", "wait_for"):
                if self._receiver_is_held_lock(call, ctx, recv):
                    return None  # Condition.wait releases its own lock
                return "wait"
            if f.attr in ("get", "put", "get_nowait_blocking"):
                if "queue" in low or low.endswith("_q"):
                    return "queue wait"
                return None
            if low.endswith("client") or low.endswith("stub"):
                return "RPC"
        return None

    @staticmethod
    def _receiver_is_held_lock(call: ast.Call, ctx: FileContext,
                               recv: str) -> bool:
        """``self._cv.wait()`` inside ``with self._cv:`` is the
        condition-variable idiom, not a foreign blocking wait."""
        for anc in ctx.ancestors(call):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                return False
            if isinstance(anc, ast.With):
                for item in anc.items:
                    if ast.unparse(item.context_expr) == recv:
                        return True
        return False
