"""Signal-handler composition contract (ISSUE 9).

The drain coordinator and the flight recorder both arm SIGTERM; they
compose only because every ``signal.signal`` call either CAPTURES the
previous disposition (assignment, so the new handler can chain it) or
RESTORES one (handler expression references a ``prev``-named variable
or SIG_DFL/SIG_IGN). A bare overwrite silently disables whichever armed
first — a bug that only shows up when a preemption and a hang land in
the same incarnation.
"""

import ast

from tools.dlint.core import FileContext, Rule


def _handler_chains_prior(expr: ast.AST) -> bool:
    """True when the installed handler references a captured prior
    disposition (``prev``-named variable) or an explicit SIG_DFL /
    SIG_IGN restore."""
    for n in ast.walk(expr):
        if isinstance(n, ast.Name) and "prev" in n.id:
            return True
        if isinstance(n, ast.Attribute) and n.attr in ("SIG_DFL",
                                                       "SIG_IGN"):
            return True
    return False


class SignalChainRule(Rule):
    id = "signal-chain"
    title = "signal.signal captures or restores the prior disposition"
    interest = (ast.Call,)
    targets = ("dlrover_tpu/",)

    def visit(self, node: ast.AST, ctx: FileContext) -> None:
        assert isinstance(node, ast.Call)
        f = node.func
        if not (isinstance(f, ast.Attribute) and f.attr == "signal"
                and isinstance(f.value, ast.Name)
                and f.value.id == "signal"):
            return
        parent = ctx.parents.get(node)
        captured = isinstance(parent, (ast.Assign, ast.AnnAssign))
        restores = (
            len(node.args) >= 2 and _handler_chains_prior(node.args[1])
        )
        if not (captured or restores):
            self.report(
                ctx.relpath, node.lineno,
                "signal.signal call neither captures nor restores the "
                "prior disposition — handlers must compose (see "
                "docs/FAULT_TOLERANCE.md)",
                anchor="signal.signal",
            )
