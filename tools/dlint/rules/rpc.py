"""Reconnect-supervision coverage (PR 6).

Every public ``MasterClient`` method that performs an RPC must be
``@supervised_rpc``-wrapped or deliberately listed in
``UNSUPERVISED_RPCS`` — an RPC that bypasses reconnect supervision is a
lint failure here, not a hang when the master restarts in production.
The UNSUPERVISED_RPCS allowlist is read from the module's own AST so
the lint and the runtime can never disagree about its contents.
"""

import ast
from typing import List, Optional

from tools.dlint.core import FileContext, Rule


def _calls_rpc(fn_node: ast.FunctionDef) -> bool:
    for node in ast.walk(fn_node):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "_call"
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == "self"):
            return True
    return False


def _decorators(fn_node: ast.FunctionDef) -> List[str]:
    names = []
    for d in fn_node.decorator_list:
        if isinstance(d, ast.Name):
            names.append(d.id)
        elif isinstance(d, ast.Attribute):
            names.append(d.attr)
        elif isinstance(d, ast.Call):
            names.extend(_decorators_of_expr(d.func))
    return names


def _decorators_of_expr(expr: ast.AST) -> List[str]:
    if isinstance(expr, ast.Name):
        return [expr.id]
    if isinstance(expr, ast.Attribute):
        return [expr.attr]
    return []


class SupervisedRpcRule(Rule):
    id = "supervised-rpc"
    title = "public MasterClient RPCs ride the reconnect supervisor"
    interest = ()  # operates on the one file's module structure
    targets = ("dlrover_tpu/agent/master_client.py",)

    def end_file(self, ctx: FileContext) -> None:
        cls: Optional[ast.ClassDef] = next(
            (n for n in ctx.tree.body
             if isinstance(n, ast.ClassDef) and n.name == "MasterClient"),
            None,
        )
        if cls is None:
            self.report(
                ctx.relpath, 1,
                "no MasterClient class found — did the client move? "
                "(update SupervisedRpcRule.targets)",
                anchor="coverage",
            )
            return
        allowlist = self._unsupervised_allowlist(ctx.tree)
        methods = [
            n for n in cls.body if isinstance(n, ast.FunctionDef)
        ]
        for fn in methods:
            if fn.name.startswith("_") or not _calls_rpc(fn):
                continue
            decorated = "supervised_rpc" in _decorators(fn)
            if fn.name in allowlist:
                if decorated:
                    self.report(
                        ctx.relpath, fn.lineno,
                        f"{fn.name} is listed in UNSUPERVISED_RPCS but "
                        "decorated @supervised_rpc — drop one",
                        anchor=f"rpc:{fn.name}",
                    )
                continue
            if not decorated:
                self.report(
                    ctx.relpath, fn.lineno,
                    f"public MasterClient RPC {fn.name} without "
                    "@supervised_rpc — wrap it or add it to "
                    "UNSUPERVISED_RPCS with a justification",
                    anchor=f"rpc:{fn.name}",
                )

    @staticmethod
    def _unsupervised_allowlist(tree: ast.AST) -> frozenset:
        for node in getattr(tree, "body", []):
            if (isinstance(node, ast.Assign)
                    and any(isinstance(t, ast.Name)
                            and t.id == "UNSUPERVISED_RPCS"
                            for t in node.targets)):
                try:
                    return frozenset(ast.literal_eval(node.value))
                except (ValueError, TypeError):
                    return frozenset()
        return frozenset()
