"""Commit-before-reply on the shard ledger (PRs 6, 8, 12).

The exactly-once argument for shard delivery rests on ONE invariant:
every ledger mutation is persisted through the state journal BEFORE the
RPC reply leaves the master. If a reply could escape with the mutation
only in memory, a master crash between the two would re-deliver (or
lose) shards. This rule holds ``TaskManager`` to it statically:

  * a method that mutates a dataset ledger (``get_task`` /
    ``report_task_status`` / ``recover_tasks_of_node`` /
    ``restore_checkpoint`` / ``reset``) must also call
    ``self._persist_locked(...)``;
  * no ``return`` may sit between the last mutation and the next
    persist (line-order approximation of "every return path reaches a
    persist" — the TaskManager style keeps mutation and persist in the
    same ``with self._lock`` block, so line order IS path order there);
  * servicer ``rpc_*`` methods must not reach around the TaskManager
    into ledger internals (``.todo`` / ``.doing`` / ``._datasets``) —
    the persist discipline lives in TaskManager and bypassing it
    silently skips the journal.
"""

import ast
from typing import List, Optional

from tools.dlint.core import FileContext, Rule

#: calls that mutate a dataset ledger
_LEDGER_MUTATORS = frozenset({
    "get_task", "report_task_status", "recover_tasks_of_node",
    "restore_checkpoint", "reset",
})
#: local-alias call names for getattr-resolved mutators
#: (``recover = getattr(ds, "recover_tasks_of_node", None)``)
_ALIAS_MUTATORS = frozenset({"recover"})

_LEDGER_INTERNALS = frozenset({"todo", "doing", "_datasets"})


def _is_persist_call(node: ast.Call) -> bool:
    f = node.func
    return (isinstance(f, ast.Attribute)
            and f.attr in ("_persist_locked", "save_dataset_checkpoint")
            and not isinstance(f.value, ast.Constant))


def _is_mutator_call(node: ast.Call) -> bool:
    f = node.func
    if isinstance(f, ast.Attribute) and f.attr in _LEDGER_MUTATORS:
        return True
    if isinstance(f, ast.Name) and f.id in _ALIAS_MUTATORS:
        return True
    return False


class CommitBeforeReplyRule(Rule):
    id = "commit-before-reply"
    title = "shard-ledger mutations persist before any reply leaves"
    interest = (ast.FunctionDef,)
    targets = (
        "dlrover_tpu/master/shard/task_manager.py",
        "dlrover_tpu/master/servicer.py",
    )

    def visit(self, node: ast.AST, ctx: FileContext) -> None:
        assert isinstance(node, ast.FunctionDef)
        if ctx.relpath.endswith("servicer.py"):
            self._check_servicer(node, ctx)
        else:
            self._check_task_manager(node, ctx)

    # ---------------------------------------------------------- servicer

    def _check_servicer(self, fn: ast.FunctionDef,
                        ctx: FileContext) -> None:
        if not fn.name.startswith("rpc_"):
            return
        for n in ast.walk(fn):
            if (isinstance(n, ast.Attribute)
                    and n.attr in _LEDGER_INTERNALS):
                self.report(
                    ctx.relpath, n.lineno,
                    f"servicer {fn.name} touches ledger internal "
                    f".{n.attr} directly — mutations must go through "
                    "TaskManager so the commit-before-reply journal "
                    "write cannot be skipped",
                    anchor=f"{fn.name}:{n.attr}",
                )

    # ------------------------------------------------------ task manager

    def _check_task_manager(self, fn: ast.FunctionDef,
                            ctx: FileContext) -> None:
        # only methods of TaskManager itself (skip nested defs —
        # ast.walk from the engine hands us every FunctionDef)
        cls = self._owning_class(ctx, fn)
        if cls is None or cls.name != "TaskManager":
            return
        muts: List[int] = []
        persists: List[int] = []
        returns: List[int] = []
        for n in ast.walk(fn):
            if isinstance(n, ast.Call):
                if _is_persist_call(n):
                    persists.append(n.lineno)
                elif _is_mutator_call(n):
                    muts.append(n.lineno)
            elif isinstance(n, ast.Return):
                returns.append(n.lineno)
        if not muts:
            return
        if not persists:
            self.report(
                ctx.relpath, muts[0],
                f"TaskManager.{fn.name} mutates the shard ledger but "
                "never calls self._persist_locked(...) — a master restart "
                "would resume from a stale ledger (commit-before-"
                "reply, PR 6)",
                anchor=f"{fn.name}:no-persist",
            )
            return
        for r in sorted(returns):
            before = [m for m in muts if m < r]
            if not before:
                continue
            last_mut = max(before)
            if not any(last_mut <= p <= r for p in persists):
                self.report(
                    ctx.relpath, r,
                    f"TaskManager.{fn.name} can return at line {r} "
                    f"after a ledger mutation (line {last_mut}) "
                    "without persisting — every return path must "
                    "reach self._persist_locked(...) first",
                    anchor=f"{fn.name}:return-{r - last_mut}",
                )

    @staticmethod
    def _owning_class(ctx: FileContext,
                      fn: ast.FunctionDef) -> Optional[ast.ClassDef]:
        for anc in ctx.ancestors(fn):
            if isinstance(anc, ast.ClassDef):
                return anc
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return None  # nested def, not a method
        return None
