"""CLI: ``python -m tools.dlint`` — the repo's static-analysis gate.

Modes
-----
(default)            lint the production surface, print every finding
                     (baselined ones marked), per-rule timings, exit 0.
--check              the tier-1 gate: exit 1 on any finding missing
                     from the committed baseline OR any stale baseline
                     entry (the ratchet — the baseline can only shrink).
--json               machine output: {findings, baselined, stale,
                     timings, files, seconds}; composes with --check
                     (exit code still reflects the gate).
--rule ID            run a subset (repeatable).
--update-baseline    regenerate tools/dlint/baseline.json, preserving
                     existing reason strings; new entries get
                     "TODO: justify or fix" for the reviewer to see.
--write-knobs        regenerate docs/KNOBS.md from the code's env reads.

See docs/STATIC_ANALYSIS.md for the rule catalog.
"""

import argparse
import json
import sys
import time

from tools.dlint.baseline import (
    BASELINE_PATH,
    diff_baseline,
    load_baseline,
    write_baseline,
)
from tools.dlint.core import lint_repo


def _print_timings(result) -> None:
    print(f"\n{result.file_count} files, parse "
          f"{result.parse_seconds * 1000:.0f}ms")
    for rule_id in sorted(result.timings,
                          key=lambda r: -result.timings[r]):
        print(f"  {rule_id:<24} {result.timings[rule_id] * 1000:7.1f}ms")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.dlint",
        description="project-native static analysis "
                    "(docs/STATIC_ANALYSIS.md)",
    )
    ap.add_argument("--check", action="store_true",
                    help="gate mode: fail on unbaselined or stale")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="structured JSON on stdout")
    ap.add_argument("--rule", action="append", default=None,
                    help="run only this rule id (repeatable)")
    ap.add_argument("--update-baseline", action="store_true",
                    help="regenerate the committed baseline")
    ap.add_argument("--write-knobs", action="store_true",
                    help="regenerate docs/KNOBS.md")
    args = ap.parse_args(argv)

    if args.write_knobs:
        from tools.dlint.rules.knobs import write_knobs_md

        print(f"wrote {write_knobs_md()}")
        return 0

    t0 = time.perf_counter()
    result = lint_repo(rules=args.rule)
    elapsed = time.perf_counter() - t0

    if args.update_baseline:
        entries = write_baseline(result.findings)
        todo = sum(1 for e in entries.values()
                   if e["reason"].startswith("TODO"))
        print(f"wrote {BASELINE_PATH.relative_to(BASELINE_PATH.parents[2])}"
              f": {len(entries)} entries ({todo} with TODO reasons)")
        return 0

    baseline = load_baseline()
    if args.rule:
        # a subset run must not call untouched baseline entries stale
        active = set(args.rule)
        baseline = {fp: e for fp, e in baseline.items()
                    if e["rule"] in active}
    new, stale = diff_baseline(result.findings, baseline)

    if args.as_json:
        print(json.dumps({
            "findings": [f.to_dict() for f in result.findings],
            "new": [f.fingerprint for f in new],
            "baselined": sorted(
                f.fingerprint for f in result.findings
                if f.fingerprint in baseline
            ),
            "stale": stale,
            "timings": result.timings,
            "files": result.file_count,
            "seconds": round(elapsed, 3),
        }, indent=1))
    else:
        for f in result.findings:
            mark = " [baselined]" if f.fingerprint in baseline else ""
            print(f"{f.location()}: {f.rule}: {f.message} "
                  f"[{f.fingerprint}]{mark}")
        if not result.findings:
            print("clean: no findings")
        _print_timings(result)

    if args.check:
        problems = []
        if new:
            problems.append(f"{len(new)} finding(s) not in baseline")
        if stale:
            problems.append(
                f"{len(stale)} stale baseline entr(y/ies): "
                + ", ".join(stale)
            )
        if problems:
            if not args.as_json:
                print("\nFAIL: " + "; ".join(problems))
                print("fix the code, or (justified only) "
                      "`python -m tools.dlint --update-baseline` and "
                      "fill in the reason")
            return 1
        if not args.as_json:
            print(f"\nOK: gate clean in {elapsed:.2f}s "
                  f"({len(result.findings)} baselined finding(s))")
    return 0


if __name__ == "__main__":
    sys.exit(main())
