"""dlint core: the single-traversal rule engine.

Every prior lint in this repo re-parsed the tree it inspected (~8
``ast.walk`` loops across three test files by PR 14). Here each file is
parsed ONCE, a parent map is built ONCE, and every rule that targets
the file gets its ``visit`` callback during ONE walk — so the whole-repo
run stays inside the tier-1 <15s budget no matter how many contracts we
add.

A rule is a small class:

  * ``id`` / ``title`` — identity and the one-liner shown in reports;
  * ``interest`` — the AST node types its ``visit`` wants (empty means
    no per-node dispatch; the rule works from ``begin_file``/
    ``end_file``/``finalize`` only);
  * ``targets`` — repo-relative path prefixes the rule lints;
  * ``finalize(full_run)`` — cross-file checks (closed vocabularies,
    the knob registry). ``full_run`` is False when the engine was
    pointed at an explicit file list (fixtures, tests): set-equality
    checks that assume whole-repo coverage must skip then.

Findings are identified by a *fingerprint* — rule id + file +
semantic anchor (class.attr, function name, knob name…), deliberately
NOT the line number — so grandfathered findings in the committed
baseline survive unrelated edits but die with the code they describe.
"""

import ast
import dataclasses
import hashlib
import time
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Tuple, Type

REPO_ROOT = Path(__file__).resolve().parents[2]


@dataclasses.dataclass
class Finding:
    """One contract violation at one site."""

    rule: str
    path: str  # repo-relative
    line: int
    message: str
    #: stable semantic handle for fingerprinting (survives line shifts)
    anchor: str
    fingerprint: str = ""

    def location(self) -> str:
        return f"{self.path}:{self.line}"

    def to_dict(self) -> Dict[str, object]:
        return dataclasses.asdict(self)


class FileContext:
    """Per-file state shared by every rule during the one walk."""

    def __init__(self, path: Path, relpath: str, source: str,
                 tree: ast.AST):
        self.path = path
        self.relpath = relpath
        self.source = source
        self.tree = tree
        self._parents: Optional[Dict[ast.AST, ast.AST]] = None

    @property
    def parents(self) -> Dict[ast.AST, ast.AST]:
        """child -> parent map, built on first use and shared."""
        if self._parents is None:
            self._parents = {
                child: parent
                for parent in ast.walk(self.tree)
                for child in ast.iter_child_nodes(parent)
            }
        return self._parents

    def ancestors(self, node: ast.AST) -> Iterable[ast.AST]:
        """node's ancestor chain, nearest first."""
        cur = self.parents.get(node)
        while cur is not None:
            yield cur
            cur = self.parents.get(cur)


class Rule:
    """Base class for one enforced contract."""

    id: str = ""
    title: str = ""
    #: AST node classes visit() is called for; () disables dispatch
    interest: Tuple[type, ...] = ()
    #: repo-relative prefixes (dirs end with "/") or exact file paths
    targets: Tuple[str, ...] = ("dlrover_tpu/",)

    def __init__(self):
        self.findings: List[Finding] = []

    def wants(self, relpath: str) -> bool:
        return any(
            relpath == t or (t.endswith("/") and relpath.startswith(t))
            for t in self.targets
        )

    # lifecycle hooks -----------------------------------------------------
    def begin_file(self, ctx: FileContext) -> None:
        pass

    def visit(self, node: ast.AST, ctx: FileContext) -> None:
        pass

    def end_file(self, ctx: FileContext) -> None:
        pass

    def finalize(self, full_run: bool) -> None:
        pass

    # reporting -----------------------------------------------------------
    def report(self, relpath: str, line: int, message: str,
               anchor: str) -> None:
        self.findings.append(
            Finding(self.id, relpath, line, message, anchor)
        )


@dataclasses.dataclass
class LintResult:
    findings: List[Finding]
    timings: Dict[str, float]  # rule id -> seconds
    file_count: int
    parse_seconds: float

    def by_rule(self) -> Dict[str, List[Finding]]:
        out: Dict[str, List[Finding]] = {}
        for f in self.findings:
            out.setdefault(f.rule, []).append(f)
        return out


def default_files() -> List[Path]:
    """The production surface the contracts cover: the package plus the
    bench harness (tests enforce their own contracts on themselves)."""
    files = sorted(
        p for p in (REPO_ROOT / "dlrover_tpu").rglob("*.py")
        if "__pycache__" not in p.parts
    )
    files.append(REPO_ROOT / "bench.py")
    return files


def _assign_fingerprints(findings: List[Finding]) -> None:
    """Fingerprint = rule|path|anchor plus an occurrence index so two
    findings with the same anchor in one file stay distinct. Line
    numbers are deliberately excluded."""
    seen: Dict[Tuple[str, str, str], int] = {}
    for f in sorted(findings, key=lambda f: (f.rule, f.path, f.line)):
        key = (f.rule, f.path, f.anchor)
        occ = seen.get(key, 0)
        seen[key] = occ + 1
        raw = f"{f.rule}|{f.path}|{f.anchor}|{occ}"
        f.fingerprint = hashlib.sha1(raw.encode()).hexdigest()[:12]


def resolve_rules(
    rules: Optional[Sequence] = None,
) -> List[Rule]:
    """Accepts rule ids, Rule classes or instances; None = all."""
    from tools.dlint.rules import ALL_RULES

    if rules is None:
        return [cls() for cls in ALL_RULES]
    by_id: Dict[str, Type[Rule]] = {cls.id: cls for cls in ALL_RULES}
    out: List[Rule] = []
    for r in rules:
        if isinstance(r, Rule):
            out.append(r)
        elif isinstance(r, type) and issubclass(r, Rule):
            out.append(r())
        elif isinstance(r, str):
            if r not in by_id:
                raise KeyError(
                    f"unknown rule {r!r}; known: {sorted(by_id)}"
                )
            out.append(by_id[r]())
        else:
            raise TypeError(f"cannot resolve rule from {r!r}")
    return out


def lint_files(paths: Sequence[Path],
               rules: Optional[Sequence] = None,
               full_run: bool = False,
               respect_targets: bool = True) -> LintResult:
    """Run ``rules`` over ``paths`` with one parse + one walk per file.

    ``respect_targets=False`` forces every rule onto every path — the
    fixture tests use it to point one rule at one file outside the
    production tree."""
    active_rules = resolve_rules(rules)
    timings = {r.id: 0.0 for r in active_rules}
    parse_s = 0.0
    file_count = 0

    def timed(rule: Rule, fn, *args) -> None:
        t0 = time.perf_counter()
        fn(*args)
        timings[rule.id] += time.perf_counter() - t0

    for path in paths:
        path = Path(path)
        try:
            relpath = str(path.resolve().relative_to(REPO_ROOT))
        except ValueError:
            relpath = str(path)
        active = [
            r for r in active_rules
            if not respect_targets or r.wants(relpath)
        ]
        if not active:
            continue
        t0 = time.perf_counter()
        source = path.read_text()
        tree = ast.parse(source, filename=str(path))
        ctx = FileContext(path, relpath, source, tree)
        parse_s += time.perf_counter() - t0
        file_count += 1
        for r in active:
            timed(r, r.begin_file, ctx)
        dispatch = [r for r in active if r.interest]
        if dispatch:
            for node in ast.walk(tree):
                for r in dispatch:
                    if isinstance(node, r.interest):
                        timed(r, r.visit, node, ctx)
        for r in active:
            timed(r, r.end_file, ctx)

    findings: List[Finding] = []
    for r in active_rules:
        timed(r, r.finalize, full_run)
        findings.extend(r.findings)
    _assign_fingerprints(findings)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return LintResult(findings, timings, file_count, parse_s)


def lint_repo(rules: Optional[Sequence] = None) -> LintResult:
    """Lint the full production surface (the tier-1 entry)."""
    return lint_files(default_files(), rules=rules, full_run=True)
