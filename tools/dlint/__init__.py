"""dlint: the project's static-analysis framework (ISSUE 15).

The system's correctness story is a set of *contracts* — closed journal
vocabularies, capture-or-restore signal handlers, supervised RPCs,
commit-before-reply ledger persistence, lock discipline — that no
general-purpose linter knows about. dlint encodes each contract as a
declarative :class:`~tools.dlint.core.Rule` and checks all of them in a
single AST traversal per file, so the whole repo lints in seconds and a
new invariant costs one small class, not another ad-hoc ``ast.walk``
loop in a test file.

Entry points:

  * ``python -m tools.dlint --check`` — the tier-1 gate (exits nonzero
    on any finding not in the committed baseline, or any stale baseline
    entry);
  * ``python -m tools.dlint --json`` — structured output for CI;
  * :func:`tools.dlint.engine.lint_repo` — the in-process API the test
    shims use.

See docs/STATIC_ANALYSIS.md for the rule catalog, the bug class each
rule encodes, and the baseline workflow.
"""

from tools.dlint.core import Finding, Rule, lint_files, lint_repo
from tools.dlint.baseline import load_baseline

__all__ = [
    "Finding",
    "Rule",
    "lint_files",
    "lint_repo",
    "load_baseline",
]
