"""Headline benchmark: Llama decoder training throughput on one chip.

Prints ONE JSON line:
  {"metric": "mfu_percent", "value": N, "unit": "%", "vs_baseline": N,
   ...detail fields}

Baseline: the reference's published HFU with ATorch is 49.6% on A100/H100
clusters (docs/blogs/stabilize_llm_training_cn.md:281, BASELINE.md);
vs_baseline = our MFU / 49.6.

On a real TPU this runs a ~1.1B-param Llama (bf16, seq 2048) sized for a
single chip; on CPU (driver-less dev runs) it degrades to the tiny config
so the script always produces a line.
"""

import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np

BASELINE_HFU_PERCENT = 49.6
#: the reference's CRITEO Wide&Deep rate AFTER DeepRec PS autoscaling
#: added 3 workers (docs/blogs/deeprec_autoscale_cn.md:223, BASELINE.md)
BASELINE_DLRM_STEPS_PER_SEC = 100.0


def bench_dlrm():
    """Single-chip recommender throughput (BASELINE config #4).

    The reference's comparable is steps/sec on the CRITEO Wide&Deep
    job: 30 -> 100 step/s after DeepRec's PS autoscaler added 3
    workers (CPU cluster). Here the same model shape (dim-8 deep
    embeddings + wide tower over the CRITEO vocab stats) trains on one
    TPU chip with the vocab-stacked table — no PS tier at all;
    vs_baseline = our steps/sec over their post-scaling 100."""
    import optax

    from dlrover_tpu.models import dlrm
    from dlrover_tpu.parallel.mesh import create_mesh

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    cfg = dlrm.criteo_wide_deep()
    batch = 4096 if on_tpu else 256
    steps, warmup = (30, 5) if on_tpu else (6, 2)

    mesh = create_mesh([("data", 1), ("fsdp", 1)], devices=[dev])
    trainer = dlrm.make_trainer(
        cfg, mesh, optimizer=optax.adagrad(0.05)
    )
    params, opt_state = trainer.init(jax.random.key(0))

    rng = np.random.default_rng(0)
    dense = rng.standard_normal(
        (1, batch, cfg.dense_dim), dtype=np.float32
    )
    cat = np.stack(
        [rng.integers(0, s, (1, batch)) for s in cfg.vocab_sizes], -1
    ).astype(np.int32)
    labels = rng.integers(0, 2, (1, batch)).astype(np.int32)
    mb = trainer.shard_batch((dense, cat, labels))

    for _ in range(warmup):
        params, opt_state, loss = trainer.train_step(
            params, opt_state, mb
        )
    float(loss)  # hard sync (axon tunnel ignores block_until_ready)
    t0 = time.perf_counter()
    for _ in range(steps):
        params, opt_state, loss = trainer.train_step(
            params, opt_state, mb
        )
    loss_val = float(loss)
    dt = time.perf_counter() - t0

    step_time = dt / steps
    sps = 1.0 / step_time
    print(json.dumps({
        "metric": "dlrm_steps_per_sec",
        "value": round(sps, 1),
        "unit": "steps/s",
        "vs_baseline": round(sps / BASELINE_DLRM_STEPS_PER_SEC, 3),
        "baseline": "DeepRec CRITEO Wide&Deep 100 step/s after PS "
        "autoscale (deeprec_autoscale_cn.md:223)",
        "examples_per_sec": round(batch * sps, 1),
        "batch": batch,
        "step_time_ms": round(step_time * 1e3, 2),
        "table_rows": cfg.padded_vocab,
        "embed_dim": cfg.embed_dim,
        "device": getattr(dev, "device_kind", dev.platform),
        "platform": dev.platform,
        "final_loss": round(loss_val, 4),
    }))


class _BenchProducer:
    """Module-level (spawn-picklable) synthetic batch stream for the
    --data shm path."""

    def __init__(self, n_batches, batch, seq, vocab):
        self.n_batches = n_batches
        self.batch = batch
        self.seq = seq
        self.vocab = vocab

    def __call__(self):
        rng = np.random.default_rng(0)
        for _ in range(self.n_batches):
            t = rng.integers(
                0, self.vocab, (self.batch, self.seq), dtype=np.int32
            )
            yield t, t


def _honor_platform_env():
    """Site hooks may rewrite jax's platform priority (the TPU-tunnel
    sitecustomize sets axon,cpu); a dev run launched with
    JAX_PLATFORMS=cpu must not probe the tunnel first."""
    import os

    plat = os.getenv("JAX_PLATFORMS", "")
    if plat and jax.config.jax_platforms != plat:
        try:
            jax.config.update("jax_platforms", plat)
        except Exception:
            pass  # backend already initialized


def _guard_backend_discovery(metric: str, unit: str,
                             timeout_s: float = 300.0):
    """A wedged device service (e.g. a TPU tunnel whose claim is stuck)
    makes jax.devices() block FOREVER — the bench must emit its one
    JSON line either way, so discovery runs under a watchdog and a
    fast init failure also becomes the error line. 300s is far above
    healthy backend init (seconds) and unrelated to compile time,
    which happens after discovery."""
    import threading

    done = threading.Event()
    err = []

    def probe():
        try:
            jax.devices()
        except Exception as e:
            err.append(e)
        done.set()

    def bail(reason):
        print(json.dumps({
            "metric": metric, "value": 0.0, "unit": unit,
            "vs_baseline": 0.0, "error": reason,
        }))
        raise SystemExit(2)

    t = threading.Thread(target=probe, daemon=True,
                         name="bench-device-probe")
    t.start()
    if not done.wait(timeout_s):
        bail(
            f"device discovery hung >{timeout_s:.0f}s (wedged "
            "backend/tunnel); no measurement possible"
        )
    if err:
        bail(f"backend init failed: {err[0]}")


def main():
    import argparse

    import optax

    from dlrover_tpu.auto.device_context import peak_flops_per_chip
    from dlrover_tpu.models import llama
    from dlrover_tpu.parallel.mesh import create_mesh
    from dlrover_tpu.trainer.sharded import make_trainer_for_llama

    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--data", choices=["inmem", "shm"], default="inmem",
        help="shm: feed every step from coworker processes over the "
        "C++ shm ring + DevicePrefetch (the production data plane) "
        "instead of reusing one in-memory batch",
    )
    ap.add_argument(
        "--model", choices=["llama", "dlrm"], default="llama",
        help="dlrm: the CRITEO recommender bench (steps/sec vs the "
        "reference's DeepRec autoscaling claim) instead of the "
        "headline Llama MFU",
    )
    ap.add_argument(
        "--ckpt-interval", type=int, default=0,
        help="flash-save (params, opt_state) every N timed steps and "
        "report the measured train-thread stall (ckpt_stall_ms) in "
        "the JSON line; 0 disables checkpointing (default); llama "
        "bench only",
    )
    ap.add_argument(
        "--ckpt-dir", default="",
        help="checkpoint directory for --ckpt-interval (default: a "
        "fresh temp dir, removed after the run)",
    )
    args = ap.parse_args()
    _honor_platform_env()
    if args.model == "dlrm":
        _guard_backend_discovery("dlrm_steps_per_sec", "steps/s")
        bench_dlrm()
        return
    _guard_backend_discovery("mfu_percent", "%")

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    if on_tpu:
        # sized for a 16GB-HBM chip (v5e): params+adam ≈ 8.8GB bf16.
        # "dots_attn_out" remat keeps the Pallas flash-attention call
        # OUTSIDE the checkpointed segments, so its custom_vjp
        # residuals (q,k,v,o,lse ≈ 77MB/layer at batch 3) are saved
        # and the backward never re-runs the forward kernel — official
        # line: 401 ms / 56.8% MFU vs 430 ms / 52.99% for plain "dots"
        # at the same batch (batch 4 + the residuals does not fit)
        cfg = llama.llama_1b(remat="dots_attn_out")
        batch, seq, steps, warmup = 3, 2048, 20, 3
    else:
        cfg = llama.llama_tiny()
        batch, seq, steps, warmup = 8, 128, 6, 2

    mesh = create_mesh([("data", 1)], devices=[dev])
    trainer = make_trainer_for_llama(
        cfg, mesh, strategy="ddp", accum_steps=1,
        optimizer=optax.adamw(1e-4, b1=0.9, b2=0.95),
    )
    params, opt_state = trainer.init(jax.random.key(0))

    rng = np.random.default_rng(0)
    tokens = rng.integers(
        0, cfg.vocab_size, (batch, seq), dtype=np.int32
    )
    mb = trainer.shard_batch(trainer.microbatch((tokens, tokens)))

    batches = loader = prefetch = None
    if args.data == "shm":
        from dlrover_tpu.data.shm_dataloader import (
            DevicePrefetch,
            ShmDataLoader,
        )

        loader = ShmDataLoader(
            _BenchProducer(
                warmup + steps + 1, batch, seq, cfg.vocab_size
            ),
            num_workers=2,
            slot_bytes=max(1 << 20, 4 * batch * seq * 2 + 4096),
        )
        # microbatch reshape runs on the fill thread (transform=), so
        # the train loop only dequeues device-ready microbatches and
        # the data.fetch/data.stage spans split source wait from
        # reshape+H2D staging
        prefetch = DevicePrefetch(
            loader, depth=2, sharding=trainer.microbatch_sharding,
            transform=trainer.microbatch,
        )
        batches = iter(prefetch)

    def next_mb():
        return mb if batches is None else next(batches)

    ckpt = None
    ckpt_tmp = None
    ckpt_stalls = []
    ckpt_waits = []
    if args.ckpt_interval > 0:
        import tempfile

        from dlrover_tpu.trainer.checkpoint import FlashCheckpointer

        ckpt_dir = args.ckpt_dir
        if not ckpt_dir:
            ckpt_tmp = tempfile.TemporaryDirectory(prefix="bench_ckpt_")
            ckpt_dir = ckpt_tmp.name
        # RAM tier only: the bench measures the train-thread stall of
        # the zero-stall save path (benchmarks/ckpt_stall.py covers
        # the persist pipeline under a slow store)
        ckpt = FlashCheckpointer(
            persist_dir=os.path.join(ckpt_dir, "persist"),
            ram_dir=os.path.join(ckpt_dir, "ram"),
            persist_interval=0, use_orbax=False,
        )

    for _ in range(warmup):
        params, opt_state, loss = trainer.train_step(
            params, opt_state, next_mb()
        )
    float(loss)  # host transfer = hard sync (the axon tunnel does not
    # honor block_until_ready)

    # per-phase breakdown via span tracing (docs/TELEMETRY.md): ring
    # only, armed AFTER warmup so compile time never pollutes the
    # phase means. The spans measure TRAIN-THREAD time: "data" is the
    # host-side wait on the feed, "dispatch" the step call (async
    # dispatch until the device queue back-pressures)
    from dlrover_tpu.telemetry import tracing

    tracing.clear()
    tracing.enable()

    # goodput over the timed window (telemetry/goodput.py): the bench
    # is single-process and fault-free, so training is the whole
    # window minus the measured checkpoint stalls — the same ledger
    # arithmetic the elastic trainer runs, so BENCH_*.json tracks
    # effective throughput with the fields the job-level account uses
    from dlrover_tpu.telemetry.goodput import Phase, PhaseLedger

    ledger = PhaseLedger(phase=Phase.TRAINING, journal_events=False)

    t0 = time.perf_counter()
    ckpt_pending = False
    for i in range(steps):
        if ckpt_pending:
            # donation-safety contract (docs/CHECKPOINT.md): the
            # trainer donates (params, opt_state) when resharding
            # donation is safe, so the async-staged save must own its
            # host copies before this dispatch invalidates the source
            # buffers; reported separately from the dispatch stall
            tw = time.perf_counter()
            with tracing.span("ckpt.wait_staged"):
                ckpt.wait_staged()
            ckpt_waits.append((time.perf_counter() - tw) * 1e3)
            ckpt_pending = False
        with tracing.span("data"):
            b = next_mb()
        with tracing.span("dispatch"):
            params, opt_state, loss = trainer.train_step(
                params, opt_state, b
            )
        if ckpt is not None and (i + 1) % args.ckpt_interval == 0:
            ckpt_stalls.append(
                ckpt.save(i + 1, (params, opt_state))
            )
            ckpt_pending = True
    # one sync at the end: the final loss depends on the whole step chain,
    # so this waits for all 20 steps without a per-step host round-trip
    loss_val = float(loss)
    dt = time.perf_counter() - t0
    # silent-failure guard on the bench output itself: a non-finite
    # final loss means the throughput was measured over garbage math —
    # the row says so instead of publishing a clean-looking number.
    # Checked outside the timed window (the loop deliberately avoids
    # per-step host syncs); rollbacks are structurally 0 in this
    # single-process bench, present so BENCH_*.json rows compare
    # field-for-field with elastic runs.
    from dlrover_tpu.fault_tolerance.sentinel import TrainingSentinel

    sentinel = TrainingSentinel()
    sentinel.check(steps, loss_val)
    # re-label the measured checkpoint costs (stalls + staging waits)
    # inside the window as ckpt_stall badput
    ledger.credit(
        Phase.CKPT_STALL,
        (sum(ckpt_stalls) + sum(ckpt_waits)) / 1e3,
    )
    goodput_snap = ledger.close()
    phases = tracing.summarize(
        ("data", "dispatch", "ckpt.wait_staged", "ckpt.stage",
         "data.fetch", "data.stage")
    )
    tracing.disable()

    if ckpt is not None:
        ckpt.close()  # outside the timed window: drains the pipeline
        if ckpt_tmp is not None:
            ckpt_tmp.cleanup()

    if loader is not None:
        # same shutdown order as ElasticShmDataLoader.shutdown: EOF the
        # ring, let the prefetch thread drain to the source's end, and
        # only unmap once no native pop can be in flight
        loader.close()
        joined = prefetch.join(timeout=10.0)
        loader.shutdown(destroy=joined)

    step_time = dt / steps
    tokens_per_step = batch * seq
    tokens_per_sec = tokens_per_step / step_time
    flops_per_tok = llama.flops_per_token(cfg, seq)
    model_flops_per_step = tokens_per_step * flops_per_tok
    peak = peak_flops_per_chip(dev)

    from dlrover_tpu.trainer import profiler

    # MFU: analytic model flops over the measured step time (the
    # headline); HFU: the XLA-counted hardware flops (remat recompute
    # included) over the same denominator. CAVEAT on HFU: the backend
    # flop counter excludes custom-call (Pallas) kernels, so on the
    # flash-attention path it UNDERCOUNTS — reported as a floor, not a
    # claim. Off-TPU both are 0 (peak undefined).
    mfu = (
        profiler.utilization(model_flops_per_step, step_time, peak)
        if on_tpu else 0.0
    )

    abstract = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype),
        (params, opt_state, mb),
    )
    prof = profiler.profile_step(
        trainer.train_step, *abstract, params=params
    )
    hfu = (
        profiler.utilization(prof.flops, step_time, peak)
        if on_tpu else 0.0
    )

    # which flash-attention blocks the step actually ran with, and
    # where they came from (ops/tuning.py: cache | measured |
    # heuristic); null off-TPU where the Pallas path never dispatches
    from dlrover_tpu.ops import tuning

    sel = tuning.last_selection()

    result = {
        "metric": "mfu_percent",
        "value": round(mfu, 2),
        "unit": "%",
        "vs_baseline": round(mfu / BASELINE_HFU_PERCENT, 3),
        "mfu_percent": round(mfu, 2),
        "hfu_percent": round(hfu, 2),
        "model_flops_per_step": model_flops_per_step,
        "tokens_per_sec_per_chip": round(tokens_per_sec, 1),
        "step_time_ms": round(step_time * 1e3, 1),
        "params_m": round(llama.param_count(cfg) / 1e6, 1),
        "batch": batch,
        "seq": seq,
        "device": getattr(dev, "device_kind", dev.platform),
        "platform": dev.platform,
        "final_loss": round(loss_val, 4),
        "xla_counted_flops_per_step": prof.flops,
        "hbm_gb_per_step": round(prof.hbm_bytes / 2**30, 2),
        "param_count": prof.param_count,
        "data_path": args.data,
        "attn_block_q": sel["block_q"] if sel else None,
        "attn_block_k": sel["block_k"] if sel else None,
        "attn_tuning_source": sel["source"] if sel else None,
        # per-phase train-thread breakdown from the span layer (where
        # step time goes: feed wait vs dispatch; docs/TELEMETRY.md) —
        # ckpt_wait_staged_ms / ckpt_stall_ms below stay the donation
        # and staging costs when --ckpt-interval is on
        "data_ms": round(
            phases.get("data", {}).get("mean_ms", 0.0), 3
        ),
        "data_ms_max": round(
            phases.get("data", {}).get("max_ms", 0.0), 3
        ),
        "dispatch_ms": round(
            phases.get("dispatch", {}).get("mean_ms", 0.0), 3
        ),
        "dispatch_ms_max": round(
            phases.get("dispatch", {}).get("max_ms", 0.0), 3
        ),
        # feed-side costs (docs/DATA_PIPELINE.md BENCH conventions):
        # data_stall_ms = the train thread blocked on the feed (same
        # series as data_ms; named for cross-bench comparison),
        # shard_dispatch_ms = prefetch-THREAD wait on the upstream
        # source per batch (data.fetch span; 0.0 on the inmem path
        # where no prefetch thread runs)
        "data_stall_ms": round(
            phases.get("data", {}).get("mean_ms", 0.0), 3
        ),
        "shard_dispatch_ms": round(
            phases.get("data.fetch", {}).get("mean_ms", 0.0), 3
        ),
        "data_stage_ms": round(
            phases.get("data.stage", {}).get("mean_ms", 0.0), 3
        ),
        # effective-throughput account (docs/TELEMETRY.md Goodput):
        # fraction of the timed window spent training, and the badput
        # breakdown in the job-level causes. rendezvous/restart are
        # structurally 0 in this single-process bench; they exist so
        # BENCH_*.json rows compare field-for-field with elastic runs
        "goodput_percent": goodput_snap["goodput_percent"],
        "badput_ms": {
            "rendezvous": round(
                goodput_snap["phases"][Phase.RENDEZVOUS] * 1e3, 3
            ),
            "ckpt_stall": round(
                goodput_snap["phases"][Phase.CKPT_STALL] * 1e3, 3
            ),
            "restart": round(
                goodput_snap["phases"][Phase.RESTART] * 1e3, 3
            ),
            "rollback": round(
                goodput_snap["phases"][Phase.ROLLBACK] * 1e3, 3
            ),
        },
        "anomaly_count": sentinel.anomaly_count,
        "rollbacks": 0,
    }
    if ckpt_stalls:
        # train-thread cost of the flash saves inside the timed loop
        # (docs/CHECKPOINT.md "BENCH conventions"); step_time_ms above
        # already absorbs these stalls AND the staging waits —
        # checkpointing overhead is visible, not hidden
        result["ckpt_stall_ms"] = round(
            sum(ckpt_stalls) / len(ckpt_stalls), 3
        )
        result["ckpt_stall_ms_max"] = round(max(ckpt_stalls), 3)
        if ckpt_waits:
            result["ckpt_wait_staged_ms"] = round(
                sum(ckpt_waits) / len(ckpt_waits), 3
            )
            result["ckpt_wait_staged_ms_max"] = round(
                max(ckpt_waits), 3
            )
        result["ckpt_saves"] = len(ckpt_stalls)
        result["ckpt_interval"] = args.ckpt_interval
        # archives written by this run are sharded format v2
        # (topology-elastic manifest; docs/CHECKPOINT.md "Format v2")
        result["ckpt_format"] = 2
    print(json.dumps(result))


if __name__ == "__main__":
    main()
