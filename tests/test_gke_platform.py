"""GKE pod platform tests (M12/M14 parity: the reference's
test_pod_scaler.py / test_k8s_watcher.py pattern over a fake API)."""

from dlrover_tpu.common.constants import (
    NodeEnv,
    NodeExitReason,
    NodeStatus,
    NodeType,
)
from dlrover_tpu.common.node import Node, NodeResource
from dlrover_tpu.master.scaler.base_scaler import ScalePlan
from dlrover_tpu.scheduler.gke import (
    FakeK8sApi,
    GkePodScaler,
    GkePodWatcher,
    pod_to_node,
)


def _scaler(api=None):
    api = api or FakeK8sApi()
    return GkePodScaler(
        "job", api, "10.0.0.1:5000", worker_env={"EXTRA": "1"},
    ), api


def _worker(i, relaunch=0):
    n = Node(NodeType.WORKER, i, config_resource=NodeResource(),
             relaunch_count=relaunch)
    return n


def test_launch_creates_pod_with_env_contract():
    scaler, api = _scaler()
    scaler.scale(ScalePlan(launch_nodes=[_worker(0)]))
    (rec,) = api.list_pods()
    assert rec.name == "job-worker-0"
    assert rec["labels"]["dlrover-job"] == "job"
    env = rec["env"]
    assert env[NodeEnv.MASTER_ADDR] == "10.0.0.1:5000"
    assert env[NodeEnv.NODE_ID] == "0"
    assert env["EXTRA"] == "1"


def test_remove_and_reconcile_round_trip():
    scaler, api = _scaler()
    nodes = [_worker(i) for i in range(4)]
    scaler.scale(ScalePlan(launch_nodes=nodes))
    assert len(api.list_pods()) == 4
    # explicit removal
    scaler.scale(ScalePlan(remove_nodes=[nodes[1]]))
    names = {r.name for r in api.list_pods()}
    assert "job-worker-1" not in names and len(names) == 3
    # reconcile down to 2: newest ids go first
    from dlrover_tpu.common.node import NodeGroupResource

    scaler.scale(ScalePlan(node_group_resources={
        NodeType.WORKER: NodeGroupResource(2, NodeResource()),
    }))
    names = {r.name for r in api.list_pods()}
    assert names == {"job-worker-0", "job-worker-2"}


def test_create_retry_then_give_up_marks_failed():
    scaler, api = _scaler()
    api.fail_creates = 1
    node = _worker(0)
    scaler.scale(ScalePlan(launch_nodes=[node]))
    assert not api.list_pods()  # first create failed, queued
    # drain the retry queue inline
    pending = scaler._create_queue.get_nowait()
    scaler._launch(pending)
    assert len(api.list_pods()) == 1  # retry succeeded
    # exhausting the budget surfaces a failure instead of a phantom
    api.fail_creates = 10**6
    node2 = _worker(1)
    for _ in range(10):
        scaler._launch(node2)
    assert node2.status == NodeStatus.FAILED
    assert node2.exit_reason == NodeExitReason.HARDWARE_ERROR


def test_pod_exit_reason_mapping():
    scaler, api = _scaler()
    scaler.scale(ScalePlan(launch_nodes=[_worker(i) for i in range(4)]))
    api.tick()
    api.oom_kill("job-worker-0")
    api.evict("job-worker-1")
    api.crash("job-worker-2", exit_code=1)
    api.crash("job-worker-3", exit_code=99)
    by_id = {
        n.id: n for n in map(pod_to_node, api.list_pods()) if n
    }
    assert by_id[0].exit_reason == NodeExitReason.OOM
    assert by_id[1].exit_reason == NodeExitReason.PREEMPTED
    assert by_id[2].exit_reason == NodeExitReason.FATAL_ERROR
    assert by_id[3].exit_reason == NodeExitReason.KILLED
    assert all(n.status == NodeStatus.FAILED for n in by_id.values())


def test_watcher_diffs_phases_and_deletions():
    scaler, api = _scaler()
    watcher = GkePodWatcher("job", api, poll_interval=0.01)
    scaler.scale(ScalePlan(launch_nodes=[_worker(0), _worker(1)]))
    events = watcher.poll_events()
    assert {e.node.status for e in events} == {NodeStatus.PENDING}
    api.tick()
    events = watcher.poll_events()
    assert {e.node.status for e in events} == {NodeStatus.RUNNING}
    assert watcher.poll_events() == []  # no changes, no events
    api.delete_pod("job-worker-1")
    events = watcher.poll_events()
    assert len(events) == 1
    assert events[0].node.status == NodeStatus.DELETED
    assert events[0].node.id == 1
    # list() reflects the live fleet
    assert [n.id for n in watcher.list()] == [0]


def test_scale_plan_drives_job_manager_via_watcher():
    """ScalePlan -> fake-pod mutations -> watcher events -> job manager
    bookkeeping: the round trip the reference's pod tests prove."""
    from dlrover_tpu.master.node.dist_job_manager import (
        DistributedJobManager,
    )

    scaler, api = _scaler()
    watcher = GkePodWatcher("job", api, poll_interval=0.01)
    mgr = DistributedJobManager(scaler=scaler)
    nodes = mgr._node_managers[NodeType.WORKER].scale_up_nodes(
        2, NodeResource()
    )
    scaler.scale(ScalePlan(launch_nodes=nodes))
    api.tick()
    for event in watcher.poll_events():
        mgr.process_event(event)
    running = mgr.get_running_nodes()
    assert {n.id for n in running} == {0, 1}
    # an OOM kill flows back as a relaunch with the OOM exit reason
    api.oom_kill("job-worker-0")
    for event in watcher.poll_events():
        mgr.process_event(event)
    node0 = mgr.get_node(NodeType.WORKER, 0)
    assert node0.status == NodeStatus.FAILED
    assert node0.exit_reason == NodeExitReason.OOM
    # relaunch created a replacement pod through the scaler
    assert any(
        r.name == "job-worker-2" for r in api.list_pods()
    )


def test_factory_builds_gke_platform(monkeypatch):
    from types import SimpleNamespace

    from dlrover_tpu.scheduler.factory import build_platform

    monkeypatch.setenv("DLROVER_TPU_FAKE_PLATFORM", "1")
    scaler, watcher = build_platform(
        SimpleNamespace(platform="gke", job_name="j", worker_env={}),
        "localhost:1",
    )
    assert isinstance(scaler, GkePodScaler)
    assert isinstance(watcher, GkePodWatcher)
