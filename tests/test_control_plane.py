"""Control-plane fan-in drills (ISSUE 12).

Four layers of the batched-report path, tested where each contract
actually lives:

* FileStore ``set_many`` crash consistency — a kill inside the flush
  window restores to pre- or post-batch state, never a torn mix;
* the journal lane over it — write-behind staging, redo-log recovery
  surfacing ``control.journal_recovered``, and the shard ledger's
  commit-before-reply writes staying synchronous;
* DeltaTracker / servicer delta semantics — sections ride only when
  changed since the last *acked* report, sheds never advance the
  baseline, resync on unknown reporter or new incarnation;
* the swarm bench's smoke tier end to end (real gRPC master), gating
  zero dropped heartbeats under load shed.
"""

import json
import os
import subprocess
import sys
import time

import pytest

from dlrover_tpu.common import comm
from dlrover_tpu.agent.status_reporter import (
    CPU_MIN_DELTA_PCT,
    DeltaTracker,
    MEM_MIN_DELTA_MB,
)
from dlrover_tpu.common.constants import NodeStatus, NodeType
from dlrover_tpu.common.node import Node
from dlrover_tpu.master.node.dist_job_manager import DistributedJobManager
from dlrover_tpu.master.monitor.speed_monitor import SpeedMonitor
from dlrover_tpu.master.servicer import MasterServicer
from dlrover_tpu.master.state_journal import (
    MasterStateJournal,
    build_master_state_journal,
)
from dlrover_tpu.telemetry.journal import (
    EventJournal,
    default_journal,
    set_default_journal,
)
from dlrover_tpu.util.state_store import FileStore

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh_event_journal():
    set_default_journal(EventJournal())
    yield
    set_default_journal(EventJournal())


# --------------------------------------------------------- store crashes


def _batch():
    return {
        "j/kv": {"a": "1", "b": "2"},
        "j/rdzv/worker": {"round": 7},
        "j/speed": {"step": 1200, "batch_feed": False},
    }


def test_crash_before_commit_point_restores_pre_batch(tmp_path,
                                                      monkeypatch):
    """A kill before the redo-log rename leaves every key at its
    pre-batch value — the batch simply never happened."""
    root = str(tmp_path / "store")
    store = FileStore(root)
    store.set("j/speed", {"step": 1000, "batch_feed": False})

    real_replace = os.replace

    def dying_replace(src, dst):
        if dst.endswith(".redo"):
            raise OSError("simulated kill before commit point")
        return real_replace(src, dst)

    import dlrover_tpu.util.state_store as state_store_mod
    monkeypatch.setattr(state_store_mod.os, "replace", dying_replace)
    with pytest.raises(OSError):
        store.set_many(_batch())
    monkeypatch.undo()

    survivor = FileStore(root)
    assert survivor.recovered_txn_keys == []
    assert survivor.get("j/speed") == {"step": 1000, "batch_feed": False}
    assert survivor.get("j/kv") is None
    assert survivor.get("j/rdzv/worker") is None


def test_crash_after_commit_point_replays_to_post_batch(tmp_path,
                                                        monkeypatch):
    """A kill after the rename but mid-apply is replayed by the next
    FileStore on the root: every key ends at its post-batch value —
    never a mix."""
    root = str(tmp_path / "store")
    store = FileStore(root)
    store.set("j/speed", {"step": 1000, "batch_feed": False})

    applied = []
    real_set_locked = FileStore._set_locked

    def dying_set_locked(self, key, value):
        if applied:
            # first key landed; die before the rest of the batch
            raise SystemExit("simulated kill mid-apply")
        applied.append(key)
        return real_set_locked(self, key, value)

    monkeypatch.setattr(FileStore, "_set_locked", dying_set_locked)
    with pytest.raises(SystemExit):
        store.set_many(_batch())
    monkeypatch.undo()
    assert len(applied) == 1  # genuinely torn on disk at "crash" time

    survivor = FileStore(root)
    assert sorted(survivor.recovered_txn_keys) == sorted(_batch())
    for key, value in _batch().items():
        assert survivor.get(key) == value, key


def test_journal_recovery_surfaces_control_event(tmp_path):
    """build_master_state_journal over a root holding an interrupted
    commit replays it and records control.journal_recovered."""
    root = str(tmp_path / "state")
    os.makedirs(root)
    with open(os.path.join(root, FileStore.TXN_FILE), "w") as f:
        json.dump({"items": [[k, v] for k, v in _batch().items()]}, f)

    journal = build_master_state_journal("drill", state_dir=root)
    try:
        events = default_journal().events("control.journal_recovered")
        assert len(events) == 1
        assert events[0]["data"]["keys"] == len(_batch())
        assert journal._store.get("j/speed") == {
            "step": 1200, "batch_feed": False,
        }
    finally:
        journal.close()


# --------------------------------------------------------- journal lane


def test_unflushed_window_is_lost_whole_never_torn(tmp_path):
    """Staged-but-unflushed mutations are the documented crash-window
    loss: a successor reading the DISK sees the pre-batch state for
    every key (the batch vanishes atomically, it never half-lands)."""
    root = str(tmp_path / "state")
    store = FileStore(root)
    journal = MasterStateJournal(store, "drill", commit_window=3600.0)
    journal.save_global_step(500)
    journal.flush()

    journal.save_global_step(900)
    journal.save_rdzv_round("worker", 3)
    journal.save_kv({"token": b"xyz"})
    # crash: the journal object is abandoned without flush/close
    survivor = MasterStateJournal(FileStore(root), "drill")
    assert survivor.load_global_step() == (500, False)
    assert survivor.load_rdzv_rounds() == {}
    assert survivor.load_kv() == {}

    # graceful path: flush commits the whole window as one transaction
    journal.flush()
    survivor = MasterStateJournal(FileStore(root), "drill")
    assert survivor.load_global_step() == (900, False)
    assert survivor.load_rdzv_rounds() == {"worker": 3}
    assert survivor.load_kv() == {"token": b"xyz"}
    journal.close()


def test_shard_ledger_writes_through_the_lane(tmp_path):
    """Dataset checkpoints keep the commit-before-reply contract: even
    with a huge commit window they hit disk synchronously, because the
    exactly-once argument for shard redelivery depends on it."""
    root = str(tmp_path / "state")
    journal = MasterStateJournal(FileStore(root), "drill",
                                 commit_window=3600.0)
    journal.save_dataset_params("train", {"dataset_name": "train",
                                          "dataset_size": 100})
    journal.save_dataset_checkpoint("train", json.dumps({"done": [1]}))
    # a DIFFERENT store instance only sees what reached the disk
    params, ckpt = MasterStateJournal(
        FileStore(root), "drill"
    ).load_dataset("train")
    assert params == {"dataset_name": "train", "dataset_size": 100}
    assert json.loads(ckpt) == {"done": [1]}
    journal.close()


def test_durable_put_jumps_the_window(tmp_path):
    root = str(tmp_path / "state")
    journal = MasterStateJournal(FileStore(root), "drill",
                                 commit_window=3600.0)
    journal.save_rdzv_round("worker", 9, durable=True)
    assert MasterStateJournal(
        FileStore(root), "drill"
    ).load_rdzv_rounds() == {"worker": 9}
    journal.close()


# --------------------------------------------------------- delta tracker


GP = {
    "goodput_phases": {"init": 45.0, "training": 120.0},
    "goodput_elapsed_s": 170.0,
    "goodput_start_ts": 1000.0,
    "goodput_phase": "training",
}


def _compose(tracker, **kw):
    kw.setdefault("step", 100)
    kw.setdefault("pid", 4242)
    kw.setdefault("goodput_fields", dict(GP))
    kw.setdefault("resource", (50.0, 4096))
    kw.setdefault("host", "host-a")
    return tracker.compose(time.time(), **kw)


def test_first_report_is_full_then_deltas_shrink():
    tracker = DeltaTracker(incarnation=1)
    first = _compose(tracker)
    assert first.full and first.has_step and first.has_goodput
    assert first.has_resource and first.host == "host-a"
    tracker.commit(first)

    unchanged = _compose(tracker)
    assert not unchanged.full
    assert not unchanged.has_step        # step did not advance
    assert not unchanged.has_goodput     # phases within min delta
    assert not unchanged.has_resource    # cpu/mem within thresholds
    assert unchanged.host == ""          # host rides only with goodput
    assert unchanged.seq == first.seq + 1


def test_sections_reappear_exactly_when_changed():
    tracker = DeltaTracker(incarnation=1)
    tracker.commit(_compose(tracker))

    stepped = _compose(tracker, step=101)
    assert stepped.has_step and stepped.step == 101
    assert not stepped.has_goodput and not stepped.has_resource

    gp = dict(GP)
    gp["goodput_phases"] = {"init": 45.0, "training": 125.0}
    moved = _compose(tracker, goodput_fields=gp)
    assert moved.has_goodput and moved.host == "host-a"

    hot = _compose(tracker,
                   resource=(50.0 + CPU_MIN_DELTA_PCT, 4096))
    assert hot.has_resource
    fat = _compose(tracker,
                   resource=(50.0, 4096 + MEM_MIN_DELTA_MB))
    assert fat.has_resource


def test_shed_report_never_advances_the_baseline():
    """A composed-but-unacked report (load shed) keeps the baseline:
    the delta is carried again until an ack commits it."""
    tracker = DeltaTracker(incarnation=1)
    tracker.commit(_compose(tracker))
    shed = _compose(tracker, step=105)
    assert shed.has_step
    # no commit — the master never applied it
    retry = _compose(tracker, step=105)
    assert retry.has_step and retry.step == 105
    tracker.commit(retry)
    assert not _compose(tracker, step=105).has_step


def test_max_skip_bounds_section_staleness():
    tracker = DeltaTracker(incarnation=1, max_skip=3)
    tracker.commit(_compose(tracker))
    reports = [_compose(tracker) for _ in range(3)]
    assert not any(r.has_goodput for r in reports[:-1])
    assert reports[-1].has_goodput  # forced refresh on the Nth skip
    assert not any(r.has_resource for r in reports[:-1])
    assert reports[-1].has_resource


def test_request_full_resends_everything():
    tracker = DeltaTracker(incarnation=1)
    tracker.commit(_compose(tracker))
    tracker.request_full()
    full = _compose(tracker)
    assert full.full and full.has_step and full.has_goodput
    assert full.has_resource and full.host == "host-a"


# ------------------------------------------------------- sparse encoding


def test_sparse_wire_encoding_round_trips_and_shrinks():
    """Default-valued fields are omitted on the wire and restored by
    the decoder from the dataclass defaults — a delta report must not
    pay for the sections it is not carrying."""
    tracker = DeltaTracker(incarnation=1)
    tracker.commit(_compose(tracker))
    delta = _compose(tracker)
    delta.node_id, delta.node_type = 7, "worker"
    wire = comm.serialize(delta)
    clone = comm.deserialize(wire)
    assert clone == delta
    full = _compose(DeltaTracker(incarnation=1))
    full.node_id, full.node_type = 7, "worker"
    assert len(wire) < len(comm.serialize(full)) / 2


def test_sparse_encoding_is_type_strict():
    """True == 1 and 0 == 0.0 in Python; the encoder must not treat a
    value of a different type as "still the default" or decode would
    silently re-type the field."""
    hb = comm.HeartBeat(node_id=0, node_type="worker", timestamp=1.0)
    assert comm.deserialize(comm.serialize(hb)).node_id == 0
    rep = comm.NodeStatusReport(timestamp=1.0, step=0)
    clone = comm.deserialize(comm.serialize(rep))
    assert clone.step == 0 and type(clone.step) is int


# ------------------------------------------------- servicer delta logic


def _servicer(agents=4):
    speed = SpeedMonitor()
    jm = DistributedJobManager(speed_monitor=speed,
                               heartbeat_timeout=3600.0)
    jm._node_managers[NodeType.WORKER].update_nodes({
        i: Node(NodeType.WORKER, i, status=NodeStatus.RUNNING)
        for i in range(agents)
    })
    return MasterServicer(job_manager=jm, speed_monitor=speed), jm


def _report(tracker, node_id, **kw):
    rep = _compose(tracker, **kw)
    rep.node_id, rep.node_type = node_id, NodeType.WORKER
    return rep


def test_delta_report_lands_heartbeat_step_and_resource():
    sv, jm = _servicer()
    tracker = DeltaTracker(incarnation=0)
    ack = sv.handle("report_node_status", _report(tracker, 1, step=77))
    assert ack.accepted and ack.acked_seq == 1
    assert not ack.resync  # full=True needs no resync
    node = jm._node_managers[NodeType.WORKER].nodes[1]
    assert node.heartbeat_time > 0
    assert sv._speed_monitor._global_step == 77


def test_unknown_reporter_and_new_incarnation_force_resync():
    sv, _ = _servicer()
    tracker = DeltaTracker(incarnation=0)
    tracker.commit(_compose(tracker))  # baseline the master never saw
    delta = _report(tracker, 2, step=101)
    assert not delta.full
    ack = sv.handle("report_node_status", delta)
    assert ack.accepted and ack.resync

    # the master now knows incarnation 0; a NON-full report claiming
    # incarnation 1 (agent restarted) must resync too
    reborn = DeltaTracker(incarnation=1)
    reborn.commit(_compose(reborn))
    ack = sv.handle("report_node_status", _report(reborn, 2, step=102))
    assert ack.accepted and ack.resync
    # ...and once a full report lands, deltas flow without resync
    reborn.request_full()
    ack = sv.handle("report_node_status", _report(reborn, 2, step=103))
    assert ack.accepted and not ack.resync
    ack = sv.handle("report_node_status", _report(reborn, 2, step=104))
    assert ack.accepted and not ack.resync


def test_load_shed_backpressure_then_retry_lands():
    """Over the admission limit the servicer sheds with retry_after_s
    instead of queueing into collapse; the SAME report retried after
    the backoff is applied exactly once."""
    sv, _ = _servicer()
    tracker = DeltaTracker(incarnation=0)
    rep = _report(tracker, 3, step=55)

    sv._report_inflight_limit = 0  # everything sheds
    shed_ack = sv.handle("report_node_status", rep)
    assert not shed_ack.accepted
    assert shed_ack.retry_after_s > 0
    assert (NodeType.WORKER, 3) not in sv._reporters  # nothing applied
    assert default_journal().events("control.load_shed")

    sv._report_inflight_limit = 48
    ack = sv.handle("report_node_status", rep)  # same payload, retried
    assert ack.accepted and ack.acked_seq == rep.seq
    assert sv._reporters[(NodeType.WORKER, 3)] == (0, rep.seq)


# ------------------------------------------------------- swarm smoke


def test_swarm_bench_smoke():
    """The swarm bench's tier-1 smoke tier end to end: a real gRPC
    master per phase, batched beats unary, the journal coalesces, the
    shed phase actually sheds, and NO agent's last-acked seq diverges
    from the master's ledger — zero dropped heartbeats. --smoke also
    forces a 2-relay aggregator tier (ISSUE 16): two-hop delivery must
    hold (relay_phase_dropped == 0) with real coalesced forwarding."""
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               DLROVER_TPU_METRICS_PORT="off")
    out = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "benchmarks", "master_swarm.py"),
         "--smoke"],
        capture_output=True, text=True, timeout=180, env=env,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    result = json.loads(out.stdout.strip().splitlines()[-1])
    assert result["ok"] is True
    assert result["vs_baseline"] >= 2.0
    assert result["journal_coalesce_ratio"] >= 5.0
    assert result["shed_phase_sheds"] > 0
    assert result["dropped"] == 0
    assert result["shed_phase_dropped"] == 0
    # the relay tier (--smoke forces --relays 2)
    assert result["relays"] == 2
    assert result["relay_phase_dropped"] == 0
    assert result["relay_forwarded_batches"] > 0
    assert result["relay_forwarded_reports"] > 0
    # the fleet roll-up phase (ISSUE 17, --smoke forces --fleet):
    # quantiles materialize at the master from relay-pre-merged
    # digests — zero agent scrapes, one digest source per RELAY —
    # and the digest costs at most 2x the bare delta on the wire
    assert result["fleet_agent_scrapes"] == 0
    assert result["fleet_step_count"] > 0
    assert result["fleet_step_p99_ms"] > 0.0
    assert 0 < result["fleet_sources"] <= 2
    assert result["fleet_digests"] > 0
    assert result["fleet_digest_ratio"] <= 2.0
    # the job axis (ISSUE 19, --smoke forces --jobs 2): every job
    # namespace materializes ITS OWN quantiles from the same relay
    # pre-merge, still with zero per-agent scrapes
    assert result["fleet_jobs"] == 2
    assert set(result["fleet_job_step_counts"]) == {"job-0", "job-1"}
    assert all(
        c > 0 for c in result["fleet_job_step_counts"].values()
    )
    assert all(
        p > 0.0 for p in result["fleet_job_step_p99_ms"].values()
    )
