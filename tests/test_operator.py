"""ElasticJob operator reconcile-loop tests (L0/G1 parity:
elasticjob_controller.go Reconcile)."""

import pytest

from dlrover_tpu.scheduler.operator import (
    ElasticJobOperator,
    JobPhase,
    MasterHandle,
)

SPEC = {
    "apiVersion": "dlrover-tpu/v1",
    "kind": "ElasticTpuJob",
    "metadata": {"name": "llama-pretrain"},
    "spec": {
        "distributionStrategy": "allreduce",
        "worker": {"replicas": 2, "minReplicas": 1},
    },
}


class FakeMaster(MasterHandle):
    """Scriptable master: .exit(rc) simulates the process dying."""

    launched = []

    def __init__(self):
        self._rc = None
        self.terminated = False
        FakeMaster.launched.append(self)

    def poll(self):
        return self._rc

    def exit(self, rc):
        self._rc = rc

    def terminate(self):
        self.terminated = True
        self._rc = -15


@pytest.fixture(autouse=True)
def _clear():
    FakeMaster.launched = []


def _operator(max_restarts=2):
    return ElasticJobOperator(
        master_launcher=lambda spec, name, extra_args=None: FakeMaster(),
        master_max_restarts=max_restarts,
    )


def test_submit_launches_master_and_runs_to_success():
    op = _operator()
    name = op.submit(SPEC)
    assert name == "llama-pretrain"
    assert op.phase(name) == JobPhase.PENDING
    op.reconcile_once()
    assert op.phase(name) == JobPhase.RUNNING
    assert len(FakeMaster.launched) == 1
    FakeMaster.launched[0].exit(0)
    op.reconcile_once()
    assert op.phase(name) == JobPhase.SUCCEEDED


def test_master_crash_relaunches_up_to_budget():
    op = _operator(max_restarts=2)
    name = op.submit(SPEC)
    op.reconcile_once()
    for expected_total in (2, 3):  # two relaunches allowed
        FakeMaster.launched[-1].exit(1)
        op.reconcile_once()
        assert op.phase(name) == JobPhase.RUNNING
        assert len(FakeMaster.launched) == expected_total
    FakeMaster.launched[-1].exit(1)
    op.reconcile_once()
    assert op.phase(name) == JobPhase.FAILED
    assert "budget exhausted" in op.status()[name]["message"]


def test_suspend_resume_cycle():
    op = _operator()
    name = op.submit(SPEC)
    op.reconcile_once()
    op.suspend(name)
    assert op.phase(name) == JobPhase.SUSPENDED
    assert FakeMaster.launched[0].terminated
    op.reconcile_once()  # suspended jobs are left alone
    assert len(FakeMaster.launched) == 1
    op.resume(name)
    op.reconcile_once()
    assert op.phase(name) == JobPhase.RUNNING
    assert len(FakeMaster.launched) == 2


def test_delete_tears_down_master():
    op = _operator()
    name = op.submit(SPEC)
    op.reconcile_once()
    op.delete(name)
    assert op.phase(name) == JobPhase.DELETED
    assert FakeMaster.launched[0].terminated


def test_duplicate_submit_rejected():
    op = _operator()
    op.submit(SPEC)
    with pytest.raises(ValueError):
        op.submit(SPEC)


def test_invalid_spec_rejected_at_submit():
    op = _operator()
    with pytest.raises(Exception):
        op.submit({"spec": {"worker": {"replicas": "not-a-number"}}})


def test_e2e_subprocess_master_standalone():
    """The default launcher runs a real dlrover_tpu.master.main process
    and the operator sees it through its lifecycle."""
    import os
    import time

    from dlrover_tpu.scheduler.operator import launch_master_subprocess

    env_spec = dict(SPEC)
    op = ElasticJobOperator(
        master_launcher=lambda spec, name, extra_args=None:
        launch_master_subprocess(
            spec, name, extra_args=["--port", "0", "--platform", "local"]
        ),
    )
    name = op.submit(env_spec, name="real-master")
    op.reconcile_once()
    assert op.phase(name) == JobPhase.RUNNING
    # give the master a moment to come up, then tear the job down
    time.sleep(2.0)
    assert op.phase(name) == JobPhase.RUNNING
    op.delete(name)
    assert op.phase(name) == JobPhase.DELETED
