"""Agent reconnect supervision: classification, backoff, re-hello.

Unit path: ConnectionSupervisor against a scripted fake client — error
classification, hook ordering (re-hello BEFORE the retried RPC), the
hard deadline, and hook-bypass recursion safety.

Wire path: a real MasterClient rides a LocalJobMaster stop/restart on
the same port without its caller seeing the outage.

Lint path: an AST check that every public MasterClient RPC (anything
calling ``self._call``) is wrapped by ``@supervised_rpc`` or explicitly
listed in ``UNSUPERVISED_RPCS`` — a new RPC added without supervision
fails the suite, not a production failover.
"""

import os
import threading
import time

import pytest

from dlrover_tpu.agent import master_client as mc_module
from dlrover_tpu.agent.master_client import (
    ConnectionSupervisor,
    MasterClient,
    MasterLostError,
    UNSUPERVISED_RPCS,
    is_connection_error,
)
from dlrover_tpu.common.constants import NodeType
from dlrover_tpu.master.local_master import LocalJobMaster

import grpc


# ----------------------------------------------------------------- unit path


class FakeClient:
    """Scripted transport: down() makes every call raise ConnectionError
    (including the supervisor's ping probe) until up() is called."""

    def __init__(self):
        self._up = True
        self.calls = []

    def down(self):
        self._up = False

    def up(self):
        self._up = True

    def call(self, method, message):
        self.calls.append(method)
        if not self._up:
            raise ConnectionError("transport down")

        class R:
            success = True

        return R()


def _supervisor(client, timeout=5.0):
    sup = ConnectionSupervisor(client, node_desc="worker-0",
                               reconnect_timeout=timeout)
    sup._backoff_cap = 0.05  # keep the probe loop tight in tests
    return sup


def test_app_error_surfaces_immediately():
    client = FakeClient()
    sup = _supervisor(client)
    attempts = []

    def fn():
        attempts.append(1)
        raise ValueError("bad dataset name")

    with pytest.raises(ValueError):
        sup.call("get_task", fn)
    assert len(attempts) == 1  # no blind retries on app errors
    assert "ping" not in client.calls  # and no reconnect probing


def test_reconnect_runs_hooks_before_retry():
    client = FakeClient()
    sup = _supervisor(client)
    order = []
    sup.add_hook("re-hello", lambda: order.append("hook"))
    state = {"failed": False}

    def fn():
        if not state["failed"]:
            state["failed"] = True
            client.down()
            # recover shortly, from another thread, like a restarted
            # master coming back while the supervisor backs off
            threading.Timer(0.15, client.up).start()
            raise ConnectionError("master gone")
        order.append("rpc")
        return "ok"

    assert sup.call("report_task_result", fn) == "ok"
    assert order == ["hook", "rpc"]  # re-hello strictly first


def test_deadline_raises_master_lost():
    client = FakeClient()
    client.down()
    sup = _supervisor(client, timeout=0.4)
    start = time.monotonic()
    with pytest.raises(MasterLostError) as err:
        sup.call("report_heartbeat",
                 lambda: client.call("report_heartbeat", None))
    assert time.monotonic() - start >= 0.3
    assert isinstance(err.value.__cause__, ConnectionError)


def test_hooks_bypass_supervision():
    """A re-hello hook calling a supervised RPC while the master is
    still flapping must fail fast inside the hook instead of recursing
    into its own reconnect loop."""
    client = FakeClient()
    sup = _supervisor(client, timeout=2.0)
    hook_errors = []

    def hook():
        # supervision bypassed inside hooks: this propagates (and is
        # swallowed by the hook runner), never recurses
        try:
            sup.call("update_node_status", lambda: 1 / 0)
        except ZeroDivisionError:
            hook_errors.append("direct")

    sup.add_hook("h", hook)
    client.down()
    threading.Timer(0.1, client.up).start()
    sup.call("get_task", lambda: client.call("get_task", None))
    assert hook_errors == ["direct"]


def test_error_classification():
    assert is_connection_error(ConnectionError())
    assert is_connection_error(OSError())
    assert not is_connection_error(ValueError())

    class FakeRpcError(grpc.RpcError):
        def __init__(self, c):
            self._c = c

        def code(self):
            return self._c

    assert is_connection_error(FakeRpcError(grpc.StatusCode.UNAVAILABLE))
    assert is_connection_error(
        FakeRpcError(grpc.StatusCode.DEADLINE_EXCEEDED)
    )
    # the generic server aborts INTERNAL on handler exceptions and
    # INVALID_ARGUMENT on wire errors: remote code talking, not outage
    assert not is_connection_error(FakeRpcError(grpc.StatusCode.INTERNAL))
    assert not is_connection_error(
        FakeRpcError(grpc.StatusCode.INVALID_ARGUMENT)
    )


# ----------------------------------------------------------------- wire path


def test_rpc_survives_master_restart_on_same_port():
    m1 = LocalJobMaster(port=0)
    m1.prepare()
    port = m1.port
    client = MasterClient(f"localhost:{port}", node_id=0,
                          node_type=NodeType.WORKER,
                          reconnect_timeout=30.0)
    client._supervisor._backoff_cap = 0.2
    rehellos = []
    client.add_reconnect_hook("mark", lambda: rehellos.append(1))
    try:
        assert client.kv_store_set("k", b"v1").success
        m1.stop()

        result = {}

        def caller():
            # issued against a DEAD master; must ride out the restart
            result["value"] = client.kv_store_get("k")

        t = threading.Thread(target=caller)
        t.start()
        time.sleep(0.3)  # let the supervisor enter its probe loop
        m2 = LocalJobMaster(port=port)
        m2.prepare()
        try:
            t.join(timeout=30)
            assert not t.is_alive()
            # the restarted LocalJobMaster has a fresh KV store — the
            # point is the CALL survived and the re-hello ran
            assert result["value"] == b""
            assert rehellos == [1]
            assert client.kv_store_set("k", b"v2").success
            assert client.kv_store_get("k") == b"v2"
        finally:
            m2.stop()
    finally:
        client.close()


# ----------------------------------------------------------------- lint path


def test_every_public_rpc_is_supervised():
    """Every public MasterClient method that performs an RPC must be
    @supervised_rpc-wrapped or deliberately listed in UNSUPERVISED_RPCS
    — adding an RPC that bypasses reconnect supervision is a test
    failure here, not a hang in production. (Enforced by dlint's
    supervised-rpc rule — tools/dlint/rules/rpc.py — this shim keeps
    the historical entry point.)"""
    from tools.dlint.core import lint_repo
    from tools.dlint.rules import SupervisedRpcRule

    res = lint_repo(rules=[SupervisedRpcRule])
    assert not res.findings, "\n".join(
        f"{f.location()}: {f.message}" for f in res.findings
    )


def test_runtime_decoration_matches_lint():
    """Belt and braces: the live class agrees with the AST view."""
    import inspect

    for name, member in inspect.getmembers(MasterClient,
                                           inspect.isfunction):
        if name.startswith("_") or name in ("close",):
            continue
        decorated = getattr(member, "_supervised_rpc", False)
        if name in UNSUPERVISED_RPCS:
            assert not decorated
        elif name in ("add_reconnect_hook", "remove_reconnect_hook"):
            assert not decorated  # local hook management, not RPCs
        else:
            assert decorated, f"{name} lost its @supervised_rpc"


def test_retry_rpc_request_is_gone():
    """The blind 10x6s retry decorator was replaced wholesale."""
    assert not hasattr(mc_module, "retry_rpc_request")
