"""M24 state store / event queue + M23 brain archive tests."""

import threading
import time

import pytest

from dlrover_tpu.brain.client import BrainClient, BrainReporter
from dlrover_tpu.master.stats.reporter import JobMeta, StatsReporter
from dlrover_tpu.master.stats.training_metrics import (
    RuntimeMetric,
    TrainingHyperParams,
)
from dlrover_tpu.util.event_queue import EventQueue
from dlrover_tpu.util.state_store import (
    FileStore,
    MemoryStore,
    build_state_store,
)


def _mutate_appender(root, key, proc_idx, count):
    """Spawn-context child for test_mutate_cross_process_atomicity
    (must be a top-level function to be picklable)."""
    from dlrover_tpu.util.state_store import FileStore

    store = FileStore(root)
    for i in range(count):
        store.mutate(
            key, lambda v: (v or []) + [[proc_idx, i]], default=[]
        )


class TestStateStore:
    def test_memory_roundtrip(self):
        s = MemoryStore()
        s.set("a/b", {"x": 1})
        assert s.get("a/b") == {"x": 1}
        assert s.get("missing", 42) == 42
        s.set("a/c", 2)
        assert s.keys("a/") == ["a/b", "a/c"]
        s.delete("a/b")
        assert s.keys("a/") == ["a/c"]

    def test_file_store_survives_reopen(self, tmp_path):
        root = str(tmp_path / "state")
        s = FileStore(root)
        s.set("brain/job/run1/runtime", [{"speed": 2.5}])
        s.set("brain/job/run2/runtime", [{"speed": 3.5}])
        # a new instance (fresh master) sees the same data
        s2 = FileStore(root)
        assert s2.get("brain/job/run1/runtime") == [{"speed": 2.5}]
        assert s2.keys("brain/job/") == [
            "brain/job/run1/runtime", "brain/job/run2/runtime",
        ]

    def test_file_store_rejects_traversal(self, tmp_path):
        s = FileStore(str(tmp_path))
        with pytest.raises(ValueError):
            s.set("../escape", 1)

    def test_mutate_cross_process_atomicity(self, tmp_path):
        """N processes appending to ONE key must not lose a single
        update: mutate() serializes read-modify-write through the
        per-key fcntl sidecar lock, which is the property the shared
        brain archive (and the master state dir) depend on when two
        masters write the same store."""
        import multiprocessing as mp

        root = str(tmp_path)
        procs_n, per_proc = 4, 25
        ctx = mp.get_context("spawn")  # spawn: no inherited lock state
        procs = [
            ctx.Process(
                target=_mutate_appender,
                args=(root, "shared/log", i, per_proc),
            )
            for i in range(procs_n)
        ]
        for p in procs:
            p.start()
        for p in procs:
            p.join(timeout=60)
            assert p.exitcode == 0
        entries = FileStore(root).get("shared/log")
        assert len(entries) == procs_n * per_proc, (
            f"lost updates: {len(entries)} != {procs_n * per_proc}"
        )
        # every (proc, seq) pair arrived exactly once
        assert len({tuple(e) for e in entries}) == procs_n * per_proc

    def test_factory_singleton_and_env(self, tmp_path, monkeypatch):
        a = build_state_store("memory")
        b = build_state_store("memory")
        assert a is b
        f = build_state_store("file", str(tmp_path / "s"))
        assert isinstance(f, FileStore)
        with pytest.raises(ValueError):
            build_state_store("mysql")


class TestEventQueue:
    def test_fifo_and_timeout(self):
        q = EventQueue(max_size=3)
        q.put(1)
        q.put(2)
        assert q.get(timeout=0.1) == 1
        assert q.get(timeout=0.1) == 2
        t0 = time.monotonic()
        assert q.get(timeout=0.1) is None
        assert time.monotonic() - t0 >= 0.1

    def test_overflow_drops_oldest(self):
        q = EventQueue(max_size=2)
        for i in range(5):
            q.put(i)
        assert q.get(timeout=0.1) == 3
        assert q.get(timeout=0.1) == 4

    def test_blocking_get_wakes_on_put(self):
        q = EventQueue()
        got = []
        t = threading.Thread(
            target=lambda: got.append(q.get(timeout=5)), daemon=True
        )
        t.start()
        time.sleep(0.05)
        q.put("evt")
        t.join(timeout=2)
        assert got == ["evt"]


class TestBrain:
    def _meta(self, uuid, name="llama-job"):
        return JobMeta(uuid=uuid, name=name, user="ci")

    def test_archive_and_optimize_across_runs(self, tmp_path):
        store = FileStore(str(tmp_path / "brain"))
        client = BrainClient(store)
        # run 1: 4 workers, slow; run 2: 8 workers, faster
        for uuid, workers, speed in [
            ("run1", 4, 1.5), ("run2", 8, 2.8),
        ]:
            meta = self._meta(uuid)
            client.report_job_meta(meta)
            for step in range(5):
                client.report_runtime_stats(meta, RuntimeMetric(
                    worker_num=workers, global_step=step,
                    speed=speed, timestamp=float(step),
                ))
            client.report_exit_reason(meta, "Succeeded")
        assert client.get_job_runs("llama-job") == ["run1", "run2"]
        plan = client.get_optimization_plan("llama-job")
        assert plan is not None
        assert plan.worker_num == 8
        assert plan.source_job == "run2"
        # a fresh client over the same files (new master) agrees
        plan2 = BrainClient(
            FileStore(str(tmp_path / "brain"))
        ).get_optimization_plan("llama-job")
        assert plan2.worker_num == 8

    def test_optimizer_warm_starts_from_archive(self, tmp_path):
        """A new run of an archived job starts at the historically
        fastest worker count (bounded + node_unit aligned)."""
        from types import SimpleNamespace

        from dlrover_tpu.common.constants import NodeType
        from dlrover_tpu.master.resource.local_optimizer import (
            TPULocalOptimizer,
        )

        store = FileStore(str(tmp_path / "brain"))
        client = BrainClient(store)
        meta = self._meta("old-run", name="warm-job")
        for speed, workers in [(1.0, 2), (3.0, 6)]:
            for step in range(3):
                client.report_runtime_stats(meta, RuntimeMetric(
                    worker_num=workers, global_step=step, speed=speed,
                    timestamp=float(step),
                ))
        # configured 8; history says 6 was fastest -> shrink to 6
        args = SimpleNamespace(
            job_name="warm-job", node_num=8, min_node_num=2,
        )
        opt = TPULocalOptimizer(
            job_args=args, node_unit=2, brain_client=client,
        )
        plan = opt.init_job_resource()
        assert plan.node_group_resources[NodeType.WORKER].count == 6
        # the declared floor wins over a smaller historical best
        client2 = BrainClient(FileStore(str(tmp_path / "brain2")))
        meta2 = self._meta("tiny-run", name="floor-job")
        client2.report_runtime_stats(meta2, RuntimeMetric(
            worker_num=2, global_step=1, speed=9.0, timestamp=1.0,
        ))
        args_floor = SimpleNamespace(
            job_name="floor-job", node_num=8, min_node_num=4,
        )
        plan_f = TPULocalOptimizer(
            job_args=args_floor, node_unit=2, brain_client=client2,
        ).init_job_resource()
        assert plan_f.node_group_resources[NodeType.WORKER].count == 4
        # unknown job: config stands
        args2 = SimpleNamespace(
            job_name="never-seen", node_num=2, min_node_num=1,
        )
        opt2 = TPULocalOptimizer(
            job_args=args2, node_unit=2, brain_client=client,
        )
        plan2 = opt2.init_job_resource()
        assert plan2.node_group_resources[NodeType.WORKER].count == 2

    def test_brain_reporter_via_seam(self, tmp_path):
        """reporter='brain' plugs persistence in through the standard
        new_stats_reporter seam."""
        meta = self._meta("run-x", name="seam-job")
        rep = StatsReporter.new_stats_reporter(meta, reporter="brain")
        assert isinstance(rep, BrainReporter)
        rep.report_training_hyper_params(
            TrainingHyperParams(batch_size=8)
        )
        rep.report_runtime_stats(RuntimeMetric(
            worker_num=2, global_step=10, speed=1.0, timestamp=1.0,
        ))
        client = BrainClient()  # same default (memory) store singleton
        stats = client.get_runtime_stats("seam-job", "run-x")
        assert stats and stats[0]["worker_num"] == 2
