"""Apiserver watch streams (VERDICT r3 Missing #3 / item #5).

A scripted stub serves the k8s watch wire format — chunked JSON lines
of ADDED/MODIFIED/DELETED/BOOKMARK/ERROR events — and the tests drive
RestK8sApi.watch_pods + GkePodWatcher end to end: event mapping,
bookmark resume across a mid-stream disconnect, 410-Gone re-list, and
the headline property that reaction latency is the event's arrival,
not a poll interval. Parity: k8s_watcher.py:139-152
``watch.Watch().stream``.
"""

import json
import queue
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qsl, urlparse

import pytest

from dlrover_tpu.common.constants import (
    NodeEventType,
    NodeExitReason,
    NodeStatus,
)
from dlrover_tpu.scheduler.gke import (
    GkePodWatcher,
    RestK8sApi,
    StaleResourceVersion,
)

JOB = "jobx"


def _pod(name, phase="Running", rv="", exit_code=None, reason=None):
    status = {"phase": phase}
    if exit_code is not None:
        status["containerStatuses"] = [{
            "state": {"terminated": {
                "exitCode": exit_code, "reason": reason or "",
            }},
        }]
    node_id = name.rsplit("-", 1)[-1]
    return {
        "metadata": {
            "name": name,
            "labels": {
                "dlrover-job": JOB,
                "dlrover-id": node_id,
                "dlrover-type": "worker",
                "dlrover-rank": node_id,
            },
            **({"resourceVersion": rv} if rv else {}),
        },
        "status": status,
    }


class WatchStub(BaseHTTPRequestHandler):
    """Scripted apiserver: ``server.lists`` are popped per LIST call;
    ``server.watches`` are popped per WATCH call — each a list of event
    dicts streamed as JSON lines (then the connection closes, which is
    exactly a server-side watch timeout/disconnect)."""

    protocol_version = "HTTP/1.1"

    def do_GET(self):
        q = dict(parse_qsl(urlparse(self.path).query))
        self.server.requests.append(q)
        if q.get("watch") == "1":
            events = (
                self.server.watches.pop(0)
                if self.server.watches else []
            )
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()
            for ev in events:
                if ev == "hang":
                    # keep the stream open briefly with no events
                    time.sleep(0.2)
                    continue
                line = json.dumps(ev).encode() + b"\n"
                chunk = f"{len(line):x}\r\n".encode() + line + b"\r\n"
                try:
                    self.wfile.write(chunk)
                    self.wfile.flush()
                except OSError:
                    return
            try:
                self.wfile.write(b"0\r\n\r\n")
            except OSError:
                pass
            return
        body = (
            self.server.lists.pop(0)
            if self.server.lists
            else {"items": [], "metadata": {"resourceVersion": "0"}}
        )
        data = json.dumps(body).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def log_message(self, *a):
        pass


@pytest.fixture()
def stub():
    server = ThreadingHTTPServer(("127.0.0.1", 0), WatchStub)
    server.requests = []
    server.lists = []
    server.watches = []
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    try:
        yield server
    finally:
        server.shutdown()
        server.server_close()


def _api(server) -> RestK8sApi:
    return RestK8sApi(
        namespace="ns", job_name=JOB,
        base_url=f"http://127.0.0.1:{server.server_address[1]}",
        token_provider=None,
    )


def test_watch_pods_yields_typed_events_and_bookmarks(stub):
    stub.watches.append([
        {"type": "ADDED", "object": _pod(f"{JOB}-worker-0", rv="11")},
        {"type": "BOOKMARK", "object": {
            "metadata": {"resourceVersion": "15"},
        }},
        {"type": "MODIFIED", "object": _pod(
            f"{JOB}-worker-0", phase="Failed", rv="16",
            exit_code=137, reason="OOMKilled",
        )},
        {"type": "DELETED", "object": _pod(
            f"{JOB}-worker-0", phase="Failed", rv="17",
        )},
    ])
    got = list(_api(stub).watch_pods("10", timeout_seconds=5))
    kinds = [k for k, _ in got]
    assert kinds == ["ADDED", "BOOKMARK", "MODIFIED", "DELETED"]
    assert got[1][1] == "15"
    assert got[2][1]["exit_code"] == 137
    assert got[2][1]["resource_version"] == "16"
    # the request carried watch + bookmark + selector params
    q = stub.requests[0]
    assert q["watch"] == "1" and q["resourceVersion"] == "10"
    assert q["labelSelector"] == f"dlrover-job={JOB}"


def test_watch_pods_raises_on_410_gone(stub):
    stub.watches.append([
        {"type": "ERROR", "object": {
            "code": 410, "message": "too old resource version",
        }},
    ])
    with pytest.raises(StaleResourceVersion):
        list(_api(stub).watch_pods("1", timeout_seconds=5))


def _collect(watcher, n, timeout=20.0):
    out: "queue.Queue" = queue.Queue()

    def run():
        for ev in watcher.watch():
            out.put(ev)

    threading.Thread(target=run, daemon=True).start()
    got = []
    deadline = time.time() + timeout
    while len(got) < n and time.time() < deadline:
        try:
            got.append(out.get(timeout=0.5))
        except queue.Empty:
            continue
    return got


def test_watcher_streams_events_and_resumes_after_disconnect(stub):
    """list -> watch; the stream drops mid-way; the watcher re-lists
    and resumes from the advanced bookmark without losing the
    transition that happened during the gap."""
    stub.lists.append({
        "items": [_pod(f"{JOB}-worker-0", rv="5")],
        "metadata": {"resourceVersion": "5"},
    })
    # first watch: one healthy event, then the server drops the stream
    stub.watches.append([
        {"type": "MODIFIED", "object": _pod(
            f"{JOB}-worker-1", phase="Running", rv="8",
        )},
    ])
    # the re-list reflects a failure that happened during the gap
    stub.lists.append({
        "items": [
            _pod(f"{JOB}-worker-0", rv="5"),
            _pod(f"{JOB}-worker-1", phase="Failed", rv="9",
                 exit_code=137, reason="OOMKilled"),
        ],
        "metadata": {"resourceVersion": "9"},
    })
    stub.watches.append(["hang"])

    watcher = GkePodWatcher(JOB, _api(stub), watch_timeout=5)
    events = _collect(watcher, 3)
    watcher.stop()
    assert len(events) >= 3
    # initial list: worker-0 running
    assert events[0].node.name == f"{JOB}-worker-0"
    # stream: worker-1 appears
    assert events[1].node.name == f"{JOB}-worker-1"
    assert events[1].node.status == NodeStatus.RUNNING
    # after the drop, the re-list diff surfaces the missed OOM failure
    assert events[2].node.name == f"{JOB}-worker-1"
    assert events[2].node.exit_reason == NodeExitReason.OOM
    # the second watch resumed with the re-listed version (the watch
    # request is issued when the consumer pulls the next event — give
    # the generator thread a beat)
    deadline = time.time() + 5
    watch_reqs = []
    while time.time() < deadline:
        watch_reqs = [
            r for r in stub.requests if r.get("watch") == "1"
        ]
        if len(watch_reqs) >= 2:
            break
        time.sleep(0.05)
    assert len(watch_reqs) >= 2
    assert watch_reqs[1]["resourceVersion"] == "9"


def test_watcher_recovers_from_stale_bookmark(stub):
    stub.lists.append({
        "items": [_pod(f"{JOB}-worker-0", rv="5")],
        "metadata": {"resourceVersion": "5"},
    })
    stub.watches.append([
        {"type": "ERROR", "object": {"code": 410, "message": "gone"}},
    ])
    stub.lists.append({
        "items": [_pod(f"{JOB}-worker-0", phase="Succeeded", rv="30")],
        "metadata": {"resourceVersion": "30"},
    })
    stub.watches.append(["hang"])
    watcher = GkePodWatcher(JOB, _api(stub), watch_timeout=5)
    events = _collect(watcher, 2)
    watcher.stop()
    assert events[0].node.status == NodeStatus.RUNNING
    assert events[1].node.status == NodeStatus.SUCCEEDED


def test_reaction_latency_is_event_arrival_not_poll_interval(stub):
    """The whole point: with a 1000s poll interval the event still
    lands in well under a second of its emission."""
    stub.lists.append({
        "items": [], "metadata": {"resourceVersion": "1"},
    })
    stub.watches.append([
        {"type": "ADDED", "object": _pod(f"{JOB}-worker-0", rv="2")},
        "hang",
    ])
    watcher = GkePodWatcher(
        JOB, _api(stub), poll_interval=1000.0, watch_timeout=5
    )
    t0 = time.time()
    events = _collect(watcher, 1, timeout=10.0)
    elapsed = time.time() - t0
    watcher.stop()
    assert events and events[0].event_type == NodeEventType.MODIFIED
    assert elapsed < 5.0, elapsed


def test_deleted_event_maps_to_deleted_node(stub):
    stub.lists.append({
        "items": [_pod(f"{JOB}-worker-3", rv="5")],
        "metadata": {"resourceVersion": "5"},
    })
    stub.watches.append([
        {"type": "DELETED", "object": _pod(
            f"{JOB}-worker-3", phase="Running", rv="6",
        )},
        "hang",
    ])
    watcher = GkePodWatcher(JOB, _api(stub), watch_timeout=5)
    events = _collect(watcher, 2)
    watcher.stop()
    assert events[1].event_type == NodeEventType.DELETED
    assert events[1].node.status == NodeStatus.DELETED


def test_physical_host_captured_for_blacklist(stub):
    """Review fix: node events key on spec.nodeName (the physical
    host), which _to_record must surface — pod names embed the job
    name and can never repeat across jobs."""
    body = _pod(f"{JOB}-worker-0", rv="5")
    body["spec"] = {"nodeName": "gke-node-abc"}
    body["status"]["hostIP"] = "10.0.0.7"
    rec = RestK8sApi._to_record(body)
    assert rec["host_name"] == "gke-node-abc"
    assert rec["host_ip"] == "10.0.0.7"
    from dlrover_tpu.scheduler.gke import pod_to_node

    node = pod_to_node(rec)
    assert node.host_name == "gke-node-abc"


def test_transient_list_failure_does_not_mass_delete(stub):
    """Review fix: a failed list (empty version) must not be diffed
    against known state — that would read as the fleet being deleted."""
    watcher = GkePodWatcher(
        JOB, _api(stub), poll_interval=0.05, watch_timeout=5
    )
    watcher._last = {f"{JOB}-worker-0": "Running//"}
    # simulate the api failing the list
    watcher._api.list_pods_with_version = lambda: ([], "")
    gen = watcher._watch_stream()
    collected = []

    def run():
        for ev in gen:
            collected.append(ev)

    t = threading.Thread(target=run, daemon=True)
    t.start()
    time.sleep(0.5)
    watcher.stop()
    assert collected == []  # no phantom DELETED events
    assert watcher._last  # baseline preserved
