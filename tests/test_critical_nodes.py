"""Critical-node marking + fast job-fail (M6 parity:
training_node.py:40-104 + the job-failure path)."""

from types import SimpleNamespace

from dlrover_tpu.common.constants import (
    NodeEventType,
    NodeExitReason,
    NodeStatus,
    NodeType,
)
from dlrover_tpu.common.node import Node, NodeResource
from dlrover_tpu.master.node.dist_job_manager import (
    DistributedJobManager,
)
from dlrover_tpu.master.scaler.base_scaler import ScalePlan, Scaler
from dlrover_tpu.scheduler.job_spec import (
    JobArgs,
    parse_critical_worker_index,
)


class RecordingScaler(Scaler):
    def __init__(self):
        self.plans = []

    def start(self):
        pass

    def stop(self):
        pass

    def scale(self, plan: ScalePlan):
        self.plans.append(plan)


def test_parse_critical_worker_index():
    assert parse_critical_worker_index("default", 3, 4) == {0: 3}
    assert parse_critical_worker_index("all", 2, 3) == {
        0: 2, 1: 2, 2: 2,
    }
    assert parse_critical_worker_index("none", 3, 4) == {}
    assert parse_critical_worker_index("0:1,2:5", 3, 4) == {0: 1, 2: 5}
    assert parse_critical_worker_index("1", 3, 4) == {1: 3}


def test_spec_parses_critical_index():
    args = JobArgs.from_dict({
        "spec": {"worker": {
            "replicas": 4, "maxRelaunchCount": 2,
            "criticalWorkerIndex": "0:1",
        }},
    })
    assert args.critical_worker_index == {0: 1}
    # default: rank 0 critical with the full budget
    args2 = JobArgs.from_dict({"spec": {"worker": {"replicas": 2}}})
    assert args2.critical_worker_index == {0: 3}


def _manager(critical_index):
    scaler = RecordingScaler()
    args = SimpleNamespace(
        node_num=2, node_resource=NodeResource(),
        max_relaunch_count=1, relaunch_always=False,
        critical_worker_index=critical_index,
    )
    mgr = DistributedJobManager(job_args=args, scaler=scaler)
    # start() without threads: do the scale-up part inline
    nodes = mgr._node_managers[NodeType.WORKER].scale_up_nodes(
        2, NodeResource(), max_relaunch_count=1,
    )
    mgr._mark_critical_nodes(nodes)
    return mgr, scaler, nodes


def _fail_node(mgr, node, reason=NodeExitReason.FATAL_ERROR):
    from dlrover_tpu.master.watcher.base_watcher import NodeEvent

    failed = Node(node.type, node.id, status=NodeStatus.FAILED,
                  name=node.name)
    failed.exit_reason = reason
    # drive through the status flow: INITIAL -> RUNNING -> FAILED
    mgr.process_event(NodeEvent(
        NodeEventType.MODIFIED,
        Node(node.type, node.id, status=NodeStatus.RUNNING,
             name=node.name),
    ))
    mgr.process_event(NodeEvent(NodeEventType.MODIFIED, failed))


def test_critical_node_fatal_error_fails_job():
    mgr, _, nodes = _manager({0: 1})
    assert nodes[0].critical and not nodes[1].critical
    _fail_node(mgr, nodes[0], NodeExitReason.FATAL_ERROR)
    assert mgr.is_job_failed()
    assert "critical" in mgr.failed_reason


def test_non_critical_node_loss_does_not_fail_job():
    mgr, _, nodes = _manager({0: 1})
    _fail_node(mgr, nodes[1], NodeExitReason.FATAL_ERROR)
    assert not mgr.is_job_failed()


def test_critical_node_relaunchable_failure_relaunches_not_fails():
    """A recoverable failure of a critical node relaunches it (with
    criticality carried to the replacement), job keeps running."""
    mgr, scaler, nodes = _manager({0: 1})
    _fail_node(mgr, nodes[0], NodeExitReason.KILLED)
    assert not mgr.is_job_failed()
    launched = [
        n for p in scaler.plans for n in p.launch_nodes
        if n.rank_index == 0
    ]
    assert launched and launched[-1].critical
    # the replacement's permanent loss now fails the job
    _fail_node(mgr, launched[-1], NodeExitReason.FATAL_ERROR)
    assert mgr.is_job_failed()


def test_parse_critical_worker_index_yaml_booleans():
    assert parse_critical_worker_index(False, 3, 4) == {}
    assert parse_critical_worker_index(True, 3, 4) == {0: 3}
