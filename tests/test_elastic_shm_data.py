"""The shm data plane wired into the flagship trainer (VERDICT #9):
master-coordinated shards -> coworker producers -> C++ ring ->
DevicePrefetch -> ShardedTrainer."""

import numpy as np
import pytest

import jax
import optax

from dlrover_tpu.data.elastic_shm import ElasticShmDataLoader
from dlrover_tpu.master.local_master import LocalJobMaster
from dlrover_tpu.models import llama
from dlrover_tpu.parallel.mesh import create_mesh
from dlrover_tpu.trainer.sharded import make_trainer_for_llama


@pytest.fixture()
def master():
    m = LocalJobMaster(port=0)
    m.prepare()
    yield m
    m.stop()


class MarkerBatchFn:
    """Picklable batch_fn whose output encodes the shard range, so the
    consumer can verify exactly-once coverage."""

    def __init__(self, seq_len=16, vocab=128):
        self.seq_len = seq_len
        self.vocab = vocab

    def __call__(self, start, end):
        idx = np.arange(start, end, dtype=np.int32)
        tokens = (
            idx[:, None] + np.arange(self.seq_len, dtype=np.int32)
        ) % self.vocab
        return idx, tokens


def test_elastic_shm_covers_dataset_exactly_once(master):
    n, batch = 64, 8
    loader = ElasticShmDataLoader(
        MarkerBatchFn(),
        dataset_name="cov",
        batch_size=batch,
        dataset_size=n,
        num_epochs=1,
        num_workers=2,
        master_addr=master.addr,
        slot_bytes=1 << 20,
        sharding=None,
    )
    seen = []
    for idx, tokens in loader:
        seen.extend(np.asarray(idx).tolist())
        # batch content derives from the shard range
        assert tokens.shape == (len(np.asarray(idx)), 16)
    loader.shutdown()
    # both coworkers pulled disjoint shards covering every sample once
    assert sorted(seen) == list(range(n))


class TokenBatchFn:
    """Module-level (spawn-picklable) synthetic token producer."""

    def __call__(self, start, end):
        rng = np.random.default_rng(start)
        t = rng.integers(0, 128, (end - start, 16), dtype=np.int32)
        return t, t


def test_llama_trains_from_shm_ring(master):
    """Llama + ShardedTrainer consuming ring batches end-to-end: the
    done-criterion workload of VERDICT #9 in-process."""
    cfg = llama.llama_tiny()
    mesh = create_mesh([("data", 1), ("fsdp", 8)])
    trainer = make_trainer_for_llama(
        cfg, mesh, strategy="fsdp", optimizer=optax.adamw(1e-3),
    )
    params, opt_state = trainer.init(jax.random.key(0))

    n, batch = 32, 8
    loader = ElasticShmDataLoader(
        TokenBatchFn(),
        dataset_name="llama-shm",
        batch_size=batch,
        dataset_size=n,
        num_epochs=1,
        num_workers=2,
        master_addr=master.addr,
        slot_bytes=1 << 20,
        sharding=trainer.batch_sharding,
    )
    steps = 0
    for batch_data in loader:
        mb = jax.tree.map(lambda x: x[None], batch_data)
        params, opt_state, loss = trainer.train_step(
            params, opt_state, mb
        )
        assert np.isfinite(float(loss))
        steps += 1
    loader.shutdown()
    assert steps == n // batch
