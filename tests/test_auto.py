"""auto_accelerate strategy search tests (8-device CPU mesh).

Parity coverage for the reference's auto_accelerate/engine tests
(atorch/atorch/tests/auto_accelerate_test.py)."""

import os
import tempfile

import jax
import numpy as np
import pytest

# tier-1 budget (ISSUE 2 satellite): this module costs >50s of the
# 870s budget on a 1-core box; the nightly/full shard still runs it
pytestmark = pytest.mark.slow

from dlrover_tpu.auto.accelerate import (
    adjust_strategy,
    auto_accelerate,
    build_trainer,
)
from dlrover_tpu.auto.analyser import (
    ModelProfile,
    estimate_memory,
    estimate_step_time,
)
from dlrover_tpu.auto.strategy import (
    Strategy,
    enumerate_strategies,
    load_strategy,
    save_strategy,
)
from dlrover_tpu.models import llama


def test_strategy_roundtrip():
    s = Strategy(
        mesh_spec=(("data", 2), ("fsdp", 2), ("tensor", 2)),
        sharding="tp_fsdp", remat="minimal", accum_steps=4,
    )
    s2 = Strategy.from_json(s.to_json())
    assert s2 == s
    with tempfile.TemporaryDirectory() as tmp:
        p = os.path.join(tmp, "s.json")
        save_strategy(s, p)
        assert load_strategy(p) == s


def test_enumerate_covers_all_factorizations():
    cands = enumerate_strategies(8, global_batch=8)
    assert all(c.num_devices == 8 for c in cands)
    names = {c.sharding for c in cands}
    assert {"ddp", "fsdp", "tp", "tp_fsdp"} <= names
    # MoE adds expert-axis candidates
    moe = enumerate_strategies(8, 8, num_experts=4)
    assert any(c.axis("expert") > 1 for c in moe)


def test_memory_model_orders_strategies_sanely():
    cfg = llama.llama2_7b()
    profile = ModelProfile.from_llama(cfg, 2048)
    ddp = Strategy(mesh_spec=(("data", 8),), sharding="ddp")
    fsdp = Strategy(mesh_spec=(("fsdp", 8),), sharding="fsdp")
    m_ddp = estimate_memory(profile, ddp, 8, 2048)
    m_fsdp = estimate_memory(profile, fsdp, 8, 2048)
    # ZeRO-3 shards params 8 ways; DDP replicates
    assert m_fsdp.params_bytes * 7 < m_ddp.params_bytes
    # 7B replicated + adam cannot fit a 16GB chip; sharded 8-way can
    assert m_ddp.total > 16e9
    assert m_fsdp.total < m_ddp.total


def test_time_model_prefers_parallelism():
    cfg = llama.llama2_7b()
    profile = ModelProfile.from_llama(cfg, 2048)
    one = Strategy(mesh_spec=(("data", 1),), sharding="ddp")
    eight = Strategy(mesh_spec=(("fsdp", 8),), sharding="fsdp")
    t1 = estimate_step_time(profile, one, 8, 2048)
    t8 = estimate_step_time(profile, eight, 8, 2048)
    assert t8 < t1


def test_auto_accelerate_end_to_end_cpu():
    cfg = llama.llama_tiny()
    result = auto_accelerate(
        cfg, global_batch=8, seq_len=32, hbm_bytes=16e9,
    )
    assert result.strategy.num_devices == 8
    params, opt_state = result.trainer.init(jax.random.key(0))
    tokens = np.random.randint(0, cfg.vocab_size, (8, 32),
                               dtype=np.int32)
    batch = result.trainer.shard_batch(
        result.trainer.microbatch((tokens, tokens))
    )
    _, _, loss = result.trainer.train_step(params, opt_state, batch)
    assert np.isfinite(float(loss))


def test_auto_accelerate_dryrun_measures():
    cfg = llama.llama_tiny()
    result = auto_accelerate(
        cfg, global_batch=8, seq_len=32, hbm_bytes=16e9, dryrun_top_k=2,
    )
    measured = [
        r for r in result.reports if r.measured_step_seconds is not None
    ]
    assert measured, "dryrun produced no measurements"


def test_saved_strategy_adjusts_to_cluster():
    """Elastic reuse: a strategy saved on 16 devices refits to 8 by
    shrinking the data dim, keeping model-parallel dims."""
    s16 = Strategy(
        mesh_spec=(("data", 4), ("fsdp", 2), ("tensor", 2)),
        sharding="tp_fsdp",
    )
    s8 = adjust_strategy(s16, 8, global_batch=8)
    assert s8.axis("data") == 2
    assert s8.axis("fsdp") == 2 and s8.axis("tensor") == 2
    with pytest.raises(ValueError):
        adjust_strategy(s16, 6, 8)  # 6 % 4 != 0


def test_load_strategy_path_fast_path():
    cfg = llama.llama_tiny()
    s = Strategy(
        mesh_spec=(("data", 2), ("fsdp", 4)), sharding="fsdp",
    )
    with tempfile.TemporaryDirectory() as tmp:
        p = os.path.join(tmp, "s.json")
        save_strategy(s, p)
        result = auto_accelerate(
            cfg, global_batch=8, seq_len=32, load_strategy_path=p,
        )
    assert result.strategy.axis("fsdp") == 4
    assert result.trainer is not None


def test_enumerate_includes_zero_variants():
    cands = enumerate_strategies(8, global_batch=8)
    names = {c.sharding for c in cands}
    assert {"zero1", "zero2"} <= names
    z = next(c for c in cands if c.sharding == "zero1")
    assert z.axis("fsdp") > 1  # zero needs a shard axis


def test_zero_memory_between_ddp_and_fsdp():
    """ZeRO-1 keeps params replicated but shards Adam state; its
    footprint must land strictly between DDP and full FSDP, and ZeRO-2
    at or below ZeRO-1 (sharded grads)."""
    cfg = llama.llama2_7b()
    profile = ModelProfile.from_llama(cfg, 2048)
    mesh = (("data", 1), ("fsdp", 8))
    mems = {
        name: estimate_memory(
            profile,
            Strategy(mesh_spec=mesh, sharding=name), 8, 2048,
        ).total
        for name in ("ddp", "zero1", "zero2", "fsdp")
    }
    assert mems["fsdp"] < mems["zero2"] <= mems["zero1"] < mems["ddp"]


def test_time_model_remat_ordering():
    """Recompute costs FLOPs: minimal > dots > off at fixed layout."""
    cfg = llama.llama2_7b()
    profile = ModelProfile.from_llama(cfg, 2048)
    times = [
        estimate_step_time(
            profile,
            Strategy(mesh_spec=(("fsdp", 8),), sharding="fsdp",
                     remat=remat),
            8, 2048,
        )
        for remat in ("off", "dots", "minimal")
    ]
    assert times[0] < times[1] < times[2]


def test_analyser_ordering_matches_compiled_flops():
    """VERDICT #7(a): the analytic ranking must agree with the REAL
    program on the cost dimension that survives the TPU->CPU constant
    swap — remat recompute FLOPs. VERDICT r2 Weak #1 history: wall-clock
    dryruns flaked under CI load even as 3-run medians with a 5% rank
    band, so the measured side is now XLA's own flop count of the
    compiled step (deterministic, and exactly what rematerialization
    changes). Wall-clock refinement is covered by
    test_auto_accelerate_bo_path."""
    from dlrover_tpu.auto.accelerate import build_trainer

    cfg = llama.LlamaConfig(
        vocab_size=512, hidden_size=256, intermediate_size=1024,
        num_layers=6, num_heads=8, num_kv_heads=4, max_seq_len=128,
    )
    profile = ModelProfile.from_llama(cfg, 128)
    mesh = (("data", 2), ("fsdp", 4))
    cands = [
        Strategy(mesh_spec=mesh, sharding="zero1", remat="off"),
        Strategy(mesh_spec=mesh, sharding="zero1", remat="dots"),
        Strategy(mesh_spec=mesh, sharding="zero1", remat="minimal"),
    ]
    est = [estimate_step_time(profile, s, 16, 128) for s in cands]

    def compiled_flops(s):
        trainer = build_trainer(cfg, s)
        params, opt_state = trainer.init(jax.random.key(0))
        tokens = np.zeros((16, 128), np.int32)
        batch = trainer.shard_batch(
            trainer.microbatch((tokens, tokens))
        )
        compiled = trainer.train_step.lower(
            params, opt_state, batch
        ).compile()
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0]
        return float(cost.get("flops", 0.0))

    flops = [compiled_flops(s) for s in cands]
    # predicted: off < dots < minimal (REMAT_COMPUTE ordering)
    assert est[0] < est[1] < est[2]
    # the compiled programs must show the same recompute ordering
    assert flops[0] > 0
    assert flops[0] < flops[1] < flops[2], flops


def test_bo_search_finds_optimum_with_few_measurements():
    """The GP+EI loop locates the best strategy while measuring only a
    fraction of the candidate set (parity: bo_sg.py's role)."""
    from dlrover_tpu.auto.bo import bo_search

    cands = enumerate_strategies(8, global_batch=8)

    # synthetic ground truth: tensor axes hurt, minimal remat hurts,
    # fsdp helps a bit — a deterministic landscape with a unique best
    def true_time(s):
        t = 1.0
        t += 0.5 * (s.axis("tensor") - 1)
        t += 0.4 * (s.remat == "minimal")
        t -= 0.1 * (s.axis("fsdp") > 1)
        t += 0.05 * s.axis("data")
        return t

    calls = []

    def measure(s):
        calls.append(s)
        return true_time(s)

    best, measured = bo_search(
        cands, measure, n_init=3, n_iters=6,
    )
    assert len(calls) <= 9 < len(cands)
    true_best = min(cands, key=true_time)
    assert true_time(best) <= true_time(true_best) * 1.1


def test_bo_skips_failing_candidates():
    from dlrover_tpu.auto.bo import bo_search

    cands = enumerate_strategies(8, global_batch=8)[:6]

    def measure(s):
        if s.remat == "minimal":
            raise RuntimeError("compile OOM")
        return 1.0 + 0.1 * s.axis("tensor")

    best, measured = bo_search(cands, measure, n_init=2, n_iters=8)
    assert best.remat != "minimal"
    assert all(s.remat != "minimal" for s in measured)


def test_auto_accelerate_bo_path():
    cfg = llama.llama_tiny()
    result = auto_accelerate(
        cfg, global_batch=8, seq_len=32, hbm_bytes=16e9,
        dryrun_top_k=2, bo_iters=2,
    )
    measured = [
        r for r in result.reports if r.measured_step_seconds is not None
    ]
    assert len(measured) >= 2
    # the winner was actually measured, not just predicted
    assert any(r.strategy == result.strategy for r in measured)


def test_dryrun_abstract_measures_memory_without_materializing():
    """U2: the abstract (eval_shape + AOT) dryrun returns XLA's real
    memory analysis with zero arrays allocated."""
    from dlrover_tpu.auto.accelerate import dryrun_abstract

    cfg = llama.llama_tiny()
    s = Strategy(mesh_spec=(("data", 2), ("fsdp", 4)), sharding="fsdp")
    args_b, temp_b, out_b = dryrun_abstract(cfg, s, 8, 32)
    # params + opt state + batch dominate argument bytes; must be the
    # right order of magnitude for the tiny model (~0.5M params, fsdp/4)
    assert args_b > 1e4
    assert out_b > 0


def test_build_trainer_context_parallel():
    cfg = llama.llama_tiny()
    s = Strategy(
        mesh_spec=(("data", 2), ("seq", 4)), sharding="sequence",
        context_parallel="ring",
    )
    trainer = build_trainer(cfg, s)
    params, opt_state = trainer.init(jax.random.key(0))
    tokens = np.random.randint(0, cfg.vocab_size, (4, 64),
                               dtype=np.int32)
    batch = trainer.shard_batch(trainer.microbatch((tokens, tokens)))
    _, _, loss = trainer.train_step(params, opt_state, batch)
    assert np.isfinite(float(loss))


def test_auto_selects_sequence_parallel_past_envelope():
    """VERDICT r4 Weak #3: for a 16k-context flagship config the
    search must choose sequence parallelism BY ITSELF — non-SP
    candidates are gated unfit by the measured single-chip envelope
    (strategy.envelope_max_seq: 8192 was the longest measured fit on
    the 15.75 GB chip), and the SP meshes compose fsdp for params."""
    from dlrover_tpu.auto.accelerate import auto_accelerate

    cfg = llama.llama_1b()
    res = auto_accelerate(
        cfg, global_batch=8, seq_len=16384, hbm_bytes=15.75e9,
        dryrun_top_k=0,
    )
    s = res.strategy
    assert s.context_parallel == "ring"
    assert s.sharding == "sequence"
    assert s.axis("seq") >= 2
    assert s.axis("fsdp") >= 2  # replicated 1.1B + Adam cannot fit
    # the search trace shows the gate did the work: every fitting
    # candidate is SP, every non-SP flagship candidate is unfit
    fitting = [r for r in res.reports if r.fits]
    assert fitting and all(
        r.strategy.context_parallel for r in fitting
    )
    # and at the measured envelope (8k) the gate stays OUT of the way
    res8k = auto_accelerate(
        cfg, global_batch=8, seq_len=8192, hbm_bytes=15.75e9,
        dryrun_top_k=0,
    )
    assert res8k.strategy.context_parallel is None


def test_ulysses_candidates_gated_on_head_divisibility():
    """The model-blind enumeration emits ulysses variants; the search
    drops those whose Q-head count doesn't divide by the seq axis
    (ulysses_attention's hard constraint — indivisible KV broadcasts)."""
    from dlrover_tpu.auto.accelerate import auto_accelerate

    # 6 Q heads: sp=2 divides, sp=4/8 don't
    cfg = llama.llama_tiny(
        hidden_size=96, num_heads=6, num_kv_heads=3,
        max_seq_len=16384,
    )
    res = auto_accelerate(
        cfg, global_batch=8, seq_len=16384, hbm_bytes=15.75e9,
        dryrun_top_k=0,
    )
    ulysses = [
        r.strategy for r in res.reports
        if r.strategy.context_parallel == "ulysses"
    ]
    assert ulysses
    assert all(
        cfg.num_heads % s.axis("seq") == 0 for s in ulysses
    )
    assert {s.axis("seq") for s in ulysses} == {2}
    # KV indivisibility alone (3 kv heads, sp=2) does NOT gate: the
    # kernel broadcasts KV
    assert any(
        cfg.num_kv_heads % s.axis("seq") != 0 for s in ulysses
    )
