"""auto_accelerate strategy search tests (8-device CPU mesh).

Parity coverage for the reference's auto_accelerate/engine tests
(atorch/atorch/tests/auto_accelerate_test.py)."""

import os
import tempfile

import jax
import numpy as np
import pytest

from dlrover_tpu.auto.accelerate import (
    adjust_strategy,
    auto_accelerate,
    build_trainer,
)
from dlrover_tpu.auto.analyser import (
    ModelProfile,
    estimate_memory,
    estimate_step_time,
)
from dlrover_tpu.auto.strategy import (
    Strategy,
    enumerate_strategies,
    load_strategy,
    save_strategy,
)
from dlrover_tpu.models import llama


def test_strategy_roundtrip():
    s = Strategy(
        mesh_spec=(("data", 2), ("fsdp", 2), ("tensor", 2)),
        sharding="tp_fsdp", remat="minimal", accum_steps=4,
    )
    s2 = Strategy.from_json(s.to_json())
    assert s2 == s
    with tempfile.TemporaryDirectory() as tmp:
        p = os.path.join(tmp, "s.json")
        save_strategy(s, p)
        assert load_strategy(p) == s


def test_enumerate_covers_all_factorizations():
    cands = enumerate_strategies(8, global_batch=8)
    assert all(c.num_devices == 8 for c in cands)
    names = {c.sharding for c in cands}
    assert {"ddp", "fsdp", "tp", "tp_fsdp"} <= names
    # MoE adds expert-axis candidates
    moe = enumerate_strategies(8, 8, num_experts=4)
    assert any(c.axis("expert") > 1 for c in moe)


def test_memory_model_orders_strategies_sanely():
    cfg = llama.llama2_7b()
    profile = ModelProfile.from_llama(cfg, 2048)
    ddp = Strategy(mesh_spec=(("data", 8),), sharding="ddp")
    fsdp = Strategy(mesh_spec=(("fsdp", 8),), sharding="fsdp")
    m_ddp = estimate_memory(profile, ddp, 8, 2048)
    m_fsdp = estimate_memory(profile, fsdp, 8, 2048)
    # ZeRO-3 shards params 8 ways; DDP replicates
    assert m_fsdp.params_bytes * 7 < m_ddp.params_bytes
    # 7B replicated + adam cannot fit a 16GB chip; sharded 8-way can
    assert m_ddp.total > 16e9
    assert m_fsdp.total < m_ddp.total


def test_time_model_prefers_parallelism():
    cfg = llama.llama2_7b()
    profile = ModelProfile.from_llama(cfg, 2048)
    one = Strategy(mesh_spec=(("data", 1),), sharding="ddp")
    eight = Strategy(mesh_spec=(("fsdp", 8),), sharding="fsdp")
    t1 = estimate_step_time(profile, one, 8, 2048)
    t8 = estimate_step_time(profile, eight, 8, 2048)
    assert t8 < t1


def test_auto_accelerate_end_to_end_cpu():
    cfg = llama.llama_tiny()
    result = auto_accelerate(
        cfg, global_batch=8, seq_len=32, hbm_bytes=16e9,
    )
    assert result.strategy.num_devices == 8
    params, opt_state = result.trainer.init(jax.random.key(0))
    tokens = np.random.randint(0, cfg.vocab_size, (8, 32),
                               dtype=np.int32)
    batch = result.trainer.shard_batch(
        result.trainer.microbatch((tokens, tokens))
    )
    _, _, loss = result.trainer.train_step(params, opt_state, batch)
    assert np.isfinite(float(loss))


def test_auto_accelerate_dryrun_measures():
    cfg = llama.llama_tiny()
    result = auto_accelerate(
        cfg, global_batch=8, seq_len=32, hbm_bytes=16e9, dryrun_top_k=2,
    )
    measured = [
        r for r in result.reports if r.measured_step_seconds is not None
    ]
    assert measured, "dryrun produced no measurements"


def test_saved_strategy_adjusts_to_cluster():
    """Elastic reuse: a strategy saved on 16 devices refits to 8 by
    shrinking the data dim, keeping model-parallel dims."""
    s16 = Strategy(
        mesh_spec=(("data", 4), ("fsdp", 2), ("tensor", 2)),
        sharding="tp_fsdp",
    )
    s8 = adjust_strategy(s16, 8, global_batch=8)
    assert s8.axis("data") == 2
    assert s8.axis("fsdp") == 2 and s8.axis("tensor") == 2
    with pytest.raises(ValueError):
        adjust_strategy(s16, 6, 8)  # 6 % 4 != 0


def test_load_strategy_path_fast_path():
    cfg = llama.llama_tiny()
    s = Strategy(
        mesh_spec=(("data", 2), ("fsdp", 4)), sharding="fsdp",
    )
    with tempfile.TemporaryDirectory() as tmp:
        p = os.path.join(tmp, "s.json")
        save_strategy(s, p)
        result = auto_accelerate(
            cfg, global_batch=8, seq_len=32, load_strategy_path=p,
        )
    assert result.strategy.axis("fsdp") == 4
    assert result.trainer is not None


def test_build_trainer_context_parallel():
    cfg = llama.llama_tiny()
    s = Strategy(
        mesh_spec=(("data", 2), ("seq", 4)), sharding="sequence",
        context_parallel="ring",
    )
    trainer = build_trainer(cfg, s)
    params, opt_state = trainer.init(jax.random.key(0))
    tokens = np.random.randint(0, cfg.vocab_size, (4, 64),
                               dtype=np.int32)
    batch = trainer.shard_batch(trainer.microbatch((tokens, tokens)))
    _, _, loss = trainer.train_step(params, opt_state, batch)
    assert np.isfinite(float(loss))
