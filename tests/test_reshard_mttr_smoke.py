"""Tier-1 gate on the reshard-in-place MTTR claim.

Runs ``benchmarks/reshard_mttr.py --smoke`` (tiny state, one sample
per path) and holds the acceptance lines: an in-process mesh
transition must beat restart-the-world by >= 5x, live migration
(ISSUE 18: device-to-device moves for survivor-held shards) must beat
the checkpoint-tier transition by >= 2x, and the migrated state must
be exactly-once (bit-identical, zero digest mismatches). The measured
evidence at real state sizes lives in RESHARD_r08.json (the full tier
of the same script).
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_reshard_mttr_smoke():
    proc = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "benchmarks", "reshard_mttr.py"),
         "--smoke"],
        capture_output=True, text=True, timeout=300, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
    line = proc.stdout.strip().splitlines()[-1]
    res = json.loads(line)
    assert set(res) == {
        "live_migration_ms", "reshard_mttr_ms", "restart_mttr_ms",
        "speedup", "live_speedup", "live_vs_restart", "exactly_once",
    }
    assert res["exactly_once"] is True
    assert res["live_migration_ms"] > 0
    assert res["reshard_mttr_ms"] > 0
    assert res["speedup"] >= 5.0, res
    assert res["live_speedup"] >= 2.0, res
