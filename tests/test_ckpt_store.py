"""Object-store checkpoint tier + safe archive codec (VERDICT r3 #6).

The persist tier must behave like a bucket (put/get/list, COMMIT-marker
atomicity, no rename) and the archive format must be unexecutable
(npz + JSON manifest, numpy allow_pickle=False) — a spare host reading
another host's checkpoint is consuming network input.
"""

import json
import os
import pickle

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlrover_tpu.trainer import ckpt_store
from dlrover_tpu.trainer.checkpoint import FlashCheckpointer, _local_shards


def _state():
    return {
        "params": {
            "w": jnp.arange(12, dtype=jnp.float32).reshape(3, 4),
            "b": jnp.ones((4,), jnp.bfloat16),
        },
        "step": 7,
    }


def test_archive_round_trip_with_target():
    state = _state()
    snap = _local_shards(state)
    data = ckpt_store.snapshot_to_bytes(snap, step=7)
    got, step = ckpt_store.snapshot_from_bytes(data, target=state)
    assert step == 7
    restored_w = got["params"]["w"]
    assert restored_w["__jax_shards__"]
    np.testing.assert_array_equal(
        restored_w["shards"][0][1], np.asarray(state["params"]["w"])
    )
    assert got["step"] == 7


def test_archive_round_trip_without_target_nested_dicts():
    data = ckpt_store.snapshot_to_bytes(_local_shards(_state()), step=3)
    got, step = ckpt_store.snapshot_from_bytes(data)
    assert step == 3
    assert set(got) == {"params", "step"}
    assert got["params"]["b"]["dtype"] == "bfloat16"


def test_archive_rejects_pickle_and_garbage():
    with pytest.raises(ckpt_store.ArchiveError):
        ckpt_store.snapshot_from_bytes(pickle.dumps({"state": object()}))
    with pytest.raises(ckpt_store.ArchiveError):
        ckpt_store.snapshot_from_bytes(b"not a zip at all")


def test_archive_rejects_unserializable_leaf_at_save():
    with pytest.raises(ckpt_store.ArchiveError):
        ckpt_store.snapshot_to_bytes({"fn": lambda x: x}, step=0)


def test_archive_structure_mismatch_raises():
    data = ckpt_store.snapshot_to_bytes(_local_shards(_state()), step=1)
    with pytest.raises(ckpt_store.ArchiveError):
        ckpt_store.snapshot_from_bytes(
            data, target={"completely": {"different": jnp.zeros(2)}}
        )


def test_local_store_key_traversal_rejected(tmp_path):
    store = ckpt_store.LocalFsStore(str(tmp_path / "root"))
    with pytest.raises(KeyError):
        store.put("../outside", b"x")
    with pytest.raises(KeyError):
        store.get("/etc/passwd")


def test_commit_marker_gates_visibility(tmp_path):
    """A step whose data objects exist but whose COMMIT does not is
    invisible — object-store crash consistency without rename."""
    store = ckpt_store.LocalFsStore(str(tmp_path))
    store.put(ckpt_store.step_key(5, 0), b"data")  # no COMMIT
    assert ckpt_store.committed_steps(store) == []
    with pytest.raises(KeyError):
        ckpt_store.read_step(store, 5, 0)
    store.put(ckpt_store.commit_key(5), json.dumps({"step": 5}).encode())
    assert ckpt_store.committed_steps(store) == [5]
    assert ckpt_store.read_step(store, 5, 0) == b"data"


def test_gc_keeps_newest_and_deletes_commit_first(tmp_path):
    store = ckpt_store.LocalFsStore(str(tmp_path))
    for s in (1, 2, 3):
        ckpt_store.write_step(store, s, 0, b"d%d" % s)
    ckpt_store.gc_steps(store, keep=2)
    assert ckpt_store.committed_steps(store) == [2, 3]
    assert not store.list("step-1/")


def test_get_store_url_forms(tmp_path):
    assert isinstance(
        ckpt_store.get_store(str(tmp_path)), ckpt_store.LocalFsStore
    )
    s = ckpt_store.get_store(f"file://{tmp_path}/sub")
    assert isinstance(s, ckpt_store.LocalFsStore)
    assert ckpt_store.is_url("gs://b/p") and not ckpt_store.is_url("/p")


def test_flash_checkpointer_persist_tier_cross_host(tmp_path):
    """e2e: the writer persists through the store; a READER WITH A
    DIFFERENT RAM DIR (a spare host — local tmpfs empty) restores from
    the persist tier alone."""
    persist = f"file://{tmp_path}/bucket"
    writer = FlashCheckpointer(
        persist_dir=persist, ram_dir=str(tmp_path / "ram_a"),
        persist_interval=1, use_orbax=False,
    )
    state = _state()
    writer.save(4, state, force_persist=True)
    writer.wait()

    reader = FlashCheckpointer(
        persist_dir=persist, ram_dir=str(tmp_path / "ram_b"),
        persist_interval=0, use_orbax=False,
    )
    assert reader.latest_step() == 4
    restored, step = reader.restore(target=state)
    assert step == 4
    np.testing.assert_array_equal(
        np.asarray(restored["params"]["w"]),
        np.asarray(state["params"]["w"]),
    )


def test_evaluator_reads_object_store_tier(tmp_path):
    """VERDICT r3 #6 'done' criterion: evaluator e2e against the shim —
    eval host polls the shared store, never the trainer's local disk."""
    from dlrover_tpu.trainer.evaluator import CheckpointEvaluator

    persist = f"file://{tmp_path}/bucket"
    trainer_ckpt = FlashCheckpointer(
        persist_dir=persist, ram_dir=str(tmp_path / "trainer_ram"),
        persist_interval=1, use_orbax=False,
    )
    state = _state()
    trainer_ckpt.save(2, state, force_persist=True)
    trainer_ckpt.wait()

    eval_ckpt = FlashCheckpointer(
        persist_dir=persist, ram_dir=str(tmp_path / "eval_ram"),
        persist_interval=0, use_orbax=False,
    )
    seen = []

    def eval_fn(st, step):
        w = st["params"]["w"]
        # no target: leaves arrive as shard-snap dicts; assemble
        arr = w["shards"][0][1] if isinstance(w, dict) else np.asarray(w)
        return {"w_sum": float(np.sum(arr)), "step": step}

    ev = CheckpointEvaluator(
        eval_ckpt, eval_fn,
        report_fn=lambda step, res: seen.append((step, res)),
        poll_interval=0.01,
    )
    res = ev.poll_once()
    assert res is not None and res["step"] == 2
    assert seen and seen[0][0] == 2
    assert res["w_sum"] == float(np.sum(np.arange(12)))


def test_multiproc_commit_waits_for_all_shards(tmp_path):
    """Review fix: process 0 must not publish COMMIT until every
    process's shard object is visible (the store IS the barrier)."""
    store = ckpt_store.LocalFsStore(str(tmp_path))
    # proc 0 writes alone with 2 expected processes and a tiny timeout:
    # no COMMIT appears
    ckpt_store.write_step(
        store, 9, 0, b"p0", n_processes=2, commit_timeout=0.1
    )
    assert ckpt_store.committed_steps(store) == []
    # peer shard lands, proc 0 retries: COMMIT appears
    store.put(ckpt_store.step_key(9, 1), b"p1")
    ckpt_store.write_step(
        store, 9, 0, b"p0", n_processes=2, commit_timeout=1.0
    )
    assert ckpt_store.committed_steps(store) == [9]


def test_restore_falls_back_to_older_available_step(tmp_path):
    """Review fix: a committed step missing THIS process's shard must
    not shadow an older fully-restorable step."""
    persist = str(tmp_path / "bucket")
    ckpt = FlashCheckpointer(
        persist_dir=persist, ram_dir=str(tmp_path / "ram"),
        persist_interval=1, use_orbax=False,
    )
    state = _state()
    ckpt.save(2, state, force_persist=True)
    ckpt.wait()
    # forge a torn newer step: COMMIT without this proc's shard
    store = ckpt_store.get_store(persist)
    store.put(ckpt_store.commit_key(5), json.dumps({"step": 5}).encode())

    fresh = FlashCheckpointer(
        persist_dir=persist, ram_dir=str(tmp_path / "ram2"),
        persist_interval=0, use_orbax=False,
    )
    assert fresh.latest_step() == 2  # torn step invisible
    restored, step = fresh.restore(target=state)
    assert step == 2 and restored is not None
    # explicit request for the torn step does NOT silently fall back
    restored, step = fresh.restore(target=state, step=5)
    assert restored is None and step is None


def test_stale_attempt_shards_cannot_satisfy_commit_barrier(tmp_path):
    """Review fix: an orphan shard from a crashed earlier attempt at
    the SAME step must not let proc 0 commit a mixed-run step."""
    store = ckpt_store.LocalFsStore(str(tmp_path))
    # run 1: proc 1's shard landed, proc 0 died -> no COMMIT
    store.put(ckpt_store.step_key(100, 1, attempt="1"), b"old-p1")
    # run 2 (attempt 2): proc 0 writes; barrier must NOT see old-p1
    ckpt_store.write_step(
        store, 100, 0, b"new-p0", n_processes=2,
        commit_timeout=0.1, attempt="2",
    )
    assert ckpt_store.committed_steps(store) == []
    # run 2's peer lands with the matching attempt -> commit succeeds
    store.put(ckpt_store.step_key(100, 1, attempt="2"), b"new-p1")
    ckpt_store.write_step(
        store, 100, 0, b"new-p0", n_processes=2,
        commit_timeout=1.0, attempt="2",
    )
    assert ckpt_store.committed_steps(store) == [100]
    # readers get run 2's shard, not the orphan
    assert ckpt_store.read_step(store, 100, 1) == b"new-p1"


def test_gc_removes_orphaned_uncommitted_steps(tmp_path):
    """Review fix: shards of never-committed steps older than the
    newest committed step are pruned (bounded storage), while an
    in-flight newer step is untouched."""
    store = ckpt_store.LocalFsStore(str(tmp_path))
    store.put(ckpt_store.step_key(3, 1), b"orphan")  # torn old save
    ckpt_store.write_step(store, 10, 0, b"committed")
    store.put(ckpt_store.step_key(12, 0), b"in-flight")  # newer, no COMMIT
    ckpt_store.gc_steps(store, keep=3)
    assert not store.list("step-3/")          # orphan swept
    assert ckpt_store.committed_steps(store) == [10]
    assert store.list("step-12/")             # in-flight preserved


def test_corrupt_newest_step_falls_back_to_older(tmp_path):
    """Review fix: ArchiveError on the newest persist step continues
    the fallback walk instead of crashing restore."""
    persist = str(tmp_path / "bucket")
    ckpt = FlashCheckpointer(
        persist_dir=persist, ram_dir=str(tmp_path / "ram"),
        persist_interval=1, use_orbax=False,
    )
    state = _state()
    ckpt.save(2, state, force_persist=True)
    ckpt.wait()
    store = ckpt_store.get_store(persist)
    # forge a committed-but-corrupt newer step
    store.put(ckpt_store.step_key(8, 0), b"garbage not a zip")
    store.put(ckpt_store.commit_key(8), json.dumps({"step": 8}).encode())

    fresh = FlashCheckpointer(
        persist_dir=persist, ram_dir=str(tmp_path / "ram2"),
        persist_interval=0, use_orbax=False,
    )
    restored, step = fresh.restore(target=state)
    assert step == 2 and restored is not None


def test_exists_is_metadata_only(tmp_path, monkeypatch):
    """Review fix: availability checks must not download the blob."""
    store = ckpt_store.LocalFsStore(str(tmp_path))
    store.put("k", b"x" * 1000)
    monkeypatch.setattr(
        ckpt_store.LocalFsStore, "get",
        lambda self, key: (_ for _ in ()).throw(
            AssertionError("exists() downloaded the object")
        ),
    )
    assert store.exists("k") and not store.exists("missing")


def test_restore_consensus_across_processes(tmp_path, monkeypatch):
    """After elastic world changes, hosts can hold different RAM-tier
    histories; each restoring its own latest would silently mix
    training states. The checkpointer must pick the newest step EVERY
    process can restore (allgather + intersect), or none."""
    import numpy as np

    ckpt = FlashCheckpointer(
        persist_dir=str(tmp_path / "p"), ram_dir=str(tmp_path / "r"),
        persist_interval=0, use_orbax=False,
    )
    ckpt._n_processes = 3

    def fake_allgather(arr):
        # this process has {5, 140}; peers returned {5} and {5, 140}
        rows = [np.asarray(arr)]
        a = np.full_like(arr, -1)
        a[0] = 5
        rows.append(a)
        rows.append(np.asarray(arr))
        return np.stack(rows)

    import jax.experimental.multihost_utils as mhu

    monkeypatch.setattr(mhu, "process_allgather", fake_allgather)
    assert ckpt._consensus_step({5, 140}) == 5  # newest COMMON step

    def empty_peer(arr):
        rows = [np.asarray(arr), np.full_like(arr, -1)]
        rows.append(np.asarray(arr))
        return np.stack(rows)

    monkeypatch.setattr(mhu, "process_allgather", empty_peer)
    # one peer has nothing restorable: nobody restores (consistent
    # fresh start beats a silently mixed world)
    assert ckpt._consensus_step({5, 140}) is None

    # single process: plain local latest
    ckpt._n_processes = 1
    assert ckpt._consensus_step({5, 140}) == 140
    assert ckpt._consensus_step(set()) is None


def test_restore_collective_sequence_survives_store_errors(
    tmp_path, monkeypatch
):
    """ADVICE r4 (medium): a host whose candidate listing raises BEFORE
    the consensus allgather used to skip that collective while peers
    entered it — its agreement gather then paired against peers'
    consensus gather (mismatched shapes/dtypes). The fixed sequence
    runs BOTH collectives on every host no matter what fails locally:
    listing errors contribute an empty candidate set."""
    import numpy as np

    ckpt = FlashCheckpointer(
        persist_dir=str(tmp_path / "p"), ram_dir=str(tmp_path / "r"),
        persist_interval=0, use_orbax=False,
    )
    ckpt._n_processes = 2

    # the persist store is broken: every list/HEAD raises
    def boom(*a, **k):
        raise OSError("store unreachable")

    monkeypatch.setattr(ckpt_store, "available_steps", boom)

    calls = []

    def record_allgather(arr):
        arr = np.asarray(arr)
        calls.append((arr.shape, arr.dtype.name))
        return np.stack([arr, arr])  # peer mirrors this host

    import jax.experimental.multihost_utils as mhu

    monkeypatch.setattr(mhu, "process_allgather", record_allgather)

    state, step = ckpt.restore(target=None)
    assert (state, step) == (None, None)
    # the full fixed sequence ran: consensus (16,) int64 gather, then
    # the agreement (1,) int32 vote — identical to a healthy host's
    assert calls == [((16,), "int64"), ((1,), "int32")]
