"""End-to-end tests of the elastic launcher with the toy workload.

Mirrors the reference's strongest system-test trick: platform=local + real
gRPC + real subprocesses on one host
(.github/actions/dlrover-system-test-*/action.yaml).
"""

import os
import subprocess
import sys
import tempfile

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_launcher(extra_entry_args, tmp, timeout=180, max_restarts=3):
    out_file = os.path.join(tmp, "result.txt")
    ckpt_dir = os.path.join(tmp, "ckpt")
    cmd = [
        sys.executable, "-m", "dlrover_tpu.trainer.elastic_run",
        "--standalone", "--nnodes", "1:1",
        "--max_restarts", str(max_restarts),
        "--monitor_interval", "0.3",
        os.path.join(REPO, "examples", "toy_train.py"), "--",
        "--steps", "30", "--ckpt-dir", ckpt_dir, "--out", out_file,
    ] + extra_entry_args
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        cmd, cwd=REPO, env=env, timeout=timeout,
        capture_output=True, text=True,
    )
    return proc, out_file


def test_standalone_training_completes():
    with tempfile.TemporaryDirectory() as tmp:
        proc, out_file = _run_launcher([], tmp)
        assert proc.returncode == 0, proc.stderr[-2000:]
        step, loss, start = open(out_file).read().split(",")
        assert int(step) == 30
        assert float(loss) < 1.0  # actually learned
        assert int(start) == 0


def test_kill_and_resume_from_flash_checkpoint():
    """Training crashes mid-run; the agent restarts the process, which
    restores from the RAM-tier checkpoint and finishes."""
    with tempfile.TemporaryDirectory() as tmp:
        proc, out_file = _run_launcher(["--crash-at-step", "15"], tmp)
        assert proc.returncode == 0, proc.stderr[-2000:]
        step, loss, start = open(out_file).read().split(",")
        assert int(step) == 30
        # the resumed run restored from the step-10 flash snapshot
        assert int(start) == 10
        assert float(loss) < 2.0
