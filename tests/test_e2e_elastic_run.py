"""End-to-end tests of the elastic launcher with the toy workload.

Mirrors the reference's strongest system-test trick: platform=local + real
gRPC + real subprocesses on one host
(.github/actions/dlrover-system-test-*/action.yaml).
"""

import os
import subprocess
import sys
import tempfile

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_launcher(tmp, timeout=180, max_restarts=3, extra_env=None,
                  extra_flags=None):
    out_file = os.path.join(tmp, "result.txt")
    ckpt_dir = os.path.join(tmp, "ckpt")
    cmd = [
        sys.executable, "-m", "dlrover_tpu.trainer.elastic_run",
        "--standalone", "--nnodes", "1:1",
        "--max_restarts", str(max_restarts),
        "--monitor_interval", "0.3",
    ] + (extra_flags or []) + [
        os.path.join(REPO, "examples", "toy_train.py"), "--",
        "--steps", "30", "--ckpt-dir", ckpt_dir, "--out", out_file,
    ]
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.update(extra_env or {})
    proc = subprocess.run(
        cmd, cwd=REPO, env=env, timeout=timeout,
        capture_output=True, text=True,
    )
    return proc, out_file


def test_standalone_training_completes():
    with tempfile.TemporaryDirectory() as tmp:
        proc, out_file = _run_launcher(tmp)
        assert proc.returncode == 0, proc.stderr[-2000:]
        step, loss, start = open(out_file).read().split(",")
        assert int(step) == 30
        assert float(loss) < 1.0  # actually learned
        assert int(start) == 0


def test_kill_and_resume_from_flash_checkpoint():
    """Training crashes mid-run (injected via the first-class fault
    hook); the agent restarts the process, which restores from the
    RAM-tier checkpoint and finishes."""
    with tempfile.TemporaryDirectory() as tmp:
        proc, out_file = _run_launcher(
            tmp, extra_env={"DLROVER_FAULT_INJECT": "crash@15"}
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        step, loss, start = open(out_file).read().split(",")
        assert int(step) == 30
        # the resumed run restored from the step-10 flash snapshot
        assert int(start) == 10
        assert float(loss) < 2.0


def test_hang_detected_and_worker_restarted():
    """A worker that stops stepping (injected hang) is detected by the
    in-process step-progress detector, reported to the master, and
    recycled via the heartbeat restart action — the agent never loses a
    heartbeat and the relaunched process resumes from the flash
    checkpoint and finishes (VERDICT #6 done-criterion)."""
    with tempfile.TemporaryDirectory() as tmp:
        proc, out_file = _run_launcher(
            tmp,
            timeout=240,
            extra_flags=["--heartbeat_interval", "0.5"],
            extra_env={
                "DLROVER_FAULT_INJECT": "hang@15",
                "DLROVER_HANG_MIN_TIMEOUT": "3",
            },
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        step, loss, start = open(out_file).read().split(",")
        assert int(step) == 30
        # the restart recycles the worker with SIGTERM (10s grace):
        # when the armed DrainCoordinator finishes inside the grace it
        # lands an emergency save at the last completed step (14, one
        # short of the injected hang at 15); when the grace expires
        # first the relaunch falls back to the step-10 cadenced
        # snapshot — either way the resume point is a real checkpoint
        # at or past step 10
        assert 10 <= int(start) < 15, start
        # the node was recycled, not failed: no heartbeat-loss kill
        combined = proc.stdout + proc.stderr
        assert "heartbeat lost" not in combined
        assert "restart" in combined.lower()


def test_llama_system_e2e_with_shm_data_plane():
    """SURVEY §4(c): the single-host system test on a REAL model — the
    flagship workload (examples/llama_train.py: rendezvous -> master
    dataset sharding -> coworker shm producers -> DevicePrefetch ->
    ShardedTrainer -> flash checkpoint) under the elastic launcher."""
    with tempfile.TemporaryDirectory() as tmp:
        out_file = os.path.join(tmp, "result.txt")
        cmd = [
            sys.executable, "-m", "dlrover_tpu.trainer.elastic_run",
            "--standalone", "--nnodes", "1:1",
            "--monitor_interval", "0.3",
            os.path.join(REPO, "examples", "llama_train.py"), "--",
            "--steps", "6", "--batch-size", "8", "--seq-len", "32",
            "--num-workers", "2",
            "--ckpt-dir", os.path.join(tmp, "ckpt"),
            "--out", out_file,
        ]
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        proc = subprocess.run(
            cmd, cwd=REPO, env=env, timeout=300,
            capture_output=True, text=True,
        )
        assert proc.returncode == 0, proc.stderr[-3000:]
        step, loss, start = open(out_file).read().split(",")
        assert int(step) == 6
        assert float(loss) > 0 and float(loss) < 50
        assert int(start) == 0


def test_preemption_drill_recovers():
    """Injected preemption (SIGTERM with a reclaim notice — the
    spot-VM shape): the armed DrainCoordinator lands an emergency
    checkpoint inside the notice window and the launcher exits with
    the distinct drain rc — NOT a local relaunch; a reclaimed host
    cannot restart on itself, the master replaces the node. The next
    incarnation (same ckpt dir) resumes from the emergency step, one
    past the last cadenced snapshot, and completes."""
    from dlrover_tpu.fault_tolerance.drain import DRAIN_EXIT_CODE

    with tempfile.TemporaryDirectory() as tmp:
        proc, out_file = _run_launcher(
            tmp, extra_env={
                "DLROVER_FAULT_INJECT": "preempt@15:notice=10",
                "DLROVER_TPU_PREEMPT_NOTICE_BUDGET": "10",
            },
        )
        combined = proc.stdout + proc.stderr
        assert proc.returncode == DRAIN_EXIT_CODE, combined[-2000:]
        assert "INJECTED PREEMPTION" in combined
        assert "drained gracefully" in combined

        # the relaunched incarnation (no injection) resumes from the
        # notice-window emergency checkpoint — PAST the step-10
        # cadenced snapshot the old pre-drain behavior fell back to
        proc2, out_file = _run_launcher(tmp)
        assert proc2.returncode == 0, proc2.stderr[-2000:]
        step, loss, start = open(out_file).read().split(",")
        assert int(step) == 30
        assert 10 < int(start) <= 15, start


def test_dlrm_system_e2e_with_crash_resume():
    """BASELINE config #4 system test: the sparse-embedding recommender
    (examples/dlrm_train.py — master dataset sharding -> vocab-stacked
    embedding tables -> ShardedTrainer -> flash checkpoint) under the
    elastic launcher, with an injected mid-run crash; resumes from the
    RAM-tier checkpoint and finishes with above-chance accuracy."""
    with tempfile.TemporaryDirectory() as tmp:
        out_file = os.path.join(tmp, "result.txt")
        cmd = [
            sys.executable, "-m", "dlrover_tpu.trainer.elastic_run",
            "--standalone", "--nnodes", "1:1",
            "--monitor_interval", "0.3",
            os.path.join(REPO, "examples", "dlrm_train.py"), "--",
            "--steps", "40",
            "--ckpt-dir", os.path.join(tmp, "ckpt"),
            "--out", out_file,
        ]
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["DLROVER_FAULT_INJECT"] = "crash@25"
        proc = subprocess.run(
            cmd, cwd=REPO, env=env, timeout=300,
            capture_output=True, text=True,
        )
        assert proc.returncode == 0, proc.stderr[-3000:]
        step, loss, acc, start = open(out_file).read().split(",")
        assert int(step) == 40
        assert 0 < float(loss) < 1.0
        assert float(acc) > 0.55  # planted rule beats the base rate
        assert int(start) == 20  # resumed from the step-20 checkpoint


def test_crash_drill_writes_ordered_event_journal():
    """Acceptance (ISSUE 2): an elastic-run drill with a crash injection
    produces ONE journal file — appended by master, agent, and both
    worker incarnations — whose timeline shows rendezvous, checkpoint
    saves, the injected fault, and the post-restart restore in causal
    order."""
    from dlrover_tpu.telemetry import read_journal

    with tempfile.TemporaryDirectory() as tmp:
        journal = os.path.join(tmp, "job.journal")
        proc, out_file = _run_launcher(
            tmp,
            extra_env={
                "DLROVER_FAULT_INJECT": "crash@15",
                "DLROVER_TPU_JOURNAL": journal,
            },
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        step, _, start = open(out_file).read().split(",")
        assert int(step) == 30 and int(start) == 10
        events = read_journal(journal)
        kinds = [e["kind"] for e in events]
        # the control-plane arc is present...
        assert "rendezvous.complete" in kinds
        assert "checkpoint.save" in kinds
        assert "fault.injected" in kinds
        assert "scale.restart" in kinds
        assert "checkpoint.restore" in kinds
        # ...and in causal order: a save precedes the injected crash,
        # which precedes the agent's restart, which precedes the
        # resumed process's restore
        assert kinds.index("checkpoint.save") < kinds.index(
            "fault.injected"
        )
        assert kinds.index("fault.injected") < kinds.index(
            "scale.restart"
        )
        assert kinds.index("scale.restart") < kinds.index(
            "checkpoint.restore"
        )
        restore = next(
            e for e in events if e["kind"] == "checkpoint.restore"
        )
        assert restore["data"]["step"] == 10
        assert restore["data"]["tier"] == "ram"
        # multi-process: at least master + worker pids interleaved
        assert len({e["pid"] for e in events}) >= 2
        # and the dump CLI renders the same file
        import subprocess as sp

        dump = sp.run(
            [sys.executable, "-m", "dlrover_tpu.telemetry.dump",
             journal],
            capture_output=True, text=True, timeout=60,
        )
        assert dump.returncode == 0
        assert "fault.injected" in dump.stdout
