"""Native shm ring + coworker dataloader tests.

Parity coverage for atorch's shm data-path tests (data/shm_context.py)."""

import multiprocessing as mp
import os
import time

import numpy as np
import pytest

from dlrover_tpu.data.shm_dataloader import DevicePrefetch, ShmDataLoader
from dlrover_tpu.data.shm_ring import RingClosed, ShmRing


def _name(tag):
    return f"/dlrover_test_{tag}_{os.getpid()}"


def test_ring_roundtrip_bytes():
    ring = ShmRing(_name("rt"), slot_bytes=1 << 16, num_slots=4)
    try:
        ring.push_bytes(b"hello tpu")
        assert len(ring) == 1
        assert ring.pop_bytes() == b"hello tpu"
        assert len(ring) == 0
    finally:
        ring.destroy()


def test_ring_numpy_framing_no_pickle():
    ring = ShmRing(_name("np"), slot_bytes=1 << 20, num_slots=4)
    try:
        x = np.arange(1000, dtype=np.float32).reshape(10, 100)
        y = np.arange(10, dtype=np.int64)
        ring.push((x, y))
        rx, ry = ring.pop()
        np.testing.assert_array_equal(rx, x)
        np.testing.assert_array_equal(ry, y)
        # arbitrary pytrees fall back to pickle
        ring.push({"a": x, "b": [1, 2]})
        out = ring.pop()
        np.testing.assert_array_equal(out["a"], x)
    finally:
        ring.destroy()


def test_ring_capacity_blocks_and_times_out():
    ring = ShmRing(_name("cap"), slot_bytes=1 << 10, num_slots=2)
    try:
        ring.push_bytes(b"a")
        ring.push_bytes(b"b")
        with pytest.raises(TimeoutError):
            ring.push_bytes(b"c", timeout_ms=200)
        assert ring.pop_bytes() == b"a"
        ring.push_bytes(b"c", timeout_ms=200)  # space freed
    finally:
        ring.destroy()


def test_ring_oversize_payload_rejected():
    ring = ShmRing(_name("big"), slot_bytes=64, num_slots=2)
    try:
        with pytest.raises(ValueError):
            ring.push_bytes(b"x" * 100)
    finally:
        ring.destroy()


def test_close_drains_then_raises():
    ring = ShmRing(_name("close"), slot_bytes=1 << 10, num_slots=4)
    try:
        ring.push_bytes(b"last")
        ring.close()
        assert ring.pop_bytes() == b"last"
        with pytest.raises(RingClosed):
            ring.pop_bytes(timeout_ms=1000)
    finally:
        ring.destroy()


def _producer_proc(name):
    ring = ShmRing.attach(name)
    for i in range(20):
        ring.push(np.full((4, 4), i, dtype=np.int32))


def test_cross_process_transport():
    name = _name("xproc")
    ring = ShmRing(name, slot_bytes=1 << 20, num_slots=4)
    try:
        ctx = mp.get_context("spawn")
        p = ctx.Process(target=_producer_proc, args=(name,))
        p.start()
        got = [int(ring.pop(timeout_ms=30_000)[0, 0]) for _ in range(20)]
        p.join(timeout=10)
        assert got == list(range(20))
    finally:
        ring.destroy()


def _batches():
    for i in range(12):
        yield np.full((2, 3), i, dtype=np.float32)


def test_shm_dataloader_end_to_end():
    loader = ShmDataLoader(_batches, num_workers=2,
                           slot_bytes=1 << 20, num_slots=4)
    try:
        seen = sorted(int(b[0, 0]) for b in loader)
        assert seen == list(range(12))
    finally:
        loader.shutdown()


def test_device_prefetch_preserves_order():
    prefetched = list(DevicePrefetch(_batches(), depth=3))
    assert [int(np.asarray(b)[0, 0]) for b in prefetched] == list(
        range(12)
    )
