"""Fixture: exactly one event-vocabulary violation (an event in the
closed preempt.* namespace that is not in the canonical set)."""


def emit(record):
    record("preempt.surprise_event", node=0)
