"""Fixture: exactly one event-names violation (CamelCase, undotted)."""


def emit(record):
    record("BadEventName", step=1)  # not snake-case dotted
