"""Fixture: exactly one goodput-phases violation (a phase label the
ledger's PHASES set does not contain)."""


def book(ledger, ts):
    ledger.transition("not_a_real_phase", ts=ts)
