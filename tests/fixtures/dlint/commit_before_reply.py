"""Fixture: exactly one commit-before-reply violation — a TaskManager
method that mutates the shard ledger and replies without persisting."""


class TaskManager:
    def __init__(self):
        self._datasets = {}
        self._lock = None
        self._journal = None

    def get_task(self, name, node_id):
        ds = self._datasets[name]
        task = ds.get_task(node_id)  # ledger mutation...
        return task  # ...replies with it only in memory (no persist)
