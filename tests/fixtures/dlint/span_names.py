"""Fixture: exactly one span-names violation (spaces, capitals)."""


def trace(span):
    with span("Bad Span Name"):
        pass
