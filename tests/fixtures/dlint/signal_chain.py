"""Fixture: exactly one signal-chain violation (bare overwrite that
neither captures nor restores the prior disposition)."""

import signal


def _handler(signum, frame):
    pass


def arm():
    signal.signal(signal.SIGTERM, _handler)  # clobbers whoever armed first
