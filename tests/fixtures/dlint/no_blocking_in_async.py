"""Fixture: exactly one no-blocking-in-async violation — a sync sleep
inside an async handler stalls the event loop and every in-flight RPC
scheduled on it."""

import asyncio
import time


class Dispatcher:
    async def dispatch(self, request):
        await asyncio.sleep(0)  # fine: awaited, yields the loop
        time.sleep(0.5)  # the violation: blocks the whole loop
        return request
