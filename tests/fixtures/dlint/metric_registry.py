"""Fixture: exactly one metric-registry violation — a metric emitted
with no row in the docs/TELEMETRY.md table (invisible to operators)."""

from dlrover_tpu.telemetry import counter


def observe():
    counter(
        "dlrover_fixture_only_metric_total",
        "fixture metric no doc mentions",
    ).inc()
