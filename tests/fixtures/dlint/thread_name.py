"""Fixture: exactly one thread-name violation (anonymous thread)."""

import threading


def start(work):
    t = threading.Thread(target=work, daemon=True)  # no name=
    t.start()
    return t
