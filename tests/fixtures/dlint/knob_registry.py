"""Fixture: exactly one knob-registry violation — an env read with no
default (crashes or misbehaves differently on an unset fleet)."""

import os


def budget():
    return os.getenv("DLROVER_TPU_FIXTURE_ONLY_KNOB")  # no default
