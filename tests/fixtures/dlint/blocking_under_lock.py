"""Fixture: exactly one blocking-under-lock violation — a sleep inside
the critical section (the PR 8 report_batch_done bug class)."""

import threading
import time


class Poller:
    def __init__(self):
        self._lock = threading.Lock()
        self._ticks = 0

    def poll(self):
        with self._lock:
            time.sleep(0.5)  # the violation: blocks every contender
            self._ticks += 1
