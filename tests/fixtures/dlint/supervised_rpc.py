"""Fixture: exactly one supervised-rpc violation (a public RPC method
neither @supervised_rpc-wrapped nor in UNSUPERVISED_RPCS)."""

UNSUPERVISED_RPCS = ("close",)


def supervised_rpc(fn):
    return fn


class MasterClient:
    def __init__(self):
        self._stub = None

    @supervised_rpc
    def get_task(self, node_id):
        return self._call("get_task", node_id=node_id)

    def report_status(self, status):  # the violation: bare RPC
        return self._call("report_status", status=status)

    def close(self):  # allowlisted: fire-and-forget on shutdown
        return self._call("close")

    def _call(self, name, **kw):
        return (name, kw)
