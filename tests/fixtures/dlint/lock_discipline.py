"""Fixture: exactly one lock-discipline violation — ``_items`` is
locked in ``add`` but mutated unlocked in ``drop``."""

import threading


class Ledger:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = {}

    def add(self, key, value):
        with self._lock:
            self._items[key] = value

    def drop(self, key):
        self._items.pop(key, None)  # the violation: unlocked mutation
