"""Ring / Ulysses context-parallel attention vs dense reference, and the
sequence-parallel Llama training path, on the 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

# tier-1 budget (ISSUE 2 satellite): this module costs >50s of the
# 870s budget on a 1-core box; the nightly/full shard still runs it
pytestmark = pytest.mark.slow

from dlrover_tpu.models import llama
from dlrover_tpu.ops.attention import mha_reference
from dlrover_tpu.parallel.context_parallel import (
    make_context_parallel_attn,
    ring_attention,
    ulysses_attention,
)
from dlrover_tpu.parallel.mesh import create_mesh
from dlrover_tpu.trainer.sharded import make_trainer_for_llama


def _qkv(key, b=2, s=128, h=4, kvh=4, d=32):
    kq, kk, kv = jax.random.split(key, 3)
    return (
        jax.random.normal(kq, (b, s, h, d)),
        jax.random.normal(kk, (b, s, kvh, d)),
        jax.random.normal(kv, (b, s, kvh, d)),
    )


def test_fully_masked_rows_yield_zeros():
    q, k, v = _qkv(jax.random.key(9), b=1, s=8, h=2, kvh=2, d=4)
    mask = jnp.zeros((8, 8), dtype=bool).at[4:, :].set(True)
    out, lse = mha_reference(
        q, k, v, causal=False, mask=mask, return_lse=True
    )
    np.testing.assert_array_equal(np.asarray(out[0, :4]), 0.0)
    assert np.all(np.asarray(lse[0, :, :4]) <= -1e29)


def test_explicit_mask_intersects_causal():
    """causal=True + an explicit mask must apply BOTH constraints."""
    q, k, v = _qkv(jax.random.key(10), b=1, s=8, h=2, kvh=2, d=4)
    full = jnp.ones((8, 8), dtype=bool)
    out = mha_reference(q, k, v, causal=True, mask=full)
    ref = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(out, ref, rtol=1e-6, atol=1e-6)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_matches_dense(causal):
    mesh = create_mesh([("data", 2), ("seq", 4)])
    q, k, v = _qkv(jax.random.key(0))
    out = jax.jit(
        lambda q, k, v: ring_attention(q, k, v, mesh, causal=causal)
    )(q, k, v)
    ref = mha_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(out, ref, rtol=2e-3, atol=2e-3)


def test_ring_gqa():
    mesh = create_mesh([("seq", 8)])
    q, k, v = _qkv(jax.random.key(1), h=8, kvh=2)
    out = jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh))(q, k, v)
    ref = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(out, ref, rtol=2e-3, atol=2e-3)


def test_ring_gradients_match_dense():
    mesh = create_mesh([("seq", 4)], devices=jax.devices()[:4])
    q, k, v = _qkv(jax.random.key(2), b=1, s=64, h=2, kvh=2, d=16)

    g_ring = jax.grad(
        lambda q, k, v: jnp.sum(ring_attention(q, k, v, mesh) ** 2),
        argnums=(0, 1, 2),
    )(q, k, v)
    g_ref = jax.grad(
        lambda q, k, v: jnp.sum(mha_reference(q, k, v, causal=True) ** 2),
        argnums=(0, 1, 2),
    )(q, k, v)
    for gr, gd, n in zip(g_ring, g_ref, "qkv"):
        np.testing.assert_allclose(
            gr, gd, rtol=5e-3, atol=5e-3, err_msg=f"d{n}"
        )


@pytest.mark.parametrize("causal", [True, False])
def test_ulysses_matches_dense(causal):
    mesh = create_mesh([("seq", 4)], devices=jax.devices()[:4])
    q, k, v = _qkv(jax.random.key(3))
    out = jax.jit(
        lambda q, k, v: ulysses_attention(q, k, v, mesh, causal=causal)
    )(q, k, v)
    ref = mha_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(out, ref, rtol=2e-3, atol=2e-3)


def test_ulysses_rejects_indivisible_heads():
    mesh = create_mesh([("seq", 8)])
    q, k, v = _qkv(jax.random.key(4), h=4)
    with pytest.raises(ValueError):
        ulysses_attention(q, k, v, mesh)


@pytest.mark.parametrize("kind", ["ring", "ulysses"])
def test_llama_sequence_parallel_training(kind):
    """Full train step under the sequence strategy: tokens sharded over
    batch AND seq axes, context-parallel attention inside the jit."""
    cfg = llama.llama_tiny()
    mesh = create_mesh([("data", 2), ("seq", 4)])
    attn_fn = make_context_parallel_attn(mesh, kind=kind)
    trainer = make_trainer_for_llama(
        cfg, mesh, strategy="sequence", optimizer=optax.adam(1e-2),
        attn_fn=attn_fn,
    )
    params, opt_state = trainer.init(jax.random.key(0))
    tokens = np.asarray(
        jax.random.randint(jax.random.key(1), (4, 64), 0, cfg.vocab_size)
    )
    batch = trainer.shard_batch(trainer.microbatch((tokens, tokens)))
    losses = []
    for _ in range(6):
        params, opt_state, loss = trainer.train_step(
            params, opt_state, batch
        )
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.9, losses


def test_sequence_parallel_loss_matches_dense():
    """Sequence-parallel loss equals the dense single-mesh loss."""
    cfg = llama.llama_tiny()
    tokens = np.asarray(
        jax.random.randint(jax.random.key(1), (8, 64), 0, cfg.vocab_size)
    )
    mesh_sp = create_mesh([("seq", 8)])
    attn_fn = make_context_parallel_attn(mesh_sp, kind="ring")
    tr_sp = make_trainer_for_llama(
        cfg, mesh_sp, strategy="sequence", attn_fn=attn_fn
    )
    p, o = tr_sp.init(jax.random.key(0))
    _, _, loss_sp = tr_sp.train_step(
        p, o, tr_sp.shard_batch(tr_sp.microbatch((tokens, tokens)))
    )

    mesh_d = create_mesh([("data", 8)])
    tr_d = make_trainer_for_llama(cfg, mesh_d, strategy="ddp")
    p, o = tr_d.init(jax.random.key(0))
    _, _, loss_d = tr_d.train_step(
        p, o, tr_d.shard_batch(tr_d.microbatch((tokens, tokens)))
    )
    np.testing.assert_allclose(
        float(loss_sp), float(loss_d), rtol=2e-2
    )


def test_ring_attention_16k_matches_dense():
    """VERDICT r4 Weak #3: ring attention RUNS at seq 16384 on the
    8-device mesh (reduced width) and matches the dense reference —
    the long-context claim as execution, not documentation."""
    mesh = create_mesh([("seq", 8)])
    q, k, v = _qkv(jax.random.key(7), b=1, s=16384, h=2, kvh=2, d=32)
    out = jax.jit(
        lambda q, k, v: ring_attention(q, k, v, mesh, causal=True)
    )(q, k, v)
    ref = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4
    )


def test_sequence_parallel_train_step_16k():
    """A full sequence-strategy TRAIN step at seq 16384 (reduced
    width — CPU flops, not memory, bound this host) with the compiled
    step's own memory accounting. Execution half of the 16k story;
    auto_accelerate choosing the strategy is test_auto's. Ring
    attention is wired automatically by the sequence strategy (the
    dense fallback would materialize [16k, 16k] scores — the 1.3 GB
    vs 6.5 GB temp difference this test's bound pins down)."""
    cfg = llama.llama_tiny(
        num_layers=1, hidden_size=32, intermediate_size=64,
        num_heads=2, num_kv_heads=2, max_seq_len=16384, remat="off",
    )
    mesh = create_mesh([("seq", 8)])
    trainer = make_trainer_for_llama(
        cfg, mesh, strategy="sequence", optimizer=optax.adam(1e-2)
    )
    params, opt_state = trainer.init(jax.random.key(0))
    tokens = jax.random.randint(
        jax.random.key(1), (1, 16384), 0, cfg.vocab_size
    )
    mb = trainer.shard_batch(trainer.microbatch((tokens, tokens)))

    # compile once; XLA's memory analysis is the accounting record
    compiled = trainer.train_step.lower(
        params, opt_state, mb
    ).compile()
    analysis = compiled.memory_analysis()
    temp = getattr(analysis, "temp_size_in_bytes", 0)
    assert 0 < temp < 3e9, temp  # ring, not the dense [16k,16k] path

    losses = []
    for _ in range(2):
        params, opt_state, loss = compiled(params, opt_state, mb)
        losses.append(float(loss))
    assert all(np.isfinite(losses)), losses
    assert losses[-1] < losses[0], losses
