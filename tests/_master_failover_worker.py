"""Drill worker for the master-kill failover test (not a test module).

Speaks the real agent protocol against a live master: joins the
training rendezvous, consumes data shards via ShardingClient (which
registers the dataset re-hello reconnect hook), reports the global
step (the master's fault injector counts these), and — the moment its
connection supervisor reconnects to the restarted master — re-joins
the rendezvous mid-epoch so the test can assert the round counter
stayed monotonic across the restart.

Every consumed shard range is appended to --out as ``SHARD <start>
<end>`` the moment the task arrives; the test unions both workers'
ranges to prove exactly-once delivery across the crash.
"""

import argparse
import sys
import threading
import time


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--master_addr", required=True)
    p.add_argument("--node_id", type=int, required=True)
    p.add_argument("--out", required=True)
    p.add_argument("--dataset_size", type=int, default=96)
    p.add_argument("--batch_size", type=int, default=4)
    p.add_argument("--shard_secs", type=float, default=0.08,
                   help="simulated train time per shard")
    args = p.parse_args()

    from dlrover_tpu.agent.master_client import MasterClient
    from dlrover_tpu.agent.sharding.client import ShardingClient
    from dlrover_tpu.common.constants import RendezvousName

    out = open(args.out, "w", buffering=1)

    def emit(line: str):
        out.write(line + "\n")
        print(f"[worker {args.node_id}] {line}", flush=True)

    client = MasterClient(
        args.master_addr, node_id=args.node_id, node_type="worker",
    )
    reconnected = threading.Event()
    client.add_reconnect_hook("drill-flag", reconnected.set)

    def rendezvous(tag: str, min_round: int = 0) -> int:
        client.join_rendezvous(args.node_id, 1)
        deadline = time.monotonic() + 60
        while True:
            rdzv_round, _, world = client.get_comm_world(
                RendezvousName.TRAINING, args.node_id
            )
            if (world and args.node_id in world
                    and rdzv_round >= min_round):
                emit(f"{tag} {rdzv_round}")
                return rdzv_round
            if time.monotonic() > deadline:
                emit(f"ERROR {tag} timeout")
                raise TimeoutError(tag)
            time.sleep(0.2)

    # ---- rendezvous round 1 (pre-crash) -----------------------------
    client.report_rdzv_params(
        min_nodes=2, max_nodes=2, waiting_timeout=0.5, node_unit=1,
    )
    round1 = rendezvous("ROUND1")

    # ---- consume the dataset ---------------------------------------
    # fetch_batch puts the batched get_tasks RPC (and its group-commit
    # journal write) on the drill's hot path: shards buffered when the
    # master dies are restored in its doing set under THIS worker, and
    # the exactly-once partition assert covers them
    sharding = ShardingClient(
        dataset_name="failover-drill",
        batch_size=args.batch_size,
        num_epochs=1,
        dataset_size=args.dataset_size,
        shuffle=False,
        num_minibatches_per_shard=1,
        master_client=client,
        fetch_batch=3,
    )
    step = 0
    round2_done = False
    while True:
        if reconnected.is_set() and not round2_done:
            # master restarted under us: prove the restored round
            # counter never regressed by completing a fresh rendezvous
            # mid-epoch (both workers reconnect, so both re-join)
            rendezvous("ROUND2", min_round=round1 + 1)
            round2_done = True
        shard = sharding.fetch_shard(poll_interval=0.2, max_wait=120.0)
        if shard is None:
            break
        emit(f"SHARD {shard.start} {shard.end}")
        time.sleep(args.shard_secs)
        step += 1
        # the master-side fault injector triggers off these reports
        client.report_global_step(step)
        assert sharding._current_task is not None
        sharding.report_task_done(sharding._current_task.task_id)

    if not round2_done:
        emit("ERROR never reconnected (master crash not observed)")
        return 5
    emit("DONE")
    client.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
