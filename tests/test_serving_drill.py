"""Serving chaos drill: zero-dropped-request worker rotation under load.

A real master (RequestRouter armed) serves three elastic replicas
(``_serving_drill_worker.py``) while this test plays the load
generator. Mid-stream chaos, in order:

* ``DLROVER_FAULT_INJECT=serve_kill@25`` SIGKILLs replica 0 after 25
  responses — it dies holding leased requests plus a buffered lookahead
  batch, which the router's lease-timeout watchdog redelivers;
* the ServingAutoScaler (reading ``serve_stats`` over gRPC) sees the
  queue depth spike and scales the pool up, spawning replica 2 — which
  restores its weights from the RAM tier replica 0 warmed;
* replica 1 is rotated with SIGTERM: it finishes its in-flight batch,
  relinquishes the rest, and exits rc 21 (DRAIN_EXIT_CODE).

Asserted per request id: every request gets EXACTLY one response, with
the correct payload (so no replica served from wrong weights); p99
stays bounded; the journal carries the canonical serve.* vocabulary
(worker_ready x3, request_redelivered, relinquished, sealed, drained,
both worker_exit reasons); the master exits 0 once the stream drains;
and the job's goodput account books `serving` time for the replicas.
With ``DLROVER_TPU_SLO=serve_p99_ms<=50`` the master's SLO evaluator
journals ``slo.violated`` carrying the queue-wait vs model-time
latency split (ISSUE 17 attributed cause).
"""

import os
import signal
import subprocess
import sys
import time

import dlrover_tpu.telemetry as T
from dlrover_tpu.serving import DRAIN_EXIT_CODE, ServingAutoScaler
from dlrover_tpu.telemetry.journal import read_journal

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from test_goodput_drill import (  # noqa: E402
    _drill_env,
    _free_port,
    _killpg,
    _master_port,
    _spawn_master,
    _tail,
    _wait,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

NUM_REQUESTS = 160
BATCH_SIZE = 4
MODEL_MS = 100.0
KILL_AFTER = 25
#: sum(arange(64)) — the checksum of the shared weight artifact every
#: replica's responses must embed
WEIGHT_TAG = b"#2016"


def _spawn_replica(tmp, env, port, node_id, tag, ckpt_dir, ram_dir):
    return subprocess.Popen(
        [sys.executable,
         os.path.join(REPO, "tests", "_serving_drill_worker.py"),
         "--master_addr", f"localhost:{port}",
         "--node_id", str(node_id),
         "--out", os.path.join(tmp, f"replica-{tag}.txt"),
         "--ckpt_dir", ckpt_dir, "--ram_dir", ram_dir,
         "--batch_size", str(BATCH_SIZE),
         "--model_ms", str(MODEL_MS)],
        cwd=REPO, env=env,
        stdout=open(os.path.join(tmp, f"replica-{tag}.out"), "w"),
        stderr=subprocess.STDOUT,
        start_new_session=True,
    )


def _wait_stats(client, cond, what, timeout=60):
    deadline = time.time() + timeout
    stats = None
    while time.time() < deadline:
        stats = client.serve_stats()
        if stats and cond(stats):
            return stats
        time.sleep(0.2)
    raise AssertionError(f"timed out waiting for {what}: {stats}")


def test_serving_chaos_drill(tmp_path):
    from dlrover_tpu.agent.master_client import MasterClient

    tmp = str(tmp_path)
    state_dir = os.path.join(tmp, "state")
    ckpt_dir = os.path.join(tmp, "ckpt")
    ram_dir = os.path.join(tmp, "ram")
    journal_path = os.path.join(tmp, "journal.jsonl")
    env = _drill_env(journal_path)
    # SLO plane (ISSUE 17): with 160 requests queued upfront against
    # a 100ms model, the serve p99 is guaranteed past 50ms — the
    # master must journal slo.violated and attribute WHICH side blew
    # it (queue wait, here: the backlog dwarfs per-batch model time)
    master_env = dict(env, DLROVER_TPU_SERVE_LEASE_TIMEOUT="2.5",
                      DLROVER_TPU_SLO="serve_p99_ms<=50")
    worker_envs = {
        0: dict(env, DLROVER_FAULT_INJECT=f"serve_kill@{KILL_AFTER}"),
        1: dict(env),
        2: dict(env),
    }

    procs = []
    lb = None
    scaler = None
    try:
        master = _spawn_master(tmp, master_env, state_dir,
                               _free_port(), "serve")
        procs.append(master)
        port = _master_port(tmp, "serve", master)

        w0 = _spawn_replica(tmp, worker_envs[0], port, 0, "0",
                            ckpt_dir, ram_dir)
        w1 = _spawn_replica(tmp, worker_envs[1], port, 1, "1",
                            ckpt_dir, ram_dir)
        procs += [w0, w1]

        lb = MasterClient(f"localhost:{port}", node_id=9,
                          node_type="worker")
        # both replicas leasing == their rotation handlers are armed
        _wait_stats(lb, lambda s: s["workers"] >= 2,
                    "2 replicas leasing", timeout=90)

        req_ids = []
        for i in range(NUM_REQUESTS):
            ok, rid, reason = lb.serve_submit(b"m%d" % i)
            assert ok, f"submit {i} rejected: {reason}"
            req_ids.append(rid)
        assert len(set(req_ids)) == NUM_REQUESTS

        # the autoscaler component under test, wired the drill way:
        # stats over gRPC, scale_fn spawning a real replica process
        spawned = []

        def scale_fn(target):
            if not spawned:
                w2 = _spawn_replica(tmp, worker_envs[2], port, 2, "2",
                                    ckpt_dir, ram_dir)
                spawned.append(w2)
                procs.append(w2)

        scaler = ServingAutoScaler(
            stats_fn=lb.serve_stats, scale_fn=scale_fn,
            replicas_fn=lambda: 2 + len(spawned),
            min_replicas=2, max_replicas=3, queue_high=8,
            p99_high_ms=1e9, interval=0.25, cooldown=1e9,
        )
        scaler.start()

        # chaos #1: replica 0 SIGKILLs itself (whole group) after
        # KILL_AFTER responses, leased requests outstanding
        rc0 = _wait(w0, 90, "serve_kill replica", tmp,
                    ["replica-0.out"])
        assert rc0 == -signal.SIGKILL, _tail(tmp, "replica-0.out")

        # the queue spike scaled the pool: replica 2 is live
        deadline = time.time() + 60
        while not spawned and time.time() < deadline:
            time.sleep(0.2)
        assert spawned, "autoscaler never spawned replica 2"
        _wait_stats(lb, lambda s: s["workers"] >= 3,
                    "replica 2 leasing", timeout=90)

        # chaos #2: rotate replica 1 — SIGTERM, finish in-flight,
        # relinquish, exit DRAIN_EXIT_CODE
        os.kill(w1.pid, signal.SIGTERM)
        rc1 = _wait(w1, 60, "rotated replica", tmp, ["replica-1.out"])
        assert rc1 == DRAIN_EXIT_CODE, _tail(tmp, "replica-1.out")

        # every request id: exactly one response, correct payload
        responses = {}
        deadline = time.time() + 90
        for i, rid in enumerate(req_ids):
            while rid not in responses:
                done, payload, worker_id, latency = lb.serve_poll(rid)
                if done:
                    responses[rid] = (payload, worker_id, latency)
                    break
                assert time.time() < deadline, (
                    f"request {rid} never answered; "
                    + _tail(tmp, "replica-2.out")
                )
                time.sleep(0.05)
        for i, rid in enumerate(req_ids):
            payload, worker_id, _ = responses[rid]
            assert payload == (b"m%d" % i).upper() + WEIGHT_TAG, (
                rid, payload,
            )
            assert worker_id in (0, 1, 2)

        stats = lb.serve_stats()
        assert stats["completed"] == NUM_REQUESTS
        assert stats["redelivered"] >= 1, stats  # the SIGKILL's leases
        # bounded tail latency: one lease-timeout redelivery window
        # plus pool-restaffing headroom, nowhere near the 90s poll cap
        assert 0 < stats["p99_ms"] < 30000, stats

        lb.serve_seal()
        rc2 = _wait(spawned[0], 60, "surviving replica", tmp,
                    ["replica-2.out"])
        assert rc2 == 0, _tail(tmp, "replica-2.out")
        assert "DONE" in open(
            os.path.join(tmp, "replica-2.txt")
        ).read()
        # the master's serving-termination path: stream drained -> rc 0
        assert _wait(master, 60, "master", tmp,
                     ["master-serve.err"]) == 0

        # --- journal: the canonical serve.* story, end to end --------
        events = read_journal(journal_path)
        kinds = [e.get("kind") for e in events]
        ready = [e for e in events if e.get("kind") == "serve.worker_ready"]
        assert {e["data"]["node_id"] for e in ready} == {0, 1, 2}
        redelivered = [e for e in events
                       if e.get("kind") == "serve.request_redelivered"]
        assert any(e["data"]["cause"] == "lease_timeout"
                   for e in redelivered)
        exits = {e["data"]["node_id"]: e["data"]["reason"]
                 for e in events if e.get("kind") == "serve.worker_exit"}
        assert exits.get(1) == "signal-sigterm"
        assert exits.get(2) == "sealed"
        assert 0 not in exits  # SIGKILL leaves no goodbye — the point
        assert "serve.relinquished" in kinds
        assert "serve.sealed" in kinds and "serve.drained" in kinds
        # replica 2 restored the artifact replica 0/1 warmed into the
        # RAM tier (step >= 0 == restore, -1 == cold init)
        by_node = {e["data"]["node_id"]: e["data"] for e in ready}
        assert by_node[2]["step"] >= 0, by_node

        # the autoscale decision was journaled (in this process: the
        # drill runs the scaler) with the queue-depth trigger
        auto = T.default_journal().events("serve.autoscale")
        assert auto and auto[-1]["data"]["reason"] == "queue_depth"

        # --- SLO: the master saw the blown serve p99 and said WHY ----
        violated = [e for e in events if e.get("kind") == "slo.violated"]
        assert violated, "slo.violated never journaled"
        v = violated[0]["data"]
        assert v["objective"] == "serve_p99_ms"
        assert v["value"] > 50.0
        # attributed latency: both sides of the split ride the event,
        # and the blamed cause is the dominant side AT VIOLATION ONSET
        # (typically model_time: the first batch completes with ~zero
        # queue wait and a 100ms model against a 50ms objective)
        assert v["cause"] in ("queue_wait", "model_time")
        qw, mt = v["queue_wait_p99_ms"], v["model_time_p99_ms"]
        assert mt > 0.0
        assert v["cause"] == ("model_time" if mt > qw else "queue_wait")
        assert auto[-1]["data"]["target"] == 3

        # goodput: serving incarnations book `serving` time on the job
        # account the master journals at shutdown — not `idle`
        summaries = [e for e in events
                     if e.get("kind") == "goodput.job_summary"]
        assert summaries, "master never journaled the job account"
        assert summaries[-1]["data"].get("serving_s", 0) > 0
    finally:
        if scaler is not None:
            scaler.stop()
        if lb is not None:
            lb.close()
        for p in procs:
            _killpg(p)
