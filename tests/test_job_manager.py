"""Distributed job-manager / autoscaler tests with a fake platform.

Mirrors the reference's mocked-k8s tests (dlrover/python/tests/
test_job_manager.py feeding hand-built events)."""

import time
from types import SimpleNamespace

import pytest

from dlrover_tpu.common.constants import (
    NodeEventType,
    NodeExitReason,
    NodeStatus,
    NodeType,
)
from dlrover_tpu.common.node import Node, NodeGroupResource, NodeResource
from dlrover_tpu.master.dist_master import DistributedJobMaster
from dlrover_tpu.master.node.dist_job_manager import create_job_manager
from dlrover_tpu.master.node.job_auto_scaler import new_job_auto_scaler
from dlrover_tpu.master.resource.local_optimizer import TPULocalOptimizer
from dlrover_tpu.master.resource.optimizer import ResourcePlan
from dlrover_tpu.master.scaler.base_scaler import ScalePlan, Scaler
from dlrover_tpu.master.monitor.speed_monitor import SpeedMonitor
from dlrover_tpu.master.watcher.base_watcher import (
    InMemoryWatcher,
    NodeEvent,
)


class RecordingScaler(Scaler):
    def __init__(self):
        super().__init__("test")
        self.plans = []

    def scale(self, plan: ScalePlan):
        self.plans.append(plan)


def _evt(node_id, status, exit_reason="", etype=NodeEventType.MODIFIED):
    n = Node(NodeType.WORKER, node_id, status=status)
    if exit_reason:
        n.set_exit_reason(exit_reason)
    return NodeEvent(etype, n)


def _mgr(scaler=None, node_num=0):
    args = SimpleNamespace(node_num=node_num,
                           node_resource=NodeResource(memory=1024))
    return create_job_manager(
        args, SpeedMonitor(), scaler=scaler,
        job_optimizer=TPULocalOptimizer(job_args=args),
    )


def test_start_launches_initial_workers():
    scaler = RecordingScaler()
    mgr = _mgr(scaler, node_num=3)
    mgr.start()
    mgr.stop()
    assert len(scaler.plans) == 1
    assert len(scaler.plans[0].launch_nodes) == 3


def test_failed_worker_relaunches_with_new_id():
    scaler = RecordingScaler()
    mgr = _mgr(scaler, node_num=2)
    mgr.start()
    mgr.process_event(_evt(0, NodeStatus.RUNNING))
    mgr.process_event(_evt(0, NodeStatus.FAILED,
                           NodeExitReason.KILLED))
    mgr.stop()
    relaunch_plans = [p for p in scaler.plans[1:] if p.launch_nodes]
    assert len(relaunch_plans) == 1
    new_node = relaunch_plans[0].launch_nodes[0]
    assert new_node.id == 2  # fresh id
    assert new_node.rank_index == 0  # same rank slot
    assert new_node.relaunch_count == 1


def test_oom_relaunch_grows_memory():
    scaler = RecordingScaler()
    mgr = _mgr(scaler, node_num=1)
    mgr.start()
    node = mgr.get_node(NodeType.WORKER, 0)
    node.config_resource = NodeResource(memory=1000)
    mgr.process_event(_evt(0, NodeStatus.RUNNING))
    mgr.process_event(_evt(0, NodeStatus.FAILED, NodeExitReason.OOM))
    mgr.stop()
    assert node.config_resource.memory == 1500


def test_fatal_error_never_relaunches():
    scaler = RecordingScaler()
    mgr = _mgr(scaler, node_num=1)
    mgr.start()
    mgr.process_event(_evt(0, NodeStatus.RUNNING))
    mgr.process_event(
        _evt(0, NodeStatus.FAILED, NodeExitReason.FATAL_ERROR)
    )
    mgr.stop()
    assert not [p for p in scaler.plans[1:] if p.launch_nodes]


def test_relaunch_count_exhaustion():
    scaler = RecordingScaler()
    mgr = _mgr(scaler, node_num=1)
    mgr.start()
    nid = 0
    for round_i in range(5):
        mgr.process_event(_evt(nid, NodeStatus.RUNNING))
        mgr.process_event(
            _evt(nid, NodeStatus.FAILED, NodeExitReason.KILLED)
        )
        plans = [p for p in scaler.plans[1:] if p.launch_nodes]
        if round_i < 3:
            nid = plans[-1].launch_nodes[0].id
    mgr.stop()
    # default max_relaunch_count=3 -> exactly 3 relaunches
    assert len([p for p in scaler.plans[1:] if p.launch_nodes]) == 3


def test_heartbeat_watchdog_only_arms_after_first_report():
    scaler = RecordingScaler()
    args = SimpleNamespace(node_num=1, node_resource=NodeResource())
    mgr = create_job_manager(
        args, SpeedMonitor(), scaler=scaler,
        job_optimizer=TPULocalOptimizer(job_args=args),
    )
    mgr._heartbeat_timeout = 0.6
    mgr.start()
    mgr.process_event(_evt(0, NodeStatus.RUNNING))
    # no heartbeat ever reported -> watchdog must NOT kill the node
    time.sleep(1.0)
    assert mgr.get_node(NodeType.WORKER, 0).status == NodeStatus.RUNNING
    # a stale heartbeat arms the watchdog -> failure + relaunch
    mgr.collect_node_heartbeat(NodeType.WORKER, 0, time.time() - 100)
    deadline = time.time() + 5
    while time.time() < deadline:
        if [p for p in scaler.plans[1:] if p.launch_nodes]:
            break
        time.sleep(0.05)
    else:
        pytest.fail("heartbeat loss did not trigger relaunch")
    assert mgr.get_node(NodeType.WORKER, 0).status == NodeStatus.FAILED
    mgr.stop()


def test_watcher_event_stream_drives_manager():
    watcher = InMemoryWatcher()
    scaler = RecordingScaler()
    args = SimpleNamespace(node_num=1, node_resource=NodeResource())
    mgr = create_job_manager(args, SpeedMonitor(), scaler=scaler,
                             watcher=watcher)
    mgr.start()
    watcher.push(_evt(0, NodeStatus.RUNNING))
    deadline = time.time() + 5
    while time.time() < deadline:
        n = mgr.get_node(NodeType.WORKER, 0)
        if n and n.status == NodeStatus.RUNNING:
            break
        time.sleep(0.05)
    else:
        pytest.fail("watcher event not processed")
    mgr.stop()


def test_auto_scaler_executes_plan_diff():
    scaler = RecordingScaler()
    mgr = _mgr(scaler, node_num=2)
    mgr.start()
    mgr.process_event(_evt(0, NodeStatus.RUNNING))
    mgr.process_event(_evt(1, NodeStatus.RUNNING))
    auto = new_job_auto_scaler(
        mgr, TPULocalOptimizer(), scaler, interval=3600
    )
    plan = ResourcePlan()
    plan.node_group_resources[NodeType.WORKER] = NodeGroupResource(
        4, NodeResource()
    )
    sp = auto.execute_job_optimization_plan(plan)
    assert len(sp.launch_nodes) == 2  # 2 alive -> 4 wanted
    plan.node_group_resources[NodeType.WORKER] = NodeGroupResource(
        1, NodeResource()
    )
    sp = auto.execute_job_optimization_plan(plan)
    assert len(sp.remove_nodes) >= 1
    mgr.stop()


def test_local_optimizer_restores_lost_capacity():
    sm = SpeedMonitor()
    sm.set_target_worker_num(4)
    sm.add_running_worker(NodeType.WORKER, 0)
    sm.add_running_worker(NodeType.WORKER, 1)
    opt = TPULocalOptimizer(speed_monitor=sm, node_unit=2)
    plan = opt.generate_job_resource_plan()
    group = plan.node_group_resources[NodeType.WORKER]
    assert group.count == 4  # 2 running + 2 restored (node_unit multiple)


def test_dist_master_lifecycle_with_fake_platform():
    watcher = InMemoryWatcher()
    scaler = RecordingScaler()
    args = SimpleNamespace(node_num=2, node_unit=1,
                           node_resource=NodeResource())
    master = DistributedJobMaster(
        port=0, job_args=args, scaler=scaler, watcher=watcher,
        autoscale_interval=3600,
    )
    master.prepare()
    assert len(scaler.plans[0].launch_nodes) == 2
    watcher.push(_evt(0, NodeStatus.RUNNING))
    watcher.push(_evt(1, NodeStatus.RUNNING))
    time.sleep(0.3)
    assert len(master.job_manager.get_running_nodes()) == 2
    # both workers succeed -> run() returns 0
    watcher.push(_evt(0, NodeStatus.SUCCEEDED))
    watcher.push(_evt(1, NodeStatus.SUCCEEDED))
    time.sleep(0.3)
    rc = master.run(check_interval=0.1)
    assert rc == 0
