"""Two-slice preemption + composite-fault soak drill (VERDICT r3 #4).

Eight agents as two mocked slices (DLROVER_TPU_SLICE_SIZE=4,
node_unit=4), training examples/hybrid_train.py — which builds the
hybrid ICI x DCN mesh LIVE over every re-formed world. One continuous
run exercises, in order:

  T1  whole-slice preemption: slice 1's processes die (and keep dying
      on relaunch — preempted capacity has nowhere to come back) until
      the master prunes them; the survivors re-rendezvous at the
      node_unit-aligned world of 4, the DCN axis of the live hybrid
      mesh shrinks 2 -> 1, and training resumes from the flash
      checkpoint (loss continuity, no restart from step 0);

  T2  a straggler verdict against the minimum world: rank 2 (slice 0,
      a T1 SURVIVOR) had its pre-flight network probe delayed, so the
      initial check's two-round localization already marked it. Once
      training progresses at world 4, the auto-scaler reads the
      verdict — and the shrink plan must be VETOED: at
      min_nodes=4/node_unit=4 evicting the straggler would destroy
      the world, and a soak's accumulated faults must never let the
      straggler policy do that. (The live shrink itself is drilled in
      test_four_node_drill.py, where the world has room.)

  T3  OOM on one surviving rank (master-KV injection, crash rc 137):
      the agent escalates instead of relaunching locally (a local
      restart cannot outgrow a memory limit), the master grows the
      node's memory plan and relaunches it, and the world returns to 4
      — again resuming from checkpoint, with loss continuity over the
      whole soak.

Parity role: the reference's multi-node system tests
(.github/actions/dlrover-system-test-*) + SURVEY §5.8's slice mapping.
"""

import os
import re
import signal
import subprocess
import sys
import time

from dlrover_tpu.common.grpc_utils import find_free_port
import pytest

# tier-1 budget (ISSUE 2 satellite): this module costs >50s of the
# 870s budget on a 1-core box; the nightly/full shard still runs it
pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _strip_axon(env):
    parts = [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
             if p and "axon" not in p]
    env["PYTHONPATH"] = os.pathsep.join(parts + [REPO])
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["DLROVER_TPU_LOG_LEVEL"] = "INFO"
    return env


def _write_spec(tmp, dead_file):
    progress = os.path.join(tmp, "progress.txt")
    spec = f"""
apiVersion: dlrover-tpu/v1
kind: ElasticTpuJob
metadata:
  name: slice-soak
spec:
  platform: process
  distributionStrategy: allreduce
  nodeUnit: 4
  heartbeatTimeout: 8
  worker:
    replicas: 8
    minReplicas: 4
    maxRelaunchCount: 3
    criticalWorkerIndex: none
    env:
      DLROVER_TPU_SLICE_SIZE: "4"
      DLROVER_TPU_DEAD_SLICE_FILE: {dead_file}
      DLROVER_TPU_PROBE_DELAY: "2:40"
      DLROVER_TPU_REPORT_GATE: {os.path.join(tmp, "report_gate")}
      DLROVER_TPU_DIST_HEARTBEAT_TIMEOUT: "10"
      JAX_PLATFORMS: cpu
    command:
      - {sys.executable}
      - -m
      - dlrover_tpu.trainer.elastic_run
      - --nnodes
      - "4:8"
      - --node_unit
      - "4"
      - --network-check
      - --rdzv_timeout
      - "10"
      - --monitor_interval
      - "0.3"
      - --heartbeat_interval
      - "2"
      - --max_restarts
      - "1"
      - {os.path.join(REPO, 'examples', 'hybrid_train.py')}
      - --
      - --steps
      - "800"
      - --ckpt-dir
      - {os.path.join(tmp, 'ckpt')}
      - --progress
      - {progress}
"""
    path = os.path.join(tmp, "job.yaml")
    with open(path, "w") as f:
        f.write(spec)
    return path, progress


def _rows(path):
    """[(step, world, dcn, loss, ts)]"""
    if not os.path.exists(path):
        return []
    out = []
    for line in open(path):
        parts = line.strip().split(",")
        if len(parts) == 5:
            try:
                out.append((int(parts[0]), int(parts[1]),
                            int(parts[2]), float(parts[3]),
                            float(parts[4])))
            except ValueError:
                pass
    return out


def _killpg(proc, sig=signal.SIGKILL):
    try:
        os.killpg(os.getpgid(proc.pid), sig)
    except (ProcessLookupError, PermissionError):
        pass


def _wait(predicate, timeout, master, tmp, what):
    deadline = time.time() + timeout
    while time.time() < deadline:
        got = predicate()
        if got:
            return got
        assert master.poll() is None, (
            f"master died while waiting for {what}: "
            + open(os.path.join(tmp, "master.err")).read()[-3000:]
        )
        time.sleep(0.5)
    raise AssertionError(
        f"timed out waiting for {what}; master.err tail: "
        + open(os.path.join(tmp, "master.err")).read()[-3000:]
    )


def test_two_slice_preemption_composite_soak(tmp_path):
    tmp = str(tmp_path)
    dead_file = os.path.join(tmp, "dead_slices")
    spec_path, progress = _write_spec(tmp, dead_file)
    env = _strip_axon(dict(os.environ))
    port = find_free_port()
    master = subprocess.Popen(
        [sys.executable, "-m", "dlrover_tpu.master.main",
         "--job_spec", spec_path, "--port", str(port),
         "--autoscale_interval", "8"],
        cwd=REPO, env=env,
        stdout=open(os.path.join(tmp, "master.out"), "w"),
        stderr=open(os.path.join(tmp, "master.err"), "w"),
        start_new_session=True,
    )
    err_path = os.path.join(tmp, "master.err")
    try:
        # ---- phase 1: 2 slices / 8 hosts, dcn=2, training past step 6
        _wait(
            lambda: [r for r in _rows(progress)
                     if r[1] == 8 and r[2] == 2 and r[0] >= 6],
            300, master, tmp, "the 8-host/2-slice world to train",
        )
        w8 = [r for r in _rows(progress) if r[1] == 8][-1]

        # ---- T1: preempt slice 1 entirely
        with open(dead_file, "w") as f:
            f.write("1")
        w4_rows = _wait(
            lambda: [r for r in _rows(progress)
                     if r[1] == 4 and r[2] == 1],
            420, master, tmp,
            "the world to re-form at 4 with the DCN axis shrunk",
        )
        first_w4 = min(w4_rows, key=lambda r: r[0])
        # flash-checkpoint resume: not from scratch, and near where the
        # 8-world died (checkpoint cadence is 5 steps)
        assert first_w4[0] > 0, "world-4 run restarted from step 0"
        assert first_w4[0] >= w8[0] - 10, (first_w4, w8)
        # loss continuity across the slice loss: the resumed loss is in
        # family with the pre-fault loss, not the step-0 loss
        step0_loss = _rows(progress)[0][3]
        assert first_w4[3] <= max(w8[3] * 2.0, step0_loss * 0.5), (
            first_w4, w8, step0_loss,
        )

        # ---- T2: the straggler verdict against the minimum world.
        # Rank 2 (a T1 survivor) was localized by the initial
        # pre-flight check. Wait for the master's node view to settle
        # at exactly the 4 survivors (pending slice-1 relaunches would
        # let the shrink think it has room), then open the report gate
        # so the auto-scaler acts — and must VETO the shrink
        from dlrover_tpu.agent.master_client import MasterClient

        client = MasterClient(f"localhost:{port}", -1, "drill")
        # the preempted slice has no capacity to come back: manual
        # scaling (the reference's manualScaling CRD verb) retargets
        # the job at 4 so the restore loop stops provisioning into the
        # dead pool
        assert client.request_scale(4)

        def settled_at_4():
            try:
                live = [
                    n for n in client.query_running_nodes()
                    if n.get("status") == "running"
                    and not n.get("is_released")
                ]
            except Exception:
                return False
            return live if len(live) == 4 else False

        _wait(settled_at_4, 300, master, tmp,
              "the master's node view to settle at 4")
        with open(os.path.join(tmp, "report_gate"), "w") as f:
            f.write("on")

        def veto_seen():
            err = open(err_path).read()
            return re.search(
                r"Keeping \d+ stragglers: shrinking to \d+ breaks "
                r"min_nodes=4/node_unit=4", err,
            )

        _wait(veto_seen, 240, master, tmp,
              "the straggler shrink veto at min_nodes")

        # ---- T3: OOM one survivor via the master-KV fault injector
        # (pick a live rank that is neither the progress reporter 0
        # nor the straggler 2, from the master's own node view)
        pre_oom = max(r[0] for r in _rows(progress))
        live = [
            n.get("rank_index", n.get("id"))
            for n in client.query_running_nodes()
            if n.get("status") == "running"
            and not n.get("is_released")
        ]
        target = next(
            r for r in live if r not in (0, 2) and r is not None
        )
        client.kv_store_set(
            f"fault_inject/{target}", b"crash@now:137"
        )

        def oom_grown():
            err = open(err_path).read()
            return re.search(r"OOM on .*: host memory \d+ -> \d+ MB",
                             err)

        _wait(oom_grown, 300, master, tmp,
              "the master's OOM grow-and-relaunch plan")

        # the world returns to 4 and trains PAST the pre-OOM step
        _wait(
            lambda: [r for r in _rows(progress)
                     if r[1] == 4 and r[0] > pre_oom + 3],
            420, master, tmp, "the world to recover to 4 after OOM",
        )

        # ---- loss continuity over the whole soak: the latest loss is
        # below the run's starting loss despite three fault transitions
        rows = _rows(progress)
        assert rows[-1][3] < rows[0][3], (rows[0], rows[-1])
    finally:
        _killpg(master, signal.SIGTERM)
        time.sleep(1.0)
        _killpg(master)
        subprocess.run(
            ["pkill", "-9", "-f", "slice-soak"], capture_output=True,
        )
