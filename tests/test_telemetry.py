"""Unified telemetry: registry, journal, exposition, dump CLI, and the
instrumentation wired into servicer / event queue / tuning adapter."""

import json
import re
import urllib.request

import pytest

from dlrover_tpu import telemetry as T
from dlrover_tpu.telemetry.http import MetricsServer
from dlrover_tpu.telemetry.journal import EventJournal, read_journal
from dlrover_tpu.telemetry.registry import MetricsRegistry


@pytest.fixture(autouse=True)
def fresh_defaults():
    """Isolate the process-wide registry/journal per test."""
    reg = T.set_default_registry(None)
    jr = T.set_default_journal(EventJournal(None))
    yield reg, jr
    T.set_default_registry(None)
    T.set_default_journal(EventJournal(None))


# ---------------------------------------------------------------- registry


def test_counter_gauge_lifecycle():
    reg = MetricsRegistry()
    c = reg.counter("c_total", "a counter")
    c.inc()
    c.inc(2.5)
    assert c.value == 3.5
    with pytest.raises(ValueError):
        c.inc(-1)
    g = reg.gauge("g", "a gauge")
    g.set(7)
    g.dec(2)
    assert g.value == 5


def test_labels_are_distinct_series():
    reg = MetricsRegistry()
    c = reg.counter("rpc_total", "by method", ["method"])
    c.labels(method="a").inc()
    c.labels(method="b").inc(4)
    assert c.labels(method="a").value == 1
    assert c.labels(method="b").value == 4
    with pytest.raises(ValueError):
        c.labels(wrong="x")
    # a metric with declared labels refuses label-less use
    with pytest.raises(ValueError):
        c.inc()


def test_get_or_create_and_kind_conflict():
    reg = MetricsRegistry()
    a = reg.counter("same", "x")
    b = reg.counter("same", "x")
    assert a is b
    with pytest.raises(ValueError):
        reg.gauge("same", "x")
    with pytest.raises(ValueError):
        reg.counter("same", "x", ["extra"])


def test_histogram_buckets_cumulative_and_sum():
    reg = MetricsRegistry()
    h = reg.histogram("lat", "x", buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.5, 0.5, 5.0, 50.0):
        h.observe(v)
    snap = h._default_child().snapshot()
    assert snap["count"] == 5
    assert snap["sum"] == pytest.approx(56.05)
    assert dict(
        (b, c) for b, c in snap["buckets"]
    ) == {0.1: 1, 1.0: 3, 10.0: 4}  # cumulative; +Inf == count


def test_prometheus_text_format_validity():
    reg = MetricsRegistry()
    reg.counter("req_total", "requests", ["method"]).labels(
        method='get"task\n'
    ).inc()
    reg.gauge("up", "liveness").set(1)
    h = reg.histogram("lat_seconds", "latency", buckets=(0.5, 5.0))
    h.observe(0.2)
    h.observe(7.0)
    text = reg.to_prometheus_text()
    assert text.endswith("\n")
    # every non-comment line is `name{labels} value`
    sample = re.compile(
        r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? [^ ]+$'
    )
    for line in text.strip().splitlines():
        if line.startswith("#"):
            assert re.match(r"^# (HELP|TYPE) [a-zA-Z_:]", line), line
        else:
            assert sample.match(line), line
    # label escaping: quote and newline survive round-trippably
    assert r'method="get\"task\n"' in text
    # histogram exposition triplet with cumulative +Inf (the 7.0
    # observation exceeds every finite bucket and lands only in +Inf)
    assert 'lat_seconds_bucket{le="0.5"} 1' in text
    assert 'lat_seconds_bucket{le="5"} 1' in text
    assert 'lat_seconds_bucket{le="+Inf"} 2' in text
    assert "lat_seconds_sum" in text and "lat_seconds_count 2" in text
    assert "# TYPE lat_seconds histogram" in text


def test_registry_json_dump():
    reg = MetricsRegistry()
    reg.counter("c_total", "x", ["k"]).labels(k="v").inc(2)
    reg.histogram("h", "x", buckets=(1.0,)).observe(0.5)
    d = json.loads(reg.to_json())
    assert d["c_total"]["kind"] == "counter"
    assert d["c_total"]["series"]["k=v"] == 2
    assert d["h"]["series"][""]["count"] == 1


# ----------------------------------------------------------------- journal


def test_journal_seq_monotonic_and_file_roundtrip(tmp_path):
    path = str(tmp_path / "j.jsonl")
    j = EventJournal(path)
    j.record("rendezvous.complete", round=1, nodes=[0, 1])
    j.record("checkpoint.save", tier="ram", step=10)
    j.record("checkpoint.restore", tier="ram", step=10)
    seqs = [e["seq"] for e in j.events()]
    assert seqs == [1, 2, 3]
    evts = read_journal(path)
    assert [e["kind"] for e in evts] == [
        "rendezvous.complete", "checkpoint.save", "checkpoint.restore",
    ]
    for e in evts:
        assert {"seq", "ts", "host", "pid", "kind"} <= set(e)


def test_journal_kind_prefix_filter_and_payload_isolation():
    j = EventJournal(None)
    # payload keys that LOOK like envelope keys (a tuning key's `seq`
    # is a sequence LENGTH) stay in data, never shadow the envelope
    j.record("checkpoint.save", step=1, seq=999, ts=-5.0, pid=-1)
    j.record("checkpoint.restore", step=2)
    j.record("checkpointing", step=3)  # not a dotted child
    evs = j.events("checkpoint")
    assert [e["kind"] for e in evs] == [
        "checkpoint.save", "checkpoint.restore",
    ]
    assert evs[0]["seq"] == 1
    assert evs[0]["data"]["seq"] == 999 and evs[0]["data"]["step"] == 1


def test_journal_ring_bounded():
    j = EventJournal(None, capacity=5)
    for i in range(12):
        j.record("k", i=i)
    evs = j.events()
    assert len(evs) == 5
    assert [e["data"]["i"] for e in evs] == list(range(7, 12))
    assert evs[-1]["seq"] == 12  # seq keeps counting past eviction


def test_read_journal_skips_torn_lines(tmp_path):
    path = tmp_path / "j.jsonl"
    good = json.dumps({"seq": 1, "ts": 2.0, "kind": "a"})
    path.write_text(good + "\n{torn wri\n")
    evts = read_journal(str(path))
    assert len(evts) == 1 and evts[0]["kind"] == "a"


def test_journal_rotation_caps_disk_contiguous_tail(tmp_path):
    """ISSUE 17: with DLROVER_TPU_JOURNAL_MAX_MB set, the journal
    rotates to ``<path>.1`` at the cap. Disk stays bounded (current +
    one predecessor), the stitched read_journal() view keeps a
    CONTIGUOUS tail of the newest events (rotation drops oldest-first,
    never punches holes), and each rotation journals itself."""
    import os

    path = str(tmp_path / "j.jsonl")
    cap = 2000
    j = EventJournal(path, max_bytes=cap)
    for i in range(40):
        j.record("checkpoint.save", step=i, i=i)
    evts = read_journal(path)
    iv = [e["data"]["i"] for e in evts if e["kind"] == "checkpoint.save"]
    assert iv, "stitched view lost everything"
    assert iv == list(range(iv[0], 40)), (
        "rotation must keep a contiguous tail, got holes: %r" % (iv,)
    )
    assert iv[-1] == 39  # the newest event always survives
    rotated = [e for e in evts if e["kind"] == "journal.rotated"]
    assert rotated, "no journal.rotated marker in the stitched view"
    for e in rotated:
        assert e["data"]["rotated_to"] == path + ".1"
        assert e["data"]["max_bytes"] == cap
    disk = os.path.getsize(path)
    old = path + ".1"
    if os.path.exists(old):
        disk += os.path.getsize(old)
    assert disk < 3 * cap, f"disk {disk}B exceeds 3x the {cap}B cap"
    # the in-memory ring is unaffected by file rotation
    assert len(j.events("checkpoint.save")) == 40


def test_journal_resync_follows_sibling_rotation(tmp_path):
    """Two processes share one journal path; when a sibling rotates the
    file out from under us, the periodic fstat/inode resync reopens the
    live path instead of appending forever to the renamed ``.1``."""
    import os

    from dlrover_tpu.telemetry import journal as journal_mod

    path = str(tmp_path / "shared.jsonl")
    j = EventJournal(path, max_bytes=0)  # this writer never rotates
    j.record("checkpoint.save", i=-1)
    # a sibling process rotates the file away
    os.replace(path, path + ".1")
    for i in range(journal_mod._RESYNC_EVERY + 2):
        j.record("checkpoint.save", i=i)
    # post-resync events landed in the RECREATED live file itself
    # (read_journal would stitch the .1 back in and hide a regression)
    assert os.path.exists(path)
    with open(path) as f:
        live_is = [json.loads(line)["data"]["i"] for line in f]
    assert live_is and live_is[-1] == journal_mod._RESYNC_EVERY + 1
    assert -1 not in live_is  # pre-rotation events stayed in the .1


def test_read_journal_survives_rotation_mid_stitch(tmp_path,
                                                   monkeypatch):
    """ISSUE 19 satellite bugfix: a rotation landing BETWEEN the two
    opens of one stitching pass used to silently drop the rotated
    tail — the pass saw no ``.1`` yet, then opened the already-rotated
    (fresh, near-empty) live file. read_journal now re-stats ``.1``
    after the pass and retries once on an inode change."""
    import os

    from dlrover_tpu.telemetry import journal as journal_mod

    path = str(tmp_path / "j.jsonl")
    with open(path, "w") as f:
        for i in range(10):
            f.write(json.dumps(
                {"seq": i + 1, "ts": float(i), "kind": "checkpoint.save",
                 "data": {"i": i}}
            ) + "\n")

    real_open = journal_mod._open_for_read
    raced = {"done": False}

    def racing_open(p):
        if p == path and not raced["done"]:
            # the sibling writer rotates at the worst moment: after
            # this pass found no ".1", before it opens the live file
            raced["done"] = True
            os.replace(path, path + ".1")
            with open(path, "w") as f:
                f.write(json.dumps(
                    {"seq": 11, "ts": 10.0, "kind": "checkpoint.save",
                     "data": {"i": 10}}
                ) + "\n")
        return real_open(p)

    monkeypatch.setattr(journal_mod, "_open_for_read", racing_open)
    evts = read_journal(path)
    # nothing dropped: the pre-rotation tail AND the post-rotation
    # event both survive, in timeline order
    assert [e["data"]["i"] for e in evts] == list(range(11))


def test_journal_envelope_stamps_job_id(monkeypatch):
    """ISSUE 19: with DLROVER_TPU_JOB_ID set to a non-default job, the
    envelope gains a ``job`` field; the default job's envelopes stay
    byte-identical to the pre-job shape (no key at all)."""
    from dlrover_tpu.telemetry import journal as journal_mod

    monkeypatch.setenv(journal_mod.ENV_JOB_ID, "tenant-a")
    assert journal_mod.current_job_id() == "tenant-a"
    j = EventJournal(None)
    assert j.record("checkpoint.save", step=1)["job"] == "tenant-a"
    # "default" (explicit or unset) never stamps the key
    for raw in ("default", ""):
        monkeypatch.setenv(journal_mod.ENV_JOB_ID, raw)
        assert journal_mod.current_job_id() == "default"
        j = EventJournal(None)
        assert "job" not in j.record("checkpoint.save", step=1)


def test_default_journal_env_configured(tmp_path, monkeypatch):
    path = str(tmp_path / "env.jsonl")
    monkeypatch.setenv("DLROVER_TPU_JOURNAL", path)
    jr = T.set_default_journal(None)  # re-read env
    assert jr.path == path
    T.record("fault.injected", fault="crash", step=3)
    assert read_journal(path)[0]["data"]["fault"] == "crash"


# -------------------------------------------------------------- exposition


def _get(url: str) -> str:
    with urllib.request.urlopen(url, timeout=5) as r:
        return r.read().decode()


def test_http_metrics_and_journal_endpoint():
    T.counter("dlrover_up_total", "x").inc()
    T.record("rendezvous.complete", round=1)
    T.record("checkpoint.save", step=5)
    srv = MetricsServer(host="127.0.0.1").start()
    try:
        base = f"http://127.0.0.1:{srv.port}"
        text = _get(f"{base}/metrics")
        assert "# TYPE dlrover_up_total counter" in text
        assert "dlrover_up_total 1" in text
        tail = json.loads(_get(f"{base}/journal"))
        assert [e["kind"] for e in tail] == [
            "rendezvous.complete", "checkpoint.save",
        ]
        only = json.loads(_get(f"{base}/journal?kind=checkpoint&n=10"))
        assert [e["kind"] for e in only] == ["checkpoint.save"]
        assert _get(f"{base}/healthz").strip() == "ok"
        d = json.loads(_get(f"{base}/metrics.json"))
        assert d["dlrover_up_total"]["series"][""] == 1
    finally:
        srv.stop()


def test_start_metrics_server_env_off(monkeypatch):
    from dlrover_tpu.telemetry.http import start_metrics_server

    monkeypatch.setenv("DLROVER_TPU_METRICS_PORT", "off")
    assert start_metrics_server() is None


# ------------------------------------------------------------------- dump


def test_dump_cli_renders_timeline(tmp_path, capsys):
    from dlrover_tpu.telemetry import dump

    path = str(tmp_path / "j.jsonl")
    j = EventJournal(path)
    j.record("rendezvous.complete", round=1, duration_s=2.5)
    j.record("checkpoint.save", tier="ram", step=100)
    rc = dump.main([path])
    assert rc == 0
    out = capsys.readouterr().out
    assert "rendezvous.complete" in out and "round=1" in out
    assert "checkpoint.save" in out and "tier=ram" in out
    # the second line carries a +delta to the first
    assert "+0." in out.splitlines()[1]
    rc = dump.main([path, "--kind", "checkpoint", "--json"])
    out = capsys.readouterr().out.strip()
    assert rc == 0
    assert json.loads(out)["kind"] == "checkpoint.save"


def test_dump_cli_missing_file():
    from dlrover_tpu.telemetry import dump

    assert dump.main(["/nonexistent/journal.jsonl"]) == 2


# ------------------------------------------------- wired instrumentation


def test_servicer_rpc_metrics():
    from dlrover_tpu.common import comm
    from dlrover_tpu.master.servicer import MasterServicer

    servicer = MasterServicer()
    servicer.handle("ping", comm.BaseRequest())
    servicer.handle("ping", comm.BaseRequest())
    with pytest.raises(ValueError):
        servicer.handle("no_such_rpc", None)
    reg = T.default_registry()
    req = reg.get("dlrover_rpc_requests_total")
    assert req.labels(method="ping").value == 2
    lat = reg.get("dlrover_rpc_latency_seconds")
    assert lat.labels(method="ping").count == 2
    errs = reg.get("dlrover_rpc_errors_total")
    assert errs.labels(method="no_such_rpc").value == 1
    text = reg.to_prometheus_text()
    assert 'dlrover_rpc_latency_seconds_bucket{method="ping",le="+Inf"} 2' in text


def test_rdzv_round_emits_round_event_and_metrics():
    from dlrover_tpu.master.elastic_training.rdzv_manager import (
        ElasticTrainingRendezvousManager,
    )

    mgr = ElasticTrainingRendezvousManager()
    mgr.update_rdzv_params(2, 2, 0.1, 1)
    mgr.join_rendezvous(0, 1)
    mgr.join_rendezvous(1, 1)
    _, _, world = mgr.get_comm_world(0)
    assert world == {0: 1, 1: 1}
    evs = T.default_journal().events("rendezvous.complete")
    assert len(evs) == 1
    assert evs[0]["data"]["round"] == 1
    assert evs[0]["data"]["nodes"] == [0, 1]
    reg = T.default_registry()
    assert reg.get("dlrover_rdzv_rounds_total").labels(
        name="training"
    ).value == 1
    assert reg.get("dlrover_rdzv_world_size").labels(
        name="training"
    ).value == 2


def test_event_queue_counts_dropped_oldest():
    from dlrover_tpu.util.event_queue import EventQueue

    q = EventQueue(max_size=3)
    for i in range(5):
        q.put(i)
    # oldest dropped, newest kept, drops counted
    assert q.dropped == 2
    assert len(q) == 3
    assert [q.get(timeout=0.01) for _ in range(3)] == [2, 3, 4]
    assert q.get(timeout=0.01) is None
    assert T.default_registry().get(
        "dlrover_event_queue_dropped_total"
    ).value == 2


def test_tuning_events_adapter_keeps_legacy_shape():
    from dlrover_tpu.trainer import profiler

    profiler.record_tuning_event(
        kernel="flash_attention", block_q=512, block_k=256,
        source="measured", tuning_seconds=1.25,
    )
    evs = profiler.tuning_events()
    assert len(evs) == 1
    evt = evs[0]
    # the pre-journal flat-dict contract
    assert evt["block_q"] == 512 and evt["source"] == "measured"
    assert "time" in evt and "kind" not in evt and "seq" not in evt
    # and the same decision is on the structured timeline
    jevs = T.default_journal().events("tuning.decision")
    assert len(jevs) == 1 and jevs[0]["data"]["block_k"] == 256


def test_hang_detector_journals_stall():
    from dlrover_tpu.fault_tolerance.hanging_detector import (
        HangingDetector,
    )

    reports = []
    det = HangingDetector(
        report_fn=reports.append, min_timeout=0.05, multiplier=2.0
    )
    det.record_step(1)
    import time as _t

    _t.sleep(0.12)
    det._check_once()
    assert len(reports) == 1
    evs = T.default_journal().events("hang.detected")
    assert len(evs) == 1 and evs[0]["data"]["step"] == 1
    assert T.default_registry().get(
        "dlrover_hang_stalls_total"
    ).value == 1


def test_speed_monitor_sets_gauges():
    import time as _t

    from dlrover_tpu.master.monitor.speed_monitor import SpeedMonitor

    sm = SpeedMonitor()
    sm.add_running_worker("worker", 0)
    sm.add_running_worker("worker", 1)
    now = _t.time()
    sm.collect_global_step(10, now - 10)
    sm.collect_global_step(30, now)
    reg = T.default_registry()
    assert reg.get("dlrover_training_workers").value == 2
    assert reg.get("dlrover_training_global_step").value == 30
    assert reg.get(
        "dlrover_training_steps_per_second"
    ).value == pytest.approx(2.0, rel=0.01)


def test_local_master_serves_metrics_endpoint():
    """Acceptance: GET /metrics on a live master returns valid
    Prometheus text including RPC latency histograms and steps/s."""
    import time as _t

    from dlrover_tpu.common import comm
    from dlrover_tpu.master.local_master import LocalJobMaster

    master = LocalJobMaster(port=0)
    master.prepare()
    try:
        assert master.metrics_port > 0
        master.servicer.handle("ping", comm.BaseRequest())
        master.speed_monitor.add_running_worker("worker", 0)
        now = _t.time()
        master.servicer.handle(
            "report_global_step",
            comm.GlobalStep(step=5, timestamp=now - 1),
        )
        master.servicer.handle(
            "report_global_step",
            comm.GlobalStep(step=10, timestamp=now),
        )
        text = _get(
            f"http://127.0.0.1:{master.metrics_port}/metrics"
        )
        assert "# TYPE dlrover_rpc_latency_seconds histogram" in text
        assert (
            'dlrover_rpc_latency_seconds_count{method="ping"} 1'
            in text
        )
        assert (
            'dlrover_rpc_requests_total{method="report_global_step"} 2'
            in text
        )
        assert "dlrover_training_steps_per_second 5" in text
        assert "dlrover_training_workers 1" in text
    finally:
        master.stop()


def test_elastic_agent_serves_metrics_endpoint():
    """Acceptance: the agent exposes the same /metrics surface as the
    master (per-host scrape point)."""
    from dlrover_tpu.agent.elastic.training import (
        ElasticLaunchConfig,
        ElasticTrainingAgent,
    )

    T.counter("dlrover_agent_probe_total", "x").inc()
    agent = ElasticTrainingAgent(
        ElasticLaunchConfig(entrypoint="true"), master_client=None
    )
    try:
        assert agent._metrics_server is not None
        port = agent._metrics_server.port
        text = _get(f"http://127.0.0.1:{port}/metrics")
        assert "# TYPE dlrover_agent_probe_total counter" in text
    finally:
        agent.stop()
    assert agent._metrics_server is None
