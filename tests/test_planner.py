"""Shard planner tests (AT7 parity: mip_tp_planner's role via exact
rule-table search)."""

import jax
import numpy as np
import pytest

from dlrover_tpu.auto.planner import plan_rules_for_llama
from dlrover_tpu.models import llama
from dlrover_tpu.parallel.mesh import create_mesh


def test_tiny_model_plans_replication():
    """When everything fits replicated, the cheapest plan is DDP-style
    (no param collectives)."""
    cfg = llama.llama_tiny()
    mesh = create_mesh([("data", 2), ("fsdp", 4)])
    report = plan_rules_for_llama(cfg, mesh, 8, 32, hbm_bytes=16e9)
    planned = {
        k: v for k, v in report.rules.items()
        if k != "batch" and v is not None
    }
    assert planned == {}  # params replicated
    # comm is the DDP grad all-reduce only (no param gather/scatter):
    # ~2x param volume + one fused-collective dispatch
    assert report.comm_seconds < 1e-3


def test_big_model_small_hbm_plans_sharding():
    """A 7B model on a 16GB chip cannot replicate: the plan must shard
    params over fsdp and still fit."""
    cfg = llama.llama2_7b()
    mesh = create_mesh([("data", 1), ("fsdp", 8)])
    report = plan_rules_for_llama(cfg, mesh, 8, 2048, hbm_bytes=16e9)
    assert any(
        v == "fsdp" for k, v in report.rules.items() if k != "batch"
    )
    assert report.memory_bytes < 16e9


def test_infeasible_raises():
    cfg = llama.llama2_7b()
    mesh = create_mesh([("data", 8)])  # no shardable axis
    with pytest.raises(ValueError, match="no feasible"):
        plan_rules_for_llama(cfg, mesh, 8, 2048, hbm_bytes=16e9)


def test_divisibility_respected():
    """num_heads=6 is not divisible by tensor=4: the planner must not
    assign heads->tensor."""
    cfg = llama.LlamaConfig(
        vocab_size=512, hidden_size=96, intermediate_size=256,
        num_layers=2, num_heads=6, num_kv_heads=2, max_seq_len=64,
    )
    mesh = create_mesh([("data", 2), ("tensor", 4)])
    report = plan_rules_for_llama(
        cfg, mesh, 8, 32, hbm_bytes=2e6,  # force sharding
    )
    assert report.rules.get("heads") != "tensor"
    assert report.rules.get("kv_heads") != "tensor"


def test_planned_rules_execute_in_sharded_trainer():
    """A synthesized table is a real strategy: train one step with it
    on the 8-device mesh."""
    import optax

    from dlrover_tpu.parallel import sharding as shd
    from dlrover_tpu.trainer.sharded import ShardedTrainer

    cfg = llama.llama_tiny()
    mesh = create_mesh([("data", 2), ("fsdp", 4)])
    # small HBM forces a sharded plan (tiny llama: ~0.85 MB for
    # params+opt+grad replicated)
    report = plan_rules_for_llama(cfg, mesh, 8, 16, hbm_bytes=0.5e6)
    assert any(
        v for k, v in report.rules.items() if k != "batch"
    )
    # ADVICE r2 (medium): the batch rule must keep the data axis on a
    # data>1 mesh — batch shards over data*fsdp = all 8 devices
    assert set(report.rules["batch"]) == {"data", "fsdp"}
    shd.STRATEGIES["planned"] = lambda: dict(report.rules)
    try:
        trainer = ShardedTrainer(
            lambda p, b: llama.next_token_loss(p, b, cfg),
            lambda k: llama.init_params(k, cfg),
            llama.param_axes(cfg), mesh, strategy="planned",
            optimizer=optax.adamw(1e-3),
        )
        params, opt_state = trainer.init(jax.random.key(0))
        tokens = np.random.randint(0, cfg.vocab_size, (8, 16),
                                   dtype=np.int32)
        batch = trainer.shard_batch(
            trainer.microbatch((tokens, tokens))
        )
        _, _, loss = trainer.train_step(params, opt_state, batch)
        assert np.isfinite(float(loss))
    finally:
        shd.STRATEGIES.pop("planned", None)
