"""Trace-propagation chaos drill (ISSUE 17 acceptance): THREE real OS
processes — a worker, an aggregator relay, and a gRPC master — each
armed only by exporting ``DLROVER_TPU_TRACE_DIR``, produce ONE merged
Chrome trace in which the causal chain

    worker ``report_node_status`` span
        -> relay ``relay.forward`` span
            -> master ``rpc.report_relay_batch`` span

is asserted by span parent/child IDs (W3C context riding gRPC metadata
at each hop), exactly as an operator would see it from
``python -m dlrover_tpu.telemetry.dump <dir> --trace``.
"""

import json
import os
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_MASTER = """
import os, sys, time
from dlrover_tpu.common.constants import NodeStatus, NodeType
from dlrover_tpu.common.node import Node
from dlrover_tpu.master.node.dist_job_manager import DistributedJobManager
from dlrover_tpu.master.monitor.speed_monitor import SpeedMonitor
from dlrover_tpu.master.servicer import create_master_service

speed = SpeedMonitor()
jm = DistributedJobManager(speed_monitor=speed, heartbeat_timeout=3600.0)
jm._node_managers[NodeType.WORKER].update_nodes({
    0: Node(NodeType.WORKER, 0, status=NodeStatus.RUNNING),
})
server, servicer = create_master_service(
    0, job_manager=jm, speed_monitor=speed,
)
server.start()
print(f"PORT {server.port}", flush=True)
stop = sys.argv[1]
while not os.path.exists(stop):
    time.sleep(0.05)
server.stop(grace=0.2)
servicer.close()
"""

_WORKER = """
import sys, time
from dlrover_tpu.agent.master_client import MasterClient
from dlrover_tpu.agent.status_reporter import DeltaTracker
from dlrover_tpu.common.constants import NodeType
from dlrover_tpu.telemetry import tracing

relay_addr = sys.argv[1]
cli = MasterClient(relay_addr, node_id=0, node_type=NodeType.WORKER,
                   timeout=10.0)
tracker = DeltaTracker(incarnation=0)
rep = tracker.compose(time.time(), step=7, pid=4242, host="drill-host")
rep.node_id, rep.node_type = 0, NodeType.WORKER
tracing.set_step(7)
with tracing.span("report_node_status", {"node": 0}):
    ack = cli.report_node_status(rep)
assert ack is not None and ack.accepted, ack
cli.close()
"""


def _env(trace_dir):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["DLROVER_TPU_TRACE_DIR"] = trace_dir
    env["JAX_PLATFORMS"] = "cpu"
    return env


def _read_port(proc, tag):
    line = proc.stdout.readline().strip()
    assert line.startswith("PORT "), f"{tag}: bad handshake {line!r}"
    return int(line.split()[1])


def _spans_by_name(trace_dir):
    from dlrover_tpu.telemetry import tracing

    out = {}
    for rec in tracing.read_trace_dir(trace_dir):
        out.setdefault(rec["name"], []).append(rec)
    return out


def test_three_process_causal_chain(tmp_path):
    trace_dir = str(tmp_path / "trace")
    stop = str(tmp_path / "stop")
    env = _env(trace_dir)
    procs = []
    try:
        master = subprocess.Popen(
            [sys.executable, "-c", _MASTER, stop], cwd=REPO, env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        procs.append(master)
        master_port = _read_port(master, "master")
        relay = subprocess.Popen(
            [sys.executable, "-m", "dlrover_tpu.agent.relay",
             "--master_addr", f"localhost:{master_port}",
             "--relay_id", "0", "--interval", "0.3"],
            cwd=REPO, env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        procs.append(relay)
        relay_port = _read_port(relay, "relay")
        worker = subprocess.run(
            [sys.executable, "-c", _WORKER, f"localhost:{relay_port}"],
            cwd=REPO, env=env, capture_output=True, text=True,
            timeout=60,
        )
        assert worker.returncode == 0, worker.stderr[-2000:]
        # the relay forwards on its own clock; wait for the master's
        # handler span to land in the shared trace dir
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            if "rpc.report_relay_batch" in _spans_by_name(trace_dir):
                break
            time.sleep(0.1)
        else:
            pytest.fail("master handler span never appeared")
    finally:
        open(stop, "w").close()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()

    by_name = _spans_by_name(trace_dir)
    report = by_name["report_node_status"][0]
    forward = by_name["relay.forward"][0]
    batch = by_name["rpc.report_relay_batch"][0]
    # three DISTINCT real processes, one span file each
    assert len({report["pid"], forward["pid"], batch["pid"]}) == 3
    # the causal chain, by ids: one trace, parent -> child at each hop
    assert report["trace"] == forward["trace"] == batch["trace"]
    assert forward["parent"] == report["span"]
    assert batch["parent"] == forward["span"]
    # the worker's step stamp survives into its span record
    assert report["step"] == 7

    # and the operator view: dump --trace renders the merged chain
    # with cross-process flow arrows for both hops
    from dlrover_tpu.telemetry import dump

    out = str(tmp_path / "chain.json")
    assert dump.main([trace_dir, "--trace", "-o", out]) == 0
    with open(out) as f:
        trace = json.load(f)
    flows = [e for e in trace["traceEvents"] if e["ph"] == "s"]
    flow_pids = {e["pid"] for e in flows}
    assert {report["pid"], forward["pid"]} <= flow_pids
