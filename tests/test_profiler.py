"""Model profiler (U1): XLA cost analysis -> stats pipeline round trip."""

import time
import types

import jax
import numpy as np
import optax
import pytest

from dlrover_tpu.models import llama
from dlrover_tpu.trainer import profiler
from dlrover_tpu.trainer.elastic import ElasticTrainer


def _tiny_setup():
    cfg = llama.llama_tiny()
    params = jax.jit(lambda r: llama.init_params(r, cfg))(jax.random.key(0))
    loss = lambda p, b: llama.next_token_loss(p, b, cfg)  # noqa: E731
    return cfg, params, loss


def test_profile_step_counts_flops_and_params():
    cfg, params, loss = _tiny_setup()
    opt = optax.adamw(1e-3)
    opt_state = jax.eval_shape(opt.init, params)

    def step(p, s, batch):
        l, g = jax.value_and_grad(loss)(p, batch)
        u, s = opt.update(g, s, p)
        return optax.apply_updates(p, u), s, l

    tokens = np.zeros((2, 64), np.int32)
    abstract = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), params
    )
    batch = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), (tokens, tokens)
    )
    prof = profiler.profile_step(
        step, abstract, opt_state, batch, params=params
    )
    assert prof.param_count == llama.param_count(cfg)
    assert prof.variable_count == len(jax.tree.leaves(params))
    # a train step must cost at least 6*N flops per token-ish; just check
    # XLA counted something plausible (> fwd matmul flops of the embed)
    assert prof.flops > 1e6
    assert prof.hbm_bytes > 0
    kwargs = prof.to_model_info_kwargs(batch_size=2, seq_len=64)
    assert kwargs["param_count"] == prof.param_count
    assert kwargs["extra"]["hbm_bytes"] == prof.hbm_bytes


def test_elastic_trainer_reports_profile_to_master():
    """ElasticTrainer.report_model_profile -> gRPC -> stats reporter."""
    from dlrover_tpu.agent.master_client import MasterClient
    from dlrover_tpu.common.constants import NodeType
    from dlrover_tpu.master.dist_master import DistributedJobMaster

    job_args = types.SimpleNamespace(
        job_name="profjob", node_num=1, node_unit=1,
        distribution_strategy="allreduce",
    )
    master = DistributedJobMaster(port=0, job_args=job_args)
    master._server.start()
    try:
        client = MasterClient(master.addr, node_id=0,
                              node_type=NodeType.WORKER)
        cfg, params, loss = _tiny_setup()
        trainer = ElasticTrainer(
            loss, optax.adamw(1e-3), max_nodes=1, cur_nodes=1,
            master_client=client,
        )
        tokens = np.zeros((2, 64), np.int32)
        batches = trainer.microbatch((tokens, tokens))
        prof = trainer.report_model_profile(
            params, batches, batch_size=2, seq_len=64
        )
        assert prof is not None and prof.flops > 0

        deadline = time.time() + 5
        mm = master.stats_reporter.model_metric
        while mm.op_stats.flops == 0 and time.time() < deadline:
            time.sleep(0.05)
            mm = master.stats_reporter.model_metric
        assert mm.op_stats.flops == prof.flops
        assert mm.tensor_stats.total_variable_size == prof.param_count
        assert mm.batch_size == 2 and mm.seq_len == 64
        client.close()
    finally:
        master._server.stop(grace=0.5)


def test_trace_capture_writes_timeline(tmp_path):
    """TraceCapture (trainer/profiler.py) wraps jax.profiler into a
    step-windowed TensorBoard trace (parity role: AProfiler timeline
    export)."""
    import glob
    import os

    import jax.numpy as jnp

    from dlrover_tpu.trainer.profiler import TraceCapture

    trace_dir = str(tmp_path / "trace")
    with TraceCapture(trace_dir, start_step=2, num_steps=2) as tc:
        x = jnp.ones((8, 8))
        for step in range(1, 6):
            x = (x @ x).block_until_ready()
            tc.step(step)
    files = glob.glob(
        os.path.join(trace_dir, "**", "*"), recursive=True
    )
    assert any(os.path.isfile(f) for f in files), files


def test_trace_capture_from_env(monkeypatch, tmp_path):
    from dlrover_tpu.trainer.profiler import TraceCapture

    monkeypatch.delenv("DLROVER_TRACE_DIR", raising=False)
    assert TraceCapture.from_env() is None
    monkeypatch.setenv("DLROVER_TRACE_DIR", str(tmp_path))
    monkeypatch.setenv("DLROVER_TRACE_STEPS", "5")
    tc = TraceCapture.from_env()
    assert tc is not None and tc._stop_after == 6
