"""Timed two-host elastic failover drill with a real jax.distributed world.

The north-star SLA (BASELINE.md): recovery from a lost host in <60s.
Topology: one DistributedJobMaster + two launcher agents on this machine,
each supervising a training process; the two processes form a real
2-process jax.distributed world (CPU backend, gloo collectives) and psum
gradients every step. The drill SIGKILLs host 1's whole process group
mid-run and asserts host 0:
  - detects the loss (coordination-service heartbeat + master watchdog
    pruning the dead node -> num_nodes_waiting shrink signal),
  - re-rendezvouses into a 1-node world,
  - restores from the flash checkpoint,
  - resumes stepping, all within 60 seconds of the kill.

Parity: the reference's node-failure system tests
(.github/actions/dlrover-system-test-*) and SURVEY §4.3's
multi-node-without-cluster pattern.
"""

import os
import re
import signal
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _strip_axon(env):
    """Remove the TPU-plugin sitecustomize: it initializes jax backends at
    interpreter start, which breaks multi-process jax.distributed (the
    backend must be created AFTER the world forms)."""
    parts = [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
             if p and "axon" not in p]
    env["PYTHONPATH"] = os.pathsep.join(parts + [REPO])
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    return env


def _start_master(tmp):
    env = _strip_axon(dict(os.environ))
    out_path = os.path.join(tmp, "master.out")
    proc = subprocess.Popen(
        [sys.executable, "-m", "dlrover_tpu.master.main",
         "--platform", "tpu_vm", "--node_num", "2", "--port", "0",
         "--heartbeat_timeout", "8"],
        cwd=REPO, env=env,
        stdout=open(out_path, "w"), stderr=open(
            os.path.join(tmp, "master.err"), "w"),
        start_new_session=True,
    )
    # poll the log file instead of readline() so a hung master can't block
    # past the deadline
    deadline = time.time() + 30
    port = None
    while time.time() < deadline:
        m = re.search(r"DLROVER_TPU_MASTER_PORT=(\d+)",
                      open(out_path).read())
        if m:
            port = int(m.group(1))
            break
        if proc.poll() is not None:
            raise RuntimeError("master died during startup")
        time.sleep(0.1)
    assert port, "master did not report a port"
    return proc, f"localhost:{port}"


def _start_agent(tmp, rank, master_addr, steps=200):
    env = _strip_axon(dict(os.environ))
    # fast peer-death detection inside the training process
    env["DLROVER_TPU_DIST_HEARTBEAT_TIMEOUT"] = "10"
    progress = os.path.join(tmp, f"progress_{rank}.txt")
    out = os.path.join(tmp, f"out_{rank}.txt")
    log = open(os.path.join(tmp, f"agent_{rank}.log"), "w")
    proc = subprocess.Popen(
        [sys.executable, "-m", "dlrover_tpu.trainer.elastic_run",
         "--master_addr", master_addr,
         "--nnodes", "1:2", "--node_rank", str(rank),
         "--rdzv_timeout", "2", "--monitor_interval", "0.3",
         "--heartbeat_interval", "2", "--max_restarts", "3",
         os.path.join(REPO, "examples", "dist_train.py"), "--",
         "--steps", str(steps),
         "--ckpt-dir", os.path.join(tmp, f"ckpt_{rank}"),
         "--progress", progress, "--out", out],
        cwd=REPO, env=env, stdout=log, stderr=subprocess.STDOUT,
        start_new_session=True,
    )
    return proc, progress, out


def _read_progress(path):
    """[(step, world, loss, ts)] parsed from the progress file."""
    if not os.path.exists(path):
        return []
    rows = []
    for line in open(path):
        parts = line.strip().split(",")
        if len(parts) == 4:
            rows.append((int(parts[0]), int(parts[1]),
                         float(parts[2]), float(parts[3])))
    return rows


def _killpg(proc, sig=signal.SIGKILL):
    try:
        os.killpg(os.getpgid(proc.pid), sig)
    except (ProcessLookupError, PermissionError):
        pass


def test_two_node_failover_under_60s(tmp_path):
    tmp = str(tmp_path)
    master_proc, master_addr = _start_master(tmp)
    a0 = a1 = None
    try:
        a0, progress0, out0 = _start_agent(tmp, 0, master_addr)
        a1, progress1, _ = _start_agent(tmp, 1, master_addr)

        # phase 1: the 2-process world trains past a checkpoint (step 5)
        deadline = time.time() + 120
        while time.time() < deadline:
            rows = _read_progress(progress0)
            if any(r[0] >= 7 and r[1] == 2 for r in rows):
                break
            assert a0.poll() is None, open(
                os.path.join(tmp, "agent_0.log")).read()[-2000:]
            time.sleep(0.2)
        rows = _read_progress(progress0)
        assert any(r[1] == 2 for r in rows), (
            f"2-process world never formed: {rows[-5:]}")
        assert any(r[0] >= 7 and r[1] == 2 for r in rows), (
            f"did not reach step 7 in the 2-node world: {rows[-5:]}")

        # phase 2: kill host 1 (agent + its training process)
        t_kill = time.time()
        _killpg(a1)
        step_at_kill = max(r[0] for r in rows)

        # phase 3: host 0 must resume stepping in a 1-process world
        recovery_seconds = None
        deadline = t_kill + 120
        while time.time() < deadline:
            rows = _read_progress(progress0)
            resumed = [r for r in rows
                       if r[1] == 1 and r[3] > t_kill]
            if resumed:
                recovery_seconds = resumed[0][3] - t_kill
                break
            time.sleep(0.2)
        assert recovery_seconds is not None, (
            "survivor never resumed in a 1-node world; tail: "
            + str(_read_progress(progress0)[-5:])
            + open(os.path.join(tmp, "agent_0.log")).read()[-3000:]
        )
        print(f"RECOVERY_SECONDS={recovery_seconds:.1f} "
              f"(killed at step {step_at_kill})")
        assert recovery_seconds < 60.0, (
            f"recovery took {recovery_seconds:.1f}s, SLA is <60s")

        # the resumed run restored from a flash checkpoint, not step 0
        log0 = open(os.path.join(tmp, "agent_0.log")).read()
        assert "RESTORED from step" in log0
        m = re.search(r"RESTORED from step (\d+)", log0)
        assert int(m.group(1)) >= 5
    finally:
        for p in (a0, a1):
            if p is not None:
                _killpg(p)
        _killpg(master_proc, signal.SIGTERM)
        time.sleep(0.5)
        _killpg(master_proc)
