"""Brain optimization algorithms (VERDICT r2 Missing #2): memory-trend
resource plans, OOM-history preemptive growth, auto_accelerate warm
start. Parity roles: dlrover/go/brain/pkg/optimizer/implementation/
optalgorithm/optimize_job_worker_resource.go + the Brain feeding the
acceleration engine."""

import numpy as np
import pytest

from dlrover_tpu.brain.algorithms import (
    MEMORY_MARGIN,
    plan_worker_resource,
    predict_peak_memory_mb,
    warm_start_strategies,
)
from dlrover_tpu.brain.client import BrainClient
from dlrover_tpu.common.constants import NodeExitReason
from dlrover_tpu.common.node import NodeResource
from dlrover_tpu.master.stats.reporter import JobMeta
from dlrover_tpu.master.stats.training_metrics import RuntimeMetric
from dlrover_tpu.util.state_store import MemoryStore


def _report_run(client, job_name, uuid, mem_points, worker_num=4,
                speed=2.0, exit_reason=None):
    job = JobMeta(uuid=uuid, name=job_name)
    for step, mem in mem_points:
        client.report_runtime_stats(job, RuntimeMetric(
            running_nodes=[{"used_memory_mb": mem}],
            worker_num=worker_num, global_step=step, speed=speed,
            timestamp=float(step),
        ))
    if exit_reason:
        client.report_exit_reason(job, exit_reason)


class TestMemoryTrend:
    def test_flat_usage_predicts_peak(self):
        samples = [
            {"global_step": s, "max_used_memory_mb": 1000}
            for s in range(0, 100, 10)
        ]
        peak, pred = predict_peak_memory_mb(samples)
        assert peak == 1000
        assert pred == pytest.approx(1000, rel=0.01)

    def test_growing_usage_extrapolates(self):
        # 10 MB per step of growth over steps 0..90: trend must predict
        # past the observed 1900 peak
        samples = [
            {"global_step": s, "max_used_memory_mb": 1000 + 10 * s}
            for s in range(0, 100, 10)
        ]
        peak, pred = predict_peak_memory_mb(samples)
        assert peak == 1900
        assert pred > 1900
        # horizon = half the observed range (45 steps) at slope 10
        assert pred == pytest.approx(1900 + 450, rel=0.05)

    def test_no_memory_samples(self):
        peak, pred = predict_peak_memory_mb(
            [{"global_step": 1, "speed": 2.0}]
        )
        assert peak == 0 and pred == 0


class TestResourcePlan:
    def test_archive_changes_initial_plan(self):
        """VERDICT done-criterion: archives measurably change the
        initial ResourcePlan."""
        client = BrainClient(MemoryStore())
        base = NodeResource(cpu=8, memory=2000)
        # no history -> no plan
        assert plan_worker_resource(client, "jobA", base) is None
        # history with growing memory -> planned above observed peak
        _report_run(
            client, "jobA", "run1",
            [(s, 1500 + 5 * s) for s in range(0, 200, 10)],
        )
        planned = plan_worker_resource(client, "jobA", base)
        assert planned is not None
        peak = 1500 + 5 * 190
        assert planned.memory > peak  # margin + trend beyond the peak
        assert planned.cpu == base.cpu  # only memory is planned

    def test_oom_history_grows_preemptively(self):
        client = BrainClient(MemoryStore())
        base = NodeResource(cpu=8, memory=2000)
        _report_run(
            client, "jobB", "run1", [(s, 1000) for s in range(0, 50, 5)],
            exit_reason=NodeExitReason.OOM,
        )
        grown = plan_worker_resource(client, "jobB", base)
        # one OOM exit: max(trend*margin, base) * 1.5 growth — the OOM
        # happened at the base allocation, so growth applies past it
        assert grown.memory == pytest.approx(
            max(1000 * MEMORY_MARGIN, 2000) * 1.5, rel=0.01
        )
        # two OOM exits compound
        _report_run(
            client, "jobB", "run2", [(s, 1000) for s in range(0, 50, 5)],
            exit_reason=NodeExitReason.OOM,
        )
        grown2 = plan_worker_resource(client, "jobB", base)
        assert grown2.memory > grown.memory

    def test_oom_history_without_memory_samples_grows_base(self):
        client = BrainClient(MemoryStore())
        base = NodeResource(memory=4000)
        _report_run(client, "jobC", "run1", [],
                    exit_reason=NodeExitReason.OOM)
        planned = plan_worker_resource(client, "jobC", base)
        assert planned.memory == 6000  # base * 1.5

    def test_local_optimizer_initial_plan_uses_memory_trend(self):
        from dlrover_tpu.master.resource.local_optimizer import (
            TPULocalOptimizer,
        )
        from dlrover_tpu.scheduler.job_spec import JobArgs

        client = BrainClient(MemoryStore())
        _report_run(
            client, "jobD", "run1",
            [(s, 3000) for s in range(0, 100, 10)],
            worker_num=2,
        )
        args = JobArgs(
            job_name="jobD", node_num=2,
            node_resource=NodeResource(cpu=4, memory=1000),
        )
        opt = TPULocalOptimizer(args, brain_client=client)
        plan = opt.init_job_resource()
        group = plan.node_group_resources["worker"]
        assert group.node_resource.memory == pytest.approx(
            3000 * MEMORY_MARGIN, rel=0.01
        )


class TestStrategyWarmStart:
    def _cfg(self):
        from dlrover_tpu.models import llama

        return llama.llama_tiny()

    def test_warm_start_cuts_dryrun_count(self, monkeypatch):
        """VERDICT done-criterion: a warm-started search measures fewer
        dryruns than a cold BO search and still lands on the winner."""
        import dlrover_tpu.auto.accelerate as acc
        from dlrover_tpu.auto.accelerate import auto_accelerate

        client = BrainClient(MemoryStore())
        cfg = self._cfg()
        calls = []
        real_dryrun = acc.dryrun_strategy

        def counting_dryrun(cfg_, s, gb, sl, devices=None, **kw):
            calls.append(s)
            return real_dryrun(cfg_, s, gb, sl, devices, steps=2, **kw)

        monkeypatch.setattr(acc, "dryrun_strategy", counting_dryrun)

        cold = auto_accelerate(
            cfg, global_batch=8, seq_len=32, bo_iters=2,
            dryrun_top_k=2, job_name="warmjob", brain_client=client,
        )
        cold_count = len(calls)
        assert cold_count >= 3  # n_init + BO iterations

        calls.clear()
        warm = auto_accelerate(
            cfg, global_batch=8, seq_len=32, bo_iters=2,
            dryrun_top_k=2, job_name="warmjob", brain_client=client,
        )
        warm_count = len(calls)
        assert warm_count <= 2  # archived winner + analytic top-1
        assert warm_count < cold_count
        assert warm.strategy is not None

    def test_archive_roundtrip(self):
        from dlrover_tpu.auto.strategy import Strategy

        client = BrainClient(MemoryStore())
        s = Strategy(mesh_spec=(("data", 8),), sharding="ddp")
        client.report_strategy(
            JobMeta(uuid="u1", name="jobE"), s.to_json(), 0.5
        )
        docs = warm_start_strategies(client, "jobE")
        assert len(docs) == 1
        assert Strategy.from_json(docs[0]["strategy_json"]) == s
        assert docs[0]["measured_seconds"] == 0.5


def test_runtime_stats_capture_max_used_memory():
    client = BrainClient(MemoryStore())
    job = JobMeta(uuid="u", name="jobF")
    client.report_runtime_stats(job, RuntimeMetric(
        running_nodes=[
            {"used_memory_mb": 100}, {"used_memory_mb": 900},
        ],
        worker_num=2, global_step=5, speed=1.0, timestamp=1.0,
    ))
    samples = client.get_runtime_stats("jobF", "u")
    assert samples[0]["max_used_memory_mb"] == 900
