"""ISSUE 4: span tracing, Chrome merge, flight recorder, degraded
/healthz, straggler scorer, and the journal event-name lint."""

import gc
import json
import os
import pathlib
import signal
import subprocess
import sys
import time
import urllib.error
import urllib.request

import pytest

from dlrover_tpu import telemetry as T
from dlrover_tpu.telemetry import flight_recorder, tracing
from dlrover_tpu.telemetry import http as thttp
from dlrover_tpu.telemetry.journal import EventJournal

REPO_ROOT = pathlib.Path(__file__).resolve().parents[1]


@pytest.fixture(autouse=True)
def fresh_state():
    """Isolate the process-wide tracing/health/registry/journal state."""
    tracing.disable()
    tracing.clear()
    tracing.set_step(-1)
    thttp.set_health_check(None)
    T.set_default_registry(None)
    T.set_default_journal(EventJournal(None))
    yield
    tracing.disable()
    tracing.enable(capacity=4096)  # restore the default ring size
    tracing.disable()
    tracing.clear()
    tracing.set_step(-1)
    thttp.set_health_check(None)
    flight_recorder.uninstall_signal_hook()
    T.set_default_registry(None)
    T.set_default_journal(EventJournal(None))


def _get(url: str) -> str:
    with urllib.request.urlopen(url, timeout=5) as r:
        return r.read().decode()


# ------------------------------------------------------------ span basics


def test_disabled_span_is_shared_noop_and_allocation_free():
    assert not tracing.enabled()
    # the disabled path hands back ONE shared object — nothing is
    # constructed per call site
    assert tracing.span("a") is tracing.span("b")

    def run(n):
        span = tracing.span
        for _ in range(n):
            with span("x"):
                pass

    run(100)  # warm caches/freelists
    # allocation-free: a couple of blocks of slack for interpreter
    # noise, nothing proportional to the 2000 calls. Noise from
    # unrelated threads is strictly additive, so best-of-3 keeps the
    # invariant sharp (a real per-call allocation taints every trial)
    # without failing on a stray background wakeup mid-measurement.
    deltas = []
    for _ in range(3):
        gc.collect()
        before = sys.getallocatedblocks()
        run(2000)
        gc.collect()
        deltas.append(sys.getallocatedblocks() - before)
        if min(deltas) <= 4:
            break
    assert min(deltas) <= 4, deltas
    assert len(tracing.tail(10)) == 0  # and nothing was recorded


def test_span_records_carry_journal_envelope():
    tracing.enable()
    tracing.set_step(41)
    with tracing.span("data_load", {"batch": 7}):
        time.sleep(0.002)
    recs = tracing.tail(5)
    assert len(recs) == 1
    rec = recs[0]
    assert rec["name"] == "data_load"
    assert rec["pid"] == os.getpid()
    assert {"host", "proc", "tid", "thread", "ts", "dur"} <= set(rec)
    assert rec["step"] == 41
    assert rec["attrs"] == {"batch": 7}
    assert rec["dur"] >= 0.002


def test_span_marks_errors_and_propagates():
    tracing.enable()
    with pytest.raises(RuntimeError):
        with tracing.span("boom"):
            raise RuntimeError("x")
    assert tracing.tail(1)[0]["error"] is True


def test_ring_wraparound_keeps_newest():
    tracing.enable(capacity=8)
    for i in range(20):
        tracing.add_span(f"s{i}", 100.0 + i, 0.001)
    recs = tracing.tail(100)
    assert len(recs) == 8
    assert [r["name"] for r in recs] == [f"s{i}" for i in range(12, 20)]


def test_add_span_retroactive_and_disabled_noop():
    tracing.add_span("off", 1.0, 1.0)  # disabled: dropped
    assert tracing.tail(5) == []
    tracing.enable()
    tracing.add_span("rdzv.training", 1000.0, 2.5, {"round": 3})
    rec = tracing.tail(1)[0]
    assert rec["ts"] == 1000.0 and rec["dur"] == 2.5
    assert rec["attrs"]["round"] == 3


def test_summarize_aggregates_by_name():
    tracing.enable()
    for ms in (10, 20, 30):
        tracing.add_span("data", 100.0, ms / 1e3)
    tracing.add_span("dispatch", 100.0, 0.005)
    agg = tracing.summarize(("data",))
    assert set(agg) == {"data"}
    assert agg["data"]["count"] == 3
    assert agg["data"]["mean_ms"] == pytest.approx(20.0)
    assert agg["data"]["max_ms"] == pytest.approx(30.0)


# -------------------------------------------------- chrome export + merge


def test_write_through_and_chrome_merge(tmp_path):
    d = str(tmp_path / "trace")
    tracing.enable(trace_dir=d)
    tracing.set_step(3)
    with tracing.span("step", {"k": "v"}):
        pass
    tracing.disable()
    files = os.listdir(d)
    assert len(files) == 1 and files[0].startswith("spans-")
    trace = tracing.merge_trace_dir(d)
    evts = trace["traceEvents"]
    xs = [e for e in evts if e["ph"] == "X"]
    assert len(xs) == 1
    assert xs[0]["name"] == "step"
    args = xs[0]["args"]
    assert args["k"] == "v" and args["step"] == 3
    # root span: carries its trace/span ids but no parent edge
    assert args["trace"] and args["span"] and "parent" not in args
    assert xs[0]["pid"] == os.getpid()
    # process/thread metadata present for the trace viewer
    metas = {e["name"] for e in evts if e["ph"] == "M"}
    assert {"process_name", "thread_name"} <= metas


_CHILD = """
import sys
from dlrover_tpu.telemetry import tracing
tracing.enable(trace_dir=sys.argv[1])
tracing.set_step(int(sys.argv[2]))
with tracing.span("work", {"proc": sys.argv[2]}):
    pass
tracing.add_span("phase", 1000.0 + float(sys.argv[2]), 0.25)
tracing.disable()
"""


def _child_env():
    env = dict(os.environ)
    env["PYTHONPATH"] = (
        str(REPO_ROOT) + os.pathsep + env.get("PYTHONPATH", "")
    )
    env.pop("DLROVER_TPU_TRACE", None)
    env.pop("DLROVER_TPU_TRACE_DIR", None)
    return env


def test_cross_process_merge_two_pids_deterministic(tmp_path):
    """Acceptance: a 2-process drill yields ONE merged Chrome trace
    with spans from both pids, and the merge is deterministic."""
    d = str(tmp_path / "trace")
    for idx in ("1", "2"):
        subprocess.run(
            [sys.executable, "-c", _CHILD, d, idx],
            check=True, env=_child_env(), timeout=60,
        )
    merged = tracing.merge_trace_dir(d)
    xs = [e for e in merged["traceEvents"] if e["ph"] == "X"]
    pids = {e["pid"] for e in xs}
    assert len(pids) == 2
    assert sorted(e["name"] for e in xs) == [
        "phase", "phase", "work", "work",
    ]
    # determinism: merging the same files twice is byte-identical
    a = json.dumps(merged, sort_keys=True)
    b = json.dumps(tracing.merge_trace_dir(d), sort_keys=True)
    assert a == b
    # events are globally time-ordered across processes
    ts = [e["ts"] for e in xs]
    assert ts == sorted(ts)


def test_dump_cli_trace_mode(tmp_path, capsys):
    from dlrover_tpu.telemetry import dump

    d = str(tmp_path / "trace")
    tracing.enable(trace_dir=d)
    with tracing.span("alpha"):
        pass
    tracing.disable()
    out_file = str(tmp_path / "merged.json")
    assert dump.main([d, "--trace", "-o", out_file]) == 0
    err = capsys.readouterr().err
    assert "1 spans from 1 process(es)" in err
    with open(out_file) as f:
        trace = json.load(f)
    assert any(
        e["name"] == "alpha" for e in trace["traceEvents"]
        if e["ph"] == "X"
    )
    # stdout mode
    assert dump.main([d, "--trace"]) == 0
    assert "alpha" in capsys.readouterr().out
    # missing dir is a clean error, not a traceback
    assert dump.main([str(tmp_path / "nope"), "--trace"]) == 2


FIXTURE_TRACE = os.path.join(
    os.path.dirname(__file__), "fixtures", "trace"
)


def _merged(tmp_path, capsys, *flags):
    """Run dump --trace over the committed 2-process fixture with the
    given filter flags; return (trace dict, stderr)."""
    from dlrover_tpu.telemetry import dump

    out = str(tmp_path / "t.json")
    assert dump.main([FIXTURE_TRACE, "--trace", "-o", out, *flags]) == 0
    err = capsys.readouterr().err
    with open(out) as f:
        return json.load(f), err


def test_dump_trace_fixture_full_causal_chain(tmp_path, capsys):
    """The committed fixture is a frozen 2-process causal chain
    (worker report -> relay.forward -> rpc.report_relay_batch): the
    merged trace carries both pids and the cross-process flow arrows."""
    trace, err = _merged(tmp_path, capsys)
    xs = [e for e in trace["traceEvents"] if e["ph"] == "X"]
    assert {e["pid"] for e in xs} == {101, 202}
    assert "10 spans from 2 process(es)" in err
    by_name = {e["name"]: e for e in xs}
    assert by_name["relay.forward"]["args"]["parent"] \
        == by_name["report_node_status"]["args"]["span"]
    assert by_name["rpc.report_relay_batch"]["args"]["parent"] \
        == by_name["relay.forward"]["args"]["span"]
    # one flow arrow per cross-pid parent/child hop
    starts = [e for e in trace["traceEvents"] if e["ph"] == "s"]
    finishes = [e for e in trace["traceEvents"] if e["ph"] == "f"]
    assert len(starts) == 1 and len(finishes) == 1
    assert starts[0]["pid"] == 101 and finishes[0]["pid"] == 202


def test_dump_trace_step_filter(tmp_path, capsys):
    """--step keeps the asked-for training steps and drops unstamped
    setup spans (they are noise on a step-range query)."""
    trace, err = _merged(tmp_path, capsys, "--step", "4..6")
    xs = [e for e in trace["traceEvents"] if e["ph"] == "X"]
    assert sorted(e["args"]["step"] for e in xs) == [4, 5, 6]
    assert all(e["name"] == "train_step" for e in xs)
    assert "kept 3/10 spans" in err
    # open-ended range + single-step form
    trace, _ = _merged(tmp_path, capsys, "--step", "8..")
    assert sorted(e["name"] for e in trace["traceEvents"]
                  if e["ph"] == "X") == [
        "report_node_status", "train_step",
    ]
    trace, _ = _merged(tmp_path, capsys, "--step", "3")
    assert [e["args"]["step"] for e in trace["traceEvents"]
            if e["ph"] == "X"] == [3]


def test_dump_trace_proc_filter_recomputes_flows(tmp_path, capsys):
    """--proc matches the elastic proc index OR the OS pid; flow
    arrows are recomputed AFTER filtering so a dropped parent never
    leaves a dangling arrow."""
    # proc index 1 = the worker side only
    trace, _ = _merged(tmp_path, capsys, "--proc", "1")
    xs = [e for e in trace["traceEvents"] if e["ph"] == "X"]
    assert {e["pid"] for e in xs} == {101}
    assert not [e for e in trace["traceEvents"] if e["ph"] in "sf"]
    # OS pid 202 = the relay/master side; its parents are filtered
    # out, so again: spans survive, dangling flows do not
    trace, _ = _merged(tmp_path, capsys, "--proc", "202")
    xs = [e for e in trace["traceEvents"] if e["ph"] == "X"]
    assert sorted(e["name"] for e in xs) == [
        "relay.forward", "rpc.report_relay_batch",
    ]
    assert not [e for e in trace["traceEvents"] if e["ph"] in "sf"]


def test_dump_trace_since_filter_and_bad_value(tmp_path, capsys):
    from dlrover_tpu.telemetry import dump

    trace, err = _merged(tmp_path, capsys, "--since", "120.0")
    xs = [e for e in trace["traceEvents"] if e["ph"] == "X"]
    assert sorted(e["name"] for e in xs) == [
        "relay.forward", "report_node_status", "rpc.report_relay_batch",
    ]
    # both ends of each hop survive -> the flow arrows do too
    assert len([e for e in trace["traceEvents"] if e["ph"] == "s"]) == 1
    assert "kept 3/10 spans" in err
    # a bad --since is a clean rc-2 error, not a traceback
    assert dump.main(
        [FIXTURE_TRACE, "--trace", "--since", "yesterdayish"]
    ) == 2
    assert "--since" in capsys.readouterr().err


def test_torn_span_lines_skipped(tmp_path):
    d = tmp_path / "trace"
    d.mkdir()
    good = json.dumps({"name": "ok", "ts": 1.0, "dur": 0.1, "pid": 9,
                       "tid": 1, "host": "h", "proc": 0})
    (d / "spans-h-9.jsonl").write_text(good + "\n{torn wri\n")
    xs = [
        e for e in tracing.merge_trace_dir(str(d))["traceEvents"]
        if e["ph"] == "X"
    ]
    assert [e["name"] for e in xs] == ["ok"]


# --------------------------------------------------------- flight recorder


def test_flight_record_dump_contents(tmp_path, monkeypatch):
    monkeypatch.setenv(
        flight_recorder.ENV_CRASH_DIR, str(tmp_path / "crash")
    )
    tracing.enable()
    tracing.set_step(12)
    with tracing.span("last_op"):
        pass
    T.record("checkpoint.save", step=12, tier="ram")
    out = flight_recorder.dump_flight_record("unit test")
    assert out and os.path.isdir(out)
    assert os.path.dirname(out) == str(tmp_path / "crash")
    with open(os.path.join(out, "record.json")) as f:
        rec = json.load(f)
    assert rec["reason"] == "unit test"
    assert rec["step"] == 12
    names = [t["name"] for t in rec["threads"]]
    assert "MainThread" in names
    assert any(s["name"] == "last_op" for s in rec["spans"])
    assert any(
        e["kind"] == "checkpoint.save" for e in rec["journal"]
    )
    assert "dlrover_flight_dumps_total" in rec["metrics"]
    stacks = open(os.path.join(out, "stacks.txt")).read()
    assert 'Thread "MainThread"' in stacks
    # the dump itself lands on the journal for the incident timeline
    evs = T.default_journal().events("flight.dumped")
    assert len(evs) == 1 and evs[0]["data"]["path"] == out


def test_flight_record_on_simulated_hang(tmp_path, monkeypatch):
    """Acceptance: a forced hang produces a flight-recorder dump with
    all-thread stacks + last spans, and the hang event links it."""
    from dlrover_tpu.fault_tolerance.hanging_detector import (
        HangingDetector,
    )

    monkeypatch.setenv(flight_recorder.ENV_FLIGHT_RECORDER, "1")
    monkeypatch.setenv(
        flight_recorder.ENV_CRASH_DIR, str(tmp_path / "crash")
    )
    tracing.enable()
    with tracing.span("pre_hang"):
        pass
    reports = []
    det = HangingDetector(
        report_fn=reports.append, min_timeout=0.05, multiplier=2.0
    )
    det.record_step(7)
    time.sleep(0.12)
    det._check_once()
    assert len(reports) == 1
    evs = T.default_journal().events("hang.detected")
    assert len(evs) == 1
    data = evs[0]["data"]
    assert data["step"] == 7 and data["stalled_for"] >= 0.1
    dump_dir = data["flight_record"]
    assert dump_dir and os.path.isdir(dump_dir)
    with open(os.path.join(dump_dir, "record.json")) as f:
        rec = json.load(f)
    assert any(s["name"] == "pre_hang" for s in rec["spans"])
    assert any(t["name"] == "MainThread" for t in rec["threads"])


def test_flight_record_disabled_by_default_in_tests(tmp_path,
                                                    monkeypatch):
    monkeypatch.setenv(flight_recorder.ENV_FLIGHT_RECORDER, "0")
    assert flight_recorder.dump_on_hang(1.0, 1, 1.0) is None
    assert flight_recorder.install_signal_hook() is False


def test_signal_hook_install_and_uninstall(monkeypatch, tmp_path):
    monkeypatch.setenv(flight_recorder.ENV_FLIGHT_RECORDER, "1")
    prev = signal.getsignal(signal.SIGTERM)
    try:
        assert flight_recorder.install_signal_hook() is True
        assert signal.getsignal(signal.SIGTERM) is (
            flight_recorder._on_signal
        )
        # idempotent: re-install keeps ONE hook, not a chain of hooks
        assert flight_recorder.install_signal_hook() is True
    finally:
        flight_recorder.uninstall_signal_hook()
    assert signal.getsignal(signal.SIGTERM) is prev


_SIGTERM_CHILD = """
import os, signal, sys
os.environ["DLROVER_TPU_FLIGHT_RECORDER"] = "1"
os.environ["DLROVER_TPU_CRASH_DIR"] = sys.argv[1]
from dlrover_tpu.telemetry import flight_recorder, tracing
tracing.enable()
with tracing.span("pre_signal"):
    pass
assert flight_recorder.install_signal_hook()
os.kill(os.getpid(), signal.SIGTERM)
import time
time.sleep(30)  # never reached: the chained default disposition kills
"""


def test_sigterm_dumps_flight_record_then_dies(tmp_path):
    crash = str(tmp_path / "crash")
    p = subprocess.run(
        [sys.executable, "-c", _SIGTERM_CHILD, crash],
        env=_child_env(), timeout=60,
    )
    # the hook dumps, then re-delivers SIGTERM with the default
    # disposition restored: the process still dies of SIGTERM
    assert p.returncode == -signal.SIGTERM
    dumps = os.listdir(crash)
    assert len(dumps) == 1 and dumps[0].startswith("flight-")
    with open(os.path.join(crash, dumps[0], "record.json")) as f:
        rec = json.load(f)
    assert rec["reason"] == "signal-SIGTERM"
    assert any(s["name"] == "pre_signal" for s in rec["spans"])


# ------------------------------------------- /healthz + /debug endpoints


class _FakeDetector:
    def __init__(self):
        self.hanged = False
        self.last_step = 7

    def is_hanged(self):
        return self.hanged

    def stalled_for(self):
        return 12.3

    def timeout(self):
        return 5.0


def test_healthz_degraded_on_stall():
    det = _FakeDetector()
    thttp.attach_hang_detector(det)
    srv = thttp.MetricsServer(host="127.0.0.1").start()
    try:
        base = f"http://127.0.0.1:{srv.port}"
        assert _get(f"{base}/healthz").strip() == "ok"
        det.hanged = True
        with pytest.raises(urllib.error.HTTPError) as exc:
            _get(f"{base}/healthz")
        assert exc.value.code == 503
        body = json.loads(exc.value.read().decode())
        assert body["status"] == "degraded"
        assert body["stalled_for"] == 12.3
        assert body["last_step"] == 7
        det.hanged = False
        assert _get(f"{base}/healthz").strip() == "ok"
    finally:
        srv.stop()


def test_debug_stacks_and_trace_endpoints():
    tracing.enable()
    with tracing.span("served_span"):
        pass
    srv = thttp.MetricsServer(host="127.0.0.1").start()
    try:
        base = f"http://127.0.0.1:{srv.port}"
        stacks = _get(f"{base}/debug/stacks")
        assert 'Thread "MainThread"' in stacks
        trace = json.loads(_get(f"{base}/debug/trace?n=10"))
        xs = [
            e for e in trace["traceEvents"] if e["ph"] == "X"
        ]
        assert any(e["name"] == "served_span" for e in xs)
    finally:
        srv.stop()


def test_rpc_handling_emits_spans():
    from dlrover_tpu.common import comm
    from dlrover_tpu.master.servicer import MasterServicer

    tracing.enable()
    MasterServicer().handle("ping", comm.BaseRequest())
    names = [r["name"] for r in tracing.tail(10)]
    assert "rpc.ping" in names


# -------------------------------------------------- straggler diagnosis


def _feed(sm, node_id, step, ts):
    sm.collect_global_step(step, ts, node_id=node_id)


def test_straggler_scorer_flags_and_recovers():
    from dlrover_tpu.master.monitor.speed_monitor import SpeedMonitor

    sm = SpeedMonitor(straggler_ratio=1.5, straggler_window=2)
    t = 1000.0
    # hosts 0/1 run 0.1 s/step; host 2 runs 0.3 s/step (3x the median)
    for k in range(1, 6):
        _feed(sm, 0, 10 * k, t + k * 1.0)
        _feed(sm, 1, 10 * k, t + k * 1.0)
        _feed(sm, 2, 10 * k, t + k * 3.0)
    assert sm.straggler_ranks() == [2]
    evs = T.default_journal().events("straggler.detected")
    assert len(evs) == 1
    data = evs[0]["data"]
    assert data["node"] == 2
    assert data["ratio"] > 1.5
    assert data["fleet_median_s"] == pytest.approx(0.1, rel=0.01)
    reg = T.default_registry()
    assert reg.get("dlrover_straggler_hosts").value == 1
    assert reg.get("dlrover_stragglers_detected_total").value == 1
    assert reg.get("dlrover_host_step_duration_seconds").labels(
        node="2"
    ).count >= 2
    # recovery: host 2 speeds back up; rolling median falls under the
    # threshold and the verdict clears with a journal event
    t2 = t + 5 * 3.0
    for k in range(1, 12):
        _feed(sm, 0, 50 + 10 * k, t2 + k * 1.0)
        _feed(sm, 1, 50 + 10 * k, t2 + k * 1.0)
        _feed(sm, 2, 50 + 10 * k, t2 + k * 1.0)
    assert sm.straggler_ranks() == []
    assert len(T.default_journal().events("straggler.recovered")) == 1
    assert reg.get("dlrover_straggler_hosts").value == 0


def test_straggler_scorer_needs_two_hosts_and_ignores_replays():
    from dlrover_tpu.master.monitor.speed_monitor import SpeedMonitor

    sm = SpeedMonitor(straggler_ratio=1.5, straggler_window=1)
    t = 1000.0
    for k in range(1, 8):
        _feed(sm, 0, 10 * k, t + k * 5.0)  # slow but ALONE: no verdict
    assert sm.straggler_ranks() == []
    # duplicate/replayed reports (restart) carry no duration signal
    _feed(sm, 1, 10, t + 1.0)
    _feed(sm, 1, 10, t + 1.0)
    _feed(sm, 1, 5, t + 0.5)  # step went backwards: restart replay
    assert sm.host_step_durations().get(1) is None


def test_straggler_state_cleared_on_worker_removal():
    from dlrover_tpu.master.monitor.speed_monitor import SpeedMonitor

    sm = SpeedMonitor(straggler_ratio=1.5, straggler_window=1)
    t = 1000.0
    for k in range(1, 5):
        _feed(sm, 0, 10 * k, t + k * 1.0)
        _feed(sm, 1, 10 * k, t + k * 1.0)
        _feed(sm, 2, 10 * k, t + k * 4.0)
    assert sm.straggler_ranks() == [2]
    sm.remove_running_worker("worker", 2)
    assert sm.straggler_ranks() == []
    assert 2 not in sm.host_step_durations()
    assert T.default_registry().get(
        "dlrover_straggler_hosts"
    ).value == 0


def test_scale_down_prunes_all_per_host_straggler_state():
    """Scale-down pruning (PR 7): evicting several hosts at once drops
    their duration windows, strike counters AND last-report anchors —
    a later re-add of the same node id must start a fresh window, not
    inherit the dead incarnation's cadence."""
    from dlrover_tpu.master.monitor.speed_monitor import SpeedMonitor

    sm = SpeedMonitor(straggler_ratio=1.5, straggler_window=1)
    for i in range(4):
        sm.add_running_worker("worker", i)
    t = 1000.0
    # hosts 0/1 healthy; hosts 2/3 at 4x the median: both flagged
    for k in range(1, 5):
        _feed(sm, 0, 10 * k, t + k * 1.0)
        _feed(sm, 1, 10 * k, t + k * 1.0)
        _feed(sm, 2, 10 * k, t + k * 4.0)
        _feed(sm, 3, 10 * k, t + k * 4.0)
    assert sorted(sm.straggler_ranks()) == [2, 3]
    # the scaler shrinks the job by evicting both stragglers
    sm.remove_running_worker("worker", 2)
    sm.remove_running_worker("worker", 3)
    assert sm.straggler_ranks() == []
    assert set(sm.host_step_durations()) <= {0, 1}
    assert sm.running_workers == {("worker", 0), ("worker", 1)}
    reg = T.default_registry()
    assert reg.get("dlrover_straggler_hosts").value == 0
    assert reg.get("dlrover_training_workers").value == 2
    # node id 2 comes back (a replacement host reusing the rank): its
    # first report must carry NO duration signal — pairing it with the
    # dead incarnation's last report would fabricate a huge step time
    # and instantly re-flag the fresh host
    sm.add_running_worker("worker", 2)
    _feed(sm, 2, 100, t + 100.0)
    assert sm.host_step_durations().get(2) is None
    assert sm.straggler_ranks() == []
    # and from its SECOND report on it scores like everyone else
    _feed(sm, 2, 110, t + 101.0)
    assert sm.host_step_durations().get(2) == pytest.approx(0.1)
    assert sm.straggler_ranks() == []


def test_autoscaler_unions_speed_hint():
    """The cadence scorer's verdicts reach the shrink path alongside
    the network-check list (the `straggler.hint` journal event marks
    the union)."""
    from dlrover_tpu.master.node.job_auto_scaler import (
        AllreduceTrainingAutoScaler,
    )

    captured = {}

    class _Node:
        def __init__(self, rank):
            self.rank_index = rank
            self.type = "worker"
            self.id = rank
            self.is_released = False
            self.relaunchable = True
            self.host_name = f"h{rank}"
            self.name = f"w{rank}"

    class _Mgr:
        def unfinished_nodes(self):
            return [_Node(r) for r in range(4)]

    class _JobMgr:
        _node_managers = {"worker": _Mgr()}

    class _Monitor:
        completed_global_step = 100

        def straggler_ranks(self):
            return [2]

    class _Optimizer:
        _speed_monitor = _Monitor()

        def generate_straggler_shrink_plan(self, stragglers, live,
                                           min_nodes=0):
            captured["stragglers"] = list(stragglers)
            return None  # stop before any scaling machinery

    scaler = AllreduceTrainingAutoScaler(
        _JobMgr(), _Optimizer(), scaler=None,
        straggler_fn=lambda: [3],
    )
    scaler._maybe_shrink_stragglers()
    assert captured["stragglers"] == [2, 3]
    evs = T.default_journal().events("straggler.hint")
    assert len(evs) == 1 and evs[0]["data"]["nodes"] == [2]


# ----------------------------------------------- journal event-name lint
#
# These tests used to carry ~8 hand-rolled ast.walk loops and seven
# near-identical closed-vocabulary sets. ISSUE 15 moved the machinery
# and the vocabularies into tools/dlint (rules/events.py, rules/
# phases.py) — the single source of truth the CLI gate, CI and these
# tests all share. The test NAMES survive because docs/TELEMETRY.md
# and past PR discussions reference them; each is now a thin shim that
# asserts its slice of the dlint run is clean.


import functools

from tools.dlint.core import lint_repo
from tools.dlint.rules import (
    EventNameRule,
    EventVocabularyRule,
    GoodputPhaseRule,
    SpanNameRule,
)
from tools.dlint.rules.events import VOCABULARY


@functools.lru_cache(maxsize=None)
def _lint_findings():
    """One shared whole-repo run for every shim below (single parse +
    walk per file; the whole batch costs well under a second)."""
    res = lint_repo(rules=[EventNameRule, EventVocabularyRule,
                           SpanNameRule, GoodputPhaseRule])
    return tuple(res.findings)


def _assert_clean(findings):
    assert not findings, "\n".join(
        f"{f.location()}: {f.rule}: {f.message}" for f in findings
    )


def _assert_vocabulary_clean(group):
    """The group's namespace is a closed set: no unexpected emission,
    no documented-but-ghost event (see EventVocabularyRule)."""
    prefixes, canonical = VOCABULARY[group]
    assert canonical, f"vocabulary group {group!r} is empty"
    _assert_clean([
        f for f in _lint_findings()
        if f.rule == "event-vocabulary"
        and any(
            f.anchor.startswith(f"unexpected:{p}.")
            or f.anchor.startswith(f"ghost:{p}.")
            for p in prefixes
        )
    ])


def test_journal_event_names_are_snake_case_dotted():
    """Tier-1 typo guard (ISSUE 4): every journal event name used in
    dlrover_tpu/ is a lowercase snake-case dotted constant — a
    misspelled or free-form kind fails HERE, not in a dashboard weeks
    later. (Enforced by dlint's event-names rule; this shim keeps the
    historical entry point.)"""
    _assert_clean([
        f for f in _lint_findings() if f.rule == "event-names"
    ])


def test_preempt_event_names_are_the_canonical_set():
    """The preempt.* journal vocabulary is closed: every record() of a
    preempt event uses exactly one of the documented names, and every
    documented name is actually emitted somewhere. The canonical set
    lives in tools/dlint/rules/events.py (VOCABULARY['preempt'])."""
    _assert_vocabulary_clean("preempt")


def test_sentinel_event_names_are_the_canonical_set():
    """The anomaly.* / rollback.* / quarantine.* vocabulary is closed
    (VOCABULARY['sentinel'])."""
    _assert_vocabulary_clean("sentinel")


def test_serve_event_names_are_the_canonical_set():
    """The serve.* vocabulary is closed (VOCABULARY['serve'])."""
    _assert_vocabulary_clean("serve")


def test_reshard_event_names_are_the_canonical_set():
    """The reshard.* vocabulary is closed (VOCABULARY['reshard'])."""
    _assert_vocabulary_clean("reshard")


def test_spare_event_names_are_the_canonical_set():
    """The spare.* vocabulary is closed (VOCABULARY['spare'], new in
    ISSUE 18 with hot-spare promotion)."""
    _assert_vocabulary_clean("spare")


def test_relay_event_names_are_the_canonical_set():
    """The relay.* vocabulary is closed (VOCABULARY['relay'];
    tier_*/restarted joined in ISSUE 18 with the launcher-owned relay
    lifecycle)."""
    _assert_vocabulary_clean("relay")


def test_control_event_names_are_the_canonical_set():
    """The control.* vocabulary is closed (VOCABULARY['control'])."""
    _assert_vocabulary_clean("control")


def test_report_event_names_are_the_canonical_set():
    """The report.* vocabulary is closed (VOCABULARY['report'])."""
    _assert_vocabulary_clean("report")


def test_ckpt_event_names_are_the_canonical_set():
    """The ckpt.* vocabulary is closed (VOCABULARY['ckpt'])."""
    _assert_vocabulary_clean("ckpt")


def test_lockwatch_event_names_are_the_canonical_set():
    """The lockwatch.* vocabulary is closed (VOCABULARY['lockwatch'],
    new in ISSUE 15 with the runtime lock-order watchdog)."""
    _assert_vocabulary_clean("lockwatch")


def test_brain_event_names_are_the_canonical_set():
    """The brain.* vocabulary is closed (VOCABULARY['brain'], new in
    ISSUE 19 with the explainable resource advisor): plan_proposed /
    plan_adopted / plan_rejected / advisor_started, each with a live
    emitter in brain/advisor.py."""
    _assert_vocabulary_clean("brain")


def test_span_names_are_canonical():
    """ISSUE 8 companion to the event-name lint: every tracing span
    name is a lowercase snake-case (optionally dotted) constant —
    summarize()/dashboards match spans by exact name. (dlint's
    span-names rule.)"""
    _assert_clean([
        f for f in _lint_findings() if f.rule == "span-names"
    ])


def test_goodput_phase_labels_are_canonical():
    """Companion lint (PR 7): a phase label the ledger would reject at
    runtime (ValueError in transition/credit) or a typo'd ``Phase.X``
    member fails here, at lint speed, not mid-drill. (dlint's
    goodput-phases rule.)"""
    _assert_clean([
        f for f in _lint_findings() if f.rule == "goodput-phases"
    ])
