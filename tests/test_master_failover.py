"""Master failover: durable job-state journal + master-kill chaos drill.

Fast unit path: the ledger/journal round-trip (DatasetShardCheckpoint
detail fields, keep_doing restore semantics, MasterStateJournal
persistence, rendezvous round monotonicity, speed-monitor restore) runs
in-process with no subprocesses.

E2e drill (``test_master_kill_drill``): a real master subprocess serves
two real worker subprocesses; ``DLROVER_FAULT_INJECT=master_crash@4``
kills the master mid-epoch (rc 28); a second master starts against the
same ``--state_dir`` and port; both workers reconnect (connection
supervisor), the job finishes, and the test asserts exactly-once shard
delivery, a monotonic rendezvous round, and the
``master.restored`` / ``agent.master_lost`` / ``agent.master_reconnected``
journal events.
"""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

from dlrover_tpu.common.constants import NodeType, RendezvousName, TaskType
from dlrover_tpu.fault_tolerance.injection import MASTER_CRASH_EXIT_CODE
from dlrover_tpu.master.elastic_training.rdzv_manager import (
    ElasticTrainingRendezvousManager,
)
from dlrover_tpu.master.monitor.speed_monitor import SpeedMonitor
from dlrover_tpu.master.shard.base_dataset_manager import (
    DatasetShardCheckpoint,
)
from dlrover_tpu.master.shard.dataset_splitter import new_dataset_splitter
from dlrover_tpu.master.shard.task_manager import TaskManager
from dlrover_tpu.master.state_journal import (
    MasterStateJournal,
    build_master_state_journal,
)
from dlrover_tpu.util.state_store import build_state_store

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------- unit path


def test_checkpoint_detail_roundtrip():
    ckpt = DatasetShardCheckpoint(
        dataset_name="ds",
        todo=[[0, 10], [10, 20]],
        doing=[[20, 30]],
        epoch=1,
        todo_ids=[3, 4],
        doing_detail=[[2, 1, 20, 30, 7]],
        next_task_id=5,
        completed_step=2,
    )
    restored = DatasetShardCheckpoint.from_json(ckpt.to_json())
    assert restored.todo_ids == [3, 4]
    assert restored.doing_detail == [[2, 1, 20, 30, 7]]
    assert restored.next_task_id == 5
    assert restored.completed_step == 2


def test_checkpoint_legacy_json_still_loads():
    # a pre-journal checkpoint has none of the detail fields
    legacy = json.dumps({
        "dataset_name": "ds", "todo": [[0, 10]], "doing": [[10, 20]],
        "epoch": 1,
    })
    ckpt = DatasetShardCheckpoint.from_json(legacy)
    assert ckpt.doing_detail is None
    assert ckpt.next_task_id == 0


def _new_journaled_task_manager(state_dir, params):
    journal = build_master_state_journal("drill-job", state_dir=state_dir)
    tm = TaskManager()
    tm.attach_state_journal(journal)
    splitter = new_dataset_splitter(
        shuffle=params["shuffle"],
        shard_size=params["batch_size"]
        * params["num_minibatches_per_shard"],
        dataset_size=params["dataset_size"],
        num_epochs=params["num_epochs"],
        dataset_name=params["dataset_name"],
    )
    tm.new_dataset(
        batch_size=params["batch_size"],
        dataset_size=params["dataset_size"],
        dataset_name=params["dataset_name"],
        dataset_splitter=splitter,
        task_type=TaskType.TRAINING,
        params=params,
    )
    return journal, tm


PARAMS = dict(
    batch_size=4, num_epochs=1, dataset_size=32, shuffle=False,
    num_minibatches_per_shard=1, dataset_name="drill-ds",
    task_type=TaskType.TRAINING, storage_type="table",
)


def test_ledger_roundtrip_exactly_once(tmp_path):
    """The fast path of the master-kill drill: every shard-state
    mutation is journaled, and a fresh TaskManager restored with
    keep_doing=True accepts the surviving workers' in-flight completion
    reports instead of re-dispatching their shards."""
    state_dir = str(tmp_path)
    _, tm = _new_journaled_task_manager(state_dir, PARAMS)

    t0 = tm.get_dataset_task(NodeType.WORKER, 0, "drill-ds")
    t1 = tm.get_dataset_task(NodeType.WORKER, 1, "drill-ds")
    t2 = tm.get_dataset_task(NodeType.WORKER, 0, "drill-ds")
    assert tm.report_dataset_task("drill-ds", t0.task_id, True)
    consumed = [(t0.shard.start, t0.shard.end)]

    # "master crash": rebuild master-side state from the journal alone,
    # the way dist_master._restore_state does
    journal2 = build_master_state_journal("drill-job", state_dir=state_dir)
    assert journal2.has_state()
    assert journal2.saved_datasets() == ["drill-ds"]
    params, ckpt = journal2.load_dataset("drill-ds")
    assert params["batch_size"] == 4
    _, tm2 = _new_journaled_task_manager(state_dir, params)
    assert tm2.restore_dataset_from_checkpoint(ckpt, keep_doing=True)

    # in-flight completions are accepted under their ORIGINAL task ids
    assert tm2.report_dataset_task("drill-ds", t1.task_id, True)
    assert tm2.report_dataset_task("drill-ds", t2.task_id, True)
    consumed += [(t1.shard.start, t1.shard.end),
                 (t2.shard.start, t2.shard.end)]

    # drain the rest: the union must cover the dataset exactly once
    while True:
        t = tm2.get_dataset_task(NodeType.WORKER, 0, "drill-ds")
        if t.task_id < 0:
            break
        consumed.append((t.shard.start, t.shard.end))
        assert tm2.report_dataset_task("drill-ds", t.task_id, True)
    ranges = sorted(consumed)
    assert ranges[0][0] == 0 and ranges[-1][1] == 32
    for (_, end), (start, _) in zip(ranges, ranges[1:]):
        assert end == start, f"gap/overlap in {ranges}"
    assert tm2.finished()


def test_keep_doing_false_requeues_in_flight(tmp_path):
    """The legacy worker-driven restore still requeues doing shards."""
    state_dir = str(tmp_path)
    _, tm = _new_journaled_task_manager(state_dir, PARAMS)
    t0 = tm.get_dataset_task(NodeType.WORKER, 0, "drill-ds")
    ckpt = tm.get_dataset_checkpoint("drill-ds").to_json()
    _, tm2 = _new_journaled_task_manager(str(tmp_path / "b"), PARAMS)
    assert tm2.restore_dataset_from_checkpoint(ckpt, keep_doing=False)
    # the in-flight shard went back to todo: its old id is unknown
    assert not tm2.report_dataset_task("drill-ds", t0.task_id, True)


def test_journal_kv_rdzv_speed_roundtrip(tmp_path):
    store = build_state_store("file", str(tmp_path))
    journal = MasterStateJournal(store, "job/with spaces")
    assert not journal.has_state()
    journal.save_kv({"a": b"\x00\xffbin", "b": b"text"})
    journal.save_rdzv_round(RendezvousName.TRAINING, 7)
    journal.save_global_step(42, batch_feed=True)
    journal.mark_started()
    assert journal.has_state()

    reopened = MasterStateJournal(
        build_state_store("file", str(tmp_path)), "job/with spaces"
    )
    assert reopened.load_kv() == {"a": b"\x00\xffbin", "b": b"text"}
    assert reopened.load_rdzv_rounds() == {RendezvousName.TRAINING: 7}
    assert reopened.load_global_step() == (42, True)
    reopened.clear()
    assert not reopened.has_state()


def test_fresh_wipes_prior_state(tmp_path):
    journal = build_master_state_journal("j", state_dir=str(tmp_path))
    journal.save_global_step(9)
    fresh = build_master_state_journal(
        "j", state_dir=str(tmp_path), fresh=True
    )
    assert fresh.load_global_step() == (0, False)
    assert build_master_state_journal("j") is None  # no dir, no env


def test_rdzv_round_restore_is_monotonic():
    mgr = ElasticTrainingRendezvousManager()
    mgr.restore_round(5)
    assert mgr._rdzv_round == 5
    mgr.restore_round(3)  # a stale journal can never regress the round
    assert mgr._rdzv_round == 5


def test_speed_monitor_restore():
    sm = SpeedMonitor()
    sm.restore_global_step(40)
    assert sm.completed_global_step >= 40
    sm_batch = SpeedMonitor()
    sm_batch.restore_global_step(17, batch_feed=True)
    assert sm_batch._batches_done == 17


# ----------------------------------------------------------------- e2e drill


def _drill_env(tmp, journal_path):
    env = dict(os.environ)
    parts = [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
             if p and "axon" not in p]
    env["PYTHONPATH"] = os.pathsep.join(parts + [REPO])
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("DLROVER_FAULT_INJECT", None)
    env["DLROVER_TPU_JOURNAL"] = journal_path
    env["DLROVER_TPU_LOG_LEVEL"] = "INFO"
    return env


def _spawn_master(tmp, env, state_dir, port, tag):
    cmd = [
        sys.executable, "-m", "dlrover_tpu.master.main",
        "--platform", "process", "--node_num", "0",
        "--job_name", "failover-drill", "--port", str(port),
        "--state_dir", state_dir,
        "--autoscale_interval", "600", "--check_interval", "0.2",
    ]
    return subprocess.Popen(
        cmd, cwd=REPO, env=env,
        stdout=open(os.path.join(tmp, f"master-{tag}.out"), "w"),
        stderr=open(os.path.join(tmp, f"master-{tag}.err"), "w"),
        start_new_session=True,
    )


def _master_port(tmp, tag, proc, timeout=30):
    path = os.path.join(tmp, f"master-{tag}.out")
    deadline = time.time() + timeout
    while time.time() < deadline:
        if os.path.exists(path):
            for line in open(path):
                if line.startswith("DLROVER_TPU_MASTER_PORT="):
                    return int(line.strip().split("=", 1)[1])
        assert proc.poll() is None, _tail(tmp, f"master-{tag}.err")
        time.sleep(0.2)
    raise AssertionError(
        f"master-{tag} never printed its port; "
        + _tail(tmp, f"master-{tag}.err")
    )


def _tail(tmp, name, n=3000):
    path = os.path.join(tmp, name)
    try:
        return f"{name}: " + open(path).read()[-n:]
    except OSError:
        return f"{name}: <missing>"


def _wait(proc, timeout, what, tmp, logs):
    try:
        return proc.wait(timeout=timeout)
    except subprocess.TimeoutExpired:
        raise AssertionError(
            f"{what} did not exit in {timeout}s; "
            + " | ".join(_tail(tmp, l) for l in logs)
        )


def _killpg(proc, sig=signal.SIGKILL):
    try:
        os.killpg(os.getpgid(proc.pid), sig)
    except (ProcessLookupError, PermissionError):
        pass


def test_master_kill_drill(tmp_path):
    tmp = str(tmp_path)
    state_dir = os.path.join(tmp, "state")
    journal_path = os.path.join(tmp, "journal.jsonl")
    env = _drill_env(tmp, journal_path)
    # bound the lost-reply window: a shard whose dispatch reply died
    # with the master is requeued by the watchdog within ~21s
    master_env = dict(env, DLROVER_TPU_CTX_TASK_PROCESS_TIMEOUT="20")
    worker_env = dict(env, DLROVER_TPU_MASTER_RECONNECT_TIMEOUT="90")

    procs = []
    try:
        m1 = _spawn_master(
            tmp, dict(master_env, DLROVER_FAULT_INJECT="master_crash@4"),
            state_dir, 0, "1",
        )
        procs.append(m1)
        port = _master_port(tmp, "1", m1)

        workers = []
        for node_id in (0, 1):
            w = subprocess.Popen(
                [sys.executable,
                 os.path.join(REPO, "tests", "_master_failover_worker.py"),
                 "--master_addr", f"localhost:{port}",
                 "--node_id", str(node_id),
                 "--out", os.path.join(tmp, f"worker-{node_id}.txt")],
                cwd=REPO, env=worker_env,
                stdout=open(os.path.join(tmp, f"worker-{node_id}.out"), "w"),
                stderr=subprocess.STDOUT,
                start_new_session=True,
            )
            workers.append(w)
            procs.append(w)

        # phase 1: the injector kills master #1 once the reported global
        # step reaches 4 — rc 28, distinct from worker/job failures
        rc1 = _wait(m1, 120, "master #1 (crash expected)", tmp,
                    ["master-1.err", "worker-0.out", "worker-1.out"])
        assert rc1 == MASTER_CRASH_EXIT_CODE, (
            f"master #1 exited rc={rc1}, wanted injected crash "
            f"rc={MASTER_CRASH_EXIT_CODE}; " + _tail(tmp, "master-1.err")
        )

        # phase 2: restart against the same state dir and port, no
        # injection — workers must reconnect without being restarted
        m2 = _spawn_master(tmp, master_env, state_dir, port, "2")
        procs.append(m2)

        for node_id, w in enumerate(workers):
            rc = _wait(w, 120, f"worker {node_id}", tmp,
                       ["worker-0.out", "worker-1.out", "master-2.err"])
            assert rc == 0, (
                f"worker {node_id} exited rc={rc}; "
                + _tail(tmp, f"worker-{node_id}.out")
            )
        # the master exits 0 (SUCCEEDED) once the dataset completes
        rc2 = _wait(m2, 60, "master #2", tmp, ["master-2.err"])
        assert rc2 == 0, _tail(tmp, "master-2.err")
    finally:
        for p in procs:
            _killpg(p, signal.SIGTERM)
        time.sleep(0.5)
        for p in procs:
            _killpg(p)

    # ---- exactly-once shard delivery across the restart -------------
    ranges = []
    rounds = {}
    for node_id in (0, 1):
        lines = open(os.path.join(tmp, f"worker-{node_id}.txt")).read()
        assert "DONE" in lines, lines
        for line in lines.splitlines():
            parts = line.split()
            if parts[0] == "SHARD":
                ranges.append((int(parts[1]), int(parts[2])))
            elif parts[0] in ("ROUND1", "ROUND2"):
                rounds[(node_id, parts[0])] = int(parts[1])
    ranges.sort()
    assert ranges[0][0] == 0 and ranges[-1][1] == 96, ranges
    for (_, end), (start, _) in zip(ranges, ranges[1:]):
        assert end == start, f"shard gap/overlap at {start}: {ranges}"
    # both workers consumed a share (the crash didn't serialize the job)
    assert len(ranges) == 96 // 4

    # ---- monotonic rendezvous rounds across the restart --------------
    for node_id in (0, 1):
        assert rounds[(node_id, "ROUND2")] > rounds[(node_id, "ROUND1")], (
            rounds
        )

    # ---- failover observability (telemetry journal) ------------------
    from dlrover_tpu.telemetry.journal import read_journal

    events = read_journal(journal_path)
    kinds = [e.get("kind") for e in events]
    assert "fault.injected" in kinds
    assert "master.restored" in kinds
    assert kinds.count("agent.master_lost") >= 2  # one per worker
    assert kinds.count("agent.master_reconnected") >= 2
    restored = next(e for e in events if e["kind"] == "master.restored")
    assert restored["data"]["datasets"] == ["failover-drill"]
    # step persists are rate-limited to ~1/s, so the restored step may
    # trail the crash step — it only needs to be monotonic, not exact
    assert restored["data"]["global_step"] >= 1
