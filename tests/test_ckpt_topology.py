"""Sharded checkpoint plane (format v2): topology-elastic drills.

The virtual-host pattern from the other drill suites: one real JAX
process with 8 forced CPU devices, carved into logical processes via
``proc_of_device``, one FlashCheckpointer per logical process sharing a
single LocalFs object store. Saves under one topology (pp2xtp2,
4-process dp, 2-process world) must restore bit-identical under
another (dp over all devices, halved/doubled worlds), every shard
digest-verified on fetch, with the exactly-once sampler ledger carried
across the resize.
"""

import io
import json
import os
import subprocess
import sys
import time
import zipfile

import jax
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dlrover_tpu import telemetry as T
from dlrover_tpu.checkpoint import manifest as mf
from dlrover_tpu.telemetry.journal import EventJournal
from dlrover_tpu.trainer import ckpt_store
from dlrover_tpu.trainer.checkpoint import FlashCheckpointer
from dlrover_tpu.trainer.sampler import ElasticDistributedSampler

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 virtual devices"
)


@pytest.fixture(autouse=True)
def fresh_defaults():
    T.set_default_registry(None)
    T.set_default_journal(EventJournal(None))
    yield
    T.set_default_registry(None)
    T.set_default_journal(EventJournal(None))


def events(kind):
    return T.default_journal().events(kind)


def _state(mesh, spec):
    """A model-ish pytree: one sharded weight, one replicated bias,
    and the exactly-once sampler ledger as a py leaf."""
    sampler = ElasticDistributedSampler(
        dataset_size=1000, num_replicas=4, rank=0, shuffle=False
    )
    sampler.completed_num = 637  # mid-epoch progress to carry over
    return {
        "w": jax.device_put(
            np.arange(64, dtype=np.float32).reshape(8, 8),
            NamedSharding(mesh, spec),
        ),
        "b": jax.device_put(
            np.linspace(-1, 1, 8, dtype=np.float32),
            NamedSharding(mesh, P(None)),
        ),
        "ledger": sampler.state_dict(),
        "step_count": 7,
    }


def _fleet(tmp_path, n_procs, devs_per_proc, tag=""):
    """One virtual checkpointer per logical process over a shared
    store."""
    return [
        FlashCheckpointer(
            persist_dir=str(tmp_path / f"store{tag}"),
            ram_dir=str(tmp_path / f"ram{tag}{p}"),
            persist_interval=1, use_orbax=False,
            process_index=p, n_processes=n_procs,
            proc_of_device=lambda d: d.id // devs_per_proc,
            commit_timeout=60,
        )
        for p in range(n_procs)
    ]


def _save_all(ckpts, step, state, durable=False):
    for c in ckpts:
        c.save(step, state, force_persist=True, durable=durable)
    for c in ckpts:
        c.wait()


def _close_all(ckpts):
    for c in ckpts:
        c.close()


def _zeros_like(state, mesh, spec):
    out = dict(state)
    out["w"] = jax.device_put(
        np.zeros((8, 8), np.float32), NamedSharding(mesh, spec)
    )
    out["b"] = jax.device_put(
        np.zeros(8, np.float32), NamedSharding(mesh, P(None))
    )
    out["ledger"] = {"epoch": -1, "completed_num": -1}
    out["step_count"] = -1
    return out


# ------------------------------------------------- pp2xtp2 -> dp drill


def test_pp_tp_save_restores_under_dp(tmp_path):
    """The ISSUE acceptance drill: save under pp2xtp2 (4 virtual
    hosts), restore under a pure-dp layout by a fresh single-process
    checkpointer that never saw the save topology — bit-identical,
    every shard digest-verified, topology journaled."""
    devs = jax.devices()
    mesh = Mesh(np.array(devs).reshape(2, 4), ("pp", "tp"))
    state = _state(mesh, P("pp", "tp"))
    want = np.asarray(state["w"])
    ckpts = _fleet(tmp_path, 4, 2)
    _save_all(ckpts, 30, state)
    _close_all(ckpts)

    man = ckpt_store.step_manifest(
        ckpt_store.get_store(str(tmp_path / "store")), 30
    )
    assert man["format"] == 2
    assert man["topology"]["n_processes"] == 4
    # every globally-named shard has exactly one located member
    for loc in man["locations"].values():
        assert loc["sha256"]

    mesh_dp = Mesh(np.array(devs), ("dp",))
    r = FlashCheckpointer(
        persist_dir=str(tmp_path / "store"),
        ram_dir=str(tmp_path / "ram-new"),
        persist_interval=0, use_orbax=False,
        process_index=0, n_processes=1,
    )
    target = _zeros_like(state, mesh_dp, P("dp"))
    got, step = r.restore(target=target, step=30)
    r.close()

    assert step == 30
    assert np.array_equal(np.asarray(got["w"]), want)
    assert np.array_equal(np.asarray(got["b"]), np.asarray(state["b"]))
    assert got["ledger"] == {"epoch": 0, "completed_num": 637}
    assert got["step_count"] == 7
    ev = events("ckpt.topology_restore")
    assert ev and ev[-1]["data"]["saved_processes"] == 4


# ------------------------------------------------------- world resize


def test_world_resize_4_to_2_preserves_ledger(tmp_path):
    devs = jax.devices()
    mesh4 = Mesh(np.array(devs).reshape(4, 2), ("dp", "tp"))
    state = _state(mesh4, P("dp", "tp"))
    want = np.asarray(state["w"])
    _save_all(ckpts := _fleet(tmp_path, 4, 2), 40, state)
    _close_all(ckpts)

    # the world halves: 2 logical processes, 4 devices each
    mesh2 = Mesh(np.array(devs).reshape(2, 4), ("dp", "tp"))
    r = _fleet(tmp_path, 2, 4, tag="n")[0]
    r._store = ckpt_store.get_store(str(tmp_path / "store"))
    got, step = r.restore(
        target=_zeros_like(state, mesh2, P("dp", "tp")), step=40
    )
    r.close()
    assert step == 40
    assert np.array_equal(np.asarray(got["w"]), want)

    # exactly-once: the ledger resumes mid-epoch in the new world
    # with no shard replayed and none skipped
    s2 = ElasticDistributedSampler(
        dataset_size=1000, num_replicas=2, rank=0, shuffle=False
    )
    s2.load_state_dict(got["ledger"], num_replicas=2, rank=0)
    assert s2.completed_num == 637
    assert s2.epoch == 0


def test_world_resize_2_to_4(tmp_path):
    devs = jax.devices()
    mesh2 = Mesh(np.array(devs).reshape(2, 4), ("dp", "tp"))
    state = _state(mesh2, P(None, "tp"))  # dp-replicated weight
    want = np.asarray(state["w"])
    _save_all(ckpts := _fleet(tmp_path, 2, 4), 50, state)
    _close_all(ckpts)

    mesh4 = Mesh(np.array(devs).reshape(4, 2), ("dp", "tp"))
    r = FlashCheckpointer(
        persist_dir=str(tmp_path / "store"),
        ram_dir=str(tmp_path / "ram-up0"),
        persist_interval=0, use_orbax=False,
        process_index=0, n_processes=4,
        proc_of_device=lambda d: d.id // 2,
    )
    got, step = r.restore(
        target=_zeros_like(state, mesh4, P(None, "tp")), step=50
    )
    r.close()
    assert step == 50
    assert np.array_equal(np.asarray(got["w"]), want)
    assert got["ledger"] == {"epoch": 0, "completed_num": 637}


# ------------------------------------------------- dedup + owner election


def test_replicated_save_dedups_to_owned_shards(tmp_path):
    """A dp-replicated save must persist each logical shard once, from
    its crc32-elected owner — aggregate store bytes stop scaling with
    the replica count."""
    devs = jax.devices()
    mesh = Mesh(np.array(devs).reshape(4, 2), ("dp", "tp"))
    state = _state(mesh, P(None, "tp"))  # 4-way replicated
    _save_all(ckpts := _fleet(tmp_path, 4, 2), 60, state)
    _close_all(ckpts)

    man = ckpt_store.step_manifest(
        ckpt_store.get_store(str(tmp_path / "store")), 60
    )
    # w is tp-sharded in 2 domains, each replicated across all 4
    # procs; the location table names each domain exactly once
    wleaf = next(
        l for l in man["leaves"]
        if l["path"][-1].get("k") == "w"
    )
    assert len(wleaf["domains"]) == 2
    for d in wleaf["domains"]:
        assert sorted(d["replicas"]) == [0, 1, 2, 3]
        assert d["owner"] == mf.elect_owner(
            mf.shard_key(mf.path_key(wleaf["path"]), d["idx"]),
            d["replicas"],
        )
    dedup = events("ckpt.dedup")
    assert len(dedup) == 4  # every host journaled its subset
    owned = sum(e["data"]["members_owned"] for e in dedup)
    full = sum(e["data"]["members_full"] for e in dedup)
    assert owned < full  # replicas actually dropped members


def test_owner_election_deterministic_and_spread():
    replicas = [0, 1, 2, 3]
    owners = [
        mf.elect_owner(f"leaf-{i}|[[0,8]]", replicas)
        for i in range(200)
    ]
    assert owners == [
        mf.elect_owner(f"leaf-{i}|[[0,8]]", replicas)
        for i in range(200)
    ]
    counts = {p: owners.count(p) for p in replicas}
    assert all(c > 0 for c in counts.values())  # no pile-up on rank 0
    # order of the replica list must not matter
    assert mf.elect_owner("k", [3, 1, 0, 2]) == mf.elect_owner(
        "k", [0, 1, 2, 3]
    )


# ------------------------------------------- sentinel taint + drain save


def test_sentinel_taint_skipped_over_v2(tmp_path):
    """A step saved inside an anomaly window (clean_fn False) is
    tainted at commit and the rollback walk-down lands on the older
    clean step — unchanged semantics over the sharded format."""
    devs = jax.devices()
    mesh = Mesh(np.array(devs).reshape(2, 4), ("dp", "tp"))
    state = _state(mesh, P("dp", "tp"))
    ckpts = _fleet(tmp_path, 2, 4)
    verdict = {"clean": True}
    for c in ckpts:
        c.set_clean_fn(lambda: verdict["clean"])
    _save_all(ckpts, 70, state)
    verdict["clean"] = False
    bad = dict(state, step_count=666)
    _save_all(ckpts, 80, bad)
    _close_all(ckpts)

    store = ckpt_store.get_store(str(tmp_path / "store"))
    assert ckpt_store.step_last_good(store, 80) is False
    assert ckpt_store.step_last_good(store, 70) is True

    # the rollback restorer: a fresh single-process world (the taint
    # walk-down is the solo path; multi-process worlds agree via the
    # consensus collectives) reading the same store
    r = FlashCheckpointer(
        persist_dir=str(tmp_path / "store"),
        ram_dir=str(tmp_path / "ram-rb0"),
        persist_interval=0, use_orbax=False,
        process_index=0, n_processes=1,
    )
    got, step = r.restore(
        target=_zeros_like(state, mesh, P("dp", "tp"))
    )
    r.close()
    assert step == 70
    assert got["step_count"] == 7  # not the tainted 666


def test_durable_emergency_save_restores_after_kill(tmp_path):
    """The preemption-drain emergency save (durable=True) over the
    sharded format: both hosts' notice-window saves are on tmpfs when
    save() returns (no wait, no close — a hard kill follows); the
    relaunched host reassembles the step from its own surviving RAM
    archive plus the survivor's peer tier, never touching the store."""
    from dlrover_tpu.checkpoint.peer import PeerRegistry
    from dlrover_tpu.telemetry.http import MetricsServer

    class _KV:
        def __init__(self):
            self.kv = {}

        def kv_store_set(self, k, v):
            self.kv[k] = v

        def kv_store_get(self, k):
            return self.kv.get(k, b"")

        def kv_store_keys(self, prefix=""):
            return sorted(k for k in self.kv if k.startswith(prefix))

        def kv_store_delete(self, k):
            self.kv.pop(k, None)

    devs = jax.devices()
    mesh = Mesh(np.array(devs).reshape(2, 4), ("dp", "tp"))
    state = _state(mesh, P("dp", "tp"))
    kv = _KV()
    ckpts, servers = [], []
    for p in range(2):
        c = FlashCheckpointer(
            persist_dir=str(tmp_path / "store"),
            ram_dir=str(tmp_path / f"ram{p}"),
            persist_interval=0, use_orbax=False,
            process_index=p, n_processes=2,
            proc_of_device=lambda d: d.id // 4,
        )
        srv = MetricsServer(
            port=0, shard_provider=c.shard_provider()
        ).start()
        c._peer_registry = PeerRegistry(
            kv, p, f"http://127.0.0.1:{srv.port}"
        )
        ckpts.append(c)
        servers.append(srv)
    for c in ckpts:
        c.save(90, state, durable=True)  # returns only once on tmpfs
    # the archives must already be durable — no wait()/close() flush
    for p in range(2):
        assert os.path.exists(tmp_path / f"ram{p}" / f"step-90-proc-{p}")
    deadline = time.monotonic() + 10
    while (len(kv.kv_store_keys("ckpt/peer/90/")) < 2
           and time.monotonic() < deadline):
        time.sleep(0.02)  # advertisement rides the background lane

    # host 0 is hard-killed and relaunched over the same tmpfs
    r = FlashCheckpointer(
        persist_dir=str(tmp_path / "store"),
        ram_dir=str(tmp_path / "ram0"),
        persist_interval=0, use_orbax=False,
        process_index=0, n_processes=2,
        proc_of_device=lambda d: d.id // 4,
        peer_registry=PeerRegistry(kv, 0, "http://127.0.0.1:1"),
    )
    got, step = r.restore(
        target=_zeros_like(state, mesh, P("dp", "tp")), step=90
    )
    r.close()
    for c in ckpts:
        c.close()
    for s in servers:
        s.stop()
    assert step == 90
    assert np.array_equal(np.asarray(got["w"]), np.asarray(state["w"]))
    tr = events("ckpt.topology_restore")[-1]["data"]
    assert tr["local"] >= 1 and tr["peer"] >= 1 and tr["store"] == 0


# ------------------------------------------------- digest verification


def _corrupt_one_member(path):
    """Flip payload bytes of one npy member inside a RAM archive,
    keeping the zip well-formed (the digest must catch it)."""
    with zipfile.ZipFile(path) as z:
        members = {n: z.read(n) for n in z.namelist()}
    victim = next(
        n for n in members if n.endswith(".npy") and n != "manifest.json"
    )
    raw = bytearray(members[victim])
    raw[-1] ^= 0xFF
    members[victim] = bytes(raw)
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_STORED) as z:
        for n, data in members.items():
            z.writestr(n, data)
    with open(path, "wb") as f:
        f.write(buf.getvalue())
    return victim


def test_digest_mismatch_refetches_from_next_tier(tmp_path):
    """A corrupted local shard fails its sha256 on fetch; the loader
    journals the fallback and re-fetches that shard from the store —
    the restore still lands bit-identical."""
    devs = jax.devices()
    mesh = Mesh(np.array(devs).reshape(2, 4), ("dp", "tp"))
    state = _state(mesh, P("dp", "tp"))
    want = np.asarray(state["w"])
    _save_all(ckpts := _fleet(tmp_path, 2, 4), 100, state)
    _close_all(ckpts)

    victim = _corrupt_one_member(
        str(tmp_path / "ram0" / "step-100-proc-0")
    )
    r = FlashCheckpointer(
        persist_dir=str(tmp_path / "store"),
        ram_dir=str(tmp_path / "ram0"),
        persist_interval=0, use_orbax=False,
        process_index=0, n_processes=2,
        proc_of_device=lambda d: d.id // 4,
    )
    got, step = r.restore(
        target=_zeros_like(state, mesh, P("dp", "tp")), step=100
    )
    r.close()
    assert step == 100
    assert np.array_equal(np.asarray(got["w"]), want)

    fb = [
        e for e in events("checkpoint.restore_fallback")
        if e["data"].get("reason") == "digest_mismatch"
    ]
    assert fb, "digest mismatch must journal a restore_fallback"
    rf = events("ckpt.shard_refetch")
    assert rf and rf[-1]["data"]["failed_tier"] == "local"
    assert victim  # the corrupted member really existed


# ----------------------------------------------------- legacy format v1


def test_legacy_v1_archive_read_and_journaled(tmp_path, monkeypatch):
    """Pre-v2 monolithic archives are auto-detected and read through
    the old path, with ``checkpoint.legacy_format`` journaled."""
    monkeypatch.setattr(ckpt_store, "_FORMAT_VERSION", 1)
    state = {"w": np.arange(12, dtype=np.float32), "n": 3}
    c = FlashCheckpointer(
        persist_dir=str(tmp_path / "store"),
        ram_dir=str(tmp_path / "ram-v1"),
        persist_interval=1, use_orbax=False,
    )
    c.save(110, state, force_persist=True)
    c.wait()
    c.close()
    monkeypatch.setattr(ckpt_store, "_FORMAT_VERSION", 2)

    r = FlashCheckpointer(
        persist_dir=str(tmp_path / "store"),
        ram_dir=str(tmp_path / "ram-v1-new"),
        persist_interval=0, use_orbax=False,
    )
    got, step = r.restore(step=110)
    r.close()
    assert step == 110
    assert np.array_equal(got["w"], state["w"])
    ev = events("checkpoint.legacy_format")
    assert ev and ev[-1]["data"]["version"] == 1
    assert ev[-1]["data"]["tier"] == "persistent"


# ------------------------------------------------------------ bench smoke


def test_ckpt_topology_bench_smoke():
    """The topology bench's tier-1 smoke tier: dedup_factor from 4
    replicating virtual hosts clears the 3.5x acceptance bar, the
    cross-topology restore is bit-identical, and the kill-a-host phase
    reassembles entirely from the peer tier."""
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               DLROVER_TPU_METRICS_PORT="off")
    out = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "benchmarks", "ckpt_topology.py"),
         "--smoke"],
        capture_output=True, text=True, timeout=180, env=env,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    result = json.loads(out.stdout.strip().splitlines()[-1])
    assert result["ok"] is True
    assert result["dedup_factor"] >= 3.5
    assert result["reshard_identical"] is True
    assert result["peer_identical"] is True
    assert result["peer_hit_ratio"] >= 0.99
    assert result["bytes_written_per_host"] > 0
    assert result["restore_ms"] > 0
