"""CNN model family (BASELINE.json config #1: the reference's MNIST
CNN elastic-DDP workload, model_zoo/pytorch/mnist/mnist_cnn.py role):
models-contract compliance, learning on the procedural digits set, and
elastic-DDP execution over the 8-device mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from dlrover_tpu.models import cnn, model_module_for
from dlrover_tpu.parallel.mesh import create_mesh


def test_contract_and_dispatch():
    cfg = cnn.mnist_cnn()
    assert model_module_for(cfg) is cnn
    params = cnn.init_params(jax.random.key(0), cfg)
    n = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))
    assert n == cnn.param_count(cfg)
    axes = cnn.param_axes(cfg)
    assert jax.tree.structure(
        params, is_leaf=lambda x: hasattr(x, "shape")
    ).num_leaves == len(jax.tree.leaves(
        axes, is_leaf=lambda x: isinstance(x, tuple)
    ))
    assert cnn.flops_per_token(cfg) > 0


def test_forward_shapes_and_loss():
    cfg = cnn.mnist_cnn()
    params = cnn.init_params(jax.random.key(0), cfg)
    images = jnp.zeros((4, 28, 28, 1))
    logits = cnn.forward(params, images, cfg)
    assert logits.shape == (4, 10)
    labels = jnp.array([0, 1, 2, 3], jnp.int32)
    loss = cnn.loss(params, (images, labels), cfg)
    assert np.isfinite(float(loss))
    # untrained CE ~ log(10)
    assert abs(float(loss) - np.log(10)) < 1.0


def test_learns_procedural_digits():
    import sys

    sys.path.insert(0, "examples")
    from cnn_train import make_digits

    cfg = cnn.mnist_cnn()
    images, labels = make_digits(n=512)
    params = cnn.init_params(jax.random.key(0), cfg)
    opt = optax.adam(1e-3)
    opt_state = opt.init(params)

    @jax.jit
    def step(p, s, batch):
        l, g = jax.value_and_grad(
            lambda p_: cnn.loss(p_, batch, cfg)
        )(p)
        u, s = opt.update(g, s, p)
        return optax.apply_updates(p, u), s, l

    first = None
    for i in range(60):
        lo = (i * 64) % 512
        batch = (
            jnp.asarray(images[lo:lo + 64]),
            jnp.asarray(labels[lo:lo + 64]),
        )
        params, opt_state, loss = step(params, opt_state, batch)
        if first is None:
            first = float(loss)
    assert float(loss) < 0.5 * first, (first, float(loss))


def test_elastic_ddp_on_mesh():
    """The family runs under ShardedTrainer on the 8-device mesh
    (the elastic-DDP execution path)."""
    cfg = cnn.mnist_cnn()
    mesh = create_mesh([("data", 8)])
    trainer = cnn.make_trainer(
        cfg, mesh, strategy="ddp", optimizer=optax.adam(1e-3)
    )
    params, opt_state = trainer.init(jax.random.key(0))
    rng = np.random.RandomState(0)
    images = rng.randn(16, 28, 28, 1).astype(np.float32)
    labels = rng.randint(0, 10, 16).astype(np.int32)
    batch = trainer.shard_batch(
        trainer.microbatch((images, labels))
    )
    _, _, loss = trainer.train_step(params, opt_state, batch)
    assert np.isfinite(float(loss))
