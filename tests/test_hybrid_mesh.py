"""Slice-aware hybrid ICI x DCN mesh (VERDICT r2 Weak #7): the branch
that real multi-slice fleets take, exercised against mocked sliced
device lists; plus the CPU fallback and the misconfiguration guard.
Parity role: SURVEY §5.8 — DCN axes outermost (data/pipe over the slow
network), ICI axes within a slice."""

import pytest

from dlrover_tpu.parallel.mesh import create_hybrid_mesh


class FakeTpuDev:
    """Just enough surface for jax.experimental.mesh_utils'
    slice-grouped mesh construction."""

    platform = "tpu"

    def __init__(self, i: int, slice_index: int, per_slice: int):
        self.id = i
        self.slice_index = slice_index
        self.process_index = i // 4
        self.device_kind = "TPU v5 lite"
        self.coords = (i % per_slice, 0, 0)
        self.core_on_chip = 0

    def __repr__(self):
        return f"FakeTpuDev({self.id}, slice={self.slice_index})"


def _fleet(n_slices: int, per_slice: int):
    return [
        FakeTpuDev(i, i // per_slice, per_slice)
        for i in range(n_slices * per_slice)
    ]


def test_dcn_axis_spans_slices_ici_axis_within():
    devs = _fleet(2, 4)
    mesh = create_hybrid_mesh(
        [("fsdp", 4)], [("data", 2)], devices=devs
    )
    # DCN axes outermost
    assert mesh.axis_names == ("data", "fsdp")
    assert dict(mesh.shape) == {"data": 2, "fsdp": 4}
    # each data index is exactly one slice: fsdp collectives ride ICI
    for di in range(2):
        slice_ids = {d.slice_index for d in mesh.devices[di].flat}
        assert len(slice_ids) == 1, (
            f"fsdp axis crosses slices at data={di}: {slice_ids}"
        )
    # the data axis crosses both slices: grad all-reduce rides DCN
    assert {
        mesh.devices[di].flat[0].slice_index for di in range(2)
    } == {0, 1}


def test_two_ici_axes_within_slice():
    devs = _fleet(2, 8)
    mesh = create_hybrid_mesh(
        [("fsdp", 4), ("tensor", 2)], [("data", 2)], devices=devs
    )
    assert mesh.axis_names == ("data", "fsdp", "tensor")
    assert dict(mesh.shape) == {"data": 2, "fsdp": 4, "tensor": 2}
    for di in range(2):
        assert len({d.slice_index for d in mesh.devices[di].flat}) == 1


def test_ici_shape_resolved_from_fleet():
    """ici_spec sizes of -1 resolve against per-slice device count."""
    devs = _fleet(2, 4)
    mesh = create_hybrid_mesh(
        [("fsdp", -1)], [("data", 2)], devices=devs
    )
    assert dict(mesh.shape) == {"data": 2, "fsdp": 4}


def test_misconfigured_multislice_raises():
    """A sliced fleet that the hybrid construction cannot lay out must
    raise — never silently train with fsdp riding DCN."""

    class BrokenDev:
        platform = "tpu"

        def __init__(self, i, slice_index):
            self.id = i
            self.slice_index = slice_index
            self.process_index = i // 4
            # no coords/core_on_chip: mesh_utils will fail

    devs = [BrokenDev(i, i // 4) for i in range(8)]
    with pytest.raises(Exception):
        create_hybrid_mesh([("fsdp", 4)], [("data", 2)], devices=devs)


def test_cpu_fallback_flat_reshape():
    """Virtual CPU devices (no slice structure) take the reshape
    fallback with DCN axes still outermost."""
    import jax

    devs = jax.devices()[:8]
    mesh = create_hybrid_mesh(
        [("fsdp", 4)], [("data", 2)], devices=devs
    )
    assert mesh.axis_names == ("data", "fsdp")
    assert dict(mesh.shape) == {"data": 2, "fsdp": 4}
