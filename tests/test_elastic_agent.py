"""Elastic agent tests against an in-process master with loopback gRPC.

Mirrors reference dlrover/python/tests/test_elastic_training_agent.py:
agents constructed with explicit node ranks against a real local master.
"""

import os
import sys
import tempfile
import threading
import time

import pytest

from dlrover_tpu.agent.elastic.training import (
    ElasticLaunchConfig,
    ElasticTrainingAgent,
    MasterRendezvousHandler,
    WorkerState,
)
from dlrover_tpu.agent.master_client import MasterClient
from dlrover_tpu.agent.sharding.client import (
    IndexShardingClient,
    ShardingClient,
)
from dlrover_tpu.common.constants import NodeType
from dlrover_tpu.master.local_master import LocalJobMaster


@pytest.fixture()
def master():
    m = LocalJobMaster(port=0)
    m.prepare()
    yield m
    m.stop()


def _client(master, node_id):
    return MasterClient(master.addr, node_id=node_id,
                        node_type=NodeType.WORKER)


def test_sharding_client_batch_done(master):
    c = _client(master, 0)
    sc = ShardingClient(
        dataset_name="d", batch_size=4, num_epochs=1, dataset_size=16,
        num_minibatches_per_shard=2, master_client=c,
    )
    shard = sc.fetch_shard()
    assert shard is not None
    assert shard.end - shard.start == 8
    assert not sc.report_batch_done()  # 1 of 2 minibatches
    assert sc.report_batch_done()  # task complete -> reported
    sc.fetch_shard()
    sc.report_batch_done()
    sc.report_batch_done()
    assert sc.fetch_shard() is None  # exhausted
    assert master.task_manager.finished()


def test_index_sharding_client(master):
    c = _client(master, 0)
    sc = IndexShardingClient(
        dataset_name="idx", batch_size=4, num_epochs=1, dataset_size=10,
        num_minibatches_per_shard=1, master_client=c,
    )
    seen = []
    while True:
        idx = sc.fetch_sample_index()
        if idx is None:
            break
        seen.append(idx)
    assert sorted(seen) == list(range(10))
    sc.stop()


def test_rendezvous_handler_two_nodes(master):
    c0, c1 = _client(master, 0), _client(master, 1)
    c0.report_rdzv_params(min_nodes=2, max_nodes=2, waiting_timeout=1.0,
                          node_unit=1)
    results = {}

    def join(rank, client):
        h = MasterRendezvousHandler(client, rank, local_world_size=1,
                                    join_timeout=30)
        results[rank] = h.next_rendezvous()

    threads = [
        threading.Thread(target=join, args=(r, c))
        for r, c in ((0, c0), (1, c1))
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=40)
    assert set(results) == {0, 1}
    _, world0, pid0, nproc0, coord0 = results[0]
    _, world1, pid1, nproc1, coord1 = results[1]
    assert world0 == world1 == {0: 1, 1: 1}
    assert (pid0, pid1) == (0, 1)
    assert nproc0 == nproc1 == 2
    assert coord0 == coord1  # both learned rank0's coordinator
    assert ":" in coord0


def _write_script(tmpdir, body: str) -> str:
    path = os.path.join(tmpdir, "entry.py")
    with open(path, "w") as f:
        f.write(body)
    return path


def test_agent_runs_process_to_success(master):
    with tempfile.TemporaryDirectory() as tmp:
        out = os.path.join(tmp, "out.txt")
        script = _write_script(
            tmp,
            "import os\n"
            f"open({out!r}, 'w').write(os.environ['DLROVER_TPU_PROCESS_ID'])\n",
        )
        config = ElasticLaunchConfig(
            min_nodes=1, max_nodes=1, node_rank=0, monitor_interval=0.2,
            entrypoint=script,
        )
        c = _client(master, 0)
        c.report_rdzv_params(1, 1, 0.5, 1)
        agent = ElasticTrainingAgent(config, c)
        result = agent.run()
        assert result.state == WorkerState.SUCCEEDED
        assert open(out).read() == "0"


def test_agent_restarts_failed_process(master):
    """First run fails, second (after restart) succeeds."""
    with tempfile.TemporaryDirectory() as tmp:
        flag = os.path.join(tmp, "flag")
        script = _write_script(
            tmp,
            "import os, sys\n"
            f"if not os.path.exists({flag!r}):\n"
            f"    open({flag!r}, 'w').close()\n"
            "    sys.exit(3)\n",
        )
        config = ElasticLaunchConfig(
            min_nodes=1, max_nodes=1, node_rank=0, monitor_interval=0.2,
            max_restarts=2, entrypoint=script,
        )
        c = _client(master, 0)
        c.report_rdzv_params(1, 1, 0.5, 1)
        agent = ElasticTrainingAgent(config, c)
        result = agent.run()
        assert result.state == WorkerState.SUCCEEDED
        assert agent._restart_count == 2


def test_agent_gives_up_after_max_restarts(master):
    with tempfile.TemporaryDirectory() as tmp:
        script = _write_script(tmp, "import sys; sys.exit(7)\n")
        config = ElasticLaunchConfig(
            min_nodes=1, max_nodes=1, node_rank=0, monitor_interval=0.2,
            max_restarts=1, entrypoint=script,
        )
        c = _client(master, 0)
        c.report_rdzv_params(1, 1, 0.5, 1)
        agent = ElasticTrainingAgent(config, c)
        result = agent.run()
        assert result.state == WorkerState.FAILED
        assert result.return_code == 7


def test_agent_restarts_on_membership_change(master):
    """A new node joining triggers re-rendezvous of the running agent
    (scale-up without job restart)."""
    with tempfile.TemporaryDirectory() as tmp:
        script = _write_script(tmp, "import time; time.sleep(30)\n")
        config = ElasticLaunchConfig(
            min_nodes=1, max_nodes=2, node_rank=0, monitor_interval=0.2,
            rdzv_timeout=0.5, entrypoint=script,
        )
        # the agent re-reports its config's rdzv params on every join
        # (HA master restarts relearn them), so the config carries the
        # short timeout rather than a one-shot report here
        c0 = _client(master, 0)
        agent = ElasticTrainingAgent(config, c0)
        t = threading.Thread(target=agent.run, daemon=True)
        t.start()
        # wait for the first world (only node 0)
        deadline = time.time() + 20
        while agent._restart_count == 0 and time.time() < deadline:
            time.sleep(0.1)
        assert agent._restart_count == 1
        first_proc = agent._proc

        # second node appears
        c1 = _client(master, 1)
        h1 = MasterRendezvousHandler(c1, 1, 1, join_timeout=30)
        joined = {}

        def join_second():
            joined["res"] = h1.next_rendezvous()

        t2 = threading.Thread(target=join_second, daemon=True)
        t2.start()
        # agent should notice, kill the old proc, and re-rendezvous
        t2.join(timeout=30)
        assert "res" in joined
        _, world, _, nproc, _ = joined["res"]
        assert world == {0: 1, 1: 1}
        deadline = time.time() + 10
        while agent._restart_count < 2 and time.time() < deadline:
            time.sleep(0.1)
        assert agent._restart_count == 2
        assert first_proc.poll() is not None  # old process was stopped
        agent.stop()
        t.join(timeout=10)
