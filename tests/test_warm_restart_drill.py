"""Warm-restart drill: the persistent compilation cache makes a
same-topology worker restart measurably cheaper than its cold start
(VERDICT r4 Missing #1 / next-round item #1).

Why this matters: the reference's whole failover design restarts
training processes in place (dlrover/python/elastic_agent/torch/
training.py:441) to avoid re-setup cost. On TPU the dominant re-setup
cost is XLA recompilation; without a persistent cache the <60s SLA
only holds for models whose compile is free. This drill runs the REAL
restart path — elastic launcher, agent, fault-injected crash, flash-
checkpoint resume — and asserts the second incarnation's
process-start -> first-step time beat the first's because its jit was
a disk read (the cache directory the agent wired into the worker env).

The on-chip measurement (1.1B flagship, cold vs warm, real compile
times) is ``benchmarks/failover_warm.py`` -> FAILOVER_r05.json; this
drill keeps the mechanism honest in CI on the CPU backend.
"""

import os
import subprocess
import sys
import tempfile

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.drill


def _read_timings(path):
    rows = []
    with open(path) as f:
        for line in f:
            restart, secs = line.strip().split(",")
            rows.append((int(restart), float(secs)))
    return rows


def test_warm_restart_beats_cold_via_compile_cache():
    from dlrover_tpu.trainer import compile_cache

    if not compile_cache._persistent_cache_safe():
        pytest.skip(
            "this jax build cannot reload serialized executables; the "
            "safety gate keeps the cache off, so there is no warm "
            "path to measure"
        )
    with tempfile.TemporaryDirectory() as tmp:
        out_file = os.path.join(tmp, "result.txt")
        timing_file = os.path.join(tmp, "timing.csv")
        cache_dir = os.path.join(tmp, "compile_cache")
        cmd = [
            sys.executable, "-m", "dlrover_tpu.trainer.elastic_run",
            "--standalone", "--nnodes", "1:1",
            "--max_restarts", "2",
            "--monitor_interval", "0.3",
            "--compile_cache_dir", cache_dir,
            os.path.join(REPO, "examples", "llama_train.py"), "--",
            "--steps", "30", "--batch-size", "8", "--seq-len", "64",
            "--num-workers", "1",
            "--ckpt-dir", os.path.join(tmp, "ckpt"),
            "--out", out_file, "--timing-out", timing_file,
        ]
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        # crash at step 15: incarnation 0 pays the cold compile and
        # leaves a step-10 flash snapshot; incarnation 1 restores and
        # re-jits the SAME program over the SAME topology — the
        # persistent cache's exact hit case
        env["DLROVER_FAULT_INJECT"] = "crash@15"
        # CPU compiles are fast; cache everything so the drill
        # exercises the read path, not the size floor
        env["DLROVER_TPU_COMPILE_CACHE_MIN_SECS"] = "0.0"
        proc = subprocess.run(
            cmd, cwd=REPO, env=env, timeout=420,
            capture_output=True, text=True,
        )
        assert proc.returncode == 0, proc.stderr[-3000:]

        step, _loss, start = open(out_file).read().split(",")
        assert int(step) == 30
        assert int(start) == 10  # resumed from the flash snapshot

        # the cold incarnation populated the shared cache the agent
        # pointed both incarnations at
        from dlrover_tpu.trainer.compile_cache import cache_entries

        assert cache_entries(cache_dir) > 0, (
            "cold run wrote no cache entries"
        )

        timings = dict(_read_timings(timing_file))
        assert set(timings) == {0, 1}, timings
        cold, warm = timings[0], timings[1]
        # the warm incarnation additionally pays checkpoint restore,
        # yet must still beat cold because compile became a disk read;
        # the 0.9 factor absorbs CI noise without letting a cache miss
        # (warm == cold + restore) pass
        assert warm < 0.9 * cold, (
            f"warm restart ({warm:.2f}s) did not beat cold start "
            f"({cold:.2f}s): compilation cache not effective"
        )
