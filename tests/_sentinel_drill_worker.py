"""Drill worker for the silent-failure sentinel chaos test (not a
test module).

Speaks the real agent protocol against a live master: joins the
training rendezvous, consumes data shards with a live
:class:`TrainingSentinel` inspecting the per-step loss, saves a
RAM-tier checkpoint (tagged with the sentinel's clean verdict) plus
the matching shard-ledger snapshot every step, and reports the global
step.

Fault surface: ``DLROVER_FAULT_INJECT=nan@N:host=H`` (or
``sdc@N:flip=K:host=H``) poisons host H's step-N loss scalar through
the injector's ``corrupt_loss`` path. The sentinel must trip, report
the anomaly over the supervised RPC, receive the coordinated rollback
order, and every OTHER rank must learn the same order from the master
KV broadcast.

On an adopted order each rank restores the ordered last-good step from
its RAM tier (``ROLLED <step> ok``); the DETECTING rank additionally
rewinds the global shard ledger to the snapshot taken with that
checkpoint, voiding every shard consumed after it. ``SHARD`` lines are
emitted only for completions the master ACCEPTED, so the test's
exactly-once arithmetic (effective = accepted − voided) is exact.
"""

import argparse
import os
import sys
import time

import numpy as np


def _state_for(step: int):
    # step-stamped payload: the rollback can verify the restored arrays
    # really belong to the step the order named
    return {"w": np.full((8,), float(step)), "bias": np.arange(4.0) + step}


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--master_addr", required=True)
    p.add_argument("--node_id", type=int, required=True)
    p.add_argument("--out", required=True)
    p.add_argument("--ckpt_dir", required=True)
    p.add_argument("--ram_dir", required=True)
    p.add_argument("--dataset_size", type=int, default=96)
    p.add_argument("--batch_size", type=int, default=4)
    p.add_argument("--shard_secs", type=float, default=0.05,
                   help="simulated train time per shard")
    p.add_argument("--fetch_batch", type=int, default=2)
    p.add_argument("--lookahead", type=int, default=2,
                   help="0 = no prefetch thread, so a quarantined "
                        "worker leaves no in-flight shards behind")
    args = p.parse_args()

    from dlrover_tpu.common.log import set_process_index

    set_process_index(args.node_id)

    from dlrover_tpu.agent.master_client import MasterClient
    from dlrover_tpu.agent.sharding.client import ShardingClient
    from dlrover_tpu.common.constants import NodeEnv, RendezvousName
    from dlrover_tpu.fault_tolerance.injection import FaultInjector
    from dlrover_tpu.fault_tolerance.sentinel import TrainingSentinel
    from dlrover_tpu.telemetry import goodput, record
    from dlrover_tpu.trainer.checkpoint import FlashCheckpointer

    led = goodput.install()
    restart_count = int(os.environ.get(NodeEnv.RESTART_COUNT, "0") or 0)

    out = open(args.out, "a", buffering=1)

    def emit(line: str):
        out.write(line + "\n")
        print(f"[worker {args.node_id}] {line}", flush=True)

    client = MasterClient(
        args.master_addr, node_id=args.node_id, node_type="worker",
    )
    client.update_node_status("running", "", restart_count)
    injector = FaultInjector.from_env(role="worker")
    sentinel = TrainingSentinel.from_env(client)
    assert sentinel is not None, "drill needs the sentinel armed"

    # max_ram_keep covers the whole run: the rollback restores an
    # EXPLICIT step, so its archive must survive the RAM-tier gc
    ckpt = FlashCheckpointer(
        args.ckpt_dir,
        ram_dir=args.ram_dir,
        persist_interval=0,
        max_ram_keep=64,
        use_orbax=False,
        stage="sync",
    )
    ckpt.set_clean_fn(sentinel.is_clean)

    def rendezvous(tag: str) -> int:
        client.join_rendezvous(args.node_id, 1)
        deadline = time.monotonic() + 60
        while True:
            rdzv_round, _, world = client.get_comm_world(
                RendezvousName.TRAINING, args.node_id
            )
            if world and args.node_id in world:
                record("rendezvous.joined", round=rdzv_round,
                       node=args.node_id)
                emit(f"{tag} {rdzv_round}")
                return rdzv_round
            if time.monotonic() > deadline:
                emit(f"ERROR {tag} timeout")
                raise TimeoutError(tag)
            time.sleep(0.2)

    client.report_rdzv_params(
        min_nodes=1, max_nodes=2, waiting_timeout=0.5, node_unit=1,
    )
    rendezvous("ROUND")

    sharding = ShardingClient(
        dataset_name="sentinel-drill",
        batch_size=args.batch_size,
        num_epochs=1,
        dataset_size=args.dataset_size,
        shuffle=False,
        num_minibatches_per_shard=1,
        master_client=client,
        fetch_batch=args.fetch_batch,
        lookahead=args.lookahead,
    )

    step = 0
    last_saved = 0
    cur = _state_for(0)
    #: per-save shard-ledger snapshots keyed by step — in a production
    #: loop this JSON rides inside the model checkpoint payload
    ledgers = {}

    def do_rollback(order) -> None:
        nonlocal step, cur
        emit(f"ROLLBACK {order['step']} {step} {order['id']}")
        # the order names the DETECTOR's last-good step; each rank's
        # step counter is local in this drill (no shared global step),
        # so a slightly-behind rank restores its newest save at or
        # below the ordered step. The detector always has the exact
        # ordered step — that is where its last_good came from.
        target = min(int(order["step"]), last_saved)
        assert target > 0, (order, last_saved)
        state, got = ckpt.restore(step=target)
        assert got == target, (got, target, order)
        ok = int(state["w"][0]) == int(target)
        cur, step = state, int(got)
        # only the DETECTING rank rewinds the (global) shard ledger:
        # one incident, one rewind
        if sentinel.anomaly_count > 0 and order["step"] in ledgers:
            sharding.restore_shard_from_checkpoint(ledgers[order["step"]])
            emit(f"LEDGER_RESTORED {order['step']} {time.time():.6f}")
        sentinel.note_restored(target, order["id"])
        # the RUNNING re-report closes the rollback window on the
        # master (servicer _rollback_ranks -> rollback.recovered)
        client.update_node_status("running", "", restart_count)
        emit(f"ROLLED {int(got)} {'ok' if ok else 'STATE_MISMATCH'}")

    while True:
        order = sentinel.pending_rollback()
        if order is not None:
            do_rollback(order)
        if sentinel.job_failed:
            emit("JOB_FAILED")
            return 5
        if sentinel.quarantined:
            # the master evicted this host as a repeat offender; the
            # pending rollback was honored above (its ledger rewind
            # requeued this rank's voided work), so stand down and let
            # the remaining nodes finish the epoch
            emit("QUARANTINED")
            break
        shard = sharding.fetch_shard(poll_interval=0.2, max_wait=120.0)
        if shard is None:
            break
        time.sleep(args.shard_secs)
        step += 1
        cur = _state_for(step)
        # deterministic finite loss stream; the injector poisons it on
        # the configured host/step and the sentinel sees the result
        loss = 1.0 + 0.1 * np.sin(step)
        if injector is not None:
            loss = injector.corrupt_loss(step, loss)
        anomaly = sentinel.check(step, loss)
        if anomaly is not None:
            emit(f"TRIP {anomaly['kind']} {step}")
        led.on_step()
        if sentinel.is_clean():
            ckpt.save(step, cur, durable=True)
            sentinel.note_checkpoint(step)
            last_saved = step
            ledgers[step] = sharding.get_shard_checkpoint()
            emit(f"SAVED {step} {time.time():.6f}")
        assert sharding._current_task is not None
        task_id = sharding._current_task.task_id
        if sharding.report_task_done(task_id):
            # only master-ACCEPTED completions count: a rejected report
            # means the shard was requeued by the ledger rewind and
            # will be consumed again
            emit(f"SHARD {shard.start} {shard.end} {time.time():.6f}")
        client.report_global_step(step)

    # a rollback ordered while this rank was draining its last shard
    order = sentinel.poll_rollback_order()
    if order is not None:
        do_rollback(order)

    emit(f"STEPS {step}")
    emit(f"ANOMALIES {sentinel.anomaly_count}")
    snap = led.close()
    client.report_goodput(final=True)
    emit(f"ELAPSED {snap['elapsed_s']:.3f}")
    emit("DONE")
    ckpt.close()
    client.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
