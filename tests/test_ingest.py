"""Sharded ingest plane + aggregator relay tier (ISSUE 16).

Four layers, tested where each contract lives:

* :class:`ReporterLedger` — the per-reporter exactly-once bookkeeping:
  resync on unknown/new-incarnation deltas, immediate eviction on
  ``final``, stale-first eviction at the cap (the satellite bugfix:
  the ledger used to grow forever);
* :class:`IngestPlane` — node-id sharding, the split admission budget,
  and the PR 12 shed/retry contract surviving the shard refactor;
* the AsyncRpcServer front end — hot handlers on the event loop, cold
  RPCs on the bounded thread pool, both over a real gRPC channel;
* the relay tier — downstream termination + upstream re-delta against
  a real master, and the failover drill: kill the relay mid-interval,
  the agent's ConnectionSupervisor fails over to the direct master
  address, and NO interval is dropped or double-applied (master ledger
  seq == the agent's last acked seq).
"""

import os
import time

import pytest

from dlrover_tpu.agent.status_reporter import DeltaTracker
from dlrover_tpu.common import comm
from dlrover_tpu.common.constants import NodeStatus, NodeType
from dlrover_tpu.common.node import Node
from dlrover_tpu.master.ingest import IngestPlane, ReporterLedger
from dlrover_tpu.master.node.dist_job_manager import DistributedJobManager
from dlrover_tpu.master.monitor.speed_monitor import SpeedMonitor
from dlrover_tpu.master.servicer import create_master_service
from dlrover_tpu.telemetry.journal import (
    EventJournal,
    default_journal,
    set_default_journal,
)


@pytest.fixture(autouse=True)
def _fresh_event_journal():
    set_default_journal(EventJournal())
    yield
    set_default_journal(EventJournal())


GP = {
    "goodput_phases": {"init": 45.0, "training": 120.0},
    "goodput_elapsed_s": 170.0,
    "goodput_start_ts": 1000.0,
    "goodput_phase": "training",
}


def _compose(tracker, node_id=0, **kw):
    kw.setdefault("step", 100)
    kw.setdefault("pid", 4242)
    kw.setdefault("goodput_fields", dict(GP))
    kw.setdefault("resource", (50.0, 4096))
    kw.setdefault("host", f"host-{node_id}")
    rep = tracker.compose(time.time(), **kw)
    rep.node_id, rep.node_type = node_id, NodeType.WORKER
    return rep


def _job_manager(agents=4):
    speed = SpeedMonitor()
    jm = DistributedJobManager(speed_monitor=speed,
                               heartbeat_timeout=3600.0)
    jm._node_managers[NodeType.WORKER].update_nodes({
        i: Node(NodeType.WORKER, i, status=NodeStatus.RUNNING)
        for i in range(agents)
    })
    return jm, speed


# ------------------------------------------------------------- ledger


def test_ledger_resync_semantics():
    led = ReporterLedger(cap=64)
    key = (NodeType.WORKER, 1)
    # full first contact: no resync needed
    assert led.observe(key, 0, 1, True, 1.0) is False
    # known incarnation, delta: flows
    assert led.observe(key, 0, 2, False, 2.0) is False
    # unknown reporter delta: the ledger has no baseline
    assert led.observe((NodeType.WORKER, 2), 0, 5, False, 3.0) is True
    # incarnation flip WITHOUT a full report: the old baseline
    # describes a dead process
    assert led.observe(key, 1, 1, False, 4.0) is True
    # ...and once a full report lands, deltas flow again
    assert led.observe(key, 1, 2, True, 5.0) is False
    assert led.observe(key, 1, 3, False, 6.0) is False
    assert led.get(key) == (1, 3)


def test_ledger_final_evicts_immediately():
    led = ReporterLedger(cap=64)
    key = (NodeType.WORKER, 3)
    led.observe(key, 0, 1, True, 1.0)
    assert led.evict(key) is True
    assert led.evict(key) is False  # already gone
    assert led.evictions == 1
    assert led.get(key) is None
    # the next delta from a reborn process resyncs
    assert led.observe(key, 0, 2, False, 2.0) is True


def test_ledger_cap_evicts_stalest_first():
    led = ReporterLedger(cap=2)
    a, b, c = [(NodeType.WORKER, i) for i in range(3)]
    led.observe(a, 0, 1, True, 1.0)  # stalest
    led.observe(b, 0, 1, True, 2.0)
    led.observe(c, 0, 1, True, 3.0)  # over cap: evicts a
    assert led.evictions == 1
    assert len(led) == 2
    assert led.get(a) is None
    assert led.get(b) == (0, 1) and led.get(c) == (0, 1)
    # the evicted-but-alive reporter self-heals through resync
    assert led.observe(a, 0, 2, False, 4.0) is True


# -------------------------------------------------------------- plane


def test_plane_splits_admission_budget_across_shards():
    plane = IngestPlane(shards=4, inflight_limit=8, retry_after=0.02,
                        ledger_cap=400)
    try:
        assert len(plane.shards) == 4
        shard = plane.shard_of(NodeType.WORKER, 0)
        # routing is stable
        assert plane.shard_of(NodeType.WORKER, 0) is shard
        # 8 // 4 = 2 slots per shard, no cross-shard borrowing
        assert shard.try_admit() and shard.try_admit()
        assert not shard.try_admit()
        ack = plane.shed_ack(shard)
        assert not ack.accepted and ack.retry_after_s == 0.02
        shed = default_journal().events("control.load_shed")
        assert shed and shed[-1]["data"]["shard"] == shard.index
        shard.release()
        shard.release()
        assert shard.try_admit()
        shard.release()
    finally:
        plane.close()


def test_plane_limit_zero_sheds_everything_then_recovers():
    plane = IngestPlane(shards=4, inflight_limit=48, retry_after=0.02,
                        ledger_cap=400)
    applied = []
    try:
        tracker = DeltaTracker(incarnation=0)
        rep = _compose(tracker, node_id=1)
        plane.inflight_limit = 0
        shed = plane.report(rep, lambda r: applied.append(r.seq) or "")
        assert not shed.accepted and shed.retry_after_s > 0
        assert applied == []  # shed never applies nor advances ledger
        assert (NodeType.WORKER, 1) not in plane.reporters()
        plane.inflight_limit = 48
        ack = plane.report(rep, lambda r: applied.append(r.seq) or "")
        assert ack.accepted and ack.acked_seq == rep.seq
        assert applied == [rep.seq]
        assert plane.reporters()[(NodeType.WORKER, 1)] == (0, rep.seq)
    finally:
        plane.close()


def test_plane_exactly_once_across_shards_and_final_evicts():
    plane = IngestPlane(shards=4, inflight_limit=48, retry_after=0.02,
                        ledger_cap=400)
    try:
        trackers = {a: DeltaTracker(incarnation=0) for a in range(8)}
        for a, tr in trackers.items():
            rep = _compose(tr, node_id=a)
            ack = plane.report(rep, lambda r: "")
            tr.commit(rep)
            assert ack.accepted and not ack.resync
        view = plane.reporters()
        assert {k[1] for k in view} == set(range(8))
        assert all(v == (0, 1) for v in view.values())
        # deltas land on their own shard's ledger slice
        for a, tr in trackers.items():
            rep = _compose(tr, node_id=a, step=101)
            plane.report(rep, lambda r: "")
        assert all(v == (0, 2) for v in plane.reporters().values())
        # a final report (process exit) evicts its entry immediately
        bye = _compose(trackers[3], node_id=3, step=102, final=True)
        ack = plane.report(bye, lambda r: "")
        assert ack.accepted
        assert (NodeType.WORKER, 3) not in plane.reporters()
        assert plane.evictions() == 1
    finally:
        plane.close()


def test_resync_after_master_restart_across_shards():
    """A restarted master (fresh IngestPlane) has no baselines: every
    agent's next DELTA must come back resync=True so the tracker
    resends full — on every shard, not just shard 0."""
    old = IngestPlane(shards=4, inflight_limit=48, ledger_cap=400)
    trackers = {a: DeltaTracker(incarnation=0) for a in range(8)}
    try:
        for a, tr in trackers.items():
            rep = _compose(tr, node_id=a)
            old.report(rep, lambda r: "")
            tr.commit(rep)
    finally:
        old.close()

    reborn = IngestPlane(shards=4, inflight_limit=48, ledger_cap=400)
    try:
        for a, tr in trackers.items():
            delta = _compose(tr, node_id=a, step=101)
            assert not delta.full
            ack = reborn.report(delta, lambda r: "")
            assert ack.accepted and ack.resync
            tr.commit(delta)
            tr.request_full()  # what the agent-side resync hook does
            full = _compose(tr, node_id=a, step=102)
            assert full.full
            ack = reborn.report(full, lambda r: "")
            assert ack.accepted and not ack.resync
            tr.commit(full)
        assert all(
            v == (0, 3) for v in reborn.reporters().values()
        )
    finally:
        reborn.close()


# ------------------------------------------------- async front end


def test_async_server_hot_and_cold_lanes():
    """The event-loop server dispatches hot methods on the loop (async
    handler) and everything else on the bounded pool (sync handler),
    over a real gRPC channel."""
    from dlrover_tpu.common.grpc_utils import (
        AsyncRpcServer,
        GenericRpcClient,
    )

    calls = []

    def cold(method, message):
        calls.append(("cold", method))
        return comm.Response(success=True)

    async def hot(message):
        calls.append(("hot", message.node_id))
        return comm.NodeStatusAck(accepted=True, acked_seq=message.seq)

    server = AsyncRpcServer(
        cold, port=0, hot_handlers={"report_node_status": hot}
    )
    assert server.port > 0  # port known BEFORE start (dist_master)
    server.start()
    cli = GenericRpcClient(f"localhost:{server.port}", timeout=10.0)
    try:
        resp = cli.call("ping", comm.HeartBeat(
            node_id=0, node_type=NodeType.WORKER, timestamp=1.0,
        ))
        assert resp.success
        rep = comm.NodeStatusReport(timestamp=1.0, seq=5)
        rep.node_id, rep.node_type = 7, NodeType.WORKER
        ack = cli.call("report_node_status", rep)
        assert ack.accepted and ack.acked_seq == 5
        assert ("cold", "ping") in calls
        assert ("hot", 7) in calls
    finally:
        cli.close()
        server.stop(grace=0.2)


# ---------------------------------------------------------- relay tier


def _master_service(agents=4):
    jm, speed = _job_manager(agents)
    server, servicer = create_master_service(
        0, job_manager=jm, speed_monitor=speed
    )
    server.start()
    return server, servicer


def test_relay_terminates_redeltas_and_forwards():
    """Downstream: the relay acks like a master (immediate, resync
    semantics). Upstream: it forwards ONE coalesced batch per interval
    whose sub-reports are RE-DELTA'D against the master-acked baseline
    and keep the original agent identity."""
    from dlrover_tpu.agent.relay import AggregatorRelay

    server, servicer = _master_service()
    relay = AggregatorRelay(
        f"localhost:{server.port}", relay_id=0, interval=30.0,
    )
    batches = []
    orig = relay._upstream.report_relay_batch
    relay._upstream.report_relay_batch = (
        lambda b: (batches.append(b), orig(b))[1]
    )
    try:
        t0 = DeltaTracker(incarnation=0)
        t1 = DeltaTracker(incarnation=0)
        for node_id, tr in ((0, t0), (1, t1)):
            rep = _compose(tr, node_id=node_id)
            ack = relay.handle("report_node_status", rep)
            assert ack.accepted and ack.acked_seq == rep.seq
            assert not ack.resync
            tr.commit(rep)
        relay._forward_once()  # the interval tick, deterministically
        assert relay.forwarded_batches == 1
        assert relay.forwarded_reports == 2
        assert len(batches[0].reports) == 2
        # the master's ledger is keyed by ORIGINAL agent, seq from the
        # relay's own upstream tracker stream
        view = servicer._reporters
        assert view[(NodeType.WORKER, 0)] == (0, 1)
        assert view[(NodeType.WORKER, 1)] == (0, 1)
        chain = relay.delivery_snapshot()
        assert chain[(NodeType.WORKER, 0)] == {
            "downstream_seq": 1, "upstream_seq": 1,
        }

        # second interval: only agent 0 reports, only its step moved —
        # the upstream sub-report is a DELTA carrying just the step
        rep = _compose(t0, node_id=0, step=101)
        assert relay.handle("report_node_status", rep).accepted
        t0.commit(rep)
        relay._forward_once()
        assert len(batches[1].reports) == 1  # agent 1 was not fresh
        fwd = batches[1].reports[0]
        assert (fwd.node_type, fwd.node_id) == (NodeType.WORKER, 0)
        assert not fwd.full and fwd.has_step and fwd.step == 101
        assert not fwd.has_goodput and not fwd.has_resource
        assert servicer._reporters[(NodeType.WORKER, 0)] == (0, 2)

        # a final report retires the agent end to end: relay slot,
        # relay ledger, and the master's ledger entry
        bye = _compose(t1, node_id=1, step=200, final=True)
        assert relay.handle("report_node_status", bye).accepted
        relay._forward_once()
        assert (NodeType.WORKER, 1) not in relay._slots
        assert (NodeType.WORKER, 1) not in servicer._reporters
    finally:
        relay._upstream.report_relay_batch = orig
        relay.stop(flush=False, grace=0.0)
        server.stop(grace=0.2)
        servicer.close()


def test_relay_restart_resyncs_agent():
    """A reborn relay has no baseline for its agents: a DELTA report
    must be acked resync=True — the agent cannot tell a relay restart
    from a master restart."""
    from dlrover_tpu.agent.relay import AggregatorRelay

    server, servicer = _master_service()
    relay = AggregatorRelay(
        f"localhost:{server.port}", relay_id=1, interval=30.0,
    )
    try:
        tracker = DeltaTracker(incarnation=0)
        rep = _compose(tracker, node_id=2)
        assert not relay.handle("report_node_status", rep).resync
        tracker.commit(rep)

        reborn = AggregatorRelay(
            f"localhost:{server.port}", relay_id=1, interval=30.0,
        )
        try:
            delta = _compose(tracker, node_id=2, step=101)
            assert not delta.full
            ack = reborn.handle("report_node_status", delta)
            assert ack.accepted and ack.resync
            tracker.commit(delta)
            tracker.request_full()
            full = _compose(tracker, node_id=2, step=102)
            ack = reborn.handle("report_node_status", full)
            assert ack.accepted and not ack.resync
        finally:
            reborn.stop(flush=False, grace=0.0)
    finally:
        relay.stop(flush=False, grace=0.0)
        server.stop(grace=0.2)
        servicer.close()


def test_relay_failover_drill():
    """Kill the relay mid-interval: the agent's ConnectionSupervisor
    fails over to the direct master address after
    DLROVER_TPU_RELAY_FAILOVER_S and the report stream continues —
    zero dropped, zero duplicated intervals (the master's ledger ends
    at EXACTLY the agent's last acked seq), with the failover
    journaled."""
    from dlrover_tpu.agent.master_client import MasterClient
    from dlrover_tpu.agent.relay import AggregatorRelay

    server, servicer = _master_service()
    master_addr = f"localhost:{server.port}"
    # interval long enough that nothing auto-forwards: the kill drops
    # relay-acked-but-unforwarded state, the worst case for delivery
    relay = AggregatorRelay(master_addr, relay_id=0, interval=30.0)
    relay.start()
    cli = MasterClient(
        f"localhost:{relay.port}", node_id=0, node_type=NodeType.WORKER,
        timeout=10.0, fallback_addr=master_addr, failover_after=0.5,
    )
    tracker = DeltaTracker(incarnation=0)
    cli.add_reconnect_hook("report-resync", tracker.request_full)
    try:
        acked = []
        for i in range(6):
            rep = _compose(tracker, node_id=0, step=100 + i)
            ack = cli.report_node_status(rep)
            assert ack is not None and ack.accepted, f"interval {i}"
            tracker.commit(rep)
            acked.append(rep.seq)
            if ack.resync:
                tracker.request_full()
            if i == 2:
                relay.kill()  # mid-interval: acked seqs 1-3 unflushed
        # the supervisor failed over relay -> direct and journaled it
        assert default_journal().events("relay.failover")
        # two-hop exactly-once: the master's ledger entry is the
        # agent's LAST acked seq — nothing dropped, nothing replayed
        assert servicer._reporters[(NodeType.WORKER, 0)] == (
            0, acked[-1],
        )
        # post-failover the master forced a resync (it never saw the
        # relay-terminated intervals), so full state was re-delivered
        assert tracker._seq == acked[-1]
    finally:
        cli.close()
        server.stop(grace=0.2)
        servicer.close()
