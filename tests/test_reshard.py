"""Unit tests for the reshard plane (dlrover_tpu/reshard/).

Covers the three layers in isolation: the TransitionOrder wire
format, the master-side TransitionCoordinator state machine (cut /
complete / abort / budget / fallback), and the worker-side
MeshTransition adopt-exactly-once executor plus the migrate stats
vocabulary. The end-to-end path (real master, real SIGKILL) lives in
tests/test_reshard_drill.py.
"""

import numpy as np
import pytest

import dlrover_tpu.telemetry as T
from dlrover_tpu.common.comm import ReshardResponse
from dlrover_tpu.reshard import (
    KIND_ABORT,
    KIND_GROW,
    KIND_PROMOTE,
    KIND_SHRINK,
    SPARE_KEY_PREFIX,
    TRANSITION_ORDER_KEY,
    MeshTransition,
    TransitionCoordinator,
    TransitionOrder,
    reshard_enabled,
    reshard_opted_in,
)
from dlrover_tpu.reshard.migrate import (
    empty_stats,
    merge_stats,
    migrate_from_checkpoint,
    reshard_arrays,
)
from dlrover_tpu.telemetry.journal import EventJournal


@pytest.fixture(autouse=True)
def _fresh_journal():
    jr = T.set_default_journal(EventJournal(None))
    yield jr
    T.set_default_journal(EventJournal(None))


def _kinds(journal, prefix="reshard"):
    return [e["kind"] for e in journal.events(prefix)]


class FakeKV:
    def __init__(self):
        self.data = {}

    def set(self, key, value):
        self.data[key] = value

    def get(self, key):
        return self.data.get(key, b"")

    def keys(self, prefix=""):
        return sorted(k for k in self.data if k.startswith(prefix))

    def delete(self, key):
        self.data.pop(key, None)

    def add(self, key, amount=1):
        cur = int(self.data.get(key, b"0") or b"0") + int(amount)
        self.data[key] = str(cur).encode()
        return cur


class FakeTaskManager:
    def __init__(self, requeued=3):
        self.requeued = requeued
        self.calls = []

    def relinquish_tasks(self, node_type, rank):
        self.calls.append((node_type, rank))
        return self.requeued


class FakeGoodput:
    def __init__(self):
        self.faults = []
        self.recovered = []

    def note_fault(self, cause="", node_id=None):
        self.faults.append((cause, node_id))

    def mark_recovered(self, cause=""):
        self.recovered.append(cause)


def _coordinator(kv=None, **kw):
    kw.setdefault("max_transitions", 8)
    kw.setdefault("abort_timeout", 120.0)
    return TransitionCoordinator(kv or FakeKV(), **kw)


def _last_order(kv):
    return TransitionOrder.from_json(kv.data[TRANSITION_ORDER_KEY])


# ---------------------------------------------------------------- wire format


class TestTransitionOrder:
    def test_json_round_trip(self):
        order = TransitionOrder(
            id=3, kind=KIND_SHRINK, step=120, old_world_size=4,
            world_size=3, survivors=[0, 1, 3], lost=[2],
            reason="heartbeat timeout",
        )
        back = TransitionOrder.from_json(order.to_json())
        assert back == order

    def test_unknown_fields_are_dropped(self):
        raw = (b'{"id": 7, "kind": "grow", "survivors": [0, 1],'
               b' "joined": [1], "from_the_future": true}')
        order = TransitionOrder.from_json(raw)
        assert order.id == 7 and order.kind == KIND_GROW
        assert not hasattr(order, "from_the_future")

    def test_missing_fields_default(self):
        order = TransitionOrder.from_json(b'{"id": 1}')
        assert order.survivors == [] and order.aborted_id == 0

    def test_non_object_payload_rejected(self):
        with pytest.raises(ValueError):
            TransitionOrder.from_json(b'[1, 2, 3]')

    def test_new_index_is_position_in_survivors(self):
        order = TransitionOrder(
            id=1, kind=KIND_SHRINK, survivors=[0, 1, 3], lost=[2]
        )
        assert order.new_index(0) == 0
        assert order.new_index(3) == 2
        assert order.new_index(2) is None  # the shed rank
        assert order.new_index(9) is None


# ------------------------------------------------------------ env three-state


class TestEnvGates:
    def test_master_opt_in_requires_explicit_flag(self, monkeypatch):
        monkeypatch.delenv("DLROVER_TPU_RESHARD", raising=False)
        assert not reshard_opted_in()
        assert reshard_enabled()  # workers poll by default
        monkeypatch.setenv("DLROVER_TPU_RESHARD", "1")
        assert reshard_opted_in() and reshard_enabled()
        monkeypatch.setenv("DLROVER_TPU_RESHARD", "0")
        assert not reshard_opted_in() and not reshard_enabled()

    def test_from_env_disabled(self, monkeypatch):
        monkeypatch.setenv("DLROVER_TPU_RESHARD", "0")
        assert MeshTransition.from_env(None) is None
        monkeypatch.delenv("DLROVER_TPU_RESHARD", raising=False)
        assert MeshTransition.from_env(None) is not None


# -------------------------------------------------------------- coordinator


class TestTransitionCoordinator:
    def test_lost_member_cuts_a_shrink_order(self, _fresh_journal):
        kv = FakeKV()
        coord = _coordinator(kv)
        for r in range(4):
            coord.note_node_running(r)
        order = coord.note_node_lost(2, reason="heartbeat timeout")
        assert order is not None and order.kind == KIND_SHRINK
        assert order.survivors == [0, 1, 3] and order.lost == [2]
        assert order.old_world_size == 4 and order.world_size == 3
        # the order is on the wire, verbatim
        assert _last_order(kv) == order
        assert _kinds(_fresh_journal) == [
            "reshard.detected", "reshard.ordered", "reshard.rebalanced",
        ]

    def test_unknown_rank_takes_the_restart_path(self):
        coord = _coordinator()
        coord.note_node_running(0)
        assert coord.note_node_lost(7) is None

    def test_min_world_guard(self):
        coord = _coordinator(min_world=2)
        coord.note_node_running(0)
        coord.note_node_running(1)
        assert coord.note_node_lost(1) is None

    def test_ledger_rebalanced_exactly_once(self, _fresh_journal):
        tm = FakeTaskManager(requeued=5)
        coord = _coordinator(task_manager=tm)
        for r in range(3):
            coord.note_node_running(r)
        coord.note_node_lost(1)
        assert tm.calls == [("worker", 1)]
        (evt,) = _fresh_journal.events("reshard.rebalanced")
        assert evt["data"]["requeued"] == 5

    def test_completion_requires_every_survivor(self, _fresh_journal):
        goodput = FakeGoodput()
        coord = _coordinator(goodput=goodput)
        for r in range(4):
            coord.note_node_running(r)
        order = coord.note_node_lost(2)
        assert goodput.faults == [("reshard", 2)]
        for phase in ("adopted", "migrated", "completed"):
            assert coord.note_worker_phase(0, order.id, phase) == "ok"
        assert coord.active_order is not None  # 1 and 3 still pending
        assert coord.note_worker_phase(1, order.id, "completed") == "ok"
        assert coord.note_worker_phase(3, order.id, "completed") == "ok"
        assert coord.active_order is None
        assert coord.world == [0, 1, 3]
        assert coord.transitions_done == 1
        assert goodput.recovered == ["reshard"]
        assert "reshard.completed" in _kinds(_fresh_journal)

    def test_stale_order_id_is_rejected(self):
        coord = _coordinator()
        for r in range(3):
            coord.note_node_running(r)
        order = coord.note_node_lost(2)
        assert coord.note_worker_phase(0, order.id + 1, "adopted") == "stale"
        # and with no open transition everything is stale
        coord.abort("test")
        assert coord.note_worker_phase(0, order.id, "completed") == "stale"

    def test_second_casualty_aborts_into_fallback(self, _fresh_journal):
        kv = FakeKV()
        fallbacks = []
        coord = _coordinator(kv, fallback_fn=fallbacks.append)
        for r in range(4):
            coord.note_node_running(r)
        order = coord.note_node_lost(2)
        # a SURVIVOR of the open order dies: undecidable remap
        assert coord.note_node_lost(1) is None
        assert coord.active_order is None
        assert fallbacks == [order]
        abort = _last_order(kv)
        assert abort.kind == KIND_ABORT and abort.aborted_id == order.id
        assert abort.id > order.id  # fresh id: adopted exactly-once too
        assert "reshard.aborted" in _kinds(_fresh_journal)
        # the lost rank left the membership either way
        assert 2 not in coord.world

    def test_worker_refusal_aborts(self):
        fallbacks = []
        coord = _coordinator(fallback_fn=fallbacks.append)
        for r in range(3):
            coord.note_node_running(r)
        order = coord.note_node_lost(2)
        assert coord.note_worker_phase(0, order.id, "aborted") == "abort"
        assert coord.active_order is None and fallbacks == [order]

    def test_abort_timeout_watchdog(self):
        coord = _coordinator(abort_timeout=10.0)
        for r in range(3):
            coord.note_node_running(r)
        order = coord.note_node_lost(2)
        import time
        coord.check_abort(now=time.time() + 5)
        assert coord.active_order is order  # still inside the window
        coord.check_abort(now=time.time() + 11)
        assert coord.active_order is None

    def test_budget_degrades_to_restart(self):
        coord = _coordinator(max_transitions=1)
        for r in range(4):
            coord.note_node_running(r)
        order = coord.note_node_lost(3)
        for r in (0, 1, 2):
            coord.note_worker_phase(r, order.id, "completed")
        assert coord.transitions_done == 1
        # budget spent: the next loss takes the restart path
        assert coord.note_node_lost(2) is None

    def test_aborted_attempt_spends_budget_too(self):
        coord = _coordinator(max_transitions=1)
        for r in range(4):
            coord.note_node_running(r)
        coord.note_node_lost(3)
        coord.abort("drill")
        assert coord.transitions_done == 1
        # a job that keeps aborting degrades to always-restart
        assert coord.note_node_lost(2) is None

    def test_join_cuts_a_grow_order(self):
        kv = FakeKV()
        coord = _coordinator(kv)
        for r in range(2):
            coord.note_node_running(r)
        order = coord.note_node_join(2)
        assert order.kind == KIND_GROW and order.survivors == [0, 1, 2]
        assert order.joined == [2] and order.world_size == 3
        # the joiner acks too; completion needs all three
        for r in (0, 1):
            coord.note_worker_phase(r, order.id, "completed")
        assert coord.active_order is not None
        coord.note_worker_phase(2, order.id, "completed")
        assert coord.world == [0, 1, 2]

    def test_join_waits_while_a_transition_is_open(self):
        coord = _coordinator()
        for r in range(3):
            coord.note_node_running(r)
        coord.note_node_lost(2)
        assert coord.note_node_join(5) is None

    def test_running_widens_until_sealed_then_grows(self):
        coord = _coordinator()
        # bring-up: RUNNING reports only widen the membership
        for r in range(3):
            assert coord.note_node_running(r) is None
        assert coord.world == [0, 1, 2] and not coord.sealed
        coord.seal_world()
        assert coord.sealed
        # post-seal an unseen RUNNING rank IS a node join
        order = coord.note_node_running(3)
        assert order is not None and order.kind == KIND_GROW
        assert order.joined == [3] and order.survivors == [0, 1, 2, 3]
        # a known member re-reporting never re-cuts
        for r in coord.world:
            assert coord.note_node_running(r) is None

    def test_seal_is_a_noop_on_an_empty_world(self):
        coord = _coordinator()
        coord.seal_world()
        assert not coord.sealed
        assert coord.note_node_running(0) is None
        assert coord.world == [0]

    def test_abort_unseals_for_the_relaunch(self):
        coord = _coordinator()
        for r in range(3):
            coord.note_node_running(r)
        coord.seal_world()
        coord.note_node_lost(2)
        coord.abort("drill")
        # the fallback restarts the world: fresh incarnations'
        # RUNNING reports must widen, not cut grow orders
        assert not coord.sealed
        assert coord.note_node_running(2) is None
        assert coord.world == [0, 1, 2]

    def test_spare_is_not_grown_in(self):
        kv = FakeKV()
        coord = _coordinator(kv)
        for r in range(3):
            coord.note_node_running(r)
        coord.seal_world()
        kv.set(f"{SPARE_KEY_PREFIX}7", b"{}")
        # the spare's RUNNING report neither widens nor cuts a grow
        assert coord.note_node_running(7) is None
        assert coord.world == [0, 1, 2]

    def test_loss_promotes_a_registered_spare(self, _fresh_journal):
        kv = FakeKV()
        coord = _coordinator(kv)
        for r in range(3):
            coord.note_node_running(r)
        coord.seal_world()
        kv.set(f"{SPARE_KEY_PREFIX}7", b"{}")
        coord.note_node_running(7)
        order = coord.note_node_lost(1, reason="heartbeat")
        assert order.kind == KIND_PROMOTE
        assert order.survivors == [0, 2, 7]
        assert order.lost == [1] and order.joined == [7]
        # constant world size: the spare stands in for the casualty
        assert order.world_size == order.old_world_size == 3
        # the claim is exactly-once: the registration is consumed
        assert kv.keys(SPARE_KEY_PREFIX) == []
        assert len(_fresh_journal.events("spare.promoted")) == 1
        for r in order.survivors:
            coord.note_worker_phase(r, order.id, "completed")
        assert coord.world == [0, 2, 7]
        # a second loss has no spare left: plain shrink
        order2 = coord.note_node_lost(2)
        assert order2.kind == KIND_SHRINK

    def test_lost_rank_cannot_be_its_own_spare(self):
        kv = FakeKV()
        coord = _coordinator(kv)
        for r in range(3):
            coord.note_node_running(r)
        coord.seal_world()
        kv.set(f"{SPARE_KEY_PREFIX}1", b"{}")
        order = coord.note_node_lost(1)
        assert order.kind == KIND_SHRINK


# ------------------------------------------------------------ worker executor


class FakeMasterClient:
    def __init__(self, kv=None, action="ok"):
        self.kv = kv or FakeKV()
        self.action = action
        self.reports = []

    def kv_store_get(self, key):
        return self.kv.get(key)

    def kv_store_set(self, key, value):
        self.kv.set(key, value)

    def kv_store_add(self, key, amount=1):
        return self.kv.add(key, amount)

    def report_reshard(self, order_id, phase, detail=""):
        self.reports.append((order_id, phase))
        return ReshardResponse(action=self.action)


def _shrink(order_id=1, survivors=(0, 2), lost=(1,)):
    return TransitionOrder(
        id=order_id, kind=KIND_SHRINK,
        old_world_size=len(survivors) + len(lost),
        world_size=len(survivors),
        survivors=list(survivors), lost=list(lost),
    )


class TestMeshTransition:
    def test_adopt_exactly_once_by_id(self, _fresh_journal):
        client = FakeMasterClient()
        client.kv.set(TRANSITION_ORDER_KEY, _shrink().to_json())
        mt = MeshTransition(client, node_rank=2)
        first = mt.poll_order()
        assert first is not None and first.id == 1
        # the broadcast stays on the KV store; re-polls are no-ops
        assert mt.poll_order() is first
        assert len(_fresh_journal.events("reshard.adopted")) == 1

    def test_excluded_rank_stands_down(self):
        client = FakeMasterClient()
        client.kv.set(TRANSITION_ORDER_KEY, _shrink().to_json())
        mt = MeshTransition(client, node_rank=1)  # the shed rank
        assert mt.poll_order() is None
        assert mt.excluded and not mt.fallback

    def test_abort_cancels_the_pending_order(self, _fresh_journal):
        client = FakeMasterClient()
        client.kv.set(TRANSITION_ORDER_KEY, _shrink(order_id=1).to_json())
        mt = MeshTransition(client, node_rank=0)
        assert mt.poll_order() is not None
        client.kv.set(TRANSITION_ORDER_KEY, TransitionOrder(
            id=2, kind=KIND_ABORT, aborted_id=1, reason="timeout",
        ).to_json())
        assert mt.poll_order() is None
        assert mt.fallback
        assert len(_fresh_journal.events("reshard.aborted")) == 1

    def test_fresh_incarnation_ignores_stale_abort(self, _fresh_journal):
        # a relaunched process reads the abort broadcast of a
        # transition it never participated in: falling back would
        # loop relaunches forever — it must be ignored
        client = FakeMasterClient()
        client.kv.set(TRANSITION_ORDER_KEY, TransitionOrder(
            id=2, kind=KIND_ABORT, aborted_id=1, reason="timeout",
        ).to_json())
        mt = MeshTransition(client, node_rank=0)
        assert mt.poll_order() is None
        assert not mt.fallback
        assert _fresh_journal.events("reshard.aborted") == []
        # ...but a LATER abort addressed to an order this incarnation
        # adopted still falls back
        client.kv.set(TRANSITION_ORDER_KEY, _shrink(order_id=3).to_json())
        assert mt.poll_order() is not None
        client.kv.set(TRANSITION_ORDER_KEY, TransitionOrder(
            id=4, kind=KIND_ABORT, aborted_id=3, reason="refused",
        ).to_json())
        assert mt.poll_order() is None
        assert mt.fallback

    def test_pop_pending_clears_at_the_step_boundary(self):
        client = FakeMasterClient()
        client.kv.set(TRANSITION_ORDER_KEY, _shrink().to_json())
        mt = MeshTransition(client, node_rank=0)
        order = mt.poll_order()
        assert mt.pop_pending() is order
        assert mt.pending() is None

    def test_bad_broadcast_never_takes_training_down(self):
        client = FakeMasterClient()
        client.kv.set(TRANSITION_ORDER_KEY, b"{not json")
        mt = MeshTransition(client, node_rank=0)
        assert mt.poll_order() is None

    def test_stale_answer_flips_fallback(self):
        client = FakeMasterClient(action="stale")
        mt = MeshTransition(client, node_rank=0)
        assert mt.report_phase(_shrink(), "migrated") == "stale"
        assert mt.fallback

    def test_note_migrated_journals_move_stats(self, _fresh_journal):
        client = FakeMasterClient()
        mt = MeshTransition(client, node_rank=0)
        stats = merge_stats({"device": 4, "peer": 2, "bytes": 1024})
        assert mt.note_migrated(_shrink(), stats, duration_s=0.5) == "ok"
        (evt,) = _fresh_journal.events("reshard.migrated")
        assert evt["data"]["device"] == 4 and evt["data"]["peer"] == 2
        assert client.reports == [(1, "migrated")]

    def test_worker_abort_reports_and_falls_back(self, _fresh_journal):
        client = FakeMasterClient(action="abort")
        mt = MeshTransition(client, node_rank=0)
        mt.abort(_shrink(), "state digest mismatch")
        assert mt.fallback
        assert client.reports == [(1, "aborted")]
        assert len(_fresh_journal.events("reshard.aborted")) == 1

    def test_masterless_transition_still_functions(self):
        mt = MeshTransition(None, node_rank=0)
        assert mt.poll_order() is None
        assert mt.report_phase(_shrink(), "completed") is None
        # masterless agreement degrades to a local decision
        assert mt.agree_step(_shrink(), lambda: 7) == 7

    def test_latecomer_excluded_by_stale_cut_is_regrown(self):
        """A joiner can read the PREVIOUS order off the KV store (cut
        before it existed, excluding it) and then be grown in by the
        next order: the newest order defines membership."""
        client = FakeMasterClient()
        client.kv.set(TRANSITION_ORDER_KEY, _shrink().to_json())
        mt = MeshTransition(client, node_rank=1)  # shed by that cut
        assert mt.poll_order() is None and mt.excluded
        client.kv.set(TRANSITION_ORDER_KEY, TransitionOrder(
            id=2, kind=KIND_GROW, old_world_size=2, world_size=3,
            survivors=[0, 1, 2], joined=[1],
        ).to_json())
        order = mt.poll_order()
        assert order is not None and order.id == 2
        assert not mt.excluded

    def test_agree_step_first_claimer_decides(self, _fresh_journal):
        """Exactly ONE survivor runs compute_fn; the rest read the
        pinned value even when their own (later) answer would differ."""
        kv = FakeKV()
        ma = MeshTransition(FakeMasterClient(kv), node_rank=0)
        mb = MeshTransition(FakeMasterClient(kv), node_rank=2)
        order = _shrink()
        calls = []
        assert ma.agree_step(order, lambda: calls.append("a") or 6) == 6
        # b reaches the boundary later, when a newer step committed —
        # without agreement it would pick 7 and the worlds diverge
        assert mb.agree_step(order, lambda: calls.append("b") or 7) == 6
        assert calls == ["a"]
        (evt,) = _fresh_journal.events("reshard.step_pinned")
        assert evt["data"]["step"] == 6
        assert evt["data"]["order_id"] == order.id
        assert evt["data"]["node_rank"] == 0

    def test_agree_step_claim_failure_decides_locally(self):
        client = FakeMasterClient()
        client.kv_store_add = lambda *a, **kw: (_ for _ in ()).throw(
            RuntimeError("kv down")
        )
        mt = MeshTransition(client, node_rank=0)
        assert mt.agree_step(_shrink(), lambda: 5) == 5

    def test_agree_step_reader_times_out_without_a_decider(self):
        kv = FakeKV()
        kv.add("reshard/agree/1/step/claim", 1)  # claimed, never pinned
        mt = MeshTransition(FakeMasterClient(kv), node_rank=0)
        with pytest.raises(TimeoutError):
            mt.agree_step(_shrink(), lambda: 5, poll=0.02, timeout=0.2)


# ---------------------------------------------------------------- migration


class TestMigrate:
    def test_stats_vocabulary(self):
        stats = empty_stats()
        assert set(stats) == {
            "live", "local", "peer", "store", "device",
            "digest_mismatch", "bytes",
        }
        merged = merge_stats({"peer": 1}, {"peer": 2, "bytes": 8}, None)
        assert merged["peer"] == 3 and merged["bytes"] == 8

    def test_reshard_arrays_moves_only_what_changed(self):
        import jax

        state = {"w": np.arange(8, dtype=np.float32), "step": 3}
        sharding = jax.sharding.SingleDeviceSharding(jax.devices()[0])
        new_state, stats = reshard_arrays(
            state, {"w": sharding, "step": None}
        )
        assert stats["device"] == 1  # "step" was left alone
        assert new_state["w"].sharding == sharding
        np.testing.assert_array_equal(np.asarray(new_state["w"]),
                                      state["w"])
        # already in the target layout: zero-copy, zero moves
        again, stats2 = reshard_arrays(new_state, {"w": sharding,
                                                   "step": None})
        assert stats2["device"] == 0 and again["w"] is new_state["w"]

    def test_migrate_from_checkpoint_merges_loader_stats(self):
        class FakeCheckpointer:
            last_restore_stats = {"peer": 3, "store": 1, "bytes": 4096}

            def restore(self, target=None, step=None,
                        extra_sources=None):
                return {"w": [1, 2]}, 40

        state, step, stats = migrate_from_checkpoint(FakeCheckpointer())
        assert state == {"w": [1, 2]} and step == 40
        assert stats["peer"] == 3 and stats["store"] == 1

    def test_migrate_from_checkpoint_nothing_restorable(self):
        class EmptyCheckpointer:
            def restore(self, target=None, step=None,
                        extra_sources=None):
                return None, None

        state, step, stats = migrate_from_checkpoint(EmptyCheckpointer())
        assert state is None and step is None
        assert stats == empty_stats()


# ------------------------------------------------------------ live migration


def _saved_world(tmp_path, step=7):
    """Four virtual hosts (2 devices each) flash-save one dp-sharded
    array; returns (state, mesh, sharding)."""
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from dlrover_tpu.trainer.checkpoint import FlashCheckpointer

    devs = jax.devices()
    mesh = Mesh(np.array(devs).reshape(4, 2), ("dp", "tp"))
    sharding = NamedSharding(mesh, P("dp", None))
    state = {
        "w": jax.device_put(
            np.arange(32, dtype=np.float32).reshape(8, 4), sharding
        ),
        "step": step,
    }
    # every rank saves before anyone waits: the store COMMIT is a
    # consensus over all four process files
    ckpts = [
        FlashCheckpointer(
            persist_dir=str(tmp_path / "store"),
            ram_dir=str(tmp_path / f"ram{p}"),
            persist_interval=1, use_orbax=False,
            process_index=p, n_processes=4,
            proc_of_device=lambda d: d.id // 2,
            commit_timeout=60,
        )
        for p in range(4)
    ]
    for c in ckpts:
        c.save(step, state, force_persist=True)
    for c in ckpts:
        c.wait()
        c.close()
    return state, mesh, sharding


class TestLiveMigration:
    DEAD = 2  # old proc whose devices (4, 5) did not survive

    def _survivor_ckpt(self, tmp_path):
        from dlrover_tpu.trainer.checkpoint import FlashCheckpointer

        # the post-transition identity: one logical process over the
        # whole (shrunken, here emulated as full) device set, fresh
        # RAM dir — only the store holds the dead rank's rows
        return FlashCheckpointer(
            persist_dir=str(tmp_path / "store"),
            ram_dir=str(tmp_path / "ram-new"),
            persist_interval=0, use_orbax=False,
            process_index=0, n_processes=1,
        )

    def _target(self, mesh):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        return {
            "w": jax.device_put(
                np.zeros((8, 4), np.float32),
                NamedSharding(mesh, P(None, "tp")),
            ),
            "step": -1,
        }

    def test_survivor_shards_move_live(self, tmp_path):
        from dlrover_tpu.reshard.migrate import migrate_live

        state, mesh, _ = _saved_world(tmp_path, step=7)
        r = self._survivor_ckpt(tmp_path)
        got, step, stats = migrate_live(
            r, state, target=self._target(mesh), step=7, live_step=7,
            held_fn=lambda d: d.id // 2 != self.DEAD,
        )
        r.close()
        assert step == 7
        np.testing.assert_array_equal(
            np.asarray(got["w"]), np.asarray(state["w"])
        )
        assert got["step"] == 7
        # survivors' rows moved device-to-device, no npz round-trip;
        # only the dead rank's rows needed a checkpoint tier
        assert stats["live"] >= 1
        assert stats["local"] + stats["peer"] + stats["store"] >= 1
        assert stats["digest_mismatch"] == 0

    def test_stale_live_state_is_skipped(self, tmp_path):
        from dlrover_tpu.reshard.migrate import migrate_live

        state, mesh, _ = _saved_world(tmp_path, step=7)
        r = self._survivor_ckpt(tmp_path)
        # the live pytree is one step AHEAD of the restore candidate:
        # serving it would mix steps — the pinned source steps aside
        got, step, stats = migrate_live(
            r, state, target=self._target(mesh), step=7, live_step=8,
            held_fn=lambda d: d.id // 2 != self.DEAD,
        )
        r.close()
        assert step == 7 and stats["live"] == 0
        np.testing.assert_array_equal(
            np.asarray(got["w"]), np.asarray(state["w"])
        )
