"""Unit tests for the reshard plane (dlrover_tpu/reshard/).

Covers the three layers in isolation: the TransitionOrder wire
format, the master-side TransitionCoordinator state machine (cut /
complete / abort / budget / fallback), and the worker-side
MeshTransition adopt-exactly-once executor plus the migrate stats
vocabulary. The end-to-end path (real master, real SIGKILL) lives in
tests/test_reshard_drill.py.
"""

import numpy as np
import pytest

import dlrover_tpu.telemetry as T
from dlrover_tpu.common.comm import ReshardResponse
from dlrover_tpu.reshard import (
    KIND_ABORT,
    KIND_GROW,
    KIND_SHRINK,
    TRANSITION_ORDER_KEY,
    MeshTransition,
    TransitionCoordinator,
    TransitionOrder,
    reshard_enabled,
    reshard_opted_in,
)
from dlrover_tpu.reshard.migrate import (
    empty_stats,
    merge_stats,
    migrate_from_checkpoint,
    reshard_arrays,
)
from dlrover_tpu.telemetry.journal import EventJournal


@pytest.fixture(autouse=True)
def _fresh_journal():
    jr = T.set_default_journal(EventJournal(None))
    yield jr
    T.set_default_journal(EventJournal(None))


def _kinds(journal, prefix="reshard"):
    return [e["kind"] for e in journal.events(prefix)]


class FakeKV:
    def __init__(self):
        self.data = {}

    def set(self, key, value):
        self.data[key] = value

    def get(self, key):
        return self.data.get(key, b"")


class FakeTaskManager:
    def __init__(self, requeued=3):
        self.requeued = requeued
        self.calls = []

    def relinquish_tasks(self, node_type, rank):
        self.calls.append((node_type, rank))
        return self.requeued


class FakeGoodput:
    def __init__(self):
        self.faults = []
        self.recovered = []

    def note_fault(self, cause="", node_id=None):
        self.faults.append((cause, node_id))

    def mark_recovered(self, cause=""):
        self.recovered.append(cause)


def _coordinator(kv=None, **kw):
    kw.setdefault("max_transitions", 8)
    kw.setdefault("abort_timeout", 120.0)
    return TransitionCoordinator(kv or FakeKV(), **kw)


def _last_order(kv):
    return TransitionOrder.from_json(kv.data[TRANSITION_ORDER_KEY])


# ---------------------------------------------------------------- wire format


class TestTransitionOrder:
    def test_json_round_trip(self):
        order = TransitionOrder(
            id=3, kind=KIND_SHRINK, step=120, old_world_size=4,
            world_size=3, survivors=[0, 1, 3], lost=[2],
            reason="heartbeat timeout",
        )
        back = TransitionOrder.from_json(order.to_json())
        assert back == order

    def test_unknown_fields_are_dropped(self):
        raw = (b'{"id": 7, "kind": "grow", "survivors": [0, 1],'
               b' "joined": [1], "from_the_future": true}')
        order = TransitionOrder.from_json(raw)
        assert order.id == 7 and order.kind == KIND_GROW
        assert not hasattr(order, "from_the_future")

    def test_missing_fields_default(self):
        order = TransitionOrder.from_json(b'{"id": 1}')
        assert order.survivors == [] and order.aborted_id == 0

    def test_non_object_payload_rejected(self):
        with pytest.raises(ValueError):
            TransitionOrder.from_json(b'[1, 2, 3]')

    def test_new_index_is_position_in_survivors(self):
        order = TransitionOrder(
            id=1, kind=KIND_SHRINK, survivors=[0, 1, 3], lost=[2]
        )
        assert order.new_index(0) == 0
        assert order.new_index(3) == 2
        assert order.new_index(2) is None  # the shed rank
        assert order.new_index(9) is None


# ------------------------------------------------------------ env three-state


class TestEnvGates:
    def test_master_opt_in_requires_explicit_flag(self, monkeypatch):
        monkeypatch.delenv("DLROVER_TPU_RESHARD", raising=False)
        assert not reshard_opted_in()
        assert reshard_enabled()  # workers poll by default
        monkeypatch.setenv("DLROVER_TPU_RESHARD", "1")
        assert reshard_opted_in() and reshard_enabled()
        monkeypatch.setenv("DLROVER_TPU_RESHARD", "0")
        assert not reshard_opted_in() and not reshard_enabled()

    def test_from_env_disabled(self, monkeypatch):
        monkeypatch.setenv("DLROVER_TPU_RESHARD", "0")
        assert MeshTransition.from_env(None) is None
        monkeypatch.delenv("DLROVER_TPU_RESHARD", raising=False)
        assert MeshTransition.from_env(None) is not None


# -------------------------------------------------------------- coordinator


class TestTransitionCoordinator:
    def test_lost_member_cuts_a_shrink_order(self, _fresh_journal):
        kv = FakeKV()
        coord = _coordinator(kv)
        for r in range(4):
            coord.note_node_running(r)
        order = coord.note_node_lost(2, reason="heartbeat timeout")
        assert order is not None and order.kind == KIND_SHRINK
        assert order.survivors == [0, 1, 3] and order.lost == [2]
        assert order.old_world_size == 4 and order.world_size == 3
        # the order is on the wire, verbatim
        assert _last_order(kv) == order
        assert _kinds(_fresh_journal) == [
            "reshard.detected", "reshard.ordered", "reshard.rebalanced",
        ]

    def test_unknown_rank_takes_the_restart_path(self):
        coord = _coordinator()
        coord.note_node_running(0)
        assert coord.note_node_lost(7) is None

    def test_min_world_guard(self):
        coord = _coordinator(min_world=2)
        coord.note_node_running(0)
        coord.note_node_running(1)
        assert coord.note_node_lost(1) is None

    def test_ledger_rebalanced_exactly_once(self, _fresh_journal):
        tm = FakeTaskManager(requeued=5)
        coord = _coordinator(task_manager=tm)
        for r in range(3):
            coord.note_node_running(r)
        coord.note_node_lost(1)
        assert tm.calls == [("worker", 1)]
        (evt,) = _fresh_journal.events("reshard.rebalanced")
        assert evt["data"]["requeued"] == 5

    def test_completion_requires_every_survivor(self, _fresh_journal):
        goodput = FakeGoodput()
        coord = _coordinator(goodput=goodput)
        for r in range(4):
            coord.note_node_running(r)
        order = coord.note_node_lost(2)
        assert goodput.faults == [("reshard", 2)]
        for phase in ("adopted", "migrated", "completed"):
            assert coord.note_worker_phase(0, order.id, phase) == "ok"
        assert coord.active_order is not None  # 1 and 3 still pending
        assert coord.note_worker_phase(1, order.id, "completed") == "ok"
        assert coord.note_worker_phase(3, order.id, "completed") == "ok"
        assert coord.active_order is None
        assert coord.world == [0, 1, 3]
        assert coord.transitions_done == 1
        assert goodput.recovered == ["reshard"]
        assert "reshard.completed" in _kinds(_fresh_journal)

    def test_stale_order_id_is_rejected(self):
        coord = _coordinator()
        for r in range(3):
            coord.note_node_running(r)
        order = coord.note_node_lost(2)
        assert coord.note_worker_phase(0, order.id + 1, "adopted") == "stale"
        # and with no open transition everything is stale
        coord.abort("test")
        assert coord.note_worker_phase(0, order.id, "completed") == "stale"

    def test_second_casualty_aborts_into_fallback(self, _fresh_journal):
        kv = FakeKV()
        fallbacks = []
        coord = _coordinator(kv, fallback_fn=fallbacks.append)
        for r in range(4):
            coord.note_node_running(r)
        order = coord.note_node_lost(2)
        # a SURVIVOR of the open order dies: undecidable remap
        assert coord.note_node_lost(1) is None
        assert coord.active_order is None
        assert fallbacks == [order]
        abort = _last_order(kv)
        assert abort.kind == KIND_ABORT and abort.aborted_id == order.id
        assert abort.id > order.id  # fresh id: adopted exactly-once too
        assert "reshard.aborted" in _kinds(_fresh_journal)
        # the lost rank left the membership either way
        assert 2 not in coord.world

    def test_worker_refusal_aborts(self):
        fallbacks = []
        coord = _coordinator(fallback_fn=fallbacks.append)
        for r in range(3):
            coord.note_node_running(r)
        order = coord.note_node_lost(2)
        assert coord.note_worker_phase(0, order.id, "aborted") == "abort"
        assert coord.active_order is None and fallbacks == [order]

    def test_abort_timeout_watchdog(self):
        coord = _coordinator(abort_timeout=10.0)
        for r in range(3):
            coord.note_node_running(r)
        order = coord.note_node_lost(2)
        import time
        coord.check_abort(now=time.time() + 5)
        assert coord.active_order is order  # still inside the window
        coord.check_abort(now=time.time() + 11)
        assert coord.active_order is None

    def test_budget_degrades_to_restart(self):
        coord = _coordinator(max_transitions=1)
        for r in range(4):
            coord.note_node_running(r)
        order = coord.note_node_lost(3)
        for r in (0, 1, 2):
            coord.note_worker_phase(r, order.id, "completed")
        assert coord.transitions_done == 1
        # budget spent: the next loss takes the restart path
        assert coord.note_node_lost(2) is None

    def test_aborted_attempt_spends_budget_too(self):
        coord = _coordinator(max_transitions=1)
        for r in range(4):
            coord.note_node_running(r)
        coord.note_node_lost(3)
        coord.abort("drill")
        assert coord.transitions_done == 1
        # a job that keeps aborting degrades to always-restart
        assert coord.note_node_lost(2) is None

    def test_join_cuts_a_grow_order(self):
        kv = FakeKV()
        coord = _coordinator(kv)
        for r in range(2):
            coord.note_node_running(r)
        order = coord.note_node_join(2)
        assert order.kind == KIND_GROW and order.survivors == [0, 1, 2]
        assert order.joined == [2] and order.world_size == 3
        # the joiner acks too; completion needs all three
        for r in (0, 1):
            coord.note_worker_phase(r, order.id, "completed")
        assert coord.active_order is not None
        coord.note_worker_phase(2, order.id, "completed")
        assert coord.world == [0, 1, 2]

    def test_join_waits_while_a_transition_is_open(self):
        coord = _coordinator()
        for r in range(3):
            coord.note_node_running(r)
        coord.note_node_lost(2)
        assert coord.note_node_join(5) is None


# ------------------------------------------------------------ worker executor


class FakeMasterClient:
    def __init__(self, kv=None, action="ok"):
        self.kv = kv or FakeKV()
        self.action = action
        self.reports = []

    def kv_store_get(self, key):
        return self.kv.get(key)

    def report_reshard(self, order_id, phase, detail=""):
        self.reports.append((order_id, phase))
        return ReshardResponse(action=self.action)


def _shrink(order_id=1, survivors=(0, 2), lost=(1,)):
    return TransitionOrder(
        id=order_id, kind=KIND_SHRINK,
        old_world_size=len(survivors) + len(lost),
        world_size=len(survivors),
        survivors=list(survivors), lost=list(lost),
    )


class TestMeshTransition:
    def test_adopt_exactly_once_by_id(self, _fresh_journal):
        client = FakeMasterClient()
        client.kv.set(TRANSITION_ORDER_KEY, _shrink().to_json())
        mt = MeshTransition(client, node_rank=2)
        first = mt.poll_order()
        assert first is not None and first.id == 1
        # the broadcast stays on the KV store; re-polls are no-ops
        assert mt.poll_order() is first
        assert len(_fresh_journal.events("reshard.adopted")) == 1

    def test_excluded_rank_stands_down(self):
        client = FakeMasterClient()
        client.kv.set(TRANSITION_ORDER_KEY, _shrink().to_json())
        mt = MeshTransition(client, node_rank=1)  # the shed rank
        assert mt.poll_order() is None
        assert mt.excluded and not mt.fallback

    def test_abort_cancels_the_pending_order(self, _fresh_journal):
        client = FakeMasterClient()
        client.kv.set(TRANSITION_ORDER_KEY, _shrink(order_id=1).to_json())
        mt = MeshTransition(client, node_rank=0)
        assert mt.poll_order() is not None
        client.kv.set(TRANSITION_ORDER_KEY, TransitionOrder(
            id=2, kind=KIND_ABORT, aborted_id=1, reason="timeout",
        ).to_json())
        assert mt.poll_order() is None
        assert mt.fallback
        assert len(_fresh_journal.events("reshard.aborted")) == 1

    def test_fresh_incarnation_ignores_stale_abort(self, _fresh_journal):
        # a relaunched process reads the abort broadcast of a
        # transition it never participated in: falling back would
        # loop relaunches forever — it must be ignored
        client = FakeMasterClient()
        client.kv.set(TRANSITION_ORDER_KEY, TransitionOrder(
            id=2, kind=KIND_ABORT, aborted_id=1, reason="timeout",
        ).to_json())
        mt = MeshTransition(client, node_rank=0)
        assert mt.poll_order() is None
        assert not mt.fallback
        assert _fresh_journal.events("reshard.aborted") == []
        # ...but a LATER abort addressed to an order this incarnation
        # adopted still falls back
        client.kv.set(TRANSITION_ORDER_KEY, _shrink(order_id=3).to_json())
        assert mt.poll_order() is not None
        client.kv.set(TRANSITION_ORDER_KEY, TransitionOrder(
            id=4, kind=KIND_ABORT, aborted_id=3, reason="refused",
        ).to_json())
        assert mt.poll_order() is None
        assert mt.fallback

    def test_pop_pending_clears_at_the_step_boundary(self):
        client = FakeMasterClient()
        client.kv.set(TRANSITION_ORDER_KEY, _shrink().to_json())
        mt = MeshTransition(client, node_rank=0)
        order = mt.poll_order()
        assert mt.pop_pending() is order
        assert mt.pending() is None

    def test_bad_broadcast_never_takes_training_down(self):
        client = FakeMasterClient()
        client.kv.set(TRANSITION_ORDER_KEY, b"{not json")
        mt = MeshTransition(client, node_rank=0)
        assert mt.poll_order() is None

    def test_stale_answer_flips_fallback(self):
        client = FakeMasterClient(action="stale")
        mt = MeshTransition(client, node_rank=0)
        assert mt.report_phase(_shrink(), "migrated") == "stale"
        assert mt.fallback

    def test_note_migrated_journals_move_stats(self, _fresh_journal):
        client = FakeMasterClient()
        mt = MeshTransition(client, node_rank=0)
        stats = merge_stats({"device": 4, "peer": 2, "bytes": 1024})
        assert mt.note_migrated(_shrink(), stats, duration_s=0.5) == "ok"
        (evt,) = _fresh_journal.events("reshard.migrated")
        assert evt["data"]["device"] == 4 and evt["data"]["peer"] == 2
        assert client.reports == [(1, "migrated")]

    def test_worker_abort_reports_and_falls_back(self, _fresh_journal):
        client = FakeMasterClient(action="abort")
        mt = MeshTransition(client, node_rank=0)
        mt.abort(_shrink(), "state digest mismatch")
        assert mt.fallback
        assert client.reports == [(1, "aborted")]
        assert len(_fresh_journal.events("reshard.aborted")) == 1

    def test_masterless_transition_still_functions(self):
        mt = MeshTransition(None, node_rank=0)
        assert mt.poll_order() is None
        assert mt.report_phase(_shrink(), "completed") is None


# ---------------------------------------------------------------- migration


class TestMigrate:
    def test_stats_vocabulary(self):
        stats = empty_stats()
        assert set(stats) == {
            "local", "peer", "store", "device", "digest_mismatch",
            "bytes",
        }
        merged = merge_stats({"peer": 1}, {"peer": 2, "bytes": 8}, None)
        assert merged["peer"] == 3 and merged["bytes"] == 8

    def test_reshard_arrays_moves_only_what_changed(self):
        import jax

        state = {"w": np.arange(8, dtype=np.float32), "step": 3}
        sharding = jax.sharding.SingleDeviceSharding(jax.devices()[0])
        new_state, stats = reshard_arrays(
            state, {"w": sharding, "step": None}
        )
        assert stats["device"] == 1  # "step" was left alone
        assert new_state["w"].sharding == sharding
        np.testing.assert_array_equal(np.asarray(new_state["w"]),
                                      state["w"])
        # already in the target layout: zero-copy, zero moves
        again, stats2 = reshard_arrays(new_state, {"w": sharding,
                                                   "step": None})
        assert stats2["device"] == 0 and again["w"] is new_state["w"]

    def test_migrate_from_checkpoint_merges_loader_stats(self):
        class FakeCheckpointer:
            last_restore_stats = {"peer": 3, "store": 1, "bytes": 4096}

            def restore(self, target=None, step=None):
                return {"w": [1, 2]}, 40

        state, step, stats = migrate_from_checkpoint(FakeCheckpointer())
        assert state == {"w": [1, 2]} and step == 40
        assert stats["peer"] == 3 and stats["store"] == 1

    def test_migrate_from_checkpoint_nothing_restorable(self):
        class EmptyCheckpointer:
            def restore(self, target=None, step=None):
                return None, None

        state, step, stats = migrate_from_checkpoint(EmptyCheckpointer())
        assert state is None and step is None
        assert stats == empty_stats()
