"""Two-job observability drill (ISSUE 19 acceptance).

Two master-attached agent groups with distinct job ids report through
ONE shared relay into ONE real master service. The per-job telemetry
pipeline must keep them apart at every layer:

* the relay pre-merges digests PER JOB and the batch wire carries the
  per-job ``digests`` dict (never the legacy single-job field);
* ``/fleet?job=a`` vs ``?job=b`` never cross-contaminate — counters,
  quantiles, hosts, stragglers are each job's own;
* the SLO state machine fires independently per job;
* one shared journal file splits back into per-job goodput accounts
  via ``dump --goodput --job``;
* the Brain advisor reads the per-job accounts and journals a
  ``brain.plan_proposed`` whose evidence chain replays end-to-end
  from the journal file.
"""

import json
import time
import urllib.request

import pytest

from dlrover_tpu.common import comm
from dlrover_tpu.common.constants import NodeType
from dlrover_tpu.telemetry import fleet as fleet_mod
from dlrover_tpu.telemetry import goodput as goodput_mod
from dlrover_tpu.telemetry.fleet import (
    DigestCollector,
    FleetAggregator,
    SLOEvaluator,
    TimeSeriesStore,
)
from dlrover_tpu.telemetry.goodput import Phase, PhaseLedger
from dlrover_tpu.telemetry.journal import (
    ENV_JOB_ID,
    EventJournal,
    read_journal,
    set_default_journal,
)

T0 = 1_700_000_000.0


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    set_default_journal(EventJournal())
    fleet_mod.set_default_collector(DigestCollector())
    yield
    set_default_journal(EventJournal())
    fleet_mod.set_default_collector(None)
    goodput_mod.set_job_provider(None)


def _goodput_fields(phases, start_ts):
    return {
        "goodput_phases": dict(phases),
        "goodput_elapsed_s": float(sum(phases.values())),
        "goodput_start_ts": start_ts,
        "goodput_phase": Phase.TRAINING,
    }


def test_two_jobs_through_one_relay_never_cross_contaminate(tmp_path):
    """The full wire: 2 agents of job "a" + 2 of job "b" through one
    AggregatorRelay into one master. Job a runs slow steps and burns
    42% of its wall in ckpt_stall/rendezvous; job b is healthy. Every
    consumer — fleet views, SLO, goodput accounts, the HTTP endpoints,
    the Brain — must attribute each signal to exactly one job."""
    from dlrover_tpu.agent.relay import AggregatorRelay
    from dlrover_tpu.agent.status_reporter import DeltaTracker
    from dlrover_tpu.brain.advisor import MODE_OBSERVE, ResourceAdvisor
    from dlrover_tpu.master.servicer import create_master_service
    from dlrover_tpu.telemetry.http import MetricsServer, set_fleet_provider
    from tests.test_ingest import _job_manager

    journal_path = str(tmp_path / "drill.jsonl")
    set_default_journal(EventJournal(journal_path))

    agg = FleetAggregator(
        store=TimeSeriesStore(max_mb=4),
        slo=SLOEvaluator(spec="step_p99_ms<=50"),
    )
    gp = goodput_mod.GoodputAggregator()
    jm, speed = _job_manager(4)
    server, servicer = create_master_service(
        0, job_manager=jm, speed_monitor=speed, fleet_aggregator=agg,
        goodput_aggregator=gp,
    )
    server.start()
    relay = AggregatorRelay(
        f"localhost:{server.port}", relay_id=0, interval=30.0,
    )
    srv = None
    try:
        now = time.time()
        # job "a": nodes 0-1, 200ms steps (violates the SLO), heavy
        # ckpt_stall + rendezvous badput
        # job "b": nodes 2-3, 10ms steps, clean account
        groups = {
            "a": ((0, 1), 0.2, 100,
                  {Phase.INIT: 8.0, Phase.TRAINING: 50.0,
                   Phase.CKPT_STALL: 30.0, Phase.RENDEZVOUS: 12.0}),
            "b": ((2, 3), 0.01, 200,
                  {Phase.INIT: 2.0, Phase.TRAINING: 98.0}),
        }
        for job, (node_ids, step_s, step, phases) in groups.items():
            for node_id in node_ids:
                tracker = DeltaTracker(incarnation=0, job_id=job)
                c = DigestCollector()
                for _ in range(30):
                    c.observe("step", step_s)
                    c.incr("steps")
                rep = tracker.compose(
                    now, step=step, pid=100 + node_id,
                    goodput_fields=_goodput_fields(phases, now - 100.0),
                    host=f"host-{node_id}",
                )
                rep.node_type, rep.node_id = NodeType.WORKER, node_id
                rep.has_metrics, rep.metrics = True, c.compose()
                assert relay.handle("report_node_status", rep).accepted

        # ------------------------------------------------ wire format
        batches = []
        orig = relay._upstream.report_relay_batch
        relay._upstream.report_relay_batch = (
            lambda b: (batches.append(b), orig(b))[1]
        )
        try:
            relay._forward_once()
        finally:
            relay._upstream.report_relay_batch = orig
        assert len(batches) == 1  # still ONE batch for both jobs
        assert set(batches[0].digests) == {"a", "b"}
        assert not batches[0].digest  # legacy field stays empty

        # --------------------------------------------- fleet views
        assert agg.jobs() == ["a", "b"]
        sa, sb = agg.snapshot(job="a"), agg.snapshot(job="b")
        assert sa["counters"] == {"steps": 60}
        assert sb["counters"] == {"steps": 60}
        assert sa["series"]["step"]["count"] == 60
        assert sa["series"]["step"]["p99_ms"] > 150.0
        assert sb["series"]["step"]["p99_ms"] < 50.0
        assert [h["host"] for h in sa["hosts"]] == ["host-0", "host-1"]
        assert [h["host"] for h in sb["hosts"]] == ["host-2", "host-3"]
        # per-job straggler lead: each job measures against ITS OWN
        # fastest host, not the other job's
        assert all(s["behind"] == 0 for s in agg.stragglers(job="b"))
        # fleet-wide view is the merge
        snap = agg.snapshot()
        assert snap["counters"] == {"steps": 120}
        assert {h["host"] for h in snap["hosts"]} == {
            "host-0", "host-1", "host-2", "host-3",
        }

        # ------------------------------------------------- SLO per job
        assert agg.slo.violated("step_p99_ms", job="a")
        assert not agg.slo.violated("step_p99_ms", job="b")
        assert sa["slo"]["step_p99_ms"]["violated"] is True
        assert sb["slo"]["step_p99_ms"]["violated"] is False

        # ------------------------------------------ goodput accounts
        ga = gp.summary(job="a")["job"]
        gb = gp.summary(job="b")["job"]
        assert ga["procs"] == 2 and gb["procs"] == 2
        assert ga["badput_s"][Phase.CKPT_STALL] == pytest.approx(60.0)
        assert ga["badput_s"][Phase.RENDEZVOUS] == pytest.approx(24.0)
        assert gb["badput_s"][Phase.CKPT_STALL] == 0.0
        assert gb["goodput_percent"] == pytest.approx(98.0)
        assert gp.jobs() == ["a", "b"]

        # ------------------------------------------- HTTP endpoints
        srv = MetricsServer(host="127.0.0.1").start()
        set_fleet_provider(agg.snapshot)
        goodput_mod.set_job_provider(gp.summary)
        base = f"http://127.0.0.1:{srv.port}"

        def get(path):
            with urllib.request.urlopen(base + path, timeout=5) as r:
                return json.loads(r.read().decode())

        doc_a = get("/fleet.json?job=a")
        doc_b = get("/fleet.json?job=b")
        assert doc_a["job"] == "a" and doc_b["job"] == "b"
        assert [h["host"] for h in doc_a["hosts"]] == [
            "host-0", "host-1",
        ]
        assert [h["host"] for h in doc_b["hosts"]] == [
            "host-2", "host-3",
        ]
        assert doc_a["slo"]["step_p99_ms"]["violated"] is True
        assert doc_b["slo"]["step_p99_ms"]["violated"] is False
        gdoc = get("/goodput?job=a")
        assert gdoc["job"]["procs"] == 2
        assert gdoc["job"]["badput_s"][Phase.CKPT_STALL] == \
            pytest.approx(60.0)

        # ------------------------------- Brain: evidence from journal
        adv = ResourceAdvisor(
            fleet=agg, goodput=gp,
            speed_monitors_fn=servicer.job_speed_monitors,
            mode=MODE_OBSERVE, interval=0,
        )
        plans = adv.step(now=now)
        assert [
            (p["job"], p["action"]) for p in plans
        ] == [("a", "shrink")]

        # replay the journal FILE: the proposal and its full evidence
        # chain must reconstruct from disk, not from live state
        events = read_journal(journal_path)
        proposed = [
            e for e in events if e["kind"] == "brain.plan_proposed"
        ]
        assert len(proposed) == 1
        d = proposed[0]["data"]
        assert d["job"] == "a" and d["action"] == "shrink"
        assert d["rule"] == "shrink_badput"
        assert d["mode"] == MODE_OBSERVE
        assert d["evidence_ckpt_stall_s"] == pytest.approx(60.0)
        assert d["evidence_rendezvous_s"] == pytest.approx(24.0)
        assert d["evidence_stall_pct"] == pytest.approx(42.0)
        assert d["evidence_threshold_pct"] == 25.0
        assert d["evidence_window_s"] == pytest.approx(200.0)
        assert d["evidence_workers"] == 2
        assert d["target_nodes"] == 1
        assert d["expected_goodput_delta"] == pytest.approx(42.0)
        # the SLO violation that fired for job a is on disk too, and
        # never for job b
        violated = [
            e["data"] for e in events if e["kind"] == "slo.violated"
        ]
        assert "a" in {v.get("job") for v in violated}
        assert all(v.get("job") != "b" for v in violated)
    finally:
        goodput_mod.set_job_provider(None)
        set_fleet_provider(None)
        if srv is not None:
            srv.stop()
        relay.stop(flush=False, grace=0.0)
        server.stop(grace=0.2)
        servicer.close()


def test_shared_journal_splits_into_per_job_goodput_accounts(
        tmp_path, monkeypatch, capsys):
    """Two jobs' ledgers write breadcrumbs into ONE journal file (the
    launcher-shared layout); ``dump --goodput --job`` rebuilds each
    job's account with zero bleed from the sibling."""
    from dlrover_tpu.telemetry import dump

    path = str(tmp_path / "shared.jsonl")

    # job "a": 10s init, then training with 40s re-labeled ckpt_stall
    monkeypatch.setenv(ENV_JOB_ID, "a")
    set_default_journal(EventJournal(path))
    led_a = PhaseLedger(start_ts=T0, phase=Phase.INIT)
    led_a.transition(Phase.TRAINING, ts=T0 + 10)
    led_a.credit(Phase.CKPT_STALL, 40.0, ts=T0 + 90)
    led_a.close(ts=T0 + 100)

    # job "b": 5s init, training straight through — same host, same
    # pid, same file: only the envelope job field keeps them apart
    monkeypatch.setenv(ENV_JOB_ID, "b")
    set_default_journal(EventJournal(path))
    led_b = PhaseLedger(start_ts=T0, phase=Phase.INIT)
    led_b.transition(Phase.TRAINING, ts=T0 + 5)
    led_b.close(ts=T0 + 100)

    monkeypatch.delenv(ENV_JOB_ID)
    set_default_journal(EventJournal())

    events = read_journal(path)
    assert {e.get("job") for e in events} == {"a", "b"}

    # library path: reconstruct() splits on the envelope namespace
    ra = goodput_mod.reconstruct(events, job="a")["job"]
    rb = goodput_mod.reconstruct(events, job="b")["job"]
    assert ra["badput_s"][Phase.CKPT_STALL] == pytest.approx(40.0)
    assert ra["goodput_percent"] == pytest.approx(50.0)
    assert rb["badput_s"][Phase.CKPT_STALL] == 0.0
    assert rb["goodput_percent"] == pytest.approx(95.0)

    # CLI path: dump --goodput --json --job
    assert dump.main([path, "--goodput", "--json", "--job", "a"]) == 0
    doc_a = json.loads(capsys.readouterr().out)
    assert doc_a["job"]["goodput_percent"] == pytest.approx(50.0)
    assert doc_a["job"]["badput_s"][Phase.CKPT_STALL] == \
        pytest.approx(40.0)
    assert dump.main([path, "--goodput", "--json", "--job", "b"]) == 0
    doc_b = json.loads(capsys.readouterr().out)
    assert doc_b["job"]["goodput_percent"] == pytest.approx(95.0)
    assert doc_b["job"]["badput_s"][Phase.CKPT_STALL] == 0.0
