"""Evaluator side-job role (VERDICT r2 Missing #5 / M6 role depth):
spec-declared eval replicas provisioned next to the worker fleet, a
checkpoint-watching eval loop, and eval results flowing into the
master's custom-metric stats channel. Parity role:
dlrover/python/master/node/worker.py:32 EvaluatorManager + the
estimator evaluator replica."""

import jax.numpy as jnp
import numpy as np

from dlrover_tpu.common.constants import NodeType
from dlrover_tpu.common.node import Node, NodeResource
from dlrover_tpu.master.scaler.base_scaler import ScalePlan, Scaler
from dlrover_tpu.scheduler.job_spec import JobArgs
from dlrover_tpu.trainer.checkpoint import FlashCheckpointer
from dlrover_tpu.trainer.evaluator import CheckpointEvaluator


class RecordingScaler(Scaler):
    def __init__(self):
        super().__init__("j")
        self.launched = []

    def supports_role(self, node_type):
        return True  # test double: every role has an entrypoint

    def scale(self, plan: ScalePlan):
        self.launched.extend(plan.launch_nodes)


def test_spec_declares_evaluator_role(tmp_path):
    spec = tmp_path / "job.yaml"
    spec.write_text("""
apiVersion: dlrover-tpu/v1
kind: ElasticTpuJob
metadata: {name: evaljob}
spec:
  platform: process
  worker:
    replicas: 2
  evaluator:
    replicas: 1
    command: [python, eval.py]
    env: {EVAL_SPLIT: validation}
    resource: {cpu: 4, memory: 8Gi}
""")
    args = JobArgs.from_file(str(spec))
    assert args.evaluator_num == 1
    assert args.evaluator_command == ["python", "eval.py"]
    assert args.evaluator_env == {"EVAL_SPLIT": "validation"}
    assert args.evaluator_resource.memory == 8192


def test_job_manager_provisions_evaluators():
    from dlrover_tpu.master.node.dist_job_manager import (
        DistributedJobManager,
    )

    args = JobArgs(
        job_name="j", node_num=2,
        node_resource=NodeResource(cpu=1),
        evaluator_num=1,
        evaluator_resource=NodeResource(cpu=4),
    )
    scaler = RecordingScaler()
    jm = DistributedJobManager(job_args=args, scaler=scaler)
    jm.start()
    try:
        workers = [
            n for n in scaler.launched if n.type == NodeType.WORKER
        ]
        evals = [
            n for n in scaler.launched if n.type == NodeType.EVALUATOR
        ]
        assert len(workers) == 2
        assert len(evals) == 1
        assert not evals[0].critical
        # evaluators never gate job completion (workers-only check)
        assert not jm.all_workers_exited()
    finally:
        jm.stop()


def test_evaluator_failure_relaunches_without_touching_workers():
    from dlrover_tpu.common.constants import (
        NodeEventType,
        NodeExitReason,
        NodeStatus,
    )
    from dlrover_tpu.master.node.dist_job_manager import (
        DistributedJobManager,
    )
    from dlrover_tpu.master.watcher.base_watcher import NodeEvent

    args = JobArgs(
        job_name="j", node_num=1, evaluator_num=1,
        node_resource=NodeResource(cpu=1),
    )
    scaler = RecordingScaler()
    jm = DistributedJobManager(job_args=args, scaler=scaler)
    jm.start()
    try:
        ev = next(
            n for n in scaler.launched
            if n.type == NodeType.EVALUATOR
        )
        dead = Node(NodeType.EVALUATOR, ev.id, name=ev.name,
                    status=NodeStatus.FAILED)
        dead.set_exit_reason(NodeExitReason.KILLED)
        jm.process_event(NodeEvent(NodeEventType.MODIFIED, dead))
        emgr = jm._node_managers[NodeType.EVALUATOR]
        relaunched = [
            n for n in emgr.nodes.values() if not n.is_released
        ]
        assert len(relaunched) == 1
        assert relaunched[0].id != ev.id
        # the worker fleet is untouched
        wmgr = jm._node_managers[NodeType.WORKER]
        assert len(wmgr.unfinished_nodes()) == 1
        assert not jm.is_job_failed()
    finally:
        jm.stop()


def test_checkpoint_evaluator_loop(tmp_path):
    ckpt = FlashCheckpointer(
        persist_dir=str(tmp_path / "persist"),
        ram_dir=str(tmp_path / "ram"),
        persist_interval=0, use_orbax=False,
    )
    reported = []
    evaluated = []

    def eval_fn(state, step):
        evaluated.append(step)
        return {"loss": float(jnp.sum(state["w"]))}

    evaluator = CheckpointEvaluator(
        ckpt, eval_fn,
        report_fn=lambda step, res: reported.append((step, res)),
        poll_interval=0.01,
    )
    assert evaluator.poll_once() is None  # nothing saved yet
    ckpt.save(5, {"w": jnp.ones((4,))})
    ckpt.wait()
    res = evaluator.poll_once()
    assert res == {"loss": 4.0}
    assert evaluator.poll_once() is None  # same step: not re-evaluated
    ckpt.save(10, {"w": jnp.full((4,), 2.0)})
    ckpt.wait()
    n = evaluator.run(max_evals=1, deadline=None)
    assert n == 1
    assert evaluated == [5, 10]
    assert reported[0][0] == 5
    assert reported[1] == (10, {"loss": 8.0})


def test_eval_results_reach_master_stats(tmp_path):
    """End-to-end over the wire: evaluator -> report_custom_data RPC ->
    job collector custom metrics."""
    from dlrover_tpu.agent.master_client import MasterClient
    from dlrover_tpu.master.servicer import create_master_service
    from dlrover_tpu.master.stats.job_collector import (
        JobMetricCollector,
    )
    from dlrover_tpu.master.stats.reporter import JobMeta

    collector = JobMetricCollector(JobMeta(name="j"))
    server, servicer = create_master_service(
        0, job_metric_collector=collector
    )
    server.start()
    try:
        client = MasterClient(
            f"localhost:{server.port}", 0, NodeType.EVALUATOR
        )
        client.report_custom_data({"eval_step": 5, "eval_loss": 1.5})
        assert collector._custom["eval_loss"] == 1.5
        assert collector._custom["eval_step"] == 5
    finally:
        server.stop()


def test_process_scaler_uses_per_role_command(tmp_path):
    import time

    from dlrover_tpu.master.scaler.process_scaler import ProcessScaler

    out = tmp_path / "role.txt"
    scaler = ProcessScaler(
        "j", "localhost:1",
        command=["python", "-c",
                 f"open(r'{out}', 'a').write('worker\\n')"],
        commands={"evaluator": [
            "python", "-c",
            f"open(r'{out}', 'a').write('evaluator\\n')",
        ]},
    )
    try:
        plan = ScalePlan()
        w = Node(NodeType.WORKER, 0, rank_index=0)
        e = Node(NodeType.EVALUATOR, 0, rank_index=0)
        w.config_resource = e.config_resource = NodeResource()
        plan.launch_nodes += [w, e]
        scaler.scale(plan)
        deadline = time.time() + 20
        while time.time() < deadline:
            lines = sorted(
                out.read_text().split()
            ) if out.exists() else []
            if lines == ["evaluator", "worker"]:
                break
            time.sleep(0.2)
        assert sorted(out.read_text().split()) == [
            "evaluator", "worker",
        ]
    finally:
        scaler.stop()


import pytest


@pytest.mark.drill
def test_evaluator_e2e_with_training_job(tmp_path):
    """Full job: master (process platform) supervising one training
    worker AND one evaluator replica; the evaluator must produce eval
    rows from the worker's flash checkpoints while training runs."""
    import os
    import signal
    import subprocess
    import sys
    import time

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    tmp = str(tmp_path)
    ckpt = os.path.join(tmp, "ckpt")
    eval_out = os.path.join(tmp, "eval.txt")
    progress = os.path.join(tmp, "progress.txt")
    spec = os.path.join(tmp, "job.yaml")
    with open(spec, "w") as f:
        f.write(f"""
apiVersion: dlrover-tpu/v1
kind: ElasticTpuJob
metadata: {{name: eval-e2e}}
spec:
  platform: process
  worker:
    replicas: 1
    env: {{JAX_PLATFORMS: cpu}}
    command:
      - {sys.executable}
      - -m
      - dlrover_tpu.trainer.elastic_run
      - --nnodes
      - "1:1"
      - --monitor_interval
      - "0.3"
      - {os.path.join(repo, 'examples', 'dist_train.py')}
      - --
      - --steps
      - "120"
      - --step-time
      - "0.1"
      - --ckpt-dir
      - {ckpt}
      - --progress
      - {progress}
  evaluator:
    replicas: 1
    env: {{JAX_PLATFORMS: cpu}}
    command:
      - {sys.executable}
      - {os.path.join(repo, 'examples', 'eval_loop.py')}
      - --ckpt-dir
      - {ckpt}
      - --poll
      - "0.5"
      - --max-evals
      - "2"
      - --out
      - {eval_out}
""")
    env = dict(os.environ)
    parts = [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
             if p and "axon" not in p]
    env["PYTHONPATH"] = os.pathsep.join(parts + [repo])
    env["JAX_PLATFORMS"] = "cpu"
    master = subprocess.Popen(
        [sys.executable, "-m", "dlrover_tpu.master.main",
         "--job_spec", spec, "--port", "0"],
        cwd=repo, env=env,
        stdout=open(os.path.join(tmp, "m.out"), "w"),
        stderr=open(os.path.join(tmp, "m.err"), "w"),
        start_new_session=True,
    )
    try:
        deadline = time.time() + 180
        rows = []
        while time.time() < deadline:
            if os.path.exists(eval_out):
                rows = [
                    ln for ln in open(eval_out).read().splitlines()
                    if "," in ln
                ]
                if len(rows) >= 2:
                    break
            assert master.poll() is None, (
                open(os.path.join(tmp, "m.err")).read()[-2000:]
            )
            time.sleep(0.5)
        assert len(rows) >= 2, (
            f"evaluator produced {rows}; master.err: "
            + open(os.path.join(tmp, "m.err")).read()[-2000:]
        )
        # rows are "step,loss" with increasing steps and finite loss
        steps = [int(r.split(",")[0]) for r in rows]
        losses = [float(r.split(",")[1]) for r in rows]
        assert steps == sorted(steps) and steps[0] > 0
        assert all(np.isfinite(v) for v in losses)
    finally:
        try:
            os.killpg(os.getpgid(master.pid), signal.SIGTERM)
        except (ProcessLookupError, PermissionError):
            pass
        time.sleep(1)
        try:
            os.killpg(os.getpgid(master.pid), signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass


def test_unsupported_platform_skips_evaluator_role():
    """A scaler with no evaluator entrypoint (GKE/TPU-VM without a
    per-role command) must skip the role with a warning, never launch
    the training workload under the evaluator label."""
    from dlrover_tpu.master.node.dist_job_manager import (
        DistributedJobManager,
    )

    class WorkerOnlyScaler(RecordingScaler):
        def supports_role(self, node_type):
            return node_type == NodeType.WORKER

    args = JobArgs(
        job_name="j", node_num=1, evaluator_num=1,
        node_resource=NodeResource(cpu=1),
    )
    scaler = WorkerOnlyScaler()
    jm = DistributedJobManager(job_args=args, scaler=scaler)
    jm.start()
    try:
        assert all(
            n.type == NodeType.WORKER for n in scaler.launched
        )
    finally:
        jm.stop()


def test_process_scaler_fails_roles_without_command(tmp_path):
    """A non-worker node with no per-role command fails FATAL instead
    of silently running the training command as a rogue trainer."""
    from dlrover_tpu.common.constants import NodeExitReason
    from dlrover_tpu.master.scaler.process_scaler import ProcessScaler

    scaler = ProcessScaler(
        "j", "localhost:1", command=["python", "-c", "pass"],
    )
    try:
        assert not scaler.supports_role(NodeType.EVALUATOR)
        node = Node(NodeType.EVALUATOR, 0, rank_index=0)
        node.config_resource = NodeResource()
        plan = ScalePlan()
        plan.launch_nodes.append(node)
        scaler.scale(plan)
        failed = scaler.watcher._nodes[(NodeType.EVALUATOR, 0)]
        assert failed.exit_reason == NodeExitReason.FATAL_ERROR
    finally:
        scaler.stop()
