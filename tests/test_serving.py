"""Serving tier unit tests: router exactly-once, redelivery, autoscale,
worker rotation, wire codec, injection grammar, goodput phase.

Mirrors the shard-ledger exactly-once suite (test_shard_dispatch.py):
the request plane must survive worker death (lease-timeout redelivery),
incarnation churn (world resize), and duplicate completions without a
single dropped or doubled response.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import pytest

from dlrover_tpu.agent.master_client import LocalMasterClient, MasterClient
from dlrover_tpu.common import comm
from dlrover_tpu.common.constants import NodeType
from dlrover_tpu.fault_tolerance.injection import (
    SERVING_KINDS,
    FaultInjector,
    parse_spec,
)
from dlrover_tpu.master.local_master import LocalJobMaster
from dlrover_tpu.serving import (
    DRAIN_EXIT_CODE,
    ReplicaRotation,
    RequestRouter,
    ServingAutoScaler,
    ServingWorker,
)
from dlrover_tpu.telemetry import goodput
from dlrover_tpu.telemetry.goodput import BADPUT_CAUSES, PHASES, Phase

W = NodeType.WORKER
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------------------------ router


def test_router_submit_lease_complete_poll():
    r = RequestRouter()
    ok, rid, reason = r.submit(b"ping")
    assert ok and rid and not reason
    batch, sealed = r.lease(W, 0, max_requests=4, incarnation=0)
    assert batch == [(rid, b"ping")] and not sealed
    assert r.complete(W, 0, rid, b"pong")
    done, payload, worker_id, latency = r.poll(rid)
    assert done and payload == b"pong" and worker_id == 0
    assert latency >= 0.0


def test_router_continuous_batching_no_waiting():
    """lease() returns whatever is queued NOW — it never blocks for a
    full batch, and a mid-flight submit rides the NEXT micro-batch."""
    r = RequestRouter()
    r.submit(b"a", req_id="a")
    batch, _ = r.lease(W, 0, max_requests=8, incarnation=0)
    assert [i for i, _ in batch] == ["a"]  # partial batch, no wait
    # submitted while "a" is in flight: lands in the next lease
    r.submit(b"b", req_id="b")
    r.submit(b"c", req_id="c")
    batch2, _ = r.lease(W, 0, max_requests=8, incarnation=0)
    assert [i for i, _ in batch2] == ["b", "c"]


def test_router_backpressure_and_seal_reject():
    r = RequestRouter(max_queue=2)
    assert r.submit(b"1")[0] and r.submit(b"2")[0]
    ok, _, reason = r.submit(b"3")
    assert not ok and reason == "backpressure"
    stats = r.stats()
    assert stats["rejected"] == 1 and stats["queue_depth"] == 2
    r.seal()
    ok, _, reason = r.submit(b"4")
    assert not ok and reason == "sealed"
    # an explicit req_id colliding with a live request is a duplicate
    r2 = RequestRouter()
    assert r2.submit(b"x", req_id="dup")[0]
    ok, _, reason = r2.submit(b"y", req_id="dup")
    assert not ok and reason == "duplicate"


def test_router_duplicate_completion_rejected():
    r = RequestRouter()
    _, rid, _ = r.submit(b"q")
    r.lease(W, 0, incarnation=0)
    assert r.complete(W, 0, rid, b"first")
    assert not r.complete(W, 0, rid, b"second")
    assert not r.complete(W, 1, rid, b"third")
    done, payload, _, _ = r.poll(rid)
    assert done and payload == b"first"  # first completion wins
    assert r.stats()["duplicates"] == 2


def test_router_lease_timeout_redelivery():
    """The watchdog requeues leased-but-unacked requests: worker death
    without a goodbye (SIGKILL) never drops a request."""
    r = RequestRouter(lease_timeout=0.15)
    _, rid, _ = r.submit(b"q")
    batch, _ = r.lease(W, 0, incarnation=0)
    assert batch
    assert r.check_timeouts() == 0  # lease still fresh
    time.sleep(0.2)
    assert r.check_timeouts() == 1
    # redelivered to the front: another worker picks it up, completes
    batch2, _ = r.lease(W, 1, incarnation=0)
    assert batch2 == [(rid, b"q")]
    assert r.complete(W, 1, rid, b"resp")
    # the dead worker's late ghost is rejected — exactly one response
    assert not r.complete(W, 0, rid, b"ghost")
    done, payload, worker_id, _ = r.poll(rid)
    assert done and payload == b"resp" and worker_id == 1
    assert r.stats()["redelivered"] == 1


def test_router_redelivered_goes_to_queue_front():
    r = RequestRouter(lease_timeout=0.1)
    r.submit(b"old", req_id="old")
    r.lease(W, 0, incarnation=0)
    r.submit(b"new", req_id="new")
    time.sleep(0.15)
    r.check_timeouts()
    batch, _ = r.lease(W, 1, max_requests=2, incarnation=0)
    # the redelivered request is the oldest outstanding work
    assert [i for i, _ in batch] == ["old", "new"]


def test_router_incarnation_reclaims_dead_workers_leases():
    """A lease from a newer incarnation of the SAME worker proves the
    older process is dead: its in-flight requests requeue instantly
    (no watchdog wait) — exactly-once across a world resize."""
    r = RequestRouter(lease_timeout=60.0)  # watchdog would be too slow
    r.submit(b"q", req_id="q")
    batch, _ = r.lease(W, 0, max_requests=1, incarnation=0)
    assert batch
    # same node id comes back as incarnation 1: old lease reclaimed and
    # immediately re-leased to the new process in the same call
    batch2, _ = r.lease(W, 0, max_requests=1, incarnation=1)
    assert batch2 == [("q", b"q")]
    assert r.complete(W, 0, "q", b"resp")
    done, payload, _, _ = r.poll("q")
    assert done and payload == b"resp"
    assert r.stats()["redelivered"] == 1
    # a DIFFERENT node's incarnation does not touch this worker
    r.submit(b"q2", req_id="q2")
    r.lease(W, 0, max_requests=1, incarnation=1)
    r.lease(W, 3, max_requests=1, incarnation=5)
    assert r.stats()["redelivered"] == 1


def test_router_relinquish_requeues_for_survivors():
    r = RequestRouter(lease_timeout=60.0)
    for i in range(3):
        r.submit(str(i).encode(), req_id=f"r{i}")
    batch, _ = r.lease(W, 0, max_requests=3, incarnation=0)
    assert len(batch) == 3
    assert r.relinquish(W, 0) == 3
    # a survivor picks up all three, in submit order
    batch2, _ = r.lease(W, 1, max_requests=3, incarnation=0)
    assert [i for i, _ in batch2] == ["r0", "r1", "r2"]
    assert r.relinquish(W, 0) == 0  # idempotent


def test_router_finished_requires_delivery_and_seal():
    r = RequestRouter()
    _, rid, _ = r.submit(b"q")
    assert not r.finished()
    r.lease(W, 0, incarnation=0)
    r.complete(W, 0, rid, b"resp")
    r.seal()
    # completed but the poller has not collected the response yet
    assert not r.finished() and not r.stats()["drained"]
    r.poll(rid)
    assert r.finished() and r.stats()["drained"]
    batch, sealed = r.lease(W, 0, incarnation=0)
    assert batch == [] and sealed  # the worker's exit signal


def test_router_stats_match_serve_stats_wire_fields():
    """rpc_serve_stats does ServeStats(**router.stats()): every stats
    key must be a wire field, or the RPC breaks at runtime."""
    r = RequestRouter()
    stats = r.stats()
    wire = comm.ServeStats(**stats)  # raises on any mismatch
    assert set(stats) == {
        f for f in wire.__dataclass_fields__
    }


# -------------------------------------------------------------- autoscaler


def _scaler(stats, calls, **kw):
    kw.setdefault("min_replicas", 1)
    kw.setdefault("max_replicas", 4)
    kw.setdefault("queue_high", 10)
    kw.setdefault("p99_high_ms", 1000.0)
    return ServingAutoScaler(
        stats_fn=lambda: stats, scale_fn=calls.append, **kw
    )


def test_autoscaler_inert_without_traffic():
    calls = []
    s = _scaler({"submitted": 0, "queue_depth": 99, "workers": 1}, calls)
    assert s.evaluate() is None and not calls
    assert _scaler(None, calls).evaluate() is None


def test_autoscaler_scales_up_on_queue_depth_and_p99():
    calls = []
    stats = {"submitted": 50, "queue_depth": 11, "p99_ms": 1.0,
             "workers": 2, "in_flight": 2, "sealed": False}
    assert _scaler(stats, calls).evaluate() == 3
    stats = {"submitted": 50, "queue_depth": 0, "p99_ms": 5000.0,
             "workers": 2, "in_flight": 2, "sealed": False}
    assert _scaler(stats, calls).evaluate() == 3
    assert calls == [3, 3]


def test_autoscaler_respects_bounds_and_idles_down():
    calls = []
    # at max: a hot queue does not scale past the ceiling
    hot = {"submitted": 9, "queue_depth": 99, "p99_ms": 9e9,
           "workers": 4, "in_flight": 1, "sealed": False}
    assert _scaler(hot, calls).evaluate() is None
    # idle (empty queue, low p99, nothing in flight): shed one replica
    idle = {"submitted": 9, "queue_depth": 0, "p99_ms": 10.0,
            "workers": 3, "in_flight": 0, "sealed": False}
    assert _scaler(idle, calls).evaluate() == 2
    # but never below min_replicas
    idle["workers"] = 1
    assert _scaler(idle, calls).evaluate() is None
    # a sealed, drained stream is left alone (workers exit on their own)
    done = {"submitted": 9, "queue_depth": 0, "p99_ms": 10.0,
            "workers": 3, "in_flight": 0, "sealed": True}
    assert _scaler(done, calls).evaluate() is None
    assert calls == [2]


def test_autoscaler_attributed_hold_on_model_time(monkeypatch):
    """ISSUE 17: a p99 blown by MODEL time is not fixable by adding a
    replica — the scaler holds and journals the attribution. The same
    p99 blown by QUEUE WAIT scales as before."""
    from dlrover_tpu.telemetry.journal import (
        EventJournal,
        default_journal,
        set_default_journal,
    )

    set_default_journal(EventJournal())
    try:
        calls = []
        held = {"submitted": 50, "queue_depth": 0, "p99_ms": 5000.0,
                "queue_wait_p99_ms": 40.0, "model_time_p99_ms": 4900.0,
                "workers": 2, "in_flight": 2, "sealed": False}
        assert _scaler(held, calls).evaluate() is None
        assert not calls
        evs = default_journal().events("serve.autoscale_held")
        assert len(evs) == 1
        ev = evs[0]["data"]
        assert ev["cause"] == "model_time"
        assert ev["model_time_p99_ms"] == 4900.0
        assert ev["queue_wait_p99_ms"] == 40.0
        assert ev["replicas"] == 2

        # queue-wait-dominated: one more replica genuinely helps
        waity = dict(held, queue_wait_p99_ms=4900.0,
                     model_time_p99_ms=40.0)
        assert _scaler(waity, calls).evaluate() == 3
        assert calls == [3]
        scaled = default_journal().events("serve.autoscale")
        assert scaled and scaled[-1]["data"]["reason"] == "p99_latency"
        assert scaled[-1]["data"]["queue_wait_p99_ms"] == 4900.0

        # stats from an older router (no split keys) keep the legacy
        # behavior: p99 alone scales
        legacy = {"submitted": 50, "queue_depth": 0, "p99_ms": 5000.0,
                  "workers": 2, "in_flight": 2, "sealed": False}
        assert _scaler(legacy, calls).evaluate() == 3
    finally:
        set_default_journal(EventJournal())


def test_router_splits_latency_into_queue_wait_and_model_time():
    """The router attributes each completion's latency to queue wait
    (submit -> winning lease) vs model time (lease -> complete) — the
    signal the autoscaler hold and the SLO attribution read."""
    r = RequestRouter()
    ok, rid, _ = r.submit(b"ping")
    assert ok
    time.sleep(0.12)  # queue wait: nobody leases yet
    batch, _sealed = r.lease(W, 0, max_requests=4, incarnation=0)
    assert batch == [(rid, b"ping")]
    time.sleep(0.02)  # model time: short
    assert r.complete(W, 0, rid, b"pong")
    stats = r.stats()
    wait_ms = stats["queue_wait_p99_ms"]
    model_ms = stats["model_time_p99_ms"]
    assert wait_ms >= 80.0  # dominated by the pre-lease sleep
    assert model_ms < wait_ms
    # the split partitions the end-to-end latency (allow scheduler slop)
    assert wait_ms + model_ms == pytest.approx(stats["p99_ms"], rel=0.25)


# -------------------------------------------------- injection grammar


def test_parse_spec_serve_kill():
    (f,) = parse_spec("serve_kill@6")
    assert f.kind == "serve_kill" and f.step == 6 and not f.arg
    (f,) = parse_spec("serve_kill@6:host=1")
    assert f.kind == "serve_kill" and f.arg == "host=1"
    assert f.due(6) and not f.due(5)
    # kv continuation across the comma split, like sdc@5:flip=2,host=1
    (f,) = parse_spec("serve_kill@3:host=0,delay=1")
    assert f.arg == "host=0,delay=1"
    assert "serve_kill" in SERVING_KINDS
    with pytest.raises(ValueError):
        parse_spec("serve_murder@6")


def test_serve_kill_role_and_host_filter():
    # only a serving-role injector keeps serve_kill; trainers and the
    # master drop it, so one shared spec can chaos a mixed job
    assert not FaultInjector("serve_kill@6", role="worker")._faults
    assert not FaultInjector("serve_kill@6", role="master")._faults
    kept = FaultInjector("serve_kill@6", role="serving")._faults
    assert [f.kind for f in kept] == ["serve_kill"]
    # host= pins the kill to one node rank
    assert FaultInjector(
        "serve_kill@6:host=1", role="serving", node_rank=1
    )._faults
    assert not FaultInjector(
        "serve_kill@6:host=1", role="serving", node_rank=0
    )._faults
    # a serving worker still drops master kinds
    assert not FaultInjector("master_crash@2", role="serving")._faults


# ----------------------------------------------------------- wire codec


def test_serving_messages_round_trip():
    lease = comm.ServeLease(
        requests=[
            comm.ServeWireRequest(req_id="a", payload=b"\x00\xffraw"),
            comm.ServeWireRequest(req_id="b", payload=b"y"),
        ],
        sealed=True,
    )
    got = comm.deserialize(comm.serialize(lease))
    assert got == lease
    assert got.requests[0].payload == b"\x00\xffraw"
    stats = comm.ServeStats(queue_depth=3, p99_ms=12.5, sealed=True)
    got = comm.deserialize(comm.serialize(stats))
    assert got.queue_depth == 3 and got.p99_ms == 12.5 and got.sealed
    resp = comm.ServeResponse(done=True, req_id="r", payload=b"z",
                              worker_id=2, latency_s=0.25)
    assert comm.deserialize(comm.serialize(resp)) == resp


# -------------------------------------------------------- goodput phase


def test_serving_phase_is_goodput_not_badput():
    assert Phase.SERVING in PHASES
    assert Phase.SERVING not in BADPUT_CAUSES
    led = goodput.PhaseLedger(start_ts=1000.0, journal_events=False)
    goodput.EVENT_RULES["serve.worker_ready"](led, 1002.0, {})
    assert led.phase == Phase.SERVING
    totals = led.totals(now=1007.0)
    assert totals[Phase.SERVING] == pytest.approx(5.0)


# ---------------------------------------------------- rotation handler


def test_replica_rotation_sets_flag_and_restores():
    rot = ReplicaRotation()
    prev = signal.getsignal(signal.SIGUSR2)
    assert rot.arm(signums=(signal.SIGUSR2,))
    assert not rot.draining
    signal.raise_signal(signal.SIGUSR2)
    # the handler only FLAGS — the serve loop finishes the in-flight
    # batch before draining, so no response is dropped
    assert rot.draining and rot.reason == "signal-sigusr2"
    rot.disarm()
    assert signal.getsignal(signal.SIGUSR2) == prev


# ----------------------------------------- worker over LocalMasterClient


def _echo_model(payloads, state):
    return [p.upper() for p in payloads]


def test_serving_worker_end_to_end_local():
    client = LocalMasterClient()
    req_ids = []
    for i in range(20):
        ok, rid, _ = client.serve_submit(f"msg{i}".encode())
        assert ok
        req_ids.append(rid)
    client.serve_seal()
    worker = ServingWorker(
        client, _echo_model, node_id=0, batch_size=4,
        poll_interval=0.01, incarnation=0,
    )
    served = worker.serve()
    assert served == 20 and worker.rejected == 0
    for i, rid in enumerate(req_ids):
        done, payload, worker_id, _ = client.serve_poll(rid)
        assert done and payload == f"msg{i}".upper().encode()
        assert worker_id == 0
    stats = client.serve_stats()
    assert stats["completed"] == 20 and stats["drained"]


def test_serving_worker_drain_rotation_exits_rc21():
    """trigger() mid-stream: the worker completes its in-flight batch,
    relinquishes the rest, and exits DRAIN_EXIT_CODE — zero dropped."""
    client = LocalMasterClient()
    for i in range(8):
        client.serve_submit(f"m{i}".encode(), req_id=f"m{i}")
    exit_codes = []
    rot = ReplicaRotation()

    def slow_model(payloads, state):
        rot.trigger("test-rotation")  # drain lands mid-batch
        return [p.upper() for p in payloads]

    worker = ServingWorker(
        client, slow_model, node_id=0, batch_size=2,
        poll_interval=0.01, incarnation=0, rotation=rot,
        exit_fn=exit_codes.append,
    )
    worker.serve()
    assert exit_codes == [DRAIN_EXIT_CODE]
    # the in-flight batch was COMPLETED before the drain...
    assert worker.served == 2
    done, payload, _, _ = client.serve_poll("m0")
    assert done and payload == b"M0"
    # ...and everything else went back to the queue for a survivor
    stats = client.serve_stats()
    assert stats["completed"] == 2
    assert stats["queue_depth"] + stats["in_flight"] == 6
    batch, _ = client._serve_router().lease(W, 1, max_requests=8,
                                            incarnation=0)
    assert len(batch) >= 6 - stats["in_flight"]


def test_serving_worker_rejected_completion_not_counted():
    """A redelivered request's late ghost completion is the ROUTER's
    rejection; the worker must not count it as served."""
    client = LocalMasterClient()
    router = client._serve_router()
    client.serve_submit(b"q", req_id="q")
    # worker 1 steals and completes the request first
    router.lease(W, 1, incarnation=0)
    router.complete(W, 1, "q", b"theirs")
    worker = ServingWorker(client, _echo_model, node_id=0,
                           incarnation=0)
    worker._process([("q", b"q")])
    assert worker.served == 0 and worker.rejected == 1


# ------------------------------------------------------ grpc round trip


def test_serving_rpcs_over_grpc():
    master = LocalJobMaster(port=0)
    master.prepare()
    try:
        lb = MasterClient(master.addr, node_id=9, node_type=W)
        wk = MasterClient(master.addr, node_id=0, node_type=W)
        ok, rid, _ = lb.serve_submit(b"\x01bin")
        assert ok
        batch, sealed = wk.serve_lease(max_requests=4, incarnation=0)
        assert batch == [(rid, b"\x01bin")] and not sealed
        assert wk.serve_complete(rid, b"\x02out")
        assert not wk.serve_complete(rid, b"\x02dup")  # exactly-once
        done, payload, worker_id, latency = lb.serve_poll(rid)
        assert done and payload == b"\x02out" and worker_id == 0
        lb.serve_seal()
        batch, sealed = wk.serve_lease(incarnation=0)
        assert batch == [] and sealed
        stats = lb.serve_stats()
        assert stats["completed"] == 1 and stats["sealed"]
        assert stats["duplicates"] == 1
        assert wk.serve_relinquish() == 0
        assert master.request_router.finished()
        lb.close()
        wk.close()
    finally:
        master.stop()


def test_serving_worker_threads_share_load_exactly_once():
    """Two worker threads over loopback gRPC: every request answered
    exactly once regardless of which replica leased it."""
    master = LocalJobMaster(port=0)
    master.prepare()
    try:
        lb = MasterClient(master.addr, node_id=9, node_type=W)
        req_ids = [lb.serve_submit(f"p{i}".encode())[1]
                   for i in range(30)]
        lb.serve_seal()
        clients = [
            MasterClient(master.addr, node_id=i, node_type=W)
            for i in range(2)
        ]
        workers = [
            ServingWorker(c, _echo_model, node_id=i, batch_size=4,
                          poll_interval=0.01, incarnation=0)
            for i, c in enumerate(clients)
        ]
        threads = [threading.Thread(target=w.serve) for w in workers]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
            assert not t.is_alive()
        assert sum(w.served for w in workers) == 30
        for i, rid in enumerate(req_ids):
            done, payload, _, _ = lb.serve_poll(rid)
            assert done and payload == f"P{i}".encode()
        for c in clients + [lb]:
            c.close()
    finally:
        master.stop()


# --------------------------------------------------------------- benchmark


def test_serve_load_smoke():
    """The serving benchmark's tier-1 smoke tier: end to end against a
    real gRPC master, every request answered exactly once, and the
    BENCH JSON carries the documented throughput/latency fields."""
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               DLROVER_TPU_METRICS_PORT="off")
    out = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "benchmarks", "serve_load.py"),
         "--smoke"],
        capture_output=True, text=True, timeout=180, env=env,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    result = json.loads(out.stdout.strip().splitlines()[-1])
    assert result["exactly_once"] is True
    assert result["requests_per_s"] > 0
    assert result["serve_p99_ms"] >= result["serve_p50_ms"] >= 0
    assert result["duplicates"] == 0
    # the ISSUE 20 axes always report, even at their defaults
    assert result["routers"] == 1
    assert result["tenants"] == 1
    assert result["fairness_spread"] == 1.0
    assert set(result["per_shard_req_s"]) == {"0"}
