"""Tests for common layer: node model, status flow, context."""

from dlrover_tpu.common.constants import NodeExitReason, NodeStatus, NodeType
from dlrover_tpu.common.global_context import Context
from dlrover_tpu.common.node import Node, NodeGroupResource, NodeResource
from dlrover_tpu.master.node.status_flow import get_node_state_flow


def test_node_resource_parse():
    res = NodeResource.resource_str_to_node_resource(
        "cpu=4,memory=8192,tpu_chips=4,tpu_type=v5p"
    )
    assert res.cpu == 4
    assert res.memory == 8192
    assert res.tpu_chips == 4
    assert res.tpu_type == "v5p"


def test_node_relaunch_clone():
    node = Node(NodeType.WORKER, 0, rank_index=2, critical=True)
    clone = node.get_relaunch_node_info(new_id=7)
    assert clone.id == 7
    assert clone.rank_index == 2
    assert clone.relaunch_count == 1
    assert clone.critical


def test_unrecoverable_failure():
    node = Node(NodeType.WORKER, 0, max_relaunch_count=2)
    node.set_exit_reason(NodeExitReason.FATAL_ERROR)
    assert node.is_unrecoverable_failure()
    node2 = Node(NodeType.WORKER, 1, max_relaunch_count=2)
    node2.set_exit_reason(NodeExitReason.KILLED)
    assert not node2.is_unrecoverable_failure()
    node2.relaunch_count = 2
    assert node2.is_unrecoverable_failure()


def test_status_flow():
    flow = get_node_state_flow(
        NodeStatus.RUNNING, "modified", NodeStatus.FAILED
    )
    assert flow is not None and flow.should_relaunch
    flow = get_node_state_flow(
        NodeStatus.RUNNING, "modified", NodeStatus.SUCCEEDED
    )
    assert flow is not None and not flow.should_relaunch
    # disallowed transition
    assert (
        get_node_state_flow(NodeStatus.SUCCEEDED, "modified",
                            NodeStatus.RUNNING)
        is None
    )
    # no-op transition
    assert (
        get_node_state_flow(NodeStatus.RUNNING, "modified",
                            NodeStatus.RUNNING)
        is None
    )


def test_context_singleton_and_override():
    ctx = Context.singleton_instance()
    assert ctx is Context.singleton_instance()
    ctx.set_params_from_optimizer(
        {"hang_detection_interval": 42, "custom_knob": "x"}
    )
    assert ctx.hang_detection_interval == 42
    assert ctx.user_defined["custom_knob"] == "x"


def test_priority_half_rule():
    group = 4
    nodes = []
    for i in range(group):
        n = Node(NodeType.WORKER, i, rank_index=i)
        n.config_resource.priority = "half"
        n.update_priority(group)
        nodes.append(n)
    assert [n.config_resource.priority for n in nodes] == [
        "high", "high", "low", "low",
    ]


# ---------------------------------------------------------------- wire codec


def test_wire_codec_round_trips_nested_and_typed_keys():
    """The schema'd JSON codec (comm.py) must preserve nested messages,
    int-keyed dicts, and bytes — the three shapes plain JSON loses."""
    from dlrover_tpu.common import comm

    task = comm.Task(
        task_id=3,
        task_type="train",
        shard=comm.Shard(name="ds", start=10, end=20,
                         record_indices=[1, 2, 3]),
    )
    got = comm.deserialize(comm.serialize(task))
    assert got == task and isinstance(got.shard, comm.Shard)

    world = comm.CommWorld(rdzv_round=2, group=0, world={0: 4, 3: 4})
    got = comm.deserialize(comm.serialize(world))
    assert got.world == {0: 4, 3: 4}
    assert all(isinstance(k, int) for k in got.world)

    kv = comm.KVStoreSetRequest(key="k", value=b"\x00\xffraw")
    assert comm.deserialize(comm.serialize(kv)).value == b"\x00\xffraw"


def test_wire_codec_rejects_unknown_and_malformed():
    """An unknown or malformed network payload raises WireError —
    nothing is instantiated or executed (VERDICT r3 Weak #1)."""
    import json
    import pickle

    import pytest

    from dlrover_tpu.common import comm

    # a pickle payload (the old wire format / an attack) is rejected
    with pytest.raises(comm.WireError):
        comm.deserialize(pickle.dumps(("get_task", object())))
    # unknown message type
    evil = json.dumps(
        {"__msg__": "os.system", "f": {}}
    ).encode()
    with pytest.raises(comm.WireError):
        comm.deserialize(evil)
    # a plain dict that is not one of the sentinel shapes
    with pytest.raises(comm.WireError):
        comm.deserialize(json.dumps({"a": 1}).encode())
    # non-JSON bytes
    with pytest.raises(comm.WireError):
        comm.deserialize(b"\x80\x05junk")
    # unknown FIELDS on a known type are ignored (rolling upgrade),
    # not an error
    newer = json.dumps({
        "__msg__": "HeartBeat",
        "f": {"timestamp": 1.0, "field_from_the_future": 9},
    }).encode()
    msg = comm.deserialize(newer)
    assert isinstance(msg, comm.HeartBeat) and msg.timestamp == 1.0


def test_wire_codec_refuses_unencodable_values():
    import pytest

    from dlrover_tpu.common import comm

    with pytest.raises(comm.WireError):
        comm.serialize(object())


def test_rpc_server_rejects_malformed_without_executing():
    """End-to-end over real gRPC: a malformed envelope gets
    INVALID_ARGUMENT and the handler is never invoked."""
    import grpc
    import pytest

    from dlrover_tpu.common import grpc_utils

    calls = []

    def handler(method, message):
        calls.append(method)
        return None

    server = grpc_utils.GenericRpcServer(handler, port=0)
    server.start()
    try:
        channel = grpc.insecure_channel(f"localhost:{server.port}")
        raw = channel.unary_unary(
            f"/{grpc_utils.SERVICE_NAME}/{grpc_utils.METHOD_NAME}",
            request_serializer=None,
            response_deserializer=None,
        )
        import pickle

        with pytest.raises(grpc.RpcError) as ei:
            raw(pickle.dumps(("ping", None)), timeout=5)
        assert ei.value.code() == grpc.StatusCode.INVALID_ARGUMENT
        assert calls == []
        channel.close()
    finally:
        server.stop(0)


def test_wire_codec_map_keys_must_be_primitive():
    """Review fix: unhashable/non-primitive map keys are a WireError on
    BOTH encode and decode — never a TypeError escaping the contract."""
    import json

    import pytest

    from dlrover_tpu.common import comm

    with pytest.raises(comm.WireError):
        comm.serialize(comm.CustomData(data={(1, 2): "tuple-key"}))
    evil = json.dumps({"__map__": [[[1, 2], 3]]}).encode()
    with pytest.raises(comm.WireError):
        comm.deserialize(evil)


def test_wire_codec_coerces_numpy_scalars():
    """Review fix: numpy scalars in free-form metric dicts must encode
    (the evaluator reports np.float32 losses through CustomData)."""
    import numpy as np

    from dlrover_tpu.common import comm

    msg = comm.CustomData(data={
        "loss": np.float32(0.5), "n": np.int64(3),
        np.int32(7): "np-key",
    })
    got = comm.deserialize(comm.serialize(msg))
    assert got.data["loss"] == 0.5 and isinstance(got.data["loss"], float)
    assert got.data["n"] == 3 and isinstance(got.data["n"], int)
    assert got.data[7] == "np-key"
