"""Tests for common layer: node model, status flow, context."""

from dlrover_tpu.common.constants import NodeExitReason, NodeStatus, NodeType
from dlrover_tpu.common.global_context import Context
from dlrover_tpu.common.node import Node, NodeGroupResource, NodeResource
from dlrover_tpu.master.node.status_flow import get_node_state_flow


def test_node_resource_parse():
    res = NodeResource.resource_str_to_node_resource(
        "cpu=4,memory=8192,tpu_chips=4,tpu_type=v5p"
    )
    assert res.cpu == 4
    assert res.memory == 8192
    assert res.tpu_chips == 4
    assert res.tpu_type == "v5p"


def test_node_relaunch_clone():
    node = Node(NodeType.WORKER, 0, rank_index=2, critical=True)
    clone = node.get_relaunch_node_info(new_id=7)
    assert clone.id == 7
    assert clone.rank_index == 2
    assert clone.relaunch_count == 1
    assert clone.critical


def test_unrecoverable_failure():
    node = Node(NodeType.WORKER, 0, max_relaunch_count=2)
    node.set_exit_reason(NodeExitReason.FATAL_ERROR)
    assert node.is_unrecoverable_failure()
    node2 = Node(NodeType.WORKER, 1, max_relaunch_count=2)
    node2.set_exit_reason(NodeExitReason.KILLED)
    assert not node2.is_unrecoverable_failure()
    node2.relaunch_count = 2
    assert node2.is_unrecoverable_failure()


def test_status_flow():
    flow = get_node_state_flow(
        NodeStatus.RUNNING, "modified", NodeStatus.FAILED
    )
    assert flow is not None and flow.should_relaunch
    flow = get_node_state_flow(
        NodeStatus.RUNNING, "modified", NodeStatus.SUCCEEDED
    )
    assert flow is not None and not flow.should_relaunch
    # disallowed transition
    assert (
        get_node_state_flow(NodeStatus.SUCCEEDED, "modified",
                            NodeStatus.RUNNING)
        is None
    )
    # no-op transition
    assert (
        get_node_state_flow(NodeStatus.RUNNING, "modified",
                            NodeStatus.RUNNING)
        is None
    )


def test_context_singleton_and_override():
    ctx = Context.singleton_instance()
    assert ctx is Context.singleton_instance()
    ctx.set_params_from_optimizer(
        {"hang_detection_interval": 42, "custom_knob": "x"}
    )
    assert ctx.hang_detection_interval == 42
    assert ctx.user_defined["custom_knob"] == "x"


def test_priority_half_rule():
    group = 4
    nodes = []
    for i in range(group):
        n = Node(NodeType.WORKER, i, rank_index=i)
        n.config_resource.priority = "half"
        n.update_priority(group)
        nodes.append(n)
    assert [n.config_resource.priority for n in nodes] == [
        "high", "high", "low", "low",
    ]
