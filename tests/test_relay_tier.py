"""RelayTier lifecycle (ISSUE 18 satellite): sizing, agent->relay
addressing, and the killed-relay drill — a SIGKILLed relay subprocess
comes back on its ORIGINAL port, so the ``DLROVER_TPU_RELAY_ADDR``
the launcher exported before the crash keeps serving."""

import os
import signal
import time

from dlrover_tpu.agent.relay import RelayTier
from dlrover_tpu.common.grpc_utils import addr_connected


def test_relay_tier_sizing_and_addressing():
    tier = RelayTier("localhost:1", n_agents=5, fanout=2)
    # ceil(5 / 2) = 3 relays, none over fanout
    assert tier.n_relays == 3
    tier = RelayTier("localhost:1", n_agents=512, fanout=256)
    assert tier.n_relays == 2
    tier = RelayTier("localhost:1", n_agents=513, fanout=256)
    assert tier.n_relays == 3
    # one agent still gets a (single-relay) tier
    tier = RelayTier("localhost:1", n_agents=1, fanout=256)
    assert tier.n_relays == 1


def test_relay_tier_restarts_killed_relay(tmp_path):
    """Kill one relay of a live tier: the monitor respawns it on the
    same port (new pid), the advertised address serves again, and the
    surviving relays were never touched."""
    # the master is unreachable on purpose — relays only need it for
    # upstream forwards, which don't happen without agent reports
    tier = RelayTier(
        "localhost:1", n_agents=5, fanout=2, check_interval=0.2,
    ).start()
    try:
        assert tier.n_relays == 3
        ports = tier.ports()
        assert sorted(ports) == [0, 1, 2]
        # contiguous rank // fanout mapping...
        assert tier.addr_for(0) == f"localhost:{ports[0]}"
        assert tier.addr_for(1) == f"localhost:{ports[0]}"
        assert tier.addr_for(2) == f"localhost:{ports[1]}"
        assert tier.addr_for(4) == f"localhost:{ports[2]}"
        # ...and ranks grown past the provisioned count wrap
        assert tier.addr_for(6) == f"localhost:{ports[0]}"
        for rid in range(3):
            assert addr_connected(f"localhost:{ports[rid]}", timeout=10)

        victim_pid = tier._procs[1].pid
        other_pids = {rid: tier._procs[rid].pid for rid in (0, 2)}
        os.kill(victim_pid, signal.SIGKILL)

        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            with tier._lock:
                p = tier._procs[1]
                respawned = p.pid != victim_pid and p.poll() is None
            if respawned:
                break
            time.sleep(0.1)
        else:
            raise AssertionError("relay 1 was not respawned in 60s")

        assert tier.restarts >= 1
        # SAME port: the address agents hold stays valid
        assert tier.ports()[1] == ports[1]
        assert addr_connected(tier.addr_for(2), timeout=10)
        # survivors undisturbed
        for rid, pid in other_pids.items():
            assert tier._procs[rid].pid == pid
            assert tier._procs[rid].poll() is None
    finally:
        tier.stop()
    # tier.stop() reaps everything
    for p in tier._procs.values():
        assert p.poll() is not None
