"""Reshard-in-place chaos drill: kill 1 of 4 real processes mid-epoch
and watch the 3 survivors resume WITHOUT a single process restart.

A real master (reshard plane opted in, heartbeat watchdog armed at
seconds-scale) serves four protocol-speaking workers
(``_reshard_drill_worker.py``), each a virtual TPU host of 2 forced
CPU devices saving a format-v2 checkpoint every step.
``DLROVER_FAULT_INJECT=node_lost@6:host=2`` SIGKILLs rank 2 after its
step-6 save is durable; the watchdog detects the silence, the
coordinator cuts a shrink order, and every survivor executes the mesh
transition in-process: re-rendezvous, rebuild, migrate through the
tiered loader (own RAM / peers / store), re-arm the data plane,
complete.

Asserted: the victim dies by SIGKILL and the survivors' ORIGINAL
processes run to rc 0 (one incarnation each — zero restarts); the
journal tells the transition story exactly once (detected/ordered/
rebalanced once, adopted/migrated per survivor, completed once, no
abort, no ``scale.restart``); every survivor restored the SAME step
with the SAME digest, bit-identical to the expected state; the shard
ledger stays exactly-once across the resize (the victim's in-flight
shard included); the migration pulled from all three tiers; and the
goodput account books the outage under the ``reshard`` phase with a
recovered fault window.

The fallback drill flips one survivor to refuse the order
(``DRILL_RESHARD_REFUSE=1``): the coordinator aborts, every survivor
exits into the restart-the-world path (rc 7), the master re-enables
relaunch for the lost rank, and relaunched fresh incarnations drain
the dataset — still exactly-once — with ``reshard.aborted`` (and no
``reshard.completed``) on the record.
"""

import os
import signal
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import subprocess  # noqa: E402

from test_goodput_drill import (  # noqa: E402
    _drill_env,
    _killpg,
    _master_port,
    _tail,
    _wait,
)

from dlrover_tpu.telemetry import goodput  # noqa: E402
from dlrover_tpu.telemetry.goodput import Phase  # noqa: E402
from dlrover_tpu.telemetry.journal import read_journal  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

N_NODES = 4
VICTIM = 2
KILL_STEP = 6
DATASET_SIZE = 720
BATCH_SIZE = 4
SHARD_SECS = 0.2
#: seconds of heartbeat silence before the watchdog declares a node
#: lost — low enough to keep the drill fast, high enough that a
#: survivor mid-migration (heartbeating from a daemon thread every
#: 0.5s) can never be mistaken for a casualty
HEARTBEAT_TIMEOUT = 5
FALLBACK_RC = 7


def _spawn_master(tmp, env, state_dir, port, tag):
    cmd = [
        sys.executable, "-m", "dlrover_tpu.master.main",
        "--platform", "process", "--node_num", "0",
        "--job_name", "reshard-drill", "--port", str(port),
        "--state_dir", state_dir,
        "--autoscale_interval", "600", "--check_interval", "0.2",
        "--heartbeat_timeout", str(HEARTBEAT_TIMEOUT),
    ]
    return subprocess.Popen(
        cmd, cwd=REPO, env=env,
        stdout=open(os.path.join(tmp, f"master-{tag}.out"), "w"),
        stderr=open(os.path.join(tmp, f"master-{tag}.err"), "w"),
        start_new_session=True,
    )


def _spawn_worker(tmp, env, port, node_id, tag, store_dir, ram_dir):
    return subprocess.Popen(
        [sys.executable,
         os.path.join(REPO, "tests", "_reshard_drill_worker.py"),
         "--master_addr", f"localhost:{port}",
         "--node_id", str(node_id),
         "--n_nodes", str(N_NODES),
         "--out", os.path.join(tmp, f"worker-{tag}.txt"),
         "--store_dir", store_dir,
         "--ram_dir", ram_dir,
         "--dataset_size", str(DATASET_SIZE),
         "--batch_size", str(BATCH_SIZE),
         "--shard_secs", str(SHARD_SECS)],
        cwd=REPO, env=env,
        stdout=open(os.path.join(tmp, f"worker-{tag}.out"), "w"),
        stderr=subprocess.STDOUT,
        start_new_session=True,
    )


def _worker_env(env, rank, extra=None):
    out = dict(
        env,
        DLROVER_TPU_NODE_RANK=str(rank),
        DLROVER_FAULT_INJECT=f"node_lost@{KILL_STEP}:host={VICTIM}",
        XLA_FLAGS="--xla_force_host_platform_device_count=8",
    )
    out.update(extra or {})
    return out


def _lines(tmp, tag, key):
    path = os.path.join(tmp, f"worker-{tag}.txt")
    if not os.path.exists(path):
        return []
    return [
        line.split()
        for line in open(path).read().splitlines()
        if line == key or line.startswith(key + " ")
    ]


def _assert_exactly_once(tmp, tags):
    ranges = []
    for tag in tags:
        for parts in _lines(tmp, tag, "SHARD"):
            ranges.append((int(parts[1]), int(parts[2])))
    ranges.sort()
    assert ranges, "no shards consumed at all"
    assert ranges[0][0] == 0 and ranges[-1][1] == DATASET_SIZE, ranges
    for (_, end), (start, _) in zip(ranges, ranges[1:]):
        assert end == start, f"shard gap/overlap at {start}: {ranges}"


def test_reshard_chaos_drill(tmp_path):
    tmp = str(tmp_path)
    journal_path = os.path.join(tmp, "journal.jsonl")
    store_dir = os.path.join(tmp, "store")
    env = _drill_env(journal_path)
    master_env = dict(env, DLROVER_TPU_RESHARD="1")

    procs = {}
    try:
        master = _spawn_master(
            tmp, master_env, os.path.join(tmp, "state"), 0, "1"
        )
        procs["master"] = master
        port = _master_port(tmp, "1", master)

        for rank in range(N_NODES):
            procs[rank] = _spawn_worker(
                tmp, _worker_env(env, rank), port, rank, str(rank),
                store_dir, os.path.join(tmp, f"ram{rank}"),
            )

        # the victim dies by its own injected SIGKILL
        rc = _wait(procs[VICTIM], 180, "victim (kill expected)", tmp,
                   [f"worker-{VICTIM}.out", "master-1.err"])
        assert rc == -signal.SIGKILL, (
            f"victim exited rc={rc}, wanted SIGKILL; "
            + _tail(tmp, f"worker-{VICTIM}.out")
        )

        # the survivors' ORIGINAL processes finish the epoch: no exit,
        # no relaunch, no fresh incarnation — rc 0 from the pids we
        # spawned before the fault
        survivors = [r for r in range(N_NODES) if r != VICTIM]
        for rank in survivors:
            rc = _wait(procs[rank], 300, f"survivor {rank}", tmp,
                       [f"worker-{rank}.out", "master-1.err"])
            assert rc == 0, (
                f"survivor {rank} exited rc={rc}; "
                + _tail(tmp, f"worker-{rank}.out")
            )
        rc = _wait(master, 60, "master", tmp, ["master-1.err"])
        assert rc == 0, _tail(tmp, "master-1.err")
    finally:
        for p in procs.values():
            _killpg(p, signal.SIGTERM)
        time.sleep(0.5)
        for p in procs.values():
            _killpg(p)

    survivors = [r for r in range(N_NODES) if r != VICTIM]

    # ---- zero process restarts: one incarnation per survivor --------
    for rank in survivors:
        pids = _lines(tmp, str(rank), "PID")
        assert len(pids) == 1 and pids[0][2] == "0", pids
        assert _lines(tmp, str(rank), "FALLBACK") == []
        # the survivor executed the transition in-process
        assert len(_lines(tmp, str(rank), "TRANSITION")) == 1

    # ---- the journal tells the story exactly once --------------------
    events = read_journal(journal_path)
    by_kind = {}
    for e in events:
        by_kind.setdefault(e.get("kind"), []).append(e)
    assert "scale.restart" not in by_kind, by_kind.get("scale.restart")
    assert "reshard.aborted" not in by_kind, by_kind["reshard.aborted"]

    (detected,) = by_kind["reshard.detected"]
    assert detected["data"]["node_rank"] == VICTIM
    (ordered,) = by_kind["reshard.ordered"]
    assert ordered["data"]["order_kind"] == "shrink"
    assert ordered["data"]["world_size"] == N_NODES - 1
    assert ordered["data"]["lost"] == [VICTIM]
    (rebalanced,) = by_kind["reshard.rebalanced"]
    # the victim died holding an in-flight shard: the ledger requeued
    # it (exactly-once is then proven by the SHARD arithmetic below)
    assert rebalanced["data"]["requeued"] >= 1, rebalanced
    assert len(by_kind["reshard.adopted"]) == len(survivors)
    (completed,) = by_kind["reshard.completed"]
    assert completed["data"]["duration_s"] > 0.0

    migrated = by_kind["reshard.migrated"]
    assert len(migrated) == len(survivors)
    assert {e["data"]["node_rank"] for e in migrated} == set(survivors)
    for e in migrated:
        assert e["data"]["digest_mismatch"] == 0, e
    # the migration exercised every tier: shards this host kept
    # (local), shards fetched from surviving peers' RAM over HTTP
    # (peer), and the dead rank's shards from the store (store)
    totals = {
        k: sum(e["data"][k] for e in migrated)
        for k in ("local", "peer", "store")
    }
    assert totals["local"] >= 1, totals
    assert totals["peer"] >= 1, totals
    assert totals["store"] >= 1, totals

    # ---- every survivor landed on the SAME bit-identical state -------
    migr_lines = [
        _lines(tmp, str(rank), "MIGRATED")[0] for rank in survivors
    ]
    steps = {parts[1] for parts in migr_lines}
    digests = {parts[2] for parts in migr_lines}
    assert len(steps) == 1 and len(digests) == 1, migr_lines
    for parts in migr_lines:
        assert parts[3] == "ok", parts
    # the restore step is the victim's durable kill-step save
    assert int(next(iter(steps))) == KILL_STEP, migr_lines

    # ---- the dataset completed exactly once across the resize --------
    _assert_exactly_once(tmp, [str(r) for r in range(N_NODES)])

    # ---- goodput books the outage under `reshard` --------------------
    report = goodput.reconstruct(events)
    job = report["job"]
    assert job["badput_s"].get(Phase.RESHARD, 0.0) > 0.0, job
    win = next(
        f for f in report["faults"] if f["cause"] == Phase.RESHARD
    )
    assert win["node_id"] == VICTIM, win
    assert win["recovered_ts"] and win["recovered_ts"] >= win["ts"], win


def test_reshard_fallback_drill(tmp_path):
    """A mid-transition refusal aborts cleanly into restart-the-world:
    survivors exit rc 7, relaunch is re-enabled for the lost rank, and
    fresh incarnations finish the dataset exactly-once."""
    tmp = str(tmp_path)
    journal_path = os.path.join(tmp, "journal.jsonl")
    store_dir = os.path.join(tmp, "store")
    env = _drill_env(journal_path)
    master_env = dict(env, DLROVER_TPU_RESHARD="1")

    procs = {}
    try:
        master = _spawn_master(
            tmp, master_env, os.path.join(tmp, "state"), 0, "1"
        )
        procs["master"] = master
        port = _master_port(tmp, "1", master)

        for rank in range(N_NODES):
            extra = {"DRILL_RESHARD_REFUSE": "1"} if rank == 0 else None
            procs[rank] = _spawn_worker(
                tmp, _worker_env(env, rank, extra), port, rank,
                f"{rank}-a", store_dir, os.path.join(tmp, f"ram{rank}"),
            )

        rc = _wait(procs[VICTIM], 180, "victim (kill expected)", tmp,
                   [f"worker-{VICTIM}-a.out", "master-1.err"])
        assert rc == -signal.SIGKILL, rc

        # rank 0 refuses the order; the abort broadcast sends every
        # survivor down the restart-the-world path it always had
        survivors = [r for r in range(N_NODES) if r != VICTIM]
        for rank in survivors:
            rc = _wait(procs[rank], 300, f"survivor {rank} (fallback)",
                       tmp, [f"worker-{rank}-a.out", "master-1.err"])
            assert rc == FALLBACK_RC, (
                f"survivor {rank} exited rc={rc}, wanted fallback "
                f"rc={FALLBACK_RC}; " + _tail(tmp, f"worker-{rank}-a.out")
            )

        # restart the world: fresh incarnations of all four ranks
        # (RESTART_COUNT=1 gates the injected fault off)
        for rank in range(N_NODES):
            procs[f"{rank}-b"] = _spawn_worker(
                tmp,
                _worker_env(env, rank,
                            {"DLROVER_TPU_RESTART_COUNT": "1"}),
                port, rank, f"{rank}-b",
                store_dir, os.path.join(tmp, f"ram{rank}"),
            )
        for rank in range(N_NODES):
            rc = _wait(procs[f"{rank}-b"], 300, f"relaunched {rank}",
                       tmp, [f"worker-{rank}-b.out", "master-1.err"])
            assert rc == 0, (
                f"relaunched {rank} exited rc={rc}; "
                + _tail(tmp, f"worker-{rank}-b.out")
            )
        rc = _wait(master, 60, "master", tmp, ["master-1.err"])
        assert rc == 0, _tail(tmp, "master-1.err")
    finally:
        for p in procs.values():
            _killpg(p, signal.SIGTERM)
        time.sleep(0.5)
        for p in procs.values():
            _killpg(p)

    events = read_journal(journal_path)
    kinds = [e.get("kind") for e in events]
    assert "reshard.ordered" in kinds
    assert "reshard.aborted" in kinds
    assert "reshard.completed" not in kinds
    # the master re-enabled relaunch for the lost rank on abort
    master_err = open(os.path.join(tmp, "master-1.err")).read()
    assert "Reshard fallback: re-enabling relaunch" in master_err

    # every survivor took the fallback exit; nobody restored twice
    for rank in (0, 1, 3):
        assert _lines(tmp, f"{rank}-a", "FALLBACK"), rank
    # fresh incarnations never saw the stale abort as addressed to them
    for rank in range(N_NODES):
        assert _lines(tmp, f"{rank}-b", "FALLBACK") == [], rank

    # exactly-once across the abort AND the restart
    tags = [f"{r}-a" for r in range(N_NODES)]
    tags += [f"{r}-b" for r in range(N_NODES)]
    _assert_exactly_once(tmp, tags)
