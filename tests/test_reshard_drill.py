"""Reshard-in-place chaos drill: kill 1 of 4 real processes mid-epoch
and watch the 3 survivors resume WITHOUT a single process restart.

A real master (reshard plane opted in, heartbeat watchdog armed at
seconds-scale) serves four protocol-speaking workers
(``_reshard_drill_worker.py``), each a virtual TPU host of 2 forced
CPU devices saving a format-v2 checkpoint every step.
``DLROVER_FAULT_INJECT=node_lost@6:host=2`` SIGKILLs rank 2 after its
step-6 save is durable; the watchdog detects the silence, the
coordinator cuts a shrink order, and every survivor executes the mesh
transition in-process: re-rendezvous, rebuild, migrate through the
tiered loader (own RAM / peers / store), re-arm the data plane,
complete.

Asserted: the victim dies by SIGKILL and the survivors' ORIGINAL
processes run to rc 0 (one incarnation each — zero restarts); the
journal tells the transition story exactly once (detected/ordered/
rebalanced once, adopted/migrated per survivor, completed once, no
abort, no ``scale.restart``); every survivor restored the SAME step
with the SAME digest, bit-identical to the expected state; the shard
ledger stays exactly-once across the resize (the victim's in-flight
shard included); every row a survivor still held moved LIVE
(device-to-device, no re-hash) while the dead rank's rows came from
the store; and the goodput account books the outage under the
``reshard`` phase with a recovered fault window.

The fallback drill flips one survivor to refuse the order
(``DRILL_RESHARD_REFUSE=1``): the coordinator aborts, every survivor
exits into the restart-the-world path (rc 7), the master re-enables
relaunch for the lost rank, and relaunched fresh incarnations drain
the dataset — still exactly-once — with ``reshard.aborted`` (and no
``reshard.completed``) on the record.

The promotion drill adds a 5th process registered as a hot spare
(``--spare``): it pre-warms the committed frontier from peers while
idle, and the same node loss now cuts a PROMOTE order — constant
world size, the spare taking the casualty's place out of its warm
cache, inside ONE step boundary and with zero process restarts.

The oscillation drill runs join -> shrink -> join on one master:
order ids stay strictly monotonic, a latecomer that reads a stale
broadcast from before its time ignores it, and the dataset stays
exactly-once across all three transitions.
"""

import os
import signal
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import subprocess  # noqa: E402

from test_goodput_drill import (  # noqa: E402
    _drill_env,
    _killpg,
    _master_port,
    _tail,
    _wait,
)

from dlrover_tpu.telemetry import goodput  # noqa: E402
from dlrover_tpu.telemetry.goodput import Phase  # noqa: E402
from dlrover_tpu.telemetry.journal import read_journal  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

N_NODES = 4
VICTIM = 2
KILL_STEP = 6
DATASET_SIZE = 720
BATCH_SIZE = 4
SHARD_SECS = 0.2
#: seconds of heartbeat silence before the watchdog declares a node
#: lost — low enough to keep the drill fast, high enough that a
#: survivor mid-migration (heartbeating from a daemon thread every
#: 0.5s) can never be mistaken for a casualty
HEARTBEAT_TIMEOUT = 5
FALLBACK_RC = 7


def _spawn_master(tmp, env, state_dir, port, tag):
    cmd = [
        sys.executable, "-m", "dlrover_tpu.master.main",
        "--platform", "process", "--node_num", "0",
        "--job_name", "reshard-drill", "--port", str(port),
        "--state_dir", state_dir,
        "--autoscale_interval", "600", "--check_interval", "0.2",
        "--heartbeat_timeout", str(HEARTBEAT_TIMEOUT),
    ]
    return subprocess.Popen(
        cmd, cwd=REPO, env=env,
        stdout=open(os.path.join(tmp, f"master-{tag}.out"), "w"),
        stderr=open(os.path.join(tmp, f"master-{tag}.err"), "w"),
        start_new_session=True,
    )


def _spawn_worker(tmp, env, port, node_id, tag, store_dir, ram_dir,
                  extra_args=(), n_nodes=N_NODES,
                  dataset_size=DATASET_SIZE):
    return subprocess.Popen(
        [sys.executable,
         os.path.join(REPO, "tests", "_reshard_drill_worker.py"),
         "--master_addr", f"localhost:{port}",
         "--node_id", str(node_id),
         "--n_nodes", str(n_nodes),
         "--out", os.path.join(tmp, f"worker-{tag}.txt"),
         "--store_dir", store_dir,
         "--ram_dir", ram_dir,
         "--dataset_size", str(dataset_size),
         "--batch_size", str(BATCH_SIZE),
         "--shard_secs", str(SHARD_SECS),
         *extra_args],
        cwd=REPO, env=env,
        stdout=open(os.path.join(tmp, f"worker-{tag}.out"), "w"),
        stderr=subprocess.STDOUT,
        start_new_session=True,
    )


def _worker_env(env, rank, extra=None):
    out = dict(
        env,
        DLROVER_TPU_NODE_RANK=str(rank),
        DLROVER_FAULT_INJECT=f"node_lost@{KILL_STEP}:host={VICTIM}",
        XLA_FLAGS="--xla_force_host_platform_device_count=8",
    )
    out.update(extra or {})
    return out


def _lines(tmp, tag, key):
    path = os.path.join(tmp, f"worker-{tag}.txt")
    if not os.path.exists(path):
        return []
    return [
        line.split()
        for line in open(path).read().splitlines()
        if line == key or line.startswith(key + " ")
    ]


def _assert_exactly_once(tmp, tags, size=DATASET_SIZE):
    ranges = []
    for tag in tags:
        for parts in _lines(tmp, tag, "SHARD"):
            ranges.append((int(parts[1]), int(parts[2])))
    ranges.sort()
    assert ranges, "no shards consumed at all"
    assert ranges[0][0] == 0 and ranges[-1][1] == size, ranges
    for (_, end), (start, _) in zip(ranges, ranges[1:]):
        assert end == start, f"shard gap/overlap at {start}: {ranges}"


def _await(check, what, timeout, procs, tmp, logs):
    """Poll ``check`` until truthy; fail loudly (with log tails and a
    liveness sweep) if the drill phase never materialises."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if check():
            return
        for name, p in procs.items():
            rc = p.poll()
            assert rc is None or rc == 0 or name == "dead", (
                f"{name} died rc={rc} while waiting for {what}; "
                + "".join(_tail(tmp, f) for f in logs)
            )
        time.sleep(0.25)
    raise AssertionError(
        f"timed out waiting for {what}; "
        + "".join(_tail(tmp, f) for f in logs)
    )


def test_reshard_chaos_drill(tmp_path):
    tmp = str(tmp_path)
    journal_path = os.path.join(tmp, "journal.jsonl")
    store_dir = os.path.join(tmp, "store")
    env = _drill_env(journal_path)
    master_env = dict(env, DLROVER_TPU_RESHARD="1")

    procs = {}
    try:
        master = _spawn_master(
            tmp, master_env, os.path.join(tmp, "state"), 0, "1"
        )
        procs["master"] = master
        port = _master_port(tmp, "1", master)

        for rank in range(N_NODES):
            procs[rank] = _spawn_worker(
                tmp, _worker_env(env, rank), port, rank, str(rank),
                store_dir, os.path.join(tmp, f"ram{rank}"),
            )

        # the victim dies by its own injected SIGKILL
        rc = _wait(procs[VICTIM], 180, "victim (kill expected)", tmp,
                   [f"worker-{VICTIM}.out", "master-1.err"])
        assert rc == -signal.SIGKILL, (
            f"victim exited rc={rc}, wanted SIGKILL; "
            + _tail(tmp, f"worker-{VICTIM}.out")
        )

        # the survivors' ORIGINAL processes finish the epoch: no exit,
        # no relaunch, no fresh incarnation — rc 0 from the pids we
        # spawned before the fault
        survivors = [r for r in range(N_NODES) if r != VICTIM]
        for rank in survivors:
            rc = _wait(procs[rank], 300, f"survivor {rank}", tmp,
                       [f"worker-{rank}.out", "master-1.err"])
            assert rc == 0, (
                f"survivor {rank} exited rc={rc}; "
                + _tail(tmp, f"worker-{rank}.out")
            )
        rc = _wait(master, 60, "master", tmp, ["master-1.err"])
        assert rc == 0, _tail(tmp, "master-1.err")
    finally:
        for p in procs.values():
            _killpg(p, signal.SIGTERM)
        time.sleep(0.5)
        for p in procs.values():
            _killpg(p)

    survivors = [r for r in range(N_NODES) if r != VICTIM]

    # ---- zero process restarts: one incarnation per survivor --------
    for rank in survivors:
        pids = _lines(tmp, str(rank), "PID")
        assert len(pids) == 1 and pids[0][2] == "0", pids
        assert _lines(tmp, str(rank), "FALLBACK") == []
        # the survivor executed the transition in-process
        assert len(_lines(tmp, str(rank), "TRANSITION")) == 1

    # ---- the journal tells the story exactly once --------------------
    events = read_journal(journal_path)
    by_kind = {}
    for e in events:
        by_kind.setdefault(e.get("kind"), []).append(e)
    assert "scale.restart" not in by_kind, by_kind.get("scale.restart")
    assert "reshard.aborted" not in by_kind, by_kind["reshard.aborted"]

    (detected,) = by_kind["reshard.detected"]
    assert detected["data"]["node_rank"] == VICTIM
    (ordered,) = by_kind["reshard.ordered"]
    assert ordered["data"]["order_kind"] == "shrink"
    assert ordered["data"]["world_size"] == N_NODES - 1
    assert ordered["data"]["lost"] == [VICTIM]
    (rebalanced,) = by_kind["reshard.rebalanced"]
    # the victim died holding an in-flight shard: the ledger requeued
    # it (exactly-once is then proven by the SHARD arithmetic below)
    assert rebalanced["data"]["requeued"] >= 1, rebalanced
    assert len(by_kind["reshard.adopted"]) == len(survivors)
    (completed,) = by_kind["reshard.completed"]
    assert completed["data"]["duration_s"] > 0.0

    migrated = by_kind["reshard.migrated"]
    assert len(migrated) == len(survivors)
    assert {e["data"]["node_rank"] for e in migrated} == set(survivors)
    for e in migrated:
        assert e["data"]["digest_mismatch"] == 0, e
        assert e["data"]["live"] >= 1, e
    # live redistribution: every row a survivor still holds moves
    # device-to-device out of the live pytree (no npz, no re-hash) —
    # the checkpoint tiers serve ONLY the dead rank's rows, and the
    # victim's RAM server died with it, so those come from the store
    totals = {
        k: sum(e["data"][k] for e in migrated)
        for k in ("live", "local", "peer", "store")
    }
    assert totals["live"] >= 1, totals
    assert totals["store"] >= 1, totals
    assert totals["live"] >= totals["local"] + totals["peer"], totals

    # ---- every survivor landed on the SAME bit-identical state -------
    migr_lines = [
        _lines(tmp, str(rank), "MIGRATED")[0] for rank in survivors
    ]
    steps = {parts[1] for parts in migr_lines}
    digests = {parts[2] for parts in migr_lines}
    assert len(steps) == 1 and len(digests) == 1, migr_lines
    for parts in migr_lines:
        assert parts[3] == "ok", parts
    # the restore step is the victim's durable kill-step save
    assert int(next(iter(steps))) == KILL_STEP, migr_lines

    # ---- the dataset completed exactly once across the resize --------
    _assert_exactly_once(tmp, [str(r) for r in range(N_NODES)])

    # ---- goodput books the outage under `reshard` --------------------
    report = goodput.reconstruct(events)
    job = report["job"]
    assert job["badput_s"].get(Phase.RESHARD, 0.0) > 0.0, job
    win = next(
        f for f in report["faults"] if f["cause"] == Phase.RESHARD
    )
    assert win["node_id"] == VICTIM, win
    assert win["recovered_ts"] and win["recovered_ts"] >= win["ts"], win


def test_reshard_fallback_drill(tmp_path):
    """A mid-transition refusal aborts cleanly into restart-the-world:
    survivors exit rc 7, relaunch is re-enabled for the lost rank, and
    fresh incarnations finish the dataset exactly-once."""
    tmp = str(tmp_path)
    journal_path = os.path.join(tmp, "journal.jsonl")
    store_dir = os.path.join(tmp, "store")
    env = _drill_env(journal_path)
    master_env = dict(env, DLROVER_TPU_RESHARD="1")

    procs = {}
    try:
        master = _spawn_master(
            tmp, master_env, os.path.join(tmp, "state"), 0, "1"
        )
        procs["master"] = master
        port = _master_port(tmp, "1", master)

        for rank in range(N_NODES):
            extra = {"DRILL_RESHARD_REFUSE": "1"} if rank == 0 else None
            procs[rank] = _spawn_worker(
                tmp, _worker_env(env, rank, extra), port, rank,
                f"{rank}-a", store_dir, os.path.join(tmp, f"ram{rank}"),
            )

        rc = _wait(procs[VICTIM], 180, "victim (kill expected)", tmp,
                   [f"worker-{VICTIM}-a.out", "master-1.err"])
        assert rc == -signal.SIGKILL, rc

        # rank 0 refuses the order; the abort broadcast sends every
        # survivor down the restart-the-world path it always had
        survivors = [r for r in range(N_NODES) if r != VICTIM]
        for rank in survivors:
            rc = _wait(procs[rank], 300, f"survivor {rank} (fallback)",
                       tmp, [f"worker-{rank}-a.out", "master-1.err"])
            assert rc == FALLBACK_RC, (
                f"survivor {rank} exited rc={rc}, wanted fallback "
                f"rc={FALLBACK_RC}; " + _tail(tmp, f"worker-{rank}-a.out")
            )

        # restart the world: fresh incarnations of all four ranks
        # (RESTART_COUNT=1 gates the injected fault off)
        for rank in range(N_NODES):
            procs[f"{rank}-b"] = _spawn_worker(
                tmp,
                _worker_env(env, rank,
                            {"DLROVER_TPU_RESTART_COUNT": "1"}),
                port, rank, f"{rank}-b",
                store_dir, os.path.join(tmp, f"ram{rank}"),
            )
        for rank in range(N_NODES):
            rc = _wait(procs[f"{rank}-b"], 300, f"relaunched {rank}",
                       tmp, [f"worker-{rank}-b.out", "master-1.err"])
            assert rc == 0, (
                f"relaunched {rank} exited rc={rc}; "
                + _tail(tmp, f"worker-{rank}-b.out")
            )
        rc = _wait(master, 60, "master", tmp, ["master-1.err"])
        assert rc == 0, _tail(tmp, "master-1.err")
    finally:
        for p in procs.values():
            _killpg(p, signal.SIGTERM)
        time.sleep(0.5)
        for p in procs.values():
            _killpg(p)

    events = read_journal(journal_path)
    kinds = [e.get("kind") for e in events]
    assert "reshard.ordered" in kinds
    assert "reshard.aborted" in kinds
    assert "reshard.completed" not in kinds
    # the master re-enabled relaunch for the lost rank on abort
    master_err = open(os.path.join(tmp, "master-1.err")).read()
    assert "Reshard fallback: re-enabling relaunch" in master_err

    # every survivor took the fallback exit; nobody restored twice
    for rank in (0, 1, 3):
        assert _lines(tmp, f"{rank}-a", "FALLBACK"), rank
    # fresh incarnations never saw the stale abort as addressed to them
    for rank in range(N_NODES):
        assert _lines(tmp, f"{rank}-b", "FALLBACK") == [], rank

    # exactly-once across the abort AND the restart
    tags = [f"{r}-a" for r in range(N_NODES)]
    tags += [f"{r}-b" for r in range(N_NODES)]
    _assert_exactly_once(tmp, tags)


def test_spare_promotion_drill(tmp_path):
    """Hot-spare promotion: a 5th process registers as a spare BEFORE
    reporting RUNNING (never grown in), pre-warms the committed
    frontier from peers while idle, and the node loss cuts a PROMOTE
    order — constant world size, the spare taking the casualty's
    place out of its warm RAM cache inside ONE step boundary, with
    zero process restarts and bit-identical state across the world."""
    tmp = str(tmp_path)
    journal_path = os.path.join(tmp, "journal.jsonl")
    store_dir = os.path.join(tmp, "store")
    env = _drill_env(journal_path)
    master_env = dict(env, DLROVER_TPU_RESHARD="1")
    SPARE = N_NODES  # rank 4

    procs = {}
    try:
        master = _spawn_master(
            tmp, master_env, os.path.join(tmp, "state"), 0, "1"
        )
        procs["master"] = master
        port = _master_port(tmp, "1", master)

        for rank in range(N_NODES):
            procs[rank] = _spawn_worker(
                tmp, _worker_env(env, rank), port, rank, str(rank),
                store_dir, os.path.join(tmp, f"ram{rank}"),
            )
        # the spare gets no fault of its own (the injected spec only
        # matches host VICTIM anyway) and idles warm from the start
        procs[SPARE] = _spawn_worker(
            tmp, _worker_env(env, SPARE), port, SPARE, str(SPARE),
            store_dir, os.path.join(tmp, f"ram{SPARE}"),
            extra_args=["--spare"],
        )

        rc = _wait(procs[VICTIM], 180, "victim (kill expected)", tmp,
                   [f"worker-{VICTIM}.out", "master-1.err"])
        assert rc == -signal.SIGKILL, (
            f"victim exited rc={rc}, wanted SIGKILL; "
            + _tail(tmp, f"worker-{VICTIM}.out")
        )

        finishers = [r for r in range(N_NODES) if r != VICTIM] + [SPARE]
        for rank in finishers:
            rc = _wait(procs[rank], 300, f"worker {rank}", tmp,
                       [f"worker-{rank}.out", "master-1.err"])
            assert rc == 0, (
                f"worker {rank} exited rc={rc}; "
                + _tail(tmp, f"worker-{rank}.out")
            )
        rc = _wait(master, 60, "master", tmp, ["master-1.err"])
        assert rc == 0, _tail(tmp, "master-1.err")
    finally:
        for p in procs.values():
            _killpg(p, signal.SIGTERM)
        time.sleep(0.5)
        for p in procs.values():
            _killpg(p)

    survivors = [r for r in range(N_NODES) if r != VICTIM]

    # ---- promotion inside one step boundary, zero restarts ----------
    for rank in survivors + [SPARE]:
        pids = _lines(tmp, str(rank), "PID")
        assert len(pids) == 1 and pids[0][2] == "0", (rank, pids)
        assert _lines(tmp, str(rank), "FALLBACK") == [], rank
        assert len(_lines(tmp, str(rank), "TRANSITION")) == 1, rank
    # the spare's own story: registered idle, warmed ahead of the
    # fault, promoted exactly once
    assert _lines(tmp, str(SPARE), "SPARE"), "spare never registered"
    assert _lines(tmp, str(SPARE), "WARM"), "spare never pre-warmed"
    assert len(_lines(tmp, str(SPARE), "PROMOTED")) == 1

    # ---- the journal tells the promotion story exactly once ---------
    events = read_journal(journal_path)
    by_kind = {}
    for e in events:
        by_kind.setdefault(e.get("kind"), []).append(e)
    assert "scale.restart" not in by_kind, by_kind.get("scale.restart")
    assert "reshard.aborted" not in by_kind, by_kind.get(
        "reshard.aborted")

    (ordered,) = by_kind["reshard.ordered"]
    assert ordered["data"]["order_kind"] == "promote"
    # constant world size: the spare replaces the casualty 1:1
    assert ordered["data"]["world_size"] == N_NODES
    assert ordered["data"]["lost"] == [VICTIM]
    assert ordered["data"]["joined"] == [SPARE]
    assert len(by_kind["spare.registered"]) == 1
    assert len(by_kind["spare.warmed"]) >= 1
    (promoted,) = by_kind["spare.promoted"]
    assert promoted["data"]["spare_rank"] == SPARE
    assert promoted["data"]["lost_rank"] == VICTIM
    (completed,) = by_kind["reshard.completed"]
    assert completed["data"]["duration_s"] > 0.0

    migrated = by_kind["reshard.migrated"]
    assert {e["data"]["node_rank"] for e in migrated} == set(
        survivors + [SPARE])
    for e in migrated:
        assert e["data"]["digest_mismatch"] == 0, e
    # the spare restored out of its warm cache (``local``): every
    # member that was reachable at warm time. Only the victim's own
    # rows can hit the store — the victim advertised its kill-step
    # save moments before dying, leaving the spare no window to pull
    # those two rows peer-to-peer
    (spare_migrated,) = [
        e for e in migrated if e["data"]["node_rank"] == SPARE
    ]
    assert spare_migrated["data"]["local"] >= 4, spare_migrated
    assert spare_migrated["data"]["store"] <= 2, spare_migrated
    # survivors still move their held rows live
    assert sum(e["data"]["live"] for e in migrated) >= 1

    # ---- bit-identical state across the whole new world -------------
    migr_lines = [
        _lines(tmp, str(rank), "MIGRATED")[0]
        for rank in survivors + [SPARE]
    ]
    assert len({parts[1] for parts in migr_lines}) == 1, migr_lines
    assert len({parts[2] for parts in migr_lines}) == 1, migr_lines
    for parts in migr_lines:
        assert parts[3] == "ok", parts
    assert int(migr_lines[0][1]) == KILL_STEP, migr_lines

    # ---- the dataset completed exactly once across the promotion ----
    _assert_exactly_once(
        tmp, [str(r) for r in range(N_NODES)] + [str(SPARE)]
    )


def test_reshard_oscillation_drill(tmp_path):
    """Join -> shrink -> join on one master: order ids strictly
    monotonic, stale broadcasts ignored by latecomers born after
    them, and the dataset exactly-once across all three transitions."""
    tmp = str(tmp_path)
    journal_path = os.path.join(tmp, "journal.jsonl")
    store_dir = os.path.join(tmp, "store")
    env = _drill_env(journal_path)
    master_env = dict(env, DLROVER_TPU_RESHARD="1")
    # no injected fault: the shrink comes from an external SIGKILL
    no_fault = {"DLROVER_FAULT_INJECT": ""}
    BASE = 3           # initial world 0..2
    OSC_DATASET = 2 * DATASET_SIZE  # room for three transitions

    def worker(rank, extra_args=()):
        return _spawn_worker(
            tmp, _worker_env(env, rank, no_fault), port, rank,
            str(rank), store_dir, os.path.join(tmp, f"ram{rank}"),
            extra_args=extra_args, n_nodes=BASE,
            dataset_size=OSC_DATASET,
        )

    procs = {}
    logs = ["master-1.err"] + [f"worker-{r}.out" for r in range(5)]
    try:
        master = _spawn_master(
            tmp, master_env, os.path.join(tmp, "state"), 0, "1"
        )
        procs["master"] = master
        port = _master_port(tmp, "1", master)

        for rank in range(BASE):
            procs[rank] = worker(rank)
        # phase 1: world sealed and training (grow orders only exist
        # on a sealed world)
        _await(lambda: _lines(tmp, "0", "SHARD"),
               "initial world progress", 120, procs, tmp, logs)

        # phase 2: rank 3 joins -> grow order, adopted by everyone
        procs[3] = worker(3, extra_args=["--join"])
        _await(lambda: _lines(tmp, "3", "TRANSITION"),
               "join transition", 120, procs, tmp, logs)

        # phase 3: rank 1 dies (external SIGKILL) -> shrink order
        _killpg(procs[1], signal.SIGKILL)
        procs["dead"] = procs.pop(1)
        _await(lambda: len(_lines(tmp, "0", "TRANSITION")) >= 2,
               "shrink transition", 120, procs, tmp, logs)

        # phase 4: rank 4 joins the shrunken world -> second grow.
        # It is born AFTER two orders were broadcast: the stale ones
        # must not make it stand down or fall back.
        procs[4] = worker(4, extra_args=["--join"])
        _await(lambda: _lines(tmp, "4", "TRANSITION"),
               "second join transition", 180, procs, tmp, logs)

        for rank in (0, 2, 3, 4):
            rc = _wait(procs[rank], 300, f"worker {rank}", tmp,
                       [f"worker-{rank}.out", "master-1.err"])
            assert rc == 0, (
                f"worker {rank} exited rc={rc}; "
                + _tail(tmp, f"worker-{rank}.out")
            )
        rc = _wait(master, 60, "master", tmp, ["master-1.err"])
        assert rc == 0, _tail(tmp, "master-1.err")
    finally:
        for p in procs.values():
            _killpg(p, signal.SIGTERM)
        time.sleep(0.5)
        for p in procs.values():
            _killpg(p)

    events = read_journal(journal_path)
    ordered = [e for e in events if e.get("kind") == "reshard.ordered"]
    ids = [e["data"]["order_id"] for e in ordered]
    kinds = [e["data"]["order_kind"] for e in ordered]
    # strictly monotonic ids across the whole oscillation
    assert all(a < b for a, b in zip(ids, ids[1:])), ids
    assert kinds == ["grow", "shrink", "grow"], kinds
    assert ordered[0]["data"]["joined"] == [3]
    assert ordered[1]["data"]["lost"] == [1]
    assert ordered[2]["data"]["joined"] == [4]
    completed = [
        e for e in events if e.get("kind") == "reshard.completed"
    ]
    assert [e["data"]["order_id"] for e in completed] == ids
    assert not [e for e in events if e.get("kind") == "reshard.aborted"]

    # single incarnations; the latecomers adopted exactly the order
    # addressed to them (stale broadcasts ignored, no fallback)
    for rank in (0, 2, 3, 4):
        pids = _lines(tmp, str(rank), "PID")
        assert len(pids) == 1 and pids[0][2] == "0", (rank, pids)
        assert _lines(tmp, str(rank), "FALLBACK") == [], rank
    adopted_by_4 = [
        e["data"]["order_id"] for e in events
        if e.get("kind") == "reshard.adopted"
        and e["data"]["node_rank"] == 4
    ]
    assert adopted_by_4 == [ids[2]], adopted_by_4
    # rank 3 rode all three orders (its join, the shrink, the second
    # grow); rank 4 only the order that grew it in
    assert len(_lines(tmp, "3", "TRANSITION")) == 3
    assert len(_lines(tmp, "4", "TRANSITION")) == 1

    # exactly-once across join -> shrink -> join
    _assert_exactly_once(tmp, [str(r) for r in range(5)],
                         size=OSC_DATASET)
