"""GPipe + interleaved pipeline-parallel tests on the 8-device CPU
mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

# tier-1 budget (ISSUE 2 satellite): this module costs >50s of the
# 870s budget on a 1-core box; the nightly/full shard still runs it
pytestmark = pytest.mark.slow

from dlrover_tpu.models import llama
from dlrover_tpu.parallel.mesh import create_mesh
from dlrover_tpu.parallel.pipeline import (
    bubble_fraction,
    gpipe_apply,
    interleaved_pipeline_apply,
    pipeline_llama_forward,
)


def _cfg():
    return llama.llama_tiny(num_layers=4, remat="off")


def test_pipeline_forward_matches_dense():
    cfg = _cfg()
    params = llama.init_params(jax.random.key(0), cfg)
    tokens = jax.random.randint(jax.random.key(1), (8, 16), 0,
                                cfg.vocab_size)
    mesh = create_mesh([("pipe", 4)], devices=jax.devices()[:4])
    logits_pp = jax.jit(
        lambda p, t: pipeline_llama_forward(
            p, t, cfg, mesh, num_microbatches=4
        )
    )(params, tokens)
    logits_dense = llama.forward(params, tokens, cfg)
    np.testing.assert_allclose(
        np.asarray(logits_pp), np.asarray(logits_dense),
        rtol=2e-2, atol=2e-2,
    )


def test_pipeline_honors_remat_policy():
    """cfg.remat must apply under the pipeline too (same numerics, less
    activation memory)."""
    cfg = llama.llama_tiny(num_layers=4, remat="dots")
    params = llama.init_params(jax.random.key(0), cfg)
    tokens = jax.random.randint(jax.random.key(1), (8, 16), 0,
                                cfg.vocab_size)
    mesh = create_mesh([("pipe", 4)], devices=jax.devices()[:4])
    logits_pp = jax.jit(
        lambda p, t: pipeline_llama_forward(
            p, t, cfg, mesh, num_microbatches=4
        )
    )(params, tokens)
    dense = llama.forward(params, tokens, cfg)
    np.testing.assert_allclose(
        np.asarray(logits_pp), np.asarray(dense), rtol=2e-2, atol=2e-2
    )


def test_pipeline_degrades_to_scan_on_pp1():
    cfg = _cfg()
    params = llama.init_params(jax.random.key(0), cfg)
    tokens = jax.random.randint(jax.random.key(1), (4, 16), 0,
                                cfg.vocab_size)
    mesh = create_mesh([("data", 8)])  # no pipe axis
    logits = pipeline_llama_forward(params, tokens, cfg, mesh,
                                    num_microbatches=2)
    dense = llama.forward(params, tokens, cfg)
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(dense), rtol=2e-2, atol=2e-2
    )


def test_pipeline_rejects_indivisible_layers():
    cfg = llama.llama_tiny(num_layers=3, remat="off")
    params = llama.init_params(jax.random.key(0), cfg)
    tokens = jnp.zeros((4, 16), jnp.int32)
    mesh = create_mesh([("pipe", 4)], devices=jax.devices()[:4])
    with pytest.raises(ValueError):
        pipeline_llama_forward(params, tokens, cfg, mesh,
                               num_microbatches=2)


def test_bubble_fraction_shrinks_with_chunks():
    assert bubble_fraction(1, 4) == 0.0
    g = bubble_fraction(4, 8, num_chunks=1)
    i2 = bubble_fraction(4, 8, num_chunks=2)
    i4 = bubble_fraction(4, 8, num_chunks=4)
    assert g == pytest.approx(3 / 11)
    assert i4 < i2 < g
    assert i2 == pytest.approx(3 / 19)


def test_interleaved_forward_matches_dense():
    """The circular schedule routes every microbatch through all V*P
    chunks in global layer order — logits must equal the dense model."""
    cfg = llama.llama_tiny(num_layers=8, remat="off")
    params = llama.init_params(jax.random.key(0), cfg)
    tokens = jax.random.randint(jax.random.key(1), (8, 16), 0,
                                cfg.vocab_size)
    mesh = create_mesh([("pipe", 4)], devices=jax.devices()[:4])
    logits_pp = jax.jit(
        lambda p, t: pipeline_llama_forward(
            p, t, cfg, mesh, num_microbatches=4, num_chunks=2
        )
    )(params, tokens)
    dense = llama.forward(params, tokens, cfg)
    np.testing.assert_allclose(
        np.asarray(logits_pp), np.asarray(dense), rtol=2e-2, atol=2e-2
    )


def test_interleaved_matches_gpipe_and_aux():
    """Same math as GPipe on the same partitioning (V=2, 8 layers)."""
    cfg = llama.llama_tiny(num_layers=8, remat="off")
    params = llama.init_params(jax.random.key(0), cfg)
    tokens = jax.random.randint(jax.random.key(2), (8, 16), 0,
                                cfg.vocab_size)
    mesh = create_mesh([("pipe", 2)], devices=jax.devices()[:2])
    y_g, aux_g = jax.jit(
        lambda p, t: pipeline_llama_forward(
            p, t, cfg, mesh, num_microbatches=4, return_aux=True
        )
    )(params, tokens)
    y_i, aux_i = jax.jit(
        lambda p, t: pipeline_llama_forward(
            p, t, cfg, mesh, num_microbatches=4, num_chunks=2,
            return_aux=True,
        )
    )(params, tokens)
    np.testing.assert_allclose(
        np.asarray(y_i), np.asarray(y_g), rtol=2e-2, atol=2e-2
    )
    np.testing.assert_allclose(
        float(aux_i), float(aux_g), rtol=1e-4, atol=1e-5
    )


def test_interleaved_rejects_bad_shapes():
    cfg = llama.llama_tiny(num_layers=8, remat="off")
    params = llama.init_params(jax.random.key(0), cfg)
    tokens = jnp.zeros((8, 16), jnp.int32)
    mesh = create_mesh([("pipe", 4)], devices=jax.devices()[:4])
    with pytest.raises(ValueError):  # 8 layers, pp*chunks = 12
        pipeline_llama_forward(params, tokens, cfg, mesh,
                               num_microbatches=4, num_chunks=3)
    with pytest.raises(ValueError):  # microbatches not multiple of pp
        pipeline_llama_forward(params, tokens, cfg, mesh,
                               num_microbatches=2, num_chunks=2)


def test_interleaved_training_learns():
    """Grads flow backward through the wrapped-ring ppermute chain."""
    cfg = llama.llama_tiny(num_layers=8, remat="off")
    mesh = create_mesh([("pipe", 2)], devices=jax.devices()[:2])
    params = llama.init_params(jax.random.key(0), cfg)
    opt = optax.adam(1e-2)
    opt_state = opt.init(params)
    tokens = jax.random.randint(jax.random.key(1), (8, 16), 0,
                                cfg.vocab_size)

    def loss_fn(p):
        logits = pipeline_llama_forward(
            p, tokens, cfg, mesh, num_microbatches=2, num_chunks=2
        )
        logp = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.mean(
            jnp.take_along_axis(logp, tokens[..., None], axis=-1)
        )

    @jax.jit
    def step(p, s):
        loss, g = jax.value_and_grad(loss_fn)(p)
        updates, s2 = opt.update(g, s, p)
        return loss, optax.apply_updates(p, updates), s2

    losses = []
    for _ in range(8):
        loss, params, opt_state = step(params, opt_state)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.9, losses


def test_pipeline_training_learns():
    """End-to-end: grads flow backward through the ppermute chain."""
    cfg = _cfg()
    mesh = create_mesh([("pipe", 4)], devices=jax.devices()[:4])
    params = llama.init_params(jax.random.key(0), cfg)
    opt = optax.adam(1e-2)
    opt_state = opt.init(params)
    tokens = jax.random.randint(jax.random.key(1), (8, 16), 0,
                                cfg.vocab_size)

    def loss_fn(p):
        logits = pipeline_llama_forward(
            p, tokens, cfg, mesh, num_microbatches=4
        )
        logp = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.mean(
            jnp.take_along_axis(logp, tokens[..., None], axis=-1)
        )

    step = jax.jit(
        lambda p, s: (lambda l, g: (l, *_apply(opt, g, s, p)))(
            *jax.value_and_grad(loss_fn)(p)
        )
    )

    def _apply(opt, g, s, p):
        updates, s2 = opt.update(g, s, p)
        return optax.apply_updates(p, updates), s2

    losses = []
    for _ in range(8):
        loss, params, opt_state = step(params, opt_state)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.9, losses


def test_measured_bubble_matches_prediction():
    """The EXECUTED schedule's occupancy (valid work items counted
    inside the compiled program, psum'd over the ring) must equal
    bubble_fraction()'s closed form — the dryrun pp=4 leg's
    load-bearing assertion (VERDICT r4 item #7)."""
    cfg = llama.llama_tiny(num_layers=8, remat="off")
    params = llama.init_params(jax.random.key(0), cfg)
    tokens = jax.random.randint(jax.random.key(1), (8, 16), 0,
                                cfg.vocab_size)
    for pp, chunks, micro in ((4, 2, 4), (2, 2, 4)):
        mesh = create_mesh([("pipe", pp)], devices=jax.devices()[:pp])
        logits, _aux, stats = pipeline_llama_forward(
            params, tokens, cfg, mesh, num_microbatches=micro,
            num_chunks=chunks, schedule_stats=True,
        )
        assert np.isfinite(np.asarray(logits)).all()
        predicted = bubble_fraction(pp, micro, chunks)
        assert float(stats["bubble_measured"]) == pytest.approx(
            predicted, abs=1e-6  # f32 division rounding only
        ), (pp, chunks, micro)
        # the underlying count is EXACT: every scheduled work item
        # executed exactly once
        assert float(stats["work_slots_used"]) == micro * chunks * pp
