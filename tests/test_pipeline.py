"""GPipe pipeline-parallel tests on the 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from dlrover_tpu.models import llama
from dlrover_tpu.parallel.mesh import create_mesh
from dlrover_tpu.parallel.pipeline import (
    gpipe_apply,
    pipeline_llama_forward,
)


def _cfg():
    return llama.llama_tiny(num_layers=4, remat="off")


def test_pipeline_forward_matches_dense():
    cfg = _cfg()
    params = llama.init_params(jax.random.key(0), cfg)
    tokens = jax.random.randint(jax.random.key(1), (8, 16), 0,
                                cfg.vocab_size)
    mesh = create_mesh([("pipe", 4)], devices=jax.devices()[:4])
    logits_pp = jax.jit(
        lambda p, t: pipeline_llama_forward(
            p, t, cfg, mesh, num_microbatches=4
        )
    )(params, tokens)
    logits_dense = llama.forward(params, tokens, cfg)
    np.testing.assert_allclose(
        np.asarray(logits_pp), np.asarray(logits_dense),
        rtol=2e-2, atol=2e-2,
    )


def test_pipeline_honors_remat_policy():
    """cfg.remat must apply under the pipeline too (same numerics, less
    activation memory)."""
    cfg = llama.llama_tiny(num_layers=4, remat="dots")
    params = llama.init_params(jax.random.key(0), cfg)
    tokens = jax.random.randint(jax.random.key(1), (8, 16), 0,
                                cfg.vocab_size)
    mesh = create_mesh([("pipe", 4)], devices=jax.devices()[:4])
    logits_pp = jax.jit(
        lambda p, t: pipeline_llama_forward(
            p, t, cfg, mesh, num_microbatches=4
        )
    )(params, tokens)
    dense = llama.forward(params, tokens, cfg)
    np.testing.assert_allclose(
        np.asarray(logits_pp), np.asarray(dense), rtol=2e-2, atol=2e-2
    )


def test_pipeline_degrades_to_scan_on_pp1():
    cfg = _cfg()
    params = llama.init_params(jax.random.key(0), cfg)
    tokens = jax.random.randint(jax.random.key(1), (4, 16), 0,
                                cfg.vocab_size)
    mesh = create_mesh([("data", 8)])  # no pipe axis
    logits = pipeline_llama_forward(params, tokens, cfg, mesh,
                                    num_microbatches=2)
    dense = llama.forward(params, tokens, cfg)
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(dense), rtol=2e-2, atol=2e-2
    )


def test_pipeline_rejects_indivisible_layers():
    cfg = llama.llama_tiny(num_layers=3, remat="off")
    params = llama.init_params(jax.random.key(0), cfg)
    tokens = jnp.zeros((4, 16), jnp.int32)
    mesh = create_mesh([("pipe", 4)], devices=jax.devices()[:4])
    with pytest.raises(ValueError):
        pipeline_llama_forward(params, tokens, cfg, mesh,
                               num_microbatches=2)


def test_pipeline_training_learns():
    """End-to-end: grads flow backward through the ppermute chain."""
    cfg = _cfg()
    mesh = create_mesh([("pipe", 4)], devices=jax.devices()[:4])
    params = llama.init_params(jax.random.key(0), cfg)
    opt = optax.adam(1e-2)
    opt_state = opt.init(params)
    tokens = jax.random.randint(jax.random.key(1), (8, 16), 0,
                                cfg.vocab_size)

    def loss_fn(p):
        logits = pipeline_llama_forward(
            p, tokens, cfg, mesh, num_microbatches=4
        )
        logp = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.mean(
            jnp.take_along_axis(logp, tokens[..., None], axis=-1)
        )

    step = jax.jit(
        lambda p, s: (lambda l, g: (l, *_apply(opt, g, s, p)))(
            *jax.value_and_grad(loss_fn)(p)
        )
    )

    def _apply(opt, g, s, p):
        updates, s2 = opt.update(g, s, p)
        return optax.apply_updates(p, updates), s2

    losses = []
    for _ in range(8):
        loss, params, opt_state = step(params, opt_state)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.9, losses
