"""dlint: the framework's own suite, and the tier-1 gate (ISSUE 15).

Three layers:

  * fixture tests — every rule has one file under tests/fixtures/dlint/
    with exactly ONE intentional violation; the rule must fire exactly
    once with the expected anchor. A rule that silently stops matching
    fails here, not months later when the bug class it guards returns.
  * the gate — ``python -m tools.dlint --check`` (the same command CI
    and humans run) must exit 0 against the committed baseline, inside
    the tier-1 time budget.
  * the ratchet — the committed baseline may only shrink: every entry
    carries a real justification, and this suite pins the count so a
    new violation can't ride in as "one more baseline line".
"""

import json
import os
import subprocess
import sys
import time
from pathlib import Path

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools.dlint.baseline import BASELINE_PATH, load_baseline  # noqa: E402
from tools.dlint.core import (  # noqa: E402
    REPO_ROOT,
    default_files,
    lint_files,
    lint_repo,
)
from tools.dlint.rules import ALL_RULES  # noqa: E402

FIXTURES = Path(__file__).parent / "fixtures" / "dlint"

#: rule id -> the anchor its fixture's single violation must carry
EXPECTED_ANCHORS = {
    "event-names": "event:BadEventName",
    "event-vocabulary": "unexpected:preempt.surprise_event",
    "span-names": "span:Bad Span Name",
    "goodput-phases": "phase:not_a_real_phase",
    "signal-chain": "signal.signal",
    "supervised-rpc": "rpc:report_status",
    "thread-name": "Thread",
    "lock-discipline": "Ledger._items",
    "blocking-under-lock": "poll:time.sleep",
    "no-blocking-in-async": "dispatch:time.sleep",
    "commit-before-reply": "get_task:no-persist",
    "knob-registry": "default:DLROVER_TPU_FIXTURE_ONLY_KNOB",
    "metric-registry": "undocumented:dlrover_fixture_only_metric_total",
}

#: the baseline ratchet: justified exceptions may be removed, never
#: added. If you fixed one, lower this number in the same commit.
MAX_BASELINE_ENTRIES = 5

#: the gate's whole-run time budget (tier-1 contract from ISSUE 15)
GATE_BUDGET_S = 15.0


# --------------------------------------------------------------- fixtures


@pytest.mark.parametrize("rule_cls", ALL_RULES, ids=lambda c: c.id)
def test_fixture_fires_exactly_once(rule_cls):
    """Each rule's fixture contains exactly one violation — and the
    rule sees exactly that one (no more, no fewer)."""
    fixture = FIXTURES / (rule_cls.id.replace("-", "_") + ".py")
    assert fixture.exists(), (
        f"rule {rule_cls.id} has no fixture at {fixture} — every rule "
        "ships one file with one intentional violation"
    )
    res = lint_files([fixture], rules=[rule_cls], full_run=False,
                     respect_targets=False)
    assert len(res.findings) == 1, (
        f"{rule_cls.id} found {len(res.findings)} violations in its "
        f"fixture, wanted exactly 1: {[f.message for f in res.findings]}"
    )
    f = res.findings[0]
    assert f.rule == rule_cls.id
    assert f.anchor == EXPECTED_ANCHORS[rule_cls.id], f.anchor
    assert f.fingerprint and len(f.fingerprint) == 12


def test_every_rule_has_expected_anchor_entry():
    assert {c.id for c in ALL_RULES} == set(EXPECTED_ANCHORS)


# ------------------------------------------------------------------- gate


def test_repo_is_clean_in_process():
    """The whole-repo run produces no findings beyond the committed
    baseline, and no baseline entry is stale — the same predicate as
    ``--check``, asserted in-process with per-rule timings on failure."""
    res = lint_repo()
    baseline = load_baseline()
    new = [f for f in res.findings if f.fingerprint not in baseline]
    active = {f.fingerprint for f in res.findings}
    stale = sorted(set(baseline) - active)
    timings = "; ".join(
        f"{rid}={s * 1000:.0f}ms" for rid, s in
        sorted(res.timings.items(), key=lambda kv: -kv[1])
    )
    assert not new, (
        "unbaselined dlint findings (fix them or justify in "
        f"tools/dlint/baseline.json):\n  "
        + "\n  ".join(f"{f.location()}: {f.rule}: {f.message}"
                      for f in new)
        + f"\n[{timings}]"
    )
    assert not stale, (
        f"stale baseline entries (the code they describe is gone — "
        f"delete them): {stale}"
    )


def test_gate_subprocess_inside_budget():
    """The command CI runs, exactly as CI runs it — and inside the
    tier-1 time budget."""
    t0 = time.monotonic()
    proc = subprocess.run(
        [sys.executable, "-m", "tools.dlint", "--check"],
        cwd=str(REPO_ROOT), capture_output=True, text=True,
        timeout=GATE_BUDGET_S * 4,
    )
    elapsed = time.monotonic() - t0
    assert proc.returncode == 0, (
        f"dlint gate failed (rc={proc.returncode}):\n{proc.stdout}"
        f"\n{proc.stderr}"
    )
    assert elapsed < GATE_BUDGET_S, (
        f"dlint gate took {elapsed:.1f}s, budget is {GATE_BUDGET_S}s"
    )


def test_json_output_schema():
    """``--json`` is the machine interface (docs/STATIC_ANALYSIS.md):
    dashboards and editors parse it, so the envelope is a contract."""
    proc = subprocess.run(
        [sys.executable, "-m", "tools.dlint", "--json"],
        cwd=str(REPO_ROOT), capture_output=True, text=True, timeout=60,
    )
    doc = json.loads(proc.stdout)
    for key in ("findings", "new", "baselined", "stale", "timings",
                "files", "seconds"):
        assert key in doc, f"--json envelope missing {key!r}"
    assert doc["new"] == []  # same predicate as the gate
    assert doc["files"] == len(default_files())
    for f in doc["findings"]:
        for key in ("rule", "path", "line", "message", "anchor",
                    "fingerprint"):
            assert key in f, f"finding missing {key!r}: {f}"
    assert set(doc["timings"]) == {c.id for c in ALL_RULES}


# ---------------------------------------------------------------- ratchet


def test_baseline_never_grows():
    baseline = load_baseline()
    assert len(baseline) <= MAX_BASELINE_ENTRIES, (
        f"baseline grew to {len(baseline)} entries (max "
        f"{MAX_BASELINE_ENTRIES}): new violations must be FIXED, not "
        "baselined — the baseline exists for the grandfathered "
        "designs documented in it, and only shrinks"
    )


def test_baseline_entries_are_justified():
    baseline = load_baseline()
    for fp, entry in baseline.items():
        for key in ("rule", "path", "anchor", "reason"):
            assert key in entry, f"{fp}: baseline entry missing {key!r}"
        reason = entry["reason"]
        assert reason and "TODO" not in reason and len(reason) > 40, (
            f"{fp} ({entry['path']}): baseline reasons must be real "
            f"justifications, got {reason!r}"
        )
    assert BASELINE_PATH.exists()
