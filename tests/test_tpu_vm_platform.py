"""Platform-layer tests: job spec ingestion, TPU-VM scaler/watcher over a
fake fleet API, and the job manager's relaunch loop end-to-end on the
fake platform.

Parity: the reference's mocked-k8s pattern (tests/test_pod_scaler.py:191
feeding a fake client, tests/test_k8s_watcher.py feeding pod events).
"""

import textwrap

from dlrover_tpu.common.constants import (
    NodeEnv,
    NodeEventType,
    NodeExitReason,
    NodeStatus,
    NodeType,
)
from dlrover_tpu.common.node import Node, NodeGroupResource, NodeResource
from dlrover_tpu.master.node.dist_job_manager import DistributedJobManager
from dlrover_tpu.master.scaler.base_scaler import ScalePlan
from dlrover_tpu.scheduler.job_spec import JobArgs, parse_memory_mb
from dlrover_tpu.scheduler.tpu_vm import FakeTpuVmApi, TpuVmState
from dlrover_tpu.scheduler.tpu_vm_scaler import TpuVmScaler
from dlrover_tpu.scheduler.tpu_vm_watcher import TpuVmWatcher


# ---------------------------------------------------------------- job spec

def test_job_spec_yaml_roundtrip(tmp_path):
    spec = tmp_path / "job.yaml"
    spec.write_text(textwrap.dedent("""\
        apiVersion: dlrover-tpu/v1
        kind: ElasticTpuJob
        metadata:
          name: llama-pretrain
        spec:
          distributionStrategy: allreduce
          nodeUnit: 4
          relaunchStrategy: always
          heartbeatTimeout: 30
          project: my-proj
          zone: us-central2-b
          worker:
            replicas: 16
            minReplicas: 8
            acceleratorType: v5litepod-16
            runtimeVersion: tpu-ubuntu2204-base
            preemptible: true
            maxRelaunchCount: 5
            resource: {cpu: 96, memory: 180Gi}
            env: {FOO: bar}
    """))
    args = JobArgs.from_file(str(spec))
    assert args.job_name == "llama-pretrain"
    assert args.node_num == 16 and args.min_node_num == 8
    assert args.node_unit == 4
    assert args.relaunch_always is True
    assert args.heartbeat_timeout == 30
    assert args.project == "my-proj" and args.zone == "us-central2-b"
    assert args.accelerator_type == "v5litepod-16"
    assert args.preemptible is True
    assert args.max_relaunch_count == 5
    assert args.node_resource.cpu == 96
    assert args.node_resource.memory == 180 * 1024
    assert args.worker_env == {"FOO": "bar"}
    assert args.worker_group.count == 16


def test_parse_memory_quantities():
    assert parse_memory_mb("512Mi") == 512
    assert parse_memory_mb("2Gi") == 2048
    assert parse_memory_mb("1.5G") == 1536
    assert parse_memory_mb(1073741824) == 1024  # bytes


# ------------------------------------------------------------------ scaler

def _scaler(api, **kw):
    return TpuVmScaler(
        "job1", api, "master:5555",
        accelerator_type="v5litepod-16",
        runtime_version="tpu-vm-base", **kw,
    )


def test_scale_launch_creates_vms_with_env_contract():
    api = FakeTpuVmApi()
    s = _scaler(api)
    plan = ScalePlan(launch_nodes=[
        Node(NodeType.WORKER, 0), Node(NodeType.WORKER, 1),
    ])
    s.scale(plan)
    fleet = {r.name: r for r in api.list_nodes()}
    assert set(fleet) == {"job1-worker-0", "job1-worker-1"}
    rec = fleet["job1-worker-0"]
    assert rec.state == TpuVmState.CREATING
    assert rec["labels"]["dlrover-job"] == "job1"
    assert rec["labels"]["dlrover-rank"] == "0"
    md = rec["metadata"]
    assert md[NodeEnv.MASTER_ADDR] == "master:5555"
    assert md[NodeEnv.NODE_ID] == "0"
    assert rec["accelerator_type"] == "v5litepod-16"


def test_scale_remove_deletes_vms():
    api = FakeTpuVmApi()
    s = _scaler(api)
    s.scale(ScalePlan(launch_nodes=[Node(NodeType.WORKER, 0)]))
    api.tick()  # READY
    node = Node(NodeType.WORKER, 0, name="job1-worker-0")
    s.scale(ScalePlan(remove_nodes=[node]))
    api.tick()  # DELETING -> gone
    assert api.list_nodes() == []


def test_scale_group_reconciles_up_and_down():
    api = FakeTpuVmApi()
    s = _scaler(api)
    group = {NodeType.WORKER: NodeGroupResource(3, NodeResource())}
    s.scale(ScalePlan(node_group_resources=group))
    assert len(api.list_nodes()) == 3
    # idempotent: same target, no extra creates
    n_creates = len(api.create_calls)
    s.scale(ScalePlan(node_group_resources=group))
    assert len(api.create_calls) == n_creates
    # shrink to 1 removes the newest ids first
    group = {NodeType.WORKER: NodeGroupResource(1, NodeResource())}
    s.scale(ScalePlan(node_group_resources=group))
    api.tick()
    assert [r.name for r in api.list_nodes()] == ["job1-worker-0"]


def test_reconcile_replaces_preempted_capacity():
    """A preempted VM no longer counts as live, so reconciling the same
    target count provisions a replacement with a fresh id."""
    api = FakeTpuVmApi()
    s = _scaler(api)
    group = {NodeType.WORKER: NodeGroupResource(2, NodeResource())}
    s.scale(ScalePlan(node_group_resources=group))
    api.tick()
    api.preempt("job1-worker-1")
    s.scale(ScalePlan(node_group_resources=group))
    names = {r.name for r in api.list_nodes()}
    assert "job1-worker-2" in names  # replacement


# ----------------------------------------------------------------- watcher

def test_watcher_lifecycle_events():
    api = FakeTpuVmApi()
    s = _scaler(api)
    w = TpuVmWatcher("job1", api, poll_interval=0.01)
    s.scale(ScalePlan(launch_nodes=[Node(NodeType.WORKER, 0)]))

    events = w.poll_once()
    assert [(e.event_type, e.node.status) for e in events] == [
        (NodeEventType.ADDED, NodeStatus.PENDING)
    ]
    api.tick()  # -> READY
    events = w.poll_once()
    assert [(e.event_type, e.node.status) for e in events] == [
        (NodeEventType.MODIFIED, NodeStatus.RUNNING)
    ]
    api.preempt("job1-worker-0")
    events = w.poll_once()
    assert events[0].node.status == NodeStatus.FAILED
    assert events[0].node.exit_reason == NodeExitReason.PREEMPTED

    api.delete_node("job1-worker-0")
    api.tick()  # gone
    events = w.poll_once()
    assert [(e.event_type, e.node.status) for e in events] == [
        (NodeEventType.DELETED, NodeStatus.DELETED)
    ]


def test_watcher_maps_hardware_fault():
    api = FakeTpuVmApi(auto_ready=True)
    s = _scaler(api)
    w = TpuVmWatcher("job1", api)
    s.scale(ScalePlan(launch_nodes=[Node(NodeType.WORKER, 0)]))
    w.poll_once()
    api.fail("job1-worker-0", state=TpuVmState.READY,
             health="UNHEALTHY_TPU")
    events = w.poll_once()
    assert events[0].node.status == NodeStatus.FAILED
    assert events[0].node.exit_reason == NodeExitReason.HARDWARE_ERROR


def test_watcher_ignores_other_jobs():
    api = FakeTpuVmApi(auto_ready=True)
    api.create_node("other-worker-0", "v5e", "base",
                    {"dlrover-job": "other", "dlrover-type": "worker",
                     "dlrover-id": "0"}, {})
    w = TpuVmWatcher("job1", api)
    assert w.poll_once() == []
    assert w.list() == []


# ------------------------------------------- job manager on the fake fleet

def test_job_manager_relaunches_preempted_vm_on_fake_platform():
    """End-to-end on the fake platform: start -> fleet provisioned;
    preemption event -> relaunch -> replacement VM appears (parity: the
    reference's pod-relaunch system tests)."""
    import types

    api = FakeTpuVmApi()
    scaler = _scaler(api)
    watcher = TpuVmWatcher("job1", api, poll_interval=0.01)
    job_args = types.SimpleNamespace(node_num=2, node_resource=None)
    mgr = DistributedJobManager(
        job_args=job_args, scaler=scaler, watcher=None,
    )
    mgr.start()
    try:
        assert len(api.list_nodes()) == 2
        api.tick()  # both READY
        for e in watcher.poll_once():
            mgr.process_event(e)
        running = mgr.get_running_nodes()
        assert len(running) == 2

        api.preempt("job1-worker-1")
        for e in watcher.poll_once():
            mgr.process_event(e)
        # the preempted node was relaunched as a fresh VM
        names = {r.name for r in api.list_nodes()}
        assert "job1-worker-2" in names
        assert "job1-worker-1" in api.delete_calls
        node1 = mgr.get_node(NodeType.WORKER, 1)
        assert node1.status == NodeStatus.FAILED
        assert node1.is_released
        node2 = mgr.get_node(NodeType.WORKER, 2)
        assert node2 is not None
        assert node2.relaunch_count == 1
    finally:
        mgr.stop()


def test_build_platform_fake_and_manual(tmp_path, monkeypatch):
    from dlrover_tpu.scheduler.factory import build_platform

    args = JobArgs(job_name="j", platform="tpu_vm")
    # no project/zone and no fake flag: manual platform (agents started
    # out of band), nothing fabricated
    monkeypatch.delenv("DLROVER_TPU_FAKE_PLATFORM", raising=False)
    assert build_platform(args, "localhost:1") == (None, None)

    monkeypatch.setenv("DLROVER_TPU_FAKE_PLATFORM", "1")
    scaler, watcher = build_platform(args, "localhost:1")
    assert isinstance(scaler, TpuVmScaler)
    assert isinstance(watcher, TpuVmWatcher)


def test_master_build_job_args_from_spec(tmp_path):
    from dlrover_tpu.master.args import parse_master_args
    from dlrover_tpu.master.main import build_job_args

    spec = tmp_path / "job.json"
    spec.write_text(
        '{"metadata": {"name": "sj"}, "spec": {"nodeUnit": 2, '
        '"worker": {"replicas": 4, "acceleratorType": "v5litepod-8"}}}'
    )
    args = parse_master_args([
        "--platform", "tpu_vm", "--job_spec", str(spec),
    ])
    job_args = build_job_args(args)
    assert job_args.job_name == "sj"
    assert job_args.node_num == 4
    assert job_args.node_unit == 2
    assert job_args.accelerator_type == "v5litepod-8"
    # CLI --node_num overrides the spec
    args = parse_master_args([
        "--platform", "tpu_vm", "--job_spec", str(spec),
        "--node_num", "6",
    ])
    assert build_job_args(args).node_num == 6


def test_spec_platform_used_unless_cli_overrides(tmp_path):
    spec = tmp_path / "j.json"
    spec.write_text('{"metadata": {"name": "x"}, '
                    '"spec": {"platform": "process", "worker": {}}}')
    assert JobArgs.from_file(str(spec)).platform == "process"
    assert JobArgs.from_file(str(spec), platform="tpu_vm").platform == \
        "tpu_vm"


def test_autoscaler_straggler_plan_removes_targeted_ranks():
    """A remove_ranks plan must evict exactly the straggler nodes, not
    the newest ids (which the generic shrink would pick)."""
    import types

    from dlrover_tpu.master.node.job_auto_scaler import (
        AllreduceTrainingAutoScaler,
    )
    from dlrover_tpu.master.resource.optimizer import ResourcePlan

    api = FakeTpuVmApi(auto_ready=True)
    scaler = _scaler(api)
    mgr = DistributedJobManager(
        job_args=types.SimpleNamespace(node_num=4, node_resource=None),
        scaler=scaler,
    )
    mgr.start()
    try:
        auto = AllreduceTrainingAutoScaler(mgr, None, scaler)
        plan = ResourcePlan()
        plan.node_group_resources[NodeType.WORKER] = NodeGroupResource(
            2, NodeResource()
        )
        plan.remove_ranks = [0, 2]
        auto.execute_job_optimization_plan(plan)
        # stragglers 0 and 2 were deleted; 1 and 3 survive
        names = {r.name for r in api.list_nodes()
                 if r.state not in ("DELETING",)}
        assert names == {"job1-worker-1", "job1-worker-3"}
    finally:
        mgr.stop()


def test_relaunch_always_overrides_fatal_exit(tmp_path):
    import types

    from dlrover_tpu.common.constants import NodeExitReason

    mgr = DistributedJobManager(
        job_args=types.SimpleNamespace(relaunch_always=True),
    )
    node = Node(NodeType.WORKER, 0)
    node.set_exit_reason(NodeExitReason.FATAL_ERROR)
    assert mgr._should_relaunch(node) is True
    mgr2 = DistributedJobManager(job_args=types.SimpleNamespace())
    node2 = Node(NodeType.WORKER, 0)
    node2.set_exit_reason(NodeExitReason.FATAL_ERROR)
    assert mgr2._should_relaunch(node2) is False
