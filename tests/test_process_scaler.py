"""ProcessScaler: real subprocesses as fake cluster nodes.

The multi-node-without-a-cluster platform (SURVEY §4): master +
DistributedJobManager + ProcessScaler drive real child processes through
the launch -> fail -> relaunch -> succeed lifecycle."""

import sys
import time
from types import SimpleNamespace

import pytest

from dlrover_tpu.common.constants import NodeStatus, NodeType
from dlrover_tpu.common.node import NodeResource
from dlrover_tpu.master.monitor.speed_monitor import SpeedMonitor
from dlrover_tpu.master.node.dist_job_manager import create_job_manager
from dlrover_tpu.master.scaler.process_scaler import ProcessScaler


def _wait(pred, timeout=15.0, interval=0.1):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


def _build(command, node_num=2):
    scaler = ProcessScaler(
        "test-job", master_addr="localhost:0", command=command,
    )
    args = SimpleNamespace(node_num=node_num,
                           node_resource=NodeResource())
    mgr = create_job_manager(
        args, SpeedMonitor(), scaler=scaler, watcher=scaler.watcher,
    )
    return scaler, mgr


def test_successful_job_lifecycle():
    scaler, mgr = _build([sys.executable, "-c", "import time; "
                          "time.sleep(0.2)"])
    mgr.start()
    try:
        # 30s: interpreter startup of the children can exceed the
        # default wait under full-suite load
        assert _wait(mgr.all_workers_exited, timeout=30)
        assert mgr.all_workers_succeeded()
    finally:
        mgr.stop()
        scaler.stop()


def test_crash_relaunch_until_exhausted():
    scaler, mgr = _build(
        [sys.executable, "-c", "import sys; sys.exit(3)"], node_num=1
    )
    mgr.start()
    try:
        # initial launch + 3 relaunches (default max_relaunch_count)
        assert _wait(
            lambda: len(mgr.get_all_nodes()) == 4, timeout=30
        ), [n.name for n in mgr.get_all_nodes()]
        assert _wait(mgr.all_workers_exited, timeout=30)
        assert not mgr.all_workers_succeeded()
    finally:
        mgr.stop()
        scaler.stop()


def test_sigterm_maps_to_killed_and_relaunches():
    scaler, mgr = _build(
        [sys.executable, "-c", "import time; time.sleep(60)"],
        node_num=1,
    )
    mgr.start()
    try:
        assert _wait(lambda: scaler._procs)
        pid_proc = next(iter(scaler._procs.values()))
        pid_proc.terminate()
        # killed -> relaunched with a fresh process
        assert _wait(
            lambda: len(mgr.get_all_nodes()) >= 2, timeout=30
        )
        node0 = mgr.get_node(NodeType.WORKER, 0)
        assert node0.exit_reason == "killed"
    finally:
        mgr.stop()
        scaler.stop()
