"""Drill worker for the preemption chaos test (not a test module).

Speaks the real agent protocol against a live master with a real
FlashCheckpointer and an armed DrainCoordinator: joins the training
rendezvous, consumes data shards (saving a RAM-tier checkpoint every
step), and reports the global step.

Fault surface: ``DLROVER_FAULT_INJECT=preempt@N:notice=S`` delivers
SIGTERM to this process mid-epoch and arms a SIGKILL reclaim S seconds
later — the platform preemption the drain must beat. The armed
DrainCoordinator turns the SIGTERM into the deadline-budgeted drain
(report PREEMPTED, emergency durable checkpoint, relinquish in-flight
shards, final goodput) and exits rc 21 before the reclaim lands.

The relaunched incarnation (RESTART_COUNT=1 gates the injection off)
restores from the emergency checkpoint, emits ``RESUMED <step>``, and
finishes the epoch — the test asserts the SHARD ranges across all
incarnations exactly partition the dataset.
"""

import argparse
import os
import sys
import threading
import time

import numpy as np


def _state_for(step: int):
    # step-stamped payload: the resumed incarnation can verify the
    # restored arrays really belong to the step the manifest claims
    return {"w": np.full((8,), float(step)), "bias": np.arange(4.0) + step}


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--master_addr", required=True)
    p.add_argument("--node_id", type=int, required=True)
    p.add_argument("--out", required=True)
    p.add_argument("--ckpt_dir", required=True)
    p.add_argument("--ram_dir", required=True)
    p.add_argument("--dataset_size", type=int, default=96)
    p.add_argument("--batch_size", type=int, default=4)
    p.add_argument("--shard_secs", type=float, default=0.08,
                   help="simulated train time per shard")
    args = p.parse_args()

    from dlrover_tpu.common.log import set_process_index

    set_process_index(args.node_id)

    from dlrover_tpu.agent.master_client import MasterClient
    from dlrover_tpu.agent.sharding.client import ShardingClient
    from dlrover_tpu.common.constants import NodeEnv, RendezvousName
    from dlrover_tpu.fault_tolerance.drain import DrainCoordinator
    from dlrover_tpu.fault_tolerance.injection import FaultInjector
    from dlrover_tpu.telemetry import goodput
    from dlrover_tpu.telemetry import record
    from dlrover_tpu.trainer.checkpoint import FlashCheckpointer

    led = goodput.install()
    restart_count = int(os.environ.get(NodeEnv.RESTART_COUNT, "0") or 0)

    out = open(args.out, "a", buffering=1)

    def emit(line: str):
        out.write(line + "\n")
        print(f"[worker {args.node_id}] {line}", flush=True)

    client = MasterClient(
        args.master_addr, node_id=args.node_id, node_type="worker",
    )
    # the RUNNING report is what closes the preemption fault window on
    # the master when the relaunched incarnation comes back (servicer
    # _preempted_ranks -> preempt.recovered)
    client.update_node_status("running", "", restart_count)
    reconnected = threading.Event()
    client.add_reconnect_hook("drill-flag", reconnected.set)
    injector = FaultInjector.from_env(role="worker")

    # persist_interval=0: the persistent tier is written only by the
    # emergency (force_persist) save, so a persisted archive in
    # ckpt_dir proves the drain ran — not a periodic save
    ckpt = FlashCheckpointer(
        args.ckpt_dir,
        ram_dir=args.ram_dir,
        persist_interval=0,
        use_orbax=False,
        stage="sync",
    )

    cur = {"step": 0, "state": _state_for(0)}
    state0, step0 = ckpt.restore()
    if step0 is not None:
        cur["step"] = int(step0)
        cur["state"] = state0
        # prove the payload matches the step the tier claims
        ok = int(state0["w"][0]) == int(step0)
        emit(f"RESUMED {int(step0)} {'ok' if ok else 'STATE_MISMATCH'}")

    drain = DrainCoordinator(
        master_client_fn=lambda: client,
        checkpointer_fn=lambda: ckpt,
        state_provider=lambda: (cur["step"], cur["state"]),
        restart_count=restart_count,
    )
    drain.arm()

    def rendezvous(tag: str) -> int:
        reconnected.clear()
        client.join_rendezvous(args.node_id, 1)
        deadline = time.monotonic() + 60
        while True:
            if reconnected.is_set():
                reconnected.clear()
                client.join_rendezvous(args.node_id, 1)
            rdzv_round, _, world = client.get_comm_world(
                RendezvousName.TRAINING, args.node_id
            )
            if world and args.node_id in world:
                record("rendezvous.joined", round=rdzv_round,
                       node=args.node_id)
                emit(f"{tag} {rdzv_round}")
                return rdzv_round
            if time.monotonic() > deadline:
                emit(f"ERROR {tag} timeout")
                raise TimeoutError(tag)
            time.sleep(0.2)

    # min_nodes=1: the relaunched incarnation re-joins alone mid-epoch
    # (its peer is busy consuming) and the round must complete without
    # waiting on the preempted rank — the instant-eviction assert
    client.report_rdzv_params(
        min_nodes=1, max_nodes=2, waiting_timeout=0.5, node_unit=1,
    )
    rendezvous("ROUND")

    sharding = ShardingClient(
        dataset_name="preempt-drill",
        batch_size=args.batch_size,
        num_epochs=1,
        dataset_size=args.dataset_size,
        shuffle=False,
        num_minibatches_per_shard=1,
        master_client=client,
        fetch_batch=2,
        lookahead=2,
    )
    step = cur["step"]
    while True:
        shard = sharding.fetch_shard(poll_interval=0.2, max_wait=120.0)
        if shard is None:
            break
        emit(f"SHARD {shard.start} {shard.end}")
        time.sleep(args.shard_secs)
        step += 1
        cur["state"] = _state_for(step)
        cur["step"] = step
        # RAM-tier-only save (persist_interval=0): keeps the pipeline
        # warm so the emergency save exercises the loaded path
        ckpt.save(step, cur["state"])
        led.on_step()
        client.report_global_step(step)
        assert sharding._current_task is not None
        sharding.report_task_done(sharding._current_task.task_id)
        if injector is not None:
            # preempt@N:notice=S fires here: SIGTERM -> armed drain ->
            # rc 21, with the SIGKILL reclaim S seconds out
            injector.maybe_inject(step)

    emit(f"STEPS {step}")
    snap = led.close()
    client.report_goodput(final=True)
    emit(f"ELAPSED {snap['elapsed_s']:.3f}")
    emit("DONE")
    ckpt.close()
    client.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
