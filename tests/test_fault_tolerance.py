"""Unit tests for the fault-tolerance package: step-progress hang
detection and the fault-injection grammar (SURVEY §5.3)."""

import time

import pytest

from dlrover_tpu.fault_tolerance.hanging_detector import HangingDetector
from dlrover_tpu.fault_tolerance.injection import (
    FaultInjector,
    parse_spec,
)


class TestHangingDetector:
    def test_not_armed_before_first_step(self):
        det = HangingDetector(min_timeout=0.01)
        time.sleep(0.05)
        assert not det.is_hanged()  # compile phase never trips it

    def test_detects_stall_and_reports_once(self):
        reports = []
        det = HangingDetector(
            report_fn=reports.append, min_timeout=0.05,
            check_interval=0.01,
        )
        det.start()
        for s in range(5):
            det.record_step(s)
            time.sleep(0.005)
        time.sleep(0.3)  # stall >> threshold
        det.stop()
        assert det.is_hanged()
        assert len(reports) == 1  # latched: one report per stall
        assert reports[0] > 0.05

    def test_no_false_positive_while_stepping(self):
        reports = []
        det = HangingDetector(
            report_fn=reports.append, min_timeout=0.2,
            check_interval=0.01,
        )
        det.start()
        for s in range(20):
            det.record_step(s)
            time.sleep(0.01)
        det.stop()
        assert not reports

    def test_adaptive_threshold_tracks_step_time(self):
        det = HangingDetector(min_timeout=0.01, multiplier=10.0)
        det.record_step(0)
        det._durations.extend([2.0, 2.0, 2.0])
        assert det.timeout() == pytest.approx(20.0)

    def test_rearms_after_progress_resumes(self):
        reports = []
        det = HangingDetector(
            report_fn=reports.append, min_timeout=0.04,
            check_interval=0.01,
        )
        det.start()
        for s in range(5):  # establish a fast cadence
            det.record_step(s)
            time.sleep(0.003)
        time.sleep(0.15)  # first stall
        det.record_step(5)  # progress resumes (stall gap is rejected
        time.sleep(0.15)  # from the cadence history); second stall
        det.stop()
        assert len(reports) == 2


class TestFaultInjectionSpec:
    def test_parse_grammar(self):
        faults = parse_spec("crash@15:3, hang@8:120, oom@5, error@2:boom")
        kinds = [(f.kind, f.step, f.arg) for f in faults]
        assert kinds == [
            ("crash", 15, "3"), ("hang", 8, "120"),
            ("oom", 5, ""), ("error", 2, "boom"),
        ]

    def test_parse_now_and_every_incarnation(self):
        (f,) = parse_spec("hang@now:30!")
        assert f.step == -1 and f.every_incarnation

    def test_parse_rejects_unknown_kind(self):
        with pytest.raises(ValueError):
            parse_spec("explode@3")

    def test_restart_count_gates_env_faults(self):
        inj = FaultInjector("error@1:boom", restart_count=1)
        inj.maybe_inject(5)  # gated out: second incarnation runs clean
        inj2 = FaultInjector("error@1:boom!", restart_count=1)
        with pytest.raises(RuntimeError, match="boom"):
            inj2.maybe_inject(5)

    def test_error_fires_at_step(self):
        inj = FaultInjector("error@3:kaput")
        inj.maybe_inject(1)
        inj.maybe_inject(2)
        with pytest.raises(RuntimeError, match="kaput"):
            inj.maybe_inject(3)
        inj.maybe_inject(4)  # fired once, never again

    def test_oom_raises_memory_error(self):
        inj = FaultInjector("oom@1")
        with pytest.raises(MemoryError):
            inj.maybe_inject(1)

    def test_hang_with_duration_sleeps(self):
        inj = FaultInjector("hang@1:0.1")
        t0 = time.monotonic()
        inj.maybe_inject(1)
        assert time.monotonic() - t0 >= 0.1

    def test_parse_node_lost_and_join(self):
        faults = parse_spec("node_lost@8:host=2, node_join@12")
        kinds = [(f.kind, f.step, f.arg) for f in faults]
        assert kinds == [
            ("node_lost", 8, "host=2"), ("node_join", 12, ""),
        ]

    def test_node_lost_host_scoping(self):
        # host=H keeps the kill on exactly one rank of a shared spec
        inj_hit = FaultInjector("node_lost@8:host=2", node_rank=2)
        inj_miss = FaultInjector("node_lost@8:host=2", node_rank=1)
        assert [f.kind for f in inj_hit._faults] == ["node_lost"]
        assert inj_miss._faults == []

    def test_node_join_is_a_marker(self, capsys):
        # no signal, no exception: the drill harness launches the
        # joiner on this line
        inj = FaultInjector("node_join@3")
        inj.maybe_inject(3)
        assert "INJECTED NODE JOIN at step 3" in capsys.readouterr().out
        inj.maybe_inject(4)  # fired once, never again
        assert "NODE JOIN" not in capsys.readouterr().out

    def test_remote_kv_injection_consumed(self):
        class FakeClient:
            def __init__(self):
                self.kv = {"fault_inject/0": b"error@now:remote"}

            def kv_store_get(self, key):
                return self.kv.get(key, b"")

            def kv_store_set(self, key, value):
                self.kv[key] = value

        client = FakeClient()
        inj = FaultInjector(master_client=client, poll_every=1)
        with pytest.raises(RuntimeError, match="remote"):
            inj.maybe_inject(10)
        assert client.kv["fault_inject/0"] == b""  # consumed
        inj.maybe_inject(11)  # no re-fire


class TestMasterHangFlow:
    def test_hang_report_becomes_restart_action(self):
        """report_failure(level=hang) -> pending restart action delivered
        on the node's next heartbeat, exactly once."""
        from dlrover_tpu.common.constants import NodeAction, NodeType
        from dlrover_tpu.master.node.local_job_manager import (
            LocalJobManager,
        )

        mgr = LocalJobManager()
        mgr.start()
        mgr.handle_training_hang(NodeType.WORKER, 0, "no progress")
        node = mgr.get_node(NodeType.WORKER, 0)
        assert node.hang
        action = mgr.collect_node_heartbeat(NodeType.WORKER, 0, 1.0)
        assert action == NodeAction.RESTART_WORKER
        assert not node.hang
        assert mgr.collect_node_heartbeat(NodeType.WORKER, 0, 2.0) == ""

    def test_dist_manager_hang_flow(self):
        from dlrover_tpu.common.constants import (
            NodeAction,
            NodeStatus,
            NodeType,
        )
        from dlrover_tpu.master.node.dist_job_manager import (
            DistributedJobManager,
        )

        mgr = DistributedJobManager()
        mgr.update_node_status(NodeType.WORKER, 0, NodeStatus.RUNNING)
        mgr.handle_training_hang(NodeType.WORKER, 0, "stalled")
        action = mgr.collect_node_heartbeat(NodeType.WORKER, 0, 1.0)
        assert action == NodeAction.RESTART_WORKER
        # node is still RUNNING: recycled, not failed
        assert (
            mgr.get_node(NodeType.WORKER, 0).status == NodeStatus.RUNNING
        )
        assert mgr.collect_node_heartbeat(NodeType.WORKER, 0, 2.0) is None
