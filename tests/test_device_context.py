"""Device context tests (AT8 parity: auto/device_context.py)."""

import jax

from dlrover_tpu.auto.device_context import (
    DeviceContext,
    build_device_context,
    hbm_bytes_per_chip,
    peak_flops_per_chip,
)


class FakeDev:
    def __init__(self, kind, platform="tpu", process_index=0):
        self.device_kind = kind
        self.platform = platform
        self.process_index = process_index


def test_chip_tables():
    assert peak_flops_per_chip(FakeDev("TPU v5 lite")) == 197.0e12
    assert hbm_bytes_per_chip(FakeDev("TPU v5 lite")) == 16e9
    assert peak_flops_per_chip(FakeDev("TPU v5p")) == 459.0e12
    assert hbm_bytes_per_chip(FakeDev("TPU v4")) == 32e9
    # unknown chips fall back to the v5p class
    assert peak_flops_per_chip(FakeDev("TPU v9 mega")) == 459.0e12


def test_build_context_counts_hosts():
    devs = [FakeDev("TPU v5e", process_index=i // 4) for i in range(8)]
    ctx = build_device_context(devs)
    assert ctx.num_devices == 8
    assert ctx.num_hosts == 2
    assert ctx.devices_per_host == 4
    assert ctx.total_hbm_bytes == 8 * 16e9
    assert ctx.host_cpu_count >= 1
    assert ctx.host_memory_mb > 0


def test_build_context_real_devices():
    ctx = build_device_context(jax.devices())
    assert isinstance(ctx, DeviceContext)
    assert ctx.num_devices == len(jax.devices())
