"""Persistent compilation cache helper (trainer/compile_cache.py)."""

import os

import pytest

from dlrover_tpu.trainer import compile_cache


_REAL_SAFE_GATE = compile_cache._persistent_cache_safe


@pytest.fixture(autouse=True)
def _cache_load_safe(monkeypatch):
    """Dir/permission logic under test is version-independent; pin the
    executable-reload safety gate open so these tests run the same on
    every jax (the gate itself is covered below)."""
    monkeypatch.setattr(
        compile_cache, "_persistent_cache_safe", lambda: True
    )


def test_disabled_values(monkeypatch):
    for v in ("off", "none", "0"):
        assert compile_cache.setup_compilation_cache(v) is None


def test_unsafe_jax_build_refuses_cache(tmp_path, monkeypatch):
    """A jax build that segfaults reloading serialized executables
    must not get the cache armed (restarted workers would crash-loop);
    the force env re-arms it."""
    monkeypatch.setattr(
        compile_cache, "_persistent_cache_safe", _REAL_SAFE_GATE
    )
    import jax

    monkeypatch.setattr(jax, "__version__", "0.4.37")
    d = str(tmp_path / "unsafe")
    assert compile_cache.setup_compilation_cache(d) is None
    monkeypatch.setenv(compile_cache.ENV_FORCE, "1")
    assert compile_cache.setup_compilation_cache(d) == d


def test_env_resolution_and_perms(tmp_path, monkeypatch):
    d = str(tmp_path / "cc")
    monkeypatch.setenv(compile_cache.ENV_CACHE_DIR, d)
    got = compile_cache.setup_compilation_cache()
    assert got == d and os.path.isdir(d)
    # executables-only dir: private to this uid
    assert (os.stat(d).st_mode & 0o777) == 0o700
    import jax

    assert jax.config.jax_compilation_cache_dir == d


def test_foreign_owned_dir_refused(tmp_path, monkeypatch):
    """Cache entries are deserialized executables: a pre-created dir
    owned by another uid must be refused, not adopted."""
    d = str(tmp_path / "trap")
    os.makedirs(d)
    real_stat = os.stat

    class FakeStat:
        def __init__(self, st):
            self.st_uid = st.st_uid + 1  # someone else
            self.st_mode = st.st_mode

    monkeypatch.setattr(
        os, "stat",
        lambda p, *a, **k: FakeStat(real_stat(p, *a, **k))
        if p == d else real_stat(p, *a, **k),
    )
    assert compile_cache.setup_compilation_cache(d) is None


def test_adopted_loose_dir_tightened_to_0700(tmp_path):
    """makedirs(mode=0o700) only applies on creation: a pre-existing
    same-uid dir with group/world access must be re-tightened before
    executables are loaded from it (the documented 0700 contract)."""
    d = str(tmp_path / "loose")
    os.makedirs(d, mode=0o755)
    os.chmod(d, 0o755)  # defeat umask
    assert compile_cache.setup_compilation_cache(d) == d
    assert (os.stat(d).st_mode & 0o777) == 0o700


def test_default_dir_is_per_uid():
    assert str(os.getuid()) in compile_cache.default_cache_dir()


def test_cache_entries_counts(tmp_path):
    d = str(tmp_path)
    assert compile_cache.cache_entries(d) == 0
    (tmp_path / "jit_f-abc-cache").mkdir()
    (tmp_path / ".hidden").write_text("x")
    assert compile_cache.cache_entries(d) == 1
    assert compile_cache.cache_entries(d + "/missing") == 0
