"""Persistent compilation cache helper (trainer/compile_cache.py)."""

import os

import pytest

from dlrover_tpu.trainer import compile_cache


def test_disabled_values(monkeypatch):
    for v in ("off", "none", "0"):
        assert compile_cache.setup_compilation_cache(v) is None


def test_env_resolution_and_perms(tmp_path, monkeypatch):
    d = str(tmp_path / "cc")
    monkeypatch.setenv(compile_cache.ENV_CACHE_DIR, d)
    got = compile_cache.setup_compilation_cache()
    assert got == d and os.path.isdir(d)
    # executables-only dir: private to this uid
    assert (os.stat(d).st_mode & 0o777) == 0o700
    import jax

    assert jax.config.jax_compilation_cache_dir == d


def test_foreign_owned_dir_refused(tmp_path, monkeypatch):
    """Cache entries are deserialized executables: a pre-created dir
    owned by another uid must be refused, not adopted."""
    d = str(tmp_path / "trap")
    os.makedirs(d)
    real_stat = os.stat

    class FakeStat:
        def __init__(self, st):
            self.st_uid = st.st_uid + 1  # someone else
            self.st_mode = st.st_mode

    monkeypatch.setattr(
        os, "stat",
        lambda p, *a, **k: FakeStat(real_stat(p, *a, **k))
        if p == d else real_stat(p, *a, **k),
    )
    assert compile_cache.setup_compilation_cache(d) is None


def test_default_dir_is_per_uid():
    assert str(os.getuid()) in compile_cache.default_cache_dir()


def test_cache_entries_counts(tmp_path):
    d = str(tmp_path)
    assert compile_cache.cache_entries(d) == 0
    (tmp_path / "jit_f-abc-cache").mkdir()
    (tmp_path / ".hidden").write_text("x")
    assert compile_cache.cache_entries(d) == 1
    assert compile_cache.cache_entries(d + "/missing") == 0
