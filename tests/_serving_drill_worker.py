"""Drill replica for the serving chaos test (not a test module).

One elastic serving replica speaking the real protocol against a live
master: it registers through the training rendezvous (the same
membership path a trainer uses), loads weights from the shared
flash-checkpoint RAM tier (the first replica warms it from
``init_state_fn``; later replicas restore the artifact), then runs
:class:`dlrover_tpu.serving.worker.ServingWorker` — continuous-batching
leases with a one-deep lookahead, exactly-once completions, SIGTERM
rotation exiting rc 21.

Fault surface: the real FaultInjector with ``role="serving"``
(``DLROVER_FAULT_INJECT=serve_kill@N`` in the env) SIGKILLs this
process after N responses served — mid-stream, with leased requests
outstanding, driving the router's lease-timeout redelivery.
"""

import argparse
import sys
import time


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--master_addr", required=True)
    p.add_argument("--node_id", type=int, required=True)
    p.add_argument("--out", required=True)
    p.add_argument("--ckpt_dir", required=True,
                   help="shared flash-checkpoint tree (persist tier)")
    p.add_argument("--ram_dir", required=True,
                   help="shared RAM-tier dir (tmpfs in production)")
    p.add_argument("--batch_size", type=int, default=4)
    p.add_argument("--model_ms", type=float, default=30.0)
    args = p.parse_args()

    # envelope `proc` = node id BEFORE any journal write, so the drill's
    # journal asserts can attribute events per replica
    from dlrover_tpu.common.log import set_process_index

    set_process_index(args.node_id)

    from dlrover_tpu.agent.master_client import MasterClient
    from dlrover_tpu.common.constants import RendezvousName
    from dlrover_tpu.fault_tolerance.injection import FaultInjector
    from dlrover_tpu.serving.worker import ServingWorker
    from dlrover_tpu.telemetry import goodput
    from dlrover_tpu.trainer.checkpoint import FlashCheckpointer

    # live ledger: the tap turns serve.worker_ready into the `serving`
    # phase, and the final report books this incarnation's time on the
    # master's job account instead of `idle`
    goodput.install()

    out = open(args.out, "a", buffering=1)

    def emit(line: str):
        out.write(line + "\n")
        print(f"[replica {args.node_id}] {line}", flush=True)

    client = MasterClient(
        args.master_addr, node_id=args.node_id, node_type="worker",
    )

    # ordinary elastic-node registration: serving replicas join the
    # same rendezvous trainers use (scale plans see one worker pool)
    client.report_rdzv_params(
        min_nodes=1, max_nodes=8, waiting_timeout=0.5, node_unit=1,
    )
    client.join_rendezvous(args.node_id, 1)
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        _, _, world = client.get_comm_world(
            RendezvousName.TRAINING, args.node_id
        )
        if world and args.node_id in world:
            emit("REGISTERED")
            break
        time.sleep(0.2)

    ckpt = FlashCheckpointer(
        persist_dir=args.ckpt_dir, ram_dir=args.ram_dir,
        use_orbax=False,
    )

    def init_state(_shape=64):
        # the "trained artifact": a deterministic weight vector every
        # replica must agree on (responses embed its checksum)
        import numpy as np

        return {"w": np.arange(_shape, dtype=np.float32)}

    def model_fn(payloads, state):
        if args.model_ms > 0:
            time.sleep(args.model_ms / 1000.0)
        tag = b"#%d" % int(state["w"].sum())
        return [p.upper() + tag for p in payloads]

    injector = FaultInjector.from_env(role="serving")
    worker = ServingWorker(
        client, model_fn, node_id=args.node_id,
        checkpointer=ckpt, init_state_fn=init_state,
        batch_size=args.batch_size, poll_interval=0.02,
        injector=injector, status_interval=1.0,
    )
    served = worker.serve()  # rotation exits inside with rc 21
    emit(f"SERVED {served}")
    emit("DONE")
    client.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
