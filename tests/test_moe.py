"""MoE / expert-parallelism tests on the 8-device CPU mesh.

Parity coverage for the reference's MOELayer + gating tests
(atorch/atorch/modules/moe/)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from dlrover_tpu.models import llama
from dlrover_tpu.parallel import sharding as shd
from dlrover_tpu.parallel.mesh import create_mesh
from dlrover_tpu.parallel.moe import moe_mlp, topk_gating
from dlrover_tpu.trainer.sharded import make_trainer_for_llama


def test_topk_gating_routes_within_capacity():
    logits = jax.random.normal(jax.random.key(0), (32, 4))
    dispatch, combine, aux = topk_gating(logits, k=2, capacity=16)
    assert dispatch.shape == (32, 4, 16)
    # each token dispatched to at most k experts
    per_token = np.asarray(jnp.sum(dispatch, axis=(1, 2)))
    assert (per_token <= 2 + 1e-6).all()
    # each (expert, slot) holds at most one token
    per_slot = np.asarray(jnp.sum(dispatch, axis=0))
    assert (per_slot <= 1 + 1e-6).all()
    # combine weights normalized per token (where any expert selected)
    w = np.asarray(jnp.sum(combine, axis=(1, 2)))
    assert np.allclose(w[per_token > 0], 1.0, atol=1e-5)
    assert float(aux) > 0


def test_capacity_drops_overflow_tokens():
    # all tokens prefer expert 0; tiny capacity forces drops
    logits = jnp.tile(jnp.array([[10.0, 0.0, 0.0, 0.0]]), (32, 1))
    dispatch, _, _ = topk_gating(logits, k=1, capacity=4)
    assert float(jnp.sum(dispatch[:, 0])) == 4.0  # only 4 slots used


def test_moe_mlp_shapes_and_grads():
    h, m, e = 16, 32, 4
    ks = jax.random.split(jax.random.key(1), 5)
    x = jax.random.normal(ks[0], (2, 8, h))
    gate_w = jax.random.normal(ks[1], (h, e)) * 0.1
    w_gate = jax.random.normal(ks[2], (e, h, m)) * 0.1
    w_up = jax.random.normal(ks[3], (e, h, m)) * 0.1
    w_down = jax.random.normal(ks[4], (e, m, h)) * 0.1
    out, aux = moe_mlp(x, gate_w, w_gate, w_up, w_down, k=2)
    assert out.shape == x.shape
    assert np.isfinite(float(aux))
    g = jax.grad(
        lambda ws: jnp.sum(
            moe_mlp(x, gate_w, ws[0], ws[1], ws[2], k=2)[0] ** 2
        )
    )((w_gate, w_up, w_down))
    # every expert that received tokens gets gradient signal
    assert float(jnp.sum(jnp.abs(g[0]))) > 0


def test_moe_llama_trains_with_expert_parallelism():
    cfg = llama.llama_moe_tiny()
    mesh = create_mesh([("data", 2), ("expert", 4)])
    trainer = make_trainer_for_llama(
        cfg, mesh, strategy="tp_fsdp", optimizer=optax.adam(1e-2),
    )
    params, opt_state = trainer.init(jax.random.key(0))
    # expert weights actually sharded over the expert axis
    wg = params["blocks"]["w_gate"]
    assert wg.sharding.spec == P(None, "expert")
    assert wg.sharding.shard_shape(wg.shape)[1] == cfg.num_experts // 4

    tokens = np.asarray(
        jax.random.randint(jax.random.key(1), (8, 16), 0, cfg.vocab_size)
    )
    batch = trainer.shard_batch(trainer.microbatch((tokens, tokens)))
    losses = []
    for _ in range(8):
        params, opt_state, loss = trainer.train_step(
            params, opt_state, batch
        )
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.9, losses


def test_top1_router_gets_lm_gradient():
    """Switch (k=1) routing must keep the RAW gate weight so the router
    trains through the LM loss (renormalizing pins weights to 1.0)."""
    h, m, e = 16, 32, 4
    ks = jax.random.split(jax.random.key(7), 5)
    x = jax.random.normal(ks[0], (2, 8, h))
    gate_w = jax.random.normal(ks[1], (h, e)) * 0.1
    w_gate = jax.random.normal(ks[2], (e, h, m)) * 0.1
    w_up = jax.random.normal(ks[3], (e, h, m)) * 0.1
    w_down = jax.random.normal(ks[4], (e, m, h)) * 0.1

    def out_only_loss(gw):
        out, _ = moe_mlp(x, gw, w_gate, w_up, w_down, k=1)
        return jnp.sum(out ** 2)

    g = jax.grad(out_only_loss)(gate_w)
    assert float(jnp.linalg.norm(g)) > 1e-5


def test_moe_dense_parity_param_count():
    """param_count accounting matches the real pytree for MoE configs."""
    for cfg in (llama.llama_tiny(), llama.llama_moe_tiny()):
        params = llama.init_params(jax.random.key(0), cfg)
        real = sum(
            x.size for x in jax.tree.leaves(params)
        )
        assert real == llama.param_count(cfg), cfg.num_experts
