"""Streaming dataset e2e through the launcher (VERDICT r4 item #8):
a live run consuming a streaming source (partition-offset shards from
StreamingDatasetSplitter), with a mid-run crash that orphans an
IN-FLIGHT shard — the restarted worker must resume at the right
offset: the orphaned range is re-delivered exactly once and the whole
stream is covered with no gaps or duplicates.

Parity: dlrover/python/master/shard/dataset_splitter.py:359 +
streaming_dataset_manager.py:32 + the reference's task-timeout
reassignment (task_manager.py:205).
"""

import json
import os
import re
import subprocess
import sys
import tempfile

TOTAL = 2000
BATCH = 100


def _run(tmp, crash_after=0, timeout=300):
    progress = os.path.join(tmp, "progress.txt")
    cmd = [
        sys.executable, "-m", "dlrover_tpu.trainer.elastic_run",
        "--standalone", "--nnodes", "1:1",
        "--max_restarts", "2", "--monitor_interval", "0.3",
        os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "examples", "stream_train.py",
        ), "--",
        "--total", str(TOTAL), "--batch-size", str(BATCH),
        "--progress", progress,
    ] + (["--crash-after", str(crash_after)] if crash_after else [])
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # the orphaned in-flight shard is recovered by the master's task
    # timeout watchdog; the default 1800s would stall the drill
    env["DLROVER_TPU_CTX_TASK_PROCESS_TIMEOUT"] = "5"
    proc = subprocess.run(
        cmd, env=env, timeout=timeout, capture_output=True, text=True,
    )
    return proc, progress


def _rows(progress):
    rows = []
    if os.path.exists(progress):
        for line in open(progress):
            parts = line.strip().split(",")
            if len(parts) == 5:
                rows.append((parts[0], int(parts[1]), int(parts[2]),
                             int(parts[3])))
    return rows


def _assert_exactly_once(rows):
    ranges = sorted((r[1], r[2]) for r in rows)
    prev_end = 0
    for start, end in ranges:
        assert start == prev_end, (
            f"gap/overlap at {start} (prev end {prev_end})"
        )
        prev_end = end
    assert prev_end == TOTAL, (prev_end, TOTAL)


def test_streaming_source_completes():
    with tempfile.TemporaryDirectory() as tmp:
        proc, progress = _run(tmp)
        assert proc.returncode == 0, proc.stderr[-3000:]
        _assert_exactly_once(_rows(progress))


def test_streaming_crash_resumes_at_right_offset():
    with tempfile.TemporaryDirectory() as tmp:
        proc, progress = _run(tmp, crash_after=5)
        out = proc.stdout + proc.stderr
        assert proc.returncode == 0, out[-3000:]

        # the crash really happened with a shard in flight
        m = re.search(r"CRASH holding (\S+):(\d+)-(\d+)", out)
        assert m, out[-3000:]
        orphan = (int(m.group(2)), int(m.group(3)))

        # the master's shard checkpoint (snapshotted by the dying
        # worker over the RPC) tracked that range as doing/todo
        ck = re.search(r"SHARD_CKPT (\{.*\})", out)
        assert ck, out[-3000:]
        doc = json.loads(ck.group(1))
        tracked = [tuple(x) for x in doc.get("doing", [])] + [
            tuple(x) for x in doc.get("todo", [])
        ]
        assert list(orphan) in [list(t) for t in tracked], (
            orphan, tracked,
        )

        rows = _rows(progress)
        # the restarted incarnation completed the orphaned range —
        # exactly once, at the right offset
        redelivered = [
            r for r in rows
            if (r[1], r[2]) == orphan and r[3] >= 1
        ]
        assert len(redelivered) == 1, (orphan, rows[-8:])
        assert not [
            r for r in rows if (r[1], r[2]) == orphan and r[3] == 0
        ]
        _assert_exactly_once(rows)
