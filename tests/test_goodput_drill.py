"""Goodput chaos drill: time attribution across a worker crash AND a
master kill.

A real master serves two protocol-speaking workers
(``_goodput_drill_worker.py``), each with a live goodput ledger armed.
``DLROVER_FAULT_INJECT=crash@4`` kills worker 0 mid-epoch (rc 17, the
ledger dies open); the test relaunches the same node id.
``DLROVER_FAULT_INJECT=master_crash@8`` then kills the master (rc 28);
a second master restores the goodput aggregator from the state journal
(its own downtime becomes a recovered ``master_restart`` fault) and
the job finishes clean.

Asserted: the live ``/goodput`` endpoint on master #2 serves the
restored job account; ≥95% of every process's wall-clock is
attributed (non-idle); per-process phase durations sum to elapsed time
(±1%); both injected faults land inside recovered restart windows and
the worker-crash gap is charged as ``restart`` badput; and ``python -m
dlrover_tpu.telemetry.dump --goodput`` reproduces the live totals the
master journaled at shutdown (``goodput.job_summary``).
"""

import json
import os
import signal
import subprocess
import sys
import time
import urllib.request

import pytest

from dlrover_tpu.fault_tolerance.injection import MASTER_CRASH_EXIT_CODE
from dlrover_tpu.telemetry import goodput
from dlrover_tpu.telemetry.goodput import Phase
from dlrover_tpu.telemetry.journal import read_journal

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER_CRASH_RC = 17
DATASET_SIZE = 192
BATCH_SIZE = 4
SHARD_SECS = 0.2


def _drill_env(journal_path):
    env = dict(os.environ)
    parts = [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
             if p and "axon" not in p]
    env["PYTHONPATH"] = os.pathsep.join(parts + [REPO])
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("DLROVER_FAULT_INJECT", None)
    env.pop("DLROVER_TPU_METRICS_PORT", None)
    env.pop("DLROVER_TPU_RESTART_COUNT", None)
    env["DLROVER_TPU_JOURNAL"] = journal_path
    env["DLROVER_TPU_LOG_LEVEL"] = "INFO"
    return env


def _spawn_master(tmp, env, state_dir, port, tag):
    cmd = [
        sys.executable, "-m", "dlrover_tpu.master.main",
        "--platform", "process", "--node_num", "0",
        "--job_name", "goodput-drill", "--port", str(port),
        "--state_dir", state_dir,
        "--autoscale_interval", "600", "--check_interval", "0.2",
    ]
    return subprocess.Popen(
        cmd, cwd=REPO, env=env,
        stdout=open(os.path.join(tmp, f"master-{tag}.out"), "w"),
        stderr=open(os.path.join(tmp, f"master-{tag}.err"), "w"),
        start_new_session=True,
    )


def _spawn_worker(tmp, env, port, node_id, tag):
    return subprocess.Popen(
        [sys.executable,
         os.path.join(REPO, "tests", "_goodput_drill_worker.py"),
         "--master_addr", f"localhost:{port}",
         "--node_id", str(node_id),
         "--out", os.path.join(tmp, f"worker-{tag}.txt"),
         "--dataset_size", str(DATASET_SIZE),
         "--batch_size", str(BATCH_SIZE),
         "--shard_secs", str(SHARD_SECS)],
        cwd=REPO, env=env,
        stdout=open(os.path.join(tmp, f"worker-{tag}.out"), "w"),
        stderr=subprocess.STDOUT,
        start_new_session=True,
    )


def _master_port(tmp, tag, proc, timeout=30):
    path = os.path.join(tmp, f"master-{tag}.out")
    deadline = time.time() + timeout
    while time.time() < deadline:
        if os.path.exists(path):
            for line in open(path):
                if line.startswith("DLROVER_TPU_MASTER_PORT="):
                    return int(line.strip().split("=", 1)[1])
        assert proc.poll() is None, _tail(tmp, f"master-{tag}.err")
        time.sleep(0.2)
    raise AssertionError(
        f"master-{tag} never printed its port; "
        + _tail(tmp, f"master-{tag}.err")
    )


def _tail(tmp, name, n=3000):
    path = os.path.join(tmp, name)
    try:
        return f"{name}: " + open(path).read()[-n:]
    except OSError:
        return f"{name}: <missing>"


def _wait(proc, timeout, what, tmp, logs):
    try:
        return proc.wait(timeout=timeout)
    except subprocess.TimeoutExpired:
        raise AssertionError(
            f"{what} did not exit in {timeout}s; "
            + " | ".join(_tail(tmp, l) for l in logs)
        )


def _killpg(proc, sig=signal.SIGKILL):
    try:
        os.killpg(os.getpgid(proc.pid), sig)
    except (ProcessLookupError, PermissionError):
        pass


def _free_port():
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _poll_goodput(port, timeout=30):
    """GET /goodput on a live master until it serves a job account."""
    deadline = time.time() + timeout
    last_err = None
    while time.time() < deadline:
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/goodput", timeout=2
            ) as resp:
                payload = json.loads(resp.read().decode())
            if (payload.get("job") or {}).get("procs", 0) >= 1:
                return payload
        except Exception as e:
            last_err = e
        time.sleep(0.2)
    raise AssertionError(f"/goodput never served a job account: {last_err}")


def test_goodput_chaos_drill(tmp_path):
    tmp = str(tmp_path)
    state_dir = os.path.join(tmp, "state")
    journal_path = os.path.join(tmp, "journal.jsonl")
    env = _drill_env(journal_path)
    master_env = dict(env, DLROVER_TPU_CTX_TASK_PROCESS_TIMEOUT="20")
    worker_env = dict(env, DLROVER_TPU_MASTER_RECONNECT_TIMEOUT="90")
    metrics_port = _free_port()

    procs = []
    try:
        # master #1 dies once the reported global step reaches 8
        m1 = _spawn_master(
            tmp, dict(master_env, DLROVER_FAULT_INJECT="master_crash@8"),
            state_dir, 0, "1",
        )
        procs.append(m1)
        port = _master_port(tmp, "1", m1)

        # worker 0 crashes at its own step 4 (first incarnation only)
        w0a = _spawn_worker(
            tmp, dict(worker_env, DLROVER_FAULT_INJECT="crash@4",
                      DLROVER_TPU_NODE_RANK="0"),
            port, 0, "0-a",
        )
        w1 = _spawn_worker(tmp, worker_env, port, 1, "1")
        procs += [w0a, w1]

        rc = _wait(w0a, 120, "worker 0 (crash expected)", tmp,
                   ["worker-0-a.out", "master-1.err"])
        assert rc == WORKER_CRASH_RC, (
            f"worker 0 exited rc={rc}, wanted injected crash "
            f"rc={WORKER_CRASH_RC}; " + _tail(tmp, "worker-0-a.out")
        )

        # relaunch the SAME node id: RESTART_COUNT=1 gates the env
        # injection off, exercising first-incarnation-only semantics
        w0b = _spawn_worker(
            tmp, dict(worker_env, DLROVER_FAULT_INJECT="crash@4",
                      DLROVER_TPU_NODE_RANK="0",
                      DLROVER_TPU_RESTART_COUNT="1"),
            port, 0, "0-b",
        )
        procs.append(w0b)

        rc1 = _wait(m1, 120, "master #1 (crash expected)", tmp,
                    ["master-1.err", "worker-1.out"])
        assert rc1 == MASTER_CRASH_EXIT_CODE, (
            f"master #1 exited rc={rc1}, wanted injected crash "
            f"rc={MASTER_CRASH_EXIT_CODE}; " + _tail(tmp, "master-1.err")
        )

        # master #2: same state dir + port, metrics server pinned so the
        # test can read the live /goodput account it restored
        m2 = _spawn_master(
            tmp,
            dict(master_env, DLROVER_TPU_METRICS_PORT=str(metrics_port)),
            state_dir, port, "2",
        )
        procs.append(m2)
        _master_port(tmp, "2", m2)

        # ---- live /goodput: the restored account is served while the
        # job is still running — procs observed by master #1 are there,
        # and master #1's downtime is a recovered master_restart fault
        live = _poll_goodput(metrics_port)
        assert live["job"]["procs"] >= 2, live["job"]
        assert any(
            f["cause"] == "master_restart" and f.get("recovered_ts")
            for f in live["faults"]
        ), live["faults"]

        for tag, w in (("0-b", w0b), ("1", w1)):
            rc = _wait(w, 180, f"worker {tag}", tmp,
                       ["worker-0-b.out", "worker-1.out", "master-2.err"])
            assert rc == 0, (
                f"worker {tag} exited rc={rc}; "
                + _tail(tmp, f"worker-{tag}.out")
            )
        rc2 = _wait(m2, 60, "master #2", tmp, ["master-2.err"])
        assert rc2 == 0, _tail(tmp, "master-2.err")
    finally:
        for p in procs:
            _killpg(p, signal.SIGTERM)
        time.sleep(0.5)
        for p in procs:
            _killpg(p)

    # ---- the work still completed exactly once -----------------------
    ranges = []
    for tag in ("0-a", "0-b", "1"):
        lines = open(os.path.join(tmp, f"worker-{tag}.txt")).read()
        for line in lines.splitlines():
            parts = line.split()
            if parts and parts[0] == "SHARD":
                ranges.append((int(parts[1]), int(parts[2])))
    ranges.sort()
    assert ranges[0][0] == 0 and ranges[-1][1] == DATASET_SIZE, ranges
    for (_, end), (start, _) in zip(ranges, ranges[1:]):
        assert end == start, f"shard gap/overlap at {start}: {ranges}"

    # ---- offline reconstruction -------------------------------------
    events = read_journal(journal_path)
    kinds = [e.get("kind") for e in events]
    injected = [e for e in events if e.get("kind") == "fault.injected"]
    injected_causes = {e["data"]["fault"] for e in injected}
    assert {"crash", "master_crash"} <= injected_causes, injected
    assert "master.restored" in kinds
    # both surviving workers closed their ledgers; the crashed
    # incarnation died with its ledger open (no snapshot)
    assert kinds.count("goodput.snapshot") == 2, kinds

    report = goodput.reconstruct(events)
    job = report["job"]

    # two worker nodes; three process incarnations, all ledgered exactly
    assert job["nodes"] == 2, report["nodes"]
    assert job["procs"] == 3, report["procs"]
    assert all(p["exact"] for p in report["procs"].values())

    # >= 95% of wall-clock attributed to a named phase
    assert job["attributed_percent"] >= 95.0, job
    assert job["goodput_percent"] > 0.0, job
    assert job["training_s"] > 0.0, job

    # per-process phase durations sum to elapsed time (+/- 1%)
    for key, p in report["procs"].items():
        total = sum(p["phases"].values())
        tol = max(0.01 * p["elapsed_s"], 0.05)
        assert abs(total - p["elapsed_s"]) <= tol, (
            f"{key}: phases sum {total} != elapsed {p['elapsed_s']}"
        )

    # ---- restart badput brackets the injected faults -----------------
    t_worker_crash = next(
        e["ts"] for e in injected if e["data"]["fault"] == "crash"
    )
    t_master_crash = next(
        e["ts"] for e in injected if e["data"]["fault"] == "master_crash"
    )
    # the node-0 incarnation gap contains the worker-crash instant and
    # is charged as restart badput
    node0_procs = sorted(
        (p for p in report["procs"].values() if p["node_id"] == 0),
        key=lambda p: p["start_ts"],
    )
    assert len(node0_procs) == 2, report["procs"]
    died = node0_procs[0]["start_ts"] + node0_procs[0]["elapsed_s"]
    reborn = node0_procs[1]["start_ts"]
    assert died <= t_worker_crash + 0.5, (died, t_worker_crash)
    assert reborn >= t_worker_crash, (reborn, t_worker_crash)
    assert report["nodes"]["0"]["restart_gap_s"] > 0.0, report["nodes"]
    assert job["badput_s"][Phase.RESTART] > 0.0, job
    # both injected faults carry recovered restart windows opening at
    # the injection instant
    for cause, t in (("crash", t_worker_crash),
                     ("master_crash", t_master_crash)):
        win = next(f for f in report["faults"] if f["cause"] == cause)
        assert abs(win["ts"] - t) < 0.001, (win, t)
        assert win["recovered_ts"] and win["recovered_ts"] >= t, win
    assert job["mttr_s"] is not None and job["mttr_s"] > 0.0, job
    assert job["mtbf_s"] is not None and job["mtbf_s"] > 0.0, job

    # ---- dump --goodput reproduces the live totals -------------------
    # master #2 journaled its aggregator's final account at shutdown
    # (goodput.job_summary == what /goodput was serving); the offline
    # replay of the same journal must tell the same story
    summaries = [e for e in events if e.get("kind") == "goodput.job_summary"]
    assert len(summaries) == 1, summaries
    live_job = summaries[0]["data"]
    assert live_job["procs"] == 3, live_job
    assert live_job["attributed_percent"] >= 95.0, live_job

    out = subprocess.run(
        [sys.executable, "-m", "dlrover_tpu.telemetry.dump",
         "--goodput", "--json", journal_path],
        cwd=REPO, env=_drill_env(os.path.join(tmp, "unused.jsonl")),
        capture_output=True, text=True, timeout=120,
    )
    assert out.returncode == 0, out.stderr
    cli_job = json.loads(out.stdout)["job"]
    for field in ("training_s", "wall_s"):
        a, b = float(cli_job[field]), float(live_job[field])
        assert abs(a - b) <= max(1.0, 0.1 * max(a, b)), (
            f"{field}: offline {a} vs live {b}"
        )
    assert abs(cli_job["goodput_percent"]
               - live_job["goodput_percent"]) <= 10.0, (cli_job, live_job)
    assert cli_job["procs"] == live_job["procs"] == 3
