"""Batched shard dispatch + group-commit journaling (ISSUE 8).

Covers the full vertical: TaskBatch wire messages, TaskManager's
get_dataset_tasks with ONE journal write per batch, crash consistency
of the group commit (a master killed between handing out a batch and
the next commit restores a ledger that still exactly partitions the
dataset), the real-gRPC batch RPC, the client's single-fetch fallback
against a master that predates the RPC, the lookahead window, the
report_batch_done lock fix, DevicePrefetch error propagation and
fill-thread transform, chunked index delivery, the vectorized
sampler, and the shard_throughput --smoke benchmark.
"""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from dlrover_tpu.agent.master_client import LocalMasterClient, MasterClient
from dlrover_tpu.agent.sharding.client import (
    IndexShardingClient,
    ShardingClient,
)
from dlrover_tpu.common import comm
from dlrover_tpu.common.constants import NodeType, TaskType
from dlrover_tpu.master.local_master import LocalJobMaster
from dlrover_tpu.master.shard.dataset_splitter import new_dataset_splitter
from dlrover_tpu.master.shard.task_manager import TaskManager
from dlrover_tpu.master.state_journal import build_master_state_journal

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

PARAMS = dict(
    batch_size=4, num_epochs=1, dataset_size=48, shuffle=False,
    num_minibatches_per_shard=1, dataset_name="batch-ds",
    task_type=TaskType.TRAINING, storage_type="table",
)


def _new_task_manager(params, state_dir=None):
    journal = None
    if state_dir is not None:
        journal = build_master_state_journal(
            "dispatch-job", state_dir=state_dir
        )
    tm = TaskManager()
    if journal is not None:
        tm.attach_state_journal(journal)
    splitter = new_dataset_splitter(
        shuffle=params["shuffle"],
        shard_size=params["batch_size"]
        * params["num_minibatches_per_shard"],
        dataset_size=params["dataset_size"],
        num_epochs=params["num_epochs"],
        dataset_name=params["dataset_name"],
    )
    tm.new_dataset(
        batch_size=params["batch_size"],
        dataset_size=params["dataset_size"],
        dataset_name=params["dataset_name"],
        dataset_splitter=splitter,
        task_type=TaskType.TRAINING,
        params=params,
    )
    return journal, tm


# ------------------------------------------------------------------ wire


def test_task_batch_wire_roundtrip():
    batch = comm.TaskBatch(tasks=[
        comm.Task(task_id=7, task_type=TaskType.TRAINING,
                  shard=comm.Shard(name="ds", start=8, end=12)),
        comm.Task(task_id=8, task_type=TaskType.TRAINING,
                  shard=comm.Shard(name="ds", start=12, end=16,
                                   record_indices=[12, 15, 13, 14])),
    ])
    decoded = comm.deserialize(batch.serialize())
    assert isinstance(decoded, comm.TaskBatch)
    assert [t.task_id for t in decoded.tasks] == [7, 8]
    assert decoded.tasks[1].shard.record_indices == [12, 15, 13, 14]

    req = comm.deserialize(comm.TaskBatchRequest(
        node_id=3, node_type="worker", dataset_name="ds",
        incarnation=2, max_tasks=16,
    ).serialize())
    assert (req.max_tasks, req.incarnation, req.node_id) == (16, 2, 3)


# ---------------------------------------------------------- task manager


def test_get_dataset_tasks_pops_up_to_n():
    _, tm = _new_task_manager(PARAMS)
    got = tm.get_dataset_tasks(NodeType.WORKER, 0, "batch-ds",
                               max_tasks=5)
    assert len(got) == 5
    assert all(t.task_id >= 0 for t in got)
    # the single-task wrapper goes through the same path
    single = tm.get_dataset_task(NodeType.WORKER, 0, "batch-ds")
    assert single.task_id >= 0
    # unknown dataset: one invalid task, never an empty list
    bad = tm.get_dataset_tasks(NodeType.WORKER, 0, "nope", max_tasks=5)
    assert len(bad) == 1 and bad[0].task_id < 0


def test_wait_and_exhausted_returned_alone():
    _, tm = _new_task_manager(PARAMS)
    # node 0 grabs everything (12 shards) in one batch
    got = tm.get_dataset_tasks(NodeType.WORKER, 0, "batch-ds",
                               max_tasks=100)
    assert len(got) == 12
    # node 1 sees a single WAIT (peer's work in flight), not a batch
    waiting = tm.get_dataset_tasks(NodeType.WORKER, 1, "batch-ds",
                                   max_tasks=8)
    assert len(waiting) == 1
    assert waiting[0].task_type == TaskType.WAIT
    for t in got:
        assert tm.report_dataset_task("batch-ds", t.task_id, True)
    # all reported: exhausted is a single invalid task
    done = tm.get_dataset_tasks(NodeType.WORKER, 1, "batch-ds",
                                max_tasks=8)
    assert len(done) == 1
    assert done[0].task_id < 0
    assert done[0].task_type != TaskType.WAIT


def test_group_commit_writes_journal_once_per_batch(tmp_path):
    journal, tm = _new_task_manager(PARAMS, state_dir=str(tmp_path))
    saves = []
    orig = journal.save_dataset_checkpoint
    journal.save_dataset_checkpoint = (
        lambda *a, **kw: (saves.append(1), orig(*a, **kw))[1]
    )
    tm.get_dataset_tasks(NodeType.WORKER, 0, "batch-ds", max_tasks=8)
    assert len(saves) == 1  # 8 shards, ONE ledger mutate
    for _ in range(4):
        tm.get_dataset_task(NodeType.WORKER, 0, "batch-ds")
    assert len(saves) == 5  # per-task still commits per call


def test_group_commit_crash_restore_exact_partition(tmp_path):
    """Kill the master between handing out a batch and the next
    commit: the journaled ledger must still exactly partition the
    dataset — in-flight batch members stay deliverable under their
    original ids, nothing is lost or handed out twice."""
    state_dir = str(tmp_path)
    _, tm = _new_task_manager(PARAMS, state_dir=state_dir)

    batch1 = tm.get_dataset_tasks(NodeType.WORKER, 0, "batch-ds",
                                  max_tasks=4)
    batch2 = tm.get_dataset_tasks(NodeType.WORKER, 1, "batch-ds",
                                  max_tasks=3)
    # consume part of batch1 pre-crash; the completion is committed
    assert tm.report_dataset_task("batch-ds", batch1[0].task_id, True)
    consumed = [(batch1[0].shard.start, batch1[0].shard.end)]

    # "master crash": rebuild from the journal alone (no next commit
    # ever happened for the outstanding batch members)
    journal2 = build_master_state_journal(
        "dispatch-job", state_dir=state_dir
    )
    assert journal2.saved_datasets() == ["batch-ds"]
    params, ckpt = journal2.load_dataset("batch-ds")
    _, tm2 = _new_task_manager(params, state_dir=state_dir)
    assert tm2.restore_dataset_from_checkpoint(ckpt, keep_doing=True)

    # surviving workers report the rest of their batches under the
    # ORIGINAL ids — all accepted exactly once
    for t in batch1[1:] + batch2:
        assert tm2.report_dataset_task("batch-ds", t.task_id, True)
        consumed.append((t.shard.start, t.shard.end))
    # a double report is rejected
    assert not tm2.report_dataset_task(
        "batch-ds", batch2[0].task_id, True
    )

    # drain the remainder in batches; union must partition exactly
    while True:
        got = tm2.get_dataset_tasks(NodeType.WORKER, 0, "batch-ds",
                                    max_tasks=4)
        if got[0].task_id < 0:
            break
        for t in got:
            consumed.append((t.shard.start, t.shard.end))
            assert tm2.report_dataset_task("batch-ds", t.task_id, True)
    ranges = sorted(consumed)
    assert ranges[0][0] == 0
    assert ranges[-1][1] == PARAMS["dataset_size"]
    for (_, end), (start, _) in zip(ranges, ranges[1:]):
        assert end == start, f"gap/overlap in {ranges}"
    assert tm2.finished()


def test_clean_exit_without_relinquish_recovered_by_watchdog(tmp_path):
    """A worker that exits cleanly mid-shard WITHOUT relinquishing
    (drain not armed, or an older agent) must still be recovered: the
    task-timeout watchdog requeues its in-flight batch members after
    ``_task_timeout``, a peer drains them, and the dataset is consumed
    exactly once — no gap, no double-count. The proactive relinquish
    path (fault_tolerance/drain.py) is an optimization on top of this
    backstop, not a correctness requirement."""
    _, tm = _new_task_manager(PARAMS, state_dir=str(tmp_path))
    tm._task_timeout = 0.5

    batch = tm.get_dataset_tasks(NodeType.WORKER, 0, "batch-ds",
                                 max_tasks=4)
    assert len(batch) == 4
    # node 0 completes its first shard, then exits cleanly with three
    # batch members still in flight — and never calls relinquish
    assert tm.report_dataset_task("batch-ds", batch[0].task_id, True)
    consumed = [(batch[0].shard.start, batch[0].shard.end)]

    tm.start()
    try:
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            if not tm._datasets["batch-ds"].get_doing_tasks():
                break
            time.sleep(0.1)
        assert not tm._datasets["batch-ds"].get_doing_tasks(), (
            "watchdog never requeued the abandoned in-flight tasks"
        )
    finally:
        tm.stop()

    # a ghost report from the dead worker's id is rejected — the
    # requeued shard must not be counted twice
    assert not tm.report_dataset_task("batch-ds", batch[1].task_id, True)

    # the surviving peer drains everything, requeued shards included
    while True:
        got = tm.get_dataset_tasks(NodeType.WORKER, 1, "batch-ds",
                                   max_tasks=6)
        if got[0].task_id < 0:
            break
        for t in got:
            consumed.append((t.shard.start, t.shard.end))
            assert tm.report_dataset_task("batch-ds", t.task_id, True)
    ranges = sorted(consumed)
    assert ranges[0][0] == 0
    assert ranges[-1][1] == PARAMS["dataset_size"]
    for (_, end), (start, _) in zip(ranges, ranges[1:]):
        assert end == start, f"gap/overlap in {ranges}"
    assert tm.finished()


# ------------------------------------------------------------- real gRPC


@pytest.fixture
def master():
    m = LocalJobMaster(port=0)
    m.prepare()
    yield m
    m.stop()


def _grpc_client(master, node_id=0):
    return MasterClient(master.addr, node_id=node_id,
                        node_type="worker", reconnect_timeout=5.0)


def test_get_tasks_rpc_over_grpc(master):
    mc = _grpc_client(master)
    mc.report_dataset_shard_params(
        batch_size=4, num_epochs=1, dataset_size=32, shuffle=False,
        num_minibatches_per_shard=1, dataset_name="grpc-ds",
    )
    got = mc.get_tasks("grpc-ds", max_tasks=3)
    assert len(got) == 3
    assert all(isinstance(t, comm.Task) and t.task_id >= 0 for t in got)
    starts = sorted(t.shard.start for t in got)
    assert starts == [0, 4, 8]
    mc.close()


def test_sharding_client_batched_over_grpc(master):
    mc = _grpc_client(master)
    sc = ShardingClient(
        dataset_name="grpc-batch-ds", batch_size=4, dataset_size=40,
        num_minibatches_per_shard=1, master_client=mc, fetch_batch=4,
    )
    seen = []
    while True:
        shard = sc.fetch_shard(max_wait=30.0)
        if shard is None:
            break
        seen.append((shard.start, shard.end))
        assert sc.report_batch_done()
    assert sorted(seen) == [(i, i + 4) for i in range(0, 40, 4)]
    assert sc._batch_supported  # the new master accepted the RPC
    mc.close()


def test_old_master_triggers_single_fetch_fallback(master):
    # a master that predates get_tasks: the servicer has no handler,
    # so the generic server answers with an APPLICATION error
    master.servicer.rpc_get_tasks = None
    mc = _grpc_client(master)
    sc = ShardingClient(
        dataset_name="old-master-ds", batch_size=4, dataset_size=24,
        num_minibatches_per_shard=1, master_client=mc, fetch_batch=4,
    )
    seen = []
    while True:
        shard = sc.fetch_shard(max_wait=30.0)
        if shard is None:
            break
        seen.append((shard.start, shard.end))
        sc.report_batch_done()
    assert sorted(seen) == [(i, i + 4) for i in range(0, 24, 4)]
    assert not sc._batch_supported  # flipped to single-fetch for good
    mc.close()


# ------------------------------------------------------- sharding client


def test_lookahead_window_drains_exactly_once():
    mc = LocalMasterClient()
    sc = ShardingClient(
        dataset_name="look-ds", batch_size=4, dataset_size=48,
        num_minibatches_per_shard=1, master_client=mc,
        fetch_batch=3, lookahead=6,
    )
    seen = []
    while True:
        shard = sc.fetch_shard(max_wait=30.0)
        if shard is None:
            break
        seen.append((shard.start, shard.end))
        assert sc.report_batch_done()
    assert sorted(seen) == [(i, i + 4) for i in range(0, 48, 4)]
    sc.stop()


def test_resize_mid_shard_keeps_exactly_once():
    """Completion accounting straddles a reshard resize: batch geometry
    changes with the head shard in flight. Counted in records, the head
    task completes exactly when its records are consumed — a minibatch
    counter recomputed at the new size would report it done with the
    tail unconsumed (lost to exactly-once if the worker then dies)."""
    mc = LocalMasterClient()
    sc = ShardingClient(
        dataset_name="resize-ds", batch_size=8, dataset_size=32,
        num_minibatches_per_shard=2, master_client=mc,
    )
    shard = sc.fetch_shard(max_wait=5.0)
    assert shard.end - shard.start == 16
    assert not sc.report_batch_done()  # 8 of 16 records
    sc.resize(batch_size=4)  # mesh transition re-arms the geometry
    assert not sc.report_batch_done()  # 12 of 16
    # 16 of 16: done exactly here. Minibatch counting would see 3 of
    # ceil(16/4)=4 "minibatches" and hold the fully-consumed task — a
    # worker death now would requeue it and replay 16 records.
    assert sc.report_batch_done()
    shard2 = sc.fetch_shard(max_wait=5.0)
    assert shard2.end - shard2.start == 16
    for done in (False, False, False, True):  # clean slate: 4x4 records
        assert sc.report_batch_done() is done
    assert sc.fetch_shard(max_wait=5.0) is None
    sc.stop()


def test_resize_updates_reconnect_rehello_params():
    """The reconnect re-hello replays _dataset_params against a master
    that lost its journal: after a resize it must carry the NEW batch
    geometry, or the re-created dataset shards under the pre-resize
    size."""
    mc = LocalMasterClient()
    sc = ShardingClient(
        dataset_name="rehello-ds", batch_size=8, dataset_size=32,
        num_minibatches_per_shard=2, master_client=mc,
    )
    sc.resize(batch_size=4)
    assert sc._dataset_params["batch_size"] == 4
    sc.stop()


def test_resize_mid_chunk_index_stream_exactly_once():
    """IndexShardingClient with its consumer cursor mid-chunk across a
    resize: every index of the dataset is handed out exactly once and
    every shard completion is accepted by the master's ledger."""
    mc = LocalMasterClient()
    sc = IndexShardingClient(
        dataset_name="resize-idx-ds", batch_size=6, dataset_size=48,
        num_minibatches_per_shard=2, master_client=mc,
    )
    seen = []
    batch = sc.fetch_batch_indices(4)  # cursor now mid-chunk
    seen.extend(batch.tolist())
    assert sc.report_batch_done(batch_size=batch.size) in (True, False)
    sc.resize(batch_size=12)
    while True:
        batch = sc.fetch_batch_indices()
        if batch is None:
            break
        seen.extend(batch.tolist())
        sc.report_batch_done(batch_size=batch.size)
    assert sorted(seen) == list(range(48))
    assert not sc._pending_tasks  # every shard reported done
    sc.stop()


def test_lookahead_surfaces_fetch_errors():
    class _Exploding(LocalMasterClient):
        def get_tasks(self, *a, **kw):
            raise ConnectionError("master gone")

        def get_task(self, *a, **kw):
            raise ConnectionError("master gone")

    sc = ShardingClient(
        dataset_name="boom-ds", batch_size=4, dataset_size=16,
        num_minibatches_per_shard=1, master_client=_Exploding(),
        fetch_batch=2, lookahead=2,
    )
    with pytest.raises(ConnectionError):
        sc.fetch_shard(poll_interval=0.05, max_wait=10.0)
    sc.stop()


class _SlowReportClient(LocalMasterClient):
    """report_task_result blocks until released; records whether the
    ShardingClient lock was free during the RPC."""

    def __init__(self):
        super().__init__()
        self.release = threading.Event()
        self.in_rpc = threading.Event()
        self.lock_free_during_rpc = None
        self.sharding_client = None

    def report_task_result(self, *a, **kw):
        self.in_rpc.set()
        # the satellite-1 contract: the client must NOT hold its lock
        # across this blocking call
        self.lock_free_during_rpc = (
            self.sharding_client._lock.acquire(timeout=1.0)
        )
        if self.lock_free_during_rpc:
            self.sharding_client._lock.release()
        assert self.release.wait(timeout=10.0)
        return super().report_task_result(*a, **kw)


def test_report_batch_done_rpc_runs_outside_lock():
    mc = _SlowReportClient()
    sc = ShardingClient(
        dataset_name="lock-ds", batch_size=4, dataset_size=16,
        num_minibatches_per_shard=1, master_client=mc,
    )
    mc.sharding_client = sc
    assert sc.fetch_shard() is not None

    results = []
    t = threading.Thread(
        target=lambda: results.append(sc.report_batch_done()),
        daemon=True,
    )
    t.start()
    assert mc.in_rpc.wait(timeout=5.0)
    # while the report RPC is blocked, stop() must not stall
    t0 = time.monotonic()
    sc.stop()
    assert time.monotonic() - t0 < 0.5
    mc.release.set()
    t.join(timeout=5.0)
    assert results == [True]
    assert mc.lock_free_during_rpc is True


def test_report_batch_done_keeps_reject_semantics():
    class _Rejecting(LocalMasterClient):
        def report_task_result(self, *a, **kw):
            super().report_task_result(*a, **kw)
            return comm.Response(success=False, reason="requeued")

    sc = ShardingClient(
        dataset_name="rej-ds", batch_size=4, dataset_size=8,
        num_minibatches_per_shard=1, master_client=_Rejecting(),
    )
    assert sc.fetch_shard() is not None
    assert sc.report_batch_done() is False


def test_index_client_chunked_delivery():
    mc = LocalMasterClient()
    ic = IndexShardingClient(
        "chunk-ds", batch_size=4, dataset_size=22,
        num_minibatches_per_shard=1, master_client=mc,
    )
    first = ic.fetch_batch_indices()
    assert isinstance(first, np.ndarray)
    assert first.dtype == np.int64
    got = list(first)
    while True:
        arr = ic.fetch_batch_indices()
        if arr is None:
            break
        assert isinstance(arr, np.ndarray)
        got.extend(arr.tolist())
    assert sorted(got) == list(range(22))
    assert ic.exhausted and not ic.failed


def test_index_client_mixed_sample_and_batch_reads():
    mc = LocalMasterClient()
    ic = IndexShardingClient(
        "mix-ds", batch_size=4, dataset_size=20,
        num_minibatches_per_shard=1, master_client=mc,
    )
    got = [ic.fetch_sample_index(), ic.fetch_sample_index()]
    assert all(isinstance(i, int) for i in got)
    while True:
        arr = ic.fetch_batch_indices(6)
        if arr is None:
            break
        assert arr.size <= 6
        got.extend(int(i) for i in arr)
    assert sorted(got) == list(range(20))


# --------------------------------------------------------- device prefetch


def test_device_prefetch_propagates_producer_error():
    from dlrover_tpu.data.shm_dataloader import DevicePrefetch

    def gen():
        yield np.ones((2, 2), np.float32)
        raise RuntimeError("producer blew up")

    pf = DevicePrefetch(gen(), depth=2)
    it = iter(pf)
    next(it)  # the good batch arrives
    with pytest.raises(RuntimeError, match="producer blew up"):
        for _ in it:
            pass


def test_device_prefetch_transform_runs_on_fill_thread():
    from dlrover_tpu.data.shm_dataloader import DevicePrefetch

    main_thread = threading.get_ident()
    transform_threads = []

    def reshape(batch):
        transform_threads.append(threading.get_ident())
        return batch.reshape(2, 2)

    pf = DevicePrefetch(
        (np.arange(4, dtype=np.float32) for _ in range(3)),
        depth=2, transform=reshape,
    )
    batches = list(pf)
    assert len(batches) == 3
    assert all(b.shape == (2, 2) for b in batches)
    assert transform_threads and all(
        t != main_thread for t in transform_threads
    )


def test_device_prefetch_transform_error_propagates():
    from dlrover_tpu.data.shm_dataloader import DevicePrefetch

    pf = DevicePrefetch(
        (np.arange(4) for _ in range(3)), depth=2,
        transform=lambda b: (_ for _ in ()).throw(ValueError("bad")),
    )
    with pytest.raises(ValueError, match="bad"):
        list(pf)


# ----------------------------------------------------------------- sampler


def test_sampler_iter_batches_matches_iter():
    from dlrover_tpu.trainer.sampler import ElasticDistributedSampler

    for kwargs in (
        dict(dataset_size=21, num_replicas=2, rank=1, shuffle=False),
        dict(dataset_size=32, num_replicas=4, rank=0, shuffle=True,
             seed=3),
        dict(dataset_size=17, num_replicas=3, rank=2, shuffle=False,
             drop_last=True),
    ):
        a = ElasticDistributedSampler(**kwargs)
        b = ElasticDistributedSampler(**kwargs)
        per_sample = list(a)
        chunks = list(b.iter_batches(4))
        assert all(isinstance(c, np.ndarray) for c in chunks)
        assert all(c.size <= 4 for c in chunks)
        batched = (
            np.concatenate(chunks).tolist() if chunks else []
        )
        assert batched == per_sample
        assert a.completed_num == b.completed_num


def test_sampler_iter_batches_resumes_from_state():
    from dlrover_tpu.trainer.sampler import ElasticDistributedSampler

    s = ElasticDistributedSampler(dataset_size=24, num_replicas=2,
                                  rank=0, shuffle=False)
    it = s.iter_batches(4)
    first = next(it)
    assert first.tolist() == [0, 2, 4, 6]
    # resume a fresh sampler from the committed offset
    s2 = ElasticDistributedSampler(dataset_size=24, num_replicas=2,
                                   rank=0, shuffle=False)
    s2.load_state_dict(s.state_dict())
    rest = np.concatenate(list(s2.iter_batches(4))).tolist()
    assert rest == [8, 10, 12, 14, 16, 18, 20, 22]


# --------------------------------------------------------------- benchmark


def test_shard_throughput_smoke():
    """The benchmark's tier-1 smoke tier: runs end to end against a
    real gRPC master with the journal on the path, delivers every
    shard exactly once, and the batched path is not slower."""
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               DLROVER_TPU_METRICS_PORT="off")
    out = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "benchmarks", "shard_throughput.py"),
         "--smoke"],
        capture_output=True, text=True, timeout=180, env=env,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    line = out.stdout.strip().splitlines()[-1]
    result = json.loads(line)
    assert result["exactly_once"] is True
    assert result["journal"] is True
    assert result["vs_baseline"] > 1.0, result
