"""Silent-failure sentinel chaos drill: NaN loss -> coordinated
last-good rollback -> finite completion, exactly once.

A real master serves two protocol-speaking workers
(``_sentinel_drill_worker.py``), each with a live goodput ledger, an
armed :class:`TrainingSentinel` and a real FlashCheckpointer whose
saves carry the sentinel's clean verdict.
``DLROVER_FAULT_INJECT=nan@6:host=0`` poisons worker 0's step-6 loss:
the sentinel must trip (``nonfinite_loss``), report over the
supervised RPC, and receive a rollback order naming its last
sentinel-clean save (step 5). Worker 1 — which saw nothing wrong —
must learn the SAME order from the master KV broadcast and restore in
concert.

Asserted: worker 0 restores exactly the ordered step with matching
arrays; both ranks adopt the same order id; the detecting rank (and
only it) rewinds the global shard ledger, so consumption voided by the
rollback is re-dispatched and the dataset is still consumed exactly
once; the journal tells the full story (anomaly.detected ->
anomaly.reported -> rollback.initiated -> rollback.ordered x2 ->
rollback.restored x2 -> rollback.recovered); a single strike stays
below the quarantine threshold and inside the rollback budget; and the
goodput account — live ``/goodput``, the master's job summary, and the
offline journal reconstruction — books the incident under the
``rollback`` badput cause.
"""

import os
import signal
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from test_goodput_drill import (  # noqa: E402
    _drill_env,
    _free_port,
    _killpg,
    _master_port,
    _poll_goodput,
    _tail,
    _wait,
)

from dlrover_tpu.telemetry import goodput
from dlrover_tpu.telemetry.goodput import Phase
from dlrover_tpu.telemetry.journal import read_journal

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

DATASET_SIZE = 96
BATCH_SIZE = 4
SHARD_SECS = 0.1
#: the injected NaN lands on worker 0's step 6, so its last clean save
#: (and therefore the ordered rollback step) is deterministically 5
TRIP_STEP = 6
LAST_GOOD = TRIP_STEP - 1


def _spawn_master(tmp, env, state_dir, port, tag):
    cmd = [
        sys.executable, "-m", "dlrover_tpu.master.main",
        "--platform", "process", "--node_num", "0",
        "--job_name", "sentinel-drill", "--port", str(port),
        "--state_dir", state_dir,
        "--autoscale_interval", "600", "--check_interval", "0.2",
    ]
    return subprocess.Popen(
        cmd, cwd=REPO, env=env,
        stdout=open(os.path.join(tmp, f"master-{tag}.out"), "w"),
        stderr=open(os.path.join(tmp, f"master-{tag}.err"), "w"),
        start_new_session=True,
    )


def _spawn_worker(tmp, env, port, node_id, tag, ckpt_dir, ram_dir,
                  dataset_size=DATASET_SIZE, fetch_batch=2,
                  lookahead=2):
    return subprocess.Popen(
        [sys.executable,
         os.path.join(REPO, "tests", "_sentinel_drill_worker.py"),
         "--master_addr", f"localhost:{port}",
         "--node_id", str(node_id),
         "--out", os.path.join(tmp, f"worker-{tag}.txt"),
         "--ckpt_dir", ckpt_dir,
         "--ram_dir", ram_dir,
         "--dataset_size", str(dataset_size),
         "--batch_size", str(BATCH_SIZE),
         "--shard_secs", str(SHARD_SECS),
         "--fetch_batch", str(fetch_batch),
         "--lookahead", str(lookahead)],
        cwd=REPO, env=env,
        stdout=open(os.path.join(tmp, f"worker-{tag}.out"), "w"),
        stderr=subprocess.STDOUT,
        start_new_session=True,
    )


def _worker_lines(tmp, tag, token):
    path = os.path.join(tmp, f"worker-{tag}.txt")
    try:
        lines = open(path).read().splitlines()
    except OSError:
        return []
    return [l.split() for l in lines if l.startswith(token)]


def _await_live_rollback(port, workers, tmp):
    """Poll /goodput until the rollback fault window shows up (open or
    already recovered) while the run is still in flight."""
    deadline = time.time() + 120
    last = None
    while time.time() < deadline:
        try:
            last = _poll_goodput(port, timeout=5)
        except AssertionError:
            last = None
        if last is not None and any(
            f.get("cause") == Phase.ROLLBACK
            for f in last.get("faults", ())
        ):
            return last
        if all(w.poll() is not None for w in workers):
            # both workers already exited: one more poll below, then
            # fail fast instead of burning the whole deadline
            try:
                last = _poll_goodput(port, timeout=5)
            except AssertionError:
                last = None
            break
        time.sleep(0.3)
    assert last is not None and any(
        f.get("cause") == Phase.ROLLBACK for f in last.get("faults", ())
    ), (
        f"/goodput never showed a rollback fault: {last}; "
        + _tail(tmp, "worker-0.out") + " | " + _tail(tmp, "master-1.err")
    )
    return last


def test_sentinel_nan_rollback_drill(tmp_path):
    tmp = str(tmp_path)
    state_dir = os.path.join(tmp, "state")
    journal_path = os.path.join(tmp, "journal.jsonl")
    ckpt_dir = {i: os.path.join(tmp, f"ckpt-{i}") for i in (0, 1)}
    ram_dir = {i: os.path.join(tmp, f"ram-{i}") for i in (0, 1)}
    env = _drill_env(journal_path)
    metrics_port = _free_port()
    master_env = dict(
        env,
        DLROVER_TPU_METRICS_PORT=str(metrics_port),
        # one strike must NOT quarantine (threshold is the SECOND
        # strike) and must stay far inside the rollback budget
        DLROVER_TPU_QUARANTINE_THRESHOLD="2",
        DLROVER_TPU_MAX_ROLLBACKS="3",
        # a generous watchdog so the only shard requeue in this drill
        # is the ledger rewind, keeping the exactly-once arithmetic
        # attributable to the rollback alone
        DLROVER_TPU_CTX_TASK_PROCESS_TIMEOUT="60",
    )

    procs = []
    try:
        m = _spawn_master(tmp, master_env, state_dir, 0, "1")
        procs.append(m)
        port = _master_port(tmp, "1", m)

        w0 = _spawn_worker(
            tmp, dict(env,
                      DLROVER_FAULT_INJECT=f"nan@{TRIP_STEP}:host=0",
                      DLROVER_TPU_NODE_RANK="0"),
            port, 0, "0", ckpt_dir[0], ram_dir[0],
        )
        w1 = _spawn_worker(
            tmp, dict(env, DLROVER_TPU_NODE_RANK="1"),
            port, 1, "1", ckpt_dir[1], ram_dir[1],
        )
        procs += [w0, w1]

        # live /goodput mid-run: the ordered rollback is a fault
        # window on the aggregator while the workers are still going
        live = _await_live_rollback(metrics_port, [w0, w1], tmp)

        for tag, w in (("0", w0), ("1", w1)):
            rc = _wait(w, 180, f"worker {tag}", tmp,
                       ["worker-0.out", "worker-1.out", "master-1.err"])
            assert rc == 0, (
                f"worker {tag} exited rc={rc}; "
                + _tail(tmp, f"worker-{tag}.out")
            )
        rc_m = _wait(m, 60, "master", tmp, ["master-1.err"])
        assert rc_m == 0, _tail(tmp, "master-1.err")
    finally:
        for p in procs:
            _killpg(p, signal.SIGTERM)
        time.sleep(0.5)
        for p in procs:
            _killpg(p)

    # ---- the trip, the order, the restore ----------------------------
    trips = _worker_lines(tmp, "0", "TRIP")
    assert trips == [["TRIP", "nonfinite_loss", str(TRIP_STEP)]], trips
    assert not _worker_lines(tmp, "1", "TRIP")

    rb0 = _worker_lines(tmp, "0", "ROLLBACK")
    rb1 = _worker_lines(tmp, "1", "ROLLBACK")
    assert len(rb0) == 1 and len(rb1) == 1, (rb0, rb1)
    # both ranks adopted the SAME order: same id, same ordered step
    assert rb0[0][1] == rb1[0][1] == str(LAST_GOOD), (rb0, rb1)
    assert rb0[0][3] == rb1[0][3], (rb0, rb1)

    # the detector restored EXACTLY the ordered last-good step, and the
    # restored arrays carry that step's stamp; the peer restored its
    # newest save at or below the order with matching arrays too
    rolled0 = _worker_lines(tmp, "0", "ROLLED")
    assert rolled0 == [["ROLLED", str(LAST_GOOD), "ok"]], rolled0
    rolled1 = _worker_lines(tmp, "1", "ROLLED")
    assert len(rolled1) == 1 and rolled1[0][2] == "ok", rolled1
    assert 0 < int(rolled1[0][1]) <= LAST_GOOD, rolled1

    # only the DETECTING rank rewound the global shard ledger
    restored = _worker_lines(tmp, "0", "LEDGER_RESTORED")
    assert len(restored) == 1 and restored[0][1] == str(LAST_GOOD), restored
    assert not _worker_lines(tmp, "1", "LEDGER_RESTORED")

    # the run finished FINITE after the rollback: no budget exhaustion,
    # exactly one anomaly job-wide, both ranks completed the epoch
    for tag in ("0", "1"):
        assert _worker_lines(tmp, tag, "DONE"), _tail(
            tmp, f"worker-{tag}.txt"
        )
        assert not _worker_lines(tmp, tag, "JOB_FAILED")
    assert _worker_lines(tmp, "0", "ANOMALIES") == [["ANOMALIES", "1"]]
    assert _worker_lines(tmp, "1", "ANOMALIES") == [["ANOMALIES", "0"]]

    # ---- exactly-once across the rollback ----------------------------
    # SHARD lines are emitted only for master-ACCEPTED completions. The
    # ledger rewind requeues (with fresh task ids) everything consumed
    # after the last-good save, so exactly those ranges are consumed a
    # second time: effective = accepted - voided.
    t_rewind = float(restored[0][2])
    by_range = {}
    for tag in ("0", "1"):
        for parts in _worker_lines(tmp, tag, "SHARD"):
            rng = (int(parts[1]), int(parts[2]))
            by_range.setdefault(rng, []).append(float(parts[3]))

    ranges = sorted(by_range)
    assert ranges[0][0] == 0 and ranges[-1][1] == DATASET_SIZE, ranges
    for (_, end), (start, _) in zip(ranges, ranges[1:]):
        assert end == start, f"shard gap/overlap at {start}: {ranges}"

    dupes = {r: ts for r, ts in by_range.items() if len(ts) > 1}
    # the detector consumed (and the master accepted) its trip-step
    # shard before the rewind voided it, so at least one range repeats
    assert dupes, by_range
    for rng, ts in dupes.items():
        # a range repeats for exactly one reason — the rewind: once
        # voided before it, once effective after it
        assert len(ts) == 2, (rng, ts)
        assert min(ts) < t_rewind < max(ts), (rng, ts, t_rewind)

    # ---- journal: the incident, step by step -------------------------
    events = read_journal(journal_path)
    kinds = [e.get("kind") for e in events]
    by_kind = {}
    for e in events:
        by_kind.setdefault(e.get("kind"), []).append(e)

    injected = [e for e in by_kind.get("fault.injected", ())
                if e["data"]["fault"] == "nan"]
    assert len(injected) == 1, by_kind.get("fault.injected")

    det = by_kind["anomaly.detected"]
    assert len(det) == 1, det
    assert det[0]["data"]["anomaly"] == "nonfinite_loss", det
    assert det[0]["data"]["step"] == TRIP_STEP, det
    assert det[0]["data"]["value"] is None, det  # NaN is not JSON
    assert det[0]["data"]["last_good_step"] == LAST_GOOD, det

    rep = by_kind["anomaly.reported"]
    assert len(rep) == 1, rep
    assert rep[0]["data"]["anomaly"] == "nonfinite_loss", rep
    assert rep[0]["data"]["last_good_step"] == LAST_GOOD, rep

    init = by_kind["rollback.initiated"]
    assert len(init) == 1, init
    assert init[0]["data"]["step"] == LAST_GOOD, init
    assert init[0]["data"]["rollbacks"] == 1, init
    order_id = init[0]["data"]["rollback_id"]
    assert order_id == int(rb0[0][3]), (init, rb0)

    # both ranks journaled the adoption and the restore of one order
    ordered = by_kind["rollback.ordered"]
    assert len(ordered) == 2, ordered
    assert {e["data"]["node_rank"] for e in ordered} == {0, 1}, ordered
    assert all(
        e["data"]["rollback_id"] == order_id for e in ordered
    ), ordered
    rest = by_kind["rollback.restored"]
    assert len(rest) == 2, rest
    assert {e["data"]["node_rank"] for e in rest} == {0, 1}, rest

    # the detector's RUNNING re-report closed the window ONCE — the
    # peer rode the same order and never burned a second window
    rec = by_kind["rollback.recovered"]
    assert len(rec) == 1 and rec[0]["data"]["rank"] == 0, rec

    # one strike: below the quarantine threshold, inside the budget
    assert "quarantine.imposed" not in kinds, kinds
    assert "rollback.budget_exhausted" not in kinds, kinds

    # ---- goodput: the incident books as rollback badput --------------
    win = next(
        f for f in live["faults"] if f["cause"] == Phase.ROLLBACK
    )
    assert win.get("node_id") == 0, win

    summaries = by_kind.get("goodput.job_summary", [])
    assert len(summaries) == 1, summaries
    live_job = summaries[0]["data"]
    assert live_job["badput_s"][Phase.ROLLBACK] > 0.0, live_job

    # offline replay tells the same story: a recovered rollback window
    # attributed to the detecting node, with rollback badput booked
    report = goodput.reconstruct(events)
    off = next(
        f for f in report["faults"] if f["cause"] == Phase.ROLLBACK
    )
    assert off["recovered_ts"] and off["recovered_ts"] >= off["ts"], off
    assert report["job"]["badput_s"][Phase.ROLLBACK] > 0.0, report["job"]
    assert report["job"]["procs"] == 2, report["job"]


#: the sdc drill needs worker 0 to reach its local step 14 (second
#: strike) while worker 1 still has shards left to drain afterwards —
#: 40 shards across two workers leaves a wide margin on both sides
SDC_DATASET = 160
SDC_TRIPS = (8, 14)  # worker-0 local steps the two sdc faults land on


def test_sdc_repeat_offender_quarantine_drill(tmp_path):
    """Repeated SDC attributed to ONE host: two loss-spike strikes on
    worker 0 order two coordinated rollbacks (inside the budget), the
    second strike imposes the quarantine — rendezvous eviction + no
    relaunch onto the host — and worker 0 honors its last rewind, then
    stands down while worker 1 finishes the epoch. The dataset is
    still consumed exactly once, and one shared injection spec (the
    documented ``sdc@STEP:flip=K,host=H`` grammar) runs on BOTH
    workers with only host 0 poisoned."""
    tmp = str(tmp_path)
    state_dir = os.path.join(tmp, "state")
    journal_path = os.path.join(tmp, "journal.jsonl")
    ckpt_dir = {i: os.path.join(tmp, f"ckpt-{i}") for i in (0, 1)}
    ram_dir = {i: os.path.join(tmp, f"ram-{i}") for i in (0, 1)}
    env = _drill_env(journal_path)
    master_env = dict(
        env,
        DLROVER_TPU_QUARANTINE_THRESHOLD="2",
        DLROVER_TPU_MAX_ROLLBACKS="3",
        DLROVER_TPU_CTX_TASK_PROCESS_TIMEOUT="60",
    )
    # one spec for the whole fleet: the host filter scopes both faults
    # to node rank 0, and MIN_STEPS=4 arms the MAD spike detector
    # before the first strike lands at local step 8
    worker_env = dict(
        env,
        DLROVER_FAULT_INJECT=(
            f"sdc@{SDC_TRIPS[0]}:flip=6,host=0,"
            f"sdc@{SDC_TRIPS[1]}:flip=6,host=0"
        ),
        DLROVER_TPU_SENTINEL_MIN_STEPS="4",
    )

    procs = []
    try:
        m = _spawn_master(tmp, master_env, state_dir, 0, "2")
        procs.append(m)
        port = _master_port(tmp, "2", m)

        workers = {}
        for i in (0, 1):
            workers[i] = _spawn_worker(
                tmp, dict(worker_env,
                          DLROVER_TPU_NODE_RANK=str(i),
                          HOSTNAME=f"sdc-host-{i}"),
                port, i, str(i), ckpt_dir[i], ram_dir[i],
                dataset_size=SDC_DATASET,
                # no prefetch: a quarantined worker must leave no
                # in-flight shards behind for the 60 s watchdog
                fetch_batch=1, lookahead=0,
            )
        procs += list(workers.values())

        for tag, w in sorted(workers.items()):
            rc = _wait(w, 180, f"worker {tag}", tmp,
                       ["worker-0.out", "worker-1.out", "master-2.err"])
            assert rc == 0, (
                f"worker {tag} exited rc={rc}; "
                + _tail(tmp, f"worker-{tag}.out")
            )
        rc_m = _wait(m, 60, "master", tmp, ["master-2.err"])
        assert rc_m == 0, _tail(tmp, "master-2.err")
    finally:
        for p in procs:
            _killpg(p, signal.SIGTERM)
        time.sleep(0.5)
        for p in procs:
            _killpg(p)

    # ---- two strikes on worker 0, none on worker 1 -------------------
    trips = _worker_lines(tmp, "0", "TRIP")
    assert trips == [
        ["TRIP", "loss_spike", str(s)] for s in SDC_TRIPS
    ], trips
    assert not _worker_lines(tmp, "1", "TRIP")

    # both rollbacks honored on BOTH ranks before worker 0 stood down
    rb0 = _worker_lines(tmp, "0", "ROLLBACK")
    rb1 = _worker_lines(tmp, "1", "ROLLBACK")
    assert [r[1] for r in rb0] == [
        str(s - 1) for s in SDC_TRIPS
    ], rb0
    assert len(rb1) == 2, rb1
    assert [r[3] for r in rb0] == [r[3] for r in rb1], (rb0, rb1)
    rolled0 = _worker_lines(tmp, "0", "ROLLED")
    assert rolled0 == [
        ["ROLLED", str(s - 1), "ok"] for s in SDC_TRIPS
    ], rolled0
    for parts in _worker_lines(tmp, "1", "ROLLED"):
        assert parts[2] == "ok", parts
    # the DETECTING rank rewound the ledger once per incident
    assert [r[1] for r in _worker_lines(tmp, "0", "LEDGER_RESTORED")] \
        == [str(s - 1) for s in SDC_TRIPS]
    assert not _worker_lines(tmp, "1", "LEDGER_RESTORED")

    # worker 0 stood down on the quarantine verdict; worker 1 carried
    # the job to completion — no budget exhaustion, no job failure
    assert _worker_lines(tmp, "0", "QUARANTINED"), _tail(
        tmp, "worker-0.txt"
    )
    assert not _worker_lines(tmp, "1", "QUARANTINED")
    for tag in ("0", "1"):
        assert _worker_lines(tmp, tag, "DONE"), _tail(
            tmp, f"worker-{tag}.txt"
        )
        assert not _worker_lines(tmp, tag, "JOB_FAILED")
    assert _worker_lines(tmp, "0", "ANOMALIES") == [["ANOMALIES", "2"]]
    assert _worker_lines(tmp, "1", "ANOMALIES") == [["ANOMALIES", "0"]]

    # ---- exactly-once across BOTH rewinds and the stand-down ---------
    by_range = {}
    for tag in ("0", "1"):
        for parts in _worker_lines(tmp, tag, "SHARD"):
            rng = (int(parts[1]), int(parts[2]))
            by_range.setdefault(rng, []).append(float(parts[3]))
    ranges = sorted(by_range)
    assert ranges[0][0] == 0 and ranges[-1][1] == SDC_DATASET, ranges
    for (_, end), (start, _) in zip(ranges, ranges[1:]):
        assert end == start, f"shard gap/overlap at {start}: {ranges}"
    # a shard voided by one rewind repeats once; a shard unlucky enough
    # to be voided by both repeats twice — never more
    dupes = {r: ts for r, ts in by_range.items() if len(ts) > 1}
    assert dupes, by_range
    for rng, ts in dupes.items():
        assert len(ts) <= 3, (rng, ts)

    # ---- journal: two incidents, one quarantine ----------------------
    events = read_journal(journal_path)
    kinds = [e.get("kind") for e in events]
    by_kind = {}
    for e in events:
        by_kind.setdefault(e.get("kind"), []).append(e)

    injected = [e for e in by_kind.get("fault.injected", ())
                if e["data"]["fault"] == "sdc"]
    assert len(injected) == 2, by_kind.get("fault.injected")
    assert all(e["data"]["node_rank"] == 0 for e in injected), injected

    det = by_kind["anomaly.detected"]
    assert [
        (e["data"]["anomaly"], e["data"]["step"]) for e in det
    ] == [("loss_spike", s) for s in SDC_TRIPS], det
    # SDC is finite-but-wrong: the spike detector carries the evidence
    assert all(e["data"]["zscore"] > 6.0 for e in det), det
    assert all(e["data"]["host"] == "sdc-host-0" for e in det), det

    init = by_kind["rollback.initiated"]
    assert [e["data"]["step"] for e in init] == [
        s - 1 for s in SDC_TRIPS
    ], init
    assert [e["data"]["rollbacks"] for e in init] == [1, 2], init
    assert all(e["data"]["host"] == "sdc-host-0" for e in init), init
    assert "rollback.budget_exhausted" not in kinds, kinds

    # both ranks adopted and restored both orders
    ids = sorted(e["data"]["rollback_id"] for e in init)
    ordered = by_kind["rollback.ordered"]
    assert len(ordered) == 4, ordered
    for rank in (0, 1):
        assert sorted(
            e["data"]["rollback_id"] for e in ordered
            if e["data"]["node_rank"] == rank
        ) == ids, ordered
    assert len(by_kind["rollback.restored"]) == 4

    # the SECOND strike imposed the quarantine on exactly host 0
    (q,) = by_kind["quarantine.imposed"]
    assert q["data"]["host"] == "sdc-host-0", q
    assert q["data"]["anomalies"] == 2, q
    assert q["data"]["threshold"] == 2, q
    assert q["data"]["anomaly"] == "loss_spike", q
    assert q["data"]["step"] == SDC_TRIPS[1], q

    # rendezvous eviction + relaunch exclusion landed on the master
    master_err = open(os.path.join(tmp, "master-2.err")).read()
    assert "QUARANTINE: host sdc-host-0" in master_err, master_err[-2000:]
    assert "Quarantine on" in master_err, master_err[-2000:]

    # offline goodput replay books BOTH incidents as recovered
    # rollback badput on the detecting node
    report = goodput.reconstruct(events)
    offs = [f for f in report["faults"] if f["cause"] == Phase.ROLLBACK]
    assert len(offs) == 2, report["faults"]
    for off in offs:
        assert off["recovered_ts"] and off["recovered_ts"] >= off["ts"], off
    assert report["job"]["badput_s"][Phase.ROLLBACK] > 0.0, report["job"]
