"""The sharded router plane (ISSUE 20): hash partitioning, per-tenant
deficit-round-robin fairness, done-store TTL GC, live resharding, and
the replica-stats delta-report section.

The exactly-once contract (done-store first-complete-wins + three
redelivery paths) is per-shard; these tests drive the cases where
requests and failures SPAN shards — the places where a partitioning
bug would break the contract without any single shard misbehaving.
"""

import json
import os
import subprocess
import sys
import time

import pytest

from dlrover_tpu.agent.status_reporter import DeltaTracker
from dlrover_tpu.common import comm
from dlrover_tpu.serving.autoscaler import ServingAutoScaler
from dlrover_tpu.serving.router import RequestRouter, shard_for
from dlrover_tpu.serving.worker import ServingWorker
from dlrover_tpu.telemetry.journal import (
    EventJournal,
    default_journal,
    set_default_journal,
)

W = "worker"


@pytest.fixture()
def journal():
    set_default_journal(EventJournal())
    try:
        yield default_journal()
    finally:
        set_default_journal(EventJournal())


def _ids_spanning_shards(n_shards, per_shard=3, prefix="rq"):
    """Request ids chosen so every shard owns at least ``per_shard``."""
    got = {s: [] for s in range(n_shards)}
    i = 0
    while any(len(v) < per_shard for v in got.values()):
        rid = f"{prefix}-{i}"
        i += 1
        s = shard_for(rid, n_shards)
        if len(got[s]) < per_shard:
            got[s].append(rid)
    return [rid for ids in got.values() for rid in ids]


def _drain_all(r, node_id=0, incarnation=0):
    """Lease until the plane hands out nothing twice in a row (one
    rotated pass can skip shards)."""
    out, dry = [], 0
    while dry < 3:
        batch, _ = r.lease(W, node_id, max_requests=64,
                           incarnation=incarnation)
        if batch:
            out.extend(batch)
            dry = 0
        else:
            dry += 1
    return out


# ------------------------------------------------------------ partitioning


def test_shard_for_is_stable_and_total():
    for n in (1, 2, 4, 7):
        for i in range(200):
            s = shard_for(f"req-{i}", n)
            assert 0 <= s < n
            assert s == shard_for(f"req-{i}", n)  # deterministic


def test_sharded_exactly_once_duplicate_submit_across_shards():
    """Duplicate submits of ids living on every shard are rejected by
    the owning shard, and each request completes exactly once."""
    r = RequestRouter(shards=4, max_queue=1024)
    ids = _ids_spanning_shards(4, per_shard=4)
    for rid in ids:
        ok, _, reason = r.submit(rid.encode(), req_id=rid)
        assert ok, reason
    # every duplicate rejected, whatever shard it hashes to
    for rid in ids:
        ok, _, reason = r.submit(b"dup", req_id=rid)
        assert not ok and reason == "duplicate"
    leased = _drain_all(r)
    assert sorted(rid for rid, _ in leased) == sorted(ids)
    for rid, payload in leased:
        assert r.complete(W, 0, rid, payload.upper())
        assert not r.complete(W, 1, rid, b"ghost")  # first wins
    stats = r.stats()
    assert stats["completed"] == len(ids)
    assert stats["duplicates"] == 2 * len(ids)
    assert stats["shards"] == 4
    for rid in ids:
        done, payload, _, _ = r.poll(rid)
        assert done and payload == rid.encode().upper()


def test_sharded_incarnation_reclaim_spans_shards():
    """A lease from a newer incarnation must reclaim the dead
    process's leases on EVERY shard — not just the shards the new
    lease's rotated pass happens to drain."""
    r = RequestRouter(shards=4, lease_timeout=60.0)
    ids = _ids_spanning_shards(4, per_shard=2)
    for rid in ids:
        assert r.submit(rid.encode(), req_id=rid)[0]
    leased = _drain_all(r, node_id=0, incarnation=0)
    assert len(leased) == len(ids)  # inc 0 holds leases on all shards
    # the restarted process leases ONCE with max_requests=1: the
    # reclaim must still cover every shard's leases
    batch, _ = r.lease(W, 0, max_requests=1, incarnation=1)
    assert len(batch) == 1
    assert r.stats()["redelivered"] == len(ids)
    reclaimed = batch + _drain_all(r, node_id=0, incarnation=1)
    assert sorted(rid for rid, _ in reclaimed) == sorted(ids)
    for rid, payload in reclaimed:
        assert r.complete(W, 0, rid, payload)
    assert r.stats()["completed"] == len(ids)


def test_lease_rotates_across_shards():
    """One lease call drains round-robin across shards: a batch fills
    from several shards, not the first one only."""
    r = RequestRouter(shards=4, max_queue=1024)
    ids = _ids_spanning_shards(4, per_shard=4)
    for rid in ids:
        assert r.submit(rid.encode(), req_id=rid)[0]
    batch, _ = r.lease(W, 0, max_requests=8, incarnation=0)
    assert len(batch) == 8
    touched = {shard_for(rid, 4) for rid, _ in batch}
    assert len(touched) >= 2


# --------------------------------------------------------------- resharding


def test_resize_with_inflight_leases_preserves_exactly_once(journal):
    """The mid-soak scenario: shard count changes 2 -> 4 with leases
    outstanding and requests queued. In-flight leases keep their
    worker, queued requests survive in submit order, completions and
    duplicates behave identically after the move."""
    r = RequestRouter(shards=2, lease_timeout=60.0, max_queue=1024)
    ids = [f"rz-{i}" for i in range(24)]
    for rid in ids:
        assert r.submit(rid.encode(), req_id=rid)[0]
    batch, _ = r.lease(W, 0, max_requests=10, incarnation=0)
    inflight = [rid for rid, _ in batch]
    assert len(inflight) == 10

    assert r.resize_shards(4) == 4
    assert r.shard_count == 4
    evs = journal.events("serve.shards_resized")
    assert evs and evs[-1]["data"]["old"] == 2 \
        and evs[-1]["data"]["new"] == 4

    st = r.stats()
    assert st["shards"] == 4
    assert st["in_flight"] == 10
    assert st["queue_depth"] == len(ids) - 10
    assert st["submitted"] == len(ids)  # lifetime counters carried

    # the old worker's leases complete against the NEW shard layout
    for rid in inflight:
        assert r.complete(W, 0, rid, rid.encode())
        assert not r.complete(W, 1, rid, b"ghost")
    # the queued remainder leases out and completes exactly once
    rest = _drain_all(r, node_id=1)
    assert sorted(rid for rid, _ in rest) == sorted(set(ids) - set(inflight))
    for rid, payload in rest:
        assert r.complete(W, 1, rid, payload)
    r.seal()
    for rid in ids:
        assert r.poll(rid)[0]
    assert r.finished()
    assert r.stats()["completed"] == len(ids)


def test_resize_preserves_submit_order_within_tenant():
    r = RequestRouter(shards=1, max_queue=1024)
    ids = [f"ord-{i}" for i in range(12)]
    for rid in ids:
        assert r.submit(rid.encode(), req_id=rid)[0]
    r.resize_shards(3)
    # per-shard FIFO must still follow global submit order
    leased = _drain_all(r)
    by_shard = {}
    for rid, _ in leased:
        by_shard.setdefault(shard_for(rid, 3), []).append(rid)
    for shard_ids in by_shard.values():
        assert shard_ids == sorted(shard_ids, key=ids.index)


def test_resize_noop_and_shrink():
    r = RequestRouter(shards=4)
    assert r.resize_shards(4) == 4  # no-op
    ids = _ids_spanning_shards(4, per_shard=2)
    for rid in ids:
        assert r.submit(rid.encode(), req_id=rid)[0]
    r.resize_shards(1)  # shrink folds every partition into one
    leased = _drain_all(r)
    assert sorted(rid for rid, _ in leased) == sorted(ids)
    for rid, payload in leased:
        assert r.complete(W, 0, rid, payload)
    assert r.stats()["completed"] == len(ids)


# ----------------------------------------------------------- fair queuing


def test_drr_starved_tenant_served_within_one_cycle():
    """Deficit round-robin: a tenant arriving behind another tenant's
    flood gets its quantum within ONE drain cycle, not after the
    flood."""
    r = RequestRouter(shards=1, max_queue=1024, drr_quantum=4)
    for i in range(50):
        assert r.submit(b"x", req_id=f"big-{i}", tenant="whale")[0]
    for i in range(2):
        assert r.submit(b"y", req_id=f"small-{i}", tenant="minnow")[0]
    batch, _ = r.lease(W, 0, max_requests=8, incarnation=0)
    tenants = [rid.split("-")[0] for rid, _ in batch]
    # one cycle = whale's quantum (4) then minnow's turn: both of
    # minnow's requests ride the FIRST batch
    assert tenants.count("small") == 2
    assert tenants.count("big") == 6


def test_drr_shares_roughly_equal_between_active_tenants():
    r = RequestRouter(shards=1, max_queue=4096, drr_quantum=4)
    for i in range(60):
        r.submit(b"x", req_id=f"a-{i}", tenant="a")
        r.submit(b"x", req_id=f"b-{i}", tenant="b")
        r.submit(b"x", req_id=f"c-{i}", tenant="c")
    batch, _ = r.lease(W, 0, max_requests=30, incarnation=0)
    counts = {}
    for rid, _ in batch:
        t = rid.split("-")[0]
        counts[t] = counts.get(t, 0) + 1
    assert set(counts) == {"a", "b", "c"}
    assert max(counts.values()) - min(counts.values()) <= 4  # one quantum


def test_priority_classes_are_strict():
    """A higher priority class drains fully before a lower one —
    priority is strict, fairness is within a class."""
    r = RequestRouter(shards=1, max_queue=1024)
    for i in range(6):
        assert r.submit(b"x", req_id=f"lo-{i}", tenant="t", priority=0)[0]
    for i in range(3):
        assert r.submit(b"x", req_id=f"hi-{i}", tenant="t", priority=5)[0]
    batch, _ = r.lease(W, 0, max_requests=6, incarnation=0)
    got = [rid for rid, _ in batch]
    assert got[:3] == ["hi-0", "hi-1", "hi-2"]
    assert all(rid.startswith("lo-") for rid in got[3:])


def test_redelivery_requeues_to_tenant_front():
    """A redelivered request goes to the front of ITS tenant's queue:
    it is that tenant's oldest work, and must not jump another
    tenant's line either."""
    r = RequestRouter(shards=1, lease_timeout=0.1, drr_quantum=4)
    assert r.submit(b"x", req_id="a-old", tenant="a")[0]
    batch, _ = r.lease(W, 0, max_requests=1, incarnation=0)
    assert [rid for rid, _ in batch] == ["a-old"]
    r.submit(b"x", req_id="a-new", tenant="a")
    time.sleep(0.15)
    assert r.check_timeouts() == 1
    batch, _ = r.lease(W, 1, max_requests=2, incarnation=0)
    assert [rid for rid, _ in batch] == ["a-old", "a-new"]


def test_default_tenant_keeps_global_fifo():
    """No tenant= -> the old behavior exactly: one FIFO, submit
    order."""
    r = RequestRouter(shards=1)
    for i in range(8):
        assert r.submit(b"x", req_id=f"f-{i}")[0]
    batch, _ = r.lease(W, 0, max_requests=8, incarnation=0)
    assert [rid for rid, _ in batch] == [f"f-{i}" for i in range(8)]


# ----------------------------------------------------------- done-store GC


def test_done_ttl_gc_evicts_delivered_keeps_undelivered():
    r = RequestRouter(shards=2, done_ttl=0.1)
    for rid in ("g-1", "g-2", "g-3"):
        assert r.submit(b"x", req_id=rid)[0]
    for rid, payload in _drain_all(r):
        assert r.complete(W, 0, rid, payload)
    assert r.poll("g-1")[0] and r.poll("g-2")[0]  # delivered
    # g-3 completed but never polled: kept forever
    time.sleep(0.15)
    assert r.gc_done() == 2
    stats = r.stats()
    assert stats["done_evicted"] == 2
    assert stats["completed"] == 3  # the counter is monotonic, not len(_done)
    done, payload, _, _ = r.poll("g-3")
    assert done and payload == b"x"  # undelivered survived the TTL


def test_done_ttl_late_ghost_completion_still_rejected():
    """Regression (the ISSUE's named case): after the done entry is
    GC'd, a late ghost completion for that id must still be rejected —
    the request is not pending, so exactly-once holds even though the
    response record is gone."""
    r = RequestRouter(shards=1, done_ttl=0.1, lease_timeout=60.0)
    assert r.submit(b"x", req_id="ghost")[0]
    batch, _ = r.lease(W, 0, max_requests=1, incarnation=0)
    assert batch
    assert r.complete(W, 0, "ghost", b"real")
    assert r.poll("ghost")[0]
    # inside the TTL: a retry is rejected as a duplicate
    assert not r.complete(W, 1, "ghost", b"late")
    time.sleep(0.15)
    assert r.gc_done() == 1
    # after eviction: STILL rejected (no pending record to win)
    assert not r.complete(W, 1, "ghost", b"later")
    assert r.stats()["duplicates"] == 2
    # and a resubmit under the same id is a fresh request (the client
    # explicitly chose to reuse the id after consuming the response)
    ok, _, reason = r.submit(b"x2", req_id="ghost")
    assert ok, reason


def test_finished_is_o1_and_survives_gc():
    r = RequestRouter(shards=2, done_ttl=0.1)
    for i in range(6):
        assert r.submit(b"x", req_id=f"fin-{i}")[0]
    for rid, payload in _drain_all(r):
        assert r.complete(W, 0, rid, payload)
    for i in range(6):
        assert r.poll(f"fin-{i}")[0]
    time.sleep(0.15)
    r.gc_done()
    r.seal()
    assert r.finished()  # drained even though _done was GC'd


# ------------------------------------------------- replica-stats delta lane


def test_delta_tracker_serve_section():
    t = DeltaTracker(incarnation=0)
    rep = t.compose(1.0, serve_fields={"served": 10, "rejected": 1,
                                      "model_ms": 5.0,
                                      "batch_fill": 0.5})
    assert rep.has_serve and rep.serve_served == 10
    assert rep.serve_model_ms == 5.0
    t.commit(rep)
    # unchanged served count: the section is delta'd away
    rep2 = t.compose(2.0, serve_fields={"served": 10, "rejected": 1,
                                        "model_ms": 5.0,
                                        "batch_fill": 0.5})
    assert not rep2.has_serve
    # progress: the section rides again
    rep3 = t.compose(3.0, serve_fields={"served": 25, "rejected": 1,
                                        "model_ms": 6.0,
                                        "batch_fill": 0.9})
    assert rep3.has_serve and rep3.serve_served == 25


def test_serve_section_wire_roundtrip():
    rep = comm.NodeStatusReport(
        timestamp=1.0, has_serve=True, serve_served=7,
        serve_model_ms=2.5, serve_batch_fill=0.75,
    )
    back = comm.deserialize(rep.serialize())
    assert back.has_serve and back.serve_served == 7
    assert back.serve_model_ms == 2.5
    # defaults stay sparse: a serve-free report carries no serve keys
    bare = comm.NodeStatusReport(timestamp=1.0)
    assert b"serve" not in bare.serialize()


def test_note_replica_stats_feeds_router_stats():
    r = RequestRouter(shards=2)
    r.note_replica_stats(W, 0, 0, {"served": 40, "rejected": 2,
                                   "model_ms": 3.0, "batch_fill": 0.8})
    r.note_replica_stats(W, 1, 0, {"served": 60, "rejected": 0,
                                   "model_ms": 4.0, "batch_fill": 0.9})
    stats = r.stats()
    assert stats["replicas_reporting"] == 2
    assert stats["replica_served"] == 100
    # the wire mirror holds every key (rpc_serve_stats does **stats)
    comm.ServeStats(**stats)


def test_worker_serve_fields_tracks_model_time():
    class _Client:
        def serve_complete(self, req_id, payload):
            return True

    w = ServingWorker(_Client(), lambda p, s: [b"r" for _ in p],
                      batch_size=4, exit_fn=lambda rc: None)
    w._process([("a", b"x"), ("b", b"y")])
    fields = w.serve_fields()
    assert fields["served"] == 2
    assert fields["model_ms"] >= 0.0
    assert 0.0 < fields["batch_fill"] <= 1.0


# --------------------------------------------------------- SLO autoscaler


def test_autoscaler_serving_share_rides_events(journal):
    calls = []
    held = {"submitted": 50, "queue_depth": 0, "p99_ms": 5000.0,
            "queue_wait_p99_ms": 40.0, "model_time_p99_ms": 4900.0,
            "workers": 2, "in_flight": 2, "sealed": False}
    s = ServingAutoScaler(
        stats_fn=lambda: held, scale_fn=calls.append,
        min_replicas=1, max_replicas=4, queue_high=10,
        p99_high_ms=1000.0, goodput_fn=lambda: 0.83,
    )
    assert s.evaluate() is None
    ev = journal.events("serve.autoscale_held")[-1]["data"]
    assert ev["serving_share"] == 0.83


def test_autoscaler_low_serving_share_opens_scale_down(journal):
    """The p99 window is sticky: a long-gone burst must not pin an
    idle pool at max size. A near-zero goodput serving share opens the
    idle path even with stale-high p99."""
    calls = []
    stale = {"submitted": 500, "queue_depth": 0, "p99_ms": 5000.0,
             "workers": 3, "in_flight": 0, "sealed": False}
    s = ServingAutoScaler(
        stats_fn=lambda: stale, scale_fn=calls.append,
        min_replicas=1, max_replicas=4, queue_high=10,
        p99_high_ms=1000.0, goodput_fn=lambda: 0.02,
    )
    # max_replicas guard: p99 is over budget but nothing is queued or
    # in flight and the pool is idle per the ledger -> shed one
    assert s.evaluate() == 2
    assert calls == [2]
    ev = journal.events("serve.autoscale")[-1]["data"]
    assert ev["reason"] == "idle" and ev["serving_share"] == 0.02
    # without the ledger feed, the sticky p99 pins the pool (legacy)
    s2 = ServingAutoScaler(
        stats_fn=lambda: dict(stale), scale_fn=calls.append,
        min_replicas=1, max_replicas=4, queue_high=10,
        p99_high_ms=1000.0,
    )
    assert s2.evaluate() == 4  # scales UP on the stale p99 instead


# --------------------------------------------------------------- benchmark


def test_serve_soak_smoke():
    """The chaos soak's tier-1 smoke tier (ISSUE 20): >=10k requests
    through 2 router shards and real ServingWorker replicas, one
    SIGKILL-style replica death mid-lease, exactly-once asserted
    id-by-id, p99 bounded — the full acceptance pipeline at 1% scale."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               DLROVER_TPU_METRICS_PORT="off")
    out = subprocess.run(
        [sys.executable,
         os.path.join(repo, "benchmarks", "serve_soak.py"),
         "--smoke"],
        capture_output=True, text=True, timeout=240, env=env,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    result = json.loads(out.stdout.strip().splitlines()[-1])
    assert result["ok"] is True
    assert result["exactly_once"] is True
    assert result["requests"] >= 10_000
    assert result["answered"] == result["requests"]
    assert result["dropped"] == 0
    assert result["shards"] == 2
    assert result["kills"] == 1
    assert result["redelivered"] >= 1
    assert all(result["checks"].values()), result["checks"]
