"""Brain service-hood (VERDICT r3 Missing #2 / item #3): a standalone
process owning a schema-versioned datastore behind a REST surface, the
same algorithm library answering on both deployments, cross-JOB
learning (sibling provisioning, cluster-wide node blacklist), and the
master wiring (brain_addr beats brain_store_path, in-process fallback
kept)."""

import time

import pytest

from dlrover_tpu.brain import algorithms
from dlrover_tpu.brain.client import (
    BrainClient,
    RemoteBrainClient,
    build_brain_client,
)
from dlrover_tpu.brain.service import (
    SCHEMA_KEY,
    SCHEMA_VERSION,
    BrainService,
)
from dlrover_tpu.common.node import NodeResource
from dlrover_tpu.master.stats.reporter import JobMeta
from dlrover_tpu.util.state_store import FileStore


@pytest.fixture()
def service(tmp_path):
    svc = BrainService(FileStore(str(tmp_path / "brain")))
    svc.start()
    yield svc
    svc.stop()


def _remote(service) -> RemoteBrainClient:
    return RemoteBrainClient(service.addr, timeout=5, retries=2)


def _archive_run(client, job, uuid, worker_speeds, mem_curve=()):
    meta = JobMeta(uuid=uuid, name=job)
    client.report_job_meta(meta)
    for i, (workers, speed) in enumerate(worker_speeds):
        client.append_doc(job, uuid, "runtime", {
            "worker_num": workers, "global_step": 10 * (i + 1),
            "speed": speed, "timestamp": time.time(),
            "max_used_memory_mb": (
                mem_curve[i] if i < len(mem_curve) else 0
            ),
        })


def test_round_trip_and_404(service):
    remote = _remote(service)
    remote.put_doc("jobA", "run1", "meta", {"x": 1})
    assert remote.get_doc("jobA", "run1", "meta") == {"x": 1}
    assert remote.get_doc("jobA", "run1", "missing", "dflt") == "dflt"
    remote.append_doc("jobA", "run1", "runtime", {"speed": 1.0})
    remote.append_doc("jobA", "run1", "runtime", {"speed": 2.0})
    assert [s["speed"] for s in remote.get_runtime_stats(
        "jobA", "run1"
    )] == [1.0, 2.0]
    assert remote.get_job_runs("jobA") == ["run1"]
    assert remote.get_job_names() == ["jobA"]


def test_job2_provisions_from_job1_archive_via_service(service):
    """The e2e criterion: master 1 archives through the service; a
    SECOND master (fresh process state, only the service address)
    warm-starts its worker count and memory plan from that archive."""
    from dlrover_tpu.master.resource.local_optimizer import (
        TPULocalOptimizer,
    )
    from dlrover_tpu.scheduler.job_spec import JobArgs

    # job run 1 measured 4 workers clearly faster than 8 (throughput
    # plateau), and an upward memory trend
    _archive_run(
        _remote(service), "bert-ctr", "run-1",
        [(4, 5.0), (4, 5.2), (8, 3.0), (8, 3.1)],
        mem_curve=[8000, 9000, 10000, 11000],
    )

    # "job 2": a brand-new master process — all it shares is brain_addr
    job_args = JobArgs(
        job_name="bert-ctr", node_num=8, min_node_num=2, node_unit=2,
        brain_addr=service.addr,
    )
    client2 = build_brain_client(job_args.brain_addr)
    assert isinstance(client2, RemoteBrainClient)
    opt = TPULocalOptimizer(
        job_args=job_args, node_unit=2, brain_client=client2
    )
    plan = opt.init_job_resource()
    group = plan.node_group_resources["worker"]
    # warm start shrinks toward the historically fastest count (the
    # spec stays the ceiling — history never grows past it)
    assert group.count == 4
    assert group.node_resource.memory >= 11000  # trend + margin


def test_sibling_job_resource_plan(service):
    """A job with NO history of its own provisions from a sibling in
    the same family (optimize_job_worker_create_resource.go role)."""
    remote = _remote(service)
    _archive_run(
        remote, "llama7b-20260730", "run-1",
        [(4, 1.0)] * 4, mem_curve=[4000, 4500, 5000, 5500],
    )
    resp = remote._rest.request(
        "GET", "api/v1/optimize/llama7b-20260731/resource?memory=1000"
    )
    assert resp["source"] == "sibling_jobs"
    assert resp["memory"] >= 5500
    # unrelated family gets nothing
    assert remote._rest.request(
        "GET", "api/v1/optimize/gpt-oss/resource"
    ) == {}


def test_cluster_blacklist_across_jobs(service):
    """One bad probe in one job is noise; the same host degrading two
    different jobs is a hardware problem."""
    remote = _remote(service)
    remote.report_node_event("host-7", "straggler", job_name="job-a")
    assert remote.get_node_blacklist() == []  # one incident: not yet
    remote.report_node_event("host-7", "straggler", job_name="job-b")
    remote.report_node_event("host-3", "oom", job_name="job-a")
    assert remote.get_node_blacklist() == ["host-7"]
    # repeated samples of the SAME (job, kind) incident count once
    remote.report_node_event("host-3", "oom", job_name="job-a")
    assert remote.get_node_blacklist() == ["host-7"]


def test_blacklist_window_expiry():
    now = time.time()
    events = [
        {"host": "h", "kind": "straggler", "job_name": "a",
         "timestamp": now - 10},
        {"host": "h", "kind": "straggler", "job_name": "b",
         "timestamp": now - 7 * 3600},  # outside the 6h window
    ]
    assert algorithms.node_blacklist(events, now=now) == []
    events[1]["timestamp"] = now - 60
    assert algorithms.node_blacklist(events, now=now) == ["h"]


def test_job_family_normalization():
    assert algorithms.job_family("llama7b-20260731") == "llama7b"
    assert algorithms.job_family("llama7b-run3") == "llama7b"
    assert algorithms.job_family("job-try2-20260731") == "job"
    assert algorithms.job_family("bert-ctr") == "bert-ctr"
    # short trailing numbers encode the MODEL, not the run: kept
    # (review fix: llama-7 must never inherit llama-70's memory plan)
    assert algorithms.job_family("llama-7") == "llama-7"
    assert algorithms.job_family("llama-70") == "llama-70"
    assert algorithms.job_family("resnet-50") == "resnet-50"
    assert algorithms.job_family("123456789") == "123456789"  # never empties


def test_schema_version_guard(tmp_path):
    store = FileStore(str(tmp_path / "brain"))
    store.set(SCHEMA_KEY, {"version": SCHEMA_VERSION + 1})
    with pytest.raises(RuntimeError, match="newer"):
        BrainService(store)
    # a fresh store gets stamped
    store2 = FileStore(str(tmp_path / "brain2"))
    svc = BrainService(store2)
    assert store2.get(SCHEMA_KEY)["version"] == SCHEMA_VERSION
    svc._server.server_close()


def test_malformed_requests_rejected(service):
    from dlrover_tpu.scheduler.rest import RestError

    remote = _remote(service)
    with pytest.raises(RestError):
        remote._rest.request("POST", "api/v1/archive", {
            "job_name": "../escape", "uuid": "u", "kind": "k",
            "doc": {},
        })
    with pytest.raises(RestError):
        remote._rest.request("POST", "api/v1/events", {"host": ""})


def test_in_process_fallback_kept(tmp_path):
    client = build_brain_client("", str(tmp_path / "archive"))
    assert isinstance(client, BrainClient)
    assert not isinstance(client, RemoteBrainClient)
    assert build_brain_client("", "") is None


def test_master_cli_carries_brain_addr(tmp_path):
    from dlrover_tpu.master.args import parse_master_args
    from dlrover_tpu.master.main import build_job_args

    args = parse_master_args([
        "--job_name", "j", "--brain_addr", "1.2.3.4:8600",
    ])
    job_args = build_job_args(args)
    assert job_args.brain_addr == "1.2.3.4:8600"


def test_failure_exits_feed_node_events(service):
    """The job manager's failure policy reports exits into the brain's
    cluster log through the optimizer seam."""
    from dlrover_tpu.master.resource.local_optimizer import (
        TPULocalOptimizer,
    )
    from dlrover_tpu.scheduler.job_spec import JobArgs

    remote = _remote(service)
    opt = TPULocalOptimizer(
        job_args=JobArgs(job_name="j1"), brain_client=remote
    )
    opt.report_node_event("worker-0", "oom")
    events = remote.get_node_events()
    assert events and events[-1]["host"] == "worker-0"
    assert events[-1]["job_name"] == "j1"


def test_standalone_process_cli(tmp_path):
    """Service-hood proper: a separate PROCESS serving the store."""
    import json
    import subprocess
    import sys
    import urllib.request

    from dlrover_tpu.common.grpc_utils import find_free_port

    port = find_free_port()
    proc = subprocess.Popen([
        sys.executable, "-m", "dlrover_tpu.brain.service",
        "--host", "127.0.0.1", "--port", str(port),
        "--store_path", str(tmp_path / "store"),
    ], stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    try:
        deadline = time.time() + 30
        last = None
        while time.time() < deadline:
            try:
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/healthz", timeout=2
                ) as resp:
                    doc = json.loads(resp.read())
                assert doc["ok"] and doc["schema_version"] == 1
                break
            except Exception as e:
                last = e
                time.sleep(0.3)
        else:
            raise AssertionError(f"service never came up: {last}")
        remote = RemoteBrainClient(f"127.0.0.1:{port}", timeout=5)
        remote.put_doc("j", "r", "meta", {"ok": 1})
        assert remote.get_doc("j", "r", "meta") == {"ok": 1}
    finally:
        proc.terminate()
        proc.wait(timeout=10)


def test_remote_client_plans_server_side(service):
    """Review fix: the remote client answers optimize queries with ONE
    service call instead of paging every sibling's runs over REST."""
    remote = _remote(service)
    _archive_run(remote, "fam-run1", "r1", [(4, 2.0)] * 3,
                 mem_curve=[1000, 1100, 1200])
    # count wire requests of a FRESH client during plan_resource
    probe = _remote(service)
    calls = []
    orig = probe._rest.request

    def counting(method, path, body=None):
        calls.append(path)
        return orig(method, path, body)

    probe._rest.request = counting
    planned, source = probe.plan_resource("fam-run2")
    assert planned is not None and source == "sibling_jobs"
    assert len(calls) == 1 and "optimize/fam-run2/resource" in calls[0]
    plan = probe.get_optimization_plan("fam-run1")
    assert plan is not None and plan.worker_num == 4
    assert len(calls) == 2 and "optimize/fam-run1/plan" in calls[1]


def test_event_timestamp_validated_and_tolerated(service):
    """Review fix: a poisoned timestamp is rejected at the service
    boundary, and node_blacklist skips (not crashes on) bad entries."""
    from dlrover_tpu.scheduler.rest import RestError

    remote = _remote(service)
    with pytest.raises(RestError):
        remote._rest.request("POST", "api/v1/events", {
            "host": "h", "kind": "straggler", "timestamp": "yesterday",
        })
    assert algorithms.node_blacklist([
        {"host": "h", "kind": "s", "job_name": "a",
         "timestamp": "garbage"},
        {"host": "h", "kind": "s", "job_name": "b",
         "timestamp": time.time()},
    ]) == []


def test_file_store_mutate_survives_concurrent_processes(tmp_path):
    """Review fix: two masters appending to the shared file archive
    must not lose each other's entries (fcntl-locked mutate)."""
    import subprocess
    import sys

    root = str(tmp_path / "store")
    script = (
        "import sys\n"
        "from dlrover_tpu.util.state_store import FileStore\n"
        f"store = FileStore({root!r})\n"
        "for i in range(50):\n"
        "    store.mutate('events',"
        " lambda v: v + [sys.argv[1]], default=[])\n"
    )
    procs = [
        subprocess.Popen([sys.executable, "-c", script, name])
        for name in ("a", "b")
    ]
    for p in procs:
        assert p.wait(timeout=60) == 0
    events = FileStore(root).get("events")
    assert len(events) == 100
    assert events.count("a") == 50 and events.count("b") == 50


def test_brain_reporter_survives_dead_service():
    """Review fix: an unreachable Brain must not crash master startup."""
    from dlrover_tpu.brain.client import BrainReporter

    dead = RemoteBrainClient("127.0.0.1:1", timeout=1, retries=1)
    reporter = BrainReporter(
        JobMeta(uuid="u", name="j"), client=dead
    )  # must not raise
    assert reporter is not None


def test_single_job_cannot_blacklist_a_host():
    """Review fix: two event KINDS from ONE job (its own data skew +
    its own OOM) must not blacklist a healthy host; distinct JOBS are
    the incident unit."""
    now = time.time()
    events = [
        {"host": "h", "kind": "straggler", "job_name": "solo",
         "timestamp": now},
        {"host": "h", "kind": "oom", "job_name": "solo",
         "timestamp": now},
    ]
    assert algorithms.node_blacklist(events, now=now) == []
    events.append({"host": "h", "kind": "oom", "job_name": "other",
                   "timestamp": now})
    assert algorithms.node_blacklist(events, now=now) == ["h"]


def test_malformed_query_is_400_not_500(service):
    """ADVICE r4: client input errors (bad query value) must map to
    400, not a stack-traced 500 — the two are indistinguishable in
    incident triage otherwise."""
    from dlrover_tpu.scheduler.rest import RestError

    remote = _remote(service)
    with pytest.raises(RestError) as ei:
        remote._rest.request(
            "GET", "api/v1/blacklist?window_seconds=abc"
        )
    assert ei.value.status == 400


def test_shared_token_auth(tmp_path):
    """ADVICE r4: the optional shared-secret check. Without the right
    bearer token every endpoint except /healthz answers 401; with it
    (RemoteBrainClient token=) everything works."""
    from dlrover_tpu.scheduler.rest import RestError

    svc = BrainService(
        FileStore(str(tmp_path / "brain")), token="s3cret"
    )
    svc.start()
    try:
        anon = RemoteBrainClient(svc.addr, timeout=5, retries=1)
        # liveness probes stay open (they carry no secrets)
        assert anon._rest.request("GET", "healthz")["ok"] is True
        with pytest.raises(RestError) as ei:
            anon._rest.request("GET", "api/v1/jobs")
        assert ei.value.status == 401
        with pytest.raises(RestError) as ei:
            anon._rest.request(
                "POST", "api/v1/events",
                {"host": "h", "kind": "oom", "job_name": "j"},
            )
        assert ei.value.status == 401

        authed = RemoteBrainClient(
            svc.addr, timeout=5, retries=1, token="s3cret"
        )
        authed.put_doc("jobA", "run1", "meta", {"x": 1})
        assert authed.get_doc("jobA", "run1", "meta") == {"x": 1}
    finally:
        svc.stop()


def test_token_from_env_reaches_in_framework_clients(
    tmp_path, monkeypatch
):
    """Review fix: build_brain_client (the path dist_master actually
    uses) must pick the shared secret up from the env, or enabling
    --token_file would 401 every in-framework client."""
    svc = BrainService(
        FileStore(str(tmp_path / "brain")), token="s3cret"
    )
    svc.start()
    try:
        tok_file = tmp_path / "tok"
        tok_file.write_text("s3cret\n")
        monkeypatch.setenv(
            "DLROVER_TPU_BRAIN_TOKEN_FILE", str(tok_file)
        )
        client = build_brain_client(svc.addr)
        client.put_doc("jobZ", "run1", "meta", {"ok": 1})
        assert client.get_doc("jobZ", "run1", "meta") == {"ok": 1}
    finally:
        svc.stop()
