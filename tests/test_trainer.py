"""Tests for ElasticTrainer, sampler, and flash checkpoint."""

import os
import tempfile

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from dlrover_tpu.trainer.checkpoint import FlashCheckpointer
from dlrover_tpu.trainer.elastic import (
    ElasticTrainer,
    compute_accum_steps,
    make_elastic_train_step,
)
from dlrover_tpu.trainer.sampler import ElasticDistributedSampler


def test_compute_accum_steps():
    assert compute_accum_steps(4, 4) == 1
    assert compute_accum_steps(4, 2) == 2
    assert compute_accum_steps(4, 3) == 2  # ceil
    assert compute_accum_steps(4, 1) == 4
    assert compute_accum_steps(1, 1) == 1


def _loss_fn(params, batch):
    x, y = batch
    pred = x @ params["w"] + params["b"]
    return jnp.mean((pred - y) ** 2)


def test_elastic_train_step_matches_large_batch():
    """accum over k microbatches == one step on the concatenated batch."""
    rng = np.random.RandomState(0)
    x = rng.randn(8, 4).astype(np.float32)
    y = rng.randn(8, 1).astype(np.float32)
    params = {
        "w": jnp.zeros((4, 1)),
        "b": jnp.zeros((1,)),
    }
    opt = optax.sgd(0.1)

    def fresh():
        p = jax.tree.map(jnp.copy, params)
        return p, opt.init(p)

    # one step, full batch (donated inputs -> use fresh copies per call)
    step1 = make_elastic_train_step(_loss_fn, opt, accum_steps=1)
    p, s = fresh()
    p1, _, loss1 = step1(p, s, (x[None], y[None]))

    # 4 microbatches of 2
    step4 = make_elastic_train_step(_loss_fn, opt, accum_steps=4)
    xs = x.reshape(4, 2, 4)
    ys = y.reshape(4, 2, 1)
    p, s = fresh()
    p4, _, loss4 = step4(p, s, (xs, ys))

    np.testing.assert_allclose(
        np.asarray(p1["w"]), np.asarray(p4["w"]), rtol=1e-5
    )
    np.testing.assert_allclose(float(loss1), float(loss4), rtol=1e-5)


def test_elastic_trainer_world_change_caches_steps():
    opt = optax.sgd(0.1)
    trainer = ElasticTrainer(_loss_fn, opt, max_nodes=4, cur_nodes=4)
    assert trainer.accum_steps == 1
    s1 = trainer.train_step
    trainer.set_world(2)
    assert trainer.accum_steps == 2
    s2 = trainer.train_step
    assert s1 is not s2
    trainer.set_world(4)
    assert trainer.train_step is s1  # cached


def test_microbatch_split():
    opt = optax.sgd(0.1)
    trainer = ElasticTrainer(_loss_fn, opt, max_nodes=2, cur_nodes=1)
    batch = {"x": np.zeros((8, 3))}
    mb = trainer.microbatch(batch)
    assert mb["x"].shape == (2, 4, 3)


# ------------------------------------------------------------------ sampler


def test_sampler_partition_and_padding():
    s = ElasticDistributedSampler(10, num_replicas=3, rank=0, shuffle=False)
    idx = list(s)
    assert len(idx) == 4  # ceil(10/3) with padding
    all_ranks = []
    for r in range(3):
        sr = ElasticDistributedSampler(10, 3, r, shuffle=False)
        all_ranks.extend(list(sr))
    assert set(all_ranks) == set(range(10))


def test_sampler_resume_after_world_change():
    s = ElasticDistributedSampler(100, num_replicas=4, rank=0,
                                  shuffle=False)
    it = iter(s)
    for _ in range(10):
        next(it)
    state = s.state_dict()
    assert state["completed_num"] == 40  # 10 yields x 4 replicas

    # resume into 2 replicas
    s2 = ElasticDistributedSampler(100, num_replicas=2, rank=0,
                                   shuffle=False)
    s2.load_state_dict(state, num_replicas=2, rank=0)
    remaining = list(s2)
    assert len(remaining) == 30  # (100-40)/2
    # first unconsumed sample is 40
    assert remaining[0] == 40


def test_sampler_set_world_shrink_exactly_once():
    """4 -> 3 shrink mid-epoch: indices consumed before the resize plus
    indices consumed by the shrunken world cover the dataset exactly
    once (the reshard ledger-rebalance contract)."""
    seen = []
    old = [ElasticDistributedSampler(24, 4, r, shuffle=False)
           for r in range(4)]
    for s in old:
        batch = next(s.iter_batches(3))  # one in-flight batch per rank
        seen.extend(batch.tolist())
    state = old[0].state_dict()
    assert state["completed_num"] == 12
    for r in range(3):
        s = ElasticDistributedSampler(24, 3, r, shuffle=False)
        s.load_state_dict(state, num_replicas=3, rank=r)
        for batch in s.iter_batches(3):
            seen.extend(batch.tolist())
    assert sorted(seen) == list(range(24))


def test_sampler_live_iterator_keeps_old_stride_across_set_world():
    """A set_world during iteration must not advance completed_num at
    the NEW stride for indices partitioned under the OLD world — that
    would mark unconsumed peers' samples complete (shrink) or replay
    consumed ones (grow)."""
    s = ElasticDistributedSampler(40, 4, 0, shuffle=False)
    batches = s.iter_batches(2)
    next(batches)
    assert s.completed_num == 8  # 2 indices x old stride 4
    s.set_world(2, 0)
    next(batches)  # same live iterator: old-geometry indices
    assert s.completed_num == 16  # still counted at stride 4
    # a FRESH iterator partitions the remainder under the new world
    fresh = np.concatenate(list(s.iter_batches(100)))
    assert fresh[0] == 16 and fresh.size == (40 - 16) // 2


def test_sampler_grow_past_remaining_pads_every_rank():
    """Grow to more replicas than remaining samples: the pad is
    shorter than the shortfall, so it must REPEAT — a short pad hands
    some ranks fewer indices than others and the lockstep collective
    stalls forever."""
    counts = []
    for r in range(4):
        s = ElasticDistributedSampler(24, 4, r, shuffle=False)
        s.load_state_dict({"epoch": 0, "completed_num": 23})
        idx = list(s)
        counts.append(len(idx))
        assert idx == [23]  # the one remaining sample, on every rank
    assert counts == [1, 1, 1, 1]


def test_sampler_world_change_after_epoch_end_stays_empty():
    """Padding overshoots completed_num past dataset_size at epoch
    end; a set_world then must see an empty remainder, not a negative
    one."""
    s = ElasticDistributedSampler(10, 3, 0, shuffle=False,
                                  drop_last=True)
    s.load_state_dict({"epoch": 0, "completed_num": 12})
    s.set_world(4, 1)
    assert len(s) == 0
    assert list(s) == []


def test_sampler_load_state_rejects_out_of_range_rank():
    s = ElasticDistributedSampler(10, 4, 3, shuffle=False)
    with pytest.raises(ValueError):
        # shrink to 2 replicas while keeping rank 3: the partition
        # would silently alias a live rank's indices
        s.load_state_dict({"epoch": 0, "completed_num": 0},
                          num_replicas=2)


def test_sampler_shuffle_is_epoch_deterministic():
    a = ElasticDistributedSampler(20, 2, 0, shuffle=True, seed=7)
    b = ElasticDistributedSampler(20, 2, 0, shuffle=True, seed=7)
    assert list(a) == list(b)
    a.set_epoch(1)
    b.set_epoch(0)
    assert list(a) != list(b)


# --------------------------------------------------------------- checkpoint


def _sharded_state():
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devs = np.array(jax.devices()[:4]).reshape(4)
    mesh = Mesh(devs, ("d",))
    w = jnp.arange(16.0).reshape(8, 2)
    sharded = jax.device_put(w, NamedSharding(mesh, P("d", None)))
    return {"w": sharded, "step": jnp.array(3)}


def test_flash_checkpoint_ram_roundtrip():
    with tempfile.TemporaryDirectory() as tmp:
        ckpt = FlashCheckpointer(
            persist_dir=os.path.join(tmp, "persist"),
            ram_dir=os.path.join(tmp, "ram"),
            persist_interval=0,  # RAM only
            use_orbax=False,
        )
        state = _sharded_state()
        ckpt.save(7, state)
        restored, step = ckpt.restore(target=state)
        assert step == 7
        np.testing.assert_array_equal(
            np.asarray(restored["w"]), np.asarray(state["w"])
        )
        assert restored["w"].sharding == state["w"].sharding


def test_flash_checkpoint_restore_after_resharding():
    """RAM snapshot taken on a 4-way mesh restores onto a 2-way mesh
    (the mesh-reformation path after losing hosts)."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    with tempfile.TemporaryDirectory() as tmp:
        ckpt = FlashCheckpointer(
            persist_dir=os.path.join(tmp, "p"),
            ram_dir=os.path.join(tmp, "r"),
            persist_interval=0, use_orbax=False,
        )
        state = _sharded_state()
        ckpt.save(5, state)

        mesh2 = Mesh(np.array(jax.devices()[:2]), ("d",))
        target = {
            "w": jax.device_put(
                jnp.zeros((8, 2)), NamedSharding(mesh2, P("d", None))
            ),
            "step": jnp.array(0),
        }
        restored, step = ckpt.restore(target=target)
        assert step == 5
        np.testing.assert_array_equal(
            np.asarray(restored["w"]), np.arange(16.0).reshape(8, 2)
        )
        assert restored["w"].sharding == target["w"].sharding


def test_flash_checkpoint_persistent_tier_orbax():
    with tempfile.TemporaryDirectory() as tmp:
        ckpt = FlashCheckpointer(
            persist_dir=os.path.join(tmp, "persist"),
            ram_dir=os.path.join(tmp, "ram"),
            persist_interval=1, use_orbax=True,
        )
        state = {"w": jnp.ones((4, 4)), "n": jnp.array(1)}
        ckpt.save(1, state, force_persist=True)
        ckpt.wait()
        # wipe RAM tier to force persistent restore
        for f in os.listdir(ckpt.ram_dir):
            os.remove(os.path.join(ckpt.ram_dir, f))
        restored, step = ckpt.restore(target=state)
        assert step == 1
        np.testing.assert_array_equal(
            np.asarray(restored["w"]), np.ones((4, 4))
        )
        ckpt.close()


def test_flash_checkpoint_keeps_max_ram():
    with tempfile.TemporaryDirectory() as tmp:
        ckpt = FlashCheckpointer(
            persist_dir=os.path.join(tmp, "p"),
            ram_dir=os.path.join(tmp, "r"),
            persist_interval=0, max_ram_keep=2, use_orbax=False,
        )
        state = {"x": jnp.zeros(2)}
        for s in range(5):
            ckpt.save(s, state)
        steps = [s for s, _ in ckpt._list_ram()]
        assert steps == [3, 4]
