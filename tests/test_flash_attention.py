"""Flash-attention kernel correctness vs the XLA reference.

Runs the Pallas kernels in interpret mode on CPU (the reference's CUDA
flash-attn tests are GPU-gated; interpret mode gives us full coverage
without a TPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlrover_tpu.ops.attention import mha_reference
from dlrover_tpu.ops.pallas.flash_attention import flash_attention_tpu


def _rand_qkv(key, b, s, h, kvh, d, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, s, h, d), dtype)
    k = jax.random.normal(kk, (b, s, kvh, d), dtype)
    v = jax.random.normal(kv, (b, s, kvh, d), dtype)
    return q, k, v


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("blocks", [(128, 128), (256, 128)])
def test_forward_matches_reference(causal, blocks):
    bq, bk = blocks
    q, k, v = _rand_qkv(jax.random.key(0), 2, 256, 4, 4, 64)
    out = flash_attention_tpu(q, k, v, causal=causal,
                              block_q=bq, block_k=bk)
    ref = mha_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(out, ref, rtol=2e-3, atol=2e-3)


def test_forward_gqa():
    q, k, v = _rand_qkv(jax.random.key(1), 2, 256, 8, 2, 64)
    out = flash_attention_tpu(q, k, v, causal=True,
                              block_q=128, block_k=128)
    ref = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(out, ref, rtol=2e-3, atol=2e-3)


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("bq,bk", [(128, 128), (64, 128), (128, 64)])
def test_gradients_match_reference(causal, bq, bk):
    # mixed blocks lock in the backward kernels' causal index-clamp
    # math ((j*bk)//bq and (i*bq+bq-1)//bk), which degenerates to the
    # trivial case at bq == bk
    q, k, v = _rand_qkv(jax.random.key(2), 1, 256, 2, 2, 64)

    def loss_flash(q, k, v):
        return jnp.sum(
            flash_attention_tpu(
                q, k, v, causal=causal, block_q=bq, block_k=bk
            ) ** 2
        )

    def loss_ref(q, k, v):
        return jnp.sum(mha_reference(q, k, v, causal=causal) ** 2)

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(
            gf, gr, rtol=5e-3, atol=5e-3,
            err_msg=f"d{name} mismatch",
        )


def test_gradients_gqa():
    q, k, v = _rand_qkv(jax.random.key(3), 1, 128, 4, 2, 64)

    def loss(attn):
        def f(q, k, v):
            return jnp.sum(attn(q, k, v) ** 2)
        return f

    flash = lambda q, k, v: flash_attention_tpu(  # noqa: E731
        q, k, v, causal=True, block_q=128, block_k=128
    )
    ref = lambda q, k, v: mha_reference(q, k, v, causal=True)  # noqa: E731
    g_flash = jax.grad(loss(flash), argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss(ref), argnums=(0, 1, 2))(q, k, v)
    for gf, gr, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(
            gf, gr, rtol=5e-3, atol=5e-3, err_msg=f"d{name} mismatch"
        )


def test_bf16_forward_close():
    q, k, v = _rand_qkv(jax.random.key(4), 1, 256, 2, 2, 64,
                        dtype=jnp.bfloat16)
    out = flash_attention_tpu(q, k, v, causal=True,
                              block_q=128, block_k=128)
    ref = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(
        out.astype(np.float32), ref.astype(np.float32),
        rtol=5e-2, atol=5e-2,
    )
