"""IndexShardingClient stop/exhaustion/failure semantics.

Regression tests: stop() must not deadlock on a full queue; a prefetch
RPC failure must surface as ``failed``, not as clean exhaustion.
"""

import threading
import time

import pytest

from dlrover_tpu.agent.sharding.client import IndexShardingClient
from dlrover_tpu.common.constants import TaskType
from dlrover_tpu.master.local_master import LocalJobMaster
from dlrover_tpu.agent.master_client import MasterClient


@pytest.fixture
def master():
    m = LocalJobMaster(port=0)
    m.prepare()
    yield m
    m.stop()


def _client(master, **kw):
    # short reconnect deadline: the failure test below kills the master
    # for good, and the point is the POST-deadline semantics (failed,
    # not exhausted) — not riding out a 10-minute production outage
    mc = MasterClient(master.addr, node_id=0, node_type="worker",
                      reconnect_timeout=2.0)
    mc._supervisor._backoff_cap = 0.2
    kw.setdefault("batch_size", 4)
    kw.setdefault("dataset_size", 10_000)
    kw.setdefault("num_minibatches_per_shard", 1)
    return IndexShardingClient("stop-ds", master_client=mc, **kw)


def test_stop_with_full_queue_does_not_deadlock(master):
    client = _client(master)
    # let the prefetch thread fill the bounded queue and block in put
    time.sleep(0.3)
    assert client._sample_queue.full()
    done = threading.Event()

    def stopper():
        client.stop()
        done.set()

    t = threading.Thread(target=stopper, daemon=True)
    t.start()
    assert done.wait(timeout=2.0), "stop() deadlocked on the full queue"
    # consumers unblock (drain then None) instead of hanging
    deadline = time.time() + 5.0
    while time.time() < deadline:
        if client.fetch_sample_index() is None:
            break
    else:
        pytest.fail("fetch_sample_index never returned None after stop()")
    assert not client.exhausted  # a stop is NOT dataset exhaustion
    assert not client.failed


def test_exhaustion_is_clean_end(master):
    client = _client(master, dataset_size=12, batch_size=4)
    seen = []
    while True:
        idx = client.fetch_sample_index()
        if idx is None:
            break
        seen.append(idx)
    assert sorted(seen) == list(range(12))
    assert client.exhausted
    assert not client.failed


def test_rpc_failure_reports_failed_not_exhausted(master):
    client = _client(master)
    time.sleep(0.1)
    # kill the master mid-iteration: the prefetch RPC will error out
    master.stop()
    # drain; the client must eventually signal the end of iteration
    deadline = time.time() + 30.0
    while time.time() < deadline:
        if client.fetch_sample_index() is None:
            break
    else:
        pytest.fail("iteration never ended after master death")
    assert client.failed
    assert not client.exhausted
