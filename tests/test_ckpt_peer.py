"""Peer shard tier: /ckpt/shard endpoint, KV registry, kill-a-host drill.

A survivor's RAM-tier archive is reachable over the telemetry server's
``/ckpt/shard`` route, advertised through the master KV store; a
relaunched host with a dead tmpfs AND an unreachable object store must
still reassemble the step from peers, digest-verified.
"""

import json
import shutil
import urllib.error
import urllib.request

import jax
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dlrover_tpu import telemetry as T
from dlrover_tpu.agent.master_client import LocalMasterClient
from dlrover_tpu.checkpoint import manifest as mf
from dlrover_tpu.checkpoint import peer
from dlrover_tpu.telemetry.http import MetricsServer
from dlrover_tpu.telemetry.journal import EventJournal
from dlrover_tpu.trainer.checkpoint import FlashCheckpointer

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 virtual devices"
)


@pytest.fixture(autouse=True)
def fresh_defaults():
    T.set_default_registry(None)
    T.set_default_journal(EventJournal(None))
    yield
    T.set_default_registry(None)
    T.set_default_journal(EventJournal(None))


def events(kind):
    return T.default_journal().events(kind)


class _BrokenStore:
    """The object store is off the network: every call raises."""

    def __getattr__(self, name):
        def boom(*a, **k):
            raise OSError("store unreachable")

        return boom


def _checkpointer(tmp_path, p, n, devs_per_proc):
    return FlashCheckpointer(
        persist_dir=str(tmp_path / "store"),
        ram_dir=str(tmp_path / f"ram{p}"),
        persist_interval=0, use_orbax=False,
        process_index=p, n_processes=n,
        proc_of_device=lambda d: d.id // devs_per_proc,
    )


def _state(mesh):
    return {
        "w": jax.device_put(
            np.arange(64, dtype=np.float32).reshape(8, 8),
            NamedSharding(mesh, P(None, "tp")),
        ),
        "epoch": 2,
    }


# ----------------------------------------------------------- endpoint


def test_shard_endpoint_serves_manifest_and_members(tmp_path):
    devs = jax.devices()
    mesh = Mesh(np.array(devs).reshape(2, 4), ("dp", "tp"))
    state = _state(mesh)
    c = _checkpointer(tmp_path, 0, 2, 4)
    c.save(5, state)
    c.wait()
    srv = MetricsServer(
        port=0, shard_provider=c.shard_provider()
    ).start()
    base = f"http://127.0.0.1:{srv.port}"
    try:
        man = peer.fetch_manifest(base, 5)
        assert man["version"] == 2
        assert man["topology"]["process_index"] == 0
        locs = mf._piece_locations(man)
        assert locs  # this host holds members
        key = next(iter(locs))
        pkey, ikey = key.rsplit("|", 1)
        body = peer.fetch_shard(base, 5, pkey, ikey)
        assert body and body[:6] == b"\x93NUMPY"

        # misses and malformed queries
        assert peer.fetch_manifest(base, 999) is None  # not held: 404
        assert peer.fetch_shard(base, 5, pkey, "[[0,999]]") is None
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(base + "/ckpt/shard")  # no step
        assert e.value.code == 400
    finally:
        srv.stop()
        c.close()


def test_shard_endpoint_without_provider_404s(tmp_path):
    srv = MetricsServer(port=0).start()
    try:
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/ckpt/shard?step=1"
            )
        assert e.value.code == 404
    finally:
        srv.stop()


# ----------------------------------------------------------- registry


def test_peer_registry_over_local_master_client():
    """The registry against the real (masterless) client surface —
    exercises the kv_store_keys RPC the master grew for this."""
    kv = LocalMasterClient()
    a = peer.PeerRegistry(kv, 0, "http://host-a:8080")
    b = peer.PeerRegistry(kv, 1, "http://host-b:8080")
    a.advertise(7)
    b.advertise(7)
    b.advertise(9)
    assert a.peers(7) == {
        0: "http://host-a:8080", 1: "http://host-b:8080"
    }
    assert a.advertised_steps() == [7, 9]
    assert len(events("ckpt.peer_advertised")) == 3

    b.withdraw(7)
    assert a.peers(7) == {0: "http://host-a:8080"}
    a.withdraw(7)
    assert a.peers(7) == {}
    assert a.advertised_steps() == [9]


def test_peer_registry_tolerates_old_master():
    """A client predating kv_store_keys: discovery degrades to empty
    instead of raising."""

    class OldClient:
        def __init__(self):
            self.kv = {}

        def kv_store_set(self, k, v):
            self.kv[k] = v

        def kv_store_get(self, k):
            return self.kv.get(k, b"")

    reg = peer.PeerRegistry(OldClient(), 0, "http://a")
    reg.advertise(3)  # set works
    assert reg.peers(3) == {}  # no key scan available
    assert reg.advertised_steps() == []
    reg.withdraw(3)  # falls back to tombstone set


# ------------------------------------------------------ kill-host drill


def test_killed_host_restores_over_peer_tier(tmp_path):
    """The ISSUE peer-restore drill: two virtual hosts save to RAM
    only; host 0 loses its tmpfs and the object store, relaunches, and
    reassembles the step entirely over /ckpt/shard from host 1 —
    bit-identical, journaled, metered."""
    devs = jax.devices()
    mesh = Mesh(np.array(devs).reshape(2, 4), ("dp", "tp"))
    state = _state(mesh)
    want = np.asarray(state["w"])
    kv = LocalMasterClient()
    ckpts, servers = [], []
    for p in range(2):
        c = _checkpointer(tmp_path, p, 2, 4)
        srv = MetricsServer(
            port=0, shard_provider=c.shard_provider()
        ).start()
        c._peer_registry = peer.PeerRegistry(
            kv, p, f"http://127.0.0.1:{srv.port}"
        )
        ckpts.append(c)
        servers.append(srv)
    for c in ckpts:
        c.save(11, state)
        c.wait()
    assert kv.kv_store_keys("ckpt/peer/11/")  # advertised

    shutil.rmtree(tmp_path / "ram0")  # host 0's tmpfs dies with it
    r = _checkpointer(tmp_path, 0, 2, 4)
    r._store = _BrokenStore()
    r._peer_registry = peer.PeerRegistry(kv, 0, "http://127.0.0.1:1")
    target = {
        "w": jax.device_put(
            np.zeros((8, 8), np.float32),
            NamedSharding(mesh, P(None, "tp")),
        ),
        "epoch": -1,
    }
    got, step = r.restore(target=target, step=11)
    r.close()
    for c in ckpts:
        c.close()
    for s in servers:
        s.stop()

    assert step == 11
    assert np.array_equal(np.asarray(got["w"]), want)
    assert got["epoch"] == 2
    assert events("ckpt.peer_fetch"), "at least one shard over HTTP"
    assert events("ckpt.peer_served")
    tr = events("ckpt.topology_restore")[-1]
    assert tr["data"]["peer"] >= 1 and tr["data"]["store"] == 0

    reg = T.default_registry()
    assert reg.get("dlrover_ckpt_shard_bytes_total").labels(
        tier="peer"
    ).value > 0
    assert reg.get("dlrover_ckpt_peer_fetches_total").labels(
        result="ok"
    ).value >= 1


def test_auto_restore_discovers_step_from_peers(tmp_path):
    """Without an explicit step, peer advertisements contribute
    candidates — a host with nothing local and no store still finds
    and restores the fleet's last step (explicitly requested here via
    consensus over its own candidate set)."""
    devs = jax.devices()
    mesh = Mesh(np.array(devs).reshape(2, 4), ("dp", "tp"))
    state = _state(mesh)
    kv = LocalMasterClient()
    c = _checkpointer(tmp_path, 1, 2, 4)
    srv = MetricsServer(
        port=0, shard_provider=c.shard_provider()
    ).start()
    c._peer_registry = peer.PeerRegistry(
        kv, 1, f"http://127.0.0.1:{srv.port}"
    )
    c.save(13, state)
    c.wait()

    r = _checkpointer(tmp_path, 0, 2, 4)
    r._store = _BrokenStore()
    r._peer_registry = peer.PeerRegistry(kv, 0, "http://127.0.0.1:1")
    assert 13 in r._local_candidate_steps()
    target = {
        "w": jax.device_put(
            np.zeros((8, 8), np.float32),
            NamedSharding(mesh, P(None, "tp")),
        ),
        "epoch": -1,
    }
    got, step = r.restore(target=target)
    r.close()
    c.close()
    srv.stop()
    assert step == 13
    assert np.array_equal(np.asarray(got["w"]), np.asarray(state["w"]))
