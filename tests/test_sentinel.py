"""Silent-failure sentinel unit tests (drill coverage lives in
test_sentinel_drill.py).

Covers each layer in isolation: the worker-side TrainingSentinel
(non-finite trips, median+MAD spike detection, anomaly-window
bookkeeping, exactly-once KV order adoption), the master-side
report_anomaly protocol (rollback orders, duplicate-report riding,
budget exhaustion, quarantine eviction), the QuarantineManager strike
counting, the ErrorMonitor dedup, the ``last_good`` checkpoint tag
end-to-end (archive manifest, COMMIT doc, restore walk-down skip), the
nan/sdc injection grammar, the optimizer non-finite guard, and the
rollback-rewind exactly-once semantics of the sampler and the
sharding client.
"""

import json
import logging
import math
from types import SimpleNamespace

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from dlrover_tpu import telemetry as T
from dlrover_tpu.agent.master_client import LocalMasterClient
from dlrover_tpu.agent.sharding.client import ShardingClient
from dlrover_tpu.common import comm
from dlrover_tpu.common.constants import TrainingExceptionLevel
from dlrover_tpu.fault_tolerance import injection
from dlrover_tpu.fault_tolerance.sentinel import (
    ROLLBACK_ORDER_KEY,
    TrainingSentinel,
)
from dlrover_tpu.master.monitor.error_monitor import ErrorMonitor
from dlrover_tpu.master.node.quarantine import QuarantineManager
from dlrover_tpu.master.servicer import MasterServicer
from dlrover_tpu.optim import bf16 as bf16_mod
from dlrover_tpu.telemetry.journal import EventJournal
from dlrover_tpu.trainer import ckpt_store
from dlrover_tpu.trainer.checkpoint import FlashCheckpointer, _local_shards
from dlrover_tpu.trainer.sampler import ElasticDistributedSampler


@pytest.fixture(autouse=True)
def fresh_defaults():
    T.set_default_registry(None)
    T.set_default_journal(EventJournal(None))
    yield
    T.set_default_registry(None)
    T.set_default_journal(EventJournal(None))


def events(kind):
    return T.default_journal().events(kind)


# ---------------------------------------------------------------- detection


def test_nonfinite_loss_trips():
    s = TrainingSentinel(node_rank=3, host="host-a")
    s.note_checkpoint(5)
    r = s.check(6, float("nan"))
    assert r["kind"] == "nonfinite_loss"
    assert r["action"] == "none"  # no master client
    assert r["value"] is None  # NaN is not journal/RPC-safe
    assert not s.is_clean()
    assert s.anomaly_count == 1
    (ev,) = events("anomaly.detected")
    assert ev["data"]["anomaly"] == "nonfinite_loss"
    assert ev["data"]["step"] == 6
    assert ev["data"]["last_good_step"] == 5
    assert ev["data"]["host"] == "host-a"
    assert ev["data"]["node_rank"] == 3


def test_nonfinite_grad_trips_before_loss():
    s = TrainingSentinel()
    r = s.check(3, 1.0, grad_norm=float("inf"))
    assert r["kind"] == "nonfinite_grad"
    assert events("anomaly.detected")[0]["data"]["anomaly"] == (
        "nonfinite_grad"
    )


def test_spike_trips_after_warmup():
    s = TrainingSentinel(window=32, zmax=6.0, min_steps=8)
    # before warm-up the spike detector is disarmed: a wild value is
    # absorbed into the window, not tripped on
    assert s.check(0, 50.0) is None
    s2 = TrainingSentinel(window=32, zmax=6.0, min_steps=8)
    for i in range(8):
        assert s2.check(i, 1.0 + 0.02 * (-1) ** i) is None
    r = s2.check(8, 100.0)
    assert r["kind"] == "loss_spike"
    assert r["zscore"] > 6.0
    assert r["value"] == 100.0
    # an ordinary sample does not trip
    s3 = TrainingSentinel(window=32, zmax=6.0, min_steps=8)
    for i in range(8):
        assert s3.check(i, 1.0 + 0.02 * (-1) ** i) is None
    assert s3.check(8, 1.03) is None


def test_degenerate_constant_window():
    s = TrainingSentinel(min_steps=4)
    for i in range(6):
        assert s.check(i, 2.0) is None
    # MAD == 0: only a departure beyond max(1.0, |median|) trips
    assert s.check(6, 3.5) is None  # |3.5-2| = 1.5 <= 2.0
    r = s.check(7, 5.0)  # |5-2| = 3 > 2
    assert r is not None and r["kind"] == "loss_spike"
    # inf z-score is sanitized for the journal/RPC
    assert r["zscore"] is None


def test_anomaly_window_gates_note_checkpoint():
    s = TrainingSentinel()
    s.note_checkpoint(4)
    s.check(5, float("nan"))
    s.note_checkpoint(6)  # inside the window: must NOT become last-good
    assert s.last_good_step == 4
    s.note_restored(4, rollback_id=1)
    assert s.is_clean()
    assert s.last_good_step == 4
    (ev,) = events("rollback.restored")
    assert ev["data"]["step"] == 4 and ev["data"]["rollback_id"] == 1
    s.note_checkpoint(8)
    assert s.last_good_step == 8


def test_note_restored_resets_spike_baseline():
    s = TrainingSentinel(min_steps=4)
    for i in range(6):
        s.check(i, 1.0 + 0.02 * (-1) ** i)
    assert s.check(6, 100.0) is not None
    s.note_restored(3)
    # the window was cleared: the detector re-arms only after min_steps
    # fresh samples, so the first post-restore loss cannot trip
    assert s.check(7, 100.0) is None


# --------------------------------------------------- rollback-order adoption


def test_adopt_order_from_kv_exactly_once():
    client = LocalMasterClient()
    s = TrainingSentinel(master_client=client)
    client.kv_store_set(
        ROLLBACK_ORDER_KEY, json.dumps({"id": 1, "step": 5}).encode()
    )
    assert s.poll_rollback_order() == {"id": 1, "step": 5}
    assert len(events("rollback.ordered")) == 1
    # re-broadcasts of the same order are adopted once
    s.poll_rollback_order()
    assert len(events("rollback.ordered")) == 1
    s.note_restored(5, rollback_id=1)
    assert s.pending_rollback() is None
    # the stale KV content must not re-open the completed rollback
    assert s.poll_rollback_order() is None
    # a NEW order (higher id) is adopted
    client.kv_store_set(
        ROLLBACK_ORDER_KEY, json.dumps({"id": 2, "step": 9}).encode()
    )
    assert s.poll_rollback_order() == {"id": 2, "step": 9}


def test_bad_order_json_is_ignored():
    client = LocalMasterClient()
    s = TrainingSentinel(master_client=client)
    client.kv_store_set(ROLLBACK_ORDER_KEY, b"not json")
    assert s.poll_rollback_order() is None


def test_check_polls_order_on_step_cadence():
    client = LocalMasterClient()
    s = TrainingSentinel(master_client=client)
    client.kv_store_set(
        ROLLBACK_ORDER_KEY, json.dumps({"id": 7, "step": 3}).encode()
    )
    assert s.check(10, 1.0) is None
    assert s.pending_rollback() == {"id": 7, "step": 3}


class _FakeClient:
    """Captures report_anomaly calls and answers a canned response."""

    def __init__(self, resp):
        self.resp = resp
        self.calls = []

    def report_anomaly(self, **kw):
        self.calls.append(kw)
        return self.resp

    def kv_store_get(self, key):
        return b""


def test_report_adopts_master_rollback_order():
    client = _FakeClient(comm.AnomalyResponse(
        action="rollback", rollback_id=3, rollback_step=11,
    ))
    s = TrainingSentinel(master_client=client, host="h0")
    s.note_checkpoint(11)
    r = s.check(12, float("nan"))
    assert r["action"] == "rollback"
    assert s.pending_rollback() == {"id": 3, "step": 11}
    assert client.calls[0]["last_good_step"] == 11
    assert client.calls[0]["host"] == "h0"
    # NaN value travels as 0.0 (JSON/RPC-safe), the kind carries meaning
    assert client.calls[0]["value"] == 0.0


def test_report_job_failed_verdict():
    s = TrainingSentinel(master_client=_FakeClient(
        comm.AnomalyResponse(action="job_failed")
    ))
    r = s.check(2, float("nan"))
    assert r["action"] == "job_failed"
    assert s.job_failed


def test_report_quarantined_verdict_rides_the_rollback():
    # the repeat-offender verdict arrives ON the rollback response: the
    # sentinel must latch it AND still adopt the order, so the host
    # honors the rewind before standing down
    client = _FakeClient(comm.AnomalyResponse(
        action="rollback", rollback_id=2, rollback_step=9,
        quarantined=True,
    ))
    s = TrainingSentinel(master_client=client, host="h0")
    assert not s.quarantined
    s.note_checkpoint(9)
    r = s.check(10, float("nan"))
    assert r["action"] == "rollback"
    assert s.quarantined
    assert s.pending_rollback() == {"id": 2, "step": 9}
    # the flag survives the restore — quarantine is not an incident
    # that recovery clears
    s.note_restored(9, 2)
    assert s.quarantined


def test_report_masterless_fallback():
    s = TrainingSentinel(master_client=LocalMasterClient())
    r = s.check(2, float("nan"))
    # LocalMasterClient has no one to coordinate with: local window only
    assert r["action"] == "none"
    assert not s.job_failed and s.pending_rollback() is None


def test_from_env_knobs(monkeypatch):
    monkeypatch.setenv("DLROVER_TPU_SENTINEL", "0")
    assert TrainingSentinel.from_env() is None
    monkeypatch.setenv("DLROVER_TPU_SENTINEL", "1")
    monkeypatch.setenv("DLROVER_TPU_SENTINEL_WINDOW", "8")
    monkeypatch.setenv("DLROVER_TPU_SENTINEL_ZMAX", "3.5")
    monkeypatch.setenv("DLROVER_TPU_SENTINEL_MIN_STEPS", "4")
    monkeypatch.setenv("DLROVER_TPU_NODE_RANK", "2")
    s = TrainingSentinel.from_env()
    assert s is not None
    assert s._window.maxlen == 8
    assert s._zmax == 3.5
    assert s._min_steps == 4
    assert s._node_rank == 2


# ----------------------------------------------------- master-side protocol


class _Rdzv:
    def __init__(self):
        self.removed = []

    def remove_alive_node(self, rank):
        self.removed.append(rank)

    def mark_node_succeeded(self, rank):
        pass


class _JobManager:
    def __init__(self):
        self.failed = []
        self.quarantined = []

    def get_node(self, node_type, node_id):
        return None

    def update_node_status(self, *a, **kw):
        pass

    def mark_job_failed(self, reason):
        self.failed.append(reason)

    def handle_quarantine(self, node_type, node_id, host):
        self.quarantined.append((node_type, node_id, host))


def _report(node_id, host, last_good=5, kind="nonfinite_loss"):
    return comm.AnomalyReport(
        node_type="worker", node_id=node_id, kind=kind, step=6,
        host=host, last_good_step=last_good,
    )


def _running(node_id):
    return comm.NodeStatusRequest(
        node_type="worker", node_id=node_id, status="running",
    )


def test_servicer_orders_rollback_and_recovers(monkeypatch):
    monkeypatch.setenv("DLROVER_TPU_MAX_ROLLBACKS", "3")
    sv = MasterServicer(error_monitor=ErrorMonitor(
        quarantine=QuarantineManager(threshold=10)
    ))
    resp = sv.handle("report_anomaly", _report(0, "host-a", last_good=5))
    assert resp.action == "rollback"
    assert resp.rollback_id == 1 and resp.rollback_step == 5
    assert not resp.quarantined
    order = json.loads(sv._kv_store.get(ROLLBACK_ORDER_KEY).decode())
    assert order["id"] == 1 and order["step"] == 5
    # a second rank tripping on the SAME corrupted state rides the
    # in-flight order instead of burning budget
    resp2 = sv.handle("report_anomaly", _report(1, "host-b", last_good=4))
    assert resp2.action == "rollback"
    assert resp2.rollback_id == 1 and resp2.rollback_step == 5
    assert sv._rollbacks_done == 1
    (ev,) = events("rollback.initiated")
    assert ev["data"]["anomaly"] == "nonfinite_loss"
    assert ev["data"]["rollbacks"] == 1 and ev["data"]["budget"] == 3
    # both ranks report RUNNING post-restore: the incident closes and
    # rollback.recovered fires per rank
    sv.handle("update_node_status", _running(0))
    assert sv._active_rollback is not None  # rank 1 still restoring
    sv.handle("update_node_status", _running(1))
    assert sv._active_rollback is None
    assert len(events("rollback.recovered")) == 2
    # a LATER anomaly is a fresh (budget-counted) incident
    resp3 = sv.handle("report_anomaly", _report(0, "host-a", last_good=9))
    assert resp3.rollback_id == 2 and resp3.rollback_step == 9
    assert sv._rollbacks_done == 2


def test_servicer_no_clean_checkpoint_means_no_rollback():
    sv = MasterServicer()
    resp = sv.handle("report_anomaly", _report(0, "h", last_good=-1))
    assert resp.action == "none"
    assert not events("rollback.initiated")


def test_servicer_rollback_budget_exhausts_to_job_failed(monkeypatch):
    monkeypatch.setenv("DLROVER_TPU_MAX_ROLLBACKS", "1")
    jm = _JobManager()
    sv = MasterServicer(job_manager=jm)
    assert sv.handle(
        "report_anomaly", _report(0, "h", last_good=5)
    ).action == "rollback"
    sv.handle("update_node_status", _running(0))
    resp = sv.handle("report_anomaly", _report(0, "h", last_good=7))
    assert resp.action == "job_failed"
    assert jm.failed and "rollback budget exhausted" in jm.failed[0]
    (ev,) = events("rollback.budget_exhausted")
    assert ev["data"]["rollbacks"] == 1 and ev["data"]["budget"] == 1


def test_servicer_quarantines_repeat_offender_host():
    jm = _JobManager()
    rdzv = _Rdzv()
    sv = MasterServicer(
        job_manager=jm, rdzv_managers={"elastic-training": rdzv},
        error_monitor=ErrorMonitor(
            quarantine=QuarantineManager(threshold=2)
        ),
    )
    r1 = sv.handle(
        "report_anomaly", _report(2, "bad-host", last_good=-1)
    )
    assert not r1.quarantined
    r2 = sv.handle(
        "report_anomaly", _report(2, "bad-host", last_good=-1)
    )
    assert r2.quarantined
    # surgical eviction: the host's rank leaves rendezvous NOW and the
    # job manager stops relaunching onto the host
    assert 2 in rdzv.removed
    assert jm.quarantined == [("worker", 2, "bad-host")]
    (ev,) = events("quarantine.imposed")
    assert ev["data"]["host"] == "bad-host"
    assert ev["data"]["anomalies"] == 2


# ------------------------------------------------------- quarantine manager


def test_quarantine_threshold_strikes_and_sink():
    seen = []
    qm = QuarantineManager(threshold=2, placement_sink=seen.append)
    assert qm.note_anomaly("h1", kind="loss_spike", step=4) is False
    assert qm.note_anomaly("h1", kind="loss_spike", step=9) is True
    # already quarantined: further strikes count but do not re-impose
    assert qm.note_anomaly("h1") is False
    assert qm.is_quarantined("h1")
    assert qm.anomaly_count("h1") == 3
    assert qm.quarantined_hosts() == ["h1"]
    qm.note_anomaly("h0")
    qm.note_anomaly("h0")
    assert seen == [["h1"], ["h0", "h1"]]  # sorted full list each time


def test_quarantine_disabled_and_anonymous():
    qm = QuarantineManager(threshold=0)
    assert qm.note_anomaly("h1") is False
    assert qm.note_anomaly("h1") is False
    assert not qm.is_quarantined("h1")
    qm2 = QuarantineManager(threshold=1)
    assert qm2.note_anomaly("") is False  # unattributable report


def test_quarantine_threshold_from_env(monkeypatch):
    monkeypatch.setenv("DLROVER_TPU_QUARANTINE_THRESHOLD", "3")
    qm = QuarantineManager()
    assert qm.note_anomaly("h") is False
    assert qm.note_anomaly("h") is False
    assert qm.note_anomaly("h") is True


# ----------------------------------------------------------- error monitor


def test_error_monitor_dedups_identical_reports():
    captured = []
    handler = logging.Handler()
    handler.emit = lambda rec: captured.append(rec.getMessage())
    logging.getLogger("dlrover_tpu").addHandler(handler)
    try:
        em = ErrorMonitor()
        node = SimpleNamespace(id=1, name="worker-1")
        lvl = TrainingExceptionLevel.PROCESS_ERROR
        assert em.process_error(node, 0, "OOM at step 3", lvl) is False
        # byte-identical re-report of the same incident: suppressed
        em.process_error(node, 0, "OOM at step 3", lvl)
        logged = [m for m in captured if "Process error" in m]
        assert len(logged) == 1
        # a DIFFERENT error in the same restart is new information
        em.process_error(node, 0, "bus error", lvl)
        logged = [m for m in captured if "Process error" in m]
        assert len(logged) == 2
    finally:
        logging.getLogger("dlrover_tpu").removeHandler(handler)
    # every report reaches the journal timeline, deduped or not
    evs = events("node.error")
    assert len(evs) == 3
    assert evs[0]["data"]["error"] == "OOM at step 3"
    assert evs[0]["data"]["restart_count"] == 0


def test_error_monitor_node_error_is_critical():
    em = ErrorMonitor()
    assert em.process_error(
        "w-2", 1, "device lost", TrainingExceptionLevel.NODE_ERROR
    ) is True
    (ev,) = events("node.error")
    assert ev["data"]["level"] == TrainingExceptionLevel.NODE_ERROR


# -------------------------------------------------------- last_good tagging


def _toy_state(v):
    return {"w": jnp.full((4,), float(v), jnp.float32)}


def test_archive_last_good_roundtrip(tmp_path):
    for tag in (True, False, None):
        path = tmp_path / f"a-{tag}"
        with open(path, "wb") as f:
            ckpt_store.snapshot_to_file(
                _local_shards(_toy_state(1)), 3, f, last_good=tag
            )
        with open(path, "rb") as f:
            f.seek(0)
            assert ckpt_store.archive_last_good(f) is tag
            # the peek must not move the cursor: a full restore still
            # works on the same fileobj
            snap, step = ckpt_store.snapshot_from_file(f)
            assert step == 3


def test_commit_doc_carries_last_good(tmp_path):
    store = ckpt_store.LocalFsStore(str(tmp_path))
    for step, tag in ((2, True), (4, False), (6, None)):
        store.put(ckpt_store.step_key(step, 0), b"shard")
        assert ckpt_store.commit_step(
            store, step, n_processes=1, last_good=tag
        )
        assert ckpt_store.step_last_good(store, step) is tag
    # a step with no COMMIT at all reads as "no verdict"
    assert ckpt_store.step_last_good(store, 99) is None


def test_restore_walkdown_skips_anomaly_window_saves(tmp_path):
    clean = [True]
    writer = FlashCheckpointer(
        persist_dir=str(tmp_path / "bucket"),
        ram_dir=str(tmp_path / "ram_a"),
        persist_interval=1, use_orbax=False, stage="sync",
    )
    writer.set_clean_fn(lambda: clean[0])
    writer.save(2, _toy_state(2))
    clean[0] = False  # anomaly window opens
    writer.save(4, _toy_state(4))
    writer.wait()

    # spare reader (empty RAM tier): auto-restore walks down past the
    # tainted newest step to the sentinel-clean one
    reader = FlashCheckpointer(
        persist_dir=str(tmp_path / "bucket"),
        ram_dir=str(tmp_path / "ram_b"),
        persist_interval=0, use_orbax=False,
    )
    state, step = reader.restore()
    assert step == 2
    evs = events("checkpoint.restore_fallback")
    assert any(
        e["data"]["reason"] == "anomaly_window"
        and e["data"]["step"] == 4 for e in evs
    )
    fb = T.default_registry().get("dlrover_ckpt_restore_fallbacks_total")
    assert fb.labels(reason="anomaly_window").value >= 1

    # the writer's own RAM tier holds the tainted archive too: the
    # RAM-tier peek rejects it for pennies before the persist walk-down
    T.set_default_journal(EventJournal(None))
    state, step = writer.restore()
    assert step == 2
    tiers = {
        e["data"]["tier"] for e in events("checkpoint.restore_fallback")
        if e["data"]["reason"] == "anomaly_window"
    }
    assert tiers == {"ram", "persistent"}

    # an EXPLICITLY requested step is the caller's choice: the master's
    # rollback order may legitimately target any committed step
    state, step = reader.restore(step=4)
    assert step == 4
    writer.close()
    reader.close()


# ------------------------------------------------------- injection grammar


def test_parse_spec_corruption_kinds():
    faults = injection.parse_spec("nan@6:host=0,sdc@5:flip=2!")
    assert [(f.kind, f.step, f.arg) for f in faults] == [
        ("nan", 6, "host=0"), ("sdc", 5, "flip=2"),
    ]
    assert [f.every_incarnation for f in faults] == [False, True]
    with pytest.raises(ValueError):
        injection.parse_spec("flip@3")
    with pytest.raises(ValueError):
        injection.parse_spec("nan6")


def test_parse_spec_kv_continuation_extends_previous_fault():
    # the spec splits on commas, but so do kv args: a "k=v" chunk
    # without "@" extends the fault before it, making the documented
    # sdc@STEP:flip=K,host=H form parseable
    (f,) = injection.parse_spec("sdc@5:flip=2,host=1")
    assert (f.kind, f.step, f.arg) == ("sdc", 5, "flip=2,host=1")
    faults = injection.parse_spec("sdc@5:flip=2,host=1!,nan@9")
    assert [(f.kind, f.arg, f.every_incarnation) for f in faults] == [
        ("sdc", "flip=2,host=1", True), ("nan", "", False),
    ]
    # the combined arg feeds both the host filter and the flip width
    other = injection.FaultInjector(
        spec="sdc@5:flip=2,host=1", node_rank=0
    )
    assert other.corrupt_loss(5, 1.25) == 1.25
    target = injection.FaultInjector(
        spec="sdc@5:flip=2,host=1", node_rank=1
    )
    out = target.corrupt_loss(5, 1.25)
    assert math.isfinite(out) and out != 1.25
    # a leading continuation has nothing to extend
    with pytest.raises(ValueError):
        injection.parse_spec("host=1,nan@3")


def test_host_filter_scopes_corruption_to_one_rank():
    other = injection.FaultInjector(spec="nan@6:host=1", node_rank=0)
    assert other.corrupt_loss(6, 1.25) == 1.25
    target = injection.FaultInjector(spec="nan@6:host=1", node_rank=1)
    assert math.isnan(target.corrupt_loss(6, 1.25))


def test_corrupt_loss_fires_once_outside_maybe_inject():
    inj = injection.FaultInjector(spec="nan@3")
    assert inj.corrupt_loss(2, 1.0) == 1.0  # not due yet
    inj.maybe_inject(3)  # corruption kinds do NOT execute here
    assert math.isnan(inj.corrupt_loss(3, 1.0))
    assert inj.corrupt_loss(4, 1.0) == 1.0  # fired once
    (ev,) = events("fault.injected")
    assert ev["data"]["fault"] == "nan" and ev["data"]["step"] == 3


def test_sdc_flip_is_finite_but_wrong():
    inj = injection.FaultInjector(spec="sdc@5:flip=2")
    out = inj.corrupt_loss(5, 1.234)
    assert math.isfinite(out) and out != 1.234
    # nbits clamps to [1, 10] and never produces inf/nan
    for nbits in (0, 1, 10, 99):
        y = injection._flip_bits(1.234, nbits)
        assert math.isfinite(y) and y != 1.234
    assert injection._flip_bits(1.234, 0) == injection._flip_bits(1.234, 1)
    assert injection._flip_bits(1.234, 99) == injection._flip_bits(
        1.234, 10
    )


def test_restart_count_gates_corruption_faults():
    relaunched = injection.FaultInjector(spec="nan@3", restart_count=1)
    assert relaunched.corrupt_loss(3, 1.0) == 1.0
    persistent = injection.FaultInjector(spec="nan@3!", restart_count=1)
    assert math.isnan(persistent.corrupt_loss(3, 1.0))


def test_from_env_none_without_spec(monkeypatch):
    monkeypatch.delenv(injection.ENV_SPEC, raising=False)
    assert injection.FaultInjector.from_env() is None


# --------------------------------------------------- optimizer guard (bf16)


def test_nonfinite_guard_skips_poisoned_update(monkeypatch):
    monkeypatch.setattr(bf16_mod, "_skips_published", 0)
    opt = bf16_mod.nonfinite_guard(optax.sgd(0.1, momentum=0.9))
    params = {"w": jnp.ones((4,), jnp.float32)}
    state = opt.init(params)
    good = {"w": jnp.full((4,), 0.5, jnp.float32)}
    updates, state = opt.update(good, state, params)
    params = optax.apply_updates(params, updates)
    skips, norm = bf16_mod.guard_stats(state)
    assert skips == 0 and norm == pytest.approx(1.0)

    trace_before = state.inner_state
    bad = {"w": jnp.array([np.nan, 0.5, 0.5, 0.5], jnp.float32)}
    updates, state = opt.update(bad, state, params)
    # the whole update is selected to zero — params unchanged
    np.testing.assert_array_equal(
        np.asarray(updates["w"]), np.zeros(4, np.float32)
    )
    # the momentum trace kept its PREVIOUS (finite) value: a NaN must
    # not outlive the step that produced it
    np.testing.assert_array_equal(
        np.asarray(jax.tree.leaves(state.inner_state)[0]),
        np.asarray(jax.tree.leaves(trace_before)[0]),
    )
    skips, norm = bf16_mod.guard_stats(state)
    assert skips == 1 and math.isnan(norm)
    c = T.default_registry().get("dlrover_optim_nonfinite_skips_total")
    assert c.value == 1


# ------------------------------------------- rollback rewind (exactly-once)


def test_sampler_rewind_replays_voided_work_exactly_once():
    s = ElasticDistributedSampler(dataset_size=12, shuffle=False)
    it = s.iter_batches(2)
    kept = [next(it) for _ in range(2)]  # indices 0..3, then snapshot
    snap = s.state_dict()
    assert snap == {"epoch": 0, "completed_num": 4}
    voided = [next(it) for _ in range(2)]  # 4..7: rolled back
    assert [int(i) for b in voided for i in b] == [4, 5, 6, 7]

    s2 = ElasticDistributedSampler(dataset_size=12, shuffle=False)
    s2.load_state_dict(snap)
    replay = [b for b in s2.iter_batches(2)]
    consumed = [int(i) for b in kept + replay for i in b]
    # the voided indices come back exactly once; nothing is skipped or
    # double-counted
    assert consumed == list(range(12))


def test_sampler_rewind_into_resized_world():
    s = ElasticDistributedSampler(dataset_size=12, shuffle=False)
    it = s.iter_batches(2)
    next(it), next(it)  # 0..3 consumed
    snap = s.state_dict()
    seen = []
    for rank in (0, 1):
        r = ElasticDistributedSampler(dataset_size=12, shuffle=False)
        r.load_state_dict(snap, num_replicas=2, rank=rank)
        seen += [int(i) for b in r.iter_batches(2) for i in b]
    # remaining 8 samples split cleanly across the new world: union
    # covers the tail exactly once
    assert sorted(seen) == list(range(4, 12))


def test_sampler_state_clamps_overrun():
    s = ElasticDistributedSampler(dataset_size=10, shuffle=False)
    s.completed_num = 14  # padded epoch overran the dataset size
    assert s.state_dict()["completed_num"] == 10


def test_shard_ledger_rewind_voids_stale_completions():
    client = LocalMasterClient()
    sc = ShardingClient(
        dataset_name="ds", batch_size=4, num_epochs=1,
        dataset_size=24, shuffle=False, num_minibatches_per_shard=1,
        master_client=client, fetch_batch=1, lookahead=0,
    )
    done = []
    for _ in range(2):
        shard = sc.fetch_shard(max_wait=10)
        task_id = sc._current_task.task_id
        assert sc.report_task_done(task_id) is True
        done.append((shard.start, shard.end))
    ledger = sc.get_shard_checkpoint()  # the rollback target's ledger
    sc.fetch_shard(max_wait=10)  # in flight past the snapshot
    stale_id = sc._current_task.task_id
    sc.restore_shard_from_checkpoint(ledger)
    # the rewound master requeued that range under a FRESH id: the
    # stale completion must be rejected, not double-counted
    assert sc.report_task_done(stale_id) is False
    while True:
        shard = sc.fetch_shard(max_wait=10)
        if shard is None:
            break
        task_id = sc._current_task.task_id
        assert sc.report_task_done(task_id) is True
        done.append((shard.start, shard.end))
    # accepted completions partition the dataset exactly once
    assert sorted(done) == [(i, i + 4) for i in range(0, 24, 4)]
