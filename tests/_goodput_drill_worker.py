"""Drill worker for the goodput chaos test (not a test module).

Speaks the real agent protocol against a live master with a live
goodput ledger armed: joins the training rendezvous (journaling the
``rendezvous.joined`` the tap turns into a phase credit), consumes
data shards while marking ``training`` per step and crediting a
simulated ``ckpt_stall``, and reports the global step — each report
piggybacks the ledger snapshot, which is what the master's
GoodputAggregator folds into the job account.

Fault surface: the real FaultInjector (``DLROVER_FAULT_INJECT`` in the
env, e.g. ``crash@6`` for worker 0's first incarnation — the relaunch
sets RESTART_COUNT=1 so it doesn't refire) journals ``fault.injected``
and dies rc 17 without closing the ledger, exercising the
died-without-goodbye accounting; the master kill mid-run is observed
through ``agent.master_lost`` / ``agent.master_reconnected``, which
the tap turns into a ``restart`` phase window.

On a clean finish the worker closes its ledger (``goodput.snapshot``
ground truth in the journal) and pushes one ``report_goodput
(final=True)`` so the master closes the incarnation, then emits DONE.
"""

import argparse
import sys
import threading
import time


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--master_addr", required=True)
    p.add_argument("--node_id", type=int, required=True)
    p.add_argument("--out", required=True)
    p.add_argument("--dataset_size", type=int, default=96)
    p.add_argument("--batch_size", type=int, default=4)
    p.add_argument("--shard_secs", type=float, default=0.08,
                   help="simulated train time per shard")
    args = p.parse_args()

    # envelope `proc` = node id BEFORE any journal write, so the offline
    # reconstruction groups this process under the same node identity
    # the master aggregates it as
    from dlrover_tpu.common.log import set_process_index

    set_process_index(args.node_id)

    from dlrover_tpu.agent.master_client import MasterClient
    from dlrover_tpu.agent.sharding.client import ShardingClient
    from dlrover_tpu.common.constants import RendezvousName
    from dlrover_tpu.fault_tolerance.injection import FaultInjector
    from dlrover_tpu.telemetry import goodput
    from dlrover_tpu.telemetry import record
    from dlrover_tpu.telemetry.goodput import Phase

    led = goodput.install()

    out = open(args.out, "a", buffering=1)

    def emit(line: str):
        out.write(line + "\n")
        print(f"[worker {args.node_id}] {line}", flush=True)

    client = MasterClient(
        args.master_addr, node_id=args.node_id, node_type="worker",
    )
    reconnected = threading.Event()
    client.add_reconnect_hook("drill-flag", reconnected.set)
    injector = FaultInjector.from_env(role="worker")

    def rendezvous(tag: str) -> int:
        reconnected.clear()
        client.join_rendezvous(args.node_id, 1)
        deadline = time.monotonic() + 60
        while True:
            if reconnected.is_set():
                # our waiting-set entry may have died with the old
                # master (join landed just before the kill): re-join so
                # the restarted master can complete the round
                reconnected.clear()
                client.join_rendezvous(args.node_id, 1)
            rdzv_round, _, world = client.get_comm_world(
                RendezvousName.TRAINING, args.node_id
            )
            if world and args.node_id in world:
                # the event the agent records at this point in a real
                # run — the goodput tap credits the wait as rendezvous
                record("rendezvous.joined", round=rdzv_round,
                       node=args.node_id)
                emit(f"{tag} {rdzv_round}")
                return rdzv_round
            if time.monotonic() > deadline:
                emit(f"ERROR {tag} timeout")
                raise TimeoutError(tag)
            time.sleep(0.2)

    # min_nodes=1: the relaunched incarnation re-joins alone mid-epoch
    # (its peer is busy consuming), and the round must still complete
    client.report_rdzv_params(
        min_nodes=1, max_nodes=2, waiting_timeout=0.5, node_unit=1,
    )
    rendezvous("ROUND")

    # batched dispatch + background lookahead on the drill path: the
    # crashing worker dies holding buffered-but-unconsumed shards,
    # which the successor incarnation reclaims on its first fetch —
    # the exactly-once assert covers the buffered window
    sharding = ShardingClient(
        dataset_name="goodput-drill",
        batch_size=args.batch_size,
        num_epochs=1,
        dataset_size=args.dataset_size,
        shuffle=False,
        num_minibatches_per_shard=1,
        master_client=client,
        fetch_batch=2,
        lookahead=2,
    )
    step = 0
    while True:
        shard = sharding.fetch_shard(poll_interval=0.2, max_wait=120.0)
        if shard is None:
            break
        emit(f"SHARD {shard.start} {shard.end}")
        time.sleep(args.shard_secs)
        step += 1
        led.on_step()
        if step % 4 == 0:
            # a simulated checkpoint stall: re-label the trailing 20ms
            led.credit(Phase.CKPT_STALL, 0.02)
        # the report carries the ledger snapshot; the master-side fault
        # injector also counts these (master_crash@N)
        client.report_global_step(step)
        assert sharding._current_task is not None
        sharding.report_task_done(sharding._current_task.task_id)
        if injector is not None:
            # worker-side faults (crash@N) fire here: fault.injected is
            # journaled, the tap marks `restart`, then os._exit(17) —
            # the ledger never closes, which is the point
            injector.maybe_inject(step)

    emit(f"STEPS {step}")
    # close first (freezes totals + journals goodput.snapshot), THEN
    # report: the master's final observation equals the journal's
    snap = led.close()
    client.report_goodput(final=True)
    emit(f"ELAPSED {snap['elapsed_s']:.3f}")
    emit("DONE")
    client.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
