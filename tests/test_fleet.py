"""Fleet observability plane (ISSUE 17): roll-ups, store, SLO.

The tentpole's layers 2–3, tested where each contract lives:

* :class:`HistogramSketch` — merge is associative/commutative (the
  property the relay pre-merge rests on) and quantiles stay inside the
  ~9 % bucket resolution;
* :class:`DigestCollector` — the PR 12 compose/commit contract: a
  failed forward re-merges losslessly, a shed retry reuses the same
  payload, commit clears exactly the acked samples;
* :class:`TimeSeriesStore` — raw→10s→1m downsampling and the hard
  byte cap (raw detail evicts first);
* :class:`FleetAggregator` + the relay — K agents' digests pre-merge
  into ONE ``RelayBatchReport.digest`` per interval, consumed by the
  master servicer with zero agent scrapes;
* ``/fleet`` + ``/fleet.json`` — including under concurrent load;
* :class:`SLOEvaluator` — violation/recovery state machine, the
  ``min_count`` gate, pluggable signals and attributed cause.
"""

import json
import threading
import time
import urllib.request

import pytest

from dlrover_tpu.common import comm
from dlrover_tpu.common.constants import NodeType
from dlrover_tpu.telemetry import fleet
from dlrover_tpu.telemetry.fleet import (
    DigestCollector,
    FleetAggregator,
    HistogramSketch,
    SLOEvaluator,
    TimeSeriesStore,
    merge_digest,
)
from dlrover_tpu.telemetry.journal import (
    EventJournal,
    default_journal,
    set_default_journal,
)


@pytest.fixture(autouse=True)
def _fresh_journal_and_collector():
    set_default_journal(EventJournal())
    fleet.set_default_collector(DigestCollector())
    yield
    set_default_journal(EventJournal())
    fleet.set_default_collector(None)


def _events(kind):
    return default_journal().events(kind)


# ------------------------------------------------------------------ sketch


def _values(n, base=0.050):
    # deterministic spread over ~3 octaves — no RNG in tests
    return [base * (1.0 + ((i * 37) % 100) / 25.0) for i in range(n)]


def test_sketch_quantiles_within_bucket_resolution():
    vals = _values(1000)
    sk = HistogramSketch()
    for v in vals:
        sk.observe(v)
    ordered = sorted(vals)
    for q in (0.5, 0.9, 0.99):
        true = ordered[int(q * len(ordered)) - 1]
        est = sk.quantile(q)
        # upper-edge estimate: never below the true quantile's bucket,
        # never more than one bucket width (~9%) above it
        assert est >= true * 0.92
        assert est <= true * 1.10
    assert sk.quantile(0.0) == min(vals)  # exact extremes
    assert sk.quantile(1.0) == max(vals)
    assert sk.mean == pytest.approx(sum(vals) / len(vals))


def test_sketch_merge_is_associative_and_commutative():
    vals = _values(300)
    parts = [vals[0::3], vals[1::3], vals[2::3]]
    sks = []
    for part in parts:
        sk = HistogramSketch()
        for v in part:
            sk.observe(v)
        sks.append(sk)
    whole = HistogramSketch()
    for v in vals:
        whole.observe(v)

    def merged(order):
        out = HistogramSketch()
        for i in order:
            out.merge(HistogramSketch.from_wire(sks[i].to_wire()))
        return out

    a = merged([0, 1, 2])
    b = merged([2, 0, 1])
    assert a.to_wire() == b.to_wire() == whole.to_wire()
    assert a.quantile(0.99) == whole.quantile(0.99)


def test_sketch_wire_round_trip_and_garbage_tolerance():
    sk = HistogramSketch()
    for v in (0.01, 0.1, 1.0):
        sk.observe(v)
    back = HistogramSketch.from_wire(sk.to_wire())
    assert back.to_wire() == sk.to_wire()
    assert back.count == 3 and back.min == 0.01 and back.max == 1.0
    # malformed wire never raises — a bad agent must not poison a relay
    junk = HistogramSketch.from_wire({"b": {"x": "y", "3": 2}, "n": 2})
    assert junk.buckets == {3: 2}
    assert HistogramSketch.from_wire("nope").count == 0
    # non-positive values park in the edge bucket, quantile stays sane
    sk.observe(0.0)
    assert sk.quantile(0.001) == 0.0


def test_merge_digest_pure_wire_arithmetic():
    a = DigestCollector()
    b = DigestCollector()
    for v in (0.1, 0.2):
        a.observe("step", v)
    a.incr("steps", 2)
    for v in (0.4, 0.8):
        b.observe("step", v)
    b.incr("steps", 2)
    b.incr("rpc_calls", 7)
    merged = merge_digest(a.compose(), b.compose())
    assert merged["c"] == {"steps": 4, "rpc_calls": 7}
    sk = HistogramSketch.from_wire(merged["h"]["step"])
    assert sk.count == 4 and sk.min == 0.1 and sk.max == 0.8
    # malformed entries from one agent are dropped, not raised on
    out = merge_digest(merged, {"c": {"steps": "NaNsense"},
                                "h": {"step": "junk"}})
    assert out["c"]["steps"] == 4
    assert merge_digest(merged, "garbage") is merged


# --------------------------------------------------------------- collector


def test_collector_compose_commit_contract():
    c = DigestCollector()
    assert c.compose() == {} and not c.dirty()
    c.observe("step", 0.5)
    c.incr("steps")
    first = c.compose()
    assert first["c"] == {"steps": 1}
    # shed retry: nothing new arrived — the SAME payload recomposes
    # (nothing double-counted)
    assert c.compose() == first
    # failed forward, new samples land, recompose: in-flight samples
    # RE-INCLUDE plus the new ones (nothing lost)
    c.observe("step", 0.25)
    c.incr("steps")
    second = c.compose()
    assert second["c"] == {"steps": 2}
    assert HistogramSketch.from_wire(second["h"]["step"]).count == 2
    # the acked ack clears exactly the in-flight samples
    c.commit()
    assert c.compose() == {} and not c.dirty()
    c.incr("steps")
    assert c.compose()["c"] == {"steps": 1}


def test_collector_compose_payload_does_not_alias_state():
    c = DigestCollector()
    c.observe("step", 0.5)
    payload = c.compose()
    before = json.dumps(payload, sort_keys=True)
    c.observe("step", 0.1)  # accumulates toward the NEXT compose
    assert json.dumps(payload, sort_keys=True) == before


def test_module_hooks_respect_digest_gate(monkeypatch):
    monkeypatch.setenv(fleet.ENV_FLEET_DIGEST, "0")
    fleet.observe("step", 1.0)
    fleet.incr("steps")
    assert fleet.default_collector().compose() == {}
    monkeypatch.setenv(fleet.ENV_FLEET_DIGEST, "1")
    fleet.observe("step", 1.0)
    assert fleet.default_collector().compose() != {}


# ------------------------------------------------------------------- store


def _sk(*values):
    sk = HistogramSketch()
    for v in values:
        sk.observe(v)
    return sk


def test_store_downsamples_into_tiers():
    store = TimeSeriesStore(max_mb=4)
    t0 = 1_000_020  # minute-aligned: 25 s stays in one 1m bucket
    for i in range(25):
        store.add("step", t0 + i, _sk(0.1 * (1 + i % 3)))
    raw = store.window("step", "raw")
    ten = store.window("step", "10s")
    one = store.window("step", "1m")
    assert len(raw) == 25  # one bucket per second
    assert len(ten) == 3   # 25 s spans three 10 s buckets
    assert len(one) == 1
    # every tier accounts for every sample — downsampling loses
    # resolution, never mass
    assert sum(sk.count for _ts, sk in raw) == 25
    assert sum(sk.count for _ts, sk in ten) == 25
    assert one[0][1].count == 25
    cur = store.current("step")
    assert cur is not None and cur.count >= 1
    assert store.current("nope") is None


def test_store_byte_cap_evicts_raw_detail_first():
    store = TimeSeriesStore(max_mb=0.002)  # ~2 KiB
    t0 = 2_000_000
    for i in range(300):
        store.add("step", t0 + i, _sk(0.1, 0.2, 0.4))
    assert store.memory_bytes() <= 2.5 * 1024  # cap held (open slack)
    raw = store.window("step", "raw")
    one = store.window("step", "1m")
    # raw detail was sacrificed; the coarse history survives
    assert len(raw) < 300
    assert len(one) >= 1


# -------------------------------------------------------------- aggregator


def test_aggregator_folds_digests_and_snapshots():
    agg = FleetAggregator(store=TimeSeriesStore(max_mb=4))
    c = DigestCollector()
    for i in range(50):
        c.observe("step", 0.1)
        c.incr("steps")
    agg.observe_digest(c.compose(), source="relay-0")
    c.commit()
    for i in range(50):
        c.observe("step", 0.2)
        c.incr("steps")
    agg.observe_digest(c.compose(), source="relay-1")
    snap = agg.snapshot()
    assert snap["counters"] == {"steps": 100}
    assert snap["sources"] == 2 and snap["digests"] == 2
    s = snap["series"]["step"]
    assert s["count"] == 100
    assert 95.0 <= s["p50_ms"] <= 230.0
    assert s["max_ms"] == pytest.approx(200.0, rel=0.01)
    assert snap["store_bytes"] > 0
    # garbage digests are ignored, never raised on
    agg.observe_digest({}, source="relay-0")
    agg.observe_digest("junk", source="relay-0")
    assert agg.snapshot()["digests"] == 2


def test_aggregator_host_breakdown_and_stragglers():
    agg = FleetAggregator(store=TimeSeriesStore(max_mb=4))
    for node_id, step in ((0, 110), (1, 90), (2, 108)):
        rep = comm.NodeStatusReport(
            node_id=node_id, node_type=NodeType.WORKER,
            timestamp=time.time(), host=f"host-{node_id}",
            has_step=True, step=step, step_ts=time.time(),
        )
        agg.observe_report(rep)
    lag = agg.stragglers(k=2)
    assert [h["host"] for h in lag] == ["host-1", "host-2"]
    assert lag[0]["behind"] == 20
    # a final report retires the host from the breakdown
    agg.observe_report(comm.NodeStatusReport(
        node_id=1, node_type=NodeType.WORKER, timestamp=time.time(),
        host="host-1", final=True,
    ))
    assert all(h["host"] != "host-1"
               for h in agg.snapshot()["hosts"])


def _host_report(node_id, step, job_id="default", ts=None):
    return comm.NodeStatusReport(
        node_id=node_id, node_type=NodeType.WORKER,
        timestamp=ts or time.time(), host=f"host-{node_id}",
        has_step=True, step=step, step_ts=ts or time.time(),
        job_id=job_id,
    )


def test_fleet_host_breakdown_capped_at_topk(monkeypatch):
    """ISSUE 19 satellite: /fleet's per-host breakdown is bounded. A
    10k-host fleet serves the top-k hosts by the straggler sort metric
    (furthest behind the lead step) plus an ``omitted_hosts`` count —
    never an unbounded multi-MB document."""
    monkeypatch.setenv(fleet.ENV_FLEET_TOPK, "4")
    agg = FleetAggregator(store=TimeSeriesStore(max_mb=4))
    for node_id in range(10):
        # host-0 leads at step 100, host-9 is furthest behind
        agg.observe_report(_host_report(node_id, 100 - node_id * 10))
    snap = agg.snapshot()
    assert len(snap["hosts"]) == 4
    assert snap["omitted_hosts"] == 6
    # the kept entries are the operators' hosts-of-interest: the ones
    # furthest behind the fleet-max step
    kept = {h["host"] for h in snap["hosts"]}
    assert kept == {"host-6", "host-7", "host-8", "host-9"}
    # output stays host-sorted for stable diffing
    assert [h["host"] for h in snap["hosts"]] == sorted(kept)
    # raising the cap above the fleet size disables omission
    monkeypatch.setenv(fleet.ENV_FLEET_TOPK, "64")
    snap = agg.snapshot()
    assert len(snap["hosts"]) == 10 and snap["omitted_hosts"] == 0


def test_aggregator_job_views_never_cross_contaminate():
    """ISSUE 19 tentpole: digests and reports stamped with a job land
    in that job's view AND the fleet-wide merge — never in a sibling
    job's. The fleet-wide snapshot keeps pre-job semantics."""
    agg = FleetAggregator(store=TimeSeriesStore(max_mb=4))
    ca, cb = DigestCollector(), DigestCollector()
    for _ in range(30):
        ca.observe("step", 0.1)
        ca.incr("steps")
        cb.observe("step", 0.4)
        cb.incr("steps")
    agg.observe_digest(ca.compose(), source="relay-0", job="a")
    agg.observe_digest(cb.compose(), source="relay-0", job="b")
    agg.observe_report(_host_report(0, 50, job_id="a"))
    agg.observe_report(_host_report(1, 90, job_id="b"))
    assert agg.jobs() == ["a", "b"]
    sa, sb = agg.snapshot(job="a"), agg.snapshot(job="b")
    assert sa["counters"] == {"steps": 30}
    assert sb["counters"] == {"steps": 30}
    assert sa["series"]["step"]["count"] == 30
    # job a's quantiles come from ITS samples only (0.1s ≈ 100ms)
    assert sa["series"]["step"]["p99_ms"] < 150.0
    assert sb["series"]["step"]["p99_ms"] > 300.0
    assert [h["host"] for h in sa["hosts"]] == ["host-0"]
    assert [h["host"] for h in sb["hosts"]] == ["host-1"]
    # per-job straggler lead is per job: host-0 IS job a's lead, so it
    # is not behind anyone
    assert agg.stragglers(job="a")[0]["behind"] == 0
    # the fleet-wide view is the merge across jobs
    snap = agg.snapshot()
    assert snap["counters"] == {"steps": 60}
    assert snap["series"]["step"]["count"] == 60
    assert {h["host"] for h in snap["hosts"]} == {"host-0", "host-1"}
    assert snap["jobs"] == ["a", "b"]
    # an unknown job reads as empty, not an error
    empty = agg.snapshot(job="ghost")
    assert empty["hosts"] == [] and empty["series"] == {}


def test_slo_state_is_job_scoped():
    """Per-job SLO machines (ISSUE 19): job a's violation neither
    fires nor clears job b's, and the fleet-wide machine is
    independent of both."""
    slo = SLOEvaluator(spec="step_p99_ms<=50")
    agg = FleetAggregator(store=TimeSeriesStore(max_mb=4), slo=slo)
    t0 = 6_000_000
    ca = DigestCollector()
    for _ in range(30):
        ca.observe("step", 0.2)  # 200ms: violates
    agg.observe_digest(ca.compose(), source="r", ts=t0, job="a")
    cb = DigestCollector()
    for _ in range(30):
        cb.observe("step", 0.01)  # 10ms: healthy
    agg.observe_digest(cb.compose(), source="r", ts=t0, job="b")
    assert slo.violated("step_p99_ms", job="a")
    assert not slo.violated("step_p99_ms", job="b")
    violated = [e["data"] for e in _events("slo.violated")]
    assert {v.get("job") for v in violated} >= {"a"}
    assert all(v.get("job") != "b" for v in violated)
    assert slo.status(job="a")["step_p99_ms"]["violated"]
    assert not slo.status(job="b")["step_p99_ms"]["violated"]


# --------------------------------------------------------------------- SLO


def _feed(agg, value, n=30, ts=None):
    c = DigestCollector()
    for _ in range(n):
        c.observe("step", value)
    agg.observe_digest(c.compose(), source="relay-0", ts=ts)


def test_slo_violation_and_recovery_lifecycle():
    slo = SLOEvaluator(spec="step_p99_ms<=50")
    agg = FleetAggregator(store=TimeSeriesStore(max_mb=4), slo=slo)
    t0 = 3_000_000
    _feed(agg, 0.2, ts=t0)  # 200 ms >> 50 ms
    violated = _events("slo.violated")
    assert len(violated) == 1
    data = violated[0]["data"]
    assert data["objective"] == "step_p99_ms" and data["op"] == "<="
    assert data["target"] == 50.0 and data["value"] > 50.0
    assert slo.violated("step_p99_ms")
    # still violated: no duplicate event (state machine, not a siren)
    _feed(agg, 0.2, ts=t0 + 1)
    assert len(_events("slo.violated")) == 1
    st = slo.status()["step_p99_ms"]
    assert st["violated"] and st["violated_since"] is not None
    # fast samples age the slow window out of current(): recovery
    _feed(agg, 0.01, ts=t0 + 10)
    _feed(agg, 0.01, ts=t0 + 11)
    recovered = _events("slo.recovered")
    assert len(recovered) == 1
    assert recovered[0]["data"]["violated_s"] >= 0.0
    assert not slo.violated("step_p99_ms")


def test_slo_min_count_gates_blips():
    slo = SLOEvaluator(spec="step_p99_ms<=50", min_count=20)
    agg = FleetAggregator(store=TimeSeriesStore(max_mb=4), slo=slo)
    _feed(agg, 0.2, n=3, ts=4_000_000)  # a 3-sample blip
    assert _events("slo.violated") == []
    _feed(agg, 0.2, n=30, ts=4_000_000)
    assert len(_events("slo.violated")) == 1


def test_slo_registered_signal_and_attribution():
    slo = SLOEvaluator(spec="goodput_percent>=95;step_p99_ms<=50")
    goodput = {"value": 80.0}
    slo.register_signal(
        "goodput_percent", lambda: goodput["value"],
        attribution=lambda: {"cause": "rendezvous", "badput_s": 12.5},
    )
    # fn=None: the built-in store quantile keeps providing the value,
    # only the attribution provider attaches
    slo.register_signal(
        "step_p99_ms", attribution=lambda: {"cause": "straggler"},
    )
    agg = FleetAggregator(store=TimeSeriesStore(max_mb=4), slo=slo)
    _feed(agg, 0.2, ts=5_000_000)
    by_obj = {
        e["data"]["objective"]: e["data"]
        for e in _events("slo.violated")
    }
    assert by_obj["goodput_percent"]["cause"] == "rendezvous"
    assert by_obj["goodput_percent"]["badput_s"] == 12.5
    assert by_obj["goodput_percent"]["value"] == 80.0
    assert by_obj["step_p99_ms"]["cause"] == "straggler"
    # a crashing signal is a None sample, never a crash
    slo.register_signal("goodput_percent",
                        lambda: (_ for _ in ()).throw(RuntimeError()))
    _feed(agg, 0.2, ts=5_000_001)


def test_slo_spec_parsing_is_forgiving():
    slo = SLOEvaluator(
        spec="step_p99_ms<=500; ;typo=5;goodput_percent>=95;bad<=x"
    )
    assert [(n, op) for n, op, _t in slo.objectives] == [
        ("step_p99_ms", "<="), ("goodput_percent", ">="),
    ]


# ----------------------------------------------------- relay + master wire


def test_relay_premerges_digests_and_master_consumes():
    """K agents' digests leave the relay as ONE RelayBatchReport.digest
    per interval, and the master's FleetAggregator sees the merged
    totals — fleet quantiles with zero agent scrapes. A failed forward
    keeps the digest in flight (recompose re-merges, nothing lost);
    the accepted ack clears it (nothing double-counted)."""
    from dlrover_tpu.agent.relay import AggregatorRelay
    from dlrover_tpu.agent.status_reporter import DeltaTracker

    agg = FleetAggregator(store=TimeSeriesStore(max_mb=4))
    from tests.test_ingest import _job_manager
    from dlrover_tpu.master.servicer import create_master_service

    jm, speed = _job_manager(4)
    server, servicer = create_master_service(
        0, job_manager=jm, speed_monitor=speed, fleet_aggregator=agg,
    )
    server.start()
    relay = AggregatorRelay(
        f"localhost:{server.port}", relay_id=0, interval=30.0,
    )
    try:
        for node_id in (0, 1):
            tracker = DeltaTracker(incarnation=0)
            c = DigestCollector()
            for _ in range(25):
                c.observe("step", 0.1 * (node_id + 1))
                c.incr("steps")
            rep = tracker.compose(time.time(), step=100,
                                  host=f"host-{node_id}")
            rep.node_type, rep.node_id = NodeType.WORKER, node_id
            rep.has_metrics, rep.metrics = True, c.compose()
            assert relay.handle("report_node_status", rep).accepted
        # interval 1: the upstream rejects — digest must survive
        orig = relay._upstream.report_relay_batch
        relay._upstream.report_relay_batch = lambda b: (
            (_ for _ in ()).throw(RuntimeError("master down"))
        )
        relay._forward_once()
        assert agg.snapshot()["digests"] == 0
        assert relay._inflight_digests  # parked, not dropped
        # interval 2: upstream back — ONE batch carries the merged
        # digest of both agents
        batches = []
        relay._upstream.report_relay_batch = (
            lambda b: (batches.append(b), orig(b))[1]
        )
        relay._forward_once()
        assert len(batches) == 1
        assert batches[0].digest["c"] == {"steps": 50}
        snap = agg.snapshot()
        assert snap["digests"] == 1 and snap["counters"] == {"steps": 50}
        assert snap["series"]["step"]["count"] == 50
        assert snap["sources"] == 1  # ONE relay source, not 2 agents
        assert not relay._inflight_digests  # acked: cleared
        # interval 3: nothing new — no digest travels
        relay._forward_once()
        assert len(batches) == 1 or not batches[-1].digest
    finally:
        relay._upstream.report_relay_batch = orig
        relay.stop(flush=False, grace=0.0)
        server.stop(grace=0.2)
        servicer.close()


def test_servicer_consumes_direct_agent_digest():
    """Relay-less deployments: the digest on a direct
    report_node_status reaches the aggregator too."""
    from dlrover_tpu.agent.status_reporter import DeltaTracker
    from tests.test_ingest import _job_manager
    from dlrover_tpu.master.servicer import MasterServicer

    agg = FleetAggregator(store=TimeSeriesStore(max_mb=4))
    jm, speed = _job_manager(2)
    servicer = MasterServicer(job_manager=jm, speed_monitor=speed,
                              fleet_aggregator=agg)
    try:
        tracker = DeltaTracker(incarnation=0)
        c = DigestCollector()
        for _ in range(30):
            c.observe("rpc", 0.005)
        c.incr("rpc_calls", 30)
        rep = tracker.compose(time.time(), step=7, host="host-0")
        rep.node_type, rep.node_id = NodeType.WORKER, 0
        rep.has_metrics, rep.metrics = True, c.compose()
        ack = servicer.rpc_report_node_status(rep)
        assert ack.accepted
        snap = agg.snapshot()
        assert snap["counters"] == {"rpc_calls": 30}
        assert snap["series"]["rpc"]["count"] == 30
        assert snap["sources"] == 1
        assert [h["host"] for h in snap["hosts"]] == ["host-0"]
    finally:
        servicer.close()


# --------------------------------------------------------------- endpoint


def test_fleet_endpoint_serves_and_survives_concurrent_load():
    from dlrover_tpu.telemetry.http import (
        MetricsServer,
        set_fleet_provider,
    )

    agg = FleetAggregator(
        store=TimeSeriesStore(max_mb=4),
        slo=SLOEvaluator(spec="step_p99_ms<=50"),
    )
    _feed(agg, 0.2, ts=6_000_000)
    srv = MetricsServer(host="127.0.0.1").start()
    set_fleet_provider(agg.snapshot)
    try:
        base = f"http://127.0.0.1:{srv.port}"

        def get(path):
            with urllib.request.urlopen(base + path, timeout=5) as r:
                return r.read().decode()

        doc = json.loads(get("/fleet.json"))
        assert doc["series"]["step"]["count"] == 30
        assert doc["slo"]["step_p99_ms"]["violated"] is True
        text = get("/fleet")
        assert "step" in text and "slo" in text
        # concurrent readers + a writer folding digests: no tears, no
        # 500s — the endpoint snapshots under the aggregator lock
        errors = []

        def reader():
            try:
                for _ in range(20):
                    json.loads(get("/fleet.json"))
            except Exception as e:  # pragma: no cover - the assert
                errors.append(e)

        def writer():
            for i in range(40):
                _feed(agg, 0.01, n=5, ts=6_000_001 + i)

        threads = [threading.Thread(target=reader) for _ in range(4)]
        threads.append(threading.Thread(target=writer))
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors
    finally:
        set_fleet_provider(None)
        srv.stop()


def test_fleet_endpoint_404_without_aggregator():
    from dlrover_tpu.telemetry.http import MetricsServer

    srv = MetricsServer(host="127.0.0.1").start()
    try:
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/fleet.json", timeout=5
            )
        assert exc.value.code == 404
    finally:
        srv.stop()
