"""Regression tests for rendezvous-manager correctness fixes.

Covers: round advancing on same-membership re-rendezvous (stale coordinator
bug), truncated nodes kept waiting for the next round, lazy-splitter final
epoch tail, network-check grouping on world (not waiting) state.
"""

import time

from dlrover_tpu.common.constants import TaskType
from dlrover_tpu.master.elastic_training.rdzv_manager import (
    ElasticTrainingRendezvousManager,
    NetworkCheckRendezvousManager,
)
from dlrover_tpu.master.shard.batch_dataset_manager import BatchDatasetManager
from dlrover_tpu.master.shard.dataset_splitter import TableDatasetSplitter


def _mgr(min_nodes, max_nodes, timeout=0.2, node_unit=1):
    m = ElasticTrainingRendezvousManager()
    m.update_rdzv_params(min_nodes, max_nodes, timeout, node_unit)
    return m


def test_rerendezvous_same_membership_advances_round():
    """After all nodes of a completed world re-join (process restart), a NEW
    round must form — the round number keys the coordinator election, so a
    stale round would hand restarted processes a dead coordinator."""
    m = _mgr(2, 2)
    m.join_rendezvous(0, 1)
    m.join_rendezvous(1, 1)
    r1, _, world1 = m.get_comm_world(0)
    assert world1 == {0: 1, 1: 1}
    assert r1 == 1

    # both nodes restart and re-join with identical membership
    m.join_rendezvous(0, 1)
    # node 0 has re-joined: must NOT be handed the old world
    r_stale, _, w_stale = m.get_comm_world(0)
    assert w_stale == {}
    m.join_rendezvous(1, 1)
    r2, _, world2 = m.get_comm_world(0)
    assert world2 == {0: 1, 1: 1}
    assert r2 == 2  # round advanced -> fresh coordinator key


def test_waiting_node_signals_membership_change():
    m = _mgr(1, 2)
    m.join_rendezvous(0, 1)
    time.sleep(0.25)  # min-nodes completion waits out the waiting_timeout
    r, _, w = m.get_comm_world(0)
    assert w == {0: 1}
    assert m.num_nodes_waiting() == 0
    m.join_rendezvous(1, 1)
    assert m.num_nodes_waiting() == 1  # running agents see the change


def test_truncated_node_stays_waiting_for_next_round():
    """node_unit=2, 3 joiners: world is 2 nodes; the third stays in the
    waiting set and joins the next round instead of being dropped."""
    m = _mgr(2, 4, timeout=0.1, node_unit=2)
    for r in range(3):
        m.join_rendezvous(r, 1)
    time.sleep(0.15)
    _, _, world = m.get_comm_world(0)
    assert sorted(world) == [0, 1]
    # node 2 still waiting, not silently dropped — but a lone leftover
    # (< node_unit) must NOT signal membership change, or the running
    # agents would livelock restarting into the same truncated world
    assert m.num_nodes_waiting() == 0
    _, _, w2 = m.get_comm_world(2)
    assert w2 == {}
    # a 4th node joins -> a full node_unit of new nodes now signals
    m.join_rendezvous(3, 1)
    assert m.num_nodes_waiting() == 2
    time.sleep(0.15)
    _, _, w_next = m.get_comm_world(2)
    assert sorted(w_next) == [2, 3]


def test_spare_replaces_dead_member():
    """World at max_nodes; a member is reported dead and a spare joins:
    the spare must signal re-rendezvous (it REPLACES the dead member),
    even though the world cannot grow."""
    m = _mgr(2, 4, timeout=0.1, node_unit=1)
    for r in range(4):
        m.join_rendezvous(r, 1)
    _, _, world = m.get_comm_world(0)
    assert sorted(world) == [0, 1, 2, 3]
    # spare joins while everyone is healthy: same prospective world -> 0
    m.join_rendezvous(4, 1)
    assert m.num_nodes_waiting() == 0
    # control plane reports node 3 dead -> spare 4 now changes the world
    m.remove_alive_node(3)
    assert m.num_nodes_waiting() == 1


def test_member_rejoin_always_signals_membership_change():
    """A current-world member re-waiting (restart/loss) must signal even
    when fewer than node_unit nodes wait."""
    m = _mgr(2, 4, timeout=0.1, node_unit=2)
    m.join_rendezvous(0, 1)
    m.join_rendezvous(1, 1)
    time.sleep(0.15)
    _, _, world = m.get_comm_world(0)
    assert sorted(world) == [0, 1]
    assert m.num_nodes_waiting() == 0
    m.join_rendezvous(1, 1)  # member restarts
    assert m.num_nodes_waiting() == 1


def test_lazy_splitter_serves_full_final_epoch():
    """max_shard_count-limited splitter must not drop the epoch tail."""
    splitter = TableDatasetSplitter(
        "big", dataset_size=100, shard_size=10, num_epochs=1,
        max_shard_count=4,
    )
    mgr = BatchDatasetManager(TaskType.TRAINING, 5, splitter)
    served = 0
    while True:
        t = mgr.get_task("worker", 0)
        if t.task_id < 0:
            break
        served += t.shard.end - t.shard.start
        mgr.report_task_status(t.task_id, success=True)
    assert served == 100  # every record of the epoch dispatched
    assert mgr.completed()


def test_network_check_rounds_regroup():
    m = NetworkCheckRendezvousManager()
    m.update_rdzv_params(4, 4, 0.2, node_unit=4)  # node_unit ignored
    for r in range(4):
        m.join_rendezvous(r, 1)
    _, g0, w0 = m.get_comm_world(0)
    assert w0 == {0: 1, 1: 1} and g0 == 0
    _, g2, w2 = m.get_comm_world(2)
    assert w2 == {2: 1, 3: 1} and g2 == 1
    # round 0 results: node 3 abnormal
    for r in range(4):
        m.report_network_check_result(r, r != 3, 1.0)
    ok, reason = m.network_check_success()
    assert not ok
    # round 1: rejoin all; abnormal node 3 paired with a normal node
    for r in range(4):
        m.join_rendezvous(r, 1)
    _, _, w3 = m.get_comm_world(3)
    assert 3 in w3 and len(w3) == 2
    # node 3 passes when re-paired -> healthy overall
    for r in range(4):
        m.report_network_check_result(r, True, 1.0)
    ok, _ = m.network_check_success()
    assert ok
    assert m.get_fault_nodes() == []


def test_singleton_probe_cannot_clear_abnormal_status():
    """Round-1 leaves some abnormal nodes without a healthy partner; their
    solo probe exercises no inter-host link, so its success must not mark
    them healthy (a broken-switch scenario would otherwise pass)."""
    m = NetworkCheckRendezvousManager()
    m.update_rdzv_params(4, 4, 0.2, node_unit=1)
    for r in range(4):
        m.join_rendezvous(r, 1)
    for r in range(4):
        m.get_comm_world(r)
    # round 0: the switch serving nodes 1-3 is broken
    for r in range(4):
        m.report_network_check_result(r, r == 0, 1.0)
    ok, _ = m.network_check_success()
    assert not ok
    # round 1: only one healthy partner (node 0) for three abnormal nodes
    for r in range(4):
        m.join_rendezvous(r, 1)
    worlds = {r: m.get_comm_world(r)[2] for r in range(4)}
    solo = [r for r, w in worlds.items() if len(w) == 1]
    assert len(solo) == 2  # two abnormal nodes probe alone
    for r in range(4):
        m.report_network_check_result(r, True, 1.0)
    ok, _ = m.network_check_success()
    assert not ok
    assert sorted(m.get_fault_nodes()) == sorted(solo)


def test_dead_member_signals_shrink_without_waiters():
    """A member pruned from the alive set (heartbeat loss / node failure)
    must signal membership change even though nobody is WAITING — the
    survivors' agents re-rendezvous into the smaller world. Regression:
    num_nodes_waiting used to return 0 whenever the waiting set was
    empty, so a 2-node world losing a host never re-formed."""
    m = _mgr(1, 2)
    m.join_rendezvous(0, 1)
    m.join_rendezvous(1, 1)
    _, _, world = m.get_comm_world(0)
    assert world == {0: 1, 1: 1}
    assert m.num_nodes_waiting() == 0  # healthy steady state

    m.remove_alive_node(1)  # master watchdog pruned the dead host
    assert m.num_nodes_waiting() > 0  # survivor must re-rendezvous

    m.join_rendezvous(0, 1)
    time.sleep(0.25)  # waiting_timeout elapses; min_nodes=1 completes
    _, _, world = m.get_comm_world(0)
    assert world == {0: 1}
    assert m.num_nodes_waiting() == 0  # signal clears after re-form


def test_dead_member_no_signal_below_min_nodes():
    """If the survivors cannot form a valid world (fewer than min_nodes),
    the shrink must NOT signal — restarting the survivors would only tear
    down work that cannot resume anyway; they wait for a replacement."""
    m = _mgr(2, 2)
    m.join_rendezvous(0, 1)
    m.join_rendezvous(1, 1)
    _, _, world = m.get_comm_world(0)
    assert world == {0: 1, 1: 1}

    m.remove_alive_node(1)
    assert m.num_nodes_waiting() == 0  # 1 survivor < min_nodes=2

    m.join_rendezvous(2, 1)  # a replacement arrives
    assert m.num_nodes_waiting() > 0  # now a new 2-node world can form


def test_succeeded_member_does_not_signal_shrink():
    """A member that exits SUCCEEDED leaves the alive set but must not
    trip the shrink signal — otherwise every staggered multi-node
    completion restarts the still-finishing survivors."""
    m = _mgr(1, 2)
    m.join_rendezvous(0, 1)
    m.join_rendezvous(1, 1)
    _, _, world = m.get_comm_world(0)
    assert world == {0: 1, 1: 1}

    m.mark_node_succeeded(1)  # normal exit, NOT a failure
    assert m.num_nodes_waiting() == 0

    # but the same rank re-joining later (a new run) still works
    m.join_rendezvous(1, 1)
    assert m.num_nodes_waiting() > 0


def test_straggler_localized_across_two_paired_rounds():
    """The probe is collective: a slow node inflates its whole group's
    elapsed time, so one round cannot localize. Two rounds with
    different pairings can — the straggler is the common member of its
    slow groups (VERDICT r3: live straggler shrink)."""
    from dlrover_tpu.master.elastic_training.rdzv_manager import (
        NetworkCheckRendezvousManager,
    )

    m = NetworkCheckRendezvousManager()
    m.update_rdzv_params(4, 4, 0.1, 1)
    for r in range(4):
        m.join_rendezvous(r, 1)
    # round 1: pairs {0,1}, {2,3}; node 3 is slow -> group {2,3} slow
    rnd1, _, _ = m.get_comm_world(0)
    for r in range(4):
        t = 17.0 if r in (2, 3) else 3.0
        m.report_network_check_result(r, True, t, rdzv_round=rnd1)
    # one informative round: both members of the slow pair are
    # suspects, neither is localized yet
    assert m._straggler_suspects() == {2, 3}
    assert m.get_straggler_nodes() in ([2, 3], [])
    # round 2: suspects re-pair with known-good partners -> {2,a},{3,b}
    for r in range(4):
        m.join_rendezvous(r, 1)
    rnd2, _, _ = m.get_comm_world(0)
    groups2 = m._round_groups[rnd2]
    pair_of_3 = next(g for g in groups2 if 3 in g)
    assert pair_of_3 != {2, 3}, groups2  # the pairing changed
    for r in range(4):
        t = 17.0 if r in pair_of_3 else 3.0
        m.report_network_check_result(r, True, t, rdzv_round=rnd2)
    assert m.get_straggler_nodes() == [3]


def test_no_straggler_when_all_groups_uniform():
    from dlrover_tpu.master.elastic_training.rdzv_manager import (
        NetworkCheckRendezvousManager,
    )

    m = NetworkCheckRendezvousManager()
    m.update_rdzv_params(4, 4, 0.1, 1)
    for rnd in (1, 2):
        for r in range(4):
            m.join_rendezvous(r, 1)
        got, _, _ = m.get_comm_world(0)
        for r in range(4):
            m.report_network_check_result(r, True, 3.0, rdzv_round=got)
    assert m.get_straggler_nodes() == []


def test_no_world_before_params_reported():
    """A fast-starting node must not form a solo world against the
    min=max=1 defaults while the rest of the fleet is still launching
    (four-node drill flake class)."""
    from dlrover_tpu.master.elastic_training.rdzv_manager import (
        ElasticTrainingRendezvousManager,
    )

    import time

    m = ElasticTrainingRendezvousManager()
    m.join_rendezvous(0, 1)
    # even after the min=1 waiting_timeout would have elapsed, no
    # round may complete while params are unreported
    time.sleep(0.15)
    rnd, _, world = m.get_comm_world(0)
    assert world == {}
    m.update_rdzv_params(1, 2, 0.1, 1)
    # the node is still waiting from its first join; once params are
    # known (min=1, timeout already elapsed) the round completes
    _, _, world = m.get_comm_world(0)
    assert 0 in world


def test_ha_master_restart_relearns_params_from_rejoin():
    """After a master (HA) relaunch the new managers start with
    _params_reported=False; agents re-report their config's params
    before every join (MasterRendezvousHandler), so the world re-forms
    instead of deadlocking."""
    from dlrover_tpu.master.elastic_training.rdzv_manager import (
        ElasticTrainingRendezvousManager,
    )

    # "relaunched master": a brand-new manager, nothing reported
    m = ElasticTrainingRendezvousManager()
    # surviving agents re-join; each re-reports params first (the
    # handler's behavior) — simulate the same call order
    for rank in (0, 1):
        m.update_rdzv_params(2, 2, 5.0, 1)
        m.join_rendezvous(rank, 1)
    _, _, world = m.get_comm_world(0)
    assert world == {0: 1, 1: 1}


def test_subset_check_rounds_do_not_clear_straggler_verdicts():
    """Soak-drill regression: a relaunched slice's own check rounds
    (probing only themselves) must neither clear nor smear an earlier
    straggler verdict for nodes they never probed."""
    from dlrover_tpu.master.elastic_training.rdzv_manager import (
        NetworkCheckRendezvousManager,
    )

    mgr = NetworkCheckRendezvousManager()
    # round 1: pairwise groups; rank 2's group slow (collective probe)
    mgr._round_groups[1] = [{0, 1}, {2, 3}, {4, 5}, {6, 7}]
    mgr._round_times[1] = {0: 1.0, 1: 1.1, 2: 26.0, 3: 25.5,
                           4: 1.0, 5: 1.2, 6: 0.9, 7: 1.0}
    # round 2: re-pair — rank 2 slow with a known-good partner,
    # rank 3 fast with another: rank 2 localized
    mgr._round_groups[2] = [{2, 0}, {3, 1}, {4, 5, 6, 7}]
    mgr._round_times[2] = {0: 26.0, 2: 26.0, 1: 1.0, 3: 1.1,
                           4: 1.0, 5: 1.0, 6: 1.0, 7: 1.0}
    assert mgr.get_straggler_nodes() == [2]

    # rounds 3-4: a relaunched slice (ranks 4-7) probes ITSELF — rank
    # 2 is not a participant; its verdict must survive
    mgr._round_groups[3] = [{4, 5}, {6, 7}]
    mgr._round_times[3] = {4: 1.0, 5: 1.1, 6: 0.9, 7: 1.0}
    mgr._round_groups[4] = [{4, 6}, {5, 7}]
    mgr._round_times[4] = {4: 1.0, 6: 1.1, 5: 0.9, 7: 1.0}
    assert mgr.get_straggler_nodes() == [2]

    # a later round where rank 2 participates and is FAST clears it
    mgr._round_groups[5] = [{2, 4}, {5, 6}]
    mgr._round_times[5] = {2: 1.0, 4: 1.1, 5: 0.9, 6: 1.0}
    assert mgr.get_straggler_nodes() == []
