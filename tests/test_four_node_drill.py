"""Four-node elastic drill with LIVE straggler shrink (VERDICT r2 Next
#7): a 4-agent job (node_unit=2) whose rank-3 network probe is delayed
past the straggler threshold; the master's auto-scaler must read the
network-check verdict, generate the straggler shrink plan, evict down
to the aligned world of 2, and the survivors must re-rendezvous and
resume from the flash checkpoint.

Covers live the path that was previously only unit-tested
(master/resource/local_optimizer.generate_straggler_shrink_plan +
master/node/job_auto_scaler._maybe_shrink_stragglers). Parity role:
dlrover rdzv_manager.py:368 straggler handling + the reference's
node-failure system tests.
"""

import os
import re
import signal
import subprocess
import sys
import time
import pytest

# tier-1 budget (ISSUE 2 satellite): this module costs >50s of the
# 870s budget on a 1-core box; the nightly/full shard still runs it
pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _strip_axon(env):
    parts = [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
             if p and "axon" not in p]
    env["PYTHONPATH"] = os.pathsep.join(parts + [REPO])
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    # the drill asserts on master INFO logs (straggler plan); the test
    # conftest's WARNING default would hide them
    env["DLROVER_TPU_LOG_LEVEL"] = "INFO"
    return env


def _write_spec(tmp):
    progress = os.path.join(tmp, "progress.txt")
    spec = f"""
apiVersion: dlrover-tpu/v1
kind: ElasticTpuJob
metadata:
  name: straggler-drill
spec:
  platform: process
  distributionStrategy: allreduce
  nodeUnit: 2
  relaunchStrategy: always
  heartbeatTimeout: 8
  worker:
    replicas: 4
    minReplicas: 2
    maxRelaunchCount: 2
    criticalWorkerIndex: none
    env:
      DLROVER_TPU_PROBE_DELAY: "3:35"
      DLROVER_TPU_DIST_HEARTBEAT_TIMEOUT: "10"
      JAX_PLATFORMS: cpu
    command:
      - {sys.executable}
      - -m
      - dlrover_tpu.trainer.elastic_run
      - --nnodes
      - "2:4"
      - --node_unit
      - "2"
      - --network-check
      - --rdzv_timeout
      - "10"
      - --monitor_interval
      - "0.3"
      - --heartbeat_interval
      - "2"
      - --max_restarts
      - "4"
      - {os.path.join(REPO, 'examples', 'dist_train.py')}
      - --
      - --steps
      - "600"
      - --ckpt-dir
      - {os.path.join(tmp, 'ckpt')}
      - --progress
      - {progress}
"""
    path = os.path.join(tmp, "job.yaml")
    with open(path, "w") as f:
        f.write(spec)
    return path, progress


def _read_progress(path):
    """[(step, world, loss, ts)] rows."""
    if not os.path.exists(path):
        return []
    rows = []
    for line in open(path):
        parts = line.strip().split(",")
        if len(parts) == 4:
            try:
                rows.append((int(parts[0]), int(parts[1]),
                             float(parts[2]), float(parts[3])))
            except ValueError:
                pass
    return rows


def _killpg(proc, sig=signal.SIGKILL):
    try:
        os.killpg(os.getpgid(proc.pid), sig)
    except (ProcessLookupError, PermissionError):
        pass


def test_four_node_straggler_shrink_live(tmp_path):
    tmp = str(tmp_path)
    spec_path, progress = _write_spec(tmp)
    env = _strip_axon(dict(os.environ))
    master_out = os.path.join(tmp, "master.out")
    master = subprocess.Popen(
        [sys.executable, "-m", "dlrover_tpu.master.main",
         "--job_spec", spec_path, "--port", "0",
         "--autoscale_interval", "10"],
        cwd=REPO, env=env,
        stdout=open(master_out, "w"),
        stderr=open(os.path.join(tmp, "master.err"), "w"),
        start_new_session=True,
    )
    try:
        # phase 1: the 4-node world forms and trains (agents launched
        # by the master's ProcessScaler from the job spec)
        deadline = time.time() + 240
        world4_step = None
        while time.time() < deadline:
            rows = _read_progress(progress)
            hi = [r for r in rows if r[1] == 4 and r[0] >= 7]
            if hi:
                world4_step = hi[-1][0]
                break
            assert master.poll() is None, (
                open(master_out).read()[-2000:]
                + open(os.path.join(tmp, "master.err")).read()[-2000:]
            )
            time.sleep(0.5)
        assert world4_step is not None, (
            "4-node world never trained past step 7; progress tail: "
            + str(_read_progress(progress)[-5:])
            + " master.err: "
            + open(os.path.join(tmp, "master.err")).read()[-3000:]
        )

        # phase 2: the auto-scaler's straggler shrink fires (rank 3's
        # probe was 15s slower than the median) and the world reforms
        # at the node_unit-aligned size of 2
        deadline = time.time() + 240
        world2_rows = []
        while time.time() < deadline:
            rows = _read_progress(progress)
            world2_rows = [r for r in rows if r[1] == 2]
            if world2_rows:
                break
            time.sleep(0.5)
        err = open(os.path.join(tmp, "master.err")).read()
        assert world2_rows, (
            "world never reformed at 2 after straggler shrink; "
            "progress tail: " + str(_read_progress(progress)[-5:])
            + " master.err: " + err[-3000:]
        )

        # the master really took the straggler path (not a generic
        # failure relaunch)
        assert re.search(r"shrink past stragglers \[3\]", err), (
            err[-3000:]
        )

        # phase 3: no flash-checkpoint loss — the shrunk world resumed
        # from a checkpointed step, not from scratch
        first_w2 = min(r[0] for r in world2_rows)
        assert first_w2 > 0, (
            f"world-2 run restarted from step 0 (checkpoint lost); "
            f"rows: {world2_rows[:3]}"
        )
    finally:
        _killpg(master, signal.SIGTERM)
        time.sleep(1.0)
        _killpg(master)
        # the master's scaler kills its agents on teardown; sweep any
        # stragglers of our own process tree
        subprocess.run(
            ["pkill", "-9", "-f", "straggler-drill"],
            capture_output=True,
        )
