"""The explainable resource advisor (ISSUE 19, brain/advisor.py).

Each rule is tested where its contract lives: the metric values it
reads, the proposal it emits, and — the point of the module — the
journaled evidence chain that lets ``dump --kind brain`` replay
exactly why. Advise-mode actuation must route through the scaler's
guarded path and leave a complete adopted/rejected audit trail.
"""

import time

from dlrover_tpu.brain import advisor as advisor_mod
from dlrover_tpu.brain.advisor import (
    MODE_ADVISE,
    MODE_OBSERVE,
    MODE_OFF,
    ResourceAdvisor,
    advisor_mode,
)
from dlrover_tpu.common import comm
from dlrover_tpu.common.constants import NodeType
from dlrover_tpu.telemetry.fleet import FleetAggregator, TimeSeriesStore
from dlrover_tpu.telemetry.goodput import Phase
from dlrover_tpu.telemetry.journal import (
    EventJournal,
    default_journal,
    set_default_journal,
)

import pytest


@pytest.fixture(autouse=True)
def _fresh_journal():
    set_default_journal(EventJournal())
    yield
    set_default_journal(EventJournal())


def _events(kind):
    return default_journal().events(kind)


def _summary(goodput_percent=50.0, wall_s=100.0, procs=2, nodes=2,
             badput=None, faults=0):
    return {"job": {
        "wall_s": wall_s, "procs": procs, "nodes": nodes,
        "goodput_percent": goodput_percent,
        "badput_s": badput or {}, "faults": faults,
    }}


class FakeGoodput:
    def __init__(self, per_job):
        self.per_job = per_job

    def jobs(self):
        return sorted(self.per_job)

    def summary(self, job=None):
        return self.per_job.get(job or "default", {"job": {}})


class FakeMonitor:
    def __init__(self, workers=4, speed=8.0):
        self.running_workers = {("worker", i) for i in range(workers)}
        self._target_worker_num = workers
        self._speed = speed

    def running_speed(self):
        return self._speed


class FakeQuarantine:
    def __init__(self, hosts):
        self._hosts = list(hosts)

    def quarantined_hosts(self):
        return list(self._hosts)


# ------------------------------------------------------------------- rules


def test_shrink_rule_fires_with_evidence_chain():
    """A job burning >threshold% of wall in ckpt_stall + rendezvous
    proposes a shrink; the journaled event carries the full evidence
    chain (window, metric values, rule, expected delta)."""
    gp = FakeGoodput({"a": _summary(
        goodput_percent=55.0,
        badput={Phase.CKPT_STALL: 30.0, Phase.RENDEZVOUS: 10.0},
    )})
    adv = ResourceAdvisor(
        goodput=gp, speed_monitors_fn=lambda: {"a": FakeMonitor(4)},
        local_job="a", node_unit=2, mode=MODE_OBSERVE, interval=0,
    )
    plans = adv.step(now=1000.0)
    assert [p["action"] for p in plans] == ["shrink"]
    p = plans[0]
    assert p["rule"] == "shrink_badput" and p["job"] == "a"
    assert p["target_nodes"] == 2  # 4 workers - node_unit
    assert p["expected_goodput_delta"] == pytest.approx(40.0)
    ev = _events("brain.plan_proposed")
    assert len(ev) == 1
    d = ev[0]["data"]
    assert d["rule"] == "shrink_badput" and d["action"] == "shrink"
    assert d["evidence_stall_pct"] == pytest.approx(40.0)
    assert d["evidence_ckpt_stall_s"] == 30.0
    assert d["evidence_rendezvous_s"] == 10.0
    assert d["evidence_window_s"] == 100.0
    assert d["evidence_threshold_pct"] == 25.0
    assert d["mode"] == MODE_OBSERVE


def test_shrink_rule_quiet_below_threshold():
    gp = FakeGoodput({"a": _summary(
        goodput_percent=85.0, badput={Phase.CKPT_STALL: 10.0},
    )})
    adv = ResourceAdvisor(goodput=gp, local_job="a",
                          mode=MODE_OBSERVE, interval=0)
    assert adv.step(now=1000.0) == []
    assert _events("brain.plan_proposed") == []


def test_grow_rule_requires_scaling_curve_and_no_stragglers():
    """Grow fires only for a straggler-free job at high goodput whose
    per-worker step rate held up — the advisor needs two speed
    observations before it will extrapolate."""
    gp = FakeGoodput({"a": _summary(goodput_percent=95.0)})
    fleet_agg = FleetAggregator(store=TimeSeriesStore(max_mb=4))
    mon = FakeMonitor(workers=4, speed=8.0)
    adv = ResourceAdvisor(
        fleet=fleet_agg, goodput=gp,
        speed_monitors_fn=lambda: {"a": mon},
        local_job="a", node_unit=1, mode=MODE_OBSERVE, interval=0,
    )
    # first pass only seeds the curve: no proposal yet
    assert adv.step(now=1000.0) == []
    plans = adv.step(now=1200.0)
    assert [p["rule"] for p in plans] == ["grow_scaling"]
    p = plans[0]
    assert p["action"] == "grow" and p["target_nodes"] == 5
    assert p["expected_goodput_delta"] > 0
    d = _events("brain.plan_proposed")[0]["data"]
    assert d["evidence_scaling_retention"] == pytest.approx(1.0)
    assert d["evidence_workers"] == 4
    # a degraded curve (per-worker rate fell 20%) stops proposing
    mon2 = FakeMonitor(workers=4, speed=8.0)
    adv2 = ResourceAdvisor(
        fleet=fleet_agg, goodput=gp,
        speed_monitors_fn=lambda: {"a": mon2},
        local_job="a", mode=MODE_OBSERVE, interval=0,
    )
    adv2.step(now=1000.0)
    mon2._speed = 6.0
    assert adv2.step(now=1200.0) == []
    # a straggler parks the grow even with a healthy curve
    fleet_agg.observe_report(comm.NodeStatusReport(
        node_id=0, node_type=NodeType.WORKER, timestamp=time.time(),
        host="host-0", has_step=True, step=10, step_ts=time.time(),
        job_id="a",
    ))
    fleet_agg.observe_report(comm.NodeStatusReport(
        node_id=1, node_type=NodeType.WORKER, timestamp=time.time(),
        host="host-1", has_step=True, step=90, step_ts=time.time(),
        job_id="a",
    ))
    adv3 = ResourceAdvisor(
        fleet=fleet_agg, goodput=gp,
        speed_monitors_fn=lambda: {"a": FakeMonitor(4, 8.0)},
        local_job="a", mode=MODE_OBSERVE, interval=0,
    )
    adv3.step(now=1000.0)
    assert adv3.step(now=1200.0) == []


def test_reclaim_rule_flags_quarantined_host_still_reporting():
    fleet_agg = FleetAggregator(store=TimeSeriesStore(max_mb=4))
    fleet_agg.observe_report(comm.NodeStatusReport(
        node_id=7, node_type=NodeType.WORKER, timestamp=time.time(),
        host="host-7", has_step=True, step=50, step_ts=time.time(),
    ))
    gp = FakeGoodput({"default": _summary(
        badput={Phase.RESTART: 20.0}, faults=3,
    )})
    adv = ResourceAdvisor(
        fleet=fleet_agg, goodput=gp,
        quarantine=FakeQuarantine(["host-7"]),
        mode=MODE_OBSERVE, interval=0,
    )
    plans = adv.step(now=1000.0)
    assert [p["rule"] for p in plans] == ["reclaim_quarantine"]
    p = plans[0]
    assert p["action"] == "reclaim" and p["host"] == "host-7"
    assert p["expected_goodput_delta"] == pytest.approx(20.0)
    d = _events("brain.plan_proposed")[0]["data"]
    assert d["host"] == "host-7"
    assert d["evidence_quarantined"] and d["evidence_still_reporting"]
    assert d["evidence_restart_badput_s"] == 20.0
    # an evicted (no longer reporting) host stops proposing
    fleet_agg.observe_report(comm.NodeStatusReport(
        node_id=7, node_type=NodeType.WORKER, timestamp=time.time(),
        host="host-7", final=True,
    ))
    adv2 = ResourceAdvisor(
        fleet=fleet_agg, goodput=gp,
        quarantine=FakeQuarantine(["host-7"]),
        mode=MODE_OBSERVE, interval=0,
    )
    assert adv2.step(now=2000.0) == []


# --------------------------------------------------------- cadence/cooldown


def test_proposal_cooldown_and_step_rate_limit():
    gp = FakeGoodput({"a": _summary(
        badput={Phase.CKPT_STALL: 40.0},
    )})
    adv = ResourceAdvisor(goodput=gp, local_job="a",
                          mode=MODE_OBSERVE, interval=30)
    adv.maybe_step(now=1000.0)
    # within the interval: the beat is a no-op
    adv.maybe_step(now=1010.0)
    assert len(_events("brain.plan_proposed")) == 1
    # past the interval but inside the per-(job, action) cooldown
    # (default 120s): the persistent condition does not re-journal
    adv.maybe_step(now=1040.0)
    assert len(_events("brain.plan_proposed")) == 1
    adv.maybe_step(now=1200.0)
    assert len(_events("brain.plan_proposed")) == 2


def test_off_mode_disables_everything():
    gp = FakeGoodput({"a": _summary(
        badput={Phase.CKPT_STALL: 40.0},
    )})
    adv = ResourceAdvisor(goodput=gp, local_job="a", mode=MODE_OFF,
                          interval=0)
    adv.start()
    adv.maybe_step(now=1000.0)
    assert _events("brain.advisor_started") == []
    assert _events("brain.plan_proposed") == []


def test_advisor_mode_env_parsing(monkeypatch):
    for raw, want in (
        ("", MODE_OBSERVE), ("observe", MODE_OBSERVE),
        ("shadow", MODE_OBSERVE), ("advise", MODE_ADVISE),
        ("ADVISE", MODE_ADVISE), ("off", MODE_OFF),
        ("0", MODE_OFF), ("nonsense", MODE_OFF),
    ):
        monkeypatch.setenv(advisor_mod.ENV_BRAIN, raw)
        assert advisor_mode() == want, raw
    monkeypatch.delenv(advisor_mod.ENV_BRAIN)
    assert advisor_mode() == MODE_OBSERVE


# ---------------------------------------------------------------- actuation


def test_advise_mode_routes_local_job_through_scaler():
    gp = FakeGoodput({"a": _summary(
        badput={Phase.CKPT_STALL: 40.0},
    )})
    scaled = []
    adv = ResourceAdvisor(
        goodput=gp, speed_monitors_fn=lambda: {"a": FakeMonitor(4)},
        scale_fn=lambda n: (scaled.append(n), True)[1],
        local_job="a", node_unit=1, mode=MODE_ADVISE, interval=0,
    )
    adv.start()
    assert _events("brain.advisor_started")[0]["data"]["mode"] == \
        MODE_ADVISE
    adv.step(now=1000.0)
    assert scaled == [3]  # 4 workers - 1 unit, via manual_scale guards
    adopted = _events("brain.plan_adopted")
    assert len(adopted) == 1
    assert adopted[0]["data"]["target_nodes"] == 3
    assert _events("brain.plan_rejected") == []


def test_advise_mode_rejects_nonlocal_and_failed_scales():
    """A sibling job's plan and a declined/crashed scale are journaled
    as rejected with the reason — the audit trail is complete."""
    gp = FakeGoodput({
        "a": _summary(badput={Phase.CKPT_STALL: 40.0}),
        "b": _summary(badput={Phase.CKPT_STALL: 60.0}),
    })
    adv = ResourceAdvisor(
        goodput=gp, speed_monitors_fn=lambda: {},
        scale_fn=lambda n: (_ for _ in ()).throw(RuntimeError("no")),
        local_job="a", mode=MODE_ADVISE, interval=0,
    )
    adv.step(now=1000.0)
    rejected = {
        e["data"]["job"]: e["data"]["reason"]
        for e in _events("brain.plan_rejected")
    }
    assert rejected["a"] == "scaler_declined"  # scale_fn raised
    assert rejected["b"] == "job_not_local"
    assert _events("brain.plan_adopted") == []


def test_observe_mode_never_touches_the_scaler():
    gp = FakeGoodput({"a": _summary(
        badput={Phase.CKPT_STALL: 40.0},
    )})
    scaled = []
    adv = ResourceAdvisor(
        goodput=gp, scale_fn=lambda n: (scaled.append(n), True)[1],
        local_job="a", mode=MODE_OBSERVE, interval=0,
    )
    adv.step(now=1000.0)
    assert len(_events("brain.plan_proposed")) == 1
    assert scaled == []
    assert _events("brain.plan_adopted") == []


def test_rule_crash_never_escapes_maybe_step():
    class ExplodingGoodput:
        def jobs(self):
            return ["a"]

        def summary(self, job=None):
            raise RuntimeError("ledger on fire")

    adv = ResourceAdvisor(goodput=ExplodingGoodput(), local_job="a",
                          mode=MODE_OBSERVE, interval=0)
    adv.maybe_step(now=1000.0)  # must not raise
    assert _events("brain.plan_proposed") == []
