"""GPT-2/NeoX family tests — same contract as the Llama family."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

# tier-1 budget (ISSUE 2 satellite): this module costs >50s of the
# 870s budget on a 1-core box; the nightly/full shard still runs it
pytestmark = pytest.mark.slow

from dlrover_tpu.models import gpt
from dlrover_tpu.parallel.mesh import create_mesh


def test_forward_shapes_and_param_count():
    cfg = gpt.gpt_tiny()
    params = gpt.init_params(jax.random.key(0), cfg)
    actual = sum(
        int(np.prod(p.shape)) for p in jax.tree.leaves(params)
    )
    assert actual == gpt.param_count(cfg)
    tokens = jax.random.randint(jax.random.key(1), (2, 32), 0,
                                cfg.vocab_size)
    logits = gpt.forward(params, tokens, cfg)
    assert logits.shape == (2, 32, cfg.vocab_size)
    assert logits.dtype == jnp.float32


def test_causality():
    cfg = gpt.gpt_tiny()
    params = gpt.init_params(jax.random.key(0), cfg)
    t1 = jax.random.randint(jax.random.key(1), (1, 16), 0,
                            cfg.vocab_size)
    t2 = t1.at[0, 10:].set((t1[0, 10:] + 1) % cfg.vocab_size)
    l1 = gpt.forward(params, t1, cfg)
    l2 = gpt.forward(params, t2, cfg)
    np.testing.assert_allclose(
        np.asarray(l1[0, :10]), np.asarray(l2[0, :10]),
        rtol=2e-2, atol=2e-2,
    )


def test_untied_head_and_gqa_variant():
    cfg = gpt.gpt_tiny(tie_lm_head=False, num_kv_heads=2)
    params = gpt.init_params(jax.random.key(0), cfg)
    assert "lm_head" in params
    assert params["blocks"]["wk"].shape[-1] == 2 * cfg.head_dim
    tokens = jax.random.randint(jax.random.key(1), (2, 16), 0,
                                cfg.vocab_size)
    logits = gpt.forward(params, tokens, cfg)
    assert logits.shape == (2, 16, cfg.vocab_size)
    # axes tree mirrors the params tree exactly
    assert (
        jax.tree.structure(
            gpt.param_axes(cfg),
            is_leaf=lambda x: isinstance(x, tuple),
        ).num_leaves
        == len(jax.tree.leaves(params))
    )


@pytest.mark.parametrize("strategy", ["fsdp", "tp_fsdp", "zero1"])
def test_sharded_training_learns(strategy):
    cfg = gpt.gpt_tiny()
    mesh = create_mesh([("data", 2), ("fsdp", 2), ("tensor", 2)])
    trainer = gpt.make_trainer(
        cfg, mesh, strategy=strategy, optimizer=optax.adam(1e-2),
    )
    params, opt_state = trainer.init(jax.random.key(0))
    tokens = np.asarray(jax.random.randint(
        jax.random.key(1), (8, 16), 0, cfg.vocab_size
    ))
    batch = trainer.shard_batch(trainer.microbatch((tokens, tokens)))
    losses = []
    for _ in range(8):
        params, opt_state, loss = trainer.train_step(
            params, opt_state, batch
        )
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.8, losses


def test_chunked_ce_matches_full():
    cfg_full = gpt.gpt_tiny()
    cfg_chunk = gpt.gpt_tiny(loss_chunk=16)
    params = gpt.init_params(jax.random.key(0), cfg_full)
    tokens = jax.random.randint(jax.random.key(1), (2, 24), 0,
                                cfg_full.vocab_size)
    batch = (tokens, tokens)
    full = gpt.next_token_loss(params, batch, cfg_full)
    chunked = gpt.next_token_loss(params, batch, cfg_chunk)
    np.testing.assert_allclose(
        float(full), float(chunked), rtol=1e-4
    )


def test_auto_accelerate_on_gpt_family():
    """Strategy search dispatches across model families: search + init
    + one step on the GPT config."""
    from dlrover_tpu.auto.accelerate import auto_accelerate

    cfg = gpt.gpt_tiny()
    result = auto_accelerate(
        cfg, global_batch=8, seq_len=32, hbm_bytes=16e9,
    )
    assert result.strategy.num_devices == 8
    params, opt_state = result.trainer.init(jax.random.key(0))
    tokens = np.random.randint(0, cfg.vocab_size, (8, 32),
                               dtype=np.int32)
    batch = result.trainer.shard_batch(
        result.trainer.microbatch((tokens, tokens))
    )
    _, _, loss = result.trainer.train_step(params, opt_state, batch)
    assert np.isfinite(float(loss))
