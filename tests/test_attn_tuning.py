"""Persistent kernel autotuner (ops/tuning.py).

Runs entirely on CPU (interpret mode): the measure path is stubbed
where a test needs to prove it does or does not run, so no TPU is
required for full coverage of the cache-key, persistence, and
fallback contracts.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlrover_tpu.ops import tuning
from dlrover_tpu.ops.attention import flash_attention, mha_reference
from dlrover_tpu.trainer import profiler


@pytest.fixture
def cache_dir(tmp_path, monkeypatch):
    d = str(tmp_path / "tuning")
    monkeypatch.setenv(tuning.ENV_TUNING_CACHE_DIR, d)
    tuning.reset_cache_memo()
    yield d
    tuning.reset_cache_memo()


def _key(**over):
    base = dict(
        kernel="flash_attention", seq=2048, head_dim=64, gqa_group=8,
        dtype="bfloat16", causal=True, device_kind="TPU v5e",
    )
    base.update(over)
    return tuning.TuningKey(**base)


# ------------------------------------------------------------------ keys


def test_cache_key_roundtrip():
    key = _key()
    assert tuning.TuningKey.from_dict(key.to_dict()) == key
    # json round-trip (what the cache file stores)
    assert tuning.TuningKey.from_dict(
        json.loads(json.dumps(key.to_dict()))
    ) == key


def test_cache_key_filename_stable_and_distinct():
    a, b = _key(), _key()
    assert a.filename() == b.filename()
    assert _key(seq=4096).filename() != a.filename()
    assert _key(causal=False).filename() != a.filename()
    assert _key(device_kind="TPU v4").filename() != a.filename()
    # filesystem-safe despite spaces in device_kind
    assert "/" not in a.filename() and " " not in a.filename()


def test_heuristic_matches_pre_tuning_logic():
    # g=1: full 1024x1024; g=8: q rows capped at 128
    assert tuning.heuristic_blocks(2048, 1) == (1024, 1024)
    assert tuning.heuristic_blocks(2048, 8) == (128, 1024)
    # caller cap below the 128 minimum -> no candidates -> XLA path
    assert tuning.heuristic_blocks(2048, 1, block_q=64) is None
    # nothing divides a non-pow2-multiple seq
    assert tuning.heuristic_blocks(100, 1) is None


def test_candidate_grid_heuristic_first():
    grid = tuning.candidate_grid(2048, 8)
    assert grid[0] == tuning.heuristic_blocks(2048, 8)
    assert len(set(grid)) == len(grid)


# ------------------------------------------------------- persistence


def test_store_lookup_roundtrip(cache_dir):
    cache = tuning.get_cache()
    key = _key()
    assert cache.lookup(key) is None
    cache.store(key, (256, 512), measured_ms=1.25)
    assert cache.lookup(key) == (256, 512)
    # a FRESH handle (restarted worker) reads it from disk
    fresh = tuning.TuningCache(cache.path)
    assert fresh.lookup(key) == (256, 512)
    assert fresh.entries() == 1


def test_corrupt_entry_is_a_miss_not_an_error(cache_dir):
    cache = tuning.get_cache()
    key = _key()
    path = os.path.join(cache.path, key.filename())
    with open(path, "w") as f:
        f.write("{not json")
    assert cache.lookup(key) is None  # no raise
    # schema-mismatched and block-invalid entries also miss
    for bad in (
        {"version": 99, "key": key.to_dict(), "block_q": 128,
         "block_k": 128},
        {"version": 1, "key": key.to_dict(), "block_q": 999,
         "block_k": 128},
        {"version": 1, "key": _key(seq=4096).to_dict(),
         "block_q": 128, "block_k": 128},
    ):
        with open(path, "w") as f:
            json.dump(bad, f)
        assert tuning.TuningCache(cache.path).lookup(key) is None


def test_corrupt_entry_falls_back_to_heuristic(cache_dir, monkeypatch):
    """get_blocks over a corrupt entry: no raise, and with measurement
    unavailable the heuristic prior comes back."""
    key_file = _key(device_kind="cpu", dtype="float32")
    cache = tuning.get_cache()
    with open(os.path.join(cache.path, key_file.filename()), "w") as f:
        f.write("garbage")
    monkeypatch.setattr(tuning, "_measurement_enabled", lambda: True)
    monkeypatch.setattr(
        jax, "devices",
        lambda *a: [type("D", (), {"device_kind": "cpu"})()],
    )
    monkeypatch.setattr(
        tuning, "measure_candidates", lambda key, cands: []
    )
    blocks = tuning.get_blocks(
        seq=2048, head_dim=64, group=8, dtype="float32", causal=True
    )
    assert blocks == tuning.heuristic_blocks(2048, 8)


def test_untrusted_dir_degrades_to_memory_only(tmp_path, monkeypatch):
    d = tmp_path / "loose"
    d.mkdir()
    real_stat = os.stat

    class FakeStat:
        def __init__(self, st):
            self.st_uid = st.st_uid + 1  # someone else's dir
            self.st_mode = st.st_mode

    monkeypatch.setattr(
        os, "stat",
        lambda p, *a, **k: FakeStat(real_stat(p, *a, **k))
        if str(p) == str(d) else real_stat(p, *a, **k),
    )
    tuning.reset_cache_memo()
    cache = tuning.get_cache(str(d))
    assert cache.path is None  # refused, no persistence
    key = _key()
    cache.store(key, (128, 128))
    assert cache.lookup(key) == (128, 128)  # memory still works
    assert not list(d.iterdir())
    tuning.reset_cache_memo()


def test_adopted_loose_dir_is_tightened(tmp_path):
    from dlrover_tpu.common.cachedir import ensure_private_dir

    d = str(tmp_path / "world_readable")
    os.makedirs(d, mode=0o755)
    os.chmod(d, 0o755)  # defeat umask
    assert ensure_private_dir(d) == d
    assert (os.stat(d).st_mode & 0o777) == 0o700


# ------------------------------------------------------------ get_blocks


def test_cpu_path_never_measures(cache_dir, monkeypatch):
    """Off-TPU the autotuner must do ZERO timing runs and return the
    exact heuristic answer (the bitwise-identity contract)."""

    def boom(*a, **k):
        raise AssertionError("measure path entered on CPU")

    monkeypatch.setattr(tuning, "measure_candidates", boom)
    monkeypatch.setattr(tuning, "timeit", boom)
    blocks = tuning.get_blocks(
        seq=2048, head_dim=64, group=8, dtype="bfloat16", causal=True
    )
    assert blocks == tuning.heuristic_blocks(2048, 8)
    # and the full attention op still matches the XLA reference
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((1, 256, 4, 64)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((1, 256, 4, 64)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((1, 256, 4, 64)), jnp.float32)
    np.testing.assert_array_equal(
        np.asarray(flash_attention(q, k, v)),
        np.asarray(mha_reference(q, k, v)),
    )


def test_persisted_winner_honored_without_remeasure(cache_dir,
                                                   monkeypatch):
    """First call measures and persists; a second construction (fresh
    in-memory state, same host dir) reads the winner from disk and the
    measure path is NOT re-entered."""
    calls = []

    def fake_measure(key, cands):
        calls.append(key)
        return [(bq, bk, 1.0 + i) for i, (bq, bk) in enumerate(cands)]

    monkeypatch.setattr(tuning, "_measurement_enabled", lambda: True)
    monkeypatch.setattr(
        jax, "devices",
        lambda *a: [type("D", (), {"device_kind": "TPU v5e"})()],
    )
    monkeypatch.setattr(tuning, "measure_candidates", fake_measure)

    kwargs = dict(
        seq=2048, head_dim=64, group=8, dtype="bfloat16", causal=True
    )
    first = tuning.get_blocks(**kwargs)
    assert len(calls) == 1
    # fake timings make the first candidate (the heuristic) fastest
    assert first == tuning.candidate_grid(2048, 8)[0]
    assert tuning.get_cache().entries() == 1

    # simulate a restarted worker: drop ALL in-process state
    tuning.reset_cache_memo()
    second = tuning.get_blocks(**kwargs)
    assert second == first
    assert len(calls) == 1, "measure path re-entered despite cache"
    sel = tuning.last_selection()
    assert sel["source"] == "cache"
    assert (sel["block_q"], sel["block_k"]) == first


def test_winner_is_fastest_candidate(cache_dir, monkeypatch):
    monkeypatch.setattr(tuning, "_measurement_enabled", lambda: True)
    monkeypatch.setattr(
        jax, "devices",
        lambda *a: [type("D", (), {"device_kind": "TPU v5e"})()],
    )
    grid = tuning.candidate_grid(1024, 1)
    want = grid[len(grid) // 2]

    def fake_measure(key, cands):
        return [
            (bq, bk, 0.5 if (bq, bk) == want else 2.0)
            for bq, bk in cands
        ]

    monkeypatch.setattr(tuning, "measure_candidates", fake_measure)
    got = tuning.get_blocks(
        seq=1024, head_dim=64, group=1, dtype="bfloat16", causal=True
    )
    assert got == want
    assert tuning.last_selection()["source"] == "measured"


def test_tuning_event_reaches_profiler(cache_dir, monkeypatch):
    monkeypatch.setattr(tuning, "_measurement_enabled", lambda: True)
    monkeypatch.setattr(
        jax, "devices",
        lambda *a: [type("D", (), {"device_kind": "TPU v5e"})()],
    )
    monkeypatch.setattr(
        tuning, "measure_candidates",
        lambda key, cands: [(bq, bk, 1.0) for bq, bk in cands],
    )
    before = len(profiler.tuning_events())
    tuning.get_blocks(
        seq=512, head_dim=128, group=2, dtype="float32", causal=False
    )
    events = profiler.tuning_events()
    assert len(events) == before + 1
    evt = events[-1]
    assert evt["kernel"] == "flash_attention"
    assert evt["seq"] == 512 and evt["source"] == "measured"


def test_caller_caps_join_the_filter(cache_dir):
    # an explicit cap below every valid block -> None (XLA fallback)
    assert tuning.get_blocks(
        seq=2048, head_dim=64, group=1, dtype="bfloat16", causal=True,
        block_q=32,
    ) is None
