"""Sparse-embedding recommender family (BASELINE config #4, VERDICT r3
Missing #1): vocab-parallel lookup exactness (fwd + grad), rowwise
training over the 8-device mesh, padding-mask semantics, and the
capacity argument — a table bigger than one chip's HBM plans onto the
mesh via the ordinary vocab-axis rules."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlrover_tpu.models import dlrm, model_module_for
from dlrover_tpu.parallel.embedding import vocab_parallel_lookup
from dlrover_tpu.parallel.mesh import create_mesh


def _mesh():
    return create_mesh([("data", 2), ("fsdp", 4)])


def test_lookup_matches_dense_gather_forward_and_grad():
    mesh = _mesh()
    V, D, B, F = 64, 8, 16, 5
    table = jax.random.normal(jax.random.key(0), (V, D))
    ids = jax.random.randint(jax.random.key(1), (B, F), 0, V)

    got = jax.jit(
        lambda t, i: vocab_parallel_lookup(t, i, mesh)
    )(table, ids)
    np.testing.assert_allclose(got, table[ids], rtol=1e-6)

    g_sharded = jax.jit(jax.grad(
        lambda t: jnp.sum(vocab_parallel_lookup(t, ids, mesh) ** 2)
    ))(table)
    g_dense = jax.grad(lambda t: jnp.sum(t[ids] ** 2))(table)
    np.testing.assert_allclose(g_sharded, g_dense, rtol=1e-6)


def test_lookup_rejects_batch_on_table_axis():
    mesh = _mesh()
    table = jnp.zeros((64, 8))
    ids = jnp.zeros((4, 2), jnp.int32)
    with pytest.raises(ValueError, match="must not include"):
        vocab_parallel_lookup(
            table, ids, mesh, batch_axes=("data", "fsdp")
        )


def test_contract_and_dispatch():
    cfg = dlrm.criteo_wide_deep()
    assert model_module_for(cfg) is dlrm
    assert cfg.total_vocab == 733578  # sum of the CRITEO vocab stats
    assert cfg.padded_vocab % 1024 == 0
    params = dlrm.init_params(jax.random.key(0), cfg)
    n = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))
    assert n == dlrm.param_count(cfg)
    assert dlrm.flops_per_token(cfg) > 0
    assert dlrm.table_bytes(cfg) > 4 * cfg.total_vocab * cfg.embed_dim


def test_dot_interaction_shape_guard():
    with pytest.raises(ValueError, match="bottom_mlp"):
        dlrm.DLRMConfig(embed_dim=16, bottom_mlp=(64, 8))


def test_padding_rows_carry_no_gradient():
    """Label -1 rows (elastic tail-shard padding) must not contribute
    to the loss or to table gradients."""
    cfg = dlrm.criteo_wide_deep(
        vocab_sizes=(50,) * 4, row_align=8
    )
    params = dlrm.init_params(jax.random.key(0), cfg)
    rng = np.random.RandomState(0)
    dense = rng.randn(6, cfg.dense_dim).astype(np.float32)
    cat = rng.randint(0, 50, (6, 4)).astype(np.int32)
    labels = np.array([1, 0, 1, 0, 1, 1], np.int32)

    loss_plain = dlrm.loss(
        params, (dense[:4], cat[:4], labels[:4]), cfg
    )
    padded_labels = labels.copy()
    padded_labels[4:] = -1
    loss_padded = dlrm.loss(params, (dense, cat, padded_labels), cfg)
    np.testing.assert_allclose(
        float(loss_plain), float(loss_padded), rtol=1e-6
    )
    g = jax.grad(
        lambda p: dlrm.loss(p, (dense, cat, padded_labels), cfg)
    )(params)
    # rows referenced ONLY by padded examples get zero grad
    only_padded = set(np.unique(cat[4:])) - set(np.unique(cat[:4]))
    if only_padded:
        row = sorted(only_padded)[0]
        assert float(jnp.sum(jnp.abs(g["table"][row]))) == 0.0


def test_rowwise_training_learns_on_mesh():
    """e2e on the 8-device mesh: table sharded over fsdp, batch over
    data; the planted click rule is learned (loss drops, acc beats
    the base rate). Compact vocab: this verifies the SHARDED math, and
    a CRITEO-size table's per-device dense update starves the XLA CPU
    collective watchdog when 8 device threads share one host core
    (the launcher e2e runs the full CRITEO config single-device)."""
    import os
    import sys

    examples = os.path.join(
        os.path.dirname(__file__), "..", "examples"
    )
    sys.path.insert(0, examples)
    try:
        from dlrm_train import make_clicks
    finally:
        sys.path.remove(examples)

    cfg = dlrm.criteo_wide_deep(
        vocab_sizes=(64, 40, 96, 8, 200, 33, 4, 120), row_align=8
    )
    mesh = _mesh()
    trainer = dlrm.make_trainer(cfg, mesh)
    params, opt_state = trainer.init(jax.random.key(0))
    assert "fsdp" in str(params["table"].sharding.spec)

    dense, cat, labels = make_clicks(512, cfg)
    first = None
    for i in range(80):
        lo = (i * 128) % 512
        batch = trainer.shard_batch((
            dense[None, lo:lo + 128], cat[None, lo:lo + 128],
            labels[None, lo:lo + 128],
        ))
        params, opt_state, loss = trainer.train_step(
            params, opt_state, batch
        )
        if first is None:
            first = float(loss)
    assert float(loss) < 0.8 * first, (first, float(loss))
    # probe under jit: EAGER shard_map collectives dispatch per-op and
    # can trip XLA CPU's stuck-rendezvous watchdog on a loaded host
    logits = jax.jit(
        lambda p, d, c: dlrm.forward(p, d, c, cfg, mesh=mesh)
    )(params, jnp.asarray(dense), jnp.asarray(cat))
    acc = float(jnp.mean(
        (logits > 0).astype(np.int32) == jnp.asarray(labels)
    ))
    base = max(labels.mean(), 1 - labels.mean())
    assert acc > base, (acc, base)


def test_large_table_exceeds_chip_but_plans_onto_mesh():
    """The capacity argument the PS served in the reference: a 26.4 GB
    stacked table (incl. the wide column) cannot live on one 15.75 GB
    chip; the planner's vocab-axis rule shards it over fsdp and the
    per-chip state fits."""
    from dlrover_tpu.auto.planner import plan_rules

    hbm = 15.75e9
    cfg = dlrm.dlrm_large(total_vocab=200_000_000, embed_dim=32)
    assert dlrm.table_bytes(cfg) > hbm  # one chip cannot hold it

    abs_params = jax.eval_shape(
        lambda k: dlrm.init_params(k, cfg), jax.random.key(0)
    )
    plan = plan_rules(
        abs_params, dlrm.param_axes(cfg), {"fsdp": 8}, hbm,
        tokens_per_step=8192, hidden_size=cfg.embed_dim,
        num_layers=cfg.num_layers, batch_axes=("data",),
        # f32 params + adagrad accumulator + grads ~ 3x in-dtype bytes
        state_bytes_multiplier=3.0,
    )
    assert plan.rules.get("vocab") == "fsdp"
    assert plan.memory_bytes < hbm
    assert plan.memory_bytes * 8 >= dlrm.table_bytes(cfg) * 3 * 0.9


def test_out_of_range_ids_clip_within_own_feature():
    """Review fix: an id >= its feature's vocab clips to the feature's
    LAST row rather than silently reading a neighboring feature."""
    cfg = dlrm.criteo_wide_deep(vocab_sizes=(4, 4), row_align=8)
    params = dlrm.init_params(jax.random.key(0), cfg)
    dense = np.zeros((1, cfg.dense_dim), np.float32)
    bad = np.array([[9, 0]], np.int32)      # feature-0 id out of range
    clipped = np.array([[3, 0]], np.int32)  # feature 0's last valid row
    out_bad = dlrm.forward(params, dense, bad, cfg)
    out_clip = dlrm.forward(params, dense, clipped, cfg)
    np.testing.assert_allclose(
        np.asarray(out_bad), np.asarray(out_clip), rtol=1e-6
    )


def test_auto_accelerate_dispatches_dlrm():
    """The auto layer runs the recommender family end to end: rowwise
    candidates are enumerated, configs without a remat field survive
    strategy application, and the dryrun times real (dense, cat,
    labels) batches."""
    from dlrover_tpu.auto.accelerate import auto_accelerate

    cfg = dlrm.criteo_wide_deep(
        vocab_sizes=(64, 40, 96, 8), row_align=8
    )
    result = auto_accelerate(
        cfg, global_batch=64, seq_len=1,
        devices=jax.devices()[:8], dryrun_top_k=2,
    )
    shardings = {r.strategy.sharding for r in result.reports}
    assert "rowwise" in shardings
    # the winner actually trains on the family's batch structure
    trainer = result.trainer
    params, opt_state = trainer.init(jax.random.key(0))
    import os
    import sys

    examples = os.path.join(
        os.path.dirname(__file__), "..", "examples"
    )
    sys.path.insert(0, examples)
    try:
        from dlrm_train import make_clicks
    finally:
        sys.path.remove(examples)

    dense, cat, labels = make_clicks(64, cfg)
    batch = trainer.shard_batch(trainer.microbatch(
        (dense, cat, labels)
    ))
    _, _, loss = trainer.train_step(params, opt_state, batch)
    assert np.isfinite(float(loss))
