"""Lockwatch unit tests (ISSUE 15): the runtime lock-order watchdog.

The load-bearing case seeds a genuine A→B / B→A acquisition-order
inversion through watched locks and asserts the watchdog journals
``lockwatch.cycle`` — the runtime twin of what dlint's lock rules
prove statically. The rest pins the machinery the drill relies on:
long-hold detection, Condition compatibility (wait() must keep the
held-stack honest), the install filter (only project-created locks are
wrapped), and the reentrancy guard.
"""

import json
import os
import sys
import threading
import time

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dlrover_tpu.telemetry import journal as journal_mod  # noqa: E402
from dlrover_tpu.telemetry import lockwatch  # noqa: E402
from dlrover_tpu.telemetry.lockwatch import (  # noqa: E402
    LockWatch,
    _ORIG_LOCK,
    _WatchedLock,
    _guard,
)


@pytest.fixture()
def events():
    """Capture every journal event recorded during the test."""
    seen = []
    journal_mod.add_tap(seen.append)
    try:
        yield seen
    finally:
        journal_mod.remove_tap(seen.append)


def _watched(name, watch):
    return _WatchedLock(_ORIG_LOCK(), name, watch)


def _kinds(events):
    return [e.get("kind") for e in events]


# ----------------------------------------------------------------- cycles


def test_inversion_journals_cycle(events):
    """A→B on one path, B→A on another: the second edge closes a cycle
    and must journal ``lockwatch.cycle`` exactly once."""
    watch = LockWatch(long_hold_s=60.0)
    a = _watched("a.py:1", watch)
    b = _watched("b.py:2", watch)

    with a:
        with b:  # edge a->b
            pass
    with b:
        with a:  # edge b->a: closes the cycle
            pass

    cycles = watch.cycles()
    assert len(cycles) == 1, cycles
    assert set(cycles[0]) == {"a.py:1", "b.py:2"}, cycles

    recs = [e for e in events if e.get("kind") == "lockwatch.cycle"]
    assert len(recs) == 1, _kinds(events)
    data = recs[0]["data"]
    assert set(data["cycle"]) == {"a.py:1", "b.py:2"}, data
    assert "->" in data["edge"], data
    assert data["thread"] == threading.current_thread().name

    # the same inversion again: the cycle was already seen, no re-spam
    with b:
        with a:
            pass
    assert len([e for e in events
                if e.get("kind") == "lockwatch.cycle"]) == 1


def test_consistent_order_is_silent(events):
    """A→B taken A→B everywhere is healthy — no cycle, no journal."""
    watch = LockWatch(long_hold_s=60.0)
    a = _watched("a.py:1", watch)
    b = _watched("b.py:2", watch)
    for _ in range(3):
        with a:
            with b:
                pass
    assert watch.cycles() == []
    assert "lockwatch.cycle" not in _kinds(events)
    assert watch.snapshot()["edges"] == {"a.py:1": ["b.py:2"]}


def test_cross_thread_inversion(events):
    """The graph is global: the two halves of the inversion may come
    from different threads (the realistic deadlock shape)."""
    watch = LockWatch(long_hold_s=60.0)
    a = _watched("a.py:1", watch)
    b = _watched("b.py:2", watch)

    with a:
        with b:
            pass

    def other():
        with b:
            with a:
                pass

    t = threading.Thread(target=other, name="lockwatch-test-other")
    t.start()
    t.join()
    recs = [e for e in events if e.get("kind") == "lockwatch.cycle"]
    assert len(recs) == 1, _kinds(events)
    assert recs[0]["data"]["thread"] == "lockwatch-test-other"


# -------------------------------------------------------------- long hold


def test_long_hold_journals_once(events):
    watch = LockWatch(long_hold_s=0.01)
    a = _watched("slow.py:9", watch)
    for _ in range(2):
        with a:
            time.sleep(0.03)
    recs = [e for e in events if e.get("kind") == "lockwatch.long_hold"]
    assert len(recs) == 1, _kinds(events)  # once per lock, not per hold
    data = recs[0]["data"]
    assert data["lock"] == "slow.py:9"
    assert data["held_ms"] >= 10.0, data
    assert data["threshold_ms"] == 10.0, data
    snap = watch.snapshot()
    assert snap["long_holds_ms"]["slow.py:9"] >= 10.0, snap


def test_fast_hold_is_silent(events):
    watch = LockWatch(long_hold_s=60.0)
    a = _watched("fast.py:3", watch)
    with a:
        pass
    assert "lockwatch.long_hold" not in _kinds(events)
    assert watch.snapshot()["long_holds_ms"] == {}


# -------------------------------------------------------------- condition


def test_condition_wait_keeps_stack_honest():
    """``threading.Condition(watched_lock)``: wait() releases and
    reacquires through _release_save/_acquire_restore — the held-stack
    must be empty afterwards and no phantom edges may appear."""
    watch = LockWatch(long_hold_s=60.0)
    inner = _watched("cv.py:5", watch)
    cv = threading.Condition(inner)
    with cv:
        cv.wait(timeout=0.01)
    assert watch._stack() == []
    assert watch.snapshot()["edges"] == {}


def test_rlock_reentry_adds_no_edges():
    watch = LockWatch(long_hold_s=60.0)
    import threading as _t
    r = _WatchedLock(_t.RLock(), "re.py:7", watch)
    with r:
        with r:  # re-entry: no self-edge, no cycle
            pass
    assert watch.snapshot()["edges"] == {}
    assert watch.cycles() == []
    assert watch._stack() == []


# ---------------------------------------------------------------- install


def test_install_wraps_project_locks_only(events, monkeypatch, tmp_path):
    """install(force=True) swaps the factories; a lock created by
    dlrover_tpu code is wrapped, a lock created here (tests/) is not."""
    monkeypatch.delenv(lockwatch.ENV_LOCKWATCH, raising=False)
    assert lockwatch.install() is None  # env off, no force: no-op
    watch = lockwatch.install(force=True)
    try:
        assert watch is not None
        assert lockwatch.current() is watch
        assert lockwatch.install(force=True) is watch  # idempotent
        # created from tests/: the caller-frame filter leaves it raw
        ours = threading.Lock()
        assert not isinstance(ours, _WatchedLock), ours
        # created from dlrover_tpu/: wrapped
        from dlrover_tpu.telemetry.registry import MetricsRegistry

        reg = MetricsRegistry()
        wrapped = [
            v for v in vars(reg).values() if isinstance(v, _WatchedLock)
        ]
        assert wrapped, vars(reg)
        # the flight recorder carries the graph as a section
        from dlrover_tpu.telemetry import flight_recorder

        out = flight_recorder.dump_flight_record(
            reason="lockwatch-test", dump_dir=str(tmp_path)
        )
        record = json.load(open(os.path.join(out, "record.json")))
        assert "lockwatch" in record, sorted(record)
        assert record["lockwatch"] == watch.snapshot()
    finally:
        lockwatch.uninstall()
    assert threading.Lock is _ORIG_LOCK
    assert lockwatch.current() is None
    out = flight_recorder.dump_flight_record(
        reason="lockwatch-test-2", dump_dir=str(tmp_path)
    )
    record = json.load(open(os.path.join(out, "record.json")))
    assert "lockwatch" not in record, sorted(record)


def test_enabled_reads_env(monkeypatch):
    monkeypatch.setenv(lockwatch.ENV_LOCKWATCH, "1")
    assert lockwatch.enabled()
    monkeypatch.setenv(lockwatch.ENV_LOCKWATCH, "0")
    assert not lockwatch.enabled()


def test_long_hold_threshold_from_env(monkeypatch):
    monkeypatch.setenv(lockwatch.ENV_LONG_HOLD_MS, "250")
    assert LockWatch().long_hold_s == 0.25
    monkeypatch.delenv(lockwatch.ENV_LONG_HOLD_MS)
    assert LockWatch().long_hold_s == 0.5  # documented default


# ------------------------------------------------------------------ guard


def test_reentrancy_guard_skips_watchdog_work():
    """Watchdog work triggered while reporting (the journal's own locks
    may be watched) is skipped, not recursed into."""
    watch = LockWatch(long_hold_s=60.0)
    a = _watched("g.py:1", watch)
    b = _watched("g.py:2", watch)
    _guard.active = True
    try:
        with a:
            with b:
                pass
    finally:
        _guard.active = False
    assert watch.snapshot()["edges"] == {}  # nothing was observed
    assert watch._stack() == []
