"""DrainCoordinator unit tests: the bounded drain sequence, the
signal-handler composition contract with the flight recorder (both
arming orders), the budget-free relaunch of an announced preemption,
and a lint that every ``signal.signal`` registration in the tree
chains the prior disposition instead of clobbering it.
"""

import os
import signal
import time
from types import SimpleNamespace

import pytest

from dlrover_tpu import telemetry as T
from dlrover_tpu.common.constants import (
    NodeAction,
    NodeEnv,
    NodeEventType,
    NodeExitReason,
    NodeStatus,
    NodeType,
)
from dlrover_tpu.common.node import Node, NodeResource
from dlrover_tpu.fault_tolerance.drain import (
    DEFAULT_NOTICE_BUDGET_S,
    DRAIN_EXIT_CODE,
    DURABLE_FLOOR_S,
    DrainCoordinator,
    notice_budget_from_env,
)
from dlrover_tpu.master.monitor.speed_monitor import SpeedMonitor
from dlrover_tpu.master.node.dist_job_manager import create_job_manager
from dlrover_tpu.master.resource.local_optimizer import TPULocalOptimizer
from dlrover_tpu.master.scaler.base_scaler import ScalePlan, Scaler
from dlrover_tpu.master.watcher.base_watcher import NodeEvent
from dlrover_tpu.telemetry import flight_recorder
from dlrover_tpu.telemetry.journal import EventJournal

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def fresh_defaults():
    T.set_default_registry(None)
    T.set_default_journal(EventJournal(None))
    yield
    T.set_default_registry(None)
    T.set_default_journal(EventJournal(None))


class StubClient:
    def __init__(self, relinquished=3, report_delay=0.0):
        self.relinquished = relinquished
        self.report_delay = report_delay
        self.preemption = None
        self.relinquish_calls = 0
        self.goodput_final = None

    def report_preemption(self, reason="", notice_budget_s=0.0,
                          deadline_ts=0.0, restart_count=0):
        if self.report_delay:
            time.sleep(self.report_delay)
        self.preemption = dict(
            reason=reason, notice_budget_s=notice_budget_s,
            deadline_ts=deadline_ts, restart_count=restart_count,
        )

    def relinquish_shards(self, dataset_name=""):
        self.relinquish_calls += 1
        return self.relinquished

    def report_goodput(self, final=False):
        self.goodput_final = final


class StubCkpt:
    def __init__(self):
        self.saves = []
        self.waited = 0

    def save(self, step, state, force_persist=False, durable=False):
        self.saves.append(dict(step=step, state=state,
                               force_persist=force_persist,
                               durable=durable))
        return 1.0

    def wait(self):
        self.waited += 1


# ------------------------------------------------------------ budget env


def test_notice_budget_from_env(monkeypatch):
    monkeypatch.delenv(NodeEnv.PREEMPT_NOTICE_BUDGET, raising=False)
    assert notice_budget_from_env() == DEFAULT_NOTICE_BUDGET_S
    monkeypatch.setenv(NodeEnv.PREEMPT_NOTICE_BUDGET, "12.5")
    assert notice_budget_from_env() == 12.5
    monkeypatch.setenv(NodeEnv.PREEMPT_NOTICE_BUDGET, "garbage")
    assert notice_budget_from_env() == DEFAULT_NOTICE_BUDGET_S
    monkeypatch.setenv(NodeEnv.PREEMPT_NOTICE_BUDGET, "-3")
    assert notice_budget_from_env() == DEFAULT_NOTICE_BUDGET_S


# --------------------------------------------------------- drain sequence


def test_drain_runs_every_step_and_journals():
    client, ckpt = StubClient(), StubCkpt()
    d = DrainCoordinator(
        master_client_fn=lambda: client,
        checkpointer_fn=lambda: ckpt,
        state_provider=lambda: (7, {"w": 1}),
        notice_budget_s=10.0,
        restart_count=2,
    )
    result = d.drain(reason="unit-test")
    assert client.preemption["reason"] == "unit-test"
    assert client.preemption["restart_count"] == 2
    assert client.relinquish_calls == 1
    assert client.goodput_final is True
    # 10s budget > DURABLE_FLOOR: the durable path drains the persist
    # queue too (tmpfs dies with a reclaimed host)
    assert ckpt.saves == [dict(step=7, state={"w": 1},
                               force_persist=True, durable=True)]
    assert ckpt.waited == 1
    assert result["checkpoint"]["ok"]
    assert result["relinquished"]["value"] == 3

    jr = T.default_journal()
    notice = jr.events("preempt.notice")[0]["data"]
    assert notice["step"] == 7 and notice["restart_count"] == 2
    eck = jr.events("preempt.emergency_ckpt")[0]["data"]
    assert eck["ok"] and eck["durable"] and eck["step"] == 7
    assert jr.events("preempt.drained")


def test_drain_never_blocks_past_the_deadline():
    # the report step eats the whole window: the remaining steps are
    # skipped, and drain() still returns quickly
    client, ckpt = StubClient(report_delay=5.0), StubCkpt()
    d = DrainCoordinator(
        master_client_fn=lambda: client,
        checkpointer_fn=lambda: ckpt,
        state_provider=lambda: (1, {}),
        notice_budget_s=0.3,
    )
    t0 = time.monotonic()
    result = d.drain()
    assert time.monotonic() - t0 < 2.0
    assert result["reported"]["timed_out"]
    assert not result["checkpoint"]["attempted"]
    assert ckpt.saves == []
    jr = T.default_journal()
    assert jr.events("preempt.step_timeout")
    assert jr.events("preempt.step_skipped")


def test_emergency_checkpoint_falls_back_to_ram_tier():
    # remaining budget below DURABLE_FLOOR: save still fires, but
    # durable=False (staged RAM tier) and no persist-queue drain
    client, ckpt = StubClient(), StubCkpt()
    d = DrainCoordinator(
        master_client_fn=lambda: client,
        checkpointer_fn=lambda: ckpt,
        state_provider=lambda: (4, {}),
        notice_budget_s=DURABLE_FLOOR_S - 1.0,
    )
    result = d.drain()
    assert ckpt.saves[0]["durable"] is False
    assert ckpt.saves[0]["force_persist"] is True
    assert ckpt.waited == 0
    assert result["checkpoint"]["ok"]


def test_drain_survives_failing_dependencies():
    class Exploding:
        def __getattr__(self, name):
            raise RuntimeError("boom")

    d = DrainCoordinator(
        master_client_fn=lambda: Exploding(),
        checkpointer_fn=lambda: Exploding(),
        state_provider=lambda: (_ for _ in ()).throw(RuntimeError("np")),
        notice_budget_s=1.0,
    )
    result = d.drain()  # must not raise
    assert result["reported"]["ok"] is False


# ------------------------------------------------------ signals + arming


def test_arm_is_idempotent_and_disarm_restores():
    before = signal.getsignal(signal.SIGTERM)
    d = DrainCoordinator(notice_budget_s=1.0, exit_fn=lambda rc: None)
    try:
        assert d.arm()
        assert d.arm()  # second arm: no re-registration
        assert signal.getsignal(signal.SIGTERM) == d._on_signal
    finally:
        d.disarm()
    assert signal.getsignal(signal.SIGTERM) == before


def test_sigterm_triggers_drain_and_distinct_exit_code():
    exits = []
    client = StubClient()
    d = DrainCoordinator(
        master_client_fn=lambda: client,
        state_provider=lambda: (3, {}),
        notice_budget_s=2.0,
        exit_fn=exits.append,
    )
    try:
        assert d.arm()
        os.kill(os.getpid(), signal.SIGTERM)
        deadline = time.monotonic() + 5
        while not exits and time.monotonic() < deadline:
            time.sleep(0.01)
    finally:
        d.disarm()
    assert exits == [DRAIN_EXIT_CODE]
    assert client.preemption is not None
    # a second notice mid/post-drain is a no-op, not a second sequence
    d.trigger()
    assert exits == [DRAIN_EXIT_CODE]


def test_chained_coordinator_never_double_drains():
    """Two armed coordinators (the trainer's plus a caller's): one
    SIGTERM runs ONE drain. The newer handler must not chain into the
    older coordinator — that would start a second sequence and
    hard-exit through the older exit_fn (os._exit in production)."""
    exits_a, exits_b = [], []
    a = DrainCoordinator(notice_budget_s=1.0, exit_fn=exits_a.append)
    b = DrainCoordinator(notice_budget_s=1.0, exit_fn=exits_b.append)
    try:
        assert a.arm()
        assert b.arm()
        os.kill(os.getpid(), signal.SIGTERM)
        deadline = time.monotonic() + 5
        while not exits_b and time.monotonic() < deadline:
            time.sleep(0.01)
    finally:
        b.disarm()
        a.disarm()
    assert exits_b == [DRAIN_EXIT_CODE]
    assert exits_a == []
    assert b.draining and not a.draining


def _wait_for(cond, timeout=5.0):
    deadline = time.monotonic() + timeout
    while not cond() and time.monotonic() < deadline:
        time.sleep(0.01)
    assert cond()


def test_composes_with_flight_recorder_drain_armed_first(
    tmp_path, monkeypatch
):
    """Trainer order: drain armed, then the flight recorder hooks on
    top. SIGTERM must BOTH dump stacks and run the drain."""
    monkeypatch.setenv(flight_recorder.ENV_FLIGHT_RECORDER, "1")
    monkeypatch.setenv(flight_recorder.ENV_CRASH_DIR, str(tmp_path))
    exits = []
    client = StubClient()
    d = DrainCoordinator(
        master_client_fn=lambda: client,
        state_provider=lambda: (5, {}),
        notice_budget_s=2.0,
        exit_fn=exits.append,
    )
    try:
        assert d.arm()
        assert flight_recorder.install_signal_hook()
        os.kill(os.getpid(), signal.SIGTERM)
        _wait_for(lambda: exits)
    finally:
        flight_recorder.uninstall_signal_hook()
        d.disarm()
    assert exits == [DRAIN_EXIT_CODE]
    assert client.preemption is not None
    dumps = [p for p in os.listdir(tmp_path) if p.startswith("flight-")]
    assert dumps, "flight recorder did not dump on preemption"


def test_composes_with_flight_recorder_recorder_first(
    tmp_path, monkeypatch
):
    """Reverse order: recorder hooked first, drain armed on top. The
    drain chains the recorder's dump WITHOUT re-delivering the signal
    (the recorder's non-callable-prev branch would kill the process
    with the wrong rc)."""
    monkeypatch.setenv(flight_recorder.ENV_FLIGHT_RECORDER, "1")
    monkeypatch.setenv(flight_recorder.ENV_CRASH_DIR, str(tmp_path))
    exits = []
    client = StubClient()
    d = DrainCoordinator(
        master_client_fn=lambda: client,
        state_provider=lambda: (6, {}),
        notice_budget_s=2.0,
        exit_fn=exits.append,
    )
    try:
        assert flight_recorder.install_signal_hook()
        assert d.arm()
        os.kill(os.getpid(), signal.SIGTERM)
        _wait_for(lambda: exits)
    finally:
        d.disarm()
        flight_recorder.uninstall_signal_hook()
    assert exits == [DRAIN_EXIT_CODE]
    assert client.preemption is not None
    dumps = [p for p in os.listdir(tmp_path)
             if p.startswith("flight-") and p.endswith("preempt-drain")]
    assert dumps, "drain did not chain the flight-recorder dump"


# ------------------------------------------------- budget-free relaunch


class RecordingScaler(Scaler):
    def __init__(self):
        super().__init__("test")
        self.plans = []

    def scale(self, plan: ScalePlan):
        self.plans.append(plan)


def _mgr(scaler, node_num=2):
    args = SimpleNamespace(node_num=node_num,
                           node_resource=NodeResource(memory=1024))
    return create_job_manager(
        args, SpeedMonitor(), scaler=scaler,
        job_optimizer=TPULocalOptimizer(job_args=args),
    )


def _evt(node_id, status, exit_reason=""):
    n = Node(NodeType.WORKER, node_id, status=status)
    if exit_reason:
        n.set_exit_reason(exit_reason)
    return NodeEvent(NodeEventType.MODIFIED, n)


def test_announced_preemption_relaunches_without_charging_budget():
    scaler = RecordingScaler()
    mgr = _mgr(scaler)
    mgr.start()
    try:
        mgr.process_event(_evt(0, NodeStatus.RUNNING))
        mgr.handle_preemption_notice(NodeType.WORKER, 0, "signal-sigterm")
        mgr.process_event(_evt(0, NodeStatus.FAILED,
                               NodeExitReason.PREEMPTED))
    finally:
        mgr.stop()
    relaunch = [p for p in scaler.plans[1:] if p.launch_nodes]
    assert len(relaunch) == 1
    new_node = relaunch[0].launch_nodes[0]
    assert new_node.rank_index == 0
    assert new_node.relaunch_count == 0  # budget intact
    assert T.default_journal().events("preempt.relaunched")


def test_unannounced_preemption_still_charges_budget():
    scaler = RecordingScaler()
    mgr = _mgr(scaler)
    mgr.start()
    try:
        mgr.process_event(_evt(0, NodeStatus.RUNNING))
        mgr.process_event(_evt(0, NodeStatus.FAILED,
                               NodeExitReason.PREEMPTED))
    finally:
        mgr.stop()
    relaunch = [p for p in scaler.plans[1:] if p.launch_nodes]
    assert len(relaunch) == 1
    assert relaunch[0].launch_nodes[0].relaunch_count == 1
    assert not T.default_journal().events("preempt.relaunched")


def test_maintenance_event_queues_drain_heartbeat_action():
    scaler = RecordingScaler()
    mgr = _mgr(scaler)
    mgr.start()
    try:
        mgr.process_event(_evt(0, NodeStatus.RUNNING))
        n = Node(NodeType.WORKER, 0, status=NodeStatus.RUNNING)
        n.maintenance_pending = True
        mgr.process_event(NodeEvent(NodeEventType.MODIFIED, n))
        action = mgr.collect_node_heartbeat(NodeType.WORKER, 0,
                                            time.time())
        assert action == NodeAction.DRAIN
        # once only: the announcement flag suppresses a duplicate
        # directive on the next identical watcher event
        mgr.process_event(NodeEvent(NodeEventType.MODIFIED, n))
        assert mgr.collect_node_heartbeat(
            NodeType.WORKER, 0, time.time()
        ) != NodeAction.DRAIN
        assert mgr.get_node(NodeType.WORKER, 0).preempt_announced
    finally:
        mgr.stop()
    assert T.default_journal().events("preempt.drain_requested")


# ----------------------------------------------------- signal-chain lint


def test_every_signal_registration_chains_the_prior_disposition():
    """Handlers must compose: a ``signal.signal`` call either CAPTURES
    the previous disposition (assignment, so the new handler can chain
    it) or RESTORES one (handler expression references prev/SIG_DFL/
    SIG_IGN). A bare overwrite silently disables whichever of the
    drain coordinator / flight recorder armed first. (Enforced by
    dlint's signal-chain rule — tools/dlint/rules/signals.py — this
    shim keeps the historical entry point.)"""
    from tools.dlint.core import lint_repo
    from tools.dlint.rules import SignalChainRule

    res = lint_repo(rules=[SignalChainRule])
    assert not res.findings, "\n".join(
        f"{f.location()}: {f.message}" for f in res.findings
    )
