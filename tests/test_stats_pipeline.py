"""Stats pipeline (M13) + resource optimizer decisions driven by it.

Parity: the reference's stats tests (test_job_collector/test_reporter)
and resource tests (test_local_optimizer: throughput plateau -> no grow,
headroom -> grow in node_unit multiples).
"""

import time
import types

from dlrover_tpu.common.constants import NodeType
from dlrover_tpu.common.node import Node
from dlrover_tpu.master.monitor.speed_monitor import SpeedMonitor
from dlrover_tpu.master.resource.local_optimizer import TPULocalOptimizer
from dlrover_tpu.master.stats import (
    JobMetricCollector,
    JobMeta,
    LocalStatsReporter,
    RuntimeMetric,
)


def _collector(min_sample_interval: float = 0.0):
    reporter = LocalStatsReporter(JobMeta(uuid="t", name="t"))
    return JobMetricCollector(
        JobMeta(uuid="t"), reporter,
        min_sample_interval=min_sample_interval,
    ), reporter


# ------------------------------------------------------------- collector

def test_runtime_stats_sampled_on_step_advance():
    collector, reporter = _collector()
    sm = SpeedMonitor()
    sm.add_running_worker(NodeType.WORKER, 0)
    sm.add_running_worker(NodeType.WORKER, 1)
    nodes = [Node(NodeType.WORKER, i, status="running") for i in (0, 1)]

    t = time.time()
    sm.collect_global_step(10, t)
    sm.collect_global_step(20, t + 5)  # speed = 2 steps/s
    collector.collect_runtime_stats(sm, nodes)
    assert len(reporter.runtime_stats) == 1
    rec = reporter.runtime_stats[0]
    assert rec.global_step == 20
    assert rec.worker_num == 2
    assert abs(rec.speed - 2.0) < 1e-6
    assert len(rec.running_nodes) == 2

    # same step again: no duplicate sample
    collector.collect_runtime_stats(sm, nodes)
    assert len(reporter.runtime_stats) == 1
    # step advances: new sample
    sm.collect_global_step(30, t + 10)
    collector.collect_runtime_stats(sm, nodes)
    assert len(reporter.runtime_stats) == 2


def test_model_and_dataset_metrics_stored():
    collector, reporter = _collector()
    info = types.SimpleNamespace(
        param_count=1_100_000_000, flops_per_step=6.0e13,
        batch_size=4, seq_len=2048,
        extra={"hbm_bytes": 2.5e11, "peak_memory_bytes": 1.2e10,
               "variable_count": 150},
    )
    collector.collect_model_metric(info)
    mm = reporter.model_metric
    assert mm.tensor_stats.total_variable_size == 1_100_000_000
    assert mm.tensor_stats.variable_count == 150
    assert mm.op_stats.flops == 6.0e13
    assert mm.op_stats.hbm_bytes == 2.5e11
    assert mm.batch_size == 4 and mm.seq_len == 2048

    collector.collect_dataset_metric("corpus", 1_000_000)
    assert reporter.dataset_metric.name == "corpus"
    assert reporter.dataset_metric.size == 1_000_000

    collector.collect_training_hyper_params(epoch=3, batch_size=32)
    assert reporter.hyper_params.batch_size == 32


def test_runtime_stats_flow_over_grpc():
    """report_global_step RPC -> speed monitor + collector -> reporter."""
    from dlrover_tpu.agent.master_client import MasterClient
    from dlrover_tpu.master.dist_master import DistributedJobMaster

    job_args = types.SimpleNamespace(
        job_name="statjob", node_num=1, node_unit=1,
        distribution_strategy="allreduce",
    )
    master = DistributedJobMaster(port=0, job_args=job_args)
    master._server.start()
    try:
        client = MasterClient(master.addr, node_id=0,
                              node_type=NodeType.WORKER)
        client.update_node_status("running")
        t = time.time()
        client.report_global_step(50, t)
        client.report_global_step(100, t + 10)
        client.report_model_info(
            param_count=123, flops_per_step=4.5e9, batch_size=8,
            seq_len=128, extra={"hbm_bytes": 1e9},
        )
        deadline = time.time() + 5
        while (not master.stats_reporter.runtime_stats
               and time.time() < deadline):
            time.sleep(0.05)
        assert master.stats_reporter.runtime_stats
        rec = master.stats_reporter.runtime_stats[-1]
        assert rec.global_step == 100
        assert rec.speed > 0
        assert master.stats_reporter.model_metric.op_stats.flops == 4.5e9
        client.close()
    finally:
        master._server.stop(grace=0.5)


# ------------------------------------------------------------- optimizer

def _optimizer_with_samples(samples, node_unit=1, target=4, running=2):
    reporter = LocalStatsReporter(JobMeta(uuid="o"))
    for worker_num, speed in samples:
        reporter.report_runtime_stats(RuntimeMetric(
            worker_num=worker_num, speed=speed, global_step=1,
            timestamp=time.time(),
        ))
    sm = SpeedMonitor()
    sm.set_target_worker_num(target)
    for i in range(running):
        sm.add_running_worker(NodeType.WORKER, i)
    return TPULocalOptimizer(
        speed_monitor=sm, node_unit=node_unit, stats_reporter=reporter,
    )


def test_linear_headroom_grows_in_node_unit_multiples():
    """Per-worker throughput held up at 4 workers -> grow back, rounded
    to node_unit."""
    opt = _optimizer_with_samples(
        [(2, 10.0), (2, 10.0), (4, 19.0), (4, 19.0)],  # ~linear scaling
        node_unit=3, target=4, running=2,
    )
    plan = opt.generate_job_resource_plan()
    assert not plan.empty()
    assert plan.node_group_resources[NodeType.WORKER].count == 6  # 4->6


def test_throughput_plateau_blocks_growth():
    """4 workers were barely faster than 2 -> growing again is churn."""
    opt = _optimizer_with_samples(
        [(2, 10.0), (2, 10.0), (4, 9.0), (4, 9.0)],  # spw 5.0 -> 2.25
        target=4, running=2,
    )
    plan = opt.generate_job_resource_plan()
    assert plan.empty()


def test_no_samples_defaults_to_restoring_capacity():
    opt = _optimizer_with_samples([], target=4, running=2)
    plan = opt.generate_job_resource_plan()
    assert plan.node_group_resources[NodeType.WORKER].count == 4


def test_at_target_no_plan():
    opt = _optimizer_with_samples([], target=2, running=2)
    assert opt.generate_job_resource_plan().empty()


def test_straggler_shrink_respects_alignment():
    opt = _optimizer_with_samples([], node_unit=2, target=4, running=4)
    plan = opt.generate_straggler_shrink_plan(
        [3], running_num=4, min_nodes=1
    )
    # 4 - 1 = 3 -> aligned down to 2
    assert plan.node_group_resources[NodeType.WORKER].count == 2
    assert plan.remove_ranks == [3]

    # shrinking below min_nodes is refused
    plan = opt.generate_straggler_shrink_plan(
        [1, 2, 3], running_num=4, min_nodes=2
    )
    assert plan.empty()


def test_stale_small_world_sample_does_not_veto_restore():
    """A startup sample at n=1 with high per-worker speed must not block
    restoring 8 -> 16 when the n=16 samples held up vs n=8."""
    opt = _optimizer_with_samples(
        [(1, 1.0), (1, 1.0),          # startup: 1.0 spw
         (8, 4.8), (8, 4.8),          # 0.6 spw at current
         (16, 7.2), (16, 7.2)],       # 0.45 spw at proposed (> 0.5*0.6)
        target=16, running=8,
    )
    plan = opt.generate_job_resource_plan()
    assert not plan.empty()
    assert plan.node_group_resources[NodeType.WORKER].count == 16


def _grow_optimizer(samples, node_unit=2, target=2, running=2,
                    max_nodes=4):
    from dlrover_tpu.scheduler.job_spec import JobArgs

    opt = _optimizer_with_samples(
        samples, node_unit=node_unit, target=target, running=running,
    )
    opt._job_args = JobArgs(
        job_name="grow", node_num=target, min_node_num=target,
        max_node_num=max_nodes, node_unit=node_unit,
    )
    return opt


def test_throughput_grow_fires_with_measured_window():
    """VERDICT r4 Missing #2: at target with headroom below maxReplicas
    and a measured window at the current size, the optimizer emits the
    DeepRec-style grow plan (one node_unit)."""
    opt = _grow_optimizer([(2, 10.0), (2, 10.0)])
    plan = opt.generate_job_resource_plan()
    assert not plan.empty()
    assert plan.node_group_resources[NodeType.WORKER].count == 4
    assert "throughput grow" in plan.comment


def test_throughput_grow_needs_measured_evidence():
    """No samples at the current size -> no speculative growth (the
    reference grows off OBSERVED speed)."""
    opt = _grow_optimizer([])
    assert opt.generate_job_resource_plan().empty()


def test_throughput_grow_stops_at_plateau():
    """After growing 2->4, the window shows the marginal workers are
    not pulling their weight -> the climb ends."""
    opt = _grow_optimizer(
        [(2, 10.0), (2, 10.0), (4, 9.0), (4, 9.0)],
        target=4, running=4, max_nodes=8,
    )
    assert opt.generate_job_resource_plan().empty()


def test_throughput_grow_bounded_by_max():
    opt = _grow_optimizer(
        [(4, 20.0), (4, 20.0)], target=4, running=4, max_nodes=4,
    )
    assert opt.generate_job_resource_plan().empty()


def test_batch_done_feed_defers_to_step_reports():
    """Shard-fed jobs drive the speed window off completed tasks; a
    job reporting REAL global steps keeps step semantics."""
    sm = SpeedMonitor()
    sm.collect_batch_done(1, 1.0)
    sm.collect_batch_done(1, 2.0)
    assert sm.completed_global_step == 2
    assert sm.running_speed() == 1.0  # 1 task/s
    # a real step report takes over; later batch feeds are ignored
    sm.collect_global_step(100, 3.0)
    sm.collect_batch_done(1, 4.0)
    assert sm.completed_global_step == 100


def test_runtime_stats_throttled_by_time():
    """Event-driven feeds (per-task completions) advance the step on
    every report RPC; the time throttle keeps the collector from
    snapshotting the whole fleet each time (the reference samples on a
    15s clock)."""
    collector, reporter = _collector(min_sample_interval=30.0)
    sm = SpeedMonitor()
    sm.add_running_worker(NodeType.WORKER, 0)
    t = time.time()
    for i in range(1, 6):
        sm.collect_batch_done(1, t + i)
        collector.collect_runtime_stats(sm, [])
    assert len(reporter.runtime_stats) == 1  # first sample only


def test_manual_scale_disables_throughput_growth():
    """Regression (soak drill): an operator's manual_scale retargeted
    the job at 4, and the throughput-grow loop regrew it to 8 minutes
    later — reprovisioning into a dead slice. manualScaling wins."""
    from dlrover_tpu.master.node.job_auto_scaler import (
        AllreduceTrainingAutoScaler,
    )

    opt = _grow_optimizer([(2, 10.0), (2, 10.0)])
    scaler = AllreduceTrainingAutoScaler(
        job_manager=None, job_optimizer=opt, scaler=None,
        min_nodes=2, max_nodes=4,
    )
    plan = opt.generate_job_resource_plan()
    assert plan.grow_target == 4  # growth WOULD fire...
    scaler._manual_override = True  # ...but the operator scaled
    # the periodic loop's gate: a grow plan is dropped under override
    assert scaler._manual_override and plan.grow_target
