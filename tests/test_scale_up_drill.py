"""Live throughput scale-UP drill (VERDICT r4 Missing #2 / item #3) —
the DeepRec autoscaling story: a shard-fed job starts BELOW its
elasticity ceiling, the speed-window optimizer emits a throughput-grow
plan off the measured window, the scaler launches NEW agents (the
survivors' agent processes are never relaunched), the world re-forms
larger, and job throughput measurably rises; shard delivery stays
exactly-once across the transition.

Parity: docs/blogs/deeprec_autoscale_cn.md:223 (30 -> 100 steps/s by
adding workers), AllreduceTrainingAutoScaler job_auto_scaler.py:251,
WorkerManager worker.py:102.
"""

import os
import re
import signal
import subprocess
import sys
import time
import pytest

# tier-1 budget (ISSUE 2 satellite): this module costs >50s of the
# 870s budget on a 1-core box; the nightly/full shard still runs it
pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

DATASET = 15000
BATCH = 50


def _strip_axon(env):
    parts = [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
             if p and "axon" not in p]
    env["PYTHONPATH"] = os.pathsep.join(parts + [REPO])
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["DLROVER_TPU_LOG_LEVEL"] = "INFO"
    return env


def _write_spec(tmp):
    progress = os.path.join(tmp, "progress.txt")
    spec = f"""
apiVersion: dlrover-tpu/v1
kind: ElasticTpuJob
metadata:
  name: scaleup-drill
spec:
  platform: process
  distributionStrategy: allreduce
  nodeUnit: 2
  heartbeatTimeout: 10
  worker:
    replicas: 2
    minReplicas: 2
    maxReplicas: 4
    maxRelaunchCount: 2
    criticalWorkerIndex: none
    env:
      JAX_PLATFORMS: cpu
    command:
      - {sys.executable}
      - -m
      - dlrover_tpu.trainer.elastic_run
      - --nnodes
      - "2:4"
      - --node_unit
      - "2"
      - --rdzv_timeout
      - "10"
      - --monitor_interval
      - "0.3"
      - --heartbeat_interval
      - "2"
      - --max_restarts
      - "4"
      - {os.path.join(REPO, 'examples', 'shard_train.py')}
      - --
      - --dataset-size
      - "{DATASET}"
      - --batch-size
      - "{BATCH}"
      - --batch-seconds
      - "0.5"
      - --progress
      - {progress}
"""
    path = os.path.join(tmp, "job.yaml")
    with open(path, "w") as f:
        f.write(spec)
    return path, progress


def _read_progress(path):
    """[(start, end, rank, world, ts)] completion rows."""
    if not os.path.exists(path):
        return []
    rows = []
    for line in open(path):
        parts = line.strip().split(",")
        if len(parts) == 5:
            try:
                rows.append((int(parts[0]), int(parts[1]),
                             int(parts[2]), int(parts[3]),
                             float(parts[4])))
            except ValueError:
                pass
    return rows


def _rate(rows):
    """Completed samples per second over the rows' time span."""
    if len(rows) < 5:
        return 0.0
    span = max(r[4] for r in rows) - min(r[4] for r in rows)
    if span <= 0:
        return 0.0
    return sum(r[1] - r[0] for r in rows) / span


def _killpg(proc, sig=signal.SIGKILL):
    try:
        os.killpg(os.getpgid(proc.pid), sig)
    except (ProcessLookupError, PermissionError):
        pass


def test_throughput_scale_up_live(tmp_path):
    tmp = str(tmp_path)
    spec_path, progress = _write_spec(tmp)
    env = _strip_axon(dict(os.environ))
    master_out = os.path.join(tmp, "master.out")
    master_err = os.path.join(tmp, "master.err")
    master = subprocess.Popen(
        [sys.executable, "-m", "dlrover_tpu.master.main",
         "--job_spec", spec_path, "--port", "0",
         "--autoscale_interval", "4"],
        cwd=REPO, env=env,
        stdout=open(master_out, "w"),
        stderr=open(master_err, "w"),
        start_new_session=True,
    )
    try:
        # phase 1: the 2-worker world consumes shards
        deadline = time.time() + 180
        while time.time() < deadline:
            if [r for r in _read_progress(progress) if r[3] == 2]:
                break
            assert master.poll() is None, (
                open(master_err).read()[-3000:]
            )
            time.sleep(0.5)
        assert [r for r in _read_progress(progress) if r[3] == 2], (
            "2-worker world never produced completions; master.err: "
            + open(master_err).read()[-3000:]
        )

        # phase 2: the speed-window grow plan fires and the world
        # re-forms at 4 — with NO relaunch of the surviving agents
        deadline = time.time() + 240
        while time.time() < deadline:
            if [r for r in _read_progress(progress) if r[3] == 4]:
                break
            assert master.poll() is None, (
                open(master_err).read()[-3000:]
            )
            time.sleep(0.5)
        rows = _read_progress(progress)
        err = open(master_err).read()
        assert [r for r in rows if r[3] == 4], (
            "world never grew to 4; master.err: " + err[-3000:]
        )
        assert re.search(r"throughput grow 2 -> 4", err), err[-3000:]

        # phase 3: the job drains the dataset; throughput in the grown
        # phase beats the initial phase (the DeepRec claim)
        rc = None
        deadline = time.time() + 300
        while time.time() < deadline:
            rc = master.poll()
            if rc is not None:
                break
            time.sleep(0.5)
        rows = _read_progress(progress)
        assert rc == 0, (
            f"master rc={rc}; err: "
            + open(master_err).read()[-3000:]
        )

        w2 = [r for r in rows if r[3] == 2]
        w4 = [r for r in rows if r[3] == 4]
        rate2, rate4 = _rate(w2), _rate(w4)
        assert rate4 > 1.4 * rate2, (
            f"throughput did not rise: {rate2:.0f} -> {rate4:.0f} "
            f"samples/s (w2={len(w2)} w4={len(w4)} rows)"
        )

        # phase 4: exactly-once shard delivery across the transition —
        # completed ranges are disjoint and cover the dataset fully
        ranges = sorted((r[0], r[1]) for r in rows)
        covered = 0
        prev_end = 0
        for start, end in ranges:
            assert start == prev_end, (
                f"gap or overlap at {start} (prev end {prev_end})"
            )
            covered += end - start
            prev_end = end
        assert covered == DATASET, (covered, DATASET)

        # the survivors' AGENT processes were never relaunched: no
        # node relaunch messages for ranks 0/1 in the master log
        assert not re.search(r"[Rr]elaunch.*worker-[01]\b", err), (
            err[-3000:]
        )
    finally:
        _killpg(master, signal.SIGTERM)
        time.sleep(1.0)
        _killpg(master)
        subprocess.run(
            ["pkill", "-9", "-f", "scaleup-drill"],
            capture_output=True,
        )
