"""Zero-stall checkpoint pipeline (ISSUE 3).

The save path must cost the train thread only staging dispatch
(serialization happens behind the step loop), the persist tier must be
BOUNDED (a slow store can pin at most queue_depth archives, overflow is
counted, forced saves back-pressure instead of dropping), and close()
must never orphan an in-flight save. The Orbax branch must consume the
host snapshot captured at save() time — never touch live device state
on the background thread (donation may have invalidated it).
"""

import io
import os
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import dlrover_tpu.telemetry as T
from dlrover_tpu.telemetry import EventJournal
from dlrover_tpu.trainer import ckpt_store
from dlrover_tpu.trainer.checkpoint import (
    FlashCheckpointer,
    _local_shards,
    _materialize_staged,
    _stage_local_shards,
)


@pytest.fixture(autouse=True)
def fresh_telemetry():
    reg = T.set_default_registry(None)
    T.set_default_journal(EventJournal(None))
    yield reg
    T.set_default_registry(None)
    T.set_default_journal(EventJournal(None))


def _state():
    return {
        "params": {
            "w": jnp.arange(24, dtype=jnp.float32).reshape(4, 6),
            "b": jnp.ones((6,), jnp.bfloat16),
        },
        "step": jnp.asarray(7),
    }


class SlowStore(ckpt_store.LocalFsStore):
    """LocalFsStore whose shard uploads take ``delay`` seconds, with
    concurrency accounting: the bounded pipeline must never run more
    than one upload at a time."""

    def __init__(self, root, delay=0.15):
        super().__init__(root)
        self.delay = delay
        self.active = 0
        self.max_active = 0
        self.puts = 0
        self._lock = threading.Lock()

    def _track(self):
        class _Ctx:
            def __enter__(ctx):
                with self._lock:
                    self.active += 1
                    self.max_active = max(self.max_active, self.active)
                    self.puts += 1
                time.sleep(self.delay)
                return ctx

            def __exit__(ctx, *exc):
                with self._lock:
                    self.active -= 1
                return False

        return _Ctx()

    def put(self, key, data):
        if "/proc-" in key:
            with self._track():
                return super().put(key, data)
        return super().put(key, data)

    def put_stream(self, key, fileobj, size=None):
        if "/proc-" in key:
            with self._track():
                return super().put_stream(key, fileobj, size=size)
        return super().put_stream(key, fileobj, size=size)


def _ckpt(tmp_path, store=None, **kw):
    kw.setdefault("use_orbax", False)
    ckpt = FlashCheckpointer(
        persist_dir=str(tmp_path / "persist"),
        ram_dir=str(tmp_path / "ram"),
        **kw,
    )
    if store is not None:
        ckpt._store = store
    return ckpt


# ----------------------------------------------------------- streaming codec


def test_streaming_archive_roundtrip_via_file(tmp_path):
    """snapshot_to_file -> snapshot_from_file round-trips the full
    leaf menagerie (sharded f32, bf16 extension dtype, scalars)."""
    state = _state()
    snap = _local_shards(state)
    path = tmp_path / "arch.ckpt"
    with open(path, "wb") as f:
        nbytes = ckpt_store.snapshot_to_file(snap, 11, f)
    assert nbytes == os.path.getsize(path) > 0
    with open(path, "rb") as f:
        got, step = ckpt_store.snapshot_from_file(f, target=state)
    assert step == 11
    np.testing.assert_array_equal(
        got["params"]["w"]["shards"][0][1],
        np.asarray(state["params"]["w"]),
    )
    # bf16 rode the encodings table, not a void dtype
    b = got["params"]["b"]
    assert b["dtype"] == "bfloat16"
    assert b["shards"][0][1].dtype.name == "bfloat16"
    # scalar shard survived with shape () (regression: the streaming
    # writer must not promote 0-d members to 1-d)
    assert got["step"]["shards"][0][1].shape == ()


def test_streaming_and_bytes_codecs_are_interchangeable():
    state = _state()
    snap = _local_shards(state)
    data = ckpt_store.snapshot_to_bytes(snap, 3)
    buf = io.BytesIO()
    ckpt_store.snapshot_to_file(snap, 3, buf)
    # one archive, two readers
    got_a, _ = ckpt_store.snapshot_from_bytes(buf.getvalue())
    got_b, _ = ckpt_store.snapshot_from_bytes(data)
    np.testing.assert_array_equal(
        got_a["params"]["w"]["shards"][0][1],
        got_b["params"]["w"]["shards"][0][1],
    )


def test_streaming_reader_rejects_corrupt_archives(tmp_path):
    path = tmp_path / "bad.ckpt"
    path.write_bytes(b"definitely not a zip archive")
    with open(path, "rb") as f:
        with pytest.raises(ckpt_store.ArchiveError):
            ckpt_store.snapshot_from_file(f)
    # truncated real archive is rejected too, never executed
    snap = _local_shards(_state())
    data = ckpt_store.snapshot_to_bytes(snap, 1)
    with pytest.raises(ckpt_store.ArchiveError):
        ckpt_store.snapshot_from_file(io.BytesIO(data[: len(data) // 2]))


def test_store_put_stream_and_open_read_roundtrip(tmp_path):
    store = ckpt_store.LocalFsStore(str(tmp_path))
    payload = os.urandom(1 << 16)
    store.put_stream("step-1/proc-0.a0.ckpt", io.BytesIO(payload))
    with store.open_read("step-1/proc-0.a0.ckpt") as f:
        assert f.read() == payload
    with pytest.raises(KeyError):
        store.open_read("missing-key")
    # base-class default path (exercised via a minimal store)
    class Mem(ckpt_store.ObjectStore):
        def __init__(self):
            self.d = {}

        def put(self, key, data):
            self.d[key] = data

        def get(self, key):
            try:
                return self.d[key]
            except KeyError:
                raise KeyError(key)

        def list(self, prefix=""):
            return sorted(k for k in self.d if k.startswith(prefix))

        def delete(self, key):
            self.d.pop(key, None)

    mem = Mem()
    mem.put_stream("k", io.BytesIO(b"xyz"))
    assert mem.open_read("k").read() == b"xyz"


# ------------------------------------------------------------- stall contract


def test_save_returns_before_serialization_completes(tmp_path,
                                                     monkeypatch):
    """The stall regression: save() must hand off BEFORE the archive
    is serialized — the train thread pays staging dispatch only."""
    serialize_started = threading.Event()
    release = threading.Event()
    real = ckpt_store.snapshot_to_file

    def gated(snapshot, step, fileobj, **kw):
        serialize_started.set()
        assert release.wait(10.0), "test deadlock"
        return real(snapshot, step, fileobj, **kw)

    monkeypatch.setattr(ckpt_store, "snapshot_to_file", gated)
    ckpt = _ckpt(tmp_path, persist_interval=0)
    state = _state()
    t0 = time.perf_counter()
    stall_ms = ckpt.save(21, state)
    returned_in = (time.perf_counter() - t0) * 1e3
    # save() came back while the serializer is still gated
    assert serialize_started.wait(5.0)
    assert not release.is_set()
    assert stall_ms < 1000.0 and returned_in < 1000.0
    release.set()
    ckpt.wait()
    restored, step = ckpt.restore(target=state)
    assert step == 21
    np.testing.assert_array_equal(
        np.asarray(restored["params"]["w"]),
        np.asarray(state["params"]["w"]),
    )
    ckpt.close()
    # stall histogram observed the save
    reg = T.default_registry()
    hist = reg.get("dlrover_checkpoint_save_stall_seconds")
    assert hist is not None and hist._default_child().count >= 1


def test_wait_staged_marks_donation_safe_point(tmp_path, monkeypatch):
    """After wait_staged() the staged snapshot owns host memory: the
    source device buffers can be deleted (donation) without corrupting
    the save."""
    gate = threading.Event()
    real = ckpt_store.snapshot_to_file

    def slow(snapshot, step, fileobj, **kw):
        assert gate.wait(10.0)
        return real(snapshot, step, fileobj, **kw)

    monkeypatch.setattr(ckpt_store, "snapshot_to_file", slow)
    ckpt = _ckpt(tmp_path, persist_interval=0)
    state = {"w": jnp.arange(64, dtype=jnp.float32)}
    expect = np.asarray(state["w"]).copy()
    ckpt.save(5, state)
    assert ckpt.wait_staged(10.0)
    state["w"].delete()  # the donation hazard, made explicit
    gate.set()
    ckpt.wait()
    target = {"w": jnp.zeros(64, dtype=jnp.float32)}
    restored, step = ckpt.restore(target=target)
    assert step == 5
    np.testing.assert_array_equal(np.asarray(restored["w"]), expect)
    ckpt.close()


def test_durable_save_lands_on_tmpfs_before_returning(tmp_path):
    """durable=True: the RAM archive survives an immediate hard kill —
    the file must exist the moment save() returns."""
    ckpt = _ckpt(tmp_path, persist_interval=0)
    state = _state()
    ckpt.save(30, state, durable=True)
    assert os.path.exists(ckpt._ram_path(30))
    ckpt.close()


def test_durable_drain_excluded_from_stall_histogram(tmp_path,
                                                     monkeypatch):
    """durable=True blocks for the serializer drain, but the stall
    histogram is the staging-only zero-stall budget — the drain must
    not skew it (alerting keys off the ~25ms back-pressure buckets).
    The return value still reports the full train-thread cost."""
    real = ckpt_store.snapshot_to_file

    def slow(snapshot, step, fileobj, **kw):
        time.sleep(0.3)
        return real(snapshot, step, fileobj, **kw)

    monkeypatch.setattr(ckpt_store, "snapshot_to_file", slow)
    ckpt = _ckpt(tmp_path, persist_interval=0)
    ret = ckpt.save(9, _state(), durable=True)
    assert ret >= 300.0  # the drain is the caller's visible cost
    hist = T.default_registry().get(
        "dlrover_checkpoint_save_stall_seconds"
    )
    child = hist._default_child()
    assert child.count == 1
    assert child.sum < 0.25  # the 0.3s serialize drain stayed out
    ckpt.close()


def test_stage_then_materialize_owns_memory():
    staged = _stage_local_shards({"w": jnp.arange(8.0)})
    snap = _materialize_staged(staged)
    arr = snap["w"]["shards"][0][1]
    assert isinstance(arr, np.ndarray)
    # owned: mutating the materialized copy can't be a view of the
    # live device buffer (CPU backend would otherwise alias it)
    assert arr.base is None or arr.flags["OWNDATA"]


def test_sync_stage_mode_materializes_on_the_caller(tmp_path):
    ckpt = _ckpt(tmp_path, persist_interval=0, stage="sync")
    state = {"w": jnp.arange(16.0)}
    ckpt.save(3, state)
    # sync staging: host copies owned before save() returned
    assert ckpt.wait_staged(0.0)
    state["w"].delete()
    ckpt.wait()
    restored, step = ckpt.restore(
        target={"w": jnp.zeros(16, jnp.float32)}
    )
    assert step == 3
    np.testing.assert_array_equal(
        np.asarray(restored["w"]), np.arange(16.0)
    )
    ckpt.close()


def test_orbax_branch_persists_staged_snapshot_not_live_state(tmp_path):
    """checkpoint.py:283 bugfix: the Orbax persist must consume host
    data captured at save() time. With donation, the train loop may
    invalidate the state buffers before the background persist runs —
    device_get(state) there reads deleted arrays."""

    class FakeManager:
        def __init__(self):
            self.saved = {}
            self.entered = threading.Event()
            self.release = threading.Event()

        def save(self, step, args=None):
            self.entered.set()
            assert self.release.wait(10.0)
            self.saved[step] = args

        def wait_until_finished(self):
            pass

        def close(self):
            pass

    ckpt = _ckpt(tmp_path, persist_interval=1)
    mgr = FakeManager()
    ckpt._manager = mgr
    ckpt._store = None
    state = {"w": jnp.arange(32, dtype=jnp.float32)}
    expect = np.asarray(state["w"]).copy()
    ckpt.save(9, state, force_persist=True)
    assert ckpt.wait_staged(10.0)
    # donation: the live buffers die while the persist is in flight
    state["w"].delete()
    assert mgr.entered.wait(10.0)
    mgr.release.set()
    ckpt.wait()
    saved = mgr.saved[9]
    # StandardSave(ref) or the raw tree, depending on orbax presence;
    # unwrap defensively
    tree = getattr(saved, "item", saved)
    np.testing.assert_array_equal(np.asarray(tree["w"]), expect)
    ckpt._manager = None  # close() must not touch the fake again
    ckpt.close()


# ------------------------------------------------------ bounded persist queue


def test_persist_queue_overflow_skips_oldest_and_counts(tmp_path):
    store = SlowStore(str(tmp_path / "bucket"), delay=0.25)
    ckpt = _ckpt(
        tmp_path, store=store, persist_interval=1, queue_depth=2,
    )
    state = _state()
    for s in range(1, 7):
        ckpt.save(s, state)
    ckpt.wait()
    ckpt.close()
    # bounded: never more than one concurrent upload (single worker),
    # and some persists were skipped under the slow store
    assert store.max_active == 1
    committed = ckpt_store.committed_steps(store)
    assert committed, "no step ever committed"
    assert committed[-1] == 6, "the NEWEST save must survive the skips"
    skipped = T.default_registry().get(
        "dlrover_checkpoint_persist_skipped_total"
    )
    assert skipped is not None
    total_skipped = sum(
        child._value for _, child in skipped._snapshot()
    )
    assert total_skipped >= 1
    assert total_skipped + store.puts == 6
    # the journal carries the same story
    assert T.default_journal().events("checkpoint.persist_skipped")


def test_inflight_never_exceeds_queue_depth(tmp_path):
    store = SlowStore(str(tmp_path / "bucket"), delay=0.1)
    ckpt = _ckpt(
        tmp_path, store=store, persist_interval=1, queue_depth=2,
    )
    state = _state()
    peak = 0
    for s in range(1, 8):
        ckpt.save(s, state)
        ckpt._drain_saves()  # queue observed between uploads
        peak = max(peak, ckpt._persistq.inflight())
    assert peak <= 2
    ckpt.wait()
    assert ckpt._persistq.inflight() == 0
    ckpt.close()


def test_force_persist_backpressures_instead_of_skipping(tmp_path):
    store = SlowStore(str(tmp_path / "bucket"), delay=0.15)
    ckpt = _ckpt(
        tmp_path, store=store, persist_interval=0, queue_depth=1,
    )
    state = _state()
    for s in (1, 2, 3):
        ckpt.save(s, state, force_persist=True)
    ckpt.wait()
    ckpt.close()
    # every forced save was uploaded (none dropped by the bound)
    assert store.puts == 3
    assert ckpt_store.committed_steps(store) == [1, 2, 3]


def test_wait_joins_all_inflight_persists_not_just_last(tmp_path):
    """The old code joined only the LAST persist thread; close() could
    orphan an uncommitted save."""
    store = SlowStore(str(tmp_path / "bucket"), delay=0.2)
    ckpt = _ckpt(
        tmp_path, store=store, persist_interval=0, queue_depth=4,
    )
    state = _state()
    ckpt.save(10, state, force_persist=True)
    ckpt.save(20, state, force_persist=True)
    ckpt.close()  # wait + shutdown: both persists must have landed
    assert ckpt_store.committed_steps(store) == [10, 20]


def test_same_step_resave_supersedes_queued_predecessor(tmp_path):
    store = SlowStore(str(tmp_path / "bucket"), delay=0.2)
    ckpt = _ckpt(
        tmp_path, store=store, persist_interval=1, queue_depth=3,
    )
    state = _state()
    ckpt.save(5, state)
    ckpt.save(5, state)  # same step again: supersede, don't race
    ckpt.wait()
    ckpt.close()
    assert ckpt_store.committed_steps(store) == [5]
    # at most 2 uploads ever ran (first may have started), never 2
    # concurrently for one step
    assert store.max_active == 1


def test_ram_gc_spares_files_pinned_by_pending_persist(tmp_path):
    store = SlowStore(str(tmp_path / "bucket"), delay=0.3)
    ckpt = _ckpt(
        tmp_path, store=store, persist_interval=1, queue_depth=2,
        max_ram_keep=1,
    )
    state = _state()
    ckpt.save(1, state)  # persist of step 1 starts (slow)
    for s in (2, 3):
        ckpt.save(s, state)  # gc would love to remove step-1's file
    ckpt.wait()
    ckpt.close()
    # the persist of step 1 read a live file: it committed correctly
    assert 1 in ckpt_store.committed_steps(store)
    restored = ckpt_store.read_step(store, 1, 0)
    got, step = ckpt_store.snapshot_from_bytes(restored, target=state)
    assert step == 1


def test_ram_write_failure_still_persists_due_save(tmp_path,
                                                   monkeypatch):
    """A RAM-tier write failure must not silently drop a due persist
    (forced persists are documented as never skipped): the worker
    falls back to building the archive in memory from the snapshot
    materialized at save() time."""
    ckpt = _ckpt(tmp_path, persist_interval=1)
    state = _state()

    def boom(step, snapshot):
        raise OSError("tmpfs full")

    monkeypatch.setattr(ckpt, "_write_ram", boom)
    ckpt.save(4, state, force_persist=True)
    ckpt.wait()
    ckpt.close()
    assert ckpt_store.committed_steps(ckpt._store) == [4]
    data = ckpt_store.read_step(ckpt._store, 4, 0)
    got, step = ckpt_store.snapshot_from_bytes(data, target=state)
    assert step == 4
    np.testing.assert_array_equal(
        got["params"]["w"]["shards"][0][1],
        np.asarray(state["params"]["w"]),
    )


def test_stage_failure_counts_lost_persist(tmp_path, monkeypatch):
    """When staging itself fails there is nothing to persist — the
    loss must be observable (persist_skipped{reason=stage_failed} +
    journal), never just a log line a failover drill can't see."""
    import dlrover_tpu.trainer.checkpoint as ckpt_mod

    def boom(staged):
        raise RuntimeError("D2H failed")

    monkeypatch.setattr(ckpt_mod, "_materialize_staged", boom)
    ckpt = _ckpt(tmp_path, persist_interval=1)
    ckpt.save(2, _state(), force_persist=True)
    ckpt.wait()
    ckpt.close()
    skipped = T.default_registry().get(
        "dlrover_checkpoint_persist_skipped_total"
    )
    assert skipped is not None
    assert sum(c._value for _, c in skipped._snapshot()) >= 1
    evts = T.default_journal().events("checkpoint.persist_skipped")
    assert any(e["data"].get("reason") == "stage_failed" for e in evts)


# --------------------------------------------------------------- elastic tie


def test_elastic_trainer_save_cadence(tmp_path):
    import optax

    from dlrover_tpu.trainer.elastic import ElasticTrainer

    trainer = ElasticTrainer(
        lambda p, b: jnp.mean((b[0] @ p["w"] - b[1]) ** 2),
        optax.sgd(0.1), max_nodes=1, cur_nodes=1,
    )
    ckpt = _ckpt(tmp_path, persist_interval=0)
    trainer.attach_checkpointer(ckpt, save_interval=2)
    state = {"w": jnp.ones((3, 1))}
    stalls = []
    for _ in range(4):
        trainer.report_step()
        stalls.append(trainer.maybe_checkpoint(state))
    # cadence 2: steps 2 and 4 saved, steps 1 and 3 skipped
    assert [s is not None for s in stalls] == [
        False, True, False, True,
    ]
    assert ckpt.latest_step() == 4
    ckpt.close()
    # detached trainer is a no-op
    trainer2 = ElasticTrainer(
        lambda p, b: 0.0, optax.sgd(0.1), max_nodes=1, cur_nodes=1,
    )
    assert trainer2.maybe_checkpoint(state) is None


def test_elastic_train_step_calls_wait_staged_when_attached():
    """ElasticTrainer's jitted step donates (params, opt_state): with
    a checkpointer attached, every train_step dispatch must hit the
    donation sync point first (docs/CHECKPOINT.md contract)."""
    import optax

    from dlrover_tpu.trainer.elastic import ElasticTrainer

    class SpyCkpt:
        def __init__(self):
            self.waits = 0

        def wait_staged(self, timeout=None):
            self.waits += 1
            return True

    optimizer = optax.sgd(0.1)
    trainer = ElasticTrainer(
        lambda p, b: jnp.mean((b[0] @ p["w"] - b[1]) ** 2),
        optimizer, max_nodes=1, cur_nodes=1,
    )
    params = {"w": jnp.ones((3, 1))}
    opt_state = optimizer.init(params)
    batches = (jnp.ones((1, 4, 3)), jnp.zeros((1, 4, 1)))
    # unattached: no sync point, the step runs as-is
    params, opt_state, _ = trainer.train_step(params, opt_state, batches)
    spy = SpyCkpt()
    trainer.attach_checkpointer(spy, save_interval=1)
    for _ in range(2):
        params, opt_state, _ = trainer.train_step(
            params, opt_state, batches
        )
    assert spy.waits == 2
    # profiler path still reaches the shared jit cache
    assert hasattr(trainer.train_step, "lower")


def test_elastic_train_step_blocks_until_staging_materializes(
        tmp_path, monkeypatch):
    """The donation race end-to-end: an async save's device handles
    are still un-materialized when the next (donating) step would
    dispatch — the wrapped train_step must block until the serializer
    owns host copies, and the checkpoint must restore the pre-step
    values."""
    import optax

    import dlrover_tpu.trainer.checkpoint as ckpt_mod
    from dlrover_tpu.trainer.elastic import ElasticTrainer

    optimizer = optax.sgd(0.1)
    trainer = ElasticTrainer(
        lambda p, b: jnp.mean((b[0] @ p["w"] - b[1]) ** 2),
        optimizer, max_nodes=1, cur_nodes=1,
    )
    params = {"w": jnp.ones((3, 1))}
    opt_state = optimizer.init(params)
    batches = (jnp.ones((1, 4, 3)), jnp.zeros((1, 4, 1)))
    # warm the jit cache so the blocking assertion below never
    # measures compile time
    params, opt_state, _ = trainer.train_step(params, opt_state, batches)

    entered = threading.Event()
    release = threading.Event()
    real = ckpt_mod._materialize_staged

    def gated(staged):
        entered.set()
        assert release.wait(10.0), "test deadlock"
        return real(staged)

    monkeypatch.setattr(ckpt_mod, "_materialize_staged", gated)
    ckpt = _ckpt(tmp_path, persist_interval=0)
    trainer.attach_checkpointer(ckpt, save_interval=1)
    expect = np.asarray(params["w"]).copy()
    trainer.report_step()
    assert trainer.maybe_checkpoint((params, opt_state)) is not None
    assert entered.wait(5.0)

    done = threading.Event()

    def run():
        out = trainer.train_step(params, opt_state, batches)
        jax.block_until_ready(out[:2])
        done.set()

    t = threading.Thread(target=run, daemon=True)
    t.start()
    # the donating dispatch is gated on staging materialization
    assert not done.wait(0.5)
    release.set()
    assert done.wait(10.0)
    ckpt.wait()
    restored, step = ckpt.restore()
    assert step == 1
    np.testing.assert_array_equal(
        np.asarray(restored[0]["w"]), expect
    )
    ckpt.close()
