"""Stub-server tests for the real-cluster platform clients.

VERDICT r2 Missing #1: RestTpuVmApi (scheduler/tpu_vm.py) and
RestK8sApi (scheduler/gke.py) run their full verb sets against a local
HTTP stub, asserting auth headers, retry/backoff on 5xx, 4xx error
mapping, pagination, and the pod/node spec bodies. Parity role:
dlrover/python/tests' mocked k8sClient coverage of
scheduler/kubernetes.py:62-130.
"""

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from dlrover_tpu.common.node import Node, NodeResource
from dlrover_tpu.scheduler.gke import (
    GkePodScaler,
    RestK8sApi,
    pod_to_node,
    tpu_node_selector,
)
from dlrover_tpu.scheduler.rest import NotFound, RestClient, RestError
from dlrover_tpu.scheduler.tpu_vm import RestTpuVmApi, TpuVmState


class StubHandler(BaseHTTPRequestHandler):
    """Scriptable stub: the test enqueues (status, body) responses and
    the handler records every request (method, path, headers, body)."""

    def _handle(self):
        length = int(self.headers.get("Content-Length") or 0)
        body = self.rfile.read(length) if length else b""
        self.server.requests.append({
            "method": self.command,
            "path": self.path,
            "auth": self.headers.get("Authorization", ""),
            "body": json.loads(body) if body else None,
        })
        if self.server.responses:
            status, payload = self.server.responses.pop(0)
        else:
            status, payload = 200, {}
        data = json.dumps(payload).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    do_GET = do_POST = do_DELETE = do_PUT = _handle

    def log_message(self, *a):  # quiet
        pass


@pytest.fixture()
def stub():
    server = ThreadingHTTPServer(("127.0.0.1", 0), StubHandler)
    server.requests = []
    server.responses = []
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    try:
        yield server
    finally:
        server.shutdown()
        server.server_close()


def _url(server):
    return f"http://127.0.0.1:{server.server_address[1]}"


# ---------------------------------------------------------------- RestClient


class TestRestClient:
    def test_bearer_auth_and_json_roundtrip(self, stub):
        stub.responses.append((200, {"ok": 1}))
        client = RestClient(_url(stub), token_provider=lambda: "tok-42")
        out = client.request("POST", "v1/things", {"a": 1})
        assert out == {"ok": 1}
        req = stub.requests[0]
        assert req["auth"] == "Bearer tok-42"
        assert req["body"] == {"a": 1}

    def test_retries_5xx_then_succeeds(self, stub):
        stub.responses += [(503, {}), (500, {}), (200, {"ok": 1})]
        sleeps = []
        client = RestClient(
            _url(stub), retries=5, backoff=0.1, sleep=sleeps.append
        )
        assert client.request("GET", "x") == {"ok": 1}
        assert len(stub.requests) == 3
        # linear backoff between attempts
        assert sleeps == pytest.approx([0.1, 0.2])

    def test_404_raises_notfound_immediately(self, stub):
        stub.responses.append((404, {}))
        client = RestClient(_url(stub), sleep=lambda s: None)
        with pytest.raises(NotFound):
            client.request("DELETE", "gone")
        assert len(stub.requests) == 1  # never retried

    def test_other_4xx_not_retried(self, stub):
        stub.responses.append((403, {"message": "denied"}))
        client = RestClient(_url(stub), sleep=lambda s: None)
        with pytest.raises(RestError) as ei:
            client.request("GET", "x")
        assert ei.value.status == 403
        assert len(stub.requests) == 1

    def test_exhausted_retries_raise(self, stub):
        stub.responses += [(500, {})] * 3
        client = RestClient(
            _url(stub), retries=3, sleep=lambda s: None
        )
        with pytest.raises(RestError) as ei:
            client.request("GET", "x")
        assert ei.value.status == 500
        assert len(stub.requests) == 3

    def test_connection_refused_is_retried_then_terminal(self):
        sleeps = []
        client = RestClient(
            "http://127.0.0.1:1",  # nothing listens here
            retries=2, backoff=0.01, sleep=sleeps.append,
        )
        with pytest.raises(RestError) as ei:
            client.request("GET", "x")
        assert ei.value.status == 0  # transport, not HTTP
        assert len(sleeps) == 1

    def test_fresh_token_per_request(self, stub):
        stub.responses += [(200, {}), (200, {})]
        tokens = iter(["t1", "t2"])
        client = RestClient(_url(stub), token_provider=lambda: next(tokens))
        client.request("GET", "a")
        client.request("GET", "b")
        assert [r["auth"] for r in stub.requests] == [
            "Bearer t1", "Bearer t2",
        ]


# -------------------------------------------------------------- RestTpuVmApi


def _tpu_api(stub, **kw):
    kw.setdefault("retries", 3)
    kw.setdefault("sleep", lambda s: None)
    return RestTpuVmApi(
        "proj", "us-central2-b", base_url=_url(stub),
        token_provider=lambda: "tok", **kw,
    )


class TestRestTpuVmApi:
    def test_create_node_body_and_path(self, stub):
        api = _tpu_api(stub)
        stub.responses.append((200, {"name": "op/123"}))
        ok = api.create_node(
            "w-0", "v5litepod-16", "tpu-ubuntu2204-base",
            {"dlrover-job": "j"}, {"startup-script": "run"},
            preemptible=True,
        )
        assert ok
        req = stub.requests[0]
        assert req["method"] == "POST"
        assert req["path"] == (
            "/projects/proj/locations/us-central2-b/nodes?nodeId=w-0"
        )
        assert req["auth"] == "Bearer tok"
        assert req["body"]["acceleratorType"] == "v5litepod-16"
        assert req["body"]["schedulingConfig"] == {"preemptible": True}
        assert req["body"]["metadata"]["startup-script"] == "run"

    def test_create_409_is_idempotent_success(self, stub):
        api = _tpu_api(stub)
        stub.responses.append((409, {}))
        assert api.create_node("w-0", "t", "rv", {}, {}) is True

    def test_create_retries_then_gives_up_false(self, stub):
        api = _tpu_api(stub)
        stub.responses += [(503, {})] * 3
        assert api.create_node("w-0", "t", "rv", {}, {}) is False
        assert len(stub.requests) == 3

    def test_delete_404_returns_false(self, stub):
        api = _tpu_api(stub)
        stub.responses.append((404, {}))
        assert api.delete_node("gone") is False
        assert stub.requests[0]["method"] == "DELETE"

    def test_list_nodes_paginates_and_maps(self, stub):
        api = _tpu_api(stub)
        stub.responses += [
            (200, {
                "nodes": [{
                    "name": "projects/p/locations/z/nodes/w-0",
                    "state": "READY",
                    "labels": {"dlrover-job": "j"},
                    "health": "HEALTHY",
                }],
                "nextPageToken": "page2",
            }),
            (200, {
                "nodes": [{
                    "name": "projects/p/locations/z/nodes/w-1",
                    "state": "PREEMPTED",
                }],
            }),
        ]
        nodes = api.list_nodes()
        assert [n.name for n in nodes] == ["w-0", "w-1"]
        assert nodes[0].state == TpuVmState.READY
        assert nodes[1].state == TpuVmState.PREEMPTED
        assert "pageToken=page2" in stub.requests[1]["path"]

    def test_list_failure_returns_empty(self, stub):
        api = _tpu_api(stub)
        stub.responses += [(500, {})] * 3
        assert api.list_nodes() == []


# --------------------------------------------------------------- RestK8sApi


def _k8s_api(stub, **kw):
    kw.setdefault("retries", 3)
    kw.setdefault("sleep", lambda s: None)
    kw.setdefault("namespace", "train")
    kw.setdefault("job_name", "j")
    return RestK8sApi(
        base_url=_url(stub), token_provider=lambda: "sa-tok", **kw
    )


class TestRestK8sApi:
    def test_create_pod_spec(self, stub):
        api = _k8s_api(stub, image="gcr.io/x/worker:1")
        stub.responses.append((201, {}))
        res = NodeResource(
            cpu=8, memory=16384, tpu_chips=4, tpu_type="tpu-v5-lite"
        )
        ok = api.create_pod(
            "j-worker-0",
            {"dlrover-job": "j", "dlrover-id": "0"},
            {"DLROVER_TPU_MASTER_ADDR": "1.2.3.4:50051"},
            res,
        )
        assert ok
        req = stub.requests[0]
        assert req["method"] == "POST"
        assert req["path"] == "/api/v1/namespaces/train/pods"
        assert req["auth"] == "Bearer sa-tok"
        pod = req["body"]
        assert pod["metadata"]["name"] == "j-worker-0"
        assert pod["metadata"]["labels"]["dlrover-job"] == "j"
        ctr = pod["spec"]["containers"][0]
        assert ctr["image"] == "gcr.io/x/worker:1"
        assert {"name": "DLROVER_TPU_MASTER_ADDR",
                "value": "1.2.3.4:50051"} in ctr["env"]
        # TPU shape of pod_scaler.py:343: chip resources + node pool
        assert ctr["resources"]["requests"]["google.com/tpu"] == "4"
        assert ctr["resources"]["limits"]["memory"] == "16384Mi"
        assert pod["spec"]["nodeSelector"] == {
            "cloud.google.com/gke-tpu-accelerator": "tpu-v5-lite"
        }
        assert pod["spec"]["restartPolicy"] == "Never"

    def test_create_409_success_and_terminal_4xx_false(self, stub):
        api = _k8s_api(stub)
        stub.responses += [(409, {}), (403, {})]
        assert api.create_pod("p", {}, {}, None) is True
        assert api.create_pod("p", {}, {}, None) is False

    def test_delete_pod(self, stub):
        api = _k8s_api(stub)
        stub.responses += [(200, {}), (404, {})]
        assert api.delete_pod("j-worker-0") is True
        assert api.delete_pod("j-worker-0") is False
        assert stub.requests[0]["path"] == (
            "/api/v1/namespaces/train/pods/j-worker-0"
        )

    def test_list_pods_label_selector_pagination_exit_mapping(self, stub):
        api = _k8s_api(stub)
        stub.responses += [
            (200, {
                "items": [{
                    "metadata": {
                        "name": "j-worker-0",
                        "labels": {"dlrover-job": "j",
                                   "dlrover-id": "0",
                                   "dlrover-type": "worker"},
                    },
                    "status": {
                        "phase": "Failed",
                        "containerStatuses": [{
                            "state": {"terminated": {
                                "exitCode": 137,
                                "reason": "OOMKilled",
                            }},
                        }],
                    },
                }],
                "metadata": {"continue": "c1"},
            }),
            (200, {
                "items": [{
                    "metadata": {
                        "name": "j-worker-1",
                        "labels": {"dlrover-job": "j",
                                   "dlrover-id": "1",
                                   "dlrover-type": "worker"},
                    },
                    "status": {"phase": "Failed", "reason": "Evicted"},
                }],
            }),
        ]
        pods = api.list_pods()
        assert len(pods) == 2
        assert "labelSelector=dlrover-job%3Dj" in stub.requests[0]["path"]
        assert "continue=c1" in stub.requests[1]["path"]
        # records flow into the same exit-reason mapping the fake uses
        n0 = pod_to_node(pods[0])
        assert n0.exit_reason == "oom"
        n1 = pod_to_node(pods[1])
        assert n1.exit_reason == "preempted"

    def test_retries_on_503_with_backoff(self, stub):
        sleeps = []
        api = _k8s_api(stub, sleep=sleeps.append, backoff=0.2)
        stub.responses += [(503, {}), (200, {"items": []})]
        assert api.list_pods() == []
        assert len(stub.requests) == 2
        assert sleeps == pytest.approx([0.2])


# -------------------------------------------------- factory + scaler wiring


def test_factory_builds_real_gke_platform(monkeypatch, stub):
    from dlrover_tpu.scheduler.factory import build_platform
    from dlrover_tpu.scheduler.job_spec import JobArgs

    monkeypatch.delenv("DLROVER_TPU_FAKE_PLATFORM", raising=False)
    args = JobArgs(job_name="j", platform="gke", namespace="train")
    scaler, watcher = build_platform(args, "1.2.3.4:50051")
    assert scaler is not None and watcher is not None
    assert isinstance(scaler._api, RestK8sApi)


def test_factory_builds_real_tpu_vm_platform(monkeypatch):
    from dlrover_tpu.scheduler.factory import build_platform
    from dlrover_tpu.scheduler.job_spec import JobArgs

    monkeypatch.delenv("DLROVER_TPU_FAKE_PLATFORM", raising=False)
    args = JobArgs(
        job_name="j", platform="tpu_vm", project="p", zone="z"
    )
    scaler, watcher = build_platform(args, "1.2.3.4:50051")
    assert scaler is not None and watcher is not None
    assert isinstance(scaler._api, RestTpuVmApi)


def test_gke_scaler_launches_through_rest_api(stub):
    """End-to-end: ScalePlan -> RestK8sApi -> stub apiserver."""
    from dlrover_tpu.master.scaler.base_scaler import ScalePlan

    api = _k8s_api(stub, image="img")
    scaler = GkePodScaler("j", api, "m:50051")
    stub.responses.append((201, {}))
    node = Node("worker", 0, rank_index=0)
    node.config_resource = NodeResource(cpu=1, memory=512, tpu_chips=1)
    plan = ScalePlan()
    plan.launch_nodes.append(node)
    scaler.scale(plan)
    req = stub.requests[0]
    assert req["body"]["metadata"]["name"] == "j-worker-0"
    env = {e["name"]: e["value"]
           for e in req["body"]["spec"]["containers"][0]["env"]}
    assert env["DLROVER_TPU_MASTER_ADDR"] == "m:50051"
    assert env["DLROVER_TPU_NODE_ID"] == "0"


def test_tpu_node_selector_topology():
    sel = tpu_node_selector("tpu-v5p-slice", "2x2x4")
    assert sel == {
        "cloud.google.com/gke-tpu-accelerator": "tpu-v5p-slice",
        "cloud.google.com/gke-tpu-topology": "2x2x4",
    }
